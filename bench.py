"""Benchmark: end-to-end wall time indexing the full test_in corpus.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "ms", "vs_baseline": R}

Baseline (BASELINE.md): the reference pthread program at -O2 indexes the
same corpus in 796 ms on this container's CPU (4 mappers / 26 reducers).
``vs_baseline`` is the speedup ratio (baseline_ms / our_ms; > 1 means
faster than the reference).

Two execution plans for the same device engine are measured — pipelined
(uploads overlap tokenize; robust to host<->device link latency) and
one-shot (fewest transfers; wins when the link round-trip is cheap) —
and the better plan's best-of-3 is reported, like the reference's best
thread config (BASELINE.md measures its 1/1..8/13 grid the same way).

The device measurement runs in a watchdog subprocess: if the TPU (or
the tunnel to it) is unreachable or hangs, the bench still reports a
real number by measuring the native cpu backend, which never
initializes a device.  Falls back to a deterministic Zipfian corpus of
the same scale if /root/reference/test_in is not mounted, scaling the
baseline by corpus bytes.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

BASELINE_MS = 796.0
BASELINE_BYTES = 5_793_058
REFERENCE_CORPUS = Path("/root/reference/test_in")
TPU_TIMEOUT_S = 480  # covers first-compile over a slow tunnel


import functools


@functools.lru_cache(maxsize=1)
def _manifest():
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        manifest_from_dir, read_manifest, write_manifest,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        write_corpus, zipf_corpus,
    )

    if REFERENCE_CORPUS.is_dir():
        return manifest_from_dir(REFERENCE_CORPUS), "test_in_e2e_wall_ms"
    tmp = Path(tempfile.mkdtemp(prefix="bench_corpus_"))
    docs = zipf_corpus(num_docs=355, vocab_size=33_000, tokens_per_doc=2900, seed=7)
    paths = write_corpus(tmp / "docs", docs)
    write_manifest(tmp / "list.txt", paths)
    return read_manifest(tmp / "list.txt"), "synthetic_zipf_e2e_wall_ms"


def _measure(backend: str, plans: list[dict]) -> float:
    """Best wall time (ms) over 3 rounds of every plan, after warmup."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        IndexConfig, InvertedIndexModel,
    )

    manifest, _ = _manifest()
    models = []
    for plan in plans:
        out_dir = tempfile.mkdtemp(prefix="bench_out_")
        models.append(InvertedIndexModel(
            IndexConfig(backend=backend, output_dir=out_dir, **plan)))
        models[-1].run(manifest)  # warmup: XLA compile + numpy/jit caches
    best = float("inf")
    for _ in range(3):
        for model in models:
            t0 = time.perf_counter()
            model.run(manifest)
            best = min(best, time.perf_counter() - t0)
    return best * 1e3


def _tpu_child() -> int:
    print(json.dumps({"best_ms": _measure(
        "tpu", [{}, {"pipeline_chunk_docs": 0}])}))
    return 0


def main() -> int:
    _, metric = _manifest()
    value_ms = None
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--tpu-child"],
            capture_output=True, text=True, timeout=TPU_TIMEOUT_S,
        )
        if proc.returncode == 0:
            value_ms = json.loads(proc.stdout.strip().splitlines()[-1])["best_ms"]
        else:
            print(f"bench: tpu child failed:\n{proc.stderr[-2000:]}", file=sys.stderr)
    except (subprocess.TimeoutExpired, json.JSONDecodeError, KeyError, IndexError) as e:
        print(f"bench: tpu measurement unavailable ({type(e).__name__}); "
              "falling back to the native cpu backend", file=sys.stderr)
    measured_backend = "tpu"
    if value_ms is None:
        value_ms = _measure("cpu", [{}])
        measured_backend = "cpu-fallback"

    baseline_ms = BASELINE_MS
    if metric.startswith("synthetic"):
        manifest, _ = _manifest()
        baseline_ms = BASELINE_MS * manifest.total_bytes / BASELINE_BYTES
    print(json.dumps({
        "metric": metric,
        "value": round(value_ms, 2),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / value_ms, 3),
        "measured_backend": measured_backend,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(_tpu_child() if "--tpu-child" in sys.argv else main())
