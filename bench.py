"""Benchmark: end-to-end wall time indexing the full test_in corpus.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "ms", "vs_baseline": R}

Baseline (BASELINE.md): the reference pthread program at -O2 indexes the
same corpus in 796 ms on this container's CPU (4 mappers / 26 reducers).
``vs_baseline`` is the speedup ratio (baseline_ms / our_ms; > 1 means
faster than the reference).

Runs on whatever JAX platform is available (the driver runs it on a real
TPU chip).  Falls back to a deterministic Zipfian corpus of the same
scale if /root/reference/test_in is not mounted, scaling the baseline by
corpus bytes.
"""

from __future__ import annotations

import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

BASELINE_MS = 796.0
BASELINE_BYTES = 5_793_058
REFERENCE_CORPUS = Path("/root/reference/test_in")


def _manifest():
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        manifest_from_dir, read_manifest, write_manifest,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        write_corpus, zipf_corpus,
    )

    if REFERENCE_CORPUS.is_dir():
        return manifest_from_dir(REFERENCE_CORPUS), "test_in_e2e_wall_ms"
    tmp = Path(tempfile.mkdtemp(prefix="bench_corpus_"))
    docs = zipf_corpus(num_docs=355, vocab_size=33_000, tokens_per_doc=2900, seed=7)
    paths = write_corpus(tmp / "docs", docs)
    write_manifest(tmp / "list.txt", paths)
    return read_manifest(tmp / "list.txt"), "synthetic_zipf_e2e_wall_ms"


def main() -> int:
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        IndexConfig, InvertedIndexModel,
    )

    manifest, metric = _manifest()
    # Two execution plans for the same device engine: pipelined (uploads
    # overlap tokenize; robust to host<->device link latency) and
    # one-shot (fewest transfers; wins when the link round-trip is
    # cheap).  The framework defaults to pipelined; the bench reports
    # the better plan's best-of-3, like the reference's best thread
    # config (BASELINE.md measures its 1/1..8/13 grid the same way).
    models = []
    for plan in ({}, {"pipeline_chunk_docs": 0}):
        out_dir = tempfile.mkdtemp(prefix="bench_out_")
        models.append(InvertedIndexModel(
            IndexConfig(backend="tpu", output_dir=out_dir, **plan)))
        models[-1].run(manifest)  # warmup: XLA compile + numpy/jit caches
    best = float("inf")
    for _ in range(3):
        for model in models:
            t0 = time.perf_counter()
            model.run(manifest)
            best = min(best, time.perf_counter() - t0)

    value_ms = best * 1e3
    baseline_ms = BASELINE_MS
    if metric.startswith("synthetic"):
        baseline_ms = BASELINE_MS * manifest.total_bytes / BASELINE_BYTES
    print(json.dumps({
        "metric": metric,
        "value": round(value_ms, 2),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / value_ms, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
