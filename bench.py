"""Benchmark: end-to-end wall time indexing the full test_in corpus.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "ms", "vs_baseline": R, ...}

Baseline (BASELINE.md): the reference pthread program at -O2 indexes the
same corpus in 796 ms on this container's CPU (4 mappers / 26 reducers).
``vs_baseline`` is the speedup ratio (baseline_ms / our_ms; > 1 means
faster than the reference).

Four execution plans for the same device engine are measured —
pipelined (uploads overlap tokenize), one-shot (fewest transfers; wins
when the link round-trip is cheap), and the windowed overlap plan at
two tail fractions (device round trips hidden under the scan; wins on
the tunneled chip) — and the best plan's best-of-5 is reported, like
the reference's best thread config (BASELINE.md measures its 1/1..8/13
grid the same way).  The TPU line also records device-side
Pallas-vs-XLA timings for the fused dedup kernel (``kernel_timings``).

Tunnel-weather hardening (VERDICT r1 #1, r2 #2): the TPU measurement
runs in a watchdog subprocess with up to ``TPU_ATTEMPTS`` tries and a
persistent XLA compilation cache (first attempt pays the compile;
retries and later rounds reuse it).  The child is a FAST LANE followed
by extensions: it compiles and measures the single best-known plan
first and prints a complete result line immediately, then the full
grid and the probes, each under its own alarm, re-printing after every
stage — the parent parses the last complete line of a timed-out child,
so one hung tunnel RPC costs at most the stage it hit, never the TPU
story.  The native cpu backend is ALWAYS measured too (it never touches
a device), and both numbers are reported; ``value`` is the TPU number
when any attempt lands, else the cpu number with
``measured_backend: "cpu-fallback"``.

Falls back to a deterministic Zipfian corpus of the same scale if
/root/reference/test_in is not mounted, scaling the baseline by corpus
bytes.

``--scale`` runs the large-corpus streaming benchmark instead
(BASELINE.json config 4 magnitude): Zipfian docs through the bounded
streaming engine, reporting docs/s and the accumulator high-water mark.

``--sweep`` runs only the host map-phase scaling curve (cpu e2e at
1/2/4 scan workers with the per-worker stage split); the same block is
embedded in the main line as ``host_threads_sweep``.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))


def _load_envknobs():
    # File-path load of the knob registry: the package __init__ pulls
    # in jax, which must stay out of this parent process (the watchdog
    # children pick their own platform).
    import importlib.util
    import sys
    if "mri_envknobs" in sys.modules:
        return sys.modules["mri_envknobs"]
    path = (Path(__file__).resolve().parent
            / "parallel_computation_of_an_inverted_index_using_map_reduce_tpu"
            / "utils" / "envknobs.py")
    spec = importlib.util.spec_from_file_location("mri_envknobs", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclass processing introspects sys.modules[cls.__module__], so
    # the module must be registered before exec
    sys.modules["mri_envknobs"] = mod
    spec.loader.exec_module(mod)
    return mod


envknobs = _load_envknobs()

BASELINE_MS = 796.0
BASELINE_BYTES = 5_793_058
REFERENCE_CORPUS = Path("/root/reference/test_in")
TPU_ATTEMPTS = envknobs.get("MRI_TPU_BENCH_ATTEMPTS")
# First attempt pays XLA compile over the tunnel (round-1 evidence:
# can exceed 8 min when the link is bad) — keep its 480 s leash;
# retries reuse the persistent compilation cache and get less.
TPU_TIMEOUTS_S = tuple(
    int(s) for s in envknobs.get("MRI_TPU_BENCH_TIMEOUTS").split(","))
CACHE_DIR = Path(tempfile.gettempdir()) / "mri_tpu_xla_cache"


def _scratch_mkdtemp(prefix: str) -> str:
    """Temp dir for bench scratch (corpus + per-round letter files),
    RAM-backed when the host offers it.

    The e2e rounds rewrite ~4 MB of letter files 15+ times per run; on
    this VM's network-backed /tmp that makes the emit stage hostage to
    the kernel's dirty-page writeback throttle, whose state drifts with
    hours of unrelated disk traffic (observed: the same binary's emit
    stage 1.8 ms vs 8.6 ms depending on when it ran).  /dev/shm takes
    the storage weather out of a metric that exists to track code, not
    the shared disk.  The `scratch` field in the JSON line records
    which backing a run got, so numbers are never compared across
    backings unknowingly."""
    root = "/dev/shm"
    if os.path.isdir(root) and os.access(root, os.W_OK):
        return tempfile.mkdtemp(prefix=prefix, dir=root)
    return tempfile.mkdtemp(prefix=prefix)


def _scratch_backing() -> str:
    root = "/dev/shm"
    if os.path.isdir(root) and os.access(root, os.W_OK):
        return "tmpfs"
    return "default-tmp"


@functools.lru_cache(maxsize=1)
def _manifest():
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        manifest_from_dir, read_manifest, write_manifest,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        write_corpus, zipf_corpus,
    )

    override = envknobs.get("MRI_TPU_BENCH_CORPUS")
    if override:
        return manifest_from_dir(override), "custom_corpus_e2e_wall_ms"
    if REFERENCE_CORPUS.is_dir():
        return manifest_from_dir(REFERENCE_CORPUS), "test_in_e2e_wall_ms"
    tmp = Path(_scratch_mkdtemp("bench_corpus_"))
    docs = zipf_corpus(num_docs=355, vocab_size=33_000, tokens_per_doc=2900, seed=7)
    paths = write_corpus(tmp / "docs", docs)
    write_manifest(tmp / "list.txt", paths)
    return read_manifest(tmp / "list.txt"), "synthetic_zipf_e2e_wall_ms"


def _measure(backend: str, plans: list[dict], rounds: int = 5) -> dict:
    """Best wall time (ms) over ``rounds`` rounds of every plan, after
    warmup.

    Returns ``{"best_ms": .., "phases_ms": {..}}`` — phases from the
    best-timed run, so device vs host time is reported, not asserted.
    """
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        IndexConfig, InvertedIndexModel,
    )

    manifest, _ = _manifest()
    models = []
    for plan in plans:
        out_dir = _scratch_mkdtemp("bench_out_")
        models.append(InvertedIndexModel(
            IndexConfig(backend=backend, output_dir=out_dir, **plan)))
        models[-1].run(manifest)  # warmup: XLA compile + numpy/jit caches
    best, best_report, best_plan = float("inf"), {}, {}
    for _ in range(rounds):
        for model, plan in zip(models, plans):
            t0 = time.perf_counter()
            report = model.run(manifest)
            dt = time.perf_counter() - t0
            if dt < best:
                best, best_report, best_plan = dt, report, plan
    return {
        "best_ms": best * 1e3,
        "best_plan": best_plan,
        "phases_ms": best_report.get("phases_ms", {}),
        "host_threads": best_report.get("host_threads"),
        "report": best_report,
    }


def _kernel_timings() -> dict:
    """Pallas vs XLA device time for the fused dedup (VERDICT r1 #3:
    measured, not asserted).  Device-side dispatch loops only — a host
    sync per call would measure the link RTT instead of the kernel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops.pallas import (
        kernels as pk,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops.segment import (
        first_occurrence_mask,
    )

    n = 1 << 20
    keys = np.sort(np.random.default_rng(3).integers(
        0, 1 << 28, size=n, dtype=np.int32))
    limit = 1 << 28

    @jax.jit
    def xla_path(k):
        m = first_occurrence_mask(k) & (k < limit)
        return m.astype(jnp.int32), m.astype(jnp.int32).sum()

    lim = jnp.full((1, 1), limit, jnp.int32)

    def pallas_path(k2d):
        return pk._unique_call(k2d, lim, interpret=False)

    kd = jax.device_put(keys)
    k2d = jax.device_put(keys.reshape(n // 128, 128))
    out = {"dedup_keys": n,
           "note": "amortized over 10 chained dispatches closed by a "
                   "scalar fetch; the pallas-vs-xla RATIO is the signal "
                   "(absolute us includes link amortization)"}
    for name, fn, arg in (("xla", xla_path, kd), ("pallas", pallas_path, k2d)):
        res = fn(arg)
        np.asarray(res[1]).reshape(-1)[:1]
        best = float("inf")
        # IMPORTANT: close each batch with a real host fetch of a tiny
        # result — on the axon platform block_until_ready returns after
        # dispatch, NOT after execution (measured: a ~500 ms program
        # "blocks" in 0.1 ms), so a block-based loop would time the
        # dispatch stream instead of the kernel
        for _ in range(30):
            t0 = time.perf_counter()
            rs = [fn(arg) for _ in range(10)]
            np.asarray(rs[-1][1]).reshape(-1)[:1]
            best = min(best, (time.perf_counter() - t0) / 10)
        out[f"{name}_dedup_us"] = round(best * 1e6, 1)
    return out


def _tpu_child() -> int:
    # MRI_TPU_BENCH_PLATFORM=cpu lets the whole child run off-chip (CI
    # smoke; env JAX_PLATFORMS alone is not enough — the axon
    # sitecustomize force-selects the tpu platform via jax.config)
    plat = envknobs.get("MRI_TPU_BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    import signal

    def _probe_timeout(signum, frame):
        raise TimeoutError("stage exceeded its alarm")

    signal.signal(signal.SIGALRM, _probe_timeout)

    # FAST LANE (VERDICT r2 #2): compile + measure ONLY the best-known
    # plan and print a complete result line IMMEDIATELY — one plan's
    # compile fits even a sick tunnel's watchdog window, and the parent
    # salvages the last complete line from a timed-out child, so this
    # line alone already lands a real TPU number in the artifact.
    import jax

    # the platform actually measured (attestation gate: JAX_PLATFORMS
    # alone can redirect the child on hosts without the axon
    # sitecustomize, so the parent must not infer the platform from env)
    measured_platform = jax.devices()[0].platform
    fast_plan = {"overlap_tail_fraction": 0.5, "device_shards": 1}
    result = _measure("tpu", [fast_plan], rounds=3)
    result["stage"] = "fast-lane"
    result["platform"] = measured_platform
    print(json.dumps(result), flush=True)

    # Then extend: the full plan grid (like the reference's thread-count
    # grid, BASELINE.md) — pipelined, one-shot, and the windowed overlap
    # plan at the other tail fraction; overlap hides the link's ~60 ms
    # RTT under the scan and wins on the tunneled chip, one-shot wins on
    # a local PCIe link.  Under its own alarm so a mid-grid hang lets
    # the child exit rc=0 with the fast-lane line intact.
    signal.alarm(envknobs.get("MRI_TPU_GRID_PROBE_S"))
    try:
        grid = _measure("tpu", [
            {},
            {"pipeline_chunk_docs": 0},
            {"overlap_tail_fraction": 0.4, "device_shards": 1},
            {"overlap_tail_fraction": 0.5, "device_shards": 1,
             "overlap_device_windows": 1},
            # bigger first window -> smaller LAST window -> smaller
            # residual fetch wait after the scan (config knob docs)
            {"overlap_tail_fraction": 0.5, "device_shards": 1,
             "overlap_window_split": 0.75},
            fast_plan,
        ])
        if grid["best_ms"] < result["best_ms"]:
            result = grid
        # stamp the winner once — per-branch stamping is how the
        # dropped-platform bug happened
        result["stage"] = "grid"
        result["platform"] = measured_platform
    except BaseException as e:
        result["grid_error"] = f"{type(e).__name__}: {e}"
    finally:
        signal.alarm(0)
    print(json.dumps(result), flush=True)
    # ... then the kernel probe under its own alarm: a hung tunnel RPC
    # inside a fetch would otherwise run out the child's whole watchdog
    # budget and erase the completed measurements above.
    signal.alarm(envknobs.get("MRI_TPU_KERNEL_PROBE_S"))
    try:
        result["kernel_timings"] = _kernel_timings()
    except BaseException as e:  # never let the timing probe sink the bench
        result["kernel_timings"] = {"error": f"{type(e).__name__}: {e}"}
    finally:
        signal.alarm(0)
    print(json.dumps(result), flush=True)
    # All-device engine, recorded as its own datapoint (it cannot win on
    # a ~60 ms-RTT link — its two serial syncs are the wall — but the
    # number belongs in the artifact: on local-PCIe hardware this is
    # the headline plan).  Same alarm discipline as the kernel probe.
    signal.alarm(envknobs.get("MRI_TPU_DEVTOK_PROBE_S"))
    try:
        devtok = _measure("tpu", [{"device_tokenize": True}])
        result["device_tokenize_ms"] = round(devtok["best_ms"], 2)
        result["device_tokenize_phases_ms"] = {
            k: round(v, 2) for k, v in devtok.get("phases_ms", {}).items()}
    except BaseException as e:
        result["device_tokenize_ms"] = None
        result["device_tokenize_error"] = f"{type(e).__name__}: {e}"
    finally:
        signal.alarm(0)
    print(json.dumps(result), flush=True)
    return 0


def _tunnel_alive(timeout_s: int) -> bool:
    """Cheap liveness pre-probe: device enumeration + one tiny fetch in
    a subprocess.  A fully-down tunnel hangs any device call, so
    without this gate the bench would burn every watchdog window
    (480+300+240 s) discovering what one short probe already proves.
    Honors MRI_TPU_BENCH_PLATFORM so off-chip smoke runs probe the
    platform they will actually measure."""
    plat = envknobs.get("MRI_TPU_BENCH_PLATFORM")
    pin = (f"jax.config.update('jax_platforms', {plat!r});" if plat else "")
    probe = ("import jax;" + pin +
             "import numpy as np, jax.numpy as jnp;"
             "d = jax.devices();"
             "v = np.asarray((jnp.ones((8,), jnp.int32) + 1)[:1]);"
             "print('alive', d[0].platform)")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True,
            timeout=timeout_s, text=True)
        return proc.returncode == 0 and "alive" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def _run_tpu_attempts() -> tuple[dict | None, list[str]]:
    """Run the TPU child up to TPU_ATTEMPTS times; returns (result, log)."""
    CACHE_DIR.mkdir(parents=True, exist_ok=True)
    env = dict(os.environ, JAX_COMPILATION_CACHE_DIR=str(CACHE_DIR))
    log: list[str] = []
    attempts = TPU_ATTEMPTS
    probe_s = envknobs.get("MRI_TPU_BENCH_PROBE_S")
    if probe_s and not _tunnel_alive(probe_s):
        # A dead tunnel fails this probe AND every attempt; a merely
        # sick tunnel might pass a longer leash — so drop to ONE
        # full-leash attempt rather than zero (the fast-lane line is
        # salvageable from a timed-out child).
        log.append(f"tunnel liveness probe failed within {probe_s}s; "
                   "single salvage attempt only")
        attempts = min(1, attempts)
    for attempt in range(attempts):
        timeout = TPU_TIMEOUTS_S[min(attempt, len(TPU_TIMEOUTS_S) - 1)]
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--tpu-child"],
                capture_output=True, text=True, timeout=timeout, env=env,
            )
            if proc.returncode == 0:
                return (json.loads(proc.stdout.strip().splitlines()[-1]),
                        log)
            log.append(f"attempt {attempt + 1}: rc={proc.returncode} "
                       f"stderr={proc.stderr[-500:]}")
        except subprocess.TimeoutExpired as e:
            # the child prints the grid line BEFORE the probes — salvage
            # it so probe overruns cannot erase a finished measurement
            partial = (e.stdout or b"")
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            for line in reversed(partial.strip().splitlines()):
                try:
                    parsed = json.loads(line)
                except json.JSONDecodeError:
                    continue
                log.append(f"attempt {attempt + 1}: timeout after "
                           f"{timeout}s (grid line salvaged)")
                return parsed, log
            log.append(f"attempt {attempt + 1}: timeout after {timeout}s")
        except (json.JSONDecodeError, KeyError, IndexError) as e:
            log.append(f"attempt {attempt + 1}: bad child output "
                       f"({type(e).__name__})")
    return None, log


def _bench_scale() -> int:
    """Large-corpus streaming benchmark (BASELINE.json config 4 scale)."""
    plat = envknobs.get("MRI_TPU_SCALE_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        IndexConfig, InvertedIndexModel,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus import (
        synthetic,
    )

    num_docs = envknobs.get("MRI_TPU_SCALE_DOCS")
    vocab = envknobs.get("MRI_TPU_SCALE_VOCAB")
    shards = envknobs.get("MRI_TPU_SCALE_SHARDS")  # 0 = all devices
    # MRI_TPU_SCALE_DEVTOK=1: the streaming ALL-DEVICE engine
    # (ops/device_streaming.py, single chip) instead of the host-scan
    # streaming engine — raw byte windows up, bounded row accumulator
    devtok = bool(envknobs.get("MRI_TPU_SCALE_DEVTOK"))
    # MRI_TPU_SCALE_REALTEXT=1: BASELINE.json config 5's regime — the
    # reference books resharded at paragraph granularity and cycled to
    # magnitude (corpus/realtext.py) instead of Zipf synthesis: real
    # vocabulary growth, real letter skew, real cleaning work.
    realtext = bool(envknobs.get("MRI_TPU_SCALE_REALTEXT"))
    # Salted repeat cycles (default ON): vocabulary keeps growing with
    # real-text shape past one source pass instead of freezing at the
    # source's 33,262 terms (corpus/realtext.py salt_cycles; VERDICT r4
    # #6 — 8 cycles ≈ 266K real-shaped terms through the accumulator).
    salt = bool(envknobs.get("MRI_TPU_SCALE_SALT"))
    if realtext:
        from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.realtext import (
            ParagraphManifest,
        )

        manifest = ParagraphManifest(
            REFERENCE_CORPUS,
            num_docs=(num_docs if envknobs.is_set("MRI_TPU_SCALE_DOCS")
                      else None),
            repeats=envknobs.get("MRI_TPU_SCALE_REPEATS"),
            salt_cycles=salt)
        num_docs = len(manifest)
    else:
        manifest = synthetic.synthetic_manifest(
            num_docs=num_docs, vocab_size=vocab, tokens_per_doc=40, seed=11)
    out_dir = tempfile.mkdtemp(prefix="bench_scale_")
    # MRI_TPU_SCALE_CKPT=path: crash-resumable stream (single-chip
    # devtok only) — a rerun of the same command resumes at the last
    # checkpointed window, so a TPU worker crash (the round-3 1M-doc
    # failure, SCALE_r03.json) costs one checkpoint interval, not the
    # whole run.
    ckpt = envknobs.get("MRI_TPU_SCALE_CKPT") if devtok else None
    chunk = envknobs.get("MRI_TPU_SCALE_CHUNK")
    model = InvertedIndexModel(IndexConfig(
        backend="tpu", output_dir=out_dir,
        device_shards=shards if shards else (1 if devtok else None),
        device_tokenize=devtok,
        stream_checkpoint=ckpt,
        stream_checkpoint_every=envknobs.get("MRI_TPU_SCALE_CKPT_EVERY"),
        stream_chunk_docs=chunk))
    t0 = time.perf_counter()
    stats = model.run(manifest)
    wall = time.perf_counter() - t0
    # a RESUMED run only streamed the windows after the checkpoint:
    # docs/s over full num_docs would overstate throughput by the
    # skipped fraction
    docs_streamed = num_docs - stats.get("resumed_from_window", 0) * chunk
    line = {
        "metric": "scale_stream_docs_per_s",
        "value": round(docs_streamed / wall, 1),
        "unit": "docs/s",
        "vs_baseline": round((docs_streamed / wall) / 446.0, 3),  # ref: 446 docs/s
        "num_docs": num_docs,
        "configured_vocab": vocab,
        "unique_terms": stats.get("unique_terms"),
        "unique_pairs": stats.get("unique_pairs"),
        "wall_s": round(wall, 2),
        "accumulator_capacity": stats.get(
            "accumulator_capacity", stats.get("accumulator_capacity_per_owner")),
        "device_shards": stats.get("device_shards", 1),
        "stream_windows": stats.get("stream_windows"),
        "engine": "device-stream" if devtok else "host-stream",
        "corpus": ("realtext-paragraphs" if realtext else "zipf"),
    }
    if "vocab_curve" in stats:
        # per-window unique-term counts: the vocabulary GROWTH curve
        # (must keep climbing past one source cycle when salted)
        line["vocab_curve"] = stats["vocab_curve"]
    if "unique_rows_curve" in stats:
        line["unique_rows_curve"] = stats["unique_rows_curve"]
    if realtext:
        line["source_paragraphs"] = manifest.source_paragraphs
        line["corpus_bytes"] = manifest.total_bytes
        line["salt_cycles"] = salt
        # docs/s is not comparable across corpora (a paragraph is
        # ~430 B, a reference chapter ~16 KB): vs_baseline for the
        # real-text regime is BYTES throughput over the reference's
        # 7.28 MB/s (5,793,058 B / 0.796 s, BASELINE.md)
        bytes_streamed = manifest.total_bytes * docs_streamed / num_docs
        line["vs_baseline"] = round(
            (bytes_streamed / wall) / (BASELINE_BYTES / (BASELINE_MS / 1e3)),
            3)
        line["vs_baseline_basis"] = "bytes_throughput"
    if "resumed_from_window" in stats:
        line["resumed_from_window"] = stats["resumed_from_window"]
        line["docs_streamed"] = docs_streamed
        line["note"] = ("resumed run: value covers the "
                        f"{docs_streamed} docs streamed after the "
                        "window-"
                        f"{stats['resumed_from_window']} checkpoint")
    for k in ("checkpoint_saves", "checkpoint_ms", "checkpoint_ms_per_save",
              "checkpoint_skips", "checkpoint_budget_s",
              "checkpoint_skipped_projection_s"):
        if k in stats:
            line[k] = stats[k]
    # print the measurement NOW: the probes below re-print an enriched
    # line, but if one of them crashes or overruns a capture window's
    # timeout, the expensive scale measurement above must already be on
    # stdout (same salvage discipline as _run_tpu_attempts)
    print(json.dumps(line), flush=True)
    if realtext and envknobs.get("MRI_TPU_SCALE_SKEW"):
        # hash-vs-letter partition skew on the real text: ONE source
        # cycle through the skew-collecting one-shot engine (cycling
        # multiplies every partition count by the same factor, so one
        # cycle IS the full corpus's distribution)
        try:
            one = ParagraphManifest(REFERENCE_CORPUS, repeats=1)
            skew_stats = InvertedIndexModel(IndexConfig(
                backend="tpu", output_dir=tempfile.mkdtemp(
                    prefix="bench_scale_skew_"),
                device_shards=1, collect_skew_stats=True)).run(one)
            line["skew_one_cycle"] = {
                k: skew_stats[k]
                for k in ("letter_imbalance", "bucket_imbalance")
                if k in skew_stats}
        except BaseException as e:
            line["skew_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(line), flush=True)
    if envknobs.get("MRI_TPU_SCALE_CROSSCHECK"):
        from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.formatter import (
            letters_md5,
        )

        try:
            cpu_dir = tempfile.mkdtemp(prefix="bench_scale_cpu_")
            InvertedIndexModel(IndexConfig(
                backend="cpu", output_dir=cpu_dir)).run(manifest)
            line["md5"] = letters_md5(out_dir)
            line["md5_matches_cpu_backend"] = (
                line["md5"] == letters_md5(cpu_dir))
        except BaseException as e:
            line["crosscheck_error"] = f"{type(e).__name__}: {e}"
        print(json.dumps(line), flush=True)
    return 0


ATTEST_PATH = Path(
    envknobs.get("MRI_TPU_BENCH_ATTEST")
    or Path(__file__).resolve().parent / "BENCH_ATTEST.json")


def _git_rev() -> str:
    try:
        # --dirty: a measurement from an uncommitted tree must not be
        # attributed to the clean commit it will later land in.  -C is
        # the REPO (bench.py's dir) — the attest file may live outside
        # it (e.g. a capture directory).
        return subprocess.run(
            ["git", "-C", str(Path(__file__).resolve().parent), "describe",
             "--always", "--dirty"], capture_output=True, text=True,
            timeout=10,
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def _write_attestation(line: dict) -> None:
    """Persist the freshest builder-side TPU measurement (VERDICT r3
    #2): when the tunnel is down at driver time, the fallback artifact
    embeds this — a timestamped, rev-stamped pointer to the last real
    on-chip number instead of a bare cpu line."""
    try:
        ATTEST_PATH.write_text(json.dumps({
            "captured_unix": int(time.time()),
            "captured_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "git_rev": _git_rev(),
            "tpu_line": line,
        }, indent=2) + "\n")
    except OSError as e:
        print(f"bench: could not write attestation: {e}", file=sys.stderr)


def _host_stage_split(report: dict) -> dict:
    """read/tokenize/emit ms for the best cpu run.

    The pipelined host path surfaces native ns-level timers as
    ``stage_*_ms`` counters; the one-shot fallback only knows its two
    coarse phases (load ≈ read, index_emit ≈ tokenize+emit fused)."""
    if "stage_read_ms" in report:
        split = {k: round(float(report[f"stage_{k}_ms"]), 2)
                 for k in ("read", "tokenize", "emit")}
        # out-of-core runs carry the term-hash shard balance (postings
        # per shard + max/mean skew) so the split shows WHERE the
        # reduce-side work landed, not just how long it took
        if "build_shards" in report:
            split["build_shards"] = report["build_shards"]
        return split
    phases = report.get("phases_ms", {})
    split = {}
    if "load" in phases:
        split["read"] = round(float(phases["load"]), 2)
    if "index_emit" in phases:
        split["tokenize_emit_fused"] = round(float(phases["index_emit"]), 2)
    elif "oracle" in phases:
        split["oracle"] = round(float(phases["oracle"]), 2)
    return split


SWEEP_WORKERS = tuple(
    int(k) for k in envknobs.get("MRI_BENCH_SWEEP_WORKERS").split(","))


def _host_threads_sweep(rounds: int = 7) -> dict:
    """cpu e2e at 1/2/4 scan workers on the same corpus: the host
    map-phase scaling curve, tracked round over round.

    Each worker count is its own plan (its own model + warmup) so the
    steal-queue path and the single-worker pipelined path are measured
    as the dispatcher actually routes them.  ``host_cores`` is recorded
    because the curve is only meaningful relative to the physical
    parallelism on offer — on a 1-core container the 4-worker point
    measures coordination overhead, not speedup, and the number must
    say so rather than look like a regression."""
    sweep: dict = {"host_cores": os.cpu_count(), "rounds": rounds,
                   "points": {}}
    for k in SWEEP_WORKERS:
        res = _measure("cpu", [{"host_threads": k}], rounds=rounds)
        report = res.get("report", {})
        point = {
            "best_ms": round(res["best_ms"], 2),
            "host_threads": report.get("host_threads"),
            "stage_split_ms": _host_stage_split(report),
        }
        for key in ("stage_read_ms_per_worker",
                    "stage_tokenize_ms_per_worker",
                    "stage_emit_ms_per_reducer", "merge_ms",
                    "read_wait_ms", "consume_wait_ms", "reduce_workers"):
            if key in report:
                point[key] = ([round(float(v), 2) for v in report[key]]
                              if isinstance(report[key], list)
                              else round(float(report[key]), 2))
        sweep["points"][str(k)] = point
    pts = sweep["points"]
    if "1" in pts and "4" in pts:
        sweep["speedup_4v1"] = round(
            pts["1"]["best_ms"] / pts["4"]["best_ms"], 3)
    return sweep


def _bench_sweep() -> int:
    """Standalone sweep mode (make bench-sweep): one JSON line, no TPU."""
    _, metric = _manifest()
    sweep = _host_threads_sweep()
    print(json.dumps({
        "metric": "host_threads_sweep",
        "corpus_metric": metric,
        "unit": "ms",
        "scratch": _scratch_backing(),
        **sweep,
    }))
    return 0


def main(artifact: bool = False) -> int:
    _, metric = _manifest()
    tpu, tpu_log = _run_tpu_attempts()
    # best-of-15: the host path's run-to-run spread on the shared
    # 1-core VM (±2-5 ms) is the same order as the stage costs being
    # tracked, and cpu rounds are ~50 ms each — sample enough that the
    # floor, not the scheduler, is what gets reported
    cpu = _measure("cpu", [{}], rounds=15)
    # audited cpu run: what the --audit integrity layer costs on the
    # same corpus (the report carries audit_ms; the contract is < 5 %
    # of the unaudited cpu_ms)
    cpu_audited = _measure("cpu", [{"audit": True}], rounds=3)
    # --artifact: the same corpus built WITH the serving artifact, so
    # the pack overhead (contract: <= 10 % of the unaudited cpu e2e)
    # is measured next to the number it dilutes
    cpu_artifact = (_measure("cpu", [{"artifact": True}], rounds=3)
                    if artifact else None)

    if tpu is not None:
        value_ms, measured_backend = tpu["best_ms"], "tpu"
    else:
        value_ms, measured_backend = cpu["best_ms"], "cpu-fallback"
        print("bench: tpu measurement unavailable "
              f"({'; '.join(tpu_log)}); reporting the native cpu backend",
              file=sys.stderr)

    baseline_ms = BASELINE_MS
    if metric != "test_in_e2e_wall_ms":
        # synthetic or override corpus: scale the reference baseline by
        # corpus bytes so vs_baseline stays meaningful
        manifest, _ = _manifest()
        baseline_ms = BASELINE_MS * manifest.total_bytes / BASELINE_BYTES
    line = {
        "metric": metric,
        "value": round(value_ms, 2),
        "unit": "ms",
        "vs_baseline": round(baseline_ms / value_ms, 3),
        "measured_backend": measured_backend,
        "cpu_ms": round(cpu["best_ms"], 2),
        "cpu_host_threads": cpu.get("host_threads"),
        "host_stage_split": _host_stage_split(cpu.get("report", {})),
        "scratch": _scratch_backing(),
        # failure-handling outcome of the best cpu run (faults.py):
        # non-empty skipped_docs means the measurement itself is suspect
        "degradation": cpu.get("report", {}).get(
            "degradation", {"read_retries": 0, "skipped_docs": []}),
        # integrity-audit overhead (--audit): ledger + merge invariants
        # + output manifest, measured on a separate audited run
        "audit_ms": round(
            cpu_audited.get("report", {}).get("audit_ms", 0.0), 3),
        "audited_cpu_ms": round(cpu_audited["best_ms"], 2),
        # host map-phase scaling curve (1/2/4 scan workers, same
        # corpus) with the per-worker stage split — tracked round over
        # round; host_cores qualifies what the curve can even show
        "host_threads_sweep": _host_threads_sweep(),
    }
    if cpu_artifact is not None:
        rep = cpu_artifact.get("report", {})
        line["artifact_cpu_ms"] = round(cpu_artifact["best_ms"], 2)
        line["artifact_build_ms"] = round(
            float(rep.get("artifact_build_ms", 0.0)), 3)
        line["artifact_bytes"] = int(rep.get("artifact_bytes", 0))
    if tpu is not None:
        line["tpu_platform"] = tpu.get("platform")
        line["tpu_ms"] = round(tpu["best_ms"], 2)
        line["tpu_plan"] = tpu.get("best_plan", {})
        line["tpu_phases_ms"] = {
            k: round(v, 2) for k, v in tpu.get("phases_ms", {}).items()}
        line["tpu_host_threads"] = tpu.get("host_threads")
        if tpu.get("kernel_timings"):
            line["kernel_timings"] = tpu["kernel_timings"]
    if tpu_log:
        line["tpu_attempt_log"] = tpu_log
    if tpu is not None:
        # Attest ONLY a genuine on-chip measurement of the reference
        # corpus: the child records the platform it actually ran on
        # (env like JAX_PLATFORMS / MRI_TPU_BENCH_PLATFORM can redirect
        # it off-chip on some hosts), and smoke/synthetic corpora must
        # not masquerade as the test_in story the fallback reader cites.
        if (tpu.get("platform") not in (None, "cpu", "gpu")
                and metric == "test_in_e2e_wall_ms"):
            _write_attestation(line)
    elif ATTEST_PATH.exists():
        try:
            att = json.loads(ATTEST_PATH.read_text())
            tl = att.get("tpu_line") or {}
            line["last_builder_tpu"] = {
                "captured_utc": att.get("captured_utc"),
                "git_rev": att.get("git_rev"),
                "metric": tl.get("metric"),
                "value_ms": tl.get("value"),
                "vs_baseline": tl.get("vs_baseline"),
                "tpu_plan": tl.get("tpu_plan"),
                "note": "most recent builder-side on-chip measurement "
                        "(BENCH_ATTEST.json); the tunnel was down at "
                        "driver bench time",
            }
        except Exception as e:
            # a malformed auxiliary file must never sink the bench line
            line["last_builder_tpu_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    if "--tpu-child" in sys.argv:
        sys.exit(_tpu_child())
    if "--scale" in sys.argv:
        sys.exit(_bench_scale())
    if "--sweep" in sys.argv:
        sys.exit(_bench_sweep())
    sys.exit(main(artifact="--artifact" in sys.argv))
