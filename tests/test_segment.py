"""Property tests for ops/segment.py — the sorted-array primitives
that replace the reference reducer's linear dict scan and bubble sort
(main.c:172-187, 217-226).

The searchsorted_device contract test exists because of a round-3
advisor finding: the co-sort formulation is only correct for
NONDECREASING query arrays ``v`` (each query's own rank must equal its
index), and the precondition was documented but nothing in the tree
demonstrated what breaks without it.  test_searchsorted_device_requires
_monotone_queries pins the failure mode so a future caller who reaches
for it with unsorted queries finds a named test, not a silent wrong
answer.
"""

from __future__ import annotations

import numpy as np
import pytest

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops.keys import (
    INT32_MAX,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops.segment import (
    bucket_edges,
    compact,
    first_occurrence_mask,
    searchsorted_device,
    set_bit_positions,
    sorted_segment_counts,
)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("n,m", [(1, 1), (64, 16), (1000, 1000), (37, 257)])
def test_searchsorted_device_matches_numpy_on_monotone_queries(seed, n, m):
    rng = np.random.default_rng(seed)
    a = np.sort(rng.integers(0, 1 << 20, size=n, dtype=np.int32))
    v = np.sort(rng.integers(0, 1 << 20, size=m, dtype=np.int32))
    got = np.asarray(searchsorted_device(a, v))
    want = np.searchsorted(a, v, side="left")
    np.testing.assert_array_equal(got, want)


def test_searchsorted_device_arange_queries_exact():
    # the shape every in-tree caller uses: v = arange over segment ids
    a = np.array([0, 0, 1, 1, 1, 3, 7, 7], dtype=np.int32)
    v = np.arange(9, dtype=np.int32)
    got = np.asarray(searchsorted_device(a, v))
    np.testing.assert_array_equal(got, np.searchsorted(a, v))


def test_searchsorted_device_requires_monotone_queries():
    """FAILURE-MODE PIN (advisor r3): non-monotone ``v`` silently
    returns wrong edges — the formulation subtracts each query's index
    as its rank among queries, which only holds when ``v`` is sorted.
    If this test ever starts passing with equality, the implementation
    grew real unsorted-query support and the docstring should change.
    """
    a = np.array([0, 2, 4, 6, 8], dtype=np.int32)
    v = np.array([9, 1, 5], dtype=np.int32)  # deliberately descending-ish
    got = np.asarray(searchsorted_device(a, v))
    want = np.searchsorted(a, v, side="left")
    assert not np.array_equal(got, want), (
        "searchsorted_device unexpectedly handled non-monotone queries; "
        "update its contract docstring and this pin")


@pytest.mark.parametrize("seed", [3, 4])
def test_set_bit_positions_and_compact(seed):
    rng = np.random.default_rng(seed)
    n = 513
    mask = rng.random(n) < 0.3
    pos = np.asarray(set_bit_positions(mask, n))
    want = np.flatnonzero(mask)
    np.testing.assert_array_equal(pos[: want.size], want)
    assert (pos[want.size:] == INT32_MAX).all()

    vals = rng.integers(0, 1000, size=n).astype(np.int32)
    out = np.asarray(compact(vals, mask, n, -1))
    np.testing.assert_array_equal(out[: want.size], vals[mask])
    assert (out[want.size:] == -1).all()


def test_set_bit_positions_out_len_shorter_and_longer():
    mask = np.array([True, False, True, True])
    short = np.asarray(set_bit_positions(mask, 2))
    np.testing.assert_array_equal(short, [0, 2])
    long = np.asarray(set_bit_positions(mask, 6))
    np.testing.assert_array_equal(long, [0, 2, 3, INT32_MAX, INT32_MAX,
                                         INT32_MAX])


def test_first_occurrence_mask_runs():
    keys = np.array([5, 5, 5, 7, 9, 9], dtype=np.int32)
    np.testing.assert_array_equal(
        np.asarray(first_occurrence_mask(keys)),
        [True, False, False, True, True, False])


@pytest.mark.parametrize("seed", [5, 6])
def test_sorted_segment_counts_matches_bincount(seed):
    rng = np.random.default_rng(seed)
    ids = np.sort(rng.integers(0, 40, size=300).astype(np.int32))
    w = rng.integers(0, 5, size=300).astype(np.int32)
    got = np.asarray(sorted_segment_counts(ids, w, 40))
    want = np.bincount(ids, weights=w, minlength=40).astype(np.int32)
    np.testing.assert_array_equal(got, want)


def test_bucket_edges_counts_and_offsets():
    ids = np.array([0, 0, 2, 2, 2, 5], dtype=np.int32)
    counts, offsets = (np.asarray(x) for x in bucket_edges(ids, 6))
    np.testing.assert_array_equal(counts, [2, 0, 3, 0, 0, 1])
    np.testing.assert_array_equal(offsets, [0, 2, 2, 5, 5, 5])
    # padding bucket (>= num_buckets) rows are dropped
    ids_pad = np.array([0, 1, 6, 6], dtype=np.int32)
    counts, _ = (np.asarray(x) for x in bucket_edges(ids_pad, 2))
    np.testing.assert_array_equal(counts, [1, 1])
