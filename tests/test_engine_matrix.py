"""Cross-engine conformance sweep: every engine in the matrix —
{host scan, device scan, streaming} x {single chip, mesh} plus the
all-host backend and the letter-emit path — must produce byte-identical
output on randomized Zipfian corpora.  The broad randomized analogue of
the per-engine suites (slow-marked; `make test` runs it, `make
test-fast` skips it)."""

import numpy as np
import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    build_index,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import native
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)

ENGINES = [
    dict(backend="cpu"),
    dict(backend="tpu", device_shards=1),                      # pipelined
    dict(backend="tpu", device_shards=1, pipeline_chunk_docs=0),  # one-shot
    dict(backend="tpu", device_shards=1, overlap_tail_fraction=0.4),
    dict(backend="tpu"),                                       # mesh host-scan
    dict(backend="tpu", stream_chunk_docs=7),                  # streaming (dist on mesh)
    dict(backend="tpu", device_shards=1, device_tokenize=True),
    dict(backend="tpu", device_tokenize=True),                 # mesh device-scan
    dict(backend="tpu", emit_ownership="letter"),
    dict(backend="tpu", device_shards=1, device_tokenize=True,
         stream_chunk_docs=5),                                 # device-stream
    dict(backend="tpu", device_tokenize=True,
         emit_ownership="letter"),                  # mesh device letter-emit
    dict(backend="tpu", device_tokenize=True,
         stream_chunk_docs=6),                      # mesh device-stream
]


@pytest.mark.slow
@pytest.mark.parametrize("trial", [0, 1, 2])
def test_all_engines_agree_on_random_corpus(tmp_path, trial):
    if not native.available():
        pytest.skip("several engines need the native tokenizer")
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("matrix sweep includes mesh engines (>= 2 devices)")
    rng = np.random.default_rng(1000 + trial)
    docs = zipf_corpus(
        num_docs=int(rng.integers(5, 50)),
        vocab_size=int(rng.integers(80, 1000)),
        tokens_per_doc=int(rng.integers(8, 100)),
        seed=2000 + trial)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    golden = read_letter_files(tmp_path / "oracle")
    for e, cfg in enumerate(ENGINES):
        out = tmp_path / f"e{e}"
        build_index(m, IndexConfig(pad_multiple=64, **cfg), output_dir=out)
        assert read_letter_files(out) == golden, f"engine {cfg} diverged"
