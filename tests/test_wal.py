"""Durable mutations: the checksummed WAL, crash replay, and
segment-shipping replicas (segments/wal.py, segments/replica.py).

The contract under test is ack-ordering durability: a mutation the
client saw acknowledged survives ANY process death, because its WAL
record was fsync'd before the ack.  The flagship here is the SIGKILL-
during-tombstone-batch-flush test — buffered deletes that never
published still replay to a state byte-equal (BM25 floats included)
to a from-scratch build without them.

The replica side pins segment shipping: catch-up fetches only missing
content-hashed files (never re-indexes), verifies every byte against
the manifest's adler32 before adoption, is idempotent when current,
and refuses to roll a local manifest backwards.  Leases: a live
foreign holder rejects mutations with ``lease_lost``; expiry and
clean release both hand the lease over.
"""

from __future__ import annotations

import json
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from conftest import REPO_ROOT

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    faults,
    segments,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.audit import (
    verify_output_dir,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
    main,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.segments import (
    replica as replica_mod,
    wal as wal_mod,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.engine import (
    create_engine,
)

pytestmark = pytest.mark.wal

PKG = "parallel_computation_of_an_inverted_index_using_map_reduce_tpu"

# pure-alphabetic vocabulary (the tokenizer strips digits)
_WORDS = [f"{c}term{s}" for c in "bdfhkmqv" for s in "aeiou"]


@pytest.fixture(autouse=True)
def _disarm():
    faults.install(None)
    faults.begin_run()
    yield
    faults.install(None)
    faults.begin_run()


def make_docs(tmp_path, specs, prefix="doc"):
    ddir = tmp_path / f"{prefix}-docs"
    ddir.mkdir(exist_ok=True)
    paths = []
    for i, words in enumerate(specs):
        p = ddir / f"{prefix}{i:04d}.txt"
        p.write_text(" ".join(words) + "\n", encoding="ascii")
        paths.append(str(p))
    return paths, list(specs)


def doc_specs(rng, n, tokens=(10, 25)):
    import random

    assert isinstance(rng, random.Random)
    return [[_WORDS[rng.randrange(len(_WORDS))]
             for _ in range(rng.randrange(*tokens))] for _ in range(n)]


def build_reference(tmp_path, token_lists, name="ref"):
    """From-scratch single-artifact build of exactly these documents."""
    paths, _ = make_docs(tmp_path, token_lists, prefix=name)
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        write_manifest,
    )
    listfile = tmp_path / f"{name}-list.txt"
    write_manifest(listfile, paths)
    out = tmp_path / f"{name}-out"
    assert main(["1", "1", str(listfile), "--backend", "cpu",
                 "--output-dir", str(out), "--artifact"]) == 0
    return out


def assert_state_identical(idx_dir, truth: dict, tmp_path, tag=""):
    """Multi-segment answers == from-scratch single-artifact answers
    for the same live docs (ids remapped densely by rank), with BM25
    floats compared exactly."""
    live = sorted(truth)
    remap = {gid: i + 1 for i, gid in enumerate(live)}
    ref = build_reference(tmp_path, [truth[g] for g in live],
                          name=f"ref{tag}{len(live)}")
    vocab = sorted({w for words in truth.values() for w in words})
    with create_engine(str(idx_dir), None) as em, \
            create_engine(str(ref), None) as er:
        bm, br = em.encode_batch(vocab), er.encode_batch(vocab)
        assert em.df(bm).tolist() == er.df(br).tolist()
        for t, pm, pr in zip(vocab, em.postings(bm), er.postings(br)):
            got = [] if pm is None else [remap[g] for g in pm.tolist()]
            want = [] if pr is None else pr.tolist()
            assert got == want, t
        for q in ([vocab[0]], vocab[:3], [vocab[-1]]):
            got = [(remap[g], s) for g, s in
                   em.top_k_scored(em.encode_batch(q), 10)]
            assert got == er.top_k_scored(er.encode_batch(q), 10), q


def seed_segmented(tmp_path, rng, n=4, prefix="seed"):
    """A generation-1 segmented dir + its truth dict."""
    paths, specs = make_docs(tmp_path, doc_specs(rng, n), prefix=prefix)
    idx = tmp_path / f"{prefix}-idx"
    segments.append_files(idx, paths)
    return idx, {i + 1: w for i, w in enumerate(specs)}


# -- WAL container ------------------------------------------------------


def test_wal_container_round_trip(tmp_path):
    s1 = wal_mod.log_mutation(tmp_path, "append", {"files": ["a.txt"]})
    s2 = wal_mod.log_mutation(tmp_path, "delete", {"docs": [3, 7]})
    s3 = wal_mod.log_mutation(tmp_path, "compact", {"force": True})
    assert (s1, s2, s3) == (1, 2, 3)
    records, info = wal_mod.read_records(tmp_path)
    assert info == {}
    assert [r["op"] for r in records] == ["append", "delete", "compact"]
    assert records[1]["docs"] == [3, 7]
    assert wal_mod.tail(tmp_path, 1) == records[1:]
    assert wal_mod.tail(tmp_path, 3) == []
    # discard drops exactly the rejected record
    wal_mod.discard(tmp_path, s2)
    assert [r["seq"] for r in wal_mod.read_records(tmp_path)[0]] == [1, 3]
    # seq never reuses a discarded number
    assert wal_mod.log_mutation(tmp_path, "append", {"files": []}) == 4


def test_wal_torn_tail_quarantined(tmp_path):
    wal_mod.log_mutation(tmp_path, "append", {"files": ["a.txt"]})
    wal_mod.log_mutation(tmp_path, "delete", {"docs": [1]})
    path = wal_mod.wal_path(tmp_path)
    whole = path.read_bytes()
    # tear mid-record: whole prefix survives, tail is quarantined
    path.write_bytes(whole[:-7])
    records, info = wal_mod.read_records(tmp_path)
    assert [r["op"] for r in records] == ["append"]
    assert info["quarantined_bytes"] > 0
    assert wal_mod.corrupt_path(tmp_path).exists()
    # the log was repaired in place: a second read is clean
    assert wal_mod.read_records(tmp_path) == (records, {})
    # garbage *between* records (flipped checksum) also quarantines
    bad = bytearray(whole)
    bad[-3] ^= 0xFF
    path.write_bytes(bytes(bad))
    records, info = wal_mod.read_records(tmp_path)
    assert [r["op"] for r in records] == ["append"]
    assert "checksum" in info["damage"]


def test_wal_torn_record_fault_fails_unacked(tmp_path):
    rng = __import__("random").Random(11)
    idx, truth = seed_segmented(tmp_path, rng)
    gen = segments.load_manifest(idx).generation
    faults.install("wal-torn-record")
    faults.begin_run()
    try:
        with pytest.raises(segments.SegmentError):
            segments.delete_docs(idx, [1])
    finally:
        faults.install(None)
        faults.begin_run()
    # the mutation failed un-acked: nothing published, doc 1 still live
    assert segments.load_manifest(idx).generation == gen
    rep = segments.recover(idx)
    assert rep["replayed"] == 0
    assert wal_mod.corrupt_path(idx).exists()
    assert_state_identical(idx, truth, tmp_path, tag="torn")


def test_wal_replay_applies_unpublished_records(tmp_path):
    """A record logged but never applied (crash between fsync and
    publish) replays to the exact state the ack promised."""
    rng = __import__("random").Random(23)
    idx, truth = seed_segmented(tmp_path, rng, n=5)
    with segments.mutation_lock(idx):
        wal_mod.log_mutation(idx, "delete", {"docs": [2, 4]})
    rep = segments.replay(idx)
    assert rep["replayed"] == 1
    truth.pop(2)
    truth.pop(4)
    assert_state_identical(idx, truth, tmp_path, tag="replay")
    # replay is idempotent: the applied record was truncated
    assert segments.replay(idx)["replayed"] == 0
    ok, problems = verify_output_dir(idx)
    assert ok, problems


def test_wal_disabled_by_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("MRI_SEGMENT_WAL", "0")
    rng = __import__("random").Random(31)
    idx, truth = seed_segmented(tmp_path, rng)
    segments.delete_docs(idx, [1])
    assert not wal_mod.wal_path(idx).exists()
    truth.pop(1)
    assert_state_identical(idx, truth, tmp_path, tag="off")


def test_recover_cli_reports_json(tmp_path, capsys):
    rng = __import__("random").Random(41)
    idx, _ = seed_segmented(tmp_path, rng)
    with segments.mutation_lock(idx):
        wal_mod.log_mutation(idx, "delete", {"docs": [1]})
    assert main(["recover", str(idx)]) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["replayed"] == 1 and rep["segmented"]
    # a dir with nothing to recover is a benign no-op, not an error
    assert main(["recover", str(tmp_path / "nowhere")]) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep == {"generation": 0, "replayed": 0, "segmented": False,
                   "skipped": 0, "swept": [], "truncated": 0,
                   "wal_seq": 0}


# -- SIGKILL during tombstone batch flush (the flagship) ----------------


@pytest.mark.daemon
def test_sigkill_during_tombstone_batch_flush(tmp_path):
    """MRI_SEGMENT_TOMBSTONE_FLUSH > 1: deletes are acked buffered,
    each backed by its own fsync'd WAL record.  SIGKILL the daemon
    before the batch publishes — recovery must replay every acked
    delete, landing byte-equal to a build that never had those docs."""
    import os
    import random

    rng = random.Random(53)
    idx, truth = seed_segmented(tmp_path, rng, n=6)
    env = dict(os.environ, PYTHONPATH=str(REPO_ROOT),
               JAX_PLATFORMS="cpu", MRI_SEGMENT_TOMBSTONE_FLUSH="4")
    proc = subprocess.Popen(
        [sys.executable, "-m", PKG, "serve", str(idx),
         "--listen", "127.0.0.1:0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        cwd=str(REPO_ROOT), text=True)
    try:
        ready = json.loads(proc.stdout.readline())
        sock = socket.create_connection((ready["host"], ready["port"]),
                                        timeout=30)
        f = sock.makefile("rwb")

        def rpc(**kw):
            f.write((json.dumps(kw) + "\n").encode())
            f.flush()
            return json.loads(f.readline())

        try:
            # one published append, then three acked-buffered deletes
            more, mspecs = make_docs(tmp_path, doc_specs(rng, 2),
                                     prefix="live")
            r = rpc(id=1, op="append", files=more)
            assert r["ok"], r
            for gid, words in zip(r["result"]["doc_ids"], mspecs):
                truth[gid] = words
            for i, victim in enumerate((1, 3, 7)):
                r = rpc(id=10 + i, op="delete", docs=[victim])
                assert r["ok"] and r["result"]["buffered"], r
                assert r["result"]["wal_seq"] > 0
                truth.pop(victim)
        finally:
            f.close()
            sock.close()
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()
        proc.stdout.close()
        proc.stderr.close()
    # nothing flushed: the manifest still counts zero tombstones
    assert sum(e.tomb_count
               for e in segments.load_manifest(idx).entries) == 0
    rep = segments.recover(idx)
    assert rep["replayed"] == 3, rep
    ok, problems = verify_output_dir(idx)
    assert ok, problems
    assert_state_identical(idx, truth, tmp_path, tag="kill")


# -- leases -------------------------------------------------------------


def test_lease_renew_reject_expire_release(tmp_path, monkeypatch):
    monkeypatch.setenv("MRI_SEGMENT_LEASE_TTL_S", "30")
    assert replica_mod.read_lease(tmp_path) is None
    lease = segments.renew_lease(tmp_path, "alice")
    assert lease["owner"] == "alice"
    # the holder renews freely; a live foreign owner is rejected
    segments.renew_lease(tmp_path, "alice")
    with pytest.raises(segments.LeaseError, match="lease_lost"):
        segments.renew_lease(tmp_path, "bob")
    # expiry hands the lease over without a release
    segments.renew_lease(tmp_path, "alice", ttl=0.05)
    time.sleep(0.1)
    assert segments.renew_lease(tmp_path, "bob")["owner"] == "bob"
    # release is owner-gated
    assert not segments.release_lease(tmp_path, "alice")
    assert segments.release_lease(tmp_path, "bob")
    assert replica_mod.read_lease(tmp_path) is None


def test_lease_disabled_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("MRI_SEGMENT_LEASE_TTL_S", raising=False)
    assert segments.renew_lease(tmp_path, "anyone") is None
    assert not segments.release_lease(tmp_path, "anyone")


# -- segment shipping ---------------------------------------------------


def _daemon(idx, **kw):
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.daemon import (
        ServeDaemon,
    )
    d = ServeDaemon(str(idx), port=0, **kw)
    d.start()
    return d


def _tree_bytes(root: Path) -> dict:
    """Replicated content: manifest + every segment file, by rel path."""
    out = {"manifest": segments.manifest_path(root).read_bytes()}
    for p in sorted(segments.segments_root(root).rglob("*")):
        if p.is_file():
            out[str(p.relative_to(root))] = p.read_bytes()
    return out


@pytest.mark.daemon
def test_replicate_ships_segments_byte_equal(tmp_path):
    import random

    rng = random.Random(71)
    idx, truth = seed_segmented(tmp_path, rng, n=5)
    segments.delete_docs(idx, [2])
    truth.pop(2)
    d = _daemon(idx)
    rep = tmp_path / "replica"
    try:
        res = segments.replicate(rep, d.address)
        assert res["generation"] == 2 and res["fetched"]
        # every shipped byte identical, and a current replica is a no-op
        assert _tree_bytes(rep) == _tree_bytes(idx)
        res2 = segments.replicate(rep, d.address)
        assert not res2["changed"] and res2["fetched"] == []
        # primary moves on; the next round ships only the delta
        more, mspecs = make_docs(tmp_path, doc_specs(rng, 2), prefix="m")
        r = segments.append_files(idx, more)
        for gid, words in zip(r["doc_ids"], mspecs):
            truth[gid] = words
        res3 = segments.replicate(rep, d.address)
        assert res3["behind"] >= 1 and res3["changed"]
        assert _tree_bytes(rep) == _tree_bytes(idx)
    finally:
        d.drain()
    assert_state_identical(rep, truth, tmp_path, tag="rep")
    ok, problems = verify_output_dir(rep)
    assert ok, problems


@pytest.mark.daemon
def test_replicate_rejects_torn_fetch_then_heals(tmp_path):
    """A half-shipped file must never be adopted: the adler32 check
    rejects it and the retry fetches the whole thing."""
    import random

    rng = random.Random(83)
    idx, truth = seed_segmented(tmp_path, rng, n=4)
    d = _daemon(idx)
    rep = tmp_path / "replica"
    try:
        # the in-process daemon shares this injector: the tear fires
        # inside segment_file_payload on the serving side
        faults.install("fetch-partial")
        faults.begin_run()
        res = segments.replicate(rep, d.address)
        assert res["generation"] == 1
        assert _tree_bytes(rep) == _tree_bytes(idx)
    finally:
        d.drain()
    assert_state_identical(rep, truth, tmp_path, tag="heal")


@pytest.mark.daemon
def test_replicate_refuses_manifest_rollback(tmp_path):
    import random

    rng = random.Random(89)
    idx, _ = seed_segmented(tmp_path, rng, n=3)
    rep_idx, _ = seed_segmented(tmp_path, rng, n=3, prefix="rep")
    segments.delete_docs(rep_idx, [1])  # replica is at generation 2
    d = _daemon(idx)
    try:
        with pytest.raises(segments.ReplicaError, match="ahead"):
            segments.replicate(rep_idx, d.address)
    finally:
        d.drain()


def test_replicate_cli_and_parse_addr(tmp_path):
    assert replica_mod.parse_addr("host:99") == ("host", 99)
    for bad in ("nohost", "h:0", "h:notaport", ":7"):
        with pytest.raises(segments.ReplicaError):
            replica_mod.parse_addr(bad)
    # nothing listening: exit 2, not a traceback
    assert main(["replicate", str(tmp_path / "r"),
                 "--from", "127.0.0.1:1"]) == 2


# -- read-your-writes fence ---------------------------------------------


@pytest.mark.daemon
def test_min_generation_fence(tmp_path):
    import random

    rng = random.Random(97)
    idx, _ = seed_segmented(tmp_path, rng, n=3)
    term = _WORDS[0]
    d = _daemon(idx)
    try:
        sock = socket.create_connection(d.address)
        f = sock.makefile("rwb")

        def rpc(**kw):
            f.write((json.dumps(kw) + "\n").encode())
            f.flush()
            return json.loads(f.readline())

        try:
            ok = rpc(id=1, op="df", terms=[term], min_generation=1)
            assert "error" not in ok
            stale = rpc(id=2, op="df", terms=[term], min_generation=99)
            assert stale["error"] == "stale_generation"
            assert stale["generation"] == 1
            bad = rpc(id=3, op="df", terms=[term], min_generation=-1)
            assert bad["error"] == "bad_request"
        finally:
            f.close()
            sock.close()
    finally:
        d.drain()
