"""Format v2 suite: block-bitpacked postings must be invisible.

Three guarantees, each checked against format v1 built from the SAME
corpus through the real cpu pipeline:

* round-trip parity — every existing op (df, postings, AND/OR, top-k
  by df) answers byte-identically on v1 and v2 artifacts, on both the
  host Engine and the DeviceEngine;
* block-boundary fuzz — terms whose document frequency lands exactly
  on, just under, and just over multiples of the 128-doc block size
  (plus single-doc terms) decode exactly; partial last blocks and
  width-0 blocks are the edges that matter;
* BM25 ranked top-k — ``top_k_scored`` matches a pure-Python scoring
  oracle (tf from a brute-force re-tokenize, the documented idf and
  length norm) in both document order and score, and the device path
  agrees with the host path.
"""

import collections
import math
import os

import numpy as np
import pytest

from test_serve import _C_WHITESPACE, build_corpus, naive_index

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve import (
    Engine, load_artifact,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.artifact import (
    DEFAULT_BLOCK_SIZE, FORMAT_ENV, VERSION, VERSION_V2, artifact_path,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.device_engine import (
    DeviceEngine,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.engine import (
    BM25_B, BM25_K1,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    clean_token,
)

pytestmark = pytest.mark.serve


def build_corpus_fmt(tmp_path, docs, fmt: int):
    """build_corpus pinned to one artifact format via the env knob."""
    old = os.environ.get(FORMAT_ENV)
    os.environ[FORMAT_ENV] = str(fmt)
    try:
        return build_corpus(tmp_path, docs)
    finally:
        if old is None:
            os.environ.pop(FORMAT_ENV, None)
        else:
            os.environ[FORMAT_ENV] = old


def word(i: int) -> str:
    """Deterministic alphabetic term (tokenizer drops digits)."""
    i += 26 ** 3  # always 4+ letters so terms stay distinct
    s = ""
    while i:
        i, r = divmod(i, 26)
        s = chr(ord("a") + r) + s
    return s


@pytest.fixture(scope="module")
def both_built(tmp_path_factory):
    docs = zipf_corpus(num_docs=60, vocab_size=900, tokens_per_doc=150,
                       seed=23)
    out1 = build_corpus_fmt(tmp_path_factory.mktemp("fmt_v1"), docs, 1)
    out2 = build_corpus_fmt(tmp_path_factory.mktemp("fmt_v2"), docs, 2)
    return out1, out2, naive_index(docs)


@pytest.fixture(scope="module")
def boundary_built(tmp_path_factory):
    """One corpus whose term dfs bracket every block-size edge: 1, 2,
    B-1, B, B+1, 2B-1, 2B, 2B+1, 2B+44 (B = 128).  Term k appears in
    docs 1..df — doc i holds every term whose target df >= i."""
    B = DEFAULT_BLOCK_SIZE
    targets = {word(k): d for k, d in enumerate(
        (1, 2, B - 1, B, B + 1, 2 * B - 1, 2 * B, 2 * B + 1, 2 * B + 44))}
    ndocs = max(targets.values())
    docs = [" ".join(t for t, d in targets.items() if d >= i).encode()
            for i in range(1, ndocs + 1)]
    out1 = build_corpus_fmt(tmp_path_factory.mktemp("bnd_v1"), docs, 1)
    out2 = build_corpus_fmt(tmp_path_factory.mktemp("bnd_v2"), docs, 2)
    return out1, out2, targets, naive_index(docs)


# -- artifact shape -----------------------------------------------------


def test_versions_and_shared_fields(both_built):
    out1, out2, naive = both_built
    a1 = load_artifact(artifact_path(out1))
    a2 = load_artifact(artifact_path(out2))
    try:
        assert a1.version == VERSION
        assert a2.version == VERSION_V2
        assert a2.block_size == DEFAULT_BLOCK_SIZE
        assert a1.vocab == a2.vocab == len(naive)
        assert a1.num_postings == a2.num_postings
        assert a1.max_doc_id == a2.max_doc_id
        # term tables are byte-identical across formats
        assert a1.term_blob.tobytes() == a2.term_blob.tobytes()
        assert a1.df.tolist() == a2.df.tolist()
        # every df-derived block count is represented in the skip table
        bpt = -(-a2.df.astype(np.int64) // a2.block_size)
        assert int(bpt.sum()) == len(a2.blk_max)
    finally:
        a1.close()
        a2.close()


# -- host round-trip parity ---------------------------------------------


def test_host_engine_v1_v2_parity(both_built):
    out1, out2, naive = both_built
    terms = sorted(naive) + ["zzzzabsent"]
    with Engine(artifact_path(out1)) as e1, \
            Engine(artifact_path(out2)) as e2:
        b1, b2 = e1.encode_batch(terms), e2.encode_batch(terms)
        assert e1.df(b1).tolist() == e2.df(b2).tolist()
        for p1, p2, t in zip(e1.postings(b1), e2.postings(b2), terms):
            if p1 is None:
                assert p2 is None, t
            else:
                assert p1.tolist() == p2.tolist() == naive[t], t
        # boolean ops over every adjacent vocab pair
        pairs = [[terms[i], terms[i + 1]] for i in range(0, 40, 2)]
        for pair in pairs:
            assert e1.query_and(e1.encode_batch(pair)).tolist() == \
                e2.query_and(e2.encode_batch(pair)).tolist()
            assert e1.query_or(e1.encode_batch(pair)).tolist() == \
                e2.query_or(e2.encode_batch(pair)).tolist()
        for li in range(26):
            assert e1.top_k(li, k=10) == e2.top_k(li, k=10)
        # v2 actually exercised the block decoder
        dec = e2.decode_stats()
        assert dec["blocks_decoded"] > 0
        assert dec["bytes_decoded"] > 0


def test_device_engine_v1_v2_parity(both_built):
    out1, out2, naive = both_built
    terms = sorted(naive)[:128] + ["zzzzabsent"]
    d1 = DeviceEngine(artifact_path(out1))
    d2 = DeviceEngine(artifact_path(out2))
    try:
        assert d1.describe()["format"] == VERSION
        assert d2.describe()["format"] == VERSION_V2
        b1, b2 = d1.encode_batch(terms), d2.encode_batch(terms)
        assert d1.df(b1).tolist() == d2.df(b2).tolist()
        for p1, p2, t in zip(d1.postings(b1), d2.postings(b2), terms):
            if p1 is None:
                assert p2 is None, t
            else:
                assert p1.tolist() == p2.tolist(), t
        for pair in ([terms[0], terms[1]], [terms[4], terms[40]],
                     [terms[7], "zzzzabsent"]):
            assert d1.query_and(d1.encode_batch(pair)).tolist() == \
                d2.query_and(d2.encode_batch(pair)).tolist()
            assert d1.query_or(d1.encode_batch(pair)).tolist() == \
                d2.query_or(d2.encode_batch(pair)).tolist()
    finally:
        d1.close()
        d2.close()


# -- block-boundary fuzz ------------------------------------------------


def test_block_boundary_dfs_decode_exactly(boundary_built):
    out1, out2, targets, naive = boundary_built
    with Engine(artifact_path(out1)) as e1, \
            Engine(artifact_path(out2)) as e2:
        terms = sorted(targets)
        b1, b2 = e1.encode_batch(terms), e2.encode_batch(terms)
        assert e1.df(b1).tolist() == [len(naive[t]) for t in terms]
        assert e2.df(b2).tolist() == [len(naive[t]) for t in terms]
        for p1, p2, t in zip(e1.postings(b1), e2.postings(b2), terms):
            assert p1.tolist() == naive[t], t
            assert p2.tolist() == naive[t], t
        # AND between a rare and a block-straddling term forces the
        # skip path through a partial last block
        for pair in ([terms[0], terms[-1]], [terms[1], terms[2]]):
            assert e1.query_and(e1.encode_batch(pair)).tolist() == \
                e2.query_and(e2.encode_batch(pair)).tolist()
        dec = e2.decode_stats()
        assert dec["blocks_decoded"] > 0


def test_block_boundary_device_parity(boundary_built):
    out1, out2, targets, naive = boundary_built
    d2 = DeviceEngine(artifact_path(out2))
    try:
        terms = sorted(targets)
        batch = d2.encode_batch(terms)
        for post, t in zip(d2.postings(batch), terms):
            assert post.tolist() == naive[t], t
    finally:
        d2.close()


def test_single_doc_corpus_round_trip(tmp_path):
    """Degenerate geometry: one doc, every term df=1, every delta run
    empty — all blocks are width-0 and post_data may be empty."""
    docs = [b"lonely little document of one"]
    out = build_corpus_fmt(tmp_path, docs, 2)
    naive = naive_index(docs)
    with Engine(artifact_path(out)) as eng:
        assert eng.artifact.version == VERSION_V2
        batch = eng.encode_batch(sorted(naive))
        for post, t in zip(eng.postings(batch), sorted(naive)):
            assert post.tolist() == naive[t], t


# -- audit / verify coverage --------------------------------------------


def test_verify_manifest_covers_v2_artifact(tmp_path):
    """--audit runs put the v2 ``index.mri`` in index.manifest.json and
    --verify re-checks it: a clean dir passes, a torn v2 artifact fails
    exactly like a torn letter file."""
    import json

    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
        main,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (  # noqa: E501
        write_manifest,
    )

    ddir = tmp_path / "docs"
    ddir.mkdir()
    paths = []
    for i, blob in enumerate([b"alpha beta gamma", b"beta delta",
                              b"alpha epsilon zeta"]):
        p = ddir / f"d{i}.txt"
        p.write_bytes(blob)
        paths.append(str(p))
    listfile = tmp_path / "list.txt"
    write_manifest(listfile, paths)
    out = tmp_path / "out"
    old = os.environ.get(FORMAT_ENV)
    os.environ[FORMAT_ENV] = "2"
    try:
        assert main(["1", "1", str(listfile), "--backend", "cpu",
                     "--output-dir", str(out), "--artifact",
                     "--audit"]) == 0
    finally:
        if old is None:
            os.environ.pop(FORMAT_ENV, None)
        else:
            os.environ[FORMAT_ENV] = old
    art = artifact_path(out)
    assert load_artifact(art).version == VERSION_V2
    manifest = json.loads((out / "index.manifest.json").read_text())
    assert "index.mri" in manifest["files"]
    assert manifest["files"]["index.mri"]["bytes"] == art.stat().st_size
    assert main(["--verify", str(out)]) == 0
    # tear the v2 artifact: verify must reject the directory
    art.write_bytes(art.read_bytes()[:128])
    assert main(["--verify", str(out)]) == 2


# -- BM25 ranked top-k ---------------------------------------------------


def _bm25_oracle(docs, query_terms, k):
    """Brute-force BM25 in pure Python, mirroring the documented
    semantics: tf re-counted from text, doc length = kept tokens,
    avgdl over non-empty docs, duplicate query terms accumulate."""
    tf = collections.defaultdict(collections.Counter)
    doc_lens = collections.Counter()
    for doc_id, blob in enumerate(docs, start=1):
        for raw in _C_WHITESPACE.split(blob):
            w = clean_token(raw)
            if w:
                tf[w][doc_id] += 1
                doc_lens[doc_id] += 1
    ndocs = len(doc_lens)
    avgdl = sum(doc_lens.values()) / ndocs if ndocs else 1.0
    scores = collections.defaultdict(float)
    for t in query_terms:
        postings = tf.get(t)
        if not postings:
            continue
        df = len(postings)
        idf = math.log(1.0 + (ndocs - df + 0.5) / (df + 0.5))
        for doc, f in postings.items():
            denom = f + BM25_K1 * (
                1.0 - BM25_B + BM25_B * doc_lens[doc] / avgdl)
            scores[doc] += idf * f * (BM25_K1 + 1.0) / denom
    ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
    return ranked[:k]


def test_bm25_host_matches_oracle(both_built):
    out1, out2, naive = both_built
    docs = zipf_corpus(num_docs=60, vocab_size=900, tokens_per_doc=150,
                       seed=23)
    vocab = sorted(naive)
    queries = [
        [vocab[0]],
        [vocab[0], vocab[1]],
        [vocab[3], vocab[50], vocab[200]],
        [vocab[5], vocab[5]],              # duplicate term accumulates
        [vocab[2], "zzzzabsent"],
        ["zzzzabsent"],
    ]
    with Engine(artifact_path(out2)) as eng:
        for q in queries:
            got = eng.top_k_scored(eng.encode_batch(q), k=10)
            want = _bm25_oracle(docs, q, 10)
            assert [d for d, _ in got] == [d for d, _ in want], q
            for (_, gs), (_, ws) in zip(got, want):
                assert gs == pytest.approx(ws, rel=1e-9), q


def test_bm25_v1_fallback_is_self_consistent(both_built):
    """v1 carries no term frequencies: the documented fallback scores
    with tf=1 and lengths reconstructed from the postings.  The result
    must be deterministic, positive, and rank-sane (all returned docs
    contain at least one query term)."""
    out1, out2, naive = both_built
    vocab = sorted(naive)
    q = [vocab[0], vocab[1]]
    with Engine(artifact_path(out1)) as eng:
        got = eng.top_k_scored(eng.encode_batch(q), k=10)
        assert got == eng.top_k_scored(eng.encode_batch(q), k=10)
        members = set(naive[q[0]]) | set(naive[q[1]])
        assert got and all(d in members and s > 0 for d, s in got)


def test_bm25_device_matches_host(both_built):
    out1, out2, naive = both_built
    vocab = sorted(naive)
    queries = [[vocab[0], vocab[1]], [vocab[3], vocab[50], vocab[200]],
               [vocab[5], vocab[5]], ["zzzzabsent"]]
    with Engine(artifact_path(out2)) as host:
        dev = DeviceEngine(artifact_path(out2))
        try:
            for q in queries:
                h = host.top_k_scored(host.encode_batch(q), k=10)
                d = dev.top_k_scored(dev.encode_batch(q), k=10)
                assert [x for x, _ in h] == [x for x, _ in d], q
                for (_, hs), (_, ds) in zip(h, d):
                    assert ds == pytest.approx(hs, rel=1e-4), q
        finally:
            dev.close()
