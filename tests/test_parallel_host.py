"""Multi-worker host map/reduce: steal queue, (K, M) byte-identity,
letter-partitioned parallel reduce, and counter/report merging.

The invariant under test everywhere: scheduling — worker count, reducer
count, steal interleaving — can reorder WORK but never BYTES.  Every
(num_mappers, num_reducers) combination, under any seeded shuffle of the
window hand-out order, must write exactly the oracle's letter files.
"""

import threading

import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    build_index,
    faults,
    native,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.scheduler import (
    StealQueue,
    plan_letter_ranges,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.models import (
    inverted_index as mod,
)

pytestmark = pytest.mark.parallel_host

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="no C++ toolchain")


def _small_manifest(tmp_path, num_docs=29, seed=13):
    docs = zipf_corpus(num_docs=num_docs, vocab_size=500,
                      tokens_per_doc=60, seed=seed)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    return read_manifest(tmp_path / "list.txt")


# -- StealQueue -------------------------------------------------------


def test_steal_queue_drains_complete_in_order():
    windows = [(0, 3), (3, 7), (7, 9)]
    q = StealQueue(windows)
    assert len(q) == 3
    assert q.pop_window() == (1, (0, 3))
    assert q.pop_window() == (2, (3, 7))
    assert q.pop_window() == (3, (7, 9))
    assert q.pop_window() is None
    assert q.pop_window() is None  # drained stays drained
    assert len(q) == 0


def test_steal_queue_shuffle_keeps_global_indices():
    windows = [(i, i + 1) for i in range(10)]
    q = StealQueue(windows, shuffle_seed=7)
    popped = []
    while (item := q.pop_window()) is not None:
        popped.append(item)
    # every window handed out exactly once, each with its PLAN index
    assert sorted(popped) == [(i + 1, (i, i + 1)) for i in range(10)]
    # and the seed actually shuffles (order differs from the plan)
    assert popped != sorted(popped)
    # same seed, same order: deterministic injection/repro contract
    q2 = StealQueue(windows, shuffle_seed=7)
    popped2 = [q2.pop_window() for _ in range(10)]
    assert popped2 == popped


def test_steal_queue_concurrent_drain_no_loss_no_dup():
    windows = [(i, i + 1) for i in range(200)]
    q = StealQueue(windows)
    taken = [[] for _ in range(4)]

    def worker(w):
        while (item := q.pop_window()) is not None:
            taken[w].append(item)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    merged = sorted(x for part in taken for x in part)
    assert merged == [(i + 1, (i, i + 1)) for i in range(200)]


# -- plan_letter_ranges edges -----------------------------------------


@pytest.mark.parametrize("num_reducers", [1, 2, 3, 13, 26, 27, 100])
def test_letter_ranges_partition_exactly(num_reducers):
    """The union of reducer ranges is [0, 26) with no overlap at any M,
    including the reference's degenerate M > 26 regime."""
    ranges = plan_letter_ranges(num_reducers)
    assert len(ranges) == num_reducers
    covered = []
    for lo, hi in ranges:
        assert 0 <= lo <= hi <= 26
        covered.extend(range(lo, hi))
    assert covered == list(range(26))


def test_letter_ranges_over_26_all_letters_on_last():
    ranges = plan_letter_ranges(30)
    assert all(lo == hi for lo, hi in ranges[:-1])
    assert ranges[-1] == (0, 26)


# -- native merge parity ----------------------------------------------


@needs_native
def test_host_merge_matches_single_stream(tmp_path):
    docs = zipf_corpus(num_docs=31, vocab_size=400, tokens_per_doc=50,
                      seed=4)
    contents = [d.encode() if isinstance(d, str) else d for d in docs]
    doc_ids = list(range(1, len(contents) + 1))

    with native.HostIndexStream() as single:
        single.feed(contents, doc_ids)
        stats = single.finalize_emit(tmp_path / "single")
    golden = read_letter_files(tmp_path / "single")

    streams = [native.HostIndexStream() for _ in range(3)]
    try:
        for i, (c, d) in enumerate(zip(contents, doc_ids)):
            streams[i % 3].feed([c], [d])
        for s in streams:
            p = s.partial()
            assert p["partial_ms"] >= 0.0
        with native.HostIndexMerge(streams) as merge:
            total = sum(merge.emit_range(lo, hi, tmp_path / "merged")
                        for lo, hi in plan_letter_ranges(5))
            mstats = merge.stats()
    finally:
        for s in streams:
            s.close()
    assert read_letter_files(tmp_path / "merged") == golden
    assert total == stats["bytes_written"]
    assert mstats["unique_terms"] == stats["unique_terms"]
    assert mstats["tokens"] == stats["tokens"]
    assert mstats["unique_pairs"] == stats["unique_pairs"]


@needs_native
def test_host_merge_out_of_window_order_feed(tmp_path):
    """A worker that consumed its windows in stolen (non-plan) order
    still merges byte-identically — partial() re-sorts each run."""
    docs = zipf_corpus(num_docs=19, vocab_size=300, tokens_per_doc=40,
                      seed=6)
    contents = [d.encode() if isinstance(d, str) else d for d in docs]
    doc_ids = list(range(1, len(contents) + 1))
    with native.HostIndexStream() as single:
        single.feed(contents, doc_ids)
        single.finalize_emit(tmp_path / "single")
    golden = read_letter_files(tmp_path / "single")

    s = native.HostIndexStream()
    try:
        for c, d in reversed(list(zip(contents, doc_ids))):
            s.feed([c], [d])
        with native.HostIndexMerge([s]) as merge:
            merge.emit_range(0, 26, tmp_path / "rev")
    finally:
        s.close()
    assert read_letter_files(tmp_path / "rev") == golden


# -- end-to-end (K, M) matrix -----------------------------------------


@needs_native
@pytest.mark.parametrize("mappers", [1, 2, 4])
@pytest.mark.parametrize("reducers", [1, 3, 26])
def test_parallel_cpu_matrix_matches_oracle(tmp_path, monkeypatch,
                                            mappers, reducers):
    monkeypatch.setattr(mod.InvertedIndexModel, "_CPU_WINDOW_BYTES", 1 << 9)
    m = _small_manifest(tmp_path)
    oracle_index(m, tmp_path / "oracle")
    out = tmp_path / f"k{mappers}m{reducers}"
    r = build_index(m, IndexConfig(backend="cpu", num_mappers=mappers,
                                   num_reducers=reducers, io_prefetch=2),
                    output_dir=out)
    assert read_letter_files(out) == read_letter_files(tmp_path / "oracle")
    # --host-threads plumbing regression: the pipelined path reports
    # the RESOLVED worker count, not a hardwired 1
    assert r["host_threads"] == mappers
    assert r["io_windows"] > mappers  # the plan actually shards
    if mappers > 1 or reducers > 1:
        assert r["reduce_workers"] == reducers
        assert len(r["stage_read_ms_per_worker"]) == mappers
        assert len(r["stage_tokenize_ms_per_worker"]) == mappers
        assert len(r["stage_emit_ms_per_reducer"]) == reducers
    for key in ("stage_read_ms", "stage_tokenize_ms", "stage_emit_ms"):
        assert key in r


@needs_native
@pytest.mark.parametrize("seed", [1, 42, 20260805])
def test_steal_order_shuffle_never_changes_output(tmp_path, monkeypatch,
                                                  seed):
    """Adversarial scheduling: hand windows to workers in seeded-random
    order and the emitted bytes must not move."""
    monkeypatch.setattr(mod.InvertedIndexModel, "_CPU_WINDOW_BYTES", 1 << 9)
    m = _small_manifest(tmp_path, num_docs=37, seed=2)
    oracle_index(m, tmp_path / "oracle")
    monkeypatch.setenv("MRI_STEAL_SHUFFLE_SEED", str(seed))
    out = tmp_path / f"shuf{seed}"
    build_index(m, IndexConfig(backend="cpu", num_mappers=3,
                               num_reducers=4, io_prefetch=2),
                output_dir=out)
    assert read_letter_files(out) == read_letter_files(tmp_path / "oracle")


@needs_native
def test_host_threads_flag_drives_workers(tmp_path):
    """--host-threads wins over num_mappers, and the stats report it."""
    m = _small_manifest(tmp_path, num_docs=11, seed=1)
    r = build_index(m, IndexConfig(backend="cpu", num_mappers=1,
                                   host_threads=3, io_prefetch=2),
                    output_dir=tmp_path / "ht")
    assert r["host_threads"] == 3
    assert len(r["stage_read_ms_per_worker"]) == 3


# -- DegradationReport merging ----------------------------------------


def test_degradation_report_merge():
    a = faults.DegradationReport()
    b = faults.DegradationReport()
    a.record_retry()
    b.record_retry()
    b.record_retry()
    b.record_skip(doc_id=7, path="x", reason="boom")
    b.record_worker_recovery(windows_requeued=3)
    b.record_reducer_takeover()
    a.record_worker_recovery(windows_requeued=1)
    a.merge(b)
    a.merge(a)  # self-merge is a no-op, not a deadlock or double-count
    s = a.summary()
    assert s["read_retries"] == 3
    assert s["skipped_docs"] == [7]
    assert s["worker_recoveries"] == 2
    assert s["windows_requeued"] == 4
    assert s["reducer_takeovers"] == 1
    assert b.summary()["read_retries"] == 2  # source unchanged
    # recoveries alone never flip the report degraded (exit stays 0)
    assert b.degraded  # b carries a real skip
    c = faults.DegradationReport()
    c.record_worker_recovery(windows_requeued=2)
    c.record_reducer_takeover()
    assert not c.degraded


@needs_native
def test_multi_worker_degraded_run_reports_all_skips(tmp_path):
    """K workers, one unreadable doc: the skip lands in the run-scoped
    report (merged from the worker's private report) and rides the
    stats dict — the CLI's exit-3 source of truth."""
    m = _small_manifest(tmp_path, num_docs=12, seed=3)
    bad_doc = m.paths[5]
    import os

    os.unlink(bad_doc)  # hard skip: no retry can save it
    try:
        faults.install(None)
        faults.begin_run()
        r = build_index(m, IndexConfig(backend="cpu", num_mappers=3,
                                       num_reducers=2, io_prefetch=2),
                        output_dir=tmp_path / "deg")
    finally:
        faults.install(None)
        faults.begin_run()
    assert r["degradation"]["skipped_docs"] == [6]  # 1-based doc id
    assert "6" in r["degradation"]["skip_reasons"]
