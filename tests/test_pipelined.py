"""Pipelined single-chip fast path: provisional-key uploads overlapped
with tokenization (models/inverted_index._run_tpu_pipelined +
ops/engine.sort_prov_chunks + native.NativeKeyStream).

The invariant under test: for ANY window size, output is byte-identical
to the oracle / goldens — provisional ids are first-occurrence-stable,
so the device sort groups identically however the stream is windowed.
"""

import numpy as np
import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
    build_index,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import native
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native tokenizer unavailable")


def _cfg(**kw):
    kw.setdefault("backend", "tpu")
    kw.setdefault("device_shards", 1)  # 8 virtual devices otherwise -> dist
    kw.setdefault("pad_multiple", 64)
    return IndexConfig(**kw)


@pytest.mark.parametrize("chunk_docs", [1, 2, 100])
def test_matches_goldens_any_window(smoke_fixture, tmp_path, chunk_docs):
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    model = InvertedIndexModel(_cfg(pipeline_chunk_docs=chunk_docs))
    report = model.run(m, output_dir=tmp_path)
    assert "tokenize_feed" in report["phases_ms"]  # really took the fast path
    assert read_letter_files(tmp_path) == read_letter_files(smoke_fixture / "golden")


def test_default_config_single_chip_takes_pipelined_path(smoke_fixture, tmp_path):
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    report = InvertedIndexModel(_cfg()).run(m, output_dir=tmp_path)
    assert "tokenize_feed" in report["phases_ms"]
    assert report["upload_windows"] == 2  # auto = two windows
    assert read_letter_files(tmp_path) == read_letter_files(smoke_fixture / "golden")


def test_chunk_zero_disables(smoke_fixture, tmp_path):
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    report = InvertedIndexModel(_cfg(pipeline_chunk_docs=0)).run(
        m, output_dir=tmp_path)
    assert "tokenize_feed" not in report["phases_ms"]
    assert read_letter_files(tmp_path) == read_letter_files(smoke_fixture / "golden")


def test_property_random_corpus_vs_oracle(tmp_path):
    docs = zipf_corpus(num_docs=41, vocab_size=700, tokens_per_doc=80, seed=3)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    build_index(m, _cfg(pipeline_chunk_docs=7), output_dir=tmp_path / "pipe")
    assert read_letter_files(tmp_path / "pipe") == read_letter_files(tmp_path / "oracle")


def test_vocab_beyond_u16_uses_int32_windows(tmp_path):
    """A window whose provisional ids exceed 0xFFFE must switch that
    window's upload to int32 keys and still match the oracle."""

    def word(i: int) -> str:  # letters-only base-26 encoding
        s = ""
        while True:
            s += chr(ord("a") + i % 26)
            i //= 26
            if not i:
                return s

    n = 0x10000 + 50
    half = n // 2
    docs = [
        " ".join(word(i) for i in range(half)).encode(),
        " ".join(word(i) for i in range(half, n)).encode(),
    ]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    report = InvertedIndexModel(_cfg(pipeline_chunk_docs=1)).run(
        m, output_dir=tmp_path / "pipe")
    assert report["unique_terms"] == n  # second window really crossed 0xFFFE
    assert read_letter_files(tmp_path / "pipe") == read_letter_files(tmp_path / "oracle")


def test_empty_corpus_writes_26_empty_files(tmp_path):
    (tmp_path / "empty.txt").write_bytes(b"   \n\t \n")
    write_manifest(tmp_path / "list.txt", [str(tmp_path / "empty.txt")])
    m = read_manifest(tmp_path / "list.txt")
    report = InvertedIndexModel(_cfg()).run(m, output_dir=tmp_path / "out")
    assert read_letter_files(tmp_path / "out") == b""
    assert report["unique_terms"] == 0


def test_key_stream_matches_one_shot_tokenizer(smoke_fixture):
    """The incremental stream and the one-shot native tokenizer must
    describe the same (word, doc) pair set, df and vocab."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        load_documents,
    )

    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    contents, doc_ids = load_documents(m)
    one = native.tokenize_native(contents, doc_ids, dedup_pairs=True)

    stride = len(m) + 2
    with native.NativeKeyStream(stride) as stream:
        all_keys = []
        for i in range(len(contents)):  # one-doc windows: worst case
            keys, _ = stream.feed([contents[i]], [doc_ids[i]])
            all_keys.append(keys)
        (vocab, letters, remap, df_prov, raw_tokens, num_pairs,
         emit_order) = stream.finalize()

    assert np.array_equal(vocab, one.vocab)
    assert raw_tokens == one.raw_tokens
    assert num_pairs == one.num_tokens
    keys = np.concatenate(all_keys) if all_keys else np.empty(0, np.int32)
    # prov keys -> (rank, doc) pairs must equal the one-shot pair set
    prov, doc = keys // stride, keys % stride
    got = set(zip(remap[prov].tolist(), doc.tolist()))
    want = set(zip(one.term_ids.tolist(), one.doc_ids.tolist()))
    assert got == want
    # df in prov space == bincount of one-shot rank ids pushed through remap
    df_rank = np.zeros(len(vocab), np.int64)
    df_rank[remap] = df_prov
    assert np.array_equal(df_rank, np.bincount(one.term_ids, minlength=len(vocab)))


def test_key_overflow_falls_back_to_one_shot(tmp_path, monkeypatch):
    """A mid-stream int32 key overflow must transparently restart on the
    one-shot path with identical output."""
    docs = zipf_corpus(num_docs=9, vocab_size=300, tokens_per_doc=50, seed=11)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")

    real_feed = native.NativeKeyStream.feed
    real_feed_u16 = native.NativeKeyStream.feed_u16

    def exploding_feed(self, contents, doc_ids):
        if doc_ids and doc_ids[0] > 5:
            raise native.KeyOverflow()
        return real_feed(self, contents, doc_ids)

    def exploding_feed_u16(self, contents, doc_ids, granule=1 << 14):
        if doc_ids and doc_ids[0] > 5:
            raise native.KeyOverflow()
        return real_feed_u16(self, contents, doc_ids, granule)

    monkeypatch.setattr(native.NativeKeyStream, "feed", exploding_feed)
    monkeypatch.setattr(native.NativeKeyStream, "feed_u16", exploding_feed_u16)
    report = InvertedIndexModel(_cfg(pipeline_chunk_docs=2)).run(
        m, output_dir=tmp_path / "out")
    assert report["pipelined_fallback"] == "key_overflow"
    assert "tokenize_feed" not in report["phases_ms"]
    assert read_letter_files(tmp_path / "out") == read_letter_files(tmp_path / "oracle")


def test_pipelined_host_threads_output_invariant(tmp_path):
    """The pipelined TPU path with a multithreaded native scan is
    byte-identical to the single-threaded run (prov numbering differs;
    rank space cannot)."""
    if not native.available():
        pytest.skip("no C++ toolchain")
    docs = zipf_corpus(num_docs=37, vocab_size=500, tokens_per_doc=120, seed=5)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    outs = []
    for threads in (1, 3):
        out = tmp_path / f"t{threads}"
        report = InvertedIndexModel(IndexConfig(
            backend="tpu", device_shards=1, host_threads=threads,
        )).run(m, output_dir=out)
        assert report["host_threads"] == threads
        outs.append(read_letter_files(out))
    assert outs[0] == outs[1]
