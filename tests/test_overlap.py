"""Windowed overlap plan (models/inverted_index._run_tpu_overlap):
device windows are sorted + fetched asynchronously while the host scans
later windows; the last ``overlap_tail_fraction`` of bytes is indexed on
host; emit concatenates the per-window runs (native mri_emit_runs).

The invariant under test: for ANY tail fraction, output is byte-identical
to the oracle / goldens — windows are contiguous ascending doc ranges,
so per-term run concatenation in window order IS the merged postings
list (the reference re-derives the same grouping by re-reading spill
text, main.c:170-212).
"""

import numpy as np
import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import native
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    Manifest,
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.scheduler import (
    plan_fraction_windows,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native tokenizer unavailable")


def _cfg(**kw):
    kw.setdefault("backend", "tpu")
    kw.setdefault("device_shards", 1)
    kw.setdefault("pad_multiple", 64)
    kw.setdefault("overlap_tail_fraction", 0.4)
    return IndexConfig(**kw)


@pytest.mark.parametrize("tail", [0.1, 0.4, 0.9])
def test_matches_goldens_any_fraction(smoke_fixture, tmp_path, tail):
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    report = InvertedIndexModel(
        _cfg(overlap_tail_fraction=tail)).run(m, output_dir=tmp_path)
    assert "host_tail" in report["phases_ms"]  # really took the overlap plan
    assert read_letter_files(tmp_path) == read_letter_files(smoke_fixture / "golden")


@pytest.mark.parametrize("tail,threads", [(0.15, 1), (0.5, 4), (0.85, 1)])
def test_property_random_corpus_vs_oracle(tmp_path, tail, threads):
    # threads=4 pins the MT branch of the native df-snapshot fold
    # (mri_stream_df_snapshot) on single-core CI runners, where the
    # default host_threads would resolve to 1
    docs = zipf_corpus(num_docs=53, vocab_size=900, tokens_per_doc=70, seed=11)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    report = InvertedIndexModel(
        _cfg(overlap_tail_fraction=tail, host_threads=threads)).run(
        m, output_dir=tmp_path / "ovl")
    assert read_letter_files(tmp_path / "ovl") == read_letter_files(tmp_path / "oracle")
    # every pair lands in exactly one run
    assert report["device_pairs"] <= report["unique_pairs"]


def test_device_actually_covers_pairs(tmp_path):
    """A small tail fraction must leave most pairs on the device side."""
    docs = zipf_corpus(num_docs=64, vocab_size=500, tokens_per_doc=60, seed=5)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    report = InvertedIndexModel(
        _cfg(overlap_tail_fraction=0.2)).run(m, output_dir=tmp_path / "out")
    assert report["upload_windows"] >= 1
    assert report["device_pairs"] > report["unique_pairs"] // 2


def test_tiny_corpus_single_device_window(tmp_path):
    """< 8 docs degenerates to one device window + tail, still correct."""
    docs = [b"alpha beta gamma", b"beta beta delta", b"zeta alpha"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    InvertedIndexModel(_cfg()).run(m, output_dir=tmp_path / "ovl")
    assert read_letter_files(tmp_path / "ovl") == read_letter_files(tmp_path / "oracle")


def test_empty_corpus(tmp_path):
    (tmp_path / "e.txt").write_text("   \n\t  ")
    write_manifest(tmp_path / "list.txt", [tmp_path / "e.txt"])
    m = read_manifest(tmp_path / "list.txt")
    InvertedIndexModel(_cfg()).run(m, output_dir=tmp_path / "out")
    assert read_letter_files(tmp_path / "out") == b""


def test_numbers_only_tail(tmp_path):
    """Tail window that cleans to zero pairs."""
    docs = [b"alpha beta", b"gamma delta epsilon", b"123 456 --- !!"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    InvertedIndexModel(
        _cfg(overlap_tail_fraction=0.2)).run(m, output_dir=tmp_path / "ovl")
    assert read_letter_files(tmp_path / "ovl") == read_letter_files(tmp_path / "oracle")


def test_multi_chip_rejected(tmp_path):
    (tmp_path / "d.txt").write_text("hello world")
    write_manifest(tmp_path / "list.txt", [tmp_path / "d.txt"])
    m = read_manifest(tmp_path / "list.txt")
    model = InvertedIndexModel(
        _cfg(device_shards=4, overlap_tail_fraction=0.4))
    with pytest.raises(ValueError, match="single-chip"):
        model.run(m, output_dir=tmp_path / "out")


def test_config_validation():
    with pytest.raises(ValueError, match="overlap_tail_fraction"):
        IndexConfig(overlap_tail_fraction=0.0)
    with pytest.raises(ValueError, match="overlap_tail_fraction"):
        IndexConfig(overlap_tail_fraction=1.0)
    with pytest.raises(ValueError, match="backend"):
        IndexConfig(backend="cpu", overlap_tail_fraction=0.5)
    with pytest.raises(ValueError, match="pipelined"):
        IndexConfig(overlap_tail_fraction=0.5, pipeline_chunk_docs=0)
    with pytest.raises(ValueError, match="stream_chunk_docs"):
        IndexConfig(overlap_tail_fraction=0.5, stream_chunk_docs=100)
    with pytest.raises(ValueError, match="letter"):
        IndexConfig(overlap_tail_fraction=0.5, emit_ownership="letter")


# -- plan_fraction_windows ------------------------------------------------


def _manifest(sizes):
    return Manifest(paths=tuple(f"f{i}" for i in range(len(sizes))),
                    sizes=tuple(sizes))


def test_fraction_windows_cover_everything():
    m = _manifest([10, 30, 5, 5, 50, 10, 20, 70])
    for fr in [(0.5, 0.5), (0.3, 0.3, 0.4), (0.05, 0.95)]:
        w = plan_fraction_windows(m, fr)
        assert w[0][0] == 0 and w[-1][1] == len(m)
        for (a, b), (c, d) in zip(w, w[1:]):
            assert b == c  # contiguous, no gaps

def test_fraction_windows_byte_shares():
    m = _manifest([10] * 100)
    w = plan_fraction_windows(m, (0.25, 0.25, 0.5))
    assert w == ((0, 25), (25, 50), (50, 100))


def test_fraction_windows_rejects_bad_fractions():
    m = _manifest([10])
    with pytest.raises(ValueError):
        plan_fraction_windows(m, ())
    with pytest.raises(ValueError):
        plan_fraction_windows(m, (0.5, -0.5, 1.0))
    with pytest.raises(ValueError):
        plan_fraction_windows(m, (0.5, 0.2))


# -- native u16 feed -----------------------------------------------------


def _feed_docs(words):
    contents = [(" ".join(words)).encode()]
    return contents, [1]


def test_feed_u16_overflow_guard():
    """u16 mode must refuse keys the device would wrap past int32.

    With a huge stride, 40 distinct prov ids already exceed
    INT32_MAX when packed as ``id * stride + doc`` — the feed must take
    the int32 branch and raise KeyOverflow, never hand the device a
    uint16 buffer it would decode into wrapped (corrupt) keys.
    """
    words = [f"w{chr(97 + i)}{chr(97 + j)}" for i in range(8) for j in range(6)]
    stream = native.NativeKeyStream(1 << 26, num_threads=1)
    try:
        with pytest.raises(native.KeyOverflow):
            stream.feed_u16(*_feed_docs(words))
    finally:
        stream.close()


def test_feed_u16_near_boundary_still_u16():
    """Just under the int32 key bound, u16 mode stays on and decodes right."""
    words = [f"x{chr(97 + i)}" for i in range(20)]
    stride = 1 << 26  # 19 * 2^26 + doc < INT32_MAX
    stream = native.NativeKeyStream(stride, num_threads=1)
    try:
        mode, buf, n, _ = stream.feed_u16(*_feed_docs(words), granule=8)
        assert mode == "u16" and n == 20
        padded = buf.shape[0] // 2
        terms, docs = buf[:n], buf[padded: padded + n]
        assert sorted(terms.tolist()) == list(range(20))
        assert (docs == 1).all()
    finally:
        stream.close()


# -- native multi-run emit -----------------------------------------------


def test_emit_runs_matches_single_run(tmp_path):
    """Splitting postings into runs must render byte-identically."""
    rng = np.random.default_rng(7)
    vocab = np.sort(np.array(
        [b"ant", b"bee", b"cat", b"dog", b"emu", b"fox"], dtype="S3"))
    v = len(vocab)
    df = rng.integers(1, 9, size=v).astype(np.int64)
    offsets = np.cumsum(df) - df
    postings = np.concatenate(
        [np.sort(rng.choice(50, size=n, replace=False)) + 1 for n in df]
    ).astype(np.uint16)
    letters = np.array([w[0] - ord("a") for w in vocab.tolist()])
    order = np.lexsort((-df, letters))

    native.emit_native(tmp_path / "one", vocab, order, df, offsets, postings)

    # split each term's postings at a random point into run A and run B
    split = np.array([rng.integers(0, n + 1) for n in df], dtype=np.int64)
    ca, cb = split, df - split
    oa = np.cumsum(ca) - ca
    ob = np.cumsum(cb) - cb
    run_a = np.concatenate(
        [postings[offsets[t]: offsets[t] + ca[t]] for t in range(v)]
    ).astype(np.uint16) if ca.sum() else np.empty(0, np.uint16)
    run_b = np.concatenate(
        [postings[offsets[t] + ca[t]: offsets[t] + df[t]] for t in range(v)]
    ).astype(np.uint16) if cb.sum() else np.empty(0, np.uint16)
    native.emit_native_runs(
        tmp_path / "two", vocab, order,
        [(run_a, oa, ca), (run_b, ob, cb)])
    assert read_letter_files(tmp_path / "two") == read_letter_files(tmp_path / "one")


def test_emit_runs_empty_runs(tmp_path):
    vocab = np.array([b"abc"], dtype="S3")
    order = np.array([0], dtype=np.int64)
    zero = np.zeros(1, np.int64)
    one = np.ones(1, np.int64)
    native.emit_native_runs(
        tmp_path / "out", vocab, order,
        [(np.empty(0, np.uint16), zero, zero),
         (np.array([3], np.uint16), zero, one)])
    assert (tmp_path / "out" / "a.txt").read_bytes() == b"abc:[3]\n"


def test_overlap_window_split_is_exact_and_validated(tmp_path):
    """Any window split must stay byte-identical (the split only moves
    the upload boundary); out-of-range splits are rejected loudly."""
    docs = zipf_corpus(num_docs=24, vocab_size=300, tokens_per_doc=50, seed=5)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    for split in (0.25, 0.75):
        InvertedIndexModel(
            _cfg(overlap_tail_fraction=0.5, overlap_window_split=split)
        ).run(m, output_dir=tmp_path / f"s{split}")
        assert read_letter_files(tmp_path / f"s{split}") == \
            read_letter_files(tmp_path / "oracle")
    with pytest.raises(ValueError, match="overlap_window_split"):
        IndexConfig(overlap_window_split=1.5)
