"""Byte-soup fuzzing: every frontend and backend must agree on
adversarial input (SURVEY.md §4 item 3, pushed past printable text).

The reference's contract is byte-level (fscanf %s whitespace split +
letters-only cleaning, main.c:102-117), so the fuzz corpus draws from
the full byte range: NULs, control bytes, UTF-8 runs, \r\n soup, long
unbroken tokens, pure-garbage documents.
"""

import numpy as np
import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    build_index,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import native
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    tokenize,
)


def _byte_soup_docs(seed: int, num_docs: int) -> list[bytes]:
    rng = np.random.default_rng(seed)
    docs = []
    for _ in range(num_docs):
        kind = rng.integers(0, 5)
        n = int(rng.integers(0, 400))
        if kind == 0:      # uniform random bytes (NULs, controls, UTF-8 junk)
            doc = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
        elif kind == 1:    # whitespace soup with occasional letters
            pool = np.frombuffer(b" \t\n\v\f\rab", dtype=np.uint8)
            doc = bytes(pool[rng.integers(0, len(pool), size=n)])
        elif kind == 2:    # long unbroken token (cap-299 exercise)
            pool = np.frombuffer(b"abcXYZ019-'", dtype=np.uint8)
            doc = bytes(pool[rng.integers(0, len(pool), size=int(rng.integers(300, 900)))])
        elif kind == 3:    # words with mixed-in garbage
            words = [
                bytes(rng.integers(ord("a"), ord("z") + 1, size=int(rng.integers(1, 8)),
                                   dtype=np.uint8))
                + bytes(rng.integers(0, 256, size=int(rng.integers(0, 3)), dtype=np.uint8))
                for _ in range(int(rng.integers(0, 60)))
            ]
            doc = b" ".join(words)
        else:              # empty / whitespace-only
            doc = b" \t \r\n" * int(rng.integers(0, 4))
        docs.append(doc)
    return docs


def _dict_oracle_pairs(docs: list[bytes]) -> set:
    """Trivial per-byte reimplementation of the contract (SURVEY.md §2.3)."""
    space = b" \t\n\v\f\r"
    out = set()
    for i, doc in enumerate(docs, start=1):
        for token in _split_c_locale(doc, space):
            word = bytes(
                c + 32 if ord("A") <= c <= ord("Z") else c
                for c in token if chr(c).isascii() and chr(c).isalpha()
            )[:299]
            if word:
                out.add((word.decode("ascii"), i))
    return out


def _split_c_locale(doc: bytes, space: bytes) -> list[bytes]:
    tokens, cur = [], bytearray()
    for b in doc:
        if b in space:
            if cur:
                tokens.append(bytes(cur))
                cur = bytearray()
        else:
            cur.append(b)
    if cur:
        tokens.append(bytes(cur))
    return tokens


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_frontends_agree_on_byte_soup(seed):
    docs = _byte_soup_docs(seed, 30)
    ids = list(range(1, len(docs) + 1))
    np_corpus = tokenize(docs, ids, use_native=False, dedup_pairs=True)
    want = _dict_oracle_pairs(docs)
    words = np_corpus.vocab_strings()
    got_np = {(words[t], int(d)) for t, d in zip(np_corpus.term_ids, np_corpus.doc_ids)}
    assert got_np == want
    if native.available():
        nat = native.tokenize_native(docs, ids, dedup_pairs=True)
        words_n = [w.rstrip(b"\x00").decode("ascii") for w in nat.vocab.tolist()]
        got_nat = {(words_n[t], int(d)) for t, d in zip(nat.term_ids, nat.doc_ids)}
        assert got_nat == want


@pytest.mark.parametrize("seed", [3, 4])
def test_backends_agree_on_byte_soup(tmp_path, seed):
    docs = _byte_soup_docs(seed, 25)
    paths = []
    for i, doc in enumerate(docs):
        p = tmp_path / f"doc{i:03d}.bin"
        p.write_bytes(doc)
        paths.append(str(p))
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    golden = read_letter_files(tmp_path / "oracle")
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64, device_shards=1),
                output_dir=tmp_path / "pipe")
    assert read_letter_files(tmp_path / "pipe") == golden
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64),
                output_dir=tmp_path / "dist")
    assert read_letter_files(tmp_path / "dist") == golden
    build_index(m, IndexConfig(backend="cpu"), output_dir=tmp_path / "cpu")
    assert read_letter_files(tmp_path / "cpu") == golden


@pytest.mark.parametrize("seed", [5, 6])
def test_mt_and_letter_emit_agree_on_byte_soup(tmp_path, seed):
    """Multithreaded scan and letter-ownership emit under byte soup."""
    if not native.available():
        pytest.skip("letter emit requires the pipelined (native) path")
    docs = _byte_soup_docs(seed, 25)
    ids = list(range(1, len(docs) + 1))
    if native.available():
        st = native.tokenize_native(docs, ids, dedup_pairs=True, num_threads=1)
        mt = native.tokenize_native(docs, ids, dedup_pairs=True, num_threads=5)
        np.testing.assert_array_equal(st.term_ids, mt.term_ids)
        np.testing.assert_array_equal(st.doc_ids, mt.doc_ids)
        np.testing.assert_array_equal(st.vocab, mt.vocab)
    paths = []
    for i, doc in enumerate(docs):
        p = tmp_path / f"doc{i:03d}.bin"
        p.write_bytes(doc)
        paths.append(str(p))
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    golden = read_letter_files(tmp_path / "oracle")
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64,
                               emit_ownership="letter", host_threads=3),
                output_dir=tmp_path / "letter")
    assert read_letter_files(tmp_path / "letter") == golden
