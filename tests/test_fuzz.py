"""Byte-soup fuzzing: every frontend and backend must agree on
adversarial input (SURVEY.md §4 item 3, pushed past printable text).

The reference's contract is byte-level (fscanf %s whitespace split +
letters-only cleaning, main.c:102-117), so the fuzz corpus draws from
the full byte range: NULs, control bytes, UTF-8 runs, \r\n soup, long
unbroken tokens, pure-garbage documents.
"""

import numpy as np
import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    build_index,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import native
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    tokenize,
)


def _byte_soup_docs(seed: int, num_docs: int) -> list[bytes]:
    rng = np.random.default_rng(seed)
    docs = []
    for _ in range(num_docs):
        kind = rng.integers(0, 5)
        n = int(rng.integers(0, 400))
        if kind == 0:      # uniform random bytes (NULs, controls, UTF-8 junk)
            doc = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
        elif kind == 1:    # whitespace soup with occasional letters
            pool = np.frombuffer(b" \t\n\v\f\rab", dtype=np.uint8)
            doc = bytes(pool[rng.integers(0, len(pool), size=n)])
        elif kind == 2:    # long unbroken token (cap-299 exercise)
            pool = np.frombuffer(b"abcXYZ019-'", dtype=np.uint8)
            doc = bytes(pool[rng.integers(0, len(pool), size=int(rng.integers(300, 900)))])
        elif kind == 3:    # words with mixed-in garbage
            words = [
                bytes(rng.integers(ord("a"), ord("z") + 1, size=int(rng.integers(1, 8)),
                                   dtype=np.uint8))
                + bytes(rng.integers(0, 256, size=int(rng.integers(0, 3)), dtype=np.uint8))
                for _ in range(int(rng.integers(0, 60)))
            ]
            doc = b" ".join(words)
        else:              # empty / whitespace-only
            doc = b" \t \r\n" * int(rng.integers(0, 4))
        docs.append(doc)
    return docs


def _dict_oracle_pairs(docs: list[bytes]) -> set:
    """Trivial per-byte reimplementation of the contract (SURVEY.md §2.3)."""
    space = b" \t\n\v\f\r"
    out = set()
    for i, doc in enumerate(docs, start=1):
        for token in _split_c_locale(doc, space):
            word = bytes(
                c + 32 if ord("A") <= c <= ord("Z") else c
                for c in token if chr(c).isascii() and chr(c).isalpha()
            )[:299]
            if word:
                out.add((word.decode("ascii"), i))
    return out


def _split_c_locale(doc: bytes, space: bytes) -> list[bytes]:
    tokens, cur = [], bytearray()
    for b in doc:
        if b in space:
            if cur:
                tokens.append(bytes(cur))
                cur = bytearray()
        else:
            cur.append(b)
    if cur:
        tokens.append(bytes(cur))
    return tokens


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_frontends_agree_on_byte_soup(seed):
    docs = _byte_soup_docs(seed, 30)
    ids = list(range(1, len(docs) + 1))
    np_corpus = tokenize(docs, ids, use_native=False, dedup_pairs=True)
    want = _dict_oracle_pairs(docs)
    words = np_corpus.vocab_strings()
    got_np = {(words[t], int(d)) for t, d in zip(np_corpus.term_ids, np_corpus.doc_ids)}
    assert got_np == want
    if native.available():
        nat = native.tokenize_native(docs, ids, dedup_pairs=True)
        words_n = [w.rstrip(b"\x00").decode("ascii") for w in nat.vocab.tolist()]
        got_nat = {(words_n[t], int(d)) for t, d in zip(nat.term_ids, nat.doc_ids)}
        assert got_nat == want


def _soup_corpus(tmp_path, seed: int, num_docs: int = 25):
    """Byte-soup corpus on disk + oracle golden: (manifest, golden)."""
    docs = _byte_soup_docs(seed, num_docs)
    paths = []
    for i, doc in enumerate(docs):
        p = tmp_path / f"doc{i:03d}.bin"
        p.write_bytes(doc)
        paths.append(str(p))
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    return m, read_letter_files(tmp_path / "oracle")


@pytest.mark.parametrize("seed", [3, 4])
def test_backends_agree_on_byte_soup(tmp_path, seed):
    m, golden = _soup_corpus(tmp_path, seed)
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64, device_shards=1),
                output_dir=tmp_path / "pipe")
    assert read_letter_files(tmp_path / "pipe") == golden
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64),
                output_dir=tmp_path / "dist")
    assert read_letter_files(tmp_path / "dist") == golden
    build_index(m, IndexConfig(backend="cpu"), output_dir=tmp_path / "cpu")
    assert read_letter_files(tmp_path / "cpu") == golden


@pytest.mark.parametrize("seed", [5, 6])
def test_device_stream_engines_agree_on_byte_soup(tmp_path, seed):
    """Byte soup (NULs, punctuation runs, width-overflow-adjacent
    tokens) through the streaming all-device engines, single chip and
    mesh — the device byte classifier + row accumulators against the
    oracle on inputs far uglier than Zipf words."""
    m, golden = _soup_corpus(tmp_path, seed)
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64,
                               device_tokenize=True, device_shards=1,
                               stream_chunk_docs=4),
                output_dir=tmp_path / "ds1")
    assert read_letter_files(tmp_path / "ds1") == golden
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64,
                               device_tokenize=True, stream_chunk_docs=6),
                output_dir=tmp_path / "dsm")
    assert read_letter_files(tmp_path / "dsm") == golden


def test_simd_scan_boundary_cases():
    """Deterministic adversarial cases for the mask-driven SIMD scan
    (native/tokenizer.cc ScanChunkSimd): tokens at the exact buffer
    end, tokens spanning 64-byte mask-word boundaries, raw-cache
    aliasing via trailing NULs, and the 299-letter cap across pext
    chunks.  The numpy frontend is the reference implementation."""
    docs = [
        b"endtoken",                          # 8-byte token, no trailing space, buffer end
        b" " * 60 + b"crossingboundary",      # token spans the 64-byte mask word
        b"ab ab\x00 ab\x00\x00 ab",           # trailing NULs clean to the same word
        b"x" * 298 + b"-" + b"y" * 20,        # cap at 299 across pext chunks
        b"123 --- \x00\x00\x00",              # tokens that clean to nothing
        b"the the the the the the the",       # hot cache-hit path + combiner dedup
        b"a" * 63 + b" " + b"b" * 64,         # runs aligned to mask-word edges
        b"tail7zz",                           # 7-byte token at buffer end
        # 9..16-byte tokens: the medium (128-bit-tag) raw cache —
        # repeats (hits), punctuated variants (distinct tags, same
        # cleaned word), and a 16-byte token at the exact buffer end
        b"mediumtoken mediumtoken medium-token Mediumtoken",
        b"d'argenson-like d'argenson-like 1234567890123 word",
        b"x" * 15 + b" " + b"q" * 16,
    ]
    ids = list(range(1, len(docs) + 1))
    ref = tokenize(docs, ids, use_native=False, dedup_pairs=True)
    words = ref.vocab_strings()
    want = {(words[t], int(d)) for t, d in zip(ref.term_ids, ref.doc_ids)}
    if not native.available():
        pytest.skip("native tokenizer unavailable")
    for threads in (1, 3):
        nat = native.tokenize_native(docs, ids, dedup_pairs=True,
                                     num_threads=threads)
        words_n = [w.rstrip(b"\x00").decode("ascii") for w in nat.vocab.tolist()]
        got = {(words_n[t], int(d)) for t, d in zip(nat.term_ids, nat.doc_ids)}
        assert got == want, f"threads={threads}"
    # the capped long token must keep exactly the first 299 letters
    capped = [w for w in want if len(w[0]) == 299]
    assert capped and capped[0][0] == "x" * 298 + "y"


@pytest.mark.parametrize("seed", [7, 8])
def test_overlap_plan_agrees_on_byte_soup(tmp_path, seed):
    """The windowed overlap plan under byte soup (device windows + host
    tail + multi-run emit must agree with the oracle byte-for-byte)."""
    if not native.available():
        pytest.skip("overlap requires the pipelined (native) path")
    m, golden = _soup_corpus(tmp_path, seed)
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64, device_shards=1,
                               overlap_tail_fraction=0.4),
                output_dir=tmp_path / "ovl")
    assert read_letter_files(tmp_path / "ovl") == golden


@pytest.mark.parametrize("seed", [5, 6])
def test_mt_and_letter_emit_agree_on_byte_soup(tmp_path, seed):
    """Multithreaded scan and letter-ownership emit under byte soup."""
    import jax

    if not native.available():
        pytest.skip("letter emit requires the pipelined (native) path")
    if len(jax.devices()) < 2:
        pytest.skip("letter emit needs a multi-device mesh")
    docs = _byte_soup_docs(seed, 25)
    ids = list(range(1, len(docs) + 1))
    st = native.tokenize_native(docs, ids, dedup_pairs=True, num_threads=1)
    mt = native.tokenize_native(docs, ids, dedup_pairs=True, num_threads=5)
    np.testing.assert_array_equal(st.term_ids, mt.term_ids)
    np.testing.assert_array_equal(st.doc_ids, mt.doc_ids)
    np.testing.assert_array_equal(st.vocab, mt.vocab)
    m, golden = _soup_corpus(tmp_path, seed)
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64,
                               emit_ownership="letter", host_threads=3),
                output_dir=tmp_path / "letter")
    assert read_letter_files(tmp_path / "letter") == golden
