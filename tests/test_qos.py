"""Multi-tenant QoS + generation-keyed result-cache suite (PR 20).

Four layers:

* cache primitives — :class:`LRUCache` byte-size accounting (payloads
  counted, evicted LRU-first past ``max_bytes``, oversized entries
  refused) and :func:`key_for` normalization (two requests share an
  entry only when the engine provably answers them byte-identically);
* :class:`ResultCache` — epoch-keyed lookup/fill, exact invalidation
  on epoch change (no TTLs), copies in/copies out, disabled is inert;
* QoS primitives — the ``MRI_SERVE_TENANT_WEIGHTS`` /
  ``MRI_SERVE_TENANT_RATE`` grammars, the :class:`_TokenBucket` under
  a fake clock, and :class:`_FairQueue` weighted dequeue order with
  per-lane depth bounds;
* daemon integration — cache hits answered from the reader thread are
  byte-identical to engine answers and a live mutation's generation
  bump invalidates them; the ``tenant`` wire field is validated; a
  tenant over its bucket sheds typed ``overloaded`` without touching
  other lanes; ``stats()["tenants"]`` carries the whole per-tenant
  slice (counters, lane depth, 1m p95, 1m SLO burn) in one poll;
  ``flightdump`` slices by tenant; ``mri top`` renders tenant rows.
"""

import os
import queue
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from test_daemon import Client, serving

from test_serve import build_corpus, naive_index

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
    _top_render,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    metrics as obs_metrics,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.cache import (
    LRUCache,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.daemon import (
    _FairQueue,
    _TokenBucket,
    _parse_tenant_rates,
    _parse_tenant_weights,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.result_cache import (
    CACHEABLE_OPS,
    ResultCache,
    key_for,
)

pytestmark = [pytest.mark.qos, pytest.mark.serve]

daemonized = pytest.mark.daemon

DOCS = [b"the cat sat on the mat", b"the dog ran far",
        b"cat and dog nap", b"a quiet zebra naps",
        b"dog dog dog barks the most"]


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    out = build_corpus(tmp_path_factory.mktemp("qos_corpus"), DOCS)
    return out, naive_index(DOCS)


# -- LRUCache byte accounting -------------------------------------------


def test_lru_byte_bound_evicts_lru_first():
    c = LRUCache(10, max_bytes=100)
    for i in range(3):
        c.put(f"k{i}", i, nbytes=40)  # 120 > 100: k0 must go
    assert "k0" not in c and "k1" in c and "k2" in c
    st = c.stats()
    assert st["bytes"] == 80
    assert st["max_bytes"] == 100
    assert st["evictions"] == 1


def test_lru_byte_bound_oversized_entry_refused():
    c = LRUCache(10, max_bytes=100)
    c.put("small", 1, nbytes=60)
    c.put("huge", 2, nbytes=101)  # bigger than the whole budget
    assert "huge" not in c
    assert "small" in c, "oversized insert flushed the working set"
    assert c.stats()["bytes"] == 60


def test_lru_byte_accounting_tracks_replacement():
    c = LRUCache(10, max_bytes=100)
    c.put("k", 1, nbytes=90)
    c.put("k", 2, nbytes=10)  # replace: old size must be released
    assert c.stats()["bytes"] == 10
    c.put("j", 3, nbytes=80)  # fits only if the 90 was released
    assert "k" in c and "j" in c
    assert c.stats()["bytes"] == 90


def test_lru_purge_resets_bytes_keeps_tallies():
    c = LRUCache(4, max_bytes=100)
    c.put("k", 1, nbytes=50)
    assert c.get("k") == 1
    assert c.get("nope") is None
    assert c.purge() == 1
    st = c.stats()
    assert st["entries"] == 0 and st["bytes"] == 0
    assert st["hits"] == 1 and st["misses"] == 1


def test_lru_entry_count_bound_still_applies():
    c = LRUCache(2, max_bytes=0)  # 0 = no byte bound
    for i in range(3):
        c.put(i, i, nbytes=10 ** 9)
    assert len(c) == 2
    assert c.stats()["bytes"] == 2 * 10 ** 9  # accounted even unbounded


# -- cache key normalization --------------------------------------------


def test_key_for_and_or_order_and_dupes_collapse():
    a = key_for("and", ["b", "a", "b"], None, 0, None)
    b = key_for("and", ["a", "b"], None, 0, None)
    assert a == b
    assert key_for("or", ["x", "y"], None, 0, None) \
        == key_for("or", ["y", "x", "x"], None, 0, None)


def test_key_for_top_k_keeps_duplicates_not_order():
    dup = key_for("top_k", ["a", "a"], None, 10, "bm25")
    one = key_for("top_k", ["a"], None, 10, "bm25")
    assert dup != one, "a repeated term scores twice in BM25"
    assert key_for("top_k", ["b", "a"], None, 10, "bm25") \
        == key_for("top_k", ["a", "b"], None, 10, "bm25")
    assert key_for("top_k", ["a"], None, 10, "bm25") \
        != key_for("top_k", ["a"], None, 20, "bm25")


def test_key_for_df_postings_positional():
    assert key_for("df", ["b", "a"], None, 0, None) \
        != key_for("df", ["a", "b"], None, 0, None)
    assert key_for("postings", ["a", "a"], None, 0, None) \
        != key_for("postings", ["a"], None, 0, None)


def test_key_for_uncacheable_shapes():
    for op in ("stats", "append", "delete", "compact", "healthz",
               "flightdump", "reload"):
        assert op not in CACHEABLE_OPS
        assert key_for(op, ["a"], None, 0, None) is None
    assert key_for("and", [], None, 0, None) is None
    assert key_for("and", None, "c", 0, None) is None  # letter non-top_k
    assert key_for("top_k", None, "c", 10, "bm25") is not None


# -- ResultCache --------------------------------------------------------


def _rc(**kw):
    kw.setdefault("registry", obs_metrics.Registry())
    kw.setdefault("enabled", True)
    kw.setdefault("entries", 64)
    kw.setdefault("max_bytes", 1 << 20)
    return ResultCache(**kw)


def test_result_cache_roundtrip_epoch_keyed():
    rc = _rc()
    k = key_for("df", ["cat"], None, 0, None)
    rc.fill(k, 3, {"ok": True, "df": [2]})
    assert rc.lookup(k, 3) == {"ok": True, "df": [2]}
    assert rc.lookup(k, 4) is None, "a generation bump must miss"
    assert rc.lookup(k, None) is None, "no epoch, no cache"
    st = rc.stats()
    assert st["enabled"] is True
    assert st["hits"] == 1 and st["entries"] == 1
    assert st["bytes"] > 0


def test_result_cache_on_epoch_purges_and_counts():
    rc = _rc()
    k = key_for("and", ["a", "b"], None, 0, None)
    rc.on_epoch(1)
    base = rc.stats()["invalidations"]
    rc.fill(k, 1, {"ok": True, "docs": [0]})
    rc.on_epoch(2)  # change: purge + count
    assert rc.stats()["invalidations"] == base + 1
    assert rc.stats()["entries"] == 0
    rc.on_epoch(2)  # unchanged: neither
    assert rc.stats()["invalidations"] == base + 1


def test_result_cache_returns_copies():
    rc = _rc()
    k = key_for("df", ["x"], None, 0, None)
    payload = {"ok": True, "df": [1]}
    rc.fill(k, 1, payload)
    payload["ok"] = False  # caller mutates after fill
    hit = rc.lookup(k, 1)
    assert hit["ok"] is True
    hit["id"] = 99  # response stamping mutates the hit
    assert "id" not in rc.lookup(k, 1)


def test_result_cache_disabled_is_inert():
    rc = _rc(enabled=False)
    k = key_for("df", ["x"], None, 0, None)
    rc.fill(k, 1, {"ok": True})
    assert rc.lookup(k, 1) is None
    rc.on_epoch(2)
    st = rc.stats()
    assert st["enabled"] is False
    assert st["invalidations"] == 0 and st["capacity"] == 0


# -- tenant knob grammars -----------------------------------------------


def test_parse_tenant_weights_grammar():
    assert _parse_tenant_weights("") == {}
    assert _parse_tenant_weights("a=2, b=8 ,*=1") \
        == {"a": 2, "b": 8, "*": 1}
    for bad in ("a", "a=0", "a=x", "=2"):
        with pytest.raises(ValueError):
            _parse_tenant_weights(bad)


def test_parse_tenant_rates_grammar():
    assert _parse_tenant_rates("") == {}
    out = _parse_tenant_rates("tank=5.5:2,pay=100")
    assert out["tank"] == (5.5, 2.0)
    assert out["pay"] == (100.0, 100.0), "burst defaults to 1s of rps"
    assert _parse_tenant_rates("slow=0.25")["slow"] == (0.25, 1.0), \
        "burst floor is 1 (a sub-1 bucket could never admit)"
    for bad in ("tank", "tank=0", "tank=1:0.5", "tank=x", "=1"):
        with pytest.raises(ValueError):
            _parse_tenant_rates(bad)


def test_token_bucket_fake_clock():
    now = [0.0]
    b = _TokenBucket(2.0, 3.0, clock=lambda: now[0])
    assert [b.allow() for _ in range(4)] == [True, True, True, False]
    now[0] = 1.0  # 2 tokens refilled
    assert [b.allow() for _ in range(3)] == [True, True, False]
    now[0] = 100.0  # refill caps at burst
    assert [b.allow() for _ in range(4)] == [True, True, True, False]


# -- weighted-fair queue ------------------------------------------------


class _Lane:
    def __init__(self, weight):
        self.weight = weight


class _Item:
    def __init__(self, tstate, tag):
        self.tstate = tstate
        self.tag = tag


def test_fair_queue_weighted_dequeue_order():
    heavy, light = _Lane(2), _Lane(1)
    q = _FairQueue(16)
    for i in range(4):
        q.put_nowait(_Item(heavy, f"h{i}"))
        q.put_nowait(_Item(light, f"l{i}"))
    got = [q.get_nowait().tag for _ in range(8)]
    # heavy takes 2 per turn, light 1: h h l h h l l l (drain tail)
    assert got == ["h0", "h1", "l0", "h2", "h3", "l1", "l2", "l3"]
    with pytest.raises(queue.Empty):
        q.get_nowait()


def test_fair_queue_single_lane_is_fifo():
    lane = _Lane(3)
    q = _FairQueue(16)
    for i in range(5):
        q.put_nowait(_Item(lane, i))
    assert [q.get_nowait().tag for _ in range(5)] == list(range(5))


def test_fair_queue_full_lane_sheds_only_its_tenant():
    a, b = _Lane(1), _Lane(1)
    q = _FairQueue(2)
    q.put_nowait(_Item(a, 1))
    q.put_nowait(_Item(a, 2))
    with pytest.raises(queue.Full):
        q.put_nowait(_Item(a, 3))
    q.put_nowait(_Item(b, 4))  # other lane unaffected
    assert q.qsize() == 3
    assert q.lane_depth(a) == 2 and q.lane_depth(b) == 1


def test_fair_queue_get_timeout():
    q = _FairQueue(4)
    with pytest.raises(queue.Empty):
        q.get(timeout=0.02)


# -- daemon integration -------------------------------------------------


def _strip(resp):
    r = dict(resp)
    r.pop("id", None)
    r.pop("trace_id", None)
    return r


@daemonized
def test_daemon_cache_hit_is_byte_identical(built):
    out, naive = built
    with serving(out) as daemon, Client(daemon) as c:
        first = c.rpc(id=1, op="df", terms=["cat", "dog"])
        assert first["ok"]
        assert first["df"] == [len(naive["cat"]), len(naive["dog"])]
        second = c.rpc(id=2, op="df", terms=["cat", "dog"])
        assert _strip(second) == _strip(first)
        # the hit must carry a FRESH trace stamp, not the cached one
        assert second["trace_id"] != first["trace_id"]
        # cross-tenant hit: the key excludes the tenant — same bytes
        tagged = c.rpc(id=3, op="df", terms=["cat", "dog"],
                       tenant="alpha")
        assert _strip(tagged) == _strip(first)
        st = daemon.stats()
        assert st["result_cache"]["enabled"] is True
        assert st["result_cache"]["hits"] >= 2
        assert st["tenants"]["alpha"]["cache_hits"] == 1


@daemonized
def test_daemon_mutation_invalidates_exactly(built, tmp_path):
    out, naive = built
    idx = tmp_path / "mut"
    shutil.copytree(out, idx)
    extra = tmp_path / "extra.txt"
    extra.write_text("cat cat zebra")
    with serving(str(idx)) as daemon, Client(daemon) as c:
        before = c.rpc(id=1, op="df", terms=["cat"])
        assert before["df"] == [len(naive["cat"])]
        new_df = len(naive["cat"]) + 1  # extra.txt mentions cat
        assert _strip(c.rpc(id=2, op="df", terms=["cat"])) \
            == _strip(before)  # warm hit
        r = c.rpc(id=3, op="append", files=[str(extra)])
        assert r.get("ok"), r
        after = c.rpc(id=4, op="df", terms=["cat"])
        assert after["df"] == [new_df], \
            "post-append answer served stale cached bytes"
        st = daemon.stats()["result_cache"]
        assert st["invalidations"] >= 1


@daemonized
def test_daemon_tenant_wire_validation(built):
    out, _ = built
    with serving(out) as daemon, Client(daemon) as c:
        for bad in ("has space", "x" * 65, 7, ""):
            r = c.rpc(id=1, op="df", terms=["cat"], tenant=bad)
            assert r["error"] == "bad_request", (bad, r)
            assert "tenant" in r["detail"]
        # absent field rides the default lane untouched
        assert c.rpc(id=2, op="df", terms=["cat"])["ok"]


@daemonized
def test_daemon_tenant_bucket_sheds_typed(built, monkeypatch):
    out, _ = built
    monkeypatch.setenv("MRI_SERVE_TENANT_RATE", "tank=1:1")
    with serving(out) as daemon, Client(daemon) as c:
        n = 8
        for i in range(n):
            # novel terms: every request is a cache miss, so each one
            # must pass the admission bucket
            c.send(id=i, op="df", terms=[f"novel{i}"], tenant="tank")
        got = [c.recv() for _ in range(n)]
        ok = [r for r in got if r.get("ok")]
        shed = [r for r in got if r.get("error") == "overloaded"]
        assert len(ok) + len(shed) == n
        assert ok, "burst=1 must admit the first request"
        assert len(shed) >= n - 3
        assert all("admission rate" in r["detail"] for r in shed)
        # an untagged request is untouched by the tank's bucket
        assert c.rpc(id=99, op="df", terms=["cat"])["ok"]
        ts = daemon.stats()["tenants"]
        assert ts["tank"]["shed"] == len(shed)
        assert ts["tank"]["rate_rps"] == 1.0
        assert ts["default"]["shed"] == 0


@daemonized
def test_daemon_tenant_stats_one_poll(built, monkeypatch):
    out, _ = built
    monkeypatch.setenv("MRI_SERVE_TENANT_WEIGHTS", "alpha=4,*=1")
    with serving(out) as daemon, Client(daemon) as c:
        for i, tn in enumerate(("alpha", "alpha", "beta")):
            assert c.rpc(id=i, op="df", terms=["cat"],
                         tenant=tn)["ok"]
        ts = daemon.stats()["tenants"]
        assert set(ts) >= {"default", "alpha", "beta"}
        a = ts["alpha"]
        assert a["weight"] == 4 and ts["beta"]["weight"] == 1
        assert a["requests"] == 2 and ts["beta"]["requests"] == 1
        assert a["rate_rps"] is None
        assert a["queue_depth"] == 0
        assert isinstance(a["burn_1m"], dict) and a["burn_1m"], \
            "per-tenant SLO burn must ride the same poll"
        for entry in ("shed", "deadline_expired", "errors",
                      "cache_hits", "p95_ms"):
            assert entry in a


@daemonized
def test_daemon_flightdump_tenant_slice(built):
    out, _ = built
    with serving(out) as daemon, Client(daemon) as c:
        for i, tn in enumerate(("alpha", "beta", "alpha")):
            assert c.rpc(id=i, op="top_k", terms=["dog"], k=2,
                         score="bm25", tenant=tn)["ok"]
        r = c.rpc(id=10, op="flightdump", tenant="alpha")
        assert r["ok"]
        flight = r["flight"]
        assert flight["tenant"] == "alpha"
        reqs = flight["requests"]
        assert reqs, "alpha's requests must survive its own slice"
        assert all(e["trace"]["tenant"] == "alpha" for e in reqs)
        full = c.rpc(id=11, op="flightdump")["flight"]
        assert "tenant" not in full
        assert {e["trace"]["tenant"] for e in full["requests"]} \
            >= {"alpha", "beta"}


# -- mri top tenant rows ------------------------------------------------


def test_top_render_tenant_table():
    sample = {
        "healthz": {"ready": True, "status": "ok", "reasons": []},
        "stats": {
            "queue_depth": 0, "inflight": 0, "connections": 1,
            "counters": {}, "rolling": {},
            "tenants": {
                "paying": {"weight": 8, "rate_rps": None,
                           "requests": 120, "shed": 0,
                           "deadline_expired": 0, "errors": 0,
                           "cache_hits": 40, "queue_depth": 1,
                           "p95_ms": 4.2,
                           "burn_1m": {"availability": 0.5,
                                       "latency": 1.25}},
                "tank": {"weight": 1, "rate_rps": 6.4,
                         "requests": 900, "shed": 850,
                         "deadline_expired": 0, "errors": 0,
                         "cache_hits": 0, "queue_depth": 3,
                         "p95_ms": 9.9, "burn_1m": {}},
            },
        },
        "slo": {},
    }
    frame = _top_render("d:1", sample)
    assert "tenant" in frame and "burn 1m" in frame
    paying = next(ln for ln in frame.splitlines()
                  if ln.startswith("paying"))
    assert "120" in paying and "4.2" in paying
    assert "1.25" in paying, "burn column shows the worst 1m burn"
    tank = next(ln for ln in frame.splitlines()
                if ln.startswith("tank"))
    assert "850" in tank and "6.4" in tank
    assert "50" in tank, "admitted = requests - shed"


def test_cli_serve_bad_gc_freeze_knob_exits_2(built):
    # regression: the knob was read after daemon.start(), so a bad
    # value escaped `mri serve` as a traceback instead of the one-line
    # exit-2 env-knob contract
    out, _ = built
    env = dict(os.environ, PYTHONPATH=str(Path(__file__).parent.parent),
               JAX_PLATFORMS="cpu", MRI_SERVE_GC_FREEZE="nope")
    proc = subprocess.run(
        [sys.executable, "-m",
         "parallel_computation_of_an_inverted_index_using_map_reduce_tpu",
         "serve", str(out), "--listen", "127.0.0.1:0"],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 2
    assert "MRI_SERVE_GC_FREEZE" in proc.stderr
    assert proc.stderr.count("\n") == 1


def test_top_render_without_tenants_unchanged():
    sample = {
        "healthz": {"ready": True, "status": "ok", "reasons": []},
        "stats": {"queue_depth": 0, "inflight": 0, "connections": 1,
                  "counters": {}, "rolling": {}},
        "slo": {},
    }
    assert "tenant" not in _top_render("d:1", sample)
