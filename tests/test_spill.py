"""Out-of-core build: spill containers, term-hash shard merge, and the
byte-identity of the disk tier against the in-memory path.

The contract under test (README "Out-of-core build"): arming
``MRI_BUILD_SPILL_BYTES`` may change WHERE the postings live while the
build runs — never a byte of what it emits.  Letter files and the
``index.mri`` artifact must be identical to the in-memory path at every
(mappers, reducers, shards, budget) point; a torn run file degrades to
quarantine + reported skips (exit-3 semantics, not corruption); a dead
shard merger degrades to main-thread takeover; a SIGKILLed spill build
leaves only a stale scratch dir the next run sweeps.
"""

import logging
import os
import signal
import sys
import zlib

import numpy as np
import pytest

from conftest import REPO_ROOT, read_letter_files, run_child

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    build_index,
    faults,
    native,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.build import (
    ooc,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.build import (
    spill as spill_mod,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.scheduler import (
    term_shard_balance,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    clean_token,
)

pytestmark = pytest.mark.spill

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="no C++ toolchain")

_TINY_BUDGET = 4096          # forces several run flushes on the corpus
_HUGE_BUDGET = 1 << 30       # never trips: the zero-spill fast path


@pytest.fixture(autouse=True)
def _disarm():
    faults.install(None)
    faults.begin_run()
    yield
    faults.install(None)
    faults.begin_run()


@pytest.fixture(autouse=True)
def _small_windows(monkeypatch):
    """Many windows per worker so tiny budgets actually flush runs."""
    monkeypatch.setenv("MRI_CPU_WINDOW_BYTES", "512")


def _manifest(tmp_path, num_docs=29, seed=13, vocab=500, tokens=60):
    docs = zipf_corpus(num_docs=num_docs, vocab_size=vocab,
                       tokens_per_doc=tokens, seed=seed)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    return read_manifest(tmp_path / "list.txt"), docs


def _build(manifest, out, *, mappers=3, reducers=4, budget=None,
           shards=None, monkeypatch=None, **cfg_kw):
    if budget is not None:
        monkeypatch.setenv("MRI_BUILD_SPILL_BYTES", str(budget))
    else:
        monkeypatch.delenv("MRI_BUILD_SPILL_BYTES", raising=False)
    if shards is not None:
        monkeypatch.setenv("MRI_BUILD_SHARDS", str(shards))
    return build_index(
        manifest,
        IndexConfig(backend="cpu", num_mappers=mappers,
                    num_reducers=reducers, io_prefetch=2, **cfg_kw),
        output_dir=out)


def _no_spill_dirs(out):
    return sorted(p.name for p in out.glob(".spill-*")) == []


# -- spill container --------------------------------------------------


def _sections():
    return {
        "vocab": np.arange(12, dtype=np.uint8).reshape(3, 4),
        "df": np.array([2, 1, 3], dtype=np.int64),
        "postings": np.array([1, 4, 2, 1, 3, 9], dtype=np.int32),
    }


def test_spill_container_roundtrip(tmp_path):
    path = tmp_path / "t.bin"
    sections = _sections()
    nbytes = spill_mod.write_file(path, {"kind": "test", "n": 3}, sections)
    assert path.stat().st_size == nbytes
    with spill_mod.SpillFile(path) as sf:
        assert sf.meta == {"kind": "test", "n": 3}
        for name, arr in sections.items():
            np.testing.assert_array_equal(sf.section(name), arr)
        # row-sliced reads see the same bytes without loading the rest
        np.testing.assert_array_equal(
            sf.read_rows("vocab", 1, 3), sections["vocab"][1:3])
        np.testing.assert_array_equal(
            sf.read_rows("postings", 2, 5), sections["postings"][2:5])
    spill_mod.verify_file(path)  # pristine file passes the checksum walk


def test_spill_verify_catches_single_bit_flip(tmp_path):
    path = tmp_path / "t.bin"
    spill_mod.write_file(path, {"kind": "test"}, _sections())
    with spill_mod.SpillFile(path) as sf:
        at = sf.sections["postings"]["offset"]
    data = bytearray(path.read_bytes())
    data[at] ^= 0x40
    path.write_bytes(data)
    with pytest.raises(spill_mod.SpillError, match="postings"):
        spill_mod.verify_file(path)
    moved = spill_mod.quarantine(path)
    assert moved.name == "t.bin.corrupt" and moved.exists()
    assert not path.exists()


def test_spill_rejects_bad_magic_and_version(tmp_path):
    path = tmp_path / "bad.bin"
    path.write_bytes(b"NOTSPILL" + b"\0" * 8)
    with pytest.raises(spill_mod.SpillError, match="magic"):
        spill_mod.SpillFile(path)
    spill_mod.write_file(path, {"kind": "test"}, _sections())
    data = bytearray(path.read_bytes())
    data[8] = 99  # version field
    path.write_bytes(data)
    with pytest.raises(spill_mod.SpillError, match="version"):
        spill_mod.SpillFile(path)


def test_spill_header_checksums_are_adler32(tmp_path):
    path = tmp_path / "t.bin"
    sections = _sections()
    spill_mod.write_file(path, {"kind": "test"}, sections)
    with spill_mod.SpillFile(path) as sf:
        for name, arr in sections.items():
            want = f"{zlib.adler32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF:08x}"
            assert sf.sections[name]["adler32"] == want


def test_clean_stale_dirs_sweeps_only_foreign_pids(tmp_path):
    stale = tmp_path / ".spill-424242"
    stale.mkdir()
    (stale / "run-w000-0000.bin").write_bytes(b"torn")
    own = spill_mod.spill_dir(tmp_path)
    own.mkdir()
    (own / "live.bin").write_bytes(b"live")
    assert spill_mod.clean_stale_dirs(tmp_path) == 1
    assert not stale.exists()
    assert own.exists() and (own / "live.bin").exists()
    spill_mod.remove_dir(own)
    assert not own.exists()


# -- ooc merge algebra ------------------------------------------------


def test_gather_pairs_permutes_offsets_and_indices():
    src_off = np.array([0, 2, 3, 6], dtype=np.int64)
    order = np.array([2, 0, 1])
    idx, new_off = ooc.gather_pairs(order, src_off)
    assert new_off.tolist() == [0, 3, 5, 6]
    assert idx.tolist() == [3, 4, 5, 0, 1, 2]
    pairs = np.array([10, 11, 20, 30, 31, 32])
    assert pairs[idx].tolist() == [30, 31, 32, 10, 11, 20]


def test_letter_offsets_bounds_each_first_letter():
    terms = np.array([b"ab", b"ax", b"bz", b"da"], dtype="S2")
    off = ooc.letter_offsets(ooc.terms_to_u8(terms))
    assert off.shape == (27,)
    assert off[0] == 0 and off[1] == 2       # 'a' terms in [0, 2)
    assert off[2] == 3                        # 'b' terms in [2, 3)
    assert off[3] == 3 and off[4] == 4        # 'c' empty, 'd' in [3, 4)
    assert off[26] == 4


def test_emit_order_df_desc_word_asc():
    # lex-sorted input, df [3, 1, 3]: ties break word-ascending
    assert ooc.emit_order(np.array([3, 1, 3])).tolist() == [0, 2, 1]


def _write_run(path, terms, df, postings, tf):
    """Minimal single-shard run container for merge_shard tests."""
    u8 = ooc.terms_to_u8(np.array(terms, dtype="S2"))
    spill_mod.write_file(path, {
        "kind": "run",
        "shard_term_off": [0, len(terms)],
        "shard_pair_off": [0, len(postings)],
    }, {
        "vocab": u8,
        "df": np.array(df, dtype=np.int64),
        "postings": np.array(postings, dtype=np.int32),
        "tf": np.array(tf, dtype=np.int32),
    })
    return spill_mod.SpillFile(path)


def test_merge_shard_kway_disjoint_runs(tmp_path):
    r1 = _write_run(tmp_path / "r1.bin", [b"ab", b"cd"],
                    [2, 1], [1, 3, 2], [1, 1, 4])
    r2 = _write_run(tmp_path / "r2.bin", [b"ab", b"bb"],
                    [1, 1], [2, 5], [7, 1])
    try:
        merged = ooc.merge_shard([r1, r2], 0, 2)
    finally:
        r1.close()
        r2.close()
    assert ooc.as_terms(merged["vocab"], 2).tolist() == [b"ab", b"bb", b"cd"]
    assert merged["df"].tolist() == [3, 1, 1]
    # per-term postings doc-ascending across runs, tf riding along
    assert merged["postings"].tolist() == [1, 2, 3, 5, 2]
    assert merged["tf"].tolist() == [1, 7, 1, 1, 4]
    assert merged["offsets"].tolist() == [0, 3, 4, 5]


def test_merge_shard_duplicate_pair_raises(tmp_path):
    # runs cover disjoint documents by construction; a (term, doc)
    # collision means double-counted windows and must be fatal
    r1 = _write_run(tmp_path / "r1.bin", [b"ab"], [1], [7], [1])
    r2 = _write_run(tmp_path / "r2.bin", [b"ab"], [1], [7], [2])
    try:
        with pytest.raises(ValueError, match="duplicate"):
            ooc.merge_shard([r1, r2], 0, 2)
    finally:
        r1.close()
        r2.close()


# -- byte-identity matrix ---------------------------------------------


@needs_native
@pytest.mark.parametrize("shards", [1, 8, 64])
@pytest.mark.parametrize("budget", [_TINY_BUDGET, _HUGE_BUDGET])
def test_spill_matrix_byte_identical(tmp_path, monkeypatch, shards,
                                     budget):
    manifest, _ = _manifest(tmp_path)
    oracle_index(manifest, tmp_path / "clean")
    out = tmp_path / f"out-{shards}-{budget}"
    report = _build(manifest, out, budget=budget, shards=shards,
                    monkeypatch=monkeypatch, audit=True)
    assert read_letter_files(out) == read_letter_files(tmp_path / "clean")
    sp = report["spill"]
    if budget == _TINY_BUDGET:
        assert sp["runs"] > 0 and sp["flushes"] >= sp["runs"] > 0
        assert report["build_shards"]["shards"] == shards
        assert sum(report["build_shards"]["postings_per_shard"]) \
            == report["unique_pairs"]
    else:
        assert sp["runs"] == 0  # zero-spill fast path
    assert _no_spill_dirs(out)


@needs_native
@pytest.mark.parametrize("mappers,reducers", [(1, 1), (2, 5), (4, 3)])
def test_spill_km_grid_byte_identical(tmp_path, monkeypatch, mappers,
                                      reducers):
    manifest, _ = _manifest(tmp_path)
    oracle_index(manifest, tmp_path / "clean")
    out = tmp_path / "out"
    _build(manifest, out, mappers=mappers, reducers=reducers,
           budget=_TINY_BUDGET, shards=8, monkeypatch=monkeypatch)
    assert read_letter_files(out) == read_letter_files(tmp_path / "clean")
    assert _no_spill_dirs(out)


@needs_native
def test_spill_artifact_byte_identical(tmp_path, monkeypatch):
    manifest, _ = _manifest(tmp_path)
    mem = tmp_path / "mem"
    _build(manifest, mem, monkeypatch=monkeypatch, artifact=True,
           audit=True)
    disk = tmp_path / "disk"
    _build(manifest, disk, budget=_TINY_BUDGET, shards=8,
           monkeypatch=monkeypatch, artifact=True, audit=True)
    assert read_letter_files(disk) == read_letter_files(mem)
    assert (disk / "index.mri").read_bytes() \
        == (mem / "index.mri").read_bytes()


@needs_native
def test_reducers_over_26_all_do_real_work(tmp_path, monkeypatch):
    """Regression for the silent M > 26 clamp: the term-hash reduce has
    no 26-partition cap, so M = 64 must field 64 reduce workers and
    still write oracle bytes."""
    manifest, _ = _manifest(tmp_path)
    oracle_index(manifest, tmp_path / "clean")
    out = tmp_path / "out"
    report = _build(manifest, out, mappers=2, reducers=64,
                    budget=_TINY_BUDGET, shards=64,
                    monkeypatch=monkeypatch)
    assert report["reduce_workers"] == 64
    assert read_letter_files(out) == read_letter_files(tmp_path / "clean")


@needs_native
def test_reducers_over_26_in_memory_path_warns(tmp_path, monkeypatch,
                                               caplog):
    """The in-memory letter reduce keeps the reference's degenerate
    R > 26 contract (empty ranges past the alphabet) but must now SAY
    so instead of silently wasting the extra reducers."""
    manifest, _ = _manifest(tmp_path)
    out = tmp_path / "out"
    with caplog.at_level(logging.WARNING):
        report = _build(manifest, out, mappers=2, reducers=30,
                        monkeypatch=monkeypatch)
    assert report["reduce_workers"] == 30
    assert any("exceeds the 26 letter partitions" in r.message
               for r in caplog.records)


@needs_native
def test_spill_budget_bounds_worker_memory(tmp_path, monkeypatch):
    """The point of the tier: peak estimated postings footprint per
    worker stays under the budget on a corpus many times its size."""
    budget = 16 << 10
    manifest, docs = _manifest(tmp_path, num_docs=200, seed=3)
    assert sum(len(d) for d in docs) >= 4 * budget
    report = _build(manifest, tmp_path / "out", budget=budget, shards=8,
                    monkeypatch=monkeypatch)
    sp = report["spill"]
    assert sp["runs"] > 0
    assert 0 < sp["peak_worker_est_bytes"] <= budget
    assert sp["bytes_spilled"] > budget  # really went through disk


# -- shard balance (satellite: hash shards vs the 26-letter split) ----


@needs_native
def test_hash_shards_beat_letter_split_on_zipf(tmp_path, monkeypatch):
    """On a Zipf corpus the reference's 26-letter partition concentrates
    postings mass on hot first letters; the term-hash shards must come
    out measurably flatter (lower max/mean), even with fewer bins."""
    manifest, docs = _manifest(tmp_path, num_docs=64, vocab=800,
                               tokens=80, seed=7)
    report = _build(manifest, tmp_path / "out", budget=_TINY_BUDGET,
                    shards=8, monkeypatch=monkeypatch)
    balance = report["build_shards"]
    letter_pairs = [0] * 26
    for blob in docs:
        for word in {clean_token(r) for r in blob.split()} - {""}:
            letter_pairs[ord(word[0]) - ord("a")] += 1
    letter_balance = term_shard_balance(letter_pairs)
    assert sum(letter_pairs) == sum(balance["postings_per_shard"])
    assert balance["max_over_mean"] < letter_balance["max_over_mean"]


# -- degradation: quarantine + takeover -------------------------------


@needs_native
def test_spill_corrupt_quarantines_and_reports(tmp_path, monkeypatch):
    manifest, _ = _manifest(tmp_path)
    faults.install("spill-corrupt:spill=1")
    faults.begin_run()
    out = tmp_path / "out"
    report = _build(manifest, out, mappers=2, reducers=3,
                    budget=_TINY_BUDGET, shards=8, monkeypatch=monkeypatch)
    d = report["degradation"]
    assert report["spill"]["runs_quarantined"] == 1
    assert d["skipped_docs"]  # the loss is REPORTED, never silent
    # degraded, not dead: the full letter set is still on disk
    assert all((out / f"{chr(ord('a') + i)}.txt").exists()
               for i in range(26))
    assert _no_spill_dirs(out)


@needs_native
def test_merge_crash_takeover_byte_identical(tmp_path, monkeypatch):
    manifest, _ = _manifest(tmp_path)
    oracle_index(manifest, tmp_path / "clean")
    faults.install("merge-crash")
    faults.begin_run()
    out = tmp_path / "out"
    report = _build(manifest, out, mappers=2, reducers=3,
                    budget=_TINY_BUDGET, shards=8, monkeypatch=monkeypatch)
    d = report["degradation"]
    assert d["reducer_takeovers"] >= 1
    assert not d["skipped_docs"]
    assert read_letter_files(out) == read_letter_files(tmp_path / "clean")
    assert _no_spill_dirs(out)


# -- SIGKILL at a spill boundary + --resume=auto ----------------------


@needs_native
def test_sigkill_after_spill_write_rerun_byte_identical(tmp_path,
                                                        monkeypatch):
    """A REAL kill right after the 2nd run file lands: the child leaves
    only a stale .spill-<pid> dir; the rerun sweeps it and emits oracle
    bytes."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
        main,
    )

    manifest, _ = _manifest(tmp_path)
    oracle_index(manifest, tmp_path / "clean")
    out = tmp_path / "out"
    argv = ["2", "2", str(tmp_path / "list.txt"),
            "--output-dir", str(out),
            "--backend", "cpu", "--io-prefetch", "2", "--resume", "auto"]
    proc = run_child(
        [sys.executable, "-m",
         "parallel_computation_of_an_inverted_index_using_map_reduce_tpu"]
        + argv,
        cwd=str(REPO_ROOT),
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "MRI_CPU_WINDOW_BYTES": "512",
             "MRI_BUILD_SPILL_BYTES": str(_TINY_BUDGET),
             "MRI_SPILL_KILL_AFTER": "2"},
        timeout=300)
    assert proc.returncode == -signal.SIGKILL
    stale = sorted(p.name for p in out.glob(".spill-*"))
    assert stale  # the crash left its scratch dir behind
    assert not (out / "a.txt").exists()  # died before any emit
    monkeypatch.setenv("MRI_BUILD_SPILL_BYTES", str(_TINY_BUDGET))
    monkeypatch.delenv("MRI_SPILL_KILL_AFTER", raising=False)
    assert main(argv) == 0
    assert read_letter_files(out) == read_letter_files(tmp_path / "clean")
    assert _no_spill_dirs(out)
