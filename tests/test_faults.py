"""Failure semantics, proven by deterministic fault injection.

Every failure mode the pipeline claims to survive (README "Failure
semantics") is armed here via faults.py and asserted end to end:

- transient read errors retry to success — zero skips, output
  byte-identical to the oracle, with the retries *reported*
- permanent read errors degrade, not die — the run completes, the
  exact skipped doc ids ride the stats into CLI exit 3
- a silently dying reader thread raises ReaderDied, a hung one
  ReaderHang — never a deadlocked scan
- a corrupt/truncated checkpoint is a named CheckpointCorrupt;
  --resume=auto quarantines it and restarts fresh
- SIGKILL at an arbitrary stream-window boundary (a REAL kill, child
  process) plus a rerun with --resume=auto yields byte-identical
  a.txt..z.txt
"""

import json
import os
import signal
import sys

import pytest

from conftest import REPO_ROOT, read_letter_files, run_child

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    build_index,
    faults,
    native,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import main
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    iter_document_ranges,
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.io import (
    PipelinedWindowReader,
    ReaderDied,
    ReaderHang,
    WindowArena,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.io.reader import (
    read_window_into,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.utils import (
    checkpoint,
)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends with no injector armed and a fresh
    degradation report (both are process-global by design)."""
    faults.install(None)
    faults.begin_run()
    yield
    faults.install(None)
    faults.begin_run()


def _corpus(tmp_path, texts=("alpha beta", "beta gamma", "delta alpha")):
    paths = []
    for i, text in enumerate(texts):
        p = tmp_path / f"doc{i}.txt"
        p.write_text(text)
        paths.append(str(p))
    write_manifest(tmp_path / "list.txt", paths)
    return read_manifest(tmp_path / "list.txt")


# -- spec parsing -----------------------------------------------------


def test_spec_parses_every_kind():
    inj = faults.FaultInjector(
        "read-error:doc=2:times=2; slow-read:all:ms=1; "
        "truncate:doc=0:bytes=4; reader-death:window=1; "
        "sigkill:window=2; stream-crash:window=3; "
        "ckpt-corrupt:save=1; seed=7")
    kinds = [r.kind for r in inj.rules]
    assert kinds == ["read-error", "slow-read", "truncate",
                     "reader-death", "sigkill", "stream-crash",
                     "ckpt-corrupt"]


@pytest.mark.parametrize("bad", [
    "", "bogus:doc=1", "read-error:doc=x", "read-error:nope=1",
    "reader-death", "sigkill:window=0", "ckpt-corrupt",
    "seed=7:doc=1", "speed=9",
])
def test_spec_rejects_malformed(bad):
    with pytest.raises(faults.FaultSpecError):
        faults.FaultInjector(bad)


def test_install_and_env_arming(monkeypatch):
    assert faults.install("read-error:doc=0").spec == "read-error:doc=0"
    assert faults.install(None) is None
    # env arming happens on the first active() after an unset state
    monkeypatch.setenv(faults.ENV_VAR, "slow-read:all:ms=1")
    monkeypatch.setattr(faults, "_active", faults._UNSET)
    inj = faults.active()
    assert inj is not None and inj.rules[0].kind == "slow-read"


# -- RetryPolicy ------------------------------------------------------


def test_retry_policy_transient_then_success():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    report = faults.DegradationReport()
    policy = faults.RetryPolicy(max_attempts=3, backoff_s=0.0,
                                sleep=lambda s: None)
    assert policy.run(flaky, doc_id=1, report=report) == "ok"
    assert calls["n"] == 3 and report.read_retries == 2


def test_retry_policy_exhausts_attempts():
    policy = faults.RetryPolicy(max_attempts=2, backoff_s=0.0,
                                sleep=lambda s: None)
    with pytest.raises(OSError):
        policy.run(lambda: (_ for _ in ()).throw(OSError("always")))


def test_retry_policy_deadline_cuts_retries():
    # backoff so large the FIRST retry would already blow the deadline
    policy = faults.RetryPolicy(max_attempts=10, backoff_s=99.0,
                                deadline_s=0.01, sleep=lambda s: None)
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise OSError("down")

    with pytest.raises(OSError):
        policy.run(always)
    assert calls["n"] == 1


def test_retry_policy_from_env_reads_knobs(monkeypatch):
    monkeypatch.setenv("MRI_READ_RETRIES", "5")
    monkeypatch.setenv("MRI_READ_BACKOFF_MS", "12.5")
    monkeypatch.setenv("MRI_READ_DEADLINE_S", "7")
    policy = faults.RetryPolicy.from_env()
    assert policy.max_attempts == 5
    assert policy.backoff_s == pytest.approx(0.0125)
    assert policy.deadline_s == pytest.approx(7.0)


@pytest.mark.parametrize("var,bad", [
    ("MRI_READ_RETRIES", "zero"),
    ("MRI_READ_RETRIES", "0"),
    ("MRI_READ_RETRIES", "-1"),
    ("MRI_READ_RETRIES", "2.5"),
    ("MRI_READ_BACKOFF_MS", "fast"),
    ("MRI_READ_BACKOFF_MS", "-10"),
    ("MRI_READ_DEADLINE_S", "0"),
    ("MRI_READ_DEADLINE_S", "nope"),
])
def test_retry_policy_from_env_rejects_bad_values(monkeypatch, var, bad):
    """A typo'd env knob is a one-line configuration error naming the
    variable — never a worker-thread traceback mid-run."""
    monkeypatch.setenv(var, bad)
    with pytest.raises(ValueError, match=var):
        faults.RetryPolicy.from_env()


def test_retry_policy_bad_env_is_cli_exit_2(tmp_path, monkeypatch, capsys):
    m = _corpus(tmp_path)  # noqa: F841 — writes list.txt
    monkeypatch.setenv("MRI_READ_RETRIES", "lots")
    rc = main(["1", "1", str(tmp_path / "list.txt"),
               "--output-dir", str(tmp_path / "out"), "--backend", "cpu"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "MRI_READ_RETRIES" in err and err.count("\n") == 1


# -- read paths: retry, skip, truncate --------------------------------


def test_read_window_transient_faults_no_skips(tmp_path):
    m = _corpus(tmp_path)
    faults.install("read-error:all:times=2")
    report = faults.DegradationReport()
    policy = faults.RetryPolicy(backoff_s=0.0, sleep=lambda s: None)
    arena = read_window_into(m, 0, len(m), WindowArena(),
                             policy=policy, report=report)
    assert arena.contents() == [open(p, "rb").read() for p in m.paths]
    assert report.read_retries == 2 * len(m)
    assert not report.degraded


def test_read_window_permanent_fault_records_exact_skip(tmp_path):
    m = _corpus(tmp_path)
    faults.install("read-error:doc=1:times=-1")
    report = faults.DegradationReport()
    policy = faults.RetryPolicy(backoff_s=0.0, sleep=lambda s: None)
    arena = read_window_into(m, 0, len(m), WindowArena(),
                             policy=policy, report=report)
    _, _, ids = arena.feed_views()
    assert ids.tolist() == [1, 3]  # doc id 2 (index 1) skipped
    assert report.skipped_doc_ids() == [2]
    assert "injected read failure" in report.summary()["skip_reasons"]["2"]


def test_iter_document_ranges_resilience(tmp_path):
    m = _corpus(tmp_path)
    faults.install("read-error:doc=0:times=1; read-error:doc=2:times=-1")
    report = faults.DegradationReport()
    policy = faults.RetryPolicy(backoff_s=0.0, sleep=lambda s: None)
    out = list(iter_document_ranges(m, [(0, len(m))],
                                    policy=policy, report=report))
    (contents, doc_ids), = out
    assert doc_ids == [1, 2]           # doc id 3 (index 2) gone
    assert report.skipped_doc_ids() == [3]
    # doc 0's single transient + the 2 retries doc 2 burned before its
    # error became final (3 attempts = 2 recorded retries)
    assert report.read_retries == 3


def test_truncate_fault_shortens_document(tmp_path):
    m = _corpus(tmp_path, texts=("alpha beta", "gamma"))
    faults.install("truncate:doc=0:bytes=5")
    arena = read_window_into(m, 0, len(m), WindowArena(),
                             report=faults.DegradationReport())
    assert arena.contents()[0] == b"alpha"
    assert arena.contents()[1] == b"gamma"


def test_slow_read_fault_still_succeeds(tmp_path):
    m = _corpus(tmp_path, texts=("alpha",))
    faults.install("slow-read:doc=0:ms=1")
    arena = read_window_into(m, 0, 1, WindowArena(),
                             report=faults.DegradationReport())
    assert arena.contents() == [b"alpha"]


# -- executor lifecycle: death, hang ----------------------------------


def test_reader_death_raises_not_deadlocks(tmp_path):
    m = _corpus(tmp_path)
    faults.install("reader-death:window=1")
    reader = PipelinedWindowReader(m, [(0, len(m))], depth=1)
    with pytest.raises(ReaderDied):
        for arena in reader:
            reader.recycle(arena)
    assert reader.close()


def test_reader_hang_watchdog(tmp_path):
    m = _corpus(tmp_path)
    # the reader thread sleeps 2s inside the injected slow read; a
    # 0.2s watchdog must raise instead of waiting it out (one-doc
    # window so the abandoned thread lingers one sleep, not three)
    faults.install("slow-read:all:ms=2000")
    reader = PipelinedWindowReader(m, [(0, 1)], depth=1,
                                   watchdog_s=0.2)
    with pytest.raises(ReaderHang):
        for arena in reader:
            reader.recycle(arena)
    reader.close(timeout=0.01)  # thread still sleeping: don't wait here


# -- whole-pipeline degradation ---------------------------------------


def test_oracle_backend_transient_faults_byte_identical(tmp_path):
    m = _corpus(tmp_path)
    oracle_index(m, tmp_path / "clean")
    faults.install("read-error:all:times=2")
    stats = build_index(m, IndexConfig(backend="oracle"),
                        output_dir=tmp_path / "faulted")
    assert read_letter_files(tmp_path / "faulted") == \
        read_letter_files(tmp_path / "clean")
    deg = stats["degradation"]
    assert deg["read_retries"] > 0 and deg["skipped_docs"] == []


def test_device_stream_engine_transient_faults_byte_identical(tmp_path):
    m = _corpus(tmp_path)
    oracle_index(m, tmp_path / "clean")
    faults.install("read-error:all:times=1")
    stats = build_index(
        m, IndexConfig(device_tokenize=True, stream_chunk_docs=1,
                       device_shards=1, pad_multiple=64),
        output_dir=tmp_path / "faulted")
    assert read_letter_files(tmp_path / "faulted") == \
        read_letter_files(tmp_path / "clean")
    assert stats["degradation"]["read_retries"] >= len(m)
    assert stats["degradation"]["skipped_docs"] == []


def test_cli_degraded_exit_with_exact_doc_ids(tmp_path, capsys):
    _corpus(tmp_path)
    out = tmp_path / "out"
    rc = main(["1", "1", str(tmp_path / "list.txt"), "--backend",
               "oracle", "--output-dir", str(out), "--stats",
               "--fault-spec", "read-error:doc=1:times=-1"])
    assert rc == faults.EXIT_DEGRADED == 3
    captured = capsys.readouterr()
    assert "DEGRADED" in captured.err and "[2]" in captured.err
    stats = json.loads(captured.out.strip())
    assert stats["degradation"]["skipped_docs"] == [2]
    # the readable documents were still fully indexed
    assert b"alpha:[1 3]\n" in read_letter_files(out)


def test_bad_fault_spec_is_cli_usage_error(tmp_path, capsys):
    _corpus(tmp_path)
    rc = main(["1", "1", str(tmp_path / "list.txt"),
               "--fault-spec", "warp-core-breach"])
    assert rc == 2
    assert "unknown fault kind" in capsys.readouterr().err


# -- checkpoint corruption + quarantine -------------------------------


def _truncate(path):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size // 3, 1))


def test_load_pairs_corrupt_is_named_error(tmp_path):
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
        tokenize,
    )

    corpus = tokenize([b"alpha beta"], [1], use_native=False,
                      dedup_pairs=True)
    p = tmp_path / "pairs.npz"
    checkpoint.save_pairs(p, corpus, fingerprint="fp")
    _truncate(p)
    with pytest.raises(checkpoint.CheckpointCorrupt) as ei:
        checkpoint.load_pairs(p, expect_fingerprint="fp")
    assert str(p) in str(ei.value) and "--resume=auto" in str(ei.value)


def test_load_stream_state_corrupt_is_named_error(tmp_path):
    p = tmp_path / "stream.npz"
    p.write_bytes(b"PK\x03\x04 not actually a zip")
    with pytest.raises(checkpoint.CheckpointCorrupt):
        checkpoint.load_stream_state(p, "fp")


def test_quarantine_moves_file_aside(tmp_path):
    p = tmp_path / "c.npz"
    p.write_bytes(b"junk")
    dest = checkpoint.quarantine(p)
    assert not p.exists()
    assert dest == str(p) + ".corrupt"
    assert open(dest, "rb").read() == b"junk"


def test_resume_auto_quarantines_pairs_checkpoint(tmp_path, capsys):
    _corpus(tmp_path)
    listfile = str(tmp_path / "list.txt")
    ckpt = tmp_path / "pairs.npz"
    base = ["1", "1", listfile, "--checkpoint", str(ckpt),
            "--pad-multiple", "64", "--device-shards", "1",
            "--pipeline-chunk-docs", "0"]
    assert main(base + ["--output-dir", str(tmp_path / "o1")]) == 0
    _truncate(ckpt)
    # strict (default): hard error naming the file
    rc = main(base + ["--output-dir", str(tmp_path / "o2")])
    assert rc == 2
    assert "corrupt" in capsys.readouterr().err
    # auto: quarantine + fresh run, byte-identical output
    assert main(base + ["--output-dir", str(tmp_path / "o3"),
                        "--resume", "auto"]) == 0
    assert (tmp_path / "pairs.npz.corrupt").exists()
    assert read_letter_files(tmp_path / "o3") == \
        read_letter_files(tmp_path / "o1")


def test_resume_auto_survives_corrupted_stream_checkpoint(tmp_path):
    """ckpt-corrupt + stream-crash armed together: the crash leaves a
    TORN stream checkpoint behind; --resume=auto must quarantine it and
    still produce byte-identical output from a fresh start."""
    m = _corpus(tmp_path)
    oracle_index(m, tmp_path / "clean")
    ckpt = tmp_path / "run.ckpt.npz"
    argv = ["1", "1", str(tmp_path / "list.txt"),
            "--device-tokenize", "--stream-chunk-docs", "1",
            "--device-shards", "1", "--pad-multiple", "64",
            "--stream-checkpoint", str(ckpt),
            "--stream-checkpoint-every", "1"]
    faults.install("ckpt-corrupt:save=1; stream-crash:window=2")
    with pytest.raises(RuntimeError, match="injected stream crash"):
        main(argv + ["--output-dir", str(tmp_path / "out")])
    assert ckpt.exists()
    faults.install(None)
    # strict rerun refuses the torn file
    rc = main(argv + ["--output-dir", str(tmp_path / "out")])
    assert rc == 2
    # auto rerun quarantines and completes identically
    assert main(argv + ["--output-dir", str(tmp_path / "out"),
                        "--resume", "auto"]) == 0
    assert (tmp_path / "run.ckpt.npz.corrupt").exists()
    assert read_letter_files(tmp_path / "out") == \
        read_letter_files(tmp_path / "clean")


def test_stream_crash_resume_valid_checkpoint(tmp_path, capsys):
    """stream-crash via the fault spec (first-class replacement for the
    MRI_TPU_STREAM_CRASH_AFTER_WINDOWS env hook): the engine dies
    folding window 2 — AFTER window 1's save, BEFORE window 2's — and
    the rerun resumes at the window-1 checkpoint, not from scratch."""
    m = _corpus(tmp_path)
    oracle_index(m, tmp_path / "clean")
    ckpt = tmp_path / "run.ckpt.npz"
    argv = ["1", "1", str(tmp_path / "list.txt"),
            "--output-dir", str(tmp_path / "out"),
            "--device-tokenize", "--stream-chunk-docs", "1",
            "--device-shards", "1", "--pad-multiple", "64",
            "--stream-checkpoint", str(ckpt),
            "--stream-checkpoint-every", "1", "--stats"]
    faults.install("stream-crash:window=2")
    with pytest.raises(RuntimeError, match="injected stream crash"):
        main(argv)
    assert ckpt.exists()
    faults.install(None)
    capsys.readouterr()
    assert main(argv) == 0
    stats = json.loads(capsys.readouterr().out.strip())
    assert stats["resumed_from_window"] == 1
    assert not ckpt.exists()
    assert read_letter_files(tmp_path / "out") == \
        read_letter_files(tmp_path / "clean")


# -- SIGKILL e2e: crash-safe auto-resume ------------------------------

_KILL_TEXTS = ("alpha beta", "beta gamma", "delta alpha",
               "epsilon beta", "zeta eta alpha")


def _kill_argv(tmp_path):
    return ["1", "1", str(tmp_path / "list.txt"),
            "--output-dir", str(tmp_path / "out"),
            "--device-tokenize", "--stream-chunk-docs", "1",
            "--device-shards", "1", "--pad-multiple", "64",
            "--stream-checkpoint", str(tmp_path / "run.ckpt.npz"),
            "--stream-checkpoint-every", "1", "--resume", "auto"]


def _run_killed_child(tmp_path, window):
    """Run the CLI in a REAL child process armed to SIGKILL itself at
    the given stream-window boundary; assert it died by SIGKILL."""
    proc = run_child(
        [sys.executable, "-m",
         "parallel_computation_of_an_inverted_index_using_map_reduce_tpu"]
        + _kill_argv(tmp_path),
        cwd=str(REPO_ROOT),
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             faults.ENV_VAR: f"sigkill:window={window}"},
        timeout=300)
    assert proc.returncode == -signal.SIGKILL


def _sigkill_resume_case(tmp_path, window):
    m = _corpus(tmp_path, texts=_KILL_TEXTS)
    oracle_index(m, tmp_path / "clean")
    golden = read_letter_files(tmp_path / "clean")
    _run_killed_child(tmp_path, window)
    ckpt = tmp_path / "run.ckpt.npz"
    assert ckpt.exists()  # the kill landed after a completed save
    # rerun the SAME command in-process (jax already warm): must
    # resume — or restart cleanly — and emit byte-identical letters
    assert main(_kill_argv(tmp_path)) == 0
    assert not ckpt.exists()
    assert read_letter_files(tmp_path / "out") == golden


# Three distinct kill points across the 5-window stream: right after
# the first save, mid-stream, and after the LAST possible save (the
# final window's save is skipped by design, so window 4 is the latest
# boundary with a checkpoint behind it).
@pytest.mark.parametrize("window", [1, 2, 4])
def test_sigkill_at_window_boundary_resume_byte_identical(
        tmp_path, window):
    _sigkill_resume_case(tmp_path, window)


# The same crash discipline on the PIPELINED CPU path, at every worker
# count: the executor's reader threads fire the window-boundary hook
# with the GLOBAL plan index, so `sigkill:window=2` means the same
# thing whether one worker or four are stealing windows.  The cpu path
# has no checkpoint — durability is the atomic tmp+rename emit — so
# the rerun rebuilds from scratch and must still be byte-identical.
@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
@pytest.mark.parametrize("mappers,reducers", [(1, 1), (2, 2), (4, 3)])
def test_cpu_sigkill_at_window_boundary_rerun_byte_identical(
        tmp_path, mappers, reducers):
    m = _corpus(tmp_path, texts=_KILL_TEXTS)
    oracle_index(m, tmp_path / "clean")
    golden = read_letter_files(tmp_path / "clean")
    argv = [str(mappers), str(reducers), str(tmp_path / "list.txt"),
            "--output-dir", str(tmp_path / "out"),
            "--backend", "cpu", "--io-prefetch", "2", "--resume", "auto"]
    proc = run_child(
        [sys.executable, "-m",
         "parallel_computation_of_an_inverted_index_using_map_reduce_tpu"]
        + argv,
        cwd=str(REPO_ROOT),
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "MRI_CPU_WINDOW_BYTES": "1",  # one doc per window: 5 windows
             faults.ENV_VAR: "sigkill:window=2"},
        timeout=300)
    assert proc.returncode == -signal.SIGKILL
    # the kill landed before finalize: no complete letter set on disk
    assert not (tmp_path / "out" / "a.txt").exists()
    assert main(argv) == 0
    assert read_letter_files(tmp_path / "out") == golden


@pytest.mark.slow
@pytest.mark.parametrize("window", [3, 5])
def test_sigkill_every_remaining_window(tmp_path, window):
    """Exhaustive sweep tail (window 5 kills AFTER the stream finished
    feeding — the checkpoint is already deleted by then only if
    finalize ran; either way the rerun must converge)."""
    m = _corpus(tmp_path, texts=_KILL_TEXTS)
    oracle_index(m, tmp_path / "clean")
    proc = run_child(
        [sys.executable, "-m",
         "parallel_computation_of_an_inverted_index_using_map_reduce_tpu"]
        + _kill_argv(tmp_path),
        cwd=str(REPO_ROOT),
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             faults.ENV_VAR: f"sigkill:window={window}"},
        timeout=300)
    assert proc.returncode == -signal.SIGKILL
    assert main(_kill_argv(tmp_path)) == 0
    assert read_letter_files(tmp_path / "out") == \
        read_letter_files(tmp_path / "clean")
