"""Pallas kernel equivalence tests (interpret mode on the CPU backend).

Each kernel is checked against its XLA/numpy reference on randomized
inputs, including the padding tail the engine feeds them (INT32_MAX
sorts last and must be masked out by valid_limit).
"""

import numpy as np
import pytest

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import keys as K
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops.pallas import kernels
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops.segment import (
    first_occurrence_mask,
)

BLOCK = kernels._BLOCK


def _sorted_keys(n, n_valid, vocab, stride, seed):
    rng = np.random.default_rng(seed)
    term = rng.integers(0, vocab, n_valid)
    doc = rng.integers(1, stride - 1, n_valid)
    keys = np.full(n, K.INT32_MAX, np.int32)
    keys[:n_valid] = term * stride + doc
    return np.sort(keys)


def test_supports():
    assert kernels.supports(BLOCK)
    assert kernels.supports(4 * BLOCK)
    assert not kernels.supports(BLOCK + 128)
    assert not kernels.supports(BLOCK // 2)


@pytest.mark.parametrize("seed,blocks", [(0, 1), (1, 2), (2, 4)])
def test_unique_mask_count_matches_xla(seed, blocks):
    n = blocks * BLOCK
    vocab, stride = 5000, 357
    keys = _sorted_keys(n, n - 777, vocab, stride, seed)
    limit = vocab * stride

    mask, count = kernels.unique_mask_count(keys, limit)
    mask, count = np.asarray(mask), int(count)

    expect = np.asarray(first_occurrence_mask(keys)) & (keys < limit)
    np.testing.assert_array_equal(mask, expect)
    assert count == int(expect.sum())


def test_unique_mask_count_dense_runs():
    # long runs of equal keys exercise the cross-block carry
    n = 2 * BLOCK
    keys = np.sort(np.repeat(np.arange(64, dtype=np.int32) * 7, n // 64))
    mask, count = kernels.unique_mask_count(keys, 1 << 30)
    expect = np.asarray(first_occurrence_mask(keys))
    np.testing.assert_array_equal(np.asarray(mask), expect)
    assert int(count) == 64


def test_unique_mask_count_all_padding():
    keys = np.full(BLOCK, K.INT32_MAX, np.int32)
    mask, count = kernels.unique_mask_count(keys, 100)
    assert int(count) == 0
    assert not np.asarray(mask).any()


def test_unique_mask_count_rejects_bad_size():
    with pytest.raises(ValueError):
        kernels.unique_mask_count(np.zeros(100, np.int32), 10)


@pytest.mark.parametrize("num_buckets", [1, 8, 26, 128])
def test_bucket_histogram_matches_bincount(num_buckets):
    rng = np.random.default_rng(num_buckets)
    # include out-of-range padding values (== num_buckets) to be dropped
    vals = rng.integers(0, num_buckets + 1, 2 * BLOCK).astype(np.int32)
    counts = np.asarray(kernels.bucket_histogram(vals, num_buckets))
    expect = np.bincount(vals[vals < num_buckets], minlength=num_buckets)
    np.testing.assert_array_equal(counts, expect)


def test_engine_uses_pallas_mask_when_forced(monkeypatch):
    # index_packed through the forced Pallas dedup path must match the
    # XLA path bit-for-bit on a full-scale padded array
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import engine

    vocab, max_doc = 500, 40
    stride = max_doc + 2
    n = BLOCK
    rng = np.random.default_rng(3)
    keys = np.full(n, K.INT32_MAX, np.int32)
    nv = n - 999
    keys[:nv] = rng.integers(0, vocab, nv) * stride + rng.integers(1, max_doc + 1, nv)
    letters = np.sort(rng.integers(0, 26, vocab)).astype(np.int32)

    def run():
        engine.index_packed.clear_cache()
        return {k: np.asarray(v) for k, v in engine.index_packed(
            keys.copy(), letters, vocab_size=vocab, max_doc_id=max_doc).items()}

    monkeypatch.setattr(engine, "_PALLAS_MODE", "off")
    xla = run()
    monkeypatch.setattr(engine, "_PALLAS_MODE", "force")
    pallas = run()
    for key in xla:
        np.testing.assert_array_equal(xla[key], pallas[key], err_msg=key)


def test_partition_skew_stats():
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.utils.stats import (
        partition_skew,
    )

    rng = np.random.default_rng(0)
    vocab = 1000
    letters = np.sort(rng.integers(0, 26, vocab)).astype(np.int32)
    # Zipf-ish skew: most pairs hit low term ids (clustered letters)
    terms = (rng.zipf(1.5, 20_000) % vocab).astype(np.int32)
    s = partition_skew(terms, letters, num_buckets=8)
    assert int(s["letter_counts"].sum()) == terms.shape[0]
    assert int(s["bucket_counts"].sum()) == terms.shape[0]
    np.testing.assert_array_equal(
        s["letter_counts"], np.bincount(letters[terms], minlength=26))
    # hash buckets must balance far better than letters on Zipf input
    assert s["bucket_imbalance"] < s["letter_imbalance"]


def test_bucket_histogram_validates():
    with pytest.raises(ValueError):
        kernels.bucket_histogram(np.zeros(BLOCK, np.int32), 0)
    with pytest.raises(ValueError):
        kernels.bucket_histogram(np.zeros(BLOCK, np.int32), 200)
    with pytest.raises(ValueError):
        kernels.bucket_histogram(np.zeros(7, np.int32), 8)
