"""corpus/realtext.py — the paragraph-resharded real-text manifest
(BASELINE.json config 5's regime without egress).

The duck-typed surface must behave exactly like a file manifest: the
loaders iterate it, the oracle indexes it, and the device engines must
produce byte-identical output on it.
"""

import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
    oracle_index,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    iter_document_chunks,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.realtext import (
    ParagraphManifest,
)


@pytest.fixture(scope="module")
def src_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("rt_src")
    (d / "a.txt").write_bytes(
        b"First paragraph here.\n\nSecond one, with Words!\n\n\n"
        b"Third after a blank run.")
    (d / "b.txt").write_bytes(b"Only paragraph of file two\r\n\r\nAnd another")
    return d


def test_paragraph_split_and_cycling(src_dir):
    m = ParagraphManifest(src_dir, repeats=1)
    assert m.source_files == 2
    assert m.source_paragraphs == 5
    assert len(m) == 5
    m3 = ParagraphManifest(src_dir, repeats=3)
    assert len(m3) == 15
    # cycling: doc i is paragraph i % P, ids are 1-based positions
    assert m3.read_doc(0) == m3.read_doc(5) == m3.read_doc(10)
    assert m3.doc_id(7) == 8
    with pytest.raises(IndexError):
        m3.read_doc(15)


def test_sizes_paths_and_total_bytes(src_dir):
    m = ParagraphManifest(src_dir, num_docs=7)
    assert len(m.sizes) == 7 and len(m.paths) == 7
    for i in range(7):
        assert m.sizes[i] == len(m.read_doc(i))
    assert m.total_bytes == sum(m.sizes[i] for i in range(7))
    # sequence-protocol iteration must terminate (the _VirtualPaths bug)
    assert len(list(m.paths)) == 7
    assert sum(1 for _ in m.sizes) == 7


def test_fingerprint_distinguishes_counts_and_sources(src_dir, tmp_path):
    a = ParagraphManifest(src_dir, num_docs=5)
    b = ParagraphManifest(src_dir, num_docs=10)
    assert a.fingerprint_extra != b.fingerprint_extra
    other = tmp_path / "other_src"
    other.mkdir()
    (other / "c.txt").write_bytes(b"different corpus text")
    c = ParagraphManifest(other, num_docs=5)
    assert c.fingerprint_extra != a.fingerprint_extra


def test_streaming_loader_covers_every_doc(src_dir):
    m = ParagraphManifest(src_dir, repeats=2)
    seen = []
    for contents, ids in iter_document_chunks(m, 4):
        assert len(contents) == len(ids) <= 4
        seen.extend(ids)
    assert seen == list(range(1, 11))


def test_default_engine_on_paragraph_manifest(src_dir, tmp_path):
    """The DEFAULT tpu engine (pipelined plan) slices manifest.sizes in
    its byte-balance planner — the virtual sizes sequence must support
    slices (regression: _ParaSizes without slice handling crashed
    here with TypeError)."""
    m = ParagraphManifest(src_dir, repeats=3)
    oracle_index(m, tmp_path / "golden")
    InvertedIndexModel(IndexConfig(
        backend="tpu", output_dir=str(tmp_path / "default"),
        device_shards=1, pad_multiple=256)).run(m)
    assert read_letter_files(tmp_path / "default") == read_letter_files(
        tmp_path / "golden")


def test_engines_byte_identical_on_paragraph_manifest(src_dir, tmp_path):
    m = ParagraphManifest(src_dir, repeats=4)  # 20 docs, heavy dedup
    oracle_index(m, tmp_path / "golden")
    InvertedIndexModel(IndexConfig(
        backend="tpu", output_dir=str(tmp_path / "stream"),
        device_shards=1, stream_chunk_docs=3)).run(m)
    assert read_letter_files(tmp_path / "stream") == read_letter_files(
        tmp_path / "golden")
    InvertedIndexModel(IndexConfig(
        backend="tpu", output_dir=str(tmp_path / "devtok"),
        device_shards=1, device_tokenize=True, pad_multiple=256,
        stream_chunk_docs=4)).run(m)
    assert read_letter_files(tmp_path / "devtok") == read_letter_files(
        tmp_path / "golden")


def test_empty_source_and_zero_docs_rejected(src_dir, tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ValueError, match="no .txt files"):
        ParagraphManifest(empty)
    with pytest.raises(ValueError, match="num_docs"):
        ParagraphManifest(src_dir, num_docs=0)
