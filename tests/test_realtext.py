"""corpus/realtext.py — the paragraph-resharded real-text manifest
(BASELINE.json config 5's regime without egress).

The duck-typed surface must behave exactly like a file manifest: the
loaders iterate it, the oracle indexes it, and the device engines must
produce byte-identical output on it.
"""

import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
    oracle_index,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    iter_document_chunks,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.realtext import (
    ParagraphManifest,
)


@pytest.fixture(scope="module")
def src_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("rt_src")
    (d / "a.txt").write_bytes(
        b"First paragraph here.\n\nSecond one, with Words!\n\n\n"
        b"Third after a blank run.")
    (d / "b.txt").write_bytes(b"Only paragraph of file two\r\n\r\nAnd another")
    return d


def test_paragraph_split_and_cycling(src_dir):
    m = ParagraphManifest(src_dir, repeats=1)
    assert m.source_files == 2
    assert m.source_paragraphs == 5
    assert len(m) == 5
    m3 = ParagraphManifest(src_dir, repeats=3)
    assert len(m3) == 15
    # cycling: doc i is paragraph i % P, ids are 1-based positions
    assert m3.read_doc(0) == m3.read_doc(5) == m3.read_doc(10)
    assert m3.doc_id(7) == 8
    with pytest.raises(IndexError):
        m3.read_doc(15)


def test_sizes_paths_and_total_bytes(src_dir):
    m = ParagraphManifest(src_dir, num_docs=7)
    assert len(m.sizes) == 7 and len(m.paths) == 7
    for i in range(7):
        assert m.sizes[i] == len(m.read_doc(i))
    assert m.total_bytes == sum(m.sizes[i] for i in range(7))
    # sequence-protocol iteration must terminate (the _VirtualPaths bug)
    assert len(list(m.paths)) == 7
    assert sum(1 for _ in m.sizes) == 7


def test_fingerprint_distinguishes_counts_and_sources(src_dir, tmp_path):
    a = ParagraphManifest(src_dir, num_docs=5)
    b = ParagraphManifest(src_dir, num_docs=10)
    assert a.fingerprint_extra != b.fingerprint_extra
    other = tmp_path / "other_src"
    other.mkdir()
    (other / "c.txt").write_bytes(b"different corpus text")
    c = ParagraphManifest(other, num_docs=5)
    assert c.fingerprint_extra != a.fingerprint_extra


def test_streaming_loader_covers_every_doc(src_dir):
    m = ParagraphManifest(src_dir, repeats=2)
    seen = []
    for contents, ids in iter_document_chunks(m, 4):
        assert len(contents) == len(ids) <= 4
        seen.extend(ids)
    assert seen == list(range(1, 11))


def test_default_engine_on_paragraph_manifest(src_dir, tmp_path):
    """The DEFAULT tpu engine (pipelined plan) slices manifest.sizes in
    its byte-balance planner — the virtual sizes sequence must support
    slices (regression: _ParaSizes without slice handling crashed
    here with TypeError)."""
    m = ParagraphManifest(src_dir, repeats=3)
    oracle_index(m, tmp_path / "golden")
    InvertedIndexModel(IndexConfig(
        backend="tpu", output_dir=str(tmp_path / "default"),
        device_shards=1, pad_multiple=256)).run(m)
    assert read_letter_files(tmp_path / "default") == read_letter_files(
        tmp_path / "golden")


def test_engines_byte_identical_on_paragraph_manifest(src_dir, tmp_path):
    m = ParagraphManifest(src_dir, repeats=4)  # 20 docs, heavy dedup
    oracle_index(m, tmp_path / "golden")
    InvertedIndexModel(IndexConfig(
        backend="tpu", output_dir=str(tmp_path / "stream"),
        device_shards=1, stream_chunk_docs=3)).run(m)
    assert read_letter_files(tmp_path / "stream") == read_letter_files(
        tmp_path / "golden")
    InvertedIndexModel(IndexConfig(
        backend="tpu", output_dir=str(tmp_path / "devtok"),
        device_shards=1, device_tokenize=True, pad_multiple=256,
        stream_chunk_docs=4)).run(m)
    assert read_letter_files(tmp_path / "devtok") == read_letter_files(
        tmp_path / "golden")


def test_salted_cycles_grow_vocabulary(src_dir):
    """VERDICT r4 weak #1 / next #6: with salt_cycles the term space
    keeps growing past one source cycle — cycle r re-contributes the
    source vocabulary tagged with the cycle's letter suffix — instead
    of freezing after the first ~P docs."""
    P = ParagraphManifest(src_dir, repeats=1).source_paragraphs
    m = ParagraphManifest(src_dir, repeats=3, salt_cycles=True)
    # cycle 0 is the untouched real text
    for i in range(P):
        assert m.read_doc(i) == ParagraphManifest(src_dir,
                                                  repeats=1).read_doc(i)
    # later cycles: same word count, every word suffixed, distinct tags
    assert m.read_doc(P) == b" ".join(
        w + b"aa" for w in m.read_doc(0).split())
    assert m.read_doc(2 * P) == b" ".join(
        w + b"ab" for w in m.read_doc(0).split())

    def vocab(docs):
        return {w for d in docs for w in d.split()}

    v1 = vocab(m.read_doc(i) for i in range(P))
    v3 = vocab(m.read_doc(i) for i in range(3 * P))
    # exactly 3x on this fixture because it is collision-free; real
    # corpora can lose a few terms to raw-vs-salted collisions
    # ("cab" == "c"+"ab") — see the class docstring
    assert len(v3) == 3 * len(v1)
    # unsalted comparison: vocabulary frozen after one cycle
    u = ParagraphManifest(src_dir, repeats=3)
    assert len(vocab(u.read_doc(i) for i in range(3 * P))) == len(v1)


def test_salted_sizes_and_fingerprint(src_dir):
    m = ParagraphManifest(src_dir, num_docs=13, salt_cycles=True)
    for i in range(13):
        assert m.sizes[i] == len(m.read_doc(i)), i
    assert m.total_bytes == sum(m.sizes[i] for i in range(13))
    # whole-cycle totals too (the closed-form full-cycle branch)
    w = ParagraphManifest(src_dir, repeats=3, salt_cycles=True)
    assert w.total_bytes == sum(w.sizes[i] for i in range(len(w)))
    # a salted stream must not resume an unsalted checkpoint
    assert (m.fingerprint_extra
            != ParagraphManifest(src_dir, num_docs=13).fingerprint_extra)


def test_cycle_tag_letters_only():
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.realtext import (
        _cycle_tag,
    )

    tags = [_cycle_tag(r, 2) for r in range(1, 677)]
    assert tags[:3] == [b"aa", b"ab", b"ac"]
    assert len(set(tags)) == len(tags)  # unique per cycle
    assert all(t.isalpha() and t.islower() and len(t) == 2 for t in tags)
    with pytest.raises(ValueError, match="does not fit"):
        _cycle_tag(677, 2)
    # FIXED width is what makes word+tag unambiguous across cycles:
    # with per-cycle widths, "web"+"a" == "we"+"ba" (review r5 finding)
    assert b"web" + _cycle_tag(1, 2) != b"we" + _cycle_tag(28, 2)
    salted = {w + t for w in (b"we", b"web") for t in tags}
    assert len(salted) == 2 * len(tags)


def test_salted_engines_byte_identical(src_dir, tmp_path):
    """Salted docs are still plain text: every engine must agree with
    the oracle on them (the tags survive cleaning verbatim)."""
    m = ParagraphManifest(src_dir, repeats=3, salt_cycles=True)
    oracle_index(m, tmp_path / "golden")
    report = InvertedIndexModel(IndexConfig(
        backend="tpu", output_dir=str(tmp_path / "stream"),
        device_shards=1, stream_chunk_docs=4)).run(m)
    assert read_letter_files(tmp_path / "stream") == read_letter_files(
        tmp_path / "golden")
    # the recorded vocab-growth curve keeps climbing in the salted
    # cycles (window 1 covers cycle 0; windows 2-4 are cycles 1-2)
    curve = report["vocab_curve"]
    assert curve == sorted(curve) and curve[-1] > curve[0]
    assert curve[-1] == report["unique_terms"]


def test_empty_source_and_zero_docs_rejected(src_dir, tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ValueError, match="no .txt files"):
        ParagraphManifest(empty)
    with pytest.raises(ValueError, match="num_docs"):
        ParagraphManifest(src_dir, num_docs=0)
