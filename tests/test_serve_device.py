"""Device-engine suite: byte parity against the host engine, the
shared-prefix searchsorted-fixup path, static-shape compile discipline,
engine selection, and the empty-batch CLI contract.

Everything here runs under ``JAX_PLATFORMS=cpu`` — the device engine's
CPU-backend fallback is a tier-1 requirement (the jit/shard_map
pipeline is identical; only the mesh devices differ), so the parity
contract is enforced on every box, not just on chips.
"""

import json
import random

import numpy as np
import pytest

from test_serve import build_corpus, naive_index

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import main
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve import (
    Engine, create_engine, resolve_engine,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.artifact import (
    artifact_path, device_columns, load_artifact,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.device_engine import (
    DeviceEngine,
)

pytestmark = [pytest.mark.serve, pytest.mark.device_serve]


@pytest.fixture(scope="module")
def zipf_pair(tmp_path_factory):
    """(host, device, naive) over one pipeline-built Zipf corpus."""
    docs = zipf_corpus(num_docs=60, vocab_size=900, tokens_per_doc=150,
                       seed=11)
    out = build_corpus(tmp_path_factory.mktemp("serve_dev_zipf"), docs)
    host = Engine(artifact_path(out))
    device = DeviceEngine(artifact_path(out))
    yield host, device, naive_index(docs)
    device.close()
    host.close()


#: >= 3 vocabulary terms sharing one full 8-byte prefix — the
#: searchsorted collision-fixup arm — plus prefix-adjacent traps:
#: the bare 8-byte prefix itself, a shorter sibling, and neighbors.
PREFIX_DOCS = [
    b"aaaaaaaab aaaaaaaac common one",
    b"aaaaaaaad aaaaaaaab common two",
    b"aaaaaaaa aaaaaaa aaaaaaaabzz three",
    b"aaaaaaab aaaaaaaac zebra common",
]


@pytest.fixture(scope="module")
def prefix_pair(tmp_path_factory):
    out = build_corpus(tmp_path_factory.mktemp("serve_dev_prefix"),
                       PREFIX_DOCS)
    host = Engine(artifact_path(out))
    device = DeviceEngine(artifact_path(out))
    yield out, host, device, naive_index(PREFIX_DOCS)
    device.close()
    host.close()


def _assert_pair_matches(host, device, naive, terms):
    """Every answer byte-identical across engines AND right vs naive."""
    bh, bd = host.encode_batch(terms), device.encode_batch(terms)
    assert (bh == bd).all()
    dh, dd = host.df(bh), device.df(bd)
    assert dh.dtype == dd.dtype and dh.tolist() == dd.tolist()
    for t, post_h, post_d in zip(terms, host.postings(bh),
                                 device.postings(bd)):
        want = naive.get(t if isinstance(t, str) else t.decode("latin-1"))
        if want is None or t == "":
            assert post_h is None and post_d is None, t
        else:
            assert post_h is not None and post_d is not None, t
            assert post_h.tolist() == want, t
            assert np.array_equal(post_h, post_d), t


# -- batched parity fuzz ------------------------------------------------


@pytest.mark.parametrize("batch", [1, 32, 1024, 8192])
def test_device_parity_fuzz(zipf_pair, batch):
    """df + postings byte-identical at every required batch size,
    mixing present, absent, and junk terms."""
    host, device, naive = zipf_pair
    vocab = sorted(naive)
    rng = random.Random(batch)
    junk = ["", "zzzznope", "Aardvark!!", "x1y2z3q4", "a" * 40, "THE"]
    terms = [vocab[rng.randrange(len(vocab))] if rng.random() < 0.8
             else junk[rng.randrange(len(junk))] for _ in range(batch)]
    _assert_pair_matches(host, device, naive, terms)


def test_device_boolean_parity(zipf_pair):
    host, device, naive = zipf_pair
    vocab = sorted(naive)
    rng = random.Random(5)
    for _ in range(40):
        k = rng.choice((1, 2, 2, 3, 4))
        terms = rng.sample(vocab, k=k)
        if rng.random() < 0.25:
            terms[rng.randrange(k)] = "notinthecorpusxyz"
        bh, bd = host.encode_batch(terms), device.encode_batch(terms)
        got_and_h, got_and_d = host.query_and(bh), device.query_and(bd)
        got_or_h, got_or_d = host.query_or(bh), device.query_or(bd)
        assert got_and_h.dtype == got_and_d.dtype
        assert got_and_h.tolist() == got_and_d.tolist(), terms
        assert got_or_h.tolist() == got_or_d.tolist(), terms
        # and both equal naive set algebra
        sets = [set(naive.get(t, ())) for t in terms]
        want_and = sorted(set.intersection(*sets)) if all(sets) else []
        assert got_and_d.tolist() == want_and, terms
        assert got_or_d.tolist() == sorted(set.union(*sets)), terms


def test_device_top_k_parity(zipf_pair):
    host, device, _ = zipf_pair
    for li in range(26):
        for k in (1, 3, 1000):
            assert host.top_k(li, k) == device.top_k(li, k), (li, k)
    with pytest.raises(ValueError):
        device.top_k("1", 3)


def test_device_lookup_matches_host(zipf_pair):
    host, device, naive = zipf_pair
    vocab = sorted(naive)
    batch = host.encode_batch(vocab[:50] + ["missing"] + vocab[-50:])
    ih, fh = host.lookup(batch)
    id_, fd = device.lookup(batch)
    assert fh.tolist() == fd.tolist()
    assert ih[fh].tolist() == id_[fd].tolist()


def test_device_empty_batch(zipf_pair):
    _, device, _ = zipf_pair
    empty = device.encode_batch([])
    assert device.df(empty).tolist() == []
    assert device.postings(empty) == []
    assert device.query_and(empty).tolist() == []
    assert device.query_or(empty).tolist() == []


# -- shared-prefix fixup ------------------------------------------------


def test_prefix_columns_see_collision_group(prefix_pair):
    out, _, device, _ = prefix_pair
    art = load_artifact(artifact_path(out))
    try:
        cols = device_columns(art)
    finally:
        art.close()
    # aaaaaaaab / aaaaaaaabzz / aaaaaaaac / aaaaaaaad share the 8-byte
    # prefix "aaaaaaaa" with the bare prefix term itself: a 5-way group
    assert cols["max_prefix_group"] >= 4
    assert device._group == cols["max_prefix_group"]


@pytest.mark.parametrize("engine_kind", ["host", "device"])
def test_prefix_fixup_single_and_batched(prefix_pair, engine_kind):
    """Every colliding term resolves, single and batched, both engines."""
    _, host, device, naive = prefix_pair
    engine = host if engine_kind == "host" else device
    probes = ["aaaaaaaa", "aaaaaaa", "aaaaaaaab", "aaaaaaaabzz",
              "aaaaaaaac", "aaaaaaaad", "aaaaaaab", "aaaaaaaae",
              "aaaaaaaaz", "common", "zebra", "aaaaaaaabz"]
    # batched: one array, all collision arms at once
    batch = engine.encode_batch(probes)
    dfs = engine.df(batch)
    posts = engine.postings(batch)
    for t, df, post in zip(probes, dfs.tolist(), posts):
        want = naive.get(t)
        if want is None:
            assert df == 0 and post is None, t
        else:
            assert df == len(want), t
            assert post.tolist() == want, t
    # single: each term alone hits the same arm
    for t in probes:
        b1 = engine.encode_batch([t])
        assert engine.df(b1).tolist()[0] == len(naive.get(t, [])), t


def test_prefix_fixup_cross_engine_boolean(prefix_pair):
    _, host, device, naive = prefix_pair
    for terms in (["aaaaaaaab", "aaaaaaaac"],
                  ["aaaaaaaa", "aaaaaaaad"],
                  ["aaaaaaaab", "common", "aaaaaaaad"]):
        bh, bd = host.encode_batch(terms), device.encode_batch(terms)
        assert host.query_and(bh).tolist() == device.query_and(bd).tolist()
        assert host.query_or(bh).tolist() == device.query_or(bd).tolist()


# -- compile discipline -------------------------------------------------


def test_device_zero_recompile_steady_state(zipf_pair):
    """After one warm pass over a shape, repeats add no jit entries."""
    _, device, naive = zipf_pair
    vocab = sorted(naive)
    rng = random.Random(9)

    def one_round(seed_terms):
        device.postings(device.encode_batch(seed_terms))
        device.query_and(device.encode_batch(seed_terms[:2]))
        device.query_or(device.encode_batch(seed_terms[:2]))

    # steady state = the working set of (bucket, tier) shapes repeats;
    # replay the same batches so the second pass IS the steady state
    # (a fresh sample may legitimately hit a colder posting tier)
    rounds = [rng.sample(vocab, k=min(b, len(vocab)))
              for b in (1, 32, 257)]
    for seed_terms in rounds:
        one_round(seed_terms)
    warm = device.compile_stats()
    for seed_terms in rounds:
        one_round(seed_terms)
    assert device.compile_stats() == warm


def test_device_batch_bucketing_shares_compiles(zipf_pair):
    """Batches 200..256 share the 256 bucket: no new compile entries."""
    _, device, naive = zipf_pair
    vocab = sorted(naive)
    device.df(device.encode_batch(vocab[:256]))
    warm = device.compile_stats()
    for n in (200, 222, 256, 129):
        device.df(device.encode_batch(vocab[:n]))
    assert device.compile_stats() == warm


# -- engine selection + stats surface -----------------------------------


def test_resolve_engine_auto_is_a_backend(monkeypatch):
    # "auto" is the crossover router, a real backend of its own — it is
    # returned verbatim, not resolved to a platform name here
    assert resolve_engine("auto") == "auto"
    assert resolve_engine(None) == "auto"
    assert resolve_engine("host") == "host"
    assert resolve_engine("device") == "device"
    with pytest.raises(ValueError):
        resolve_engine("gpu")
    monkeypatch.setenv("MRI_SERVE_ENGINE", "device")
    assert resolve_engine(None) == "device"


def test_create_engine_kinds(prefix_pair):
    out, _, _, _ = prefix_pair
    with create_engine(artifact_path(out), "host") as e:
        assert isinstance(e, Engine) and e.engine_name == "host"
    with create_engine(artifact_path(out), "device") as e:
        assert isinstance(e, DeviceEngine)
        d = e.describe()
        assert d["engine"] == "device"
        assert d["device"]["shards"] >= 1
        assert "jit_cache_entries" in d["device"]


def test_describe_and_op_stats(prefix_pair):
    _, host, _, _ = prefix_pair
    host._ops.reset()
    host.df(host.encode_batch(["common"]))
    d = host.describe()
    assert d["engine"] == "host"
    assert d["ops"]["df"]["calls"] == 1
    assert {"hits", "misses", "evictions"} <= set(d["cache"])


def test_eviction_counter(prefix_pair):
    out, _, _, naive = prefix_pair
    with Engine(artifact_path(out), cache_terms=2) as e:
        terms = sorted(naive)[:5]
        e.postings(e.encode_batch(terms))
        assert e.cache_stats()["evictions"] == 3


# -- CLI ----------------------------------------------------------------


def test_query_cli_engine_flag_parity(prefix_pair, capsys):
    out, _, _, _ = prefix_pair
    outputs = {}
    for eng in ("host", "device"):
        assert main(["query", str(out), "--engine", eng,
                     "aaaaaaaab", "aaaaaaaac", "--stats"]) == 0
        lines = capsys.readouterr().out.splitlines()
        outputs[eng] = lines[:-1]
        stats = json.loads(lines[-1])
        assert stats["engine"] == eng
        if eng == "device":
            assert stats["device"]["shards"] >= 1
            assert "tiers" in stats["device"]
    assert outputs["host"] == outputs["device"]


def test_query_cli_env_override_selects_engine(prefix_pair, capsys,
                                               monkeypatch):
    """MRI_SERVE_ENGINE drives the CLI when --engine isn't given, and an
    explicit --engine flag beats the env."""
    out, _, _, _ = prefix_pair
    monkeypatch.setenv("MRI_SERVE_ENGINE", "device")
    assert main(["query", str(out), "--stats", "aaaaaaaab"]) == 0
    stats = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert stats["engine"] == "device"
    assert main(["query", str(out), "--engine", "host",
                 "--stats", "aaaaaaaab"]) == 0
    stats = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert stats["engine"] == "host"


def test_query_cli_empty_batch_file_exits_0(prefix_pair, tmp_path, capsys):
    """The empty-batch contract: exit 0, no output, both engines."""
    out, _, _, _ = prefix_pair
    empty = tmp_path / "empty.txt"
    empty.write_text("")
    for eng in ("host", "device"):
        assert main(["query", str(out), "--engine", eng,
                     "--batch-file", str(empty)]) == 0
        captured = capsys.readouterr()
        assert captured.out == "" and captured.err == ""
    # whitespace-only lines are also an empty batch
    empty.write_text("\n   \n\t\n")
    assert main(["query", str(out), "--batch-file", str(empty)]) == 0
    assert capsys.readouterr().out == ""
    # but no --batch-file at all is still the old error contract
    assert main(["query", str(out)]) == 2
    assert "error:" in capsys.readouterr().err
