"""CLI surface: reference-compatible invocation + error paths."""

import json

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import main
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)


def _mk_corpus(tmp_path):
    (tmp_path / "d1.txt").write_text("alpha beta Alpha!")
    (tmp_path / "d2.txt").write_text("beta gamma")
    write_manifest(tmp_path / "list.txt", [str(tmp_path / "d1.txt"), str(tmp_path / "d2.txt")])
    return tmp_path / "list.txt"


def test_cli_tpu_backend(tmp_path, capsys):
    listfile = _mk_corpus(tmp_path)
    out = tmp_path / "out"
    rc = main(["4", "26", str(listfile), "--output-dir", str(out),
               "--pad-multiple", "64", "--stats"])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip())
    assert stats["unique_terms"] == 3
    data = read_letter_files(out)
    assert b"alpha:[1]\n" in data and b"beta:[1 2]\n" in data and b"gamma:[2]\n" in data


def test_cli_backends_agree(tmp_path):
    listfile = _mk_corpus(tmp_path)
    out_t, out_o = tmp_path / "t", tmp_path / "o"
    assert main(["1", "1", str(listfile), "--output-dir", str(out_t), "--pad-multiple", "64"]) == 0
    assert main(["1", "1", str(listfile), "--output-dir", str(out_o), "--backend", "oracle"]) == 0
    assert read_letter_files(out_t) == read_letter_files(out_o)


def test_cli_missing_manifest(tmp_path, capsys):
    rc = main(["1", "1", str(tmp_path / "nope.txt")])
    assert rc == 2
    assert "error:" in capsys.readouterr().err


def test_cli_invalid_mapper_count(tmp_path, capsys):
    listfile = _mk_corpus(tmp_path)
    rc = main(["0", "1", str(listfile)])
    assert rc == 2
    err = capsys.readouterr().err
    assert "num_mappers" in err
    assert err.count("\n") == 1  # ONE line, not a traceback


def test_cli_invalid_reducer_count(tmp_path, capsys):
    listfile = _mk_corpus(tmp_path)
    rc = main(["1", "-3", str(listfile)])
    assert rc == 2
    err = capsys.readouterr().err
    assert "num_reducers" in err
    assert err.count("\n") == 1


def test_cli_missing_list_is_one_line(tmp_path, capsys):
    rc = main(["1", "1", str(tmp_path / "absent.txt")])
    assert rc == 2
    err = capsys.readouterr().err
    assert "does not exist" in err and "absent.txt" in err
    assert err.count("\n") == 1


def test_cli_checkpoint_resume(tmp_path):
    listfile = _mk_corpus(tmp_path)
    ckpt = tmp_path / "pairs.npz"
    out1, out2 = tmp_path / "o1", tmp_path / "o2"
    assert main(["1", "1", str(listfile), "--output-dir", str(out1),
                 "--checkpoint", str(ckpt), "--pad-multiple", "64"]) == 0
    assert ckpt.exists()
    # delete the corpus: resume must rebuild identical output from the
    # checkpoint alone (the reference's spill files, as a real feature)
    (tmp_path / "d1.txt").unlink()
    (tmp_path / "d2.txt").unlink()
    assert main(["1", "1", str(listfile), "--output-dir", str(out2),
                 "--checkpoint", str(ckpt), "--pad-multiple", "64"]) == 0
    assert read_letter_files(out1) == read_letter_files(out2)


def test_cli_checkpoint_manifest_mismatch(tmp_path, capsys):
    listfile = _mk_corpus(tmp_path)
    ckpt = tmp_path / "pairs.npz"
    assert main(["1", "1", str(listfile), "--checkpoint", str(ckpt),
                 "--output-dir", str(tmp_path / "o1"), "--pad-multiple", "64"]) == 0
    # different file list, same checkpoint: must refuse, not crash or
    # silently emit the old corpus's index
    (tmp_path / "d3.txt").write_text("delta")
    write_manifest(tmp_path / "list2.txt", [str(tmp_path / "d3.txt")])
    rc = main(["1", "1", str(tmp_path / "list2.txt"), "--checkpoint", str(ckpt),
               "--output-dir", str(tmp_path / "o2"), "--pad-multiple", "64"])
    assert rc == 2
    assert "different manifest" in capsys.readouterr().err


def test_cli_corrupt_checkpoint(tmp_path, capsys):
    listfile = _mk_corpus(tmp_path)
    ckpt = tmp_path / "bad.npz"
    ckpt.write_bytes(b"not a checkpoint")
    rc = main(["1", "1", str(listfile), "--checkpoint", str(ckpt),
               "--output-dir", str(tmp_path / "o")])
    assert rc == 2
    assert "error:" in capsys.readouterr().err


def test_cli_host_threads_and_emit_ownership(tmp_path, capsys):
    """New TPU-era flags parse and flow into run stats."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import native
    if not native.available():
        import pytest
        pytest.skip("no C++ toolchain (cpu backend falls back to oracle)")
    listfile = _mk_corpus(tmp_path)
    out = tmp_path / "out"
    rc = main(["2", "3", str(listfile), "--backend", "cpu",
               "--output-dir", str(out), "--host-threads", "3", "--stats"])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip())
    assert stats["host_threads"] == 3
    assert stats["num_mappers"] == 2 and stats["num_reducers"] == 3


def test_cli_emit_ownership_letter(tmp_path):
    import pytest

    import jax

    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import native
    if not native.available():
        pytest.skip("letter emit requires the pipelined (native) path")
    if len(jax.devices()) < 2:
        pytest.skip("letter emit needs a multi-device mesh")
    listfile = _mk_corpus(tmp_path)
    out_l, out_o = tmp_path / "l", tmp_path / "o"
    assert main(["1", "1", str(listfile), "--output-dir", str(out_l),
                 "--pad-multiple", "64", "--emit-ownership", "letter"]) == 0
    assert main(["1", "1", str(listfile), "--output-dir", str(out_o),
                 "--backend", "oracle"]) == 0
    assert read_letter_files(out_l) == read_letter_files(out_o)


def test_cli_stream_checkpoint_kill_resume(tmp_path, capsys, monkeypatch):
    """README's crash-resume example shape through the real parser:
    crash mid-stream, rerun the SAME command, resume at the checkpoint."""
    listfile = _mk_corpus(tmp_path)
    out = tmp_path / "out"
    ckpt = tmp_path / "run.ckpt.npz"
    argv = ["1", "1", str(listfile), "--output-dir", str(out),
            "--device-tokenize", "--stream-chunk-docs", "1",
            "--device-shards", "1", "--pad-multiple", "64",
            "--stream-checkpoint", str(ckpt),
            "--stream-checkpoint-every", "1", "--stats"]
    monkeypatch.setenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS", "1")
    import pytest

    with pytest.raises(RuntimeError, match="injected stream crash"):
        main(argv)
    assert ckpt.exists()
    monkeypatch.delenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS")
    capsys.readouterr()
    assert main(argv) == 0
    stats = json.loads(capsys.readouterr().out.strip())
    assert stats["resumed_from_window"] == 1
    assert not ckpt.exists()
    data = read_letter_files(out)
    assert b"alpha:[1]\n" in data and b"beta:[1 2]\n" in data


def test_cli_device_stream_engine(tmp_path, capsys):
    """README's streaming all-device example shape: --device-tokenize
    --stream-chunk-docs N --device-shards 1 through the real parser."""
    listfile = _mk_corpus(tmp_path)
    out = tmp_path / "out"
    rc = main(["1", "1", str(listfile), "--output-dir", str(out),
               "--device-tokenize", "--stream-chunk-docs", "1",
               "--device-shards", "1", "--pad-multiple", "64", "--stats"])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip())
    assert stats["stream_windows"] == 2
    assert "sort_cols" in stats  # the DEVICE streaming engine ran
    data = read_letter_files(out)
    assert b"alpha:[1]\n" in data and b"beta:[1 2]\n" in data
