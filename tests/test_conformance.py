"""Golden conformance: byte-identical output vs the compiled pthread
reference (goldens generated once, committed under tests/fixtures/).

This is the north-star acceptance criterion (SURVEY.md §4 item 1,
BASELINE.json: "output byte-identical to the pthread reducer").
"""

import hashlib

import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    build_index,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    manifest_from_dir,
)

# md5 of cat a.txt..z.txt produced by the reference binary (-O2 and ASan
# builds agree; BASELINE.md) on the full test_in corpus with a sorted
# manifest.
FULL_CORPUS_MD5 = "92600581e0685e69c056b65082326fc3"


def _golden(smoke_fixture) -> bytes:
    return read_letter_files(smoke_fixture / "golden")


def test_oracle_matches_reference_smoke(smoke_fixture, tmp_path):
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    oracle_index(m, tmp_path)
    assert read_letter_files(tmp_path) == _golden(smoke_fixture)


def test_tpu_backend_matches_reference_smoke(smoke_fixture, tmp_path):
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    stats = build_index(m, IndexConfig(backend="tpu", pad_multiple=64), output_dir=tmp_path)
    assert read_letter_files(tmp_path) == _golden(smoke_fixture)
    assert stats["lines_written"] > 0


def test_single_chip_u16_path_matches_reference_smoke(smoke_fixture, tmp_path):
    # device_shards=1 + pipeline off takes the one-shot uint16 feed/fetch
    # path (the pipelined default is covered in tests/test_pipelined.py)
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    build_index(
        m, IndexConfig(backend="tpu", pad_multiple=64, device_shards=1,
                       pipeline_chunk_docs=0),
        output_dir=tmp_path)
    assert read_letter_files(tmp_path) == _golden(smoke_fixture)


def test_numpy_tokenizer_path_matches_reference_smoke(smoke_fixture, tmp_path):
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    build_index(
        m, IndexConfig(backend="tpu", pad_multiple=64, use_native=False),
        output_dir=tmp_path)
    assert read_letter_files(tmp_path) == _golden(smoke_fixture)


def test_backends_agree_on_reference_small(reference_dir, tmp_path):
    m = read_manifest(reference_dir / "test_small.txt", base_dir=reference_dir)
    out_a, out_b = tmp_path / "oracle", tmp_path / "tpu"
    oracle_index(m, out_a)
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64), output_dir=out_b)
    got = read_letter_files(out_a)
    assert got == read_letter_files(out_b)
    # and both match the committed reference-binary goldens
    import pathlib

    golden = read_letter_files(
        pathlib.Path(__file__).parent / "fixtures" / "golden_ref_small")
    assert got == golden


@pytest.mark.slow
def test_full_corpus_md5(reference_dir, tmp_path):
    m = manifest_from_dir(reference_dir / "test_in")
    assert len(m) == 355
    build_index(m, IndexConfig(backend="tpu"), output_dir=tmp_path)
    digest = hashlib.md5(read_letter_files(tmp_path)).hexdigest()
    assert digest == FULL_CORPUS_MD5


@pytest.mark.slow
def test_full_corpus_md5_single_chip_u16(reference_dir, tmp_path):
    m = manifest_from_dir(reference_dir / "test_in")
    build_index(m, IndexConfig(backend="tpu", device_shards=1), output_dir=tmp_path)
    digest = hashlib.md5(read_letter_files(tmp_path)).hexdigest()
    assert digest == FULL_CORPUS_MD5
