"""Native serve kernels (``mri_serve_*``): byte-identity with the
numpy oracle engine.

The conformance contract is absolute: for every (query, k, planner
mode) the native backend must return the EXACT list — same doc ids,
same float64 score bits, same tie order — that the numpy engine
returns, and the decode/AND kernels must reproduce the artifact
decoders' matrices including their padding semantics.  The fuzz corpus
pins term dfs at the block-size boundaries (1/127/128/129/256/300 with
the default 128-doc blocks) and spreads doc ids so the packed delta
widths run from 0 (consecutive ids) up to the corpus maximum.
"""

import json
import os

import numpy as np
import pytest

from test_serve import build_corpus
from test_daemon import Client, serving

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    native,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve import (
    engine as engine_mod,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve import (
    planner as planner_mod,
)

pytestmark = [
    pytest.mark.serve,
    pytest.mark.skipif(not native.available(),
                       reason="no C++ toolchain"),
]

NDOCS = 1200
#: dfs straddling the default 128-doc block boundary
TARGET_DFS = (1, 127, 128, 129, 256, 300)
KS = (1, 10, 128)
MODES = ("auto", "exhaustive", "bmw", "maxscore")


def _corpus():
    """Deterministic member lists per term + the doc blobs."""
    import random
    rng = random.Random(41)
    members = {}
    for df in TARGET_DFS:
        if df == 1:
            ids = [NDOCS // 2]
        else:
            step = max(1, (NDOCS - 2) // df)
            ids = list(range(1, 1 + step * df, step))[:df]
        # tokenizer keeps alphabetic terms only: spell the df in
        # letters (1 -> "b", 127 -> "bch", ...)
        name = "df" + "".join("abcdefghij"[int(c)] for c in str(df))
        members[name] = ids
    # consecutive ids: delta-1 everywhere packs the block at width 0
    members["runzero"] = list(range(5, 5 + 300))
    # geometric gaps: deltas up to ~NDOCS push the width to the max
    g, ids = 1, []
    while g <= NDOCS:
        ids.append(g)
        g = max(g + 1, int(g * 1.9))
    members["wide"] = ids
    members["spread"] = sorted(rng.sample(range(1, NDOCS + 1), 300))
    for t in range(40):
        df = rng.randint(2, 200)
        members["noise" + "abcdefghij"[t // 10] + "abcdefghij"[t % 10]] \
            = sorted(rng.sample(range(1, NDOCS + 1), df))
    per_doc = [[] for _ in range(NDOCS + 1)]
    for name, docs in members.items():
        for d in docs:
            tf = 1 + ((d * (len(name) + 3)) % 9)
            per_doc[d].extend([name] * tf)
    blobs = []
    for d in range(1, NDOCS + 1):
        toks = per_doc[d] or ["filler"]
        rng.shuffle(toks)
        blobs.append(" ".join(toks).encode())
    return blobs, members


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    blobs, members = _corpus()
    out = build_corpus(tmp_path_factory.mktemp("native_serve"), blobs)
    return out, members


@pytest.fixture(scope="module")
def engines(built):
    """(numpy oracle, native-required) engine pair over one artifact.

    The backend knob is resolved at construction, so pinning the env
    around each constructor gives two engines with opposite backends
    that can then run side by side."""
    out, _ = built
    old = os.environ.get(engine_mod.NATIVE_ENV)
    try:
        os.environ[engine_mod.NATIVE_ENV] = "0"
        ref = engine_mod.Engine(out)
        os.environ[engine_mod.NATIVE_ENV] = "1"
        nat = engine_mod.Engine(out)
    finally:
        if old is None:
            os.environ.pop(engine_mod.NATIVE_ENV, None)
        else:
            os.environ[engine_mod.NATIVE_ENV] = old
    yield ref, nat
    nat.close()
    ref.close()


def _lex(engine, word: str) -> int:
    idx, found = engine.lookup(engine.encode_batch([word]))
    assert found[0], word
    return int(idx[0])


# -- decode kernels -------------------------------------------------------


def _assert_blocks_equal(art, h, sel):
    """ids match the oracle bit-for-bit INCLUDING its padding (rows
    past a block's count repeat the last real doc id); tf matches
    under the count mask — the only region either decoder defines."""
    want_ids, want_cnt = art.decode_blocks(sel)
    want_tf, _ = art.decode_tf_blocks(sel)
    got = h.decode_blocks(sel)
    assert got is not None
    ids, tfm, cnt = got
    np.testing.assert_array_equal(cnt, want_cnt)
    np.testing.assert_array_equal(ids, want_ids)
    mask = np.arange(art.block_size)[None, :] < want_cnt[:, None]
    np.testing.assert_array_equal(tfm[mask],
                                  want_tf[:, :art.block_size][mask])
    # native's own padding contract: tf entries past cnt are 1
    assert (tfm[~mask] == 1).all()


def test_decode_blocks_identity_all_terms(engines):
    """Every block of every term against the numpy decoders."""
    ref, nat = engines
    art = ref.artifact
    h = nat._native_handle()
    assert h is not None
    widths_seen = set()
    for i in range(art.vocab):
        b0, b1 = int(art.term_block_off[i]), int(art.term_block_off[i + 1])
        if b0 == b1:
            continue
        sel = np.arange(b0, b1, dtype=np.int64)
        widths_seen.update(art.blk_width[sel].tolist())
        _assert_blocks_equal(art, h, sel)
    assert 0 in widths_seen and max(widths_seen) >= 8, widths_seen


def test_decode_blocks_mixed_selection(engines):
    """One call over blocks of MANY terms at once (mixed widths and
    counts in a single selection vector, out of order)."""
    ref, nat = engines
    art = ref.artifact
    h = nat._native_handle()
    rng = np.random.default_rng(7)
    sel = rng.permutation(art.num_blocks)[:200].astype(np.int64)
    _assert_blocks_equal(art, h, sel)


def test_decode_postings_identity(engines, built):
    _, members = built
    ref, nat = engines
    art = ref.artifact
    h = nat._native_handle()
    for word, docs in members.items():
        i = _lex(ref, word)
        got = h.decode_postings(i, int(ref._df[i]))
        assert got is not None
        np.testing.assert_array_equal(got[0], art.decode_postings(i))
        np.testing.assert_array_equal(got[1], art.decode_tf(i))
        assert got[0].tolist() == docs


# -- AND kernel -----------------------------------------------------------


def test_and_kernel_against_set_oracle(engines, built):
    """Raw kernel vs set intersection, with candidates that miss every
    block, sit between members, or exceed the final blk_max."""
    _, members = built
    ref, nat = engines
    art = ref.artifact
    h = nat._native_handle()
    rng = np.random.default_rng(11)
    names = sorted(members)
    for trial in range(60):
        word = names[int(rng.integers(len(names)))]
        i = _lex(ref, word)
        run = art.decode_postings(i)
        n = int(rng.integers(1, 400))
        cand = np.unique(rng.integers(0, NDOCS + 40, size=n)
                         .astype(np.int32))
        res = h.query_and(cand, i)
        assert res is not None
        got, dec, skp = res
        want = np.intersect1d(cand, run)
        np.testing.assert_array_equal(got, want)
        b0, b1 = int(art.term_block_off[i]), int(art.term_block_off[i + 1])
        assert dec + skp == b1 - b0 and dec >= 0 and skp >= 0


def test_query_and_engine_parity(engines, built):
    _, members = built
    ref, nat = engines
    names = sorted(members)
    import random
    rng = random.Random(13)
    queries = [[n] for n in names[:6]]
    for _ in range(60):
        queries.append(rng.sample(names, rng.randint(2, 4)))
    queries.append(["dfb", "runzero", "wide"])
    queries.append(["dfb", "absentword"])
    for q in queries:
        a0 = ref.query_and(ref.encode_batch(q))
        a1 = nat.query_and(nat.encode_batch(q))
        np.testing.assert_array_equal(a0, a1)


# -- ranked kernel: the byte-identity fuzz matrix -------------------------


def _ranked_queries(members):
    import random
    rng = random.Random(17)
    names = sorted(members)
    qs = [[n] for n in names[:8]]          # singles, all boundary dfs
    qs += [[n, n] for n in names[:4]]      # duplicated occurrences
    for _ in range(40):
        qs.append(rng.sample(names, rng.randint(2, 5)))
    qs.append(names[:3] + ["absentword"])  # absent terms drop out
    qs.append(["absentword"])
    return qs


@pytest.mark.parametrize("mode", MODES)
def test_topk_bm25_byte_identity_matrix(engines, built, monkeypatch,
                                        mode):
    """The fuzz matrix: planner mode x k in {1,10,128} x boundary-df
    query mix.  Exact ``==`` on the (doc, score) lists — float bits
    included."""
    _, members = built
    ref, nat = engines
    monkeypatch.setenv(planner_mod.PLANNER_ENV, mode)
    for q in _ranked_queries(members):
        for k in KS:
            b = ref.encode_batch(q)
            r0 = ref.top_k_scored(b, k)
            r1 = nat.top_k_scored(nat.encode_batch(q), k)
            assert r0 == r1, (mode, q, k)
    d = nat.describe()["native"]
    assert d["ops"] > 0 and d["fallbacks"] == 0


@pytest.mark.parametrize("mode", MODES)
def test_topk_batch_parity(engines, built, monkeypatch, mode):
    """``top_k_scored_batch`` (the coalesced one-crossing path) must be
    byte-identical to issuing the group serially — cold first pass,
    warm second pass, and ragged group sizes included."""
    _, members = built
    ref, nat = engines
    monkeypatch.setenv(planner_mod.PLANNER_ENV, mode)
    qs = _ranked_queries(members)
    for k in KS:
        want = [ref.top_k_scored(ref.encode_batch(q), k) for q in qs]
        encs = [nat.encode_batch(q) for q in qs]
        for size in (1, 3, 8, len(qs)):
            got = []
            for i in range(0, len(encs), size):
                got.extend(nat.top_k_scored_batch(encs[i:i + size], k))
            assert got == want, (mode, k, size)


def test_topk_batch_accounting(engines, built, monkeypatch):
    """A coalesced group advances the planner's ranked counters by one
    per query (identical totals to the serial path) and lands its ops
    on the native counter."""
    _, members = built
    ref, nat = engines
    monkeypatch.setenv(planner_mod.PLANNER_ENV, "auto")
    names = sorted(members)
    qs = [[n, names[0]] for n in names[:6]]
    encs = [nat.encode_batch(q) for q in qs]
    for b in encs:  # warm every memo so the group fuses
        nat.top_k_scored(b, 5)
    before = nat.planner.describe()
    ops0 = nat.describe()["native"]["ops"]
    nat.top_k_scored_batch(encs, 5)
    after = nat.planner.describe()
    assert sum(after["ranked"].values()) \
        == sum(before["ranked"].values()) + len(qs)
    assert nat.describe()["native"]["ops"] >= ops0 + len(qs)
    assert after["last_ranked"]["backend"] == "native"
    # the numpy backend serves the same API through the serial path
    want = [ref.top_k_scored(ref.encode_batch(q), 5) for q in qs]
    assert ref.top_k_scored_batch(
        [ref.encode_batch(q) for q in qs], 5) == want
    assert ref.planner.describe()["last_ranked"]["backend"] == "numpy"


def test_topk_reports_native_backend(engines, built, monkeypatch):
    _, members = built
    ref, nat = engines
    monkeypatch.setenv(planner_mod.PLANNER_ENV, "auto")
    name = sorted(members)[0]
    nat.top_k_scored(nat.encode_batch([name, "spread"]), 5)
    last = nat.planner.describe()["last_ranked"]
    assert last["backend"] == "native"
    ref.top_k_scored(ref.encode_batch([name, "spread"]), 5)
    assert ref.planner.describe()["last_ranked"]["backend"] == "numpy"


def test_native_modes_zero_and_required(built, monkeypatch):
    """``0`` never builds a handle; ``1`` fails loudly when it can't."""
    out, members = built
    monkeypatch.setenv(engine_mod.NATIVE_ENV, "0")
    with engine_mod.Engine(out) as eng:
        eng.top_k_scored(eng.encode_batch([sorted(members)[0]]), 3)
        d = eng.describe()["native"]
        assert d == {"mode": "0", "active": False, "error": None,
                     "ops": 0, "fallbacks": 0}
    monkeypatch.setenv(engine_mod.NATIVE_ENV, "1")
    monkeypatch.setattr(native, "load", lambda *a, **kw: None)
    with pytest.raises(RuntimeError, match="MRI_SERVE_NATIVE=1"):
        engine_mod.Engine(out)


# -- daemon: wire parity + knob re-resolution on reload -------------------


def test_daemon_wire_parity_native_flipped(built, monkeypatch):
    """The daemon's ranked/AND answers are byte-identical with
    ``MRI_SERVE_NATIVE`` flipped both ways."""
    out, members = built
    names = sorted(members)
    got = {}
    for flag in ("1", "0"):
        monkeypatch.setenv(engine_mod.NATIVE_ENV, flag)
        with serving(out) as d, Client(d) as c:
            r = c.rpc(id=1, op="top_k", score="bm25", k=10,
                      terms=names[:3])
            assert r["ok"]
            a = c.rpc(id=2, op="and", terms=["runzero", "spread"])
            assert a["ok"]
            s = c.rpc(id=3, op="stats")["stats"]
            assert s["engine"]["native"]["mode"] == flag
            if flag == "1":
                assert s["engine"]["native"]["ops"] > 0
            # ranked "docs" carries [doc, score] pairs: float64 bits
            # round-trip exactly through the JSON wire
            got[flag] = (r["docs"], a["docs"])
    assert got["1"] == got["0"]


def test_daemon_reload_reresolves_backend_knobs(built, monkeypatch):
    """Satellite regression: knobs resolved at engine construction
    (``MRI_SERVE_NATIVE``) are NOT live-read — they must re-resolve
    when a SIGHUP reload swaps the engine, not before."""
    out, members = built
    name = sorted(members)[0]
    monkeypatch.setenv(engine_mod.NATIVE_ENV, "0")
    monkeypatch.setenv(planner_mod.PLANNER_ENV, "maxscore")
    with serving(out) as d, Client(d) as c:
        r = c.rpc(id=1, op="top_k", score="bm25", k=3,
                  terms=[name, "spread"])
        assert r["ok"]
        s = c.rpc(id=2, op="stats")["stats"]["engine"]
        assert s["native"]["mode"] == "0"
        assert s["planner"]["last_ranked"]["backend"] == "numpy"
        assert s["planner"]["last_ranked"]["mode"] == "maxscore"
        # flip the env: the serving engine must keep its memoized
        # resolution until the reload swap installs a fresh engine
        monkeypatch.setenv(engine_mod.NATIVE_ENV, "1")
        s = c.rpc(id=3, op="stats")["stats"]["engine"]
        assert s["native"]["mode"] == "0"
        ok, err = d.reload()
        assert ok, err
        r = c.rpc(id=4, op="top_k", score="bm25", k=3,
                  terms=[name, "spread"])
        assert r["ok"]
        s = c.rpc(id=5, op="stats")["stats"]["engine"]
        assert s["native"]["mode"] == "1"
        assert s["planner"]["last_ranked"]["backend"] == "native"


# -- CLI: --stats audits the answering backend ----------------------------


def test_cli_query_stats_reports_backend(built, monkeypatch, capsys):
    out, members = built
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (  # noqa: E501
        main as cli_main,
    )
    name = sorted(members)[0]
    monkeypatch.setenv(engine_mod.NATIVE_ENV, "1")
    assert cli_main(["query", str(out), name, "spread", "--score",
                     "bm25", "--top-k", "3", "--stats"]) == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["native"]["mode"] == "1" and stats["native"]["ops"] > 0
    assert stats["planner"]["last_ranked"]["backend"] == "native"
    monkeypatch.setenv(engine_mod.NATIVE_ENV, "0")
    assert cli_main(["query", str(out), name, "spread", "--score",
                     "bm25", "--top-k", "3", "--stats"]) == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["native"]["mode"] == "0" and stats["native"]["ops"] == 0
    assert stats["planner"]["last_ranked"]["backend"] == "numpy"


def test_cli_query_bad_native_knob_exits_2(built, monkeypatch, capsys):
    """A bad ``$MRI_SERVE_NATIVE`` hits the CLI's one-line exit-2
    contract even though the knob is read at engine construction,
    not lazily at query time like the planner knob."""
    out, members = built
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (  # noqa: E501
        main as cli_main,
    )
    monkeypatch.setenv(engine_mod.NATIVE_ENV, "2")
    assert cli_main(["query", str(out), sorted(members)[0], "--score",
                     "bm25", "--top-k", "3"]) == 2
    err = capsys.readouterr().err.strip()
    assert err.startswith("error:") and "MRI_SERVE_NATIVE" in err \
        and "\n" not in err
