"""Query-cost attribution suite (obs/attribution.py + explain surface).

Four layers:

* parity gate — summing per-request ``Collector.totals()`` over a 20K
  mixed-op workload reproduces the engine registry counters EXACTLY
  (blocks decoded/skipped, bytes decoded, cache hits/misses, planner
  blocks scored/skipped) on the host, device and multi-segment
  engines; every feed site sits beside the counter increment it
  mirrors, so any drift is a wiring bug, not noise;
* explain surface — ``mri query --explain`` and the daemon's
  ``{"explain": true}`` flag return the structured cost report
  (per-term resolution paths, planner decision, per-stage µs,
  per-segment breakdown), and explain'd requests run solo — never
  inside a coalesced batch;
* flight recorder — ring semantics, the ``flightdump`` admin op and
  CLI, the SIGQUIT dump-while-serving path, and the abnormal-drain
  (``drain-flush``) dump;
* exposition — OpenMetrics exemplars on histogram bucket lines,
  ``merge_expositions`` family dedup across the daemon + engine +
  per-segment registries, trace-ring contiguity while
  generation-stamped mutation spans interleave with query spans, and
  the mrilint ``trace-coverage`` checker.

Daemon-touching tests carry the ``daemon`` marker too, so the conftest
leak guard holds them to the no-stray-sockets/threads contract.
"""

import json
import os
import random
import signal
import threading
import time

import numpy as np
import pytest

from test_daemon import DOCS, Client, _reap, _spawn_serve, serving

from test_serve import build_corpus, naive_index

from test_format_v2 import build_corpus_fmt, word

from test_segments import _WORDS, doc_specs, make_docs

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    faults, segments,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
    main as cli_main,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    attribution as obs_attrib,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    metrics as obs_metrics,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.daemon import (
    ServeDaemon,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.engine import (
    create_engine,
)

pytestmark = pytest.mark.attrib


@pytest.fixture(autouse=True)
def _disarm():
    faults.install(None)
    yield
    faults.install(None)


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    out = build_corpus(tmp_path_factory.mktemp("attrib_corpus"), DOCS)
    return out, naive_index(DOCS)


@pytest.fixture(scope="module")
def fmt_built(tmp_path_factory):
    """A v2.1 (block-max) artifact over a skewed synthetic corpus —
    large enough that ranked queries exercise block skipping and the
    term-resolution memo/cache paths."""
    rng = random.Random(1311)
    docs = []
    for _ in range(120):
        n = rng.randrange(8, 40)
        docs.append(" ".join(
            word(int(rng.paretovariate(1.2)) % 80)
            for _ in range(n)).encode())
    return build_corpus_fmt(tmp_path_factory.mktemp("attrib_fmt"), docs, 3)


@pytest.fixture(scope="module")
def seg_built(tmp_path_factory):
    """A two-segment live index dir (two appends into an empty dir)."""
    tmp = tmp_path_factory.mktemp("attrib_segs")
    rng = random.Random(29)
    idx = tmp / "idx"
    p1, _ = make_docs(tmp, doc_specs(rng, 10), prefix="s1")
    p2, _ = make_docs(tmp, doc_specs(rng, 8), prefix="s2")
    segments.append_files(idx, p1)
    segments.append_files(idx, p2)
    return idx


# -- collector unit semantics ---------------------------------------------


def test_collector_feeds_report_and_rollup():
    coll = obs_attrib.Collector(op="top_k")
    coll.term(b"cat", 3, True, 7, "memo")
    coll.decoded(2, 128)
    coll.skipped(1)
    coll.cache_event(3, True, "mri_serve_cache")
    coll.cache_event(np.int64(4), False, "mri_serve_cache")
    coll.ranked("bmw", 5, 9, 14)
    coll.theta(0.5)
    coll.and_arm("gallop")
    coll.stage("engine", 12.34)
    child = coll.child("seg_1_0")
    child.decoded(1, 64)
    assert coll.totals() == {
        "blocks_decoded": 3, "blocks_skipped": 1, "bytes_decoded": 192,
        "cache_hits": 1, "cache_misses": 1,
        "planner_blocks_scored": 5, "planner_blocks_skipped": 9,
    }
    rep = coll.report()
    assert rep["op"] == "top_k"
    assert rep["terms"][0] == {"term": "cat", "idx": 3, "found": True,
                               "df": 7, "path": "memo"}
    assert rep["planner"]["mode"] == "bmw"
    assert rep["planner"]["theta"] == [0.5]
    assert rep["planner"]["and_arms"] == ["gallop"]
    assert rep["cache"]["events"][1] == {"cache": "mri_serve_cache",
                                         "key": 4, "hit": False}
    assert rep["stages_us"] == {"engine": 12.3}
    assert rep["segments"][0]["segment"] == "seg_1_0"
    assert rep["totals"] == coll.totals()
    json.dumps(rep)  # wire-safe: no numpy scalars survive assembly


def test_collect_installs_and_restores():
    assert obs_attrib.active() is None
    with obs_attrib.collect("df") as coll:
        assert obs_attrib.active() is coll
        token = obs_attrib.install(None)  # nested explicit override
        assert obs_attrib.active() is None
        obs_attrib.uninstall(token)
        assert obs_attrib.active() is coll
    assert obs_attrib.active() is None


# -- parity gate: per-request totals == registry counters -----------------

#: collector-total key -> the registry counter it must mirror exactly
_PARITY_COUNTERS = {
    "blocks_decoded": "mri_engine_blocks_decoded_total",
    "blocks_skipped": "mri_engine_blocks_skipped_total",
    "bytes_decoded": "mri_engine_bytes_decoded_total",
    "planner_blocks_scored": "mri_planner_blocks_scored_total",
    "planner_blocks_skipped": "mri_planner_blocks_skipped_total",
}

_LETTERS = "abcdefghijklmnopqrstuvwxyz"


def _registry_totals(regs) -> dict:
    out = {k: 0 for k in _PARITY_COUNTERS}
    out["cache_hits"] = 0
    out["cache_misses"] = 0
    inverse = {v: k for k, v in _PARITY_COUNTERS.items()}
    for reg in regs:
        for name, val in reg.as_dict().items():
            if not isinstance(val, (int, float)):
                continue  # histogram snapshots
            if name in inverse:
                out[inverse[name]] += int(val)
            elif name.endswith("_hits_total"):
                out["cache_hits"] += int(val)
            elif name.endswith("_misses_total"):
                out["cache_misses"] += int(val)
    return out


def _drive(eng, rng, vocab, n) -> dict:
    """``n`` requests of the mixed op set, each under its own
    collector; returns the summed per-request totals."""
    sums = {k: 0 for k in _PARITY_COUNTERS}
    sums["cache_hits"] = 0
    sums["cache_misses"] = 0
    for _ in range(n):
        r = rng.random()
        terms = [vocab[rng.randrange(len(vocab))]
                 for _ in range(rng.randrange(1, 4))]
        with obs_attrib.collect() as coll:
            if r < 0.40:
                eng.top_k_scored(eng.encode_batch(terms),
                                 rng.choice((1, 5, 20)))
            elif r < 0.50:
                eng.top_k(rng.choice(_LETTERS), 5)
            elif r < 0.65:
                eng.query_and(eng.encode_batch(terms))
            elif r < 0.75:
                eng.query_or(eng.encode_batch(terms))
            elif r < 0.90:
                eng.df(eng.encode_batch(terms))
            else:
                eng.postings(eng.encode_batch(terms[:1]))
        for k, v in coll.totals().items():
            sums[k] += v
    return sums


def _assert_parity(eng, regs, vocab, n, seed, *, want_cache=True,
                   want_planner=True):
    base = _registry_totals(regs)
    sums = _drive(eng, random.Random(seed), vocab, n)
    after = _registry_totals(regs)
    delta = {k: after[k] - base[k] for k in after}
    assert sums == delta
    # the workload actually exercised the planes being attributed
    # (the device engine keeps postings resident — its decode plane
    # counts, but the host LRU caches and block-max planner may not
    # fire there)
    assert sums["bytes_decoded"] > 0
    if want_cache:
        assert sums["cache_hits"] > 0 and sums["cache_misses"] > 0
    if want_planner:
        assert sums["planner_blocks_scored"] > 0


_FMT_VOCAB = [word(i) for i in range(80)] + ["qqabsent", "qqmissing"]


@pytest.mark.serve
def test_attribution_parity_host_20k(fmt_built):
    eng = create_engine(str(fmt_built), "host")
    try:
        _assert_parity(eng, [eng.metrics], _FMT_VOCAB, 20000, seed=5)
    finally:
        eng.close()


@pytest.mark.serve
@pytest.mark.device_serve
def test_attribution_parity_device(fmt_built):
    eng = create_engine(str(fmt_built), "device")
    try:
        _assert_parity(eng, [eng.metrics], _FMT_VOCAB, 1500, seed=7,
                       want_cache=False, want_planner=False)
    finally:
        eng.close()


@pytest.mark.slow
@pytest.mark.serve
@pytest.mark.device_serve
def test_attribution_parity_device_20k(fmt_built):
    eng = create_engine(str(fmt_built), "device")
    try:
        _assert_parity(eng, [eng.metrics], _FMT_VOCAB, 20000, seed=9,
                       want_cache=False, want_planner=False)
    finally:
        eng.close()


_SEG_VOCAB = _WORDS + ["qqabsent"]


@pytest.mark.serve
@pytest.mark.segments
def test_attribution_parity_multi_segment_20k(seg_built):
    eng = create_engine(str(seg_built), None)
    try:
        assert type(eng).__name__ == "MultiSegmentEngine"
        regs = [eng.metrics] + [s.engine.metrics for s in eng._segs]
        _assert_parity(eng, regs, _SEG_VOCAB, 20000, seed=11)
        # per-segment children appear in the report and roll up
        with obs_attrib.collect("top_k_scored") as coll:
            eng.top_k_scored(eng.encode_batch(_WORDS[:2]), 5)
        rep = coll.report()
        names = [s["segment"] for s in rep.get("segments", ())]
        assert len(names) == len(eng._segs) and len(set(names)) == 2
        for key in ("blocks_decoded", "bytes_decoded"):
            assert rep["totals"][key] == rep["engine"][key] + sum(
                s["totals"][key] for s in rep["segments"])
    finally:
        eng.close()


# -- explain surface: CLI -------------------------------------------------


@pytest.mark.serve
def test_cli_query_explain_ranked_and_boolean(built, capsys):
    out, _ = built
    assert cli_main(["query", str(out), "cat", "dog", "--top-k", "2",
                     "--score", "bm25", "--explain"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    exp = [json.loads(ln) for ln in lines if ln.startswith('{"explain"')]
    assert len(exp) == 1
    rep = exp[0]["explain"]
    assert rep["op"] == "top_k_scored"
    assert {t["term"] for t in rep["terms"]} >= {"cat", "dog"}
    for t in rep["terms"]:
        assert t["path"] in ("memo", "bisect", "cache", "device")
    # a "/native" suffix labels the span when the C kernel executed it
    assert rep["planner"]["mode"].split("/")[0] in (
        "exhaustive", "bmw", "maxscore")
    # default per-term mode explains as df+postings
    assert cli_main(["query", str(out), "cat", "--explain"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    rep = [json.loads(ln) for ln in lines
           if ln.startswith('{"explain"')][0]["explain"]
    assert rep["op"] == "df+postings"
    # without the flag no explain line is printed
    assert cli_main(["query", str(out), "cat"]) == 0
    assert '"explain"' not in capsys.readouterr().out


# -- explain surface: daemon ----------------------------------------------


@pytest.mark.daemon
@pytest.mark.serve
def test_daemon_explain_ranked_report(built):
    out, idx = built
    with serving(out) as d, Client(d) as cli:
        r = cli.rpc(id=1, op="top_k", score="bm25", k=3,
                    terms=["cat", "dog"], explain=True)
        assert r["ok"]
        rep = r["explain"]
        assert rep["op"] == "top_k"
        terms = {t["term"]: t for t in rep["terms"]}
        assert terms["cat"]["df"] == len(idx["cat"])
        assert terms["cat"]["found"]
        assert rep["planner"]["mode"].split("/")[0] in (
            "exhaustive", "bmw", "maxscore")
        assert set(rep["stages_us"]) >= {"queue", "coalesce", "engine"}
        assert all(v >= 0 for v in rep["stages_us"].values())
        assert rep["totals"]["blocks_decoded"] == \
            rep["engine"]["blocks_decoded"]
        # the flag is opt-in per request and type-checked
        r2 = cli.rpc(id=2, op="top_k", score="bm25", k=3, terms=["cat"])
        assert r2["ok"] and "explain" not in r2
        r3 = cli.rpc(id=3, op="df", terms=["cat"], explain=1)
        assert r3["error"] == "bad_request"


@pytest.mark.daemon
@pytest.mark.serve
def test_daemon_explain_runs_solo_outside_coalesced_batch(built):
    out, _ = built
    with serving(out, coalesce_us=5000, max_batch=8) as d, \
            Client(d) as cli:
        # four plain df's coalesce into one engine call; the explain'd
        # one must execute alone so its report covers only its terms
        for i in range(4):
            cli.send(id=i, op="df", terms=["zebra"])
        cli.send(id=9, op="df", terms=["cat", "dog"], explain=True)
        got = {g["id"]: g for g in (cli.recv() for _ in range(5))}
        assert all(got[i]["ok"] for i in (0, 1, 2, 3, 9))
        rep = got[9]["explain"]
        assert sorted(t["term"] for t in rep["terms"]) == ["cat", "dog"]


@pytest.mark.daemon
@pytest.mark.serve
@pytest.mark.segments
def test_daemon_explain_multi_segment_breakdown(seg_built):
    with serving(str(seg_built)) as d, Client(d) as cli:
        r = cli.rpc(id=1, op="top_k", score="bm25", k=5,
                    terms=[_WORDS[0], _WORDS[1]], explain=True)
        assert r["ok"]
        rep = r["explain"]
        segs = rep.get("segments")
        assert segs and len(segs) == 2
        for key in ("blocks_decoded", "bytes_decoded"):
            assert rep["totals"][key] == rep["engine"][key] + sum(
                s["totals"][key] for s in segs)


# -- flight recorder ------------------------------------------------------


def test_flight_recorder_ring_and_slow_retention():
    fr = obs_attrib.FlightRecorder(capacity=3, slow_threshold_ms=5.0)
    assert fr.enabled
    for i in range(5):
        fr.record({"trace_id": f"t{i}", "dur_ms": float(i)})
    assert len(fr) == 3
    doc = fr.dump("why")
    assert doc["reason"] == "why" and doc["capacity"] == 3
    assert [e["trace"]["trace_id"] for e in doc["requests"]] == \
        ["t4", "t3", "t2"]
    fr.record({"trace_id": "slowpoke", "dur_ms": 9.0}, {"op": "x"})
    # a burst of fast traffic evicts it from the recent ring but not
    # from the offenders ring
    for i in range(10):
        fr.record({"trace_id": f"f{i}", "dur_ms": 0.1})
    doc = fr.dump("again")
    assert all(e["trace"]["trace_id"] != "slowpoke"
               for e in doc["requests"])
    assert doc["slow"][0]["trace"]["trace_id"] == "slowpoke"
    assert doc["slow"][0]["report"] == {"op": "x"}
    off = obs_attrib.FlightRecorder(capacity=0)
    assert not off.enabled
    off.record({"trace_id": "x", "dur_ms": 1.0})
    assert len(off) == 0 and off.dump_to_file(".", "x") is None


def test_flight_dump_to_file_paths_and_sanitization(tmp_path):
    fr = obs_attrib.FlightRecorder(capacity=2)
    fr.record({"trace_id": "a", "dur_ms": 1.0})
    p = fr.dump_to_file(str(tmp_path), "a/b c")
    assert p is not None
    assert os.path.basename(p) == f"flight-{os.getpid()}-a-b-c.json"
    doc = json.loads(open(p, encoding="utf-8").read())
    assert doc["reason"] == "a/b c" and doc["pid"] == os.getpid()
    # a file target dumps beside it (dir-or-file-dirname semantics)
    p2 = fr.dump_to_file(str(tmp_path / "index.mri"), "z")
    assert os.path.dirname(p2) == str(tmp_path)
    # crash-path safe: unwritable target returns None, never raises
    assert fr.dump_to_file(str(tmp_path / "nope" / "deeper"),
                           "z") is None


@pytest.mark.daemon
@pytest.mark.serve
def test_daemon_flightdump_admin_op_and_cli(built, tmp_path, capsys,
                                            monkeypatch):
    monkeypatch.setenv("MRI_OBS_FLIGHT_RING", "4")
    out, _ = built
    with serving(out) as d:
        with Client(d) as cli:
            for i in range(6):
                assert cli.rpc(id=i, op="df", terms=["cat"],
                               explain=(i % 2 == 0))["ok"]
            r = cli.rpc(id=10, op="flightdump")
            assert r["ok"]
            fl = r["flight"]
            assert fl["reason"] == "admin" and fl["capacity"] == 4
            assert len(fl["requests"]) == 4  # ring covers the last N
            # explain'd requests carry their cost report in the ring
            assert any(e["report"] is not None for e in fl["requests"])
            assert all(e["report"] is None or "totals" in e["report"]
                       for e in fl["requests"])
            # write_to lands the same dump on disk
            where = tmp_path / "ops" / "dump.json"
            where.parent.mkdir()
            r2 = cli.rpc(id=11, op="flightdump", write_to=str(where))
            assert r2["ok"]
            doc = json.loads(open(r2["path"], encoding="utf-8").read())
            assert doc["reason"] == "admin"
        host, port = d.address
        outfile = tmp_path / "cli-dump.json"
        assert cli_main(["flightdump", f"{host}:{port}",
                         "--out", str(outfile)]) == 0
        printed = json.loads(capsys.readouterr().out)
        assert printed["reason"] == "admin" and printed["requests"]
        assert json.loads(outfile.read_text()) == printed


@pytest.mark.daemon
@pytest.mark.serve
def test_sigquit_dumps_flight_and_keeps_serving(built):
    out, _ = built
    proc, addr = _spawn_serve(out,
                              env_extra={"MRI_OBS_FLIGHT_RING": "8"})
    try:
        with Client(addr) as cli:
            for i in range(5):
                assert cli.rpc(id=i, op="df", terms=["cat"],
                               explain=(i % 2 == 0))["ok"]
            proc.send_signal(signal.SIGQUIT)
            path = out / f"flight-{proc.pid}-sigquit.json"
            deadline = time.monotonic() + 10.0
            while not path.exists() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert path.exists(), "SIGQUIT produced no flight dump"
            doc = json.loads(path.read_text(encoding="utf-8"))
            assert doc["reason"] == "sigquit" and doc["pid"] == proc.pid
            assert 0 < len(doc["requests"]) <= 8
            assert any(e["report"] for e in doc["requests"])
            # the dump is diagnostics, not shutdown
            assert cli.rpc(id=99, op="healthz")["ok"]
    finally:
        proc.send_signal(signal.SIGTERM)
        assert _reap(proc) == 0


@pytest.mark.daemon
@pytest.mark.serve
def test_abnormal_drain_dumps_flight(tmp_path):
    """Drain with work still queued (budget expired) must flush the
    stragglers AND leave a drain-flush flight dump behind."""
    out = build_corpus(tmp_path, DOCS)
    daemon = ServeDaemon(str(out), coalesce_us=0, max_batch=1,
                         drain_s=0.05)
    daemon.start()
    gate = threading.Event()
    eng = daemon._engine
    orig_df = eng.df

    def gated_df(batch):
        gate.wait(30.0)
        return orig_df(batch)

    eng.df = gated_df
    cli = Client(daemon)
    try:
        cli.send(id=1, op="df", terms=["cat"])  # wedges the dispatcher
        deadline = time.monotonic() + 5.0
        while daemon._queue.qsize() > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        for i in range(4):
            cli.send(id=10 + i, op="df", terms=["dog"])
        while daemon._queue.qsize() < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert daemon._queue.qsize() >= 4
        drainer = threading.Thread(target=daemon.drain,
                                   name="test-drainer")
        drainer.start()
        path = out / f"flight-{os.getpid()}-drain-flush.json"
        deadline = time.monotonic() + 15.0
        while not path.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        gate.set()  # un-wedge so drain can finish and close the engine
        drainer.join(timeout=30.0)
        assert not drainer.is_alive()
        assert path.exists(), "abnormal drain produced no flight dump"
        doc = json.loads(path.read_text(encoding="utf-8"))
        assert doc["reason"] == "drain-flush"
        assert len(doc["requests"]) >= 4
        statuses = {e["trace"]["status"] for e in doc["requests"]}
        assert "draining" in statuses
    finally:
        gate.set()
        cli.close()
        daemon.drain()


# -- OpenMetrics exemplars ------------------------------------------------


def test_histogram_exemplar_render_and_merge():
    reg = obs_metrics.Registry()
    h = reg.histogram("t_seconds")
    h.observe(0.001)
    h.observe(0.002, exemplar="abc123")
    plain = reg.render_text()
    assert "trace_id" not in plain
    ex = reg.render_text(exemplars=True)
    tagged = [ln for ln in ex.splitlines()
              if '# {trace_id="abc123"}' in ln]
    assert tagged and all("_bucket{" in ln for ln in tagged)
    # suffix carries the representative value and a unix timestamp
    suffix = tagged[0].split(" # ", 1)[1]
    _labels, val, ts = suffix.rsplit(" ", 2)
    assert float(val) == pytest.approx(0.002)
    assert float(ts) > 0
    # merge keeps the exemplar suffix and dedups the family
    merged = obs_metrics.merge_expositions([ex, plain])
    assert merged.count("# TYPE t_seconds histogram") == 1
    assert '# {trace_id="abc123"}' in merged


def test_merge_expositions_three_registry_dedup():
    daemon_reg = obs_metrics.Registry()
    eng_reg = obs_metrics.Registry()
    seg_reg = obs_metrics.Registry()
    daemon_reg.gauge("mri_generation").set(5)
    daemon_reg.counter("mri_serve_requests_total").inc()
    eng_reg.gauge("mri_generation").set(4)
    eng_reg.gauge("mri_engine_vocab_terms").set(10)
    seg_reg.gauge("mri_engine_vocab_terms").set(7)
    seg_reg.counter("mri_engine_blocks_decoded_total").inc(3)
    merged = obs_metrics.merge_expositions(
        [r.render_text() for r in (daemon_reg, eng_reg, seg_reg)])
    fams = [ln.split()[2] for ln in merged.splitlines()
            if ln.startswith("# TYPE ")]
    assert len(fams) == len(set(fams))
    # first occurrence wins for duplicated families...
    assert "mri_generation 5" in merged
    assert "mri_generation 4" not in merged
    assert "mri_engine_vocab_terms 10" in merged
    assert "mri_engine_vocab_terms 7" not in merged
    # ...and unique families survive from every part
    assert "mri_engine_blocks_decoded_total 3" in merged


@pytest.mark.daemon
@pytest.mark.serve
def test_daemon_metrics_exemplars_toggle(built, monkeypatch):
    out, _ = built
    with serving(out) as d, Client(d) as cli:
        for i in range(4):
            assert cli.rpc(id=i, op="df", terms=["cat"])["ok"]
        text = cli.rpc(id=9, op="metrics")["text"]
        assert '# {trace_id="' in text
        for ln in text.splitlines():
            if "trace_id=" in ln:
                assert "_bucket{" in ln  # exemplars ride buckets only
    monkeypatch.setenv("MRI_OBS_EXEMPLARS", "0")
    with serving(out) as d, Client(d) as cli:
        assert cli.rpc(id=1, op="df", terms=["cat"])["ok"]
        assert "trace_id=" not in cli.rpc(id=9, op="metrics")["text"]


@pytest.mark.daemon
@pytest.mark.serve
@pytest.mark.segments
def test_daemon_scrape_merges_three_registries_with_exemplars(seg_built):
    """Daemon registry + multi-engine registry + per-segment engine
    registries all fold into ONE exposition: every family named once,
    exemplar suffixes intact."""
    with serving(str(seg_built)) as d, Client(d) as cli:
        assert cli.rpc(id=1, op="top_k", score="bm25", k=3,
                       terms=[_WORDS[0]])["ok"]
        text = cli.rpc(id=2, op="metrics")["text"]
        fams = [ln.split()[2] for ln in text.splitlines()
                if ln.startswith("# TYPE ")]
        assert len(fams) == len(set(fams))
        assert "mri_generation" in fams
        assert "mri_segments_active" in fams
        assert '# {trace_id="' in text


# -- mutation spans + trace-ring contiguity -------------------------------


@pytest.mark.daemon
@pytest.mark.segments
def test_mutation_trace_spans_carry_generation(tmp_path, monkeypatch):
    monkeypatch.setenv("MRI_SEGMENT_TOMBSTONE_FLUSH", "3")
    rng = random.Random(7)
    paths, _ = make_docs(tmp_path, doc_specs(rng, 4), prefix="m")
    idx = tmp_path / "idx"
    segments.append_files(idx, paths)
    with serving(str(idx)) as d, Client(d) as cli:
        more, _ = make_docs(tmp_path, doc_specs(rng, 2), prefix="m2")
        r = cli.rpc(id=1, op="append", files=more)
        assert r["ok"]
        gen_append = r["result"]["generation"]
        r2 = cli.rpc(id=2, op="delete", docs=[1])
        assert r2["ok"] and r2["result"]["buffered"]
        r3 = cli.rpc(id=3, op="compact")
        assert r3["ok"]
        gen_compact = r3["result"]["generation"]
        traces = cli.rpc(id=4, op="trace", n=32)["traces"]
        by_op = {}
        for t in traces:
            by_op.setdefault(t["op"], []).append(t)
        ap = by_op["append"][0]
        assert ap["generation"] == gen_append
        assert ap["spans"][0]["name"] == "append"
        assert ap["spans"][0]["generation"] == gen_append
        # a buffered delete published nothing — no generation to stamp
        dl = by_op["delete"][0]
        assert "generation" not in dl
        assert "generation" not in dl["spans"][0]
        cp = by_op["compact"][0]
        assert cp["generation"] == gen_compact
        assert cp["spans"][0]["generation"] == gen_compact


@pytest.mark.daemon
@pytest.mark.segments
def test_trace_ring_contiguity_under_concurrent_mutations(
        tmp_path, monkeypatch):
    """Query spans stay complete and contiguous while append/compact
    spans (generation-stamped) land in the same ring from another
    connection under load."""
    monkeypatch.setenv("MRI_OBS_TRACE_RING", "256")
    rng = random.Random(11)
    paths, _ = make_docs(tmp_path, doc_specs(rng, 6), prefix="c")
    idx = tmp_path / "idx"
    segments.append_files(idx, paths)
    batches = [make_docs(tmp_path, doc_specs(rng, 2), prefix=f"c{i}")[0]
               for i in range(3)]
    with serving(str(idx)) as d:
        errs = []

        def mutator():
            try:
                with Client(d) as mc:
                    for i, files in enumerate(batches):
                        r = mc.rpc(id=100 + i, op="append", files=files)
                        assert r["ok"], r
                    assert mc.rpc(id=200, op="compact")["ok"]
            except Exception as e:  # surfaced on the main thread
                errs.append(e)

        mt = threading.Thread(target=mutator, name="test-mutator")
        mt.start()
        try:
            with Client(d) as qc:
                for i in range(60):
                    r = qc.rpc(id=i, op="and",
                               terms=[_WORDS[0], _WORDS[1]],
                               trace_id=f"q{i:03d}")
                    assert r["ok"], r
        finally:
            mt.join(timeout=60.0)
        assert not errs
        with Client(d) as qc:
            traces = qc.rpc(op="trace", n=256)["traces"]
        qts = [t for t in traces if t["op"] == "and"]
        assert len(qts) >= 50
        engine_traces = 0
        for t in qts:
            names = [s["name"] for s in t["spans"]]
            if names == ["result_cache"]:
                # repeats of the hot query answered by the result
                # cache between generation bumps
                assert t["spans"][0]["start_ms"] == 0.0
                continue
            engine_traces += 1
            assert names == ["queue_wait", "coalesce", "engine"]
            assert t["spans"][0]["start_ms"] == 0.0
            for a, b in zip(t["spans"], t["spans"][1:]):
                assert b["start_ms"] == pytest.approx(
                    a["start_ms"] + a["dur_ms"], abs=2e-3)
        # every generation bump purges the cache, so the engine must
        # have answered at least the cold query per generation
        assert engine_traces >= 1
        mts = [t for t in traces if t["op"] in ("append", "compact")]
        assert len(mts) == 4
        for t in mts:
            assert isinstance(t["generation"], int)
            assert t["spans"][0]["generation"] == t["generation"]


# -- mrilint trace-coverage checker ---------------------------------------


@pytest.mark.lint
def test_trace_coverage_checker_engines_and_daemon(tmp_path):
    from tools.mrilint.checks import trace_coverage
    from tools.mrilint.core import PACKAGE, Source

    def src_for(text, rel, name="x.py"):
        p = tmp_path / name
        p.write_text(text, encoding="utf-8")
        s = Source(p, root=tmp_path)
        s.rel = rel
        return s

    eng_rel = f"{PACKAGE}/serve/engine.py"
    bare = ("class FooEngine:\n"
            "    def df(self, batch):\n"
            "        return batch\n")
    found = trace_coverage.check(src_for(bare, eng_rel))
    assert [f.key for f in found] == ["engine-op@FooEngine.df"]
    # an OpTimer span, an attribution feed, or a reasoned allow each
    # satisfy the rule
    timed = bare.replace("return batch",
                         "with self._ops.time('df'):\n"
                         "            return batch")
    assert trace_coverage.check(src_for(timed, eng_rel, "t.py")) == []
    fed = bare.replace("return batch",
                       "obs_attrib.active()\n        return batch")
    assert trace_coverage.check(src_for(fed, eng_rel, "f.py")) == []
    allowed = bare.replace(
        "return batch",
        "# mrilint: allow(trace) delegation\n        return batch")
    assert trace_coverage.check(src_for(allowed, eng_rel, "a.py")) == []
    # non-op methods and helper classes are out of scope
    other = ("class Helper:\n"
             "    def df(self, batch):\n"
             "        return batch\n")
    assert trace_coverage.check(src_for(other, eng_rel, "h.py")) == []

    dmn_rel = f"{PACKAGE}/serve/daemon.py"
    dmn = ('ADMIN_OPS = ("stats", "newop")\n\n\n'
           "class D:\n"
           "    def f(self):\n"
           '        self._admin_trace("stats", 0)\n')
    found = trace_coverage.check(src_for(dmn, dmn_rel, "d.py"))
    assert [f.key for f in found] == ["admin-op@newop"]
    covered = dmn + "# mrilint: allow(trace) newop — dispatched\n"
    assert trace_coverage.check(src_for(covered, dmn_rel, "d2.py")) == []
    # any other file is out of the checker's scope entirely
    assert trace_coverage.check(
        src_for(bare, f"{PACKAGE}/serve/cache.py", "c.py")) == []
