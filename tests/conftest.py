"""Test environment: repo importable + 8 virtual CPU devices.

Must run before the first ``import jax`` anywhere in the test session so
the CPU backend is selected with 8 fake devices — this is how the
multi-chip ``shard_map``/``all_to_all`` path is exercised without a TPU
pod (SURVEY.md §4 item 4).
"""

import os
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# MRI_TPU_TESTS_ON_TPU=1 runs the suite against the real chip instead
# (used to prove Pallas kernels/XLA programs compile on hardware —
# VERDICT r1 #3); default is 8 virtual CPU devices.
ON_TPU = os.environ.get("MRI_TPU_TESTS_ON_TPU", "").lower() in ("1", "true", "yes")
if not ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sitecustomize force-selects the TPU platform via jax.config,
# which overrides JAX_PLATFORMS — override it back before any backend
# initializes so tests really run on 8 virtual CPU devices.
import jax  # noqa: E402

if not ON_TPU:
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

FIXTURES = REPO_ROOT / "tests" / "fixtures"
REFERENCE = Path("/root/reference")


@pytest.fixture(scope="session")
def smoke_fixture():
    """Own 4-doc edge-case corpus with goldens generated from the compiled
    reference binary (tests/fixtures/smoke/)."""
    return FIXTURES / "smoke"


@pytest.fixture(scope="session")
def reference_dir():
    if not REFERENCE.is_dir():
        pytest.skip("/root/reference not mounted")
    return REFERENCE


def _socket_fds() -> set:
    """(fd, socket-inode) pairs currently open in this process — the
    leak unit for the daemon guard (inode comparison survives fd-number
    reuse)."""
    out = set()
    for entry in Path("/proc/self/fd").iterdir():
        try:
            target = os.readlink(entry)
        except OSError:
            continue  # raced with a close
        if target.startswith("socket:"):
            out.add((entry.name, target))
    return out


@pytest.fixture(autouse=True)
def _daemon_leak_guard(request):
    """Every ``daemon``-marked test must leave no stray sockets or
    background threads behind: a drained ServeDaemon joins every
    reader/writer/dispatcher/accept thread and closes every socket, so
    anything surviving the (grace-looped) check is a real leak."""
    if request.node.get_closest_marker("daemon") is None:
        yield
        return
    import threading
    import time

    before_threads = set(threading.enumerate())
    before_socks = _socket_fds()
    yield
    deadline = time.monotonic() + 5.0
    while True:
        leaked_threads = [t for t in threading.enumerate()
                          if t not in before_threads and t.is_alive()]
        leaked_socks = _socket_fds() - before_socks
        if not leaked_threads and not leaked_socks:
            return
        if time.monotonic() > deadline:
            break
        time.sleep(0.05)
    assert not leaked_threads, (
        f"daemon test leaked threads: {[t.name for t in leaked_threads]}")
    assert not leaked_socks, f"daemon test leaked sockets: {leaked_socks}"


def run_child(cmd, *, env=None, cwd=None, timeout=300):
    """Run a CLI child for crash/kill tests with a hang-proof guard.

    The child gets its own process group (``start_new_session``) so a
    timeout kills the WHOLE group with ``os.killpg`` — a wedged child
    (or anything it forked) can never outlive the test or hang the
    suite.  Returns the finished ``Popen`` (check ``.returncode``);
    a timeout is a test failure, not an exception up the stack.
    """
    import signal
    import subprocess

    proc = subprocess.Popen(cmd, cwd=cwd, env=env, start_new_session=True)
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        proc.wait()
        pytest.fail(
            f"child process hung past {timeout}s and was group-killed: "
            f"{' '.join(map(str, cmd[:6]))} ...")
    return proc


def read_letter_files(directory) -> bytes:
    """Concatenate a.txt..z.txt (the golden-diff unit, SURVEY.md §4)."""
    out = bytearray()
    for i in range(26):
        p = Path(directory) / f"{chr(ord('a') + i)}.txt"
        out += p.read_bytes()
    return bytes(out)
