"""Query-serving suite: ``serve.Engine`` golden parity against a naive
text scan, ``index.mri`` corruption rejection, cache semantics.

The parity oracle is deliberately dumb: re-read every document, apply
the reference token rules (clean_token), and build a dict of sorted
postings sets in pure Python.  Every Engine answer — df, postings,
top-k, AND/OR — must match it exactly, on the 4-doc edge-case smoke
corpus and on a sampled Zipf corpus built through the real cpu
pipeline with ``--artifact``.
"""

import json
import random
import re

import numpy as np
import pytest

from conftest import FIXTURES

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import main
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve import (
    ArtifactError, Engine, load_artifact,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.artifact import (
    HEADER_BYTES, artifact_path,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    clean_token,
)

pytestmark = pytest.mark.serve

_C_WHITESPACE = re.compile(rb"[ \t\n\v\f\r]+")


def naive_index(doc_blobs) -> dict[str, list[int]]:
    """Reference-rule inverted index by brute force: C-locale whitespace
    split, clean_token per token, 1-based doc ids, sorted unique."""
    post: dict[str, set[int]] = {}
    for doc_id, blob in enumerate(doc_blobs, start=1):
        for raw in _C_WHITESPACE.split(blob):
            w = clean_token(raw)
            if w:
                post.setdefault(w, set()).add(doc_id)
    return {t: sorted(d) for t, d in post.items()}


def build_corpus(tmp_path, docs: list[bytes]):
    """Write docs + manifest, run the cpu backend with --artifact."""
    ddir = tmp_path / "docs"
    ddir.mkdir()
    paths = []
    for i, blob in enumerate(docs):
        p = ddir / f"d{i:04d}.txt"
        p.write_bytes(blob)
        paths.append(str(p))
    listfile = tmp_path / "list.txt"
    write_manifest(listfile, paths)
    out = tmp_path / "out"
    assert main(["1", "1", str(listfile), "--backend", "cpu",
                 "--output-dir", str(out), "--artifact"]) == 0
    return out


@pytest.fixture(scope="module")
def smoke_built(tmp_path_factory):
    docs = [(FIXTURES / "smoke" / "docs" / f"doc{i}.txt").read_bytes()
            for i in range(1, 5)]
    out = build_corpus(tmp_path_factory.mktemp("serve_smoke"), docs)
    return out, naive_index(docs)


@pytest.fixture(scope="module")
def zipf_built(tmp_path_factory):
    docs = zipf_corpus(num_docs=60, vocab_size=900, tokens_per_doc=150, seed=11)
    out = build_corpus(tmp_path_factory.mktemp("serve_zipf"), docs)
    return out, naive_index(docs)


def _assert_engine_matches(engine: Engine, naive: dict[str, list[int]],
                           terms) -> None:
    batch = engine.encode_batch(terms)
    dfs = engine.df(batch)
    posts = engine.postings(batch)
    for t, df, post in zip(terms, dfs, posts):
        want = naive.get(t)
        if want is None:
            assert df == 0 and post is None, t
        else:
            assert df == len(want), t
            assert post.tolist() == want, t


# -- golden parity ------------------------------------------------------


def test_smoke_parity_exhaustive(smoke_built):
    """Every vocabulary term, both directions: Engine == naive scan."""
    out, naive = smoke_built
    with Engine(artifact_path(out)) as engine:
        assert engine.vocab_size == len(naive)
        vocab = sorted(naive)
        _assert_engine_matches(engine, naive, vocab)
        # and the artifact's own term table is exactly the naive vocab
        art_terms = [engine.artifact.term(i).decode() for i in range(engine.vocab_size)]
        assert art_terms == vocab


def test_smoke_top_k_matches_letter_files(smoke_built):
    """top_k == the first k lines of the golden letter files."""
    out, _ = smoke_built
    golden = FIXTURES / "smoke" / "golden"
    with Engine(artifact_path(out)) as engine:
        for li in range(26):
            lines = (golden / f"{chr(ord('a') + li)}.txt").read_bytes().splitlines()
            lines = [ln for ln in lines if ln]
            got = engine.top_k(li, k=len(lines) or 1)
            assert len(got) == len(lines)
            for (term, df), line in zip(got, lines):
                want_term, _, ids = line.partition(b":")
                assert term == want_term
                assert df == len(ids.strip(b"[]").split())


def test_zipf_parity_sampled(zipf_built):
    """Sampled + boundary terms of a pipeline-built Zipf corpus."""
    out, naive = zipf_built
    vocab = sorted(naive)
    rng = random.Random(3)
    sample = rng.sample(vocab, k=min(200, len(vocab)))
    # per-letter boundary terms: binary-search edge cases
    by_letter: dict[str, list[str]] = {}
    for t in vocab:
        by_letter.setdefault(t[0], []).append(t)
    for ts in by_letter.values():
        sample += [ts[0], ts[-1]]
    with Engine(artifact_path(out)) as engine:
        assert engine.vocab_size == len(vocab)
        _assert_engine_matches(engine, naive, sample)


def test_zipf_boolean_parity(zipf_built):
    """AND/OR against naive set algebra, absent terms included."""
    out, naive = zipf_built
    vocab = sorted(naive)
    rng = random.Random(5)
    with Engine(artifact_path(out)) as engine:
        for _ in range(60):
            k = rng.choice((2, 2, 3))
            terms = rng.sample(vocab, k=k)
            if rng.random() < 0.25:
                terms[rng.randrange(k)] = "notinthecorpusxyz"
            batch = engine.encode_batch(terms)
            sets = [set(naive.get(t, ())) for t in terms]
            want_and = sorted(set.intersection(*sets)) if all(sets) else []
            want_or = sorted(set.union(*sets))
            assert engine.query_and(batch).tolist() == want_and, terms
            assert engine.query_or(batch).tolist() == want_or, terms


def test_zipf_fuzz_lookups(zipf_built):
    """Random batches: present, absent, mixed-case, punctuated, empty."""
    out, naive = zipf_built
    vocab = sorted(naive)
    rng = random.Random(7)
    junk = ["", "zzzznope", "Aardvark!!", "x1y2z3q4", "a" * 40, "THE"]
    with Engine(artifact_path(out)) as engine:
        for _ in range(30):
            terms = [rng.choice(vocab) if rng.random() < 0.7 else rng.choice(junk)
                     for _ in range(rng.randrange(1, 33))]
            # the engine normalizes queries with the same token rules
            normalized = [clean_token(t) for t in terms]
            _assert_engine_matches(engine, naive, normalized)


# -- artifact integrity -------------------------------------------------


def _corrupt(path, offset: int) -> None:
    data = bytearray(path.read_bytes())
    data[offset] ^= 0xFF
    path.write_bytes(bytes(data))


def test_corrupt_payload_rejected(smoke_built, tmp_path):
    out, _ = smoke_built
    art = tmp_path / "index.mri"
    art.write_bytes(artifact_path(out).read_bytes())
    _corrupt(art, HEADER_BYTES + 100)
    with pytest.raises(ArtifactError, match="payload checksum"):
        load_artifact(art)


def test_corrupt_header_rejected(smoke_built, tmp_path):
    out, _ = smoke_built
    art = tmp_path / "index.mri"
    art.write_bytes(artifact_path(out).read_bytes())
    _corrupt(art, 12)
    with pytest.raises(ArtifactError):
        load_artifact(art)


def test_truncated_artifact_rejected(smoke_built, tmp_path):
    out, _ = smoke_built
    blob = artifact_path(out).read_bytes()
    art = tmp_path / "index.mri"
    for cut in (50, HEADER_BYTES, len(blob) - 7):
        art.write_bytes(blob[:cut])
        with pytest.raises(ArtifactError):
            load_artifact(art)


def test_bad_magic_rejected(smoke_built, tmp_path):
    out, _ = smoke_built
    data = bytearray(artifact_path(out).read_bytes())
    data[:8] = b"NOTMRI00"
    art = tmp_path / "index.mri"
    art.write_bytes(bytes(data))
    with pytest.raises(ArtifactError, match="magic"):
        load_artifact(art)


def test_query_cli_corrupt_artifact_exits_2(smoke_built, tmp_path, capsys):
    """CLI maps ArtifactError to the one-line exit-2 contract."""
    out, _ = smoke_built
    qdir = tmp_path / "q"
    qdir.mkdir()
    blob = artifact_path(out).read_bytes()
    (qdir / "index.mri").write_bytes(blob[:50])
    assert main(["query", str(qdir), "the"]) == 2
    err = capsys.readouterr().err
    assert "error:" in err and err.count("\n") == 1

    data = bytearray(blob)
    data[HEADER_BYTES + 64] ^= 0xFF
    (qdir / "index.mri").write_bytes(bytes(data))
    assert main(["query", str(qdir), "the"]) == 2
    assert "checksum" in capsys.readouterr().err


def test_query_cli_missing_artifact_exits_2(tmp_path, capsys):
    assert main(["query", str(tmp_path), "the"]) == 2
    assert "error:" in capsys.readouterr().err


def test_query_cli_letter_dir_without_artifact_names_remediation(tmp_path, capsys):
    """Pointing ``mri query`` at a letter-file index built WITHOUT
    ``--artifact`` is the common operator mistake: the one-line exit-2
    diagnostic must say how to fix it (rebuild with --artifact), not just
    'cannot open'."""
    docs = [b"alpha beta", b"beta gamma"]
    ddir = tmp_path / "docs"
    ddir.mkdir()
    paths = []
    for i, blob in enumerate(docs):
        p = ddir / f"d{i}.txt"
        p.write_bytes(blob)
        paths.append(str(p))
    listfile = tmp_path / "list.txt"
    write_manifest(listfile, paths)
    out = tmp_path / "out"
    # note: no --artifact — only a.txt..z.txt letter files are written
    assert main(["1", "1", str(listfile), "--backend", "cpu",
                 "--output-dir", str(out)]) == 0
    capsys.readouterr()
    assert main(["query", str(out), "alpha"]) == 2
    err = capsys.readouterr().err
    assert err.count("\n") == 1, f"diagnostic must be one line: {err!r}"
    assert err.startswith("error:")
    assert "--artifact" in err and "letter-file" in err


def test_artifact_covered_by_audit_verify(tmp_path, capsys):
    """--audit manifests index.mri; --verify re-checks it (exit 2 on rot)."""
    docs = [b"alpha beta", b"beta gamma delta", b"alpha epsilon"]
    ddir = tmp_path / "docs"
    ddir.mkdir()
    paths = []
    for i, blob in enumerate(docs):
        p = ddir / f"d{i}.txt"
        p.write_bytes(blob)
        paths.append(str(p))
    listfile = tmp_path / "list.txt"
    write_manifest(listfile, paths)
    out = tmp_path / "out"
    assert main(["1", "1", str(listfile), "--backend", "cpu",
                 "--output-dir", str(out), "--artifact", "--audit"]) == 0
    capsys.readouterr()
    assert main(["--verify", str(out)]) == 0
    _corrupt(artifact_path(out), HEADER_BYTES + 32)
    assert main(["--verify", str(out)]) == 2
    assert "index.mri" in capsys.readouterr().err


# -- query CLI ----------------------------------------------------------


def test_query_cli_terms_and_ops(smoke_built, capsys):
    out, naive = smoke_built
    assert main(["query", str(out), "the", "nosuchword"]) == 0
    lines = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()]
    assert lines[0] == {"term": "the", "found": True,
                        "df": len(naive["the"]), "postings": naive["the"]}
    assert lines[1] == {"term": "nosuchword", "found": False,
                        "df": 0, "postings": []}

    assert main(["query", str(out), "--op", "and", "the", "dog"]) == 0
    got = json.loads(capsys.readouterr().out)
    assert got["docs"] == sorted(set(naive["the"]) & set(naive["dog"]))

    assert main(["query", str(out), "--op", "or", "zebra", "apple"]) == 0
    got = json.loads(capsys.readouterr().out)
    assert got["docs"] == sorted(set(naive["zebra"]) | set(naive["apple"]))


def test_query_cli_top_k(smoke_built, capsys):
    out, naive = smoke_built
    assert main(["query", str(out), "--top-k", "2", "--letter", "t"]) == 0
    got = json.loads(capsys.readouterr().out)
    t_terms = sorted((t for t in naive if t.startswith("t")),
                     key=lambda t: (-len(naive[t]), t))[:2]
    assert [e["term"] for e in got["top"]] == t_terms
    assert [e["df"] for e in got["top"]] == [len(naive[t]) for t in t_terms]


# -- engine internals ---------------------------------------------------


def test_lru_cache_semantics(zipf_built):
    out, naive = zipf_built
    vocab = sorted(naive)
    with Engine(artifact_path(out), cache_terms=4) as engine:
        terms = vocab[:6]
        engine.postings(engine.encode_batch(terms))       # 6 misses, 2 evictions
        stats = engine.cache_stats()
        assert stats["misses"] == 6 and stats["entries"] == 4
        engine.postings(engine.encode_batch(terms[-4:]))  # all resident
        assert engine.cache_stats()["hits"] == 4
        engine.postings(engine.encode_batch(terms[:1]))   # evicted -> miss
        assert engine.cache_stats()["misses"] == 7
        engine.cache.clear()
        assert engine.cache_stats()["entries"] == 0
        # answers identical with the cache cold again
        _assert_engine_matches(engine, naive, terms)


def test_lru_cache_thread_hammer():
    """N threads hammering one small cache: no exception, no over-capacity
    growth, no cross-key value corruption, coherent counters.  This is the
    regression test for the daemon sharing one Engine (one cache) across
    every connection — the pre-lock OrderedDict raced ``move_to_end``
    against ``popitem`` and could blow up or corrupt order under exactly
    this workload."""
    import threading

    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.cache import (
        LRUCache,
    )

    cache = LRUCache(capacity=8)
    keys = [f"k{i}" for i in range(32)]
    errors: list[BaseException] = []
    gets_per_thread = 2000
    n_threads = 8
    start = threading.Barrier(n_threads)

    def hammer(seed: int) -> None:
        rng = random.Random(seed)
        try:
            start.wait()
            for _ in range(gets_per_thread):
                k = rng.choice(keys)
                v = cache.get(k)
                if v is None:
                    cache.put(k, ("payload", k))
                else:
                    assert v == ("payload", k), f"corrupt value for {k}: {v}"
                if rng.random() < 0.01:
                    cache.stats()
                    len(cache)
        except BaseException as e:  # surfaced below — threads swallow otherwise
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, f"cache raced: {errors[:3]}"
    stats = cache.stats()
    assert stats["entries"] <= 8 and len(cache) <= 8
    assert stats["hits"] + stats["misses"] == n_threads * gets_per_thread


def test_engine_batched_equals_single(zipf_built):
    """One big batch == the same lookups one by one."""
    out, naive = zipf_built
    vocab = sorted(naive)
    terms = vocab[:97] + ["missingterm"] + vocab[-97:]
    with Engine(artifact_path(out)) as engine:
        batch = engine.encode_batch(terms)
        dfs = engine.df(batch)
        posts = engine.postings(batch)
        for i, t in enumerate(terms):
            b1 = engine.encode_batch([t])
            assert engine.df(b1)[0] == dfs[i]
            p1 = engine.postings(b1)[0]
            if posts[i] is None:
                assert p1 is None
            else:
                assert np.array_equal(p1, posts[i])


def test_artifact_layout_header_fields(smoke_built):
    out, naive = smoke_built
    art = load_artifact(artifact_path(out))
    try:
        assert art.vocab == len(naive)
        assert art.num_postings == sum(len(v) for v in naive.values())
        assert art.max_doc_id == 4
        assert art.nbytes == artifact_path(out).stat().st_size
        # sections are struct-aligned views over one mapping
        # (the postings sections differ by format version)
        if art.version >= 2:
            sections = (art.term_offsets, art.df, art.blk_max,
                        art.blk_first, art.post_words, art.tf_words,
                        art.doc_lens)
            if art.has_block_scores:
                sections += (art.blk_max_tf, art.blk_min_dl)
        else:
            sections = (art.term_offsets, art.df, art.post_offsets,
                        art.postings)
        for arr in sections:
            assert arr.flags["ALIGNED"]
    finally:
        art.close()
