"""Device-engine unit tests, including the unpacked fallback path."""

import numpy as np
import pytest

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.models.oracle import (
    oracle_postings,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import engine
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import keys as K
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    tokenize_documents,
)

DOCS = [
    b"the quick brown fox the the",
    b"quick quick zebra apple",
    b"apple the zebra zebra box",
]
IDS = [1, 2, 3]


def _expected():
    return oracle_postings(DOCS, IDS)


def _check_outputs(out, corpus, max_doc_id):
    words = corpus.vocab_strings()
    expected = _expected()
    df = np.asarray(out["df"])
    offsets = np.asarray(out["offsets"])
    postings = np.asarray(out["postings"])
    assert int(out["num_unique"]) == sum(len(v) for v in expected.values())
    for t, w in enumerate(words):
        got = postings[int(offsets[t]) : int(offsets[t]) + int(df[t])].tolist()
        assert got == expected[w], w
    # emit order: (letter asc, df desc, word asc)
    order = np.asarray(out["order"])
    keys = [(int(corpus.letter_of_term[t]), -int(df[t]), t) for t in order]
    assert keys == sorted(keys)


def test_index_packed_matches_oracle():
    corpus = tokenize_documents(DOCS, IDS)
    max_doc_id = 3
    assert K.can_pack(corpus.vocab_size, max_doc_id)
    stride = max_doc_id + 2
    n = corpus.num_tokens
    padded = 64
    host_keys = np.full(padded, K.INT32_MAX, np.int32)
    host_keys[:n] = corpus.term_ids * stride + corpus.doc_ids
    out = engine.index_packed(
        host_keys, corpus.letter_of_term,
        vocab_size=corpus.vocab_size, max_doc_id=max_doc_id)
    _check_outputs(out, corpus, max_doc_id)


def test_index_pairs_fallback_matches_oracle():
    # Force the unpacked two-key path that large corpora would take.
    corpus = tokenize_documents(DOCS, IDS)
    max_doc_id = 3
    n = corpus.num_tokens
    padded = 64
    term = np.full(padded, K.INT32_MAX, np.int32)
    doc = np.full(padded, K.INT32_MAX, np.int32)
    term[:n] = corpus.term_ids
    doc[:n] = corpus.doc_ids
    out = engine.index_pairs(
        term, doc, corpus.letter_of_term,
        vocab_size=corpus.vocab_size, max_doc_id=max_doc_id)
    _check_outputs(out, corpus, max_doc_id)


def test_index_u16_matches_oracle():
    corpus = tokenize_documents(DOCS, IDS)
    max_doc_id = 3
    n = corpus.num_tokens
    padded = 64
    feed = np.full(2 * padded, 0xFFFF, np.uint16)
    feed[:n] = corpus.term_ids
    feed[padded : padded + n] = corpus.doc_ids
    out = engine.index_u16(feed, vocab_size=corpus.vocab_size, max_doc_id=max_doc_id)
    combined = np.asarray(out["combined"])
    out = {"df": combined[: corpus.vocab_size],
           "postings": combined[corpus.vocab_size :]}
    df = np.asarray(out["df"]).astype(np.int64)
    order, offsets = engine.host_order_offsets(corpus.letter_of_term, df)
    full = {
        "df": df,
        "order": order,
        "offsets": offsets,
        "postings": np.asarray(out["postings"]),
        "num_unique": int(df.sum()),
    }
    _check_outputs(full, corpus, max_doc_id)


def test_engine_paths_agree_random():
    rng = np.random.default_rng(7)
    for _ in range(5):
        v, d, n = int(rng.integers(2, 40)), int(rng.integers(1, 20)), int(rng.integers(1, 300))
        term = rng.integers(0, v, size=n).astype(np.int32)
        doc = rng.integers(1, d + 1, size=n).astype(np.int32)
        letters = rng.integers(0, 26, size=v).astype(np.int32)
        letters.sort()  # vocab ids are sorted by string => letters non-decreasing
        padded = ((n + 63) // 64) * 64
        stride = d + 2
        pk = np.full(padded, K.INT32_MAX, np.int32)
        pk[:n] = term * stride + doc
        tp = np.full(padded, K.INT32_MAX, np.int32)
        dp = np.full(padded, K.INT32_MAX, np.int32)
        tp[:n], dp[:n] = term, doc
        a = engine.index_packed(pk, letters, vocab_size=v, max_doc_id=d)
        b = engine.index_pairs(tp, dp, letters, vocab_size=v, max_doc_id=d)
        np.testing.assert_array_equal(a["df"], b["df"])
        np.testing.assert_array_equal(a["order"], b["order"])
        np.testing.assert_array_equal(a["offsets"], b["offsets"])
        assert int(a["num_unique"]) == int(b["num_unique"])
        nu = int(a["num_unique"])
        np.testing.assert_array_equal(a["postings"][:nu], b["postings"][:nu])
