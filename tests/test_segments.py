"""Incremental indexing: segment manifests, live append/delete, and
background compaction (`segments/`, serve/multi_engine.py).

The load-bearing invariant is BYTE-IDENTITY: a multi-segment directory
at any live state (after any append/delete/compact sequence) must
answer df / postings / boolean / BM25 top-k exactly like a from-scratch
single-artifact build of the same documents, with global doc ids
remapped densely by rank.  BM25 scores are compared with ``==`` — the
global-stats seam (summed doc-lens, count-nonzero ndocs, nonzero-mean
avgdl, live global df injected per segment) is engineered to make the
floats bitwise equal, not merely close.

The rest of the file pins the lifecycle contract: atomic generation
swap (torn manifests rejected whole), tombstone integrity, compaction
preserving global ids while dropping tombstones, the three segment
fault kinds leaving the old generation serving, engine routing guards,
and the CLI + daemon admin surfaces.
"""

from __future__ import annotations

import json
import socket

import random

import numpy as np
import pytest

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    faults,
    segments,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.audit import (
    verify_output_dir,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
    main,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.segments import (
    tombstones as tomb_mod,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.artifact import (
    ArtifactError, artifact_path, is_segment_managed,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.engine import (
    Engine, create_engine,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.multi_engine import (
    MultiSegmentEngine,
)

pytestmark = pytest.mark.segments


# -- corpus helpers -----------------------------------------------------

# pure-alphabetic vocabulary: the tokenizer strips digits, so suffixes
# must be letters or distinct words would collapse to one term
_WORDS = [f"{c}word{s}" for c in "abcgkpz" for s in "abcdef"]


def make_docs(tmp_path, specs, prefix="doc"):
    """One file per token list; returns (paths, token lists)."""
    ddir = tmp_path / f"{prefix}-docs"
    ddir.mkdir(exist_ok=True)
    paths = []
    for i, words in enumerate(specs):
        p = ddir / f"{prefix}{i:04d}.txt"
        p.write_text(" ".join(words) + "\n", encoding="ascii")
        paths.append(str(p))
    return paths, list(specs)


def doc_specs(rng, n, tokens=(10, 25)):
    return [[_WORDS[rng.randrange(len(_WORDS))]
             for _ in range(rng.randrange(*tokens))] for _ in range(n)]


def build_reference(tmp_path, token_lists, name="ref"):
    """From-scratch single-artifact build of exactly these documents."""
    paths, _ = make_docs(tmp_path, token_lists, prefix=name)
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        write_manifest,
    )
    listfile = tmp_path / f"{name}-list.txt"
    write_manifest(listfile, paths)
    out = tmp_path / f"{name}-out"
    assert main(["1", "1", str(listfile), "--backend", "cpu",
                 "--output-dir", str(out), "--artifact"]) == 0
    return out


def assert_state_identical(idx_dir, truth: dict, tmp_path, tag=""):
    """The acceptance-criteria check: multi-segment answers ==
    from-scratch single-artifact answers for the same live docs."""
    live = sorted(truth)
    remap = {gid: i + 1 for i, gid in enumerate(live)}
    ref = build_reference(tmp_path, [truth[g] for g in live],
                          name=f"ref{tag}{len(live)}")
    vocab = sorted({w for words in truth.values() for w in words})
    with create_engine(str(idx_dir), None) as em, \
            Engine(artifact_path(ref)) as er:
        bm, br = em.encode_batch(vocab), er.encode_batch(vocab)
        assert em.df(bm).tolist() == er.df(br).tolist()
        for t, pm, pr in zip(vocab, em.postings(bm), er.postings(br)):
            got = [] if pm is None else [remap[g] for g in pm.tolist()]
            want = [] if pr is None else pr.tolist()
            assert got == want, t
        for pair in ([vocab[0], vocab[-1]], vocab[:2], vocab[-2:]):
            for op in ("query_and", "query_or"):
                got = [remap[g] for g in getattr(em, op)(
                    em.encode_batch(pair)).tolist()]
                assert got == getattr(er, op)(
                    er.encode_batch(pair)).tolist(), (op, pair)
        for q in ([vocab[0]], vocab[:3], [vocab[-1], vocab[1]]):
            for k in (1, 3, 10, 100):
                got = [(remap[g], s) for g, s in
                       em.top_k_scored(em.encode_batch(q), k)]
                want = er.top_k_scored(er.encode_batch(q), k)
                assert got == want, (q, k)  # exact floats, exact order


# -- manifest integrity -------------------------------------------------


def test_manifest_round_trip(tmp_path):
    e = segments.SegmentEntry(name="seg_1_0", doc_base=0, docs=4,
                              adler32="0abc1234", bytes=512)
    man = segments.SegmentManifest(generation=1, next_seg=1, entries=(e,))
    segments.save_manifest(tmp_path, man, op="seed")
    got = segments.load_manifest(tmp_path)
    assert got == man
    assert got.doc_span == 4
    assert segments.is_segmented(tmp_path)
    assert segments.load_manifest(tmp_path / "nowhere") is None


def test_manifest_rejects_tampering(tmp_path):
    e = segments.SegmentEntry(name="seg_1_0", doc_base=0, docs=4,
                              adler32="0abc1234", bytes=512)
    segments.save_manifest(
        tmp_path, segments.SegmentManifest(1, 1, (e,)), op="seed")
    path = segments.manifest_path(tmp_path)
    doc = json.loads(path.read_text())
    doc["generation"] = 9  # body edit without checksum update
    path.write_text(json.dumps(doc))
    with pytest.raises(segments.SegmentError, match="checksum"):
        segments.load_manifest(tmp_path)
    path.write_text(path.read_text()[: path.stat().st_size // 2])
    with pytest.raises(segments.SegmentError, match="torn"):
        segments.load_manifest(tmp_path)


def test_manifest_rejects_overlapping_ranges(tmp_path):
    es = (segments.SegmentEntry("a", 0, 5, "00", 1),
          segments.SegmentEntry("b", 3, 5, "00", 1))
    segments.save_manifest(
        tmp_path, segments.SegmentManifest(1, 2, es), op="seed")
    with pytest.raises(segments.SegmentError, match="overlap"):
        segments.load_manifest(tmp_path)


def test_tombstone_round_trip_and_corruption(tmp_path):
    bits = np.zeros(37, dtype=bool)
    bits[[0, 5, 36]] = True
    p = tmp_path / "tombstones_3.bin"
    crc, size = tomb_mod.save(p, bits)
    assert p.stat().st_size == size
    assert tomb_mod.load(p, ndocs=37).tolist() == bits.tolist()
    raw = bytearray(p.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    p.write_bytes(bytes(raw))
    with pytest.raises(segments.SegmentError):
        tomb_mod.load(p, ndocs=37)


# -- append / delete / compact lifecycle --------------------------------


def test_append_seeds_from_batch_artifact(tmp_path):
    rng = random.Random(3)
    base = doc_specs(rng, 5)
    out = build_reference(tmp_path, base, name="seed")
    paths, extra = make_docs(tmp_path, doc_specs(rng, 3), prefix="extra")
    res = segments.append_files(out, paths)
    # the batch-built docs keep ids 1..5; appends continue at 6
    assert res["doc_ids"] == [6, 7, 8]
    assert res["generation"] == 2 and res["segments"] == 2
    assert is_segment_managed(out)
    truth = {i + 1: w for i, w in enumerate(base + extra)}
    assert_state_identical(out, truth, tmp_path, tag="seed")
    ok, problems = verify_output_dir(out)
    assert ok, problems


def test_append_delete_compact_byte_identity(tmp_path):
    """The acceptance sequence: appends, deletes (incl. re-delete),
    compaction — identical to from-scratch at every step."""
    rng = random.Random(7)
    idx = tmp_path / "idx"
    truth: dict[int, list[str]] = {}
    gid = 1
    for batch in range(3):
        specs = doc_specs(rng, 4)
        paths, _ = make_docs(tmp_path, specs, prefix=f"b{batch}")
        res = segments.append_files(idx, paths)
        assert res["doc_base"] == gid - 1
        for w in specs:
            truth[gid] = w
            gid += 1
    assert_state_identical(idx, truth, tmp_path, tag="a")
    res = segments.delete_docs(idx, [2, 7, 11])
    assert res["newly_tombstoned"] == 3
    for g in (2, 7, 11):
        del truth[g]
    assert_state_identical(idx, truth, tmp_path, tag="d")
    # idempotent re-delete
    assert segments.delete_docs(idx, [7])["newly_tombstoned"] == 0
    res = segments.compact(idx, force=True)
    assert res["compacted"] and res["tombstones_dropped"] == 3
    man = segments.load_manifest(idx)
    assert len(man.entries) < 3  # a run was folded
    assert_state_identical(idx, truth, tmp_path, tag="c")
    ok, problems = verify_output_dir(idx)
    assert ok, problems


def test_block_boundary_dfs(tmp_path, monkeypatch):
    """A term whose merged posting list spans several v2 blocks (tiny
    block size) must keep exact df/ranking parity across segments and
    through compaction — the skip-table seams are where off-by-ones
    would live."""
    monkeypatch.setenv("MRI_SERVE_BLOCK_SIZE", "8")
    rng = random.Random(11)
    idx = tmp_path / "idx"
    truth, gid = {}, 1
    for batch in range(3):
        # every doc carries the common term -> 30 postings over
        # block_size=8 spans 4 blocks; plus per-doc filler
        specs = [["awordqq"] * (1 + int(rng.randrange(3)))
                 + [_WORDS[rng.randrange(len(_WORDS))] for _ in range(6)]
                 for _ in range(10)]
        paths, _ = make_docs(tmp_path, specs, prefix=f"bb{batch}")
        segments.append_files(idx, paths)
        for w in specs:
            truth[gid] = w
            gid += 1
    with create_engine(str(idx), None) as em:
        assert em.df(em.encode_batch(["awordqq"])).tolist() == [30]
    assert_state_identical(idx, truth, tmp_path, tag="bb")
    segments.delete_docs(idx, [1, 8, 9, 16, 17, 24])  # block edges
    for g in (1, 8, 9, 16, 17, 24):
        del truth[g]
    assert_state_identical(idx, truth, tmp_path, tag="bbd")
    segments.compact(idx, force=True)
    assert_state_identical(idx, truth, tmp_path, tag="bbc")


def test_compact_preserves_global_ids(tmp_path):
    rng = random.Random(19)
    idx = tmp_path / "idx"
    for batch in range(3):
        paths, _ = make_docs(tmp_path, doc_specs(rng, 3),
                             prefix=f"g{batch}")
        segments.append_files(idx, paths)
    segments.delete_docs(idx, [4])
    before = segments.load_manifest(idx)
    res = segments.compact(idx, force=True)
    after = segments.load_manifest(idx)
    assert after.generation == before.generation + 1
    assert after.doc_span == before.doc_span  # ids never renumber
    assert sum(e.tomb_count for e in after.entries) == 0
    # next append continues past the preserved span
    paths, _ = make_docs(tmp_path, doc_specs(rng, 2), prefix="g9")
    assert segments.append_files(idx, paths)["doc_ids"] == [10, 11]
    # retired inputs stay on disk for live readers until pruned
    retired = set(res["inputs"])
    names = {p.name for p in segments.segments_root(idx).iterdir()}
    assert retired <= names
    pruned = segments.prune_retired(idx)
    assert retired <= set(pruned)
    ok, problems = verify_output_dir(idx)
    assert ok, problems


def test_compact_trigger_and_force(tmp_path, monkeypatch):
    monkeypatch.setenv("MRI_SEGMENT_COMPACT_TRIGGER", "4")
    rng = random.Random(23)
    idx = tmp_path / "idx"
    for batch in range(2):
        paths, _ = make_docs(tmp_path, doc_specs(rng, 2),
                             prefix=f"t{batch}")
        segments.append_files(idx, paths)
    res = segments.compact(idx)  # 2 < trigger: no-op
    assert not res["compacted"] and "trigger" in res["reason"]
    assert segments.compact(idx, force=True)["compacted"]


def test_delete_validation(tmp_path):
    rng = random.Random(29)
    idx = tmp_path / "idx"
    paths, _ = make_docs(tmp_path, doc_specs(rng, 3), prefix="v")
    segments.append_files(idx, paths)
    with pytest.raises(segments.SegmentError, match="outside every"):
        segments.delete_docs(idx, [99])
    with pytest.raises(segments.SegmentError, match="at least one"):
        segments.delete_docs(idx, [])


# -- fault kinds: the old generation keeps serving ----------------------


def _armed(kind):
    faults.install(kind)
    faults.begin_run()


def test_append_torn_manifest_keeps_old_generation(tmp_path):
    rng = random.Random(31)
    idx = tmp_path / "idx"
    paths, specs = make_docs(tmp_path, doc_specs(rng, 3), prefix="f0")
    segments.append_files(idx, paths)
    before = segments.load_manifest(idx)
    truth = {i + 1: w for i, w in enumerate(specs)}
    more, _ = make_docs(tmp_path, doc_specs(rng, 2), prefix="f1")
    _armed("append-torn-manifest")
    try:
        with pytest.raises(segments.SegmentError, match="publish"):
            segments.append_files(idx, more)
    finally:
        faults.install(None)
    after = segments.load_manifest(idx)
    assert after == before  # generation unchanged, byte-intact
    names = {p.name for p in segments.segments_root(idx).iterdir()}
    assert names == {e.name for e in before.entries}  # no orphans
    ok, problems = verify_output_dir(idx)
    assert ok, problems
    assert_state_identical(idx, truth, tmp_path, tag="torn")
    # budget spent: the retry lands
    assert segments.append_files(idx, more)["generation"] == 2


def test_tombstone_corrupt_rejected(tmp_path):
    rng = random.Random(37)
    idx = tmp_path / "idx"
    paths, _ = make_docs(tmp_path, doc_specs(rng, 3), prefix="tc")
    segments.append_files(idx, paths)
    before = segments.load_manifest(idx)
    _armed("tombstone-corrupt")
    try:
        with pytest.raises(segments.SegmentError):
            segments.delete_docs(idx, [1])
    finally:
        faults.install(None)
    after = segments.load_manifest(idx)
    assert after == before
    assert sum(e.tomb_count for e in after.entries) == 0
    ok, problems = verify_output_dir(idx)
    assert ok, problems
    assert segments.delete_docs(idx, [1])["newly_tombstoned"] == 1


def test_compact_crash_old_generation_intact(tmp_path):
    rng = random.Random(41)
    idx = tmp_path / "idx"
    for batch in range(2):
        paths, _ = make_docs(tmp_path, doc_specs(rng, 2),
                             prefix=f"cc{batch}")
        segments.append_files(idx, paths)
    before = segments.load_manifest(idx)
    _armed("compact-crash")
    try:
        with pytest.raises(faults.InjectedCompactCrash):
            segments.compact(idx, force=True)
    finally:
        faults.install(None)
    assert segments.load_manifest(idx) == before
    ok, problems = verify_output_dir(idx)
    assert ok, problems
    # crash left at worst an orphan build; the retry converges
    res = segments.compact(idx, force=True)
    assert res["compacted"]
    ok, problems = verify_output_dir(idx)
    assert ok, problems


# -- engine routing guards ----------------------------------------------


def test_engine_guards_and_routing(tmp_path):
    rng = random.Random(43)
    base = doc_specs(rng, 4)
    out = build_reference(tmp_path, base, name="guard")
    paths, _ = make_docs(tmp_path, doc_specs(rng, 2), prefix="guard2")
    segments.append_files(out, paths)
    # the root index.mri is now STALE: single-artifact engines must
    # refuse rather than silently serve the pre-append state
    with pytest.raises(ArtifactError, match="segment-managed"):
        Engine(artifact_path(out))
    eng = create_engine(str(out), None)
    try:
        assert isinstance(eng, MultiSegmentEngine)
        assert eng.engine_name == "multi"
        d = eng.describe()
        assert d["generation"] == 2 and len(d["segments"]) == 2
    finally:
        eng.close()
    with pytest.raises(ArtifactError, match="device"):
        create_engine(str(out), "device")


def test_multi_engine_stats_parity(tmp_path):
    """Global (ndocs, avgdl) from summed per-segment stats equals the
    from-scratch corpus stats — the seam that makes BM25 bitwise
    identical."""
    rng = random.Random(47)
    idx = tmp_path / "idx"
    truth, gid = {}, 1
    for batch in range(2):
        specs = doc_specs(rng, 3)
        paths, _ = make_docs(tmp_path, specs, prefix=f"s{batch}")
        segments.append_files(idx, paths)
        for w in specs:
            truth[gid] = w
            gid += 1
    segments.delete_docs(idx, [3])
    del truth[3]
    ref = build_reference(tmp_path, [truth[g] for g in sorted(truth)])
    with create_engine(str(idx), None) as em, \
            Engine(artifact_path(ref)) as er:
        ndocs, avgdl = em.bm25_stats()
        from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.artifact import (
            bm25_corpus, load_artifact,
        )
        with load_artifact(artifact_path(ref)) as art:
            _dl, ref_ndocs, ref_avgdl = bm25_corpus(art)
        assert ndocs == ref_ndocs
        assert avgdl == ref_avgdl  # exact, not approx


# -- CLI surface --------------------------------------------------------


def test_cli_append_delete_compact_verify(tmp_path, capsys):
    rng = random.Random(53)
    paths, _ = make_docs(tmp_path, doc_specs(rng, 3), prefix="cli")
    idx = tmp_path / "idx"
    assert main(["append", str(idx), "--add", *paths]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["generation"] == 1 and out["doc_ids"] == [1, 2, 3]
    assert main(["delete", str(idx), "--docs", "2"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["tombstoned_total"] == 1
    more, _ = make_docs(tmp_path, doc_specs(rng, 2), prefix="cli2")
    assert main(["append", str(idx), "--add", *more]) == 0
    capsys.readouterr()
    assert main(["compact", str(idx), "--force", "--prune"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert json.loads(lines[0])["compacted"]
    assert json.loads(lines[1])["pruned"]
    assert main(["--verify", str(idx)]) == 0
    capsys.readouterr()
    # error surfaces: bad ids exit 2, armed fault exits 2
    assert main(["delete", str(idx), "--docs", "99"]) == 2
    assert main(["append", str(idx), "--add", paths[0],
                 "--fault-spec", "append-torn-manifest"]) == 2
    capsys.readouterr()
    assert main(["--verify", str(idx)]) == 0


def test_cli_query_routes_multi_segment(tmp_path, capsys):
    rng = random.Random(59)
    paths, specs = make_docs(tmp_path, doc_specs(rng, 3), prefix="q")
    idx = tmp_path / "idx"
    assert main(["append", str(idx), "--add", *paths]) == 0
    capsys.readouterr()
    term = specs[0][0]
    assert main(["query", str(idx), term]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    want = sorted(i + 1 for i, w in enumerate(specs) if term in w)
    assert out["term"] == term and out["postings"] == want


# -- daemon admin surface -----------------------------------------------


@pytest.mark.daemon
def test_daemon_live_mutations(tmp_path):
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.daemon import (
        ServeDaemon,
    )
    rng = random.Random(61)
    paths, specs = make_docs(tmp_path, doc_specs(rng, 3), prefix="d")
    idx = tmp_path / "idx"
    segments.append_files(idx, paths)
    term = specs[0][0]
    base_df = sum(term in w for w in specs)
    d = ServeDaemon(str(idx), port=0)
    d.start()
    try:
        sock = socket.create_connection(d.address)
        f = sock.makefile("rwb")

        def rpc(**kw):
            f.write((json.dumps(kw) + "\n").encode())
            f.flush()
            return json.loads(f.readline())

        try:
            assert rpc(id=1, op="df", terms=[term])["df"] == [base_df]
            more, mspecs = make_docs(tmp_path, [[term, "zz"]] * 2,
                                     prefix="d2")
            r = rpc(id=2, op="append", files=more)
            assert r["ok"] and r["result"]["doc_ids"] == [4, 5]
            # visible to queries on the SAME connection immediately
            assert rpc(id=3, op="df", terms=[term])["df"] == [base_df + 2]
            r = rpc(id=4, op="delete", docs=[4])
            assert r["ok"] and r["result"]["tombstoned_total"] == 1
            assert rpc(id=5, op="df", terms=[term])["df"] == [base_df + 1]
            r = rpc(id=6, op="compact")
            assert r["ok"] and r["result"]["compacted"]
            assert rpc(id=7, op="df", terms=[term])["df"] == [base_df + 1]
            # failure path: old generation keeps serving, counted
            r = rpc(id=8, op="append", files=["/nope/missing.txt"])
            assert r["error"] == "mutation_rejected"
            assert rpc(id=9, op="df", terms=[term])["df"] == [base_df + 1]
            st = rpc(id=10, op="stats")["stats"]
            assert st["counters"]["mutations"] == 3
            assert st["counters"]["mutation_rejected"] == 1
            assert st["engine"]["generation"] >= 4
            # exposition: segment gauges present, no duplicate families
            text = rpc(id=11, op="metrics")["text"]
            assert "mri_generation" in text
            assert "mri_serve_mutations_total 3" in text
            fams = [ln.split()[2] for ln in text.splitlines()
                    if ln.startswith("# TYPE ")]
            assert len(fams) == len(set(fams))
            # malformed mutation requests are bad_request, not crashes
            assert rpc(id=12, op="append")["error"] == "bad_request"
            assert rpc(id=13, op="delete",
                       docs=["x"])["error"] == "bad_request"
        finally:
            f.close()
            sock.close()
    finally:
        d.drain()
    ok, problems = verify_output_dir(idx)
    assert ok, problems


@pytest.mark.daemon
def test_daemon_tombstone_flush_batching(tmp_path, monkeypatch):
    monkeypatch.setenv("MRI_SEGMENT_TOMBSTONE_FLUSH", "3")
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.daemon import (
        ServeDaemon,
    )
    rng = random.Random(67)
    paths, _ = make_docs(tmp_path, doc_specs(rng, 6), prefix="fl")
    idx = tmp_path / "idx"
    segments.append_files(idx, paths)
    d = ServeDaemon(str(idx), port=0)
    d.start()
    try:
        sock = socket.create_connection(d.address)
        f = sock.makefile("rwb")

        def rpc(**kw):
            f.write((json.dumps(kw) + "\n").encode())
            f.flush()
            return json.loads(f.readline())

        try:
            assert rpc(id=1, op="delete", docs=[1])["result"]["buffered"]
            assert rpc(id=2, op="delete", docs=[2])["result"]["buffered"]
            r = rpc(id=3, op="delete", docs=[3])  # third op: flush
            assert r["result"]["deleted"] == [1, 2, 3]
            gen_after_flush = r["result"]["generation"]
            assert rpc(id=4, op="delete", docs=[4])["result"]["buffered"]
        finally:
            f.close()
            sock.close()
    finally:
        d.drain()  # drain publishes the buffered remainder
    man = segments.load_manifest(idx)
    assert man.generation == gen_after_flush + 1
    assert sum(e.tomb_count for e in man.entries) == 4
