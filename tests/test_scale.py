"""Scale / stress tests: Zipfian corpora at ~full-corpus magnitude
(BASELINE.json config 4's regime, shrunk to CI budget — SURVEY.md §4
item 5).  All engines must agree with the dict oracle byte-for-byte on
a skewed vocabulary ~30x the letter count, and the streaming
accumulator must stay bounded while doing it.
"""

import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)


@pytest.fixture(scope="module")
def zipf_fixture(tmp_path_factory):
    root = tmp_path_factory.mktemp("zipf_scale")
    docs = zipf_corpus(num_docs=400, vocab_size=3000, tokens_per_doc=600,
                       alpha=1.1, seed=42)
    paths = write_corpus(root / "docs", docs)
    write_manifest(root / "list.txt", paths)
    m = read_manifest(root / "list.txt")
    oracle_index(m, root / "oracle")
    return m, read_letter_files(root / "oracle"), root


@pytest.mark.slow
def test_pipelined_matches_oracle_at_scale(zipf_fixture, tmp_path):
    m, golden, _ = zipf_fixture
    report = InvertedIndexModel(IndexConfig(
        backend="tpu", device_shards=1)).run(m, output_dir=tmp_path)
    assert "tokenize_feed" in report["phases_ms"]
    assert report["tokens"] == 400 * 600
    assert read_letter_files(tmp_path) == golden


@pytest.mark.slow
@pytest.mark.skipif("len(__import__('jax').devices()) < 2",
                    reason="needs a multi-device mesh")
def test_multichip_matches_oracle_at_scale(zipf_fixture, tmp_path):
    m, golden, _ = zipf_fixture
    report = InvertedIndexModel(IndexConfig(backend="tpu")).run(
        m, output_dir=tmp_path)  # 8 virtual devices -> dist engine
    assert report["device_shards"] == 8
    assert read_letter_files(tmp_path) == golden


@pytest.mark.slow
def test_streaming_matches_oracle_at_scale(zipf_fixture, tmp_path):
    m, golden, _ = zipf_fixture
    report = InvertedIndexModel(IndexConfig(
        backend="tpu", stream_chunk_docs=64, pad_multiple=1 << 14,
        device_shards=1)).run(m, output_dir=tmp_path)
    assert report["stream_windows"] >= 6
    # bounded: unique pairs fit the accumulator's initial 2^18 capacity,
    # so the 240k-token stream must never have forced a growth step
    assert report["unique_pairs"] < (1 << 18)
    assert report["accumulator_capacity"] == 1 << 18
    assert read_letter_files(tmp_path) == golden


@pytest.mark.slow
def test_all_engines_agree_at_8k_docs(tmp_path):
    """Cross-engine md5 agreement at 8k docs / ~36k vocab (BASELINE.json
    config 4 shrunk to CI budget): pipelined-dist (8 virtual chips),
    one-shot dist, streaming accumulator, and the native cpu backend."""
    docs = zipf_corpus(num_docs=8000, vocab_size=40000, tokens_per_doc=100,
                       alpha=1.05, seed=1)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    outs = {}
    for name, kw in [
        ("pipelined_dist", dict(backend="tpu")),
        ("oneshot_dist", dict(backend="tpu", pipeline_chunk_docs=0)),
        ("streaming", dict(backend="tpu", stream_chunk_docs=1000)),
        ("cpu", dict(backend="cpu")),
    ]:
        InvertedIndexModel(IndexConfig(**kw)).run(m, output_dir=tmp_path / name)
        outs[name] = read_letter_files(tmp_path / name)
    assert len({v for v in outs.values()}) == 1, {
        k: len(v) for k, v in outs.items()}


@pytest.mark.slow
def test_synthetic_manifest_all_engines_agree(tmp_path):
    """SyntheticManifest (lazy generation, no files) must produce the
    same index through streaming, pipelined, and cpu backends."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        synthetic_manifest,
    )

    m = synthetic_manifest(num_docs=2000, vocab_size=5000, tokens_per_doc=30,
                           seed=3, gen_chunk=512)
    outs = {}
    for name, kw in [
        ("streaming", dict(backend="tpu", stream_chunk_docs=512)),
        ("pipelined", dict(backend="tpu", device_shards=1)),
        ("cpu", dict(backend="cpu")),
    ]:
        InvertedIndexModel(IndexConfig(**kw)).run(m, output_dir=tmp_path / name)
        outs[name] = read_letter_files(tmp_path / name)
    assert len(set(outs.values())) == 1, {k: len(v) for k, v in outs.items()}


def test_synthetic_manifest_random_access_deterministic():
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        synthetic_manifest,
    )

    m = synthetic_manifest(num_docs=100, vocab_size=200, tokens_per_doc=10,
                           seed=9, gen_chunk=16)
    # out-of-order reads cross chunk boundaries and must be stable
    a = [m.read_doc(i) for i in (99, 0, 17, 16, 15, 99, 50)]
    b = [m.read_doc(i) for i in (99, 0, 17, 16, 15, 99, 50)]
    assert a == b
    assert len(m) == 100 and m.doc_id(0) == 1
    assert m.total_bytes > 0 and len(m.sizes) == 100
