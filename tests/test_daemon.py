"""Resident serve-daemon suite (``mri-tpu serve`` / serve/daemon.py).

Three layers:

* protocol + parity — every op answered over the JSON-lines protocol
  matches the naive text-scan oracle (the same one test_serve.py holds
  the engines to);
* robustness envelope — admission control sheds with counted
  ``overloaded`` errors, expired deadlines are dropped before dispatch,
  drain flushes stragglers as counted ``draining`` errors, hot reload
  swaps atomically and a rejected reload keeps the old artifact, and
  every injected serve fault (handler-crash / client-disconnect /
  slow-client / reload-corrupt) is absorbed without killing the daemon
  or tearing a response;
* CLI signal semantics — SIGTERM drains to exit 0, a second signal
  forces exit 1, SIGHUP hot-reloads, and a missing artifact is a
  one-line exit 2.

Every test here carries the ``daemon`` marker, so the conftest leak
guard asserts no stray sockets or threads survive each one.
"""

import contextlib
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from conftest import REPO_ROOT

from test_serve import build_corpus, naive_index

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    faults,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.artifact import (
    artifact_path,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.daemon import (
    ServeDaemon,
)

pytestmark = [pytest.mark.daemon, pytest.mark.serve]

DOCS = [b"the cat sat on the mat", b"the dog ran far", b"cat and dog nap",
        b"a quiet zebra naps", b"dog dog dog barks the most"]


@pytest.fixture(autouse=True)
def _disarm():
    """Each test arms its own fault spec; none may leak to the next."""
    faults.install(None)
    yield
    faults.install(None)


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    out = build_corpus(tmp_path_factory.mktemp("daemon_corpus"), DOCS)
    return out, naive_index(DOCS)


@contextlib.contextmanager
def serving(out, **kw):
    kw.setdefault("coalesce_us", 100)
    daemon = ServeDaemon(str(out), **kw)
    daemon.start()
    try:
        yield daemon
    finally:
        daemon.drain()


class Client:
    """One protocol connection: pipelined line-at-a-time JSON."""

    def __init__(self, daemon_or_addr, timeout=15.0):
        addr = daemon_or_addr.address \
            if isinstance(daemon_or_addr, ServeDaemon) else daemon_or_addr
        self.sock = socket.create_connection(addr, timeout=timeout)
        self.f = self.sock.makefile("rb")

    def send(self, **obj):
        self.sock.sendall((json.dumps(obj) + "\n").encode())

    def send_raw(self, data: bytes):
        self.sock.sendall(data)

    def recv(self):
        line = self.f.readline()
        assert line, "server closed the connection unexpectedly"
        return json.loads(line)

    def rpc(self, **obj):
        self.send(**obj)
        return self.recv()

    def close(self):
        with contextlib.suppress(OSError):
            self.f.close()
        with contextlib.suppress(OSError):
            self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- protocol parity ----------------------------------------------------


def test_daemon_answers_match_oracle(built):
    out, naive = built
    vocab = sorted(naive)
    with serving(out) as daemon, Client(daemon) as c:
        r = c.rpc(id=1, op="df", terms=vocab)
        assert r["ok"] and r["id"] == 1
        assert r["df"] == [len(naive[t]) for t in vocab]

        r = c.rpc(id=2, op="df", terms=["nosuchword", "cat"])
        assert r["df"] == [0, len(naive["cat"])]

        r = c.rpc(id=3, op="postings", terms=vocab[:5] + ["zzzz"])
        assert r["postings"] == [naive[t] for t in vocab[:5]] + [None]

        r = c.rpc(id=4, op="and", terms=["the", "cat"])
        assert r["docs"] == sorted(set(naive["the"]) & set(naive["cat"]))

        r = c.rpc(id=5, op="or", terms=["zebra", "cat"])
        assert r["docs"] == sorted(set(naive["zebra"]) | set(naive["cat"]))

        d_terms = sorted((t for t in naive if t.startswith("d")),
                         key=lambda t: (-len(naive[t]), t))[:2]
        r = c.rpc(id=6, op="top_k", letter="d", k=2)
        assert r["top"] == [[t, len(naive[t])] for t in d_terms]


def test_daemon_bad_requests_are_counted_one_liners(built):
    out, _ = built
    with serving(out) as daemon, Client(daemon) as c:
        r = c.rpc(id=1, op="frobnicate")
        assert r["error"] == "bad_request" and r["id"] == 1

        r = c.rpc(id=2, op="df", terms="not-a-list")
        assert r["error"] == "bad_request"

        r = c.rpc(id=3, op="top_k", letter="!", k=2)
        assert r["error"] == "bad_request"

        r = c.rpc(id=4, op="df", terms=["ok"], deadline_ms=-5)
        assert r["error"] == "bad_request"

        c.send_raw(b"this is not json\n")
        assert c.recv()["error"] == "bad_request"

        # the connection survived every malformed request
        assert c.rpc(id=5, op="df", terms=["cat"])["ok"]
    assert daemon.final_stats["counters"]["bad_request"] == 5


def test_daemon_stats_and_healthz(built):
    out, _ = built
    with serving(out) as daemon, Client(daemon) as c:
        assert c.rpc(id=1, op="df", terms=["cat", "dog"])["ok"]
        h = c.rpc(id=2, op="healthz")
        assert h["ok"] and h["status"] == "ok"
        s = c.rpc(id=3, op="stats")["stats"]
        assert s["counters"]["requests"] == 1
        assert s["counters"]["shed"] == 0
        assert s["engine"]["engine"] == "auto"
        assert s["engine"]["cache"]["hit_rate"] >= 0.0
        assert "df" in s["engine"]["ops"]
        assert s["config"]["queue_depth"] == daemon.queue_depth


def test_daemon_coalesces_pipelined_requests(built):
    """A pipelined burst lands in far fewer dispatch batches than
    requests — the micro-batching QPS lever, observable in counters."""
    out, naive = built
    with serving(out, coalesce_us=100_000, max_batch=64) as daemon:
        with Client(daemon) as c:
            n = 24
            blob = b"".join(
                (json.dumps({"id": i, "op": "df", "terms": ["cat"]})
                 + "\n").encode() for i in range(n))
            c.send_raw(blob)
            got = [c.recv() for _ in range(n)]
        assert all(r["ok"] and r["df"] == [len(naive["cat"])] for r in got)
        assert sorted(r["id"] for r in got) == list(range(n))
        counters = daemon.stats()["counters"]
        assert counters["batched_requests"] == n
        assert counters["batches"] <= 4  # one 100ms window + stragglers


# -- robustness envelope ------------------------------------------------


def test_daemon_sheds_overload_with_counted_errors(built):
    """Queue full => counted, well-formed 'overloaded' responses; every
    request is answered exactly once; nothing is silently dropped."""
    out, naive = built
    n = 40
    with serving(out, queue_depth=4, max_batch=1, coalesce_us=0) as daemon:
        with Client(daemon) as c:
            with daemon._engine_lock:  # wedge the dispatcher mid-batch
                blob = b"".join(
                    (json.dumps({"id": i, "op": "df", "terms": ["dog"]})
                     + "\n").encode() for i in range(n))
                c.send_raw(blob)
                # wait until admission has classified the whole burst
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    if daemon.stats()["counters"]["requests"] >= n:
                        break
                    time.sleep(0.01)
                time.sleep(0.05)
            # lock released: the queued remainder executes; every one
            # of the n requests gets exactly one response
            got = [c.recv() for _ in range(n)]
        overloaded = [r for r in got if r.get("error") == "overloaded"]
        ok = [r for r in got if r.get("ok")]
        assert len(overloaded) + len(ok) == n
        assert len(overloaded) >= n - 8  # ~ queue_depth + in-dispatch
        assert all(r["df"] == [len(naive["dog"])] for r in ok)
        assert sorted(r["id"] for r in got) == list(range(n))
        counters = daemon.stats()["counters"]
        assert counters["shed"] == len(overloaded)


def test_daemon_drops_expired_deadlines_before_dispatch(built):
    out, _ = built
    with serving(out, max_batch=8, coalesce_us=0) as daemon:
        with Client(daemon) as c:
            with daemon._engine_lock:  # stall execution past the deadline
                c.send(id=1, op="df", terms=["cat"], deadline_ms=20)
                time.sleep(0.15)
            r = c.recv()
            assert r["error"] == "deadline_expired" and r["id"] == 1
            # an un-deadlined request right behind it is fine
            assert c.rpc(id=2, op="df", terms=["cat"])["ok"]
        assert daemon.stats()["counters"]["deadline_expired"] == 1


def test_daemon_drain_flushes_stragglers_as_counted_errors(built):
    """Queued-but-undispatched work at drain time is answered with a
    well-formed 'draining' error — never silently dropped."""
    out, _ = built
    daemon = ServeDaemon(str(out), coalesce_us=0, drain_s=0.2)
    daemon.start()
    try:
        with Client(daemon) as c:
            daemon._dispatch_stop.set()  # park the dispatcher
            daemon._dispatcher.join(timeout=5.0)
            n = 6
            for i in range(n):
                c.send(id=i, op="df", terms=["cat"])
            deadline = time.monotonic() + 5.0
            while daemon.stats()["counters"]["requests"] < n \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert daemon.drain() == 0
            got = [c.recv() for _ in range(n)]
            assert all(r["error"] == "draining" for r in got)
            assert sorted(r["id"] for r in got) == list(range(n))
        assert daemon.final_stats["counters"]["draining_rejected"] == n
    finally:
        daemon.drain()


def test_daemon_rejects_new_work_while_draining(built):
    out, _ = built
    daemon = ServeDaemon(str(out), coalesce_us=0)
    daemon.start()
    try:
        with Client(daemon) as c:
            daemon._draining = True  # drain flag flips before teardown
            r = c.rpc(id=1, op="df", terms=["cat"])
            assert r["error"] == "draining"
            h = c.rpc(id=2, op="healthz")  # admin still answers
            assert h["status"] == "draining"
    finally:
        daemon.drain()
    assert daemon.final_stats["counters"]["draining_rejected"] == 1


def test_daemon_hot_reload_swaps_and_rejects(built, tmp_path):
    """A good reload swaps answers atomically; a torn replacement is
    rejected, counted, and the old artifact keeps serving."""
    out, naive = built
    new_docs = DOCS + [b"zebra zebra cat"]
    new_out = build_corpus(tmp_path, new_docs)
    new_naive = naive_index(new_docs)
    art = artifact_path(out)
    original = art.read_bytes()

    def push(data: bytes):
        # The update discipline the daemon documents: stage the new
        # bytes next to the artifact, then atomically rename over it.
        # An in-place overwrite would tear the pages under the LIVE
        # engine's mmap — rename gives the old engine its own inode.
        staged = art.with_suffix(".push")
        staged.write_bytes(data)
        os.replace(staged, art)

    try:
        with serving(out) as daemon, Client(daemon) as c:
            assert c.rpc(id=1, op="df", terms=["zebra"])["df"] == \
                [len(naive["zebra"])]
            # push the new artifact + reload via the protocol
            push(artifact_path(new_out).read_bytes())
            r = c.rpc(id=2, op="reload")
            assert r["ok"] and r["reloaded"]
            assert c.rpc(id=3, op="df", terms=["zebra"])["df"] == \
                [len(new_naive["zebra"])]
            # torn push: reload must reject and KEEP the new_docs view
            push(original[:200])
            r = c.rpc(id=4, op="reload")
            assert r["error"] == "reload_rejected"
            assert c.rpc(id=5, op="df", terms=["zebra"])["df"] == \
                [len(new_naive["zebra"])]
            counters = c.rpc(id=6, op="stats")["stats"]["counters"]
            assert counters["reload_ok"] == 1
            assert counters["reload_rejected"] == 1
    finally:
        art.write_bytes(original)


def test_daemon_injected_reload_corrupt_keeps_serving(built):
    out, naive = built
    faults.install("reload-corrupt")
    with serving(out) as daemon, Client(daemon) as c:
        r = c.rpc(id=1, op="reload")
        assert r["error"] == "reload_rejected"
        assert "injected" in r["detail"]
        assert c.rpc(id=2, op="df", terms=["cat"])["df"] == \
            [len(naive["cat"])]
        # the once-per-rule budget is spent: the next reload succeeds
        assert c.rpc(id=3, op="reload")["ok"]
        counters = daemon.stats()["counters"]
        assert counters["reload_rejected"] == 1
        assert counters["reload_ok"] == 1


def test_daemon_handler_crash_is_counted_and_isolated(built):
    """An injected handler crash answers THAT request with a counted
    'internal' error; neighbors in the same batch still succeed."""
    out, naive = built
    faults.install("handler-crash:req=2")
    with serving(out, coalesce_us=0, max_batch=1) as daemon:
        with Client(daemon) as c:
            assert c.rpc(id=1, op="df", terms=["cat"])["ok"]
            r = c.rpc(id=2, op="df", terms=["cat"])
            assert r["error"] == "internal" and "injected" in r["detail"]
            assert c.rpc(id=3, op="df", terms=["cat"])["df"] == \
                [len(naive["cat"])]
        assert daemon.stats()["counters"]["internal_errors"] == 1


def test_daemon_client_disconnect_mid_response(built):
    """Peer vanishing as its response is written only costs that
    connection — counted, and the daemon keeps serving others."""
    out, _ = built
    faults.install("client-disconnect:req=1")
    with serving(out, coalesce_us=0) as daemon:
        with Client(daemon) as victim:
            victim.send(id=1, op="df", terms=["cat"])
            # server drops the conn instead of writing the response
            try:
                line = victim.f.readline()
            except OSError:
                line = b""
            assert line == b""
        with Client(daemon) as c:
            assert c.rpc(id=2, op="df", terms=["cat"])["ok"]
        counters = daemon.stats()["counters"]
        assert counters["client_disconnects"] == 1


def test_daemon_slow_client_response_still_correct(built):
    out, naive = built
    faults.install("slow-client:req=1:ms=150")
    with serving(out, coalesce_us=0) as daemon, Client(daemon) as c:
        t0 = time.monotonic()
        r = c.rpc(id=1, op="df", terms=["dog"])
        elapsed = time.monotonic() - t0
        assert r["ok"] and r["df"] == [len(naive["dog"])]
        assert elapsed >= 0.12  # the injected stall really happened


def test_daemon_concurrent_connections_parity(built):
    """N threads × M pipelined requests each over separate connections:
    every response is well-formed, correct, and routed to its id."""
    out, naive = built
    vocab = sorted(naive)
    errors: list = []

    def worker(daemon, wid):
        try:
            with Client(daemon) as c:
                for i in range(20):
                    t = vocab[(wid * 20 + i) % len(vocab)]
                    r = c.rpc(id=f"{wid}-{i}", op="df", terms=[t])
                    assert r["id"] == f"{wid}-{i}", r
                    assert r["df"] == [len(naive[t])], (t, r)
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    with serving(out, coalesce_us=500) as daemon:
        threads = [threading.Thread(target=worker, args=(daemon, w))
                   for w in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
    assert not errors, errors
    assert daemon.final_stats["counters"]["responses"] >= 120


# -- CLI signal semantics (subprocess) ----------------------------------


def _spawn_serve(out, *extra, env_extra=None):
    env = dict(os.environ, PYTHONPATH=str(REPO_ROOT), JAX_PLATFORMS="cpu")
    if env_extra:
        env.update(env_extra)
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "parallel_computation_of_an_inverted_index_using_map_reduce_tpu",
         "serve", str(out), "--listen", "127.0.0.1:0", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        cwd=str(REPO_ROOT), text=True)
    line = proc.stdout.readline()
    if not line:
        proc.wait(timeout=10)
        raise AssertionError(
            f"daemon died on startup: {proc.stderr.read()}")
    ready = json.loads(line)
    assert ready["event"] == "listening"
    return proc, (ready["host"], ready["port"])


def _reap(proc, timeout=30):
    try:
        rc = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        pytest.fail("serve daemon did not exit within the drain window")
    proc.stdout.close()
    proc.stderr.close()
    return rc


def test_cli_sigterm_graceful_drain_exit_0(built):
    out, naive = built
    proc, addr = _spawn_serve(out)
    try:
        with Client(addr) as c:
            assert c.rpc(id=1, op="df", terms=["cat"])["df"] == \
                [len(naive["cat"])]
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        drained = json.loads(proc.stdout.readline())
        assert rc == 0
        assert drained["event"] == "drained"
        assert drained["counters"]["requests"] == 1
        assert drained["counters"]["responses"] >= 1
    finally:
        _reap(proc)


def test_cli_second_signal_forces_exit_1(built):
    """With a writer wedged by a slow client, the drain stalls; the
    second SIGTERM is the documented forced exit 1."""
    out, _ = built
    proc, addr = _spawn_serve(
        out, "--fault-spec", "slow-client:req=1:ms=20000",
        env_extra={"MRI_SERVE_DRAIN_S": "30"})
    try:
        with Client(addr) as c:
            c.send(id=1, op="df", terms=["cat"])
            time.sleep(0.5)  # the writer is now sleeping in the stall
            proc.send_signal(signal.SIGTERM)
            time.sleep(0.5)  # drain is blocked on the wedged writer
            assert proc.poll() is None
            proc.send_signal(signal.SIGTERM)
            assert _reap(proc, timeout=10) == 1
    finally:
        if proc.poll() is None:
            proc.kill()
            _reap(proc)


def test_cli_sighup_reload_and_corrupt_reload(built, tmp_path):
    """SIGHUP hot-reloads; a SIGHUP pointing at a torn artifact is
    rejected while the daemon keeps answering from the old one."""
    out, naive = built
    art = artifact_path(out)
    original = art.read_bytes()
    proc, addr = _spawn_serve(out)
    try:
        with Client(addr) as c:
            assert c.rpc(id=1, op="df", terms=["cat"])["ok"]
            proc.send_signal(signal.SIGHUP)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                s = c.rpc(id=2, op="stats")["stats"]["counters"]
                if s["reload_ok"] == 1:
                    break
                time.sleep(0.05)
            assert s["reload_ok"] == 1
            # torn push + SIGHUP: rejected, old artifact still serving
            # (staged + rename, like a real push — an in-place write
            # would tear the pages under the live engine's mmap)
            staged = art.with_suffix(".push")
            staged.write_bytes(original[:100])
            os.replace(staged, art)
            proc.send_signal(signal.SIGHUP)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                s = c.rpc(id=3, op="stats")["stats"]["counters"]
                if s["reload_rejected"] == 1:
                    break
                time.sleep(0.05)
            assert s["reload_rejected"] == 1
            assert c.rpc(id=4, op="df", terms=["cat"])["df"] == \
                [len(naive["cat"])]
        proc.send_signal(signal.SIGTERM)
        assert _reap(proc) == 0
    finally:
        art.write_bytes(original)
        if proc.poll() is None:
            proc.kill()
            _reap(proc)


def test_cli_sighup_reload_v1_to_v2_across_formats(tmp_path):
    """A live daemon serving a FORMAT V1 artifact hot-swaps to a v2
    build of the same corpus on SIGHUP — answers stay correct across
    the swap, the reported engine format flips, and a torn v2 push is
    rejected without dropping the v2 view."""
    from test_format_v2 import build_corpus_fmt

    (tmp_path / "v1").mkdir()
    (tmp_path / "v2").mkdir()
    out_v1 = build_corpus_fmt(tmp_path / "v1", DOCS, 1)
    out_v2 = build_corpus_fmt(tmp_path / "v2", DOCS, 2)
    naive = naive_index(DOCS)
    art = artifact_path(out_v1)
    v2_bytes = artifact_path(out_v2).read_bytes()

    def push(data: bytes):
        staged = art.with_suffix(".push")
        staged.write_bytes(data)
        os.replace(staged, art)

    proc, addr = _spawn_serve(out_v1)
    try:
        with Client(addr) as c:
            s = c.rpc(id=1, op="stats")["stats"]
            assert s["engine"]["format"] == 1
            assert c.rpc(id=2, op="df", terms=["cat"])["df"] == \
                [len(naive["cat"])]
            push(v2_bytes)
            proc.send_signal(signal.SIGHUP)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                # requests keep flowing while the reload lands
                assert c.rpc(id=3, op="df", terms=["cat"])["df"] == \
                    [len(naive["cat"])]
                s = c.rpc(id=4, op="stats")["stats"]
                if s["counters"]["reload_ok"] == 1:
                    break
                time.sleep(0.05)
            assert s["counters"]["reload_ok"] == 1
            assert s["engine"]["format"] == 2
            # torn v2 push: rejected, the good v2 view keeps serving
            push(v2_bytes[:200])
            proc.send_signal(signal.SIGHUP)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                s = c.rpc(id=5, op="stats")["stats"]
                if s["counters"]["reload_rejected"] == 1:
                    break
                time.sleep(0.05)
            assert s["counters"]["reload_rejected"] == 1
            assert s["engine"]["format"] == 2
            assert c.rpc(id=6, op="df", terms=["dog"])["df"] == \
                [len(naive["dog"])]
        proc.send_signal(signal.SIGTERM)
        assert _reap(proc) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            _reap(proc)


def test_cli_sighup_reload_v2_to_v21_under_scored_traffic(tmp_path):
    """A live daemon serving plain v2 hot-swaps to a v2.1 build of the
    same corpus on SIGHUP while BM25 queries keep flowing: ranked
    answers are unchanged across the swap (same tf data, same float64
    scoring), the planner flips from forced-exhaustive to pruning on
    the new block-score columns, and a torn v2.1 push is rejected
    without dropping the good v2.1 view."""
    from test_format_v2 import build_corpus_fmt

    (tmp_path / "v2").mkdir()
    (tmp_path / "v21").mkdir()
    out_v2 = build_corpus_fmt(tmp_path / "v2", DOCS, 2)
    out_v21 = build_corpus_fmt(tmp_path / "v21", DOCS, 3)
    art = artifact_path(out_v2)
    v21_bytes = artifact_path(out_v21).read_bytes()

    def push(data: bytes):
        staged = art.with_suffix(".push")
        staged.write_bytes(data)
        os.replace(staged, art)

    def scored(c, rid):
        r = c.rpc(id=rid, op="top_k", score="bm25", k=2,
                  terms=["cat", "dog"])
        assert r["ok"]
        return r["docs"]

    proc, addr = _spawn_serve(out_v2)
    try:
        with Client(addr) as c:
            s = c.rpc(id=1, op="stats")["stats"]
            assert s["engine"]["format"] == 2
            ref = scored(c, 2)
            assert ref
            # v2 has no block-score columns: ranked queries fall back
            s = c.rpc(id=3, op="stats")["stats"]
            pl = s["engine"]["planner"]["ranked"]
            assert pl["exhaustive"] >= 1
            assert pl["bmw"] == 0 and pl["maxscore"] == 0
            push(v21_bytes)
            proc.send_signal(signal.SIGHUP)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                # scored traffic keeps flowing while the reload lands,
                # and every answer matches the pre-swap reference
                assert scored(c, 4) == ref
                s = c.rpc(id=5, op="stats")["stats"]
                if s["counters"]["reload_ok"] == 1:
                    break
                time.sleep(0.05)
            assert s["counters"]["reload_ok"] == 1
            assert s["engine"]["format"] == 3
            # the fresh engine's planner prunes on the v2.1 columns
            assert scored(c, 6) == ref
            s = c.rpc(id=7, op="stats")["stats"]
            pl = s["engine"]["planner"]["ranked"]
            assert pl["bmw"] + pl["maxscore"] >= 1
            # torn v2.1 push: rejected, the good view keeps serving
            push(v21_bytes[:200])
            proc.send_signal(signal.SIGHUP)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                s = c.rpc(id=8, op="stats")["stats"]
                if s["counters"]["reload_rejected"] == 1:
                    break
                time.sleep(0.05)
            assert s["counters"]["reload_rejected"] == 1
            assert s["engine"]["format"] == 3
            assert scored(c, 9) == ref
        proc.send_signal(signal.SIGTERM)
        assert _reap(proc) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            _reap(proc)


def test_daemon_bm25_top_k_over_protocol(built):
    """score=bm25 over the wire: ranked [doc, score] pairs that agree
    with the engine's own top_k_scored on the same artifact."""
    out, naive = built
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve import (  # noqa: E501
        Engine,
    )
    with Engine(artifact_path(out)) as eng:
        want = eng.top_k_scored(eng.encode_batch(["dog", "cat"]), 5)
    with serving(out) as daemon, Client(daemon) as c:
        r = c.rpc(id=1, op="top_k", score="bm25", k=5,
                  terms=["dog", "cat"])
        assert r["ok"]
        assert [d for d, _ in r["docs"]] == [d for d, _ in want]
        for (_, gs), (_, ws) in zip(r["docs"], want):
            assert abs(gs - ws) < 1e-9
        # validation: bm25 without terms is a counted bad request
        r = c.rpc(id=2, op="top_k", score="bm25", k=5)
        assert r["error"] == "bad_request"
        r = c.rpc(id=3, op="top_k", score="nonsense", k=5,
                  terms=["dog"])
        assert r["error"] == "bad_request"


def test_cli_serve_missing_artifact_exits_2(tmp_path):
    env = dict(os.environ, PYTHONPATH=str(REPO_ROOT), JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m",
         "parallel_computation_of_an_inverted_index_using_map_reduce_tpu",
         "serve", str(tmp_path), "--listen", "127.0.0.1:0"],
        capture_output=True, text=True, env=env, cwd=str(REPO_ROOT),
        timeout=120)
    assert proc.returncode == 2
    assert proc.stderr.startswith("error:")
    assert proc.stderr.count("\n") == 1


def test_cli_serve_bad_listen_and_env_exit_2(built):
    out, _ = built
    env = dict(os.environ, PYTHONPATH=str(REPO_ROOT), JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m",
         "parallel_computation_of_an_inverted_index_using_map_reduce_tpu",
         "serve", str(out), "--listen", "nonsense"],
        capture_output=True, text=True, env=env, cwd=str(REPO_ROOT),
        timeout=120)
    assert proc.returncode == 2 and "HOST:PORT" in proc.stderr

    env["MRI_SERVE_QUEUE_DEPTH"] = "zero"
    proc = subprocess.run(
        [sys.executable, "-m",
         "parallel_computation_of_an_inverted_index_using_map_reduce_tpu",
         "serve", str(out), "--listen", "127.0.0.1:0"],
        capture_output=True, text=True, env=env, cwd=str(REPO_ROOT),
        timeout=120)
    assert proc.returncode == 2
    assert "MRI_SERVE_QUEUE_DEPTH" in proc.stderr
    assert proc.stderr.count("\n") == 1


# -- serve-side chaos soak (tools/chaos.py --daemon) --------------------


def _load_chaos():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "mri_chaos", REPO_ROOT / "tools" / "chaos.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def chaos():
    return _load_chaos()


def _assert_daemon_contract(summary):
    assert summary["failures"] == [], \
        "daemon chaos contract violated:\n" + "\n".join(
            json.dumps(f, sort_keys=True) for f in summary["failures"])
    assert summary["clean"] == summary["trials"]


@pytest.mark.chaos
def test_daemon_chaos_scenario_cycle_fast(tmp_path, chaos):
    """One seeded trial per serve scenario (overload burst, SIGTERM
    mid-request, corrupt reload, client disconnect, watchdog stall)
    against a real subprocess daemon — the tier-1 smoke for the
    --daemon soak."""
    n = len(chaos.DAEMON_SCENARIOS)
    summary = chaos.run_daemon_soak(tmp_path, trials=n, seed_base=7000,
                                    deadline_s=60.0, verbose=False)
    _assert_daemon_contract(summary)
    assert summary["trials"] == n
    assert all(n == 1 for n in summary["by_scenario"].values())


@pytest.mark.chaos
@pytest.mark.slow
def test_daemon_chaos_soak(tmp_path, chaos):
    """The acceptance soak: 4 seeded trials per scenario — zero
    hangs, zero lost or duplicated responses, every drain exits 0."""
    n = 4 * len(chaos.DAEMON_SCENARIOS)
    summary = chaos.run_daemon_soak(tmp_path, trials=n, seed_base=7200,
                                    deadline_s=60.0, verbose=False)
    _assert_daemon_contract(summary)
    assert summary["trials"] == n
    assert all(n == 4 for n in summary["by_scenario"].values())
