"""Multi-chip shuffle on 8 virtual CPU devices (SURVEY.md §4 item 4).

The same shard_map/all_to_all program that runs over ICI on a pod runs
here on fake devices — the reference has no analogue (pthread counts
are its only scale knob).
"""

import numpy as np
import pytest

import jax

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="multi-chip paths need >= 2 devices (8 virtual on CPU; a "
           "single real TPU chip cannot form a mesh)")

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.models.oracle import (
    oracle_postings,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import engine
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import keys as K
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel import dist_engine
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel.mesh import make_mesh
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    tokenize_documents,
)


def _packed_input(docs, ids, pad_to_multiple):
    corpus = tokenize_documents(docs, ids)
    max_doc_id = max(ids)
    stride = max_doc_id + 2
    n = corpus.num_tokens
    padded = ((n + pad_to_multiple - 1) // pad_to_multiple) * pad_to_multiple
    keys = np.full(padded, K.INT32_MAX, np.int32)
    keys[:n] = corpus.term_ids * stride + corpus.doc_ids
    return corpus, keys, max_doc_id


def test_eight_device_mesh_available():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("num_devices", [2, 8])
def test_dist_matches_single_chip(num_devices):
    docs = [
        b"the quick brown fox jumps over the lazy dog",
        b"pack my box with five dozen liquor jugs",
        b"how vexingly quick daft zebras jump",
        b"the five boxing wizards jump quickly",
    ]
    ids = [1, 2, 3, 4]
    corpus, keys, max_doc_id = _packed_input(docs, ids, num_devices * 8)
    mesh = make_mesh(num_devices)
    out = dist_engine.dist_index(
        keys, corpus.letter_of_term,
        vocab_size=corpus.vocab_size, max_doc_id=max_doc_id, mesh=mesh)
    ref = engine.index_packed(
        keys.copy(), corpus.letter_of_term,
        vocab_size=corpus.vocab_size, max_doc_id=max_doc_id)
    np.testing.assert_array_equal(out["df"], ref["df"])
    np.testing.assert_array_equal(out["order"], ref["order"])
    np.testing.assert_array_equal(out["offsets"], ref["offsets"])
    assert int(out["num_unique"]) == int(ref["num_unique"])
    nu = int(ref["num_unique"])
    np.testing.assert_array_equal(
        np.asarray(out["postings"])[:nu], np.asarray(ref["postings"])[:nu])


def test_dist_matches_oracle_random():
    rng = np.random.default_rng(42)
    letters = "abcdefghijklmnopqrstuvwxyz"
    vocab_pool = ["".join(rng.choice(list(letters), size=rng.integers(1, 8)))
                  for _ in range(50)]
    docs, ids = [], []
    for d in range(6):
        words = rng.choice(vocab_pool, size=int(rng.integers(5, 60)))
        docs.append(" ".join(words).encode())
        ids.append(d + 1)
    corpus, keys, max_doc_id = _packed_input(docs, ids, 8 * 8)
    out = dist_engine.dist_index(
        keys, corpus.letter_of_term,
        vocab_size=corpus.vocab_size, max_doc_id=max_doc_id, mesh=make_mesh(8))
    expected = oracle_postings(docs, ids)
    words = corpus.vocab_strings()
    df = np.asarray(out["df"])
    offsets = np.asarray(out["offsets"])
    postings = np.asarray(out["postings"])
    assert len(words) == len(expected)
    for t, w in enumerate(words):
        got = postings[int(offsets[t]): int(offsets[t]) + int(df[t])].tolist()
        assert got == expected[w], w


def test_capacity_overflow_retry():
    # All tokens are the SAME term -> every pair lands in one bucket;
    # the default capacity (local/n * 2) must overflow and the safe
    # retry must still produce correct output.
    docs = [b"word " * 40, b"word " * 40]
    ids = [1, 2]
    corpus, keys, max_doc_id = _packed_input(docs, ids, 8 * 8)
    mesh = make_mesh(8)
    out = dist_engine.dist_index(
        keys, corpus.letter_of_term,
        vocab_size=corpus.vocab_size, max_doc_id=max_doc_id, mesh=mesh)
    assert int(out["num_unique"]) == 2
    np.testing.assert_array_equal(np.asarray(out["df"]), [2])
    np.testing.assert_array_equal(np.asarray(out["postings"])[:2], [1, 2])


# -- model-level: pipelined windowed uploads over the mesh ---------------


def _model_corpus(tmp_path):
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        read_manifest,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        write_manifest,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        write_corpus, zipf_corpus,
    )

    docs = zipf_corpus(num_docs=23, vocab_size=400, tokens_per_doc=60, seed=9)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    return read_manifest(tmp_path / "list.txt")


def test_pipelined_dist_matches_one_shot_dist(tmp_path):
    from conftest import read_letter_files
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        IndexConfig, build_index,
    )

    m = _model_corpus(tmp_path)
    # pipelined: windows sharded over all 8 virtual devices (default)
    r1 = build_index(
        m, IndexConfig(backend="tpu", pad_multiple=64, pipeline_chunk_docs=5),
        output_dir=tmp_path / "pipe")
    assert r1["device_shards"] == 8 and r1["upload_windows"] >= 4
    # one-shot dist engine (pipeline disabled)
    r2 = build_index(
        m, IndexConfig(backend="tpu", pad_multiple=64, pipeline_chunk_docs=0),
        output_dir=tmp_path / "oneshot")
    assert r2["device_shards"] == 8 and "tokenize_feed" not in r2["phases_ms"]
    assert read_letter_files(tmp_path / "pipe") == read_letter_files(tmp_path / "oneshot")


def test_pipelined_dist_capacity_overflow_retry(tmp_path):
    from conftest import read_letter_files
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        IndexConfig, build_index, oracle_index, read_manifest,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        write_manifest,
    )

    # every doc is almost one repeated word -> one hash bucket hogs the
    # exchange; the provably-safe retry must preserve byte equality
    paths = []
    for i in range(6):
        p = tmp_path / f"d{i}.txt"
        p.write_bytes(b"word " * 30 + f"extra{i}".encode())
        paths.append(str(p))
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    build_index(
        m, IndexConfig(backend="tpu", pad_multiple=64, pipeline_chunk_docs=2),
        output_dir=tmp_path / "pipe")
    assert read_letter_files(tmp_path / "pipe") == read_letter_files(tmp_path / "oracle")


def test_letter_ownership_emit_matches_merged(tmp_path):
    """emit_ownership='letter' (per-owner letter emission over a second
    all_to_all — the reference's reducer ownership, main.c:129-150, at
    mesh scale) must be byte-identical to the merged emit and track its
    fetch in stats."""
    from conftest import read_letter_files
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        IndexConfig, build_index, read_manifest,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        write_manifest,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        write_corpus, zipf_corpus,
    )

    docs = zipf_corpus(num_docs=64, vocab_size=900, tokens_per_doc=80, seed=13)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    build_index(m, IndexConfig(backend="tpu", pad_multiple=64),
                output_dir=tmp_path / "merged")
    stats = build_index(
        m, IndexConfig(backend="tpu", pad_multiple=64, emit_ownership="letter"),
        output_dir=tmp_path / "letter")
    assert stats["emit_ownership"] == "letter"
    assert stats["letter_owners"] == 8
    assert stats["dist_valid_pairs"] == stats["unique_pairs"]
    assert read_letter_files(tmp_path / "letter") == read_letter_files(tmp_path / "merged")


def test_letter_ownership_two_owners(tmp_path):
    """Sub-mesh letter ownership (2 owners over 13 letters each)."""
    from conftest import read_letter_files
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        IndexConfig, build_index, oracle_index, read_manifest,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        write_manifest,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        write_corpus, zipf_corpus,
    )

    docs = zipf_corpus(num_docs=30, vocab_size=400, tokens_per_doc=50, seed=21)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    stats = build_index(
        m, IndexConfig(backend="tpu", pad_multiple=64, device_shards=2,
                       emit_ownership="letter"),
        output_dir=tmp_path / "letter2")
    assert stats["letter_owners"] == 2
    assert read_letter_files(tmp_path / "letter2") == read_letter_files(tmp_path / "oracle")


def test_letter_ownership_requires_mesh():
    import pytest
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        IndexConfig, InvertedIndexModel,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        Manifest,
    )

    model = InvertedIndexModel(IndexConfig(
        backend="tpu", device_shards=1, emit_ownership="letter"))
    with pytest.raises(ValueError, match="multi-chip"):
        model.run(Manifest(paths=("x",), sizes=(1,)), output_dir="/tmp/nope")


def test_letter_ownership_config_validation():
    import pytest
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import IndexConfig

    with pytest.raises(ValueError, match="emit_ownership"):
        IndexConfig(emit_ownership="bogus")
    with pytest.raises(ValueError, match="backend"):
        IndexConfig(backend="cpu", emit_ownership="letter")
    with pytest.raises(ValueError, match="pipelined"):
        IndexConfig(backend="tpu", emit_ownership="letter", pipeline_chunk_docs=0)
