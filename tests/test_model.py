"""Model orchestrator behaviors beyond byte conformance."""

import numpy as np

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    Manifest,
    write_manifest,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    tokenize_documents,
)


def _manifest(tmp_path, texts):
    paths = []
    for i, t in enumerate(texts):
        p = tmp_path / f"m{i}.txt"
        p.write_text(t)
        paths.append(str(p))
    write_manifest(tmp_path / "list.txt", paths)
    return read_manifest(tmp_path / "list.txt")


def test_run_is_reentrant_with_fresh_stats(tmp_path):
    m = _manifest(tmp_path, ["one two three", "two three four"])
    model = InvertedIndexModel(IndexConfig(pad_multiple=64))
    s1 = model.run(m, tmp_path / "a")
    s2 = model.run(m, tmp_path / "b")
    # second run must not accumulate the first run's wall time
    tok2 = s2["phases_ms"].get("tokenize", s2["phases_ms"].get("tokenize_feed"))
    assert tok2 is not None and tok2 < s1["total_ms"] + 1e9  # sanity
    assert abs(s1["tokens"] - s2["tokens"]) == 0
    assert s2["total_ms"] < 2 * s1["total_ms"] + 1000


def test_long_word_two_tier_vocab():
    # words longer than the 32-byte dense pack go through the rare path;
    # a long and short word sharing a 32-byte prefix must stay distinct
    prefix = "abcdefghijklmnopqrstuvwxyzabcdef"  # exactly 32 letters
    long_word = prefix + "tail"
    docs = [f"{prefix} {long_word} zz".encode(), f"{long_word} zz".encode()]
    corpus = tokenize_documents(docs, [1, 2])
    words = corpus.vocab_strings()
    assert prefix in words and long_word in words
    assert words == sorted(words)
    got = {}
    for t, d in zip(corpus.term_ids, corpus.doc_ids):
        got.setdefault(words[t], set()).add(int(d))
    assert got == {prefix: {1}, long_word: {1, 2}, "zz": {1, 2}}
    assert np.all(corpus.letter_of_term == 0) or words[-1] == "zz"
