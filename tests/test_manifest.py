"""Manifest format, doc-id assignment, warn-and-skip policies."""

import numpy as np
import pytest

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    load_documents,
    manifest_from_dir,
    read_manifest,
    write_manifest,
)


def test_roundtrip_and_doc_ids(tmp_path):
    for i in range(3):
        (tmp_path / f"f{i}.txt").write_text(f"doc {i}")
    write_manifest(tmp_path / "list.txt", [f"f{i}.txt" for i in range(3)])
    m = read_manifest(tmp_path / "list.txt", base_dir=tmp_path)
    assert len(m) == 3
    assert [m.doc_id(i) for i in range(3)] == [1, 2, 3]  # 1-based (main.c:116)
    assert m.sizes == (5, 5, 5)


def test_missing_file_kept_with_size_zero(tmp_path, capsys):
    write_manifest(tmp_path / "list.txt", ["nope.txt"])
    m = read_manifest(tmp_path / "list.txt", base_dir=tmp_path)
    assert len(m) == 1 and m.sizes == (0,)  # main.c:293-296 keeps it


def test_count_header_truncates_extra_lines(tmp_path):
    (tmp_path / "a.txt").write_text("x")
    (tmp_path / "b.txt").write_text("y")
    (tmp_path / "list.txt").write_text("1\na.txt\nb.txt\n")
    m = read_manifest(tmp_path / "list.txt", base_dir=tmp_path)
    assert len(m) == 1  # reference reads exactly `count` entries (main.c:281)


def test_undercount_raises(tmp_path):
    (tmp_path / "list.txt").write_text("5\na.txt\n")
    with pytest.raises(ValueError):
        read_manifest(tmp_path / "list.txt", base_dir=tmp_path)


def test_load_documents_skips_unreadable(tmp_path):
    (tmp_path / "ok.txt").write_text("hello")
    write_manifest(tmp_path / "list.txt", ["ok.txt", "gone.txt"])
    m = read_manifest(tmp_path / "list.txt", base_dir=tmp_path)
    contents, doc_ids = load_documents(m)
    assert contents == [b"hello"] and doc_ids == [1]  # doc id 2 never emitted


def test_manifest_from_dir_sorted(tmp_path):
    for name in ["b/x.txt", "a/y.txt", "a/x.txt"]:
        p = tmp_path / name
        p.parent.mkdir(exist_ok=True)
        p.write_text("t")
    m = manifest_from_dir(tmp_path)
    rel = [p.split(str(tmp_path) + "/")[1] for p in m.paths]
    assert rel == ["a/x.txt", "a/y.txt", "b/x.txt"]


def test_prefetch_document_ranges_matches_and_releases_reader(tmp_path):
    """prefetch_document_ranges yields exactly what iter_document_ranges
    does, and abandoning the generator mid-iteration releases the
    reader thread (no permanently blocked q.put holding window
    buffers)."""
    import threading
    import time

    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        iter_document_ranges, prefetch_document_ranges,
    )

    names = []
    for i in range(6):
        p = tmp_path / f"d{i}.txt"
        p.write_text(f"doc {i} words here")
        names.append(f"d{i}.txt")
    write_manifest(tmp_path / "list.txt", names)
    m = read_manifest(tmp_path / "list.txt", base_dir=tmp_path)
    ranges = [(0, 2), (2, 4), (4, 6)]

    assert (list(prefetch_document_ranges(m, ranges))
            == list(iter_document_ranges(m, ranges)))

    before = threading.active_count()
    gen = prefetch_document_ranges(m, ranges)
    next(gen)
    gen.close()  # abandon with windows still queued
    deadline = time.monotonic() + 5.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before, "reader thread leaked"
