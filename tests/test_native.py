"""Native C++ tokenizer: exact equivalence with the numpy reference path."""

import numpy as np
import pytest

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import native
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    tokenize_documents,
)

pytestmark = pytest.mark.skipif(not native.available(), reason="no C++ toolchain")


def _assert_equal(docs, ids):
    a = tokenize_documents(docs, ids)
    b = native.tokenize_native(docs, ids)
    np.testing.assert_array_equal(a.term_ids, b.term_ids)
    np.testing.assert_array_equal(a.doc_ids, b.doc_ids)
    assert a.vocab_strings() == b.vocab_strings()
    np.testing.assert_array_equal(a.letter_of_term, b.letter_of_term)


def test_edge_cases():
    _assert_equal(
        [
            b"The quick brown Fox! don't stop x1y2z3",
            b"quick\tquick\nfox 42 --- caf\xc3\xa9",
            b"",
            b"...only punct 123...",
            b"A" * 350 + b" tail",
            b"no-trailing-whitespace",
        ],
        [1, 2, 3, 4, 5, 6],
    )


def test_doc_boundaries_no_whitespace():
    # doc1 ends mid-letters, doc2 starts with letters: must NOT merge
    _assert_equal([b"abc", b"def"], [1, 2])
    _assert_equal([b"abc ", b" def"], [3, 7])


def test_empty_inputs():
    _assert_equal([], [])
    _assert_equal([b"", b"   ", b"123"], [1, 2, 3])


def test_random_equivalence():
    rng = np.random.default_rng(3)
    alphabet = list(b"abcdefXYZ0-' \t\n\xc3\xa9.")
    for trial in range(20):
        n_docs = int(rng.integers(1, 8))
        docs = [bytes(rng.choice(alphabet, size=int(rng.integers(0, 400))))
                for _ in range(n_docs)]
        ids = list(range(1, n_docs + 1))
        _assert_equal(docs, ids)


def test_dedup_pairs_combiner():
    docs = [b"a b a a c b", b"a a a", b"", b"c c b"]
    ids = [1, 2, 3, 4]
    plain = native.tokenize_native(docs, ids)
    dedup = native.tokenize_native(docs, ids, dedup_pairs=True)
    assert plain.raw_tokens == dedup.raw_tokens == 12
    assert not plain.pairs_deduped and dedup.pairs_deduped
    # deduped stream = unique pairs of the plain stream, first-occurrence order
    seen, expected = set(), []
    for t, d in zip(plain.term_ids, plain.doc_ids):
        if (int(t), int(d)) not in seen:
            seen.add((int(t), int(d)))
            expected.append((int(t), int(d)))
    got = list(zip(dedup.term_ids.tolist(), dedup.doc_ids.tolist()))
    assert got == expected
    assert dedup.vocab_strings() == plain.vocab_strings()


def test_emit_native_matches_python(tmp_path):
    from conftest import read_letter_files
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops.engine import (
        host_order_offsets,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text import formatter

    rng = np.random.default_rng(11)
    docs = [b" ".join(rng.choice([b"ab", b"b", b"zeta", b"yarn", b"a"], 30))
            for _ in range(5)]
    ids = [1, 2, 3, 4, 5]
    corpus = tokenize_documents(docs, ids)
    # build postings via simple host computation
    pairs = sorted({(int(t), int(d)) for t, d in zip(corpus.term_ids, corpus.doc_ids)})
    df = np.bincount([t for t, _ in pairs], minlength=corpus.vocab_size)
    postings = np.array([d for _, d in pairs], dtype=np.uint16)
    order, offsets = host_order_offsets(corpus.letter_of_term, df)

    out_n, out_p = tmp_path / "native", tmp_path / "python"
    out_n.mkdir(), out_p.mkdir()
    native.emit_native(out_n, corpus.vocab, order, df, offsets, postings)
    formatter.emit_index(
        out_p, vocab=corpus.vocab, letter_of_term=corpus.letter_of_term,
        order=order, df=df, offsets=offsets, postings=postings.astype(np.int32),
        max_doc_id=5)
    assert read_letter_files(out_n) == read_letter_files(out_p)


def test_vocab_growth_rehash():
    # the 1<<16 seed table grows past 45,876 entries at 0.7 load; 60,000
    # unique words force (at least) one rehash of the C++ table
    import itertools

    words = ["".join(p) for p in itertools.product("abcdefghijklmnopq", repeat=4)][:60000]
    assert len(set(words)) == 60000
    docs = [" ".join(words[i::3]).encode() for i in range(3)]
    _assert_equal(docs, [1, 2, 3])


# ---------------------------------------------------------------------------
# Multithreaded map phase (the reference's mapper threads, main.c:348-365):
# output must be identical for every thread count.
# ---------------------------------------------------------------------------


def _random_docs(seed, n_docs=40, max_len=600):
    rng = np.random.default_rng(seed)
    alphabet = list(b"abcdefgh XYZ01-'\t\n.")
    docs = [bytes(rng.choice(alphabet, size=int(rng.integers(0, max_len))))
            for _ in range(n_docs)]
    return docs, list(range(1, n_docs + 1))


@pytest.mark.parametrize("threads", [2, 3, 8, 61])
def test_tokenize_mt_identical(threads):
    docs, ids = _random_docs(17)
    st = native.tokenize_native(docs, ids, dedup_pairs=True, num_threads=1)
    mt = native.tokenize_native(docs, ids, dedup_pairs=True, num_threads=threads)
    np.testing.assert_array_equal(st.term_ids, mt.term_ids)
    np.testing.assert_array_equal(st.doc_ids, mt.doc_ids)
    assert st.vocab_strings() == mt.vocab_strings()
    assert st.raw_tokens == mt.raw_tokens


def test_tokenize_mt_more_threads_than_docs():
    docs, ids = [b"alpha beta", b"beta gamma"], [1, 2]
    st = native.tokenize_native(docs, ids, num_threads=1)
    mt = native.tokenize_native(docs, ids, num_threads=16)
    np.testing.assert_array_equal(st.term_ids, mt.term_ids)
    np.testing.assert_array_equal(st.doc_ids, mt.doc_ids)


@pytest.mark.parametrize("threads", [2, 5])
def test_host_index_mt_identical(tmp_path, threads):
    from conftest import read_letter_files

    docs, ids = _random_docs(23, n_docs=60)
    out1, out2 = tmp_path / "st", tmp_path / "mt"
    s1 = native.host_index_native(docs, ids, out1, num_threads=1)
    s2 = native.host_index_native(docs, ids, out2, num_threads=threads)
    assert read_letter_files(out1) == read_letter_files(out2)
    assert s1 == s2


@pytest.mark.parametrize("threads", [2, 4])
def test_stream_mt_rank_space_identical(threads):
    """MT prov numbering may differ, but everything in rank space —
    postings multiset, df, vocab — must match the single-threaded scan."""
    docs, ids = _random_docs(29, n_docs=50)
    stride = len(docs) + 2

    def run(t):
        keys = []
        with native.NativeKeyStream(stride, num_threads=t) as s:
            for lo in range(0, len(docs), 17):
                k, _ = s.feed(docs[lo:lo + 17], ids[lo:lo + 17])
                keys.append(k)
            fin = s.finalize()
        return np.concatenate(keys), fin

    k1, (vocab1, let1, remap1, df1, raw1, np1, ord1) = run(1)
    k2, (vocab2, let2, remap2, df2, raw2, np2, ord2) = run(threads)
    np.testing.assert_array_equal(vocab1, vocab2)
    np.testing.assert_array_equal(let1, let2)
    np.testing.assert_array_equal(ord1, ord2)
    assert raw1 == raw2 and np1 == np2

    def rank_keys(k, remap):
        term, doc = np.divmod(k.astype(np.int64), stride)
        return np.sort(remap[term].astype(np.int64) * stride + doc)

    np.testing.assert_array_equal(rank_keys(k1, remap1), rank_keys(k2, remap2))
    np.testing.assert_array_equal(df1[np.argsort(remap1)], df2[np.argsort(remap2)])


def test_stream_df_snapshot_matches_bincounts():
    """mri_stream_df_snapshot diffs == per-window per-term deduped pair
    counts (what the overlap plan derives segment tables from), for
    single- and multi-threaded streams."""
    if not native.available():
        pytest.skip("native tokenizer unavailable")
    import numpy as np

    rng = np.random.default_rng(5)
    vocab = [("w%03d" % i).encode() for i in range(120)]
    windows = []
    did = 1
    for _ in range(3):
        docs = [b" ".join(rng.choice(vocab, 25)) for _ in range(6)]
        windows.append((docs, list(range(did, did + len(docs)))))
        did += len(docs)
    stride = did + 2
    for threads in (1, 3):
        s = native.NativeKeyStream(stride, num_threads=threads)
        try:
            prev = np.zeros(0, np.int32)
            for docs, ids in windows:
                keys, _ = s.feed(docs, ids)
                snap = s.df_snapshot()
                # expected per-term deduped count for THIS window
                terms = np.asarray(keys) // stride
                want = np.bincount(terms, minlength=snap.shape[0])
                got = snap.astype(np.int64).copy()
                got[: prev.shape[0]] -= prev
                np.testing.assert_array_equal(got, want)
                prev = snap
            # final snapshot == finalize's df_prov
            _, _, _, df_prov, _, _, _ = s.finalize()
            np.testing.assert_array_equal(prev, df_prov)
        finally:
            s.close()


def test_stream_finalize_emit_order_matches_lexsort():
    """The C++ emit order (stable per-letter by-df sort in
    mri_stream_finalize) must equal the numpy lexsort reference
    (letter asc, df desc, word asc — main.c:55-64), including df ties."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops.engine import (
        host_order_offsets,
    )

    rng = np.random.default_rng(23)
    vocab = [b"aa", b"ab", b"ba", b"bb", b"ca", b"cb", b"cc"] + [
        ("w%02d" % i).encode() for i in range(40)]
    docs = [b" ".join(rng.choice(vocab, 30)) for _ in range(12)]
    stride = len(docs) + 2
    with native.NativeKeyStream(stride) as s:
        for i, d in enumerate(docs):
            s.feed([d], [i + 1])
        vocab_s, letters, remap, df_prov, _, _, emit_order = s.finalize()
    df_rank = np.zeros(len(vocab_s), np.int64)
    df_rank[remap] = df_prov
    want, _ = host_order_offsets(letters, df_rank)
    np.testing.assert_array_equal(emit_order, want)
