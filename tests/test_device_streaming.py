"""Streaming all-device engine (ops/device_streaming.py +
device_tokenize=True, stream_chunk_docs=N): raw byte windows through a
bounded on-device row accumulator.

Exactness contract is the all-device engine's: byte-identical to the
oracle whenever cleaned tokens fit the row width, WidthOverflow
fallback otherwise — independent of chunk size, accumulator growth
path, or window count."""

import numpy as np
import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
    build_index,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import (
    device_streaming as DS,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import (
    device_tokenizer as DT,
)


def _cfg(**kw):
    kw.setdefault("backend", "tpu")
    kw.setdefault("device_tokenize", True)
    kw.setdefault("stream_chunk_docs", 7)
    kw.setdefault("pad_multiple", 256)
    kw.setdefault("device_shards", 1)
    return IndexConfig(**kw)


def test_merge_count_is_exact_not_upper_bound():
    """The count _merge_unique_rows returns is the TRUE unique-row
    count: _row_first_mask masks all-INT32_MAX padding rows, so the
    first padding row is NOT counted as a first occurrence (advisor r3
    flagged the opposite; this pins the verified behavior — if the
    handle ever over-counts, _unique_bound loses its 'true count after
    resolution' meaning)."""
    def win(texts, first_id):
        buf = ("\x00".join(texts) + "\x00").encode()
        data = np.frombuffer(buf, np.uint8).copy()
        ends, pos = [], 0
        for t in texts:
            pos += len(t) + 1
            ends.append(pos)
        return (data, np.array(ends, np.int32),
                np.arange(first_id, first_id + len(texts), dtype=np.int32))

    windows = [["the cat sat", "a cat ran"], ["the dog sat", "cat cat cat"]]
    eng = DS.DeviceStreamEngine(width=12)
    for i, texts in enumerate(windows):
        data, ends, ids = win(texts, 1 + 2 * i)
        eng.feed(data, ends, ids,
                 tok_count=sum(len(t.split()) for t in texts),
                 max_len=max(len(w) for t in texts for w in t.split()))
    # both merge handles are still pending (depth-2 pipeline): resolve
    # them directly and compare against the ground-truth running counts
    truth, seen, doc = [], set(), 0
    for texts in windows:
        for t in texts:
            doc += 1
            seen.update((w, doc) for w in t.split())
        truth.append(len(seen))
    got = [int(np.asarray(h)) for h, _ in eng._pending]
    assert got == truth  # exact, not an upper bound
    counts = np.asarray(eng.finalize()["counts"])
    assert counts[1] == truth[-1]


def test_matches_goldens_smoke(smoke_fixture, tmp_path):
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    report = InvertedIndexModel(_cfg(stream_chunk_docs=2)).run(
        m, output_dir=tmp_path)
    assert report["stream_windows"] >= 2  # really streamed
    assert "sort_cols" in report          # really the DEVICE engine
    assert "stream_feed" in report["phases_ms"]
    assert read_letter_files(tmp_path) == read_letter_files(
        smoke_fixture / "golden")


@pytest.mark.parametrize("chunk", [1, 5, 1000])
def test_chunk_size_invariant_vs_oracle(tmp_path, chunk):
    docs = zipf_corpus(num_docs=33, vocab_size=700, tokens_per_doc=55, seed=5)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    build_index(m, _cfg(stream_chunk_docs=chunk),
                output_dir=tmp_path / "dev")
    assert read_letter_files(tmp_path / "dev") == read_letter_files(
        tmp_path / "oracle")


def test_matches_one_shot_engine(tmp_path):
    docs = zipf_corpus(num_docs=29, vocab_size=500, tokens_per_doc=48, seed=8)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    build_index(m, _cfg(stream_chunk_docs=None), output_dir=tmp_path / "one")
    build_index(m, _cfg(stream_chunk_docs=4), output_dir=tmp_path / "str")
    assert read_letter_files(tmp_path / "str") == read_letter_files(
        tmp_path / "one")


def test_accumulator_growth_path(tmp_path):
    """Tiny initial capacity forces the host-side doubling regrowth."""
    docs = zipf_corpus(num_docs=25, vocab_size=900, tokens_per_doc=70, seed=3)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")

    import parallel_computation_of_an_inverted_index_using_map_reduce_tpu.models.inverted_index as MI

    orig = DS.DeviceStreamEngine

    class Tiny(orig):
        def __init__(self, **kw):
            kw["initial_capacity"] = 256
            kw["window_pad"] = 256
            super().__init__(**kw)

    DS.DeviceStreamEngine = Tiny
    try:
        report = InvertedIndexModel(_cfg(stream_chunk_docs=3)).run(
            m, output_dir=tmp_path / "dev")
    finally:
        DS.DeviceStreamEngine = orig
    assert report["accumulator_capacity"] > 256  # growth really happened
    assert read_letter_files(tmp_path / "dev") == read_letter_files(
        tmp_path / "oracle")


def test_capacity_tracks_unique_rows_not_stream_length(tmp_path):
    """The bounded-memory claim: a long stream over a SMALL vocabulary
    must keep the accumulator at unique-pair scale (the host bound is
    tightened from the previous merge's true count), not grow with
    total fed tokens."""
    rng = np.random.default_rng(12)
    vocab = [("w%02d" % i).encode() for i in range(50)]
    docs = [b" ".join(rng.choice(vocab, 200)) for _ in range(40)]  # 8k tokens
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")

    orig = DS.DeviceStreamEngine

    class Tiny(orig):
        def __init__(self, **kw):
            kw["initial_capacity"] = 1024
            kw["window_pad"] = 256
            super().__init__(**kw)

    DS.DeviceStreamEngine = Tiny
    try:
        report = InvertedIndexModel(_cfg(stream_chunk_docs=2)).run(
            m, output_dir=tmp_path / "dev")
    finally:
        DS.DeviceStreamEngine = orig
    # unique pairs <= 50 words x 40 docs = 2000; a stream-length bound
    # would have doubled past total tokens (8192)
    assert report["accumulator_capacity"] <= 4096
    assert read_letter_files(tmp_path / "dev") == read_letter_files(
        tmp_path / "oracle")


def test_stream_checkpoint_kill_and_resume(tmp_path, monkeypatch):
    """VERDICT r3 #3: kill a checkpointed stream mid-run, resume, and
    get byte-identical output.  The injected crash reproduces the
    round-3 on-chip failure mode (TPU worker died ~9 min into the
    1M-doc stream, SCALE_r03.json) at a deterministic window."""
    docs = zipf_corpus(num_docs=40, vocab_size=120, tokens_per_doc=12, seed=9)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "golden")
    ckpt = tmp_path / "stream.ckpt.npz"

    cfg = _cfg(stream_chunk_docs=5, stream_checkpoint=str(ckpt),
               stream_checkpoint_every=2)
    # 40 docs / 5 per window = 8 windows; crash after window 5 leaves
    # the window-4 checkpoint as the resume point
    monkeypatch.setenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS", "5")
    with pytest.raises(RuntimeError, match="injected stream crash"):
        InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out")
    assert ckpt.exists(), "crash left no checkpoint to resume from"

    monkeypatch.delenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS")
    report = InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out")
    assert report["resumed_from_window"] == 4
    assert report["stream_windows"] == 8
    assert not ckpt.exists(), "completed run must clear its checkpoint"
    assert read_letter_files(tmp_path / "out") == read_letter_files(
        tmp_path / "golden")

    # uninterrupted checkpointed run: same output, checkpoint cleared
    report2 = InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out2")
    assert "resumed_from_window" not in report2
    assert read_letter_files(tmp_path / "out2") == read_letter_files(
        tmp_path / "golden")


@pytest.mark.parametrize("crash_at,every", [(2, 1), (3, 2), (7, 3)])
def test_stream_checkpoint_resume_any_crash_point(tmp_path, monkeypatch,
                                                  crash_at, every):
    """Property: crash at ANY window under ANY cadence, resume, output
    byte-identical — resume position must be exactly the last saved
    loop index regardless of alignment."""
    docs = zipf_corpus(num_docs=32, vocab_size=90, tokens_per_doc=9, seed=21)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "golden")
    ckpt = tmp_path / "s.npz"
    cfg = _cfg(stream_chunk_docs=4, stream_checkpoint=str(ckpt),
               stream_checkpoint_every=every)

    monkeypatch.setenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS", str(crash_at))
    with pytest.raises(RuntimeError, match="injected stream crash"):
        InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out")
    monkeypatch.delenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS")
    report = InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out")
    # last checkpointed window at or before the crash, on cadence (the
    # save at an aligned win_i runs BEFORE the crash hook fires)
    expected_resume = (crash_at // every) * every
    assert report["resumed_from_window"] == expected_resume
    assert report["stream_windows"] == 8
    assert read_letter_files(tmp_path / "out") == read_letter_files(
        tmp_path / "golden")


def test_stream_checkpoint_with_empty_windows(tmp_path, monkeypatch):
    """Windows that tokenize to nothing (digits/punctuation only) make
    the engine's windows_fed run BEHIND the loop index; the checkpoint
    stores the loop position, so resume must still land on the right
    window (the round-4 review's divergence scenario, now pinned)."""
    docs = [b"alpha beta", b"   \n  ", b" \t ",
            b"gamma delta", b"epsilon zeta", b"beta alpha",
            b"eta theta", b"iota kappa"]
    # chunk=1: 8 windows; windows 2 and 3 are whitespace-only — zero
    # TOKENS, so feed() returns before counting them (an all-digit doc
    # would still count: host_token_stats counts raw tokens)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "golden")
    ckpt = tmp_path / "s.npz"
    cfg = _cfg(stream_chunk_docs=1, stream_checkpoint=str(ckpt),
               stream_checkpoint_every=2)
    monkeypatch.setenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS", "5")
    with pytest.raises(RuntimeError, match="injected stream crash"):
        InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out")
    monkeypatch.delenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS")
    report = InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out")
    assert report["resumed_from_window"] == 4      # loop position
    assert report["stream_windows"] == 6           # non-empty windows
    assert read_letter_files(tmp_path / "out") == read_letter_files(
        tmp_path / "golden")


def test_stream_checkpoint_rejects_changed_config(tmp_path, monkeypatch):
    """A checkpoint written under one chunking must not silently feed a
    resume under another (window index would mean a different doc
    range — silent corruption)."""
    docs = zipf_corpus(num_docs=20, vocab_size=60, tokens_per_doc=10, seed=3)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    ckpt = tmp_path / "stream.ckpt.npz"

    monkeypatch.setenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS", "3")
    with pytest.raises(RuntimeError, match="injected stream crash"):
        InvertedIndexModel(_cfg(
            stream_chunk_docs=4, stream_checkpoint=str(ckpt),
            stream_checkpoint_every=1)).run(m, output_dir=tmp_path / "out")
    monkeypatch.delenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS")
    with pytest.raises(ValueError, match="different .* config|different manifest"):
        InvertedIndexModel(_cfg(
            stream_chunk_docs=6, stream_checkpoint=str(ckpt),
            stream_checkpoint_every=1)).run(m, output_dir=tmp_path / "out")


def test_stream_checkpoint_rejects_changed_synthetic_params(tmp_path,
                                                            monkeypatch):
    """Synthetic manifests have placeholder path labels, so the
    fingerprint must carry the generator parameters — resuming a
    seed-11 stream under seed-12 data would silently mix corpora."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        synthetic_manifest,
    )

    ckpt = tmp_path / "stream.ckpt.npz"
    monkeypatch.setenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS", "2")
    m1 = synthetic_manifest(num_docs=30, vocab_size=50, tokens_per_doc=8,
                            seed=11)
    with pytest.raises(RuntimeError, match="injected stream crash"):
        InvertedIndexModel(_cfg(
            stream_chunk_docs=10, stream_checkpoint=str(ckpt),
            stream_checkpoint_every=1)).run(m1, output_dir=tmp_path / "out")
    monkeypatch.delenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS")
    m2 = synthetic_manifest(num_docs=30, vocab_size=50, tokens_per_doc=8,
                            seed=12)
    with pytest.raises(ValueError, match="different manifest"):
        InvertedIndexModel(_cfg(
            stream_chunk_docs=10, stream_checkpoint=str(ckpt),
            stream_checkpoint_every=1)).run(m2, output_dir=tmp_path / "out")


def _fed_engine():
    """A DeviceStreamEngine with one window folded, for snapshot tests."""
    texts = ["the cat sat", "a cat ran here"]
    buf = ("\x00".join(texts) + "\x00").encode()
    data = np.frombuffer(buf, np.uint8).copy()
    ends, pos = [], 0
    for t in texts:
        pos += len(t) + 1
        ends.append(pos)
    eng = DS.DeviceStreamEngine(width=12)
    eng.feed(data, np.array(ends, np.int32),
             np.arange(1, len(texts) + 1, dtype=np.int32),
             tok_count=sum(len(t.split()) for t in texts),
             max_len=max(len(w) for t in texts for w in t.split()))
    return eng


def test_checkpoint_budget_stretches_cadence(tmp_path, monkeypatch):
    """VERDICT r4 weak #3: a cadence save whose projected fetch time
    exceeds MRI_TPU_CKPT_BUDGET_S is skipped (recorded, not paid) — but
    only MRI_TPU_CKPT_STRETCH times in a row, then one save is FORCED
    so a mis-calibrated rate can never lock checkpointing out entirely
    (review r5).  The stream still completes byte-identically and
    per-save timings are listed when saves happen."""
    docs = zipf_corpus(num_docs=24, vocab_size=80, tokens_per_doc=10, seed=6)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "golden")
    ckpt = tmp_path / "s.npz"
    cfg = _cfg(stream_chunk_docs=4, stream_checkpoint=str(ckpt),
               stream_checkpoint_every=1)

    # zero budget, default stretch=4: cadence points are windows 1-5
    # (6 is last) -> 4 skips then a forced save at window 5
    monkeypatch.setenv("MRI_TPU_CKPT_BUDGET_S", "0")
    report = InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out")
    assert report["checkpoint_skips"] == 4
    assert len(report["checkpoint_skipped_projection_s"]) == 4
    assert report["checkpoint_saves"] == 1   # the forced save
    assert not ckpt.exists()                 # completed run clears it
    assert read_letter_files(tmp_path / "out") == read_letter_files(
        tmp_path / "golden")

    # stretch=0: the budget can delay nothing, every cadence point saves
    monkeypatch.setenv("MRI_TPU_CKPT_STRETCH", "0")
    report0 = InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out0")
    assert report0["checkpoint_saves"] == 5
    assert "checkpoint_skips" not in report0
    monkeypatch.delenv("MRI_TPU_CKPT_STRETCH")

    # generous budget: saves happen and each one's wall time is listed
    monkeypatch.setenv("MRI_TPU_CKPT_BUDGET_S", "3600")
    report2 = InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out2")
    assert report2["checkpoint_saves"] == 5
    assert len(report2["checkpoint_ms_per_save"]) == 5
    assert "checkpoint_skips" not in report2
    assert read_letter_files(tmp_path / "out2") == read_letter_files(
        tmp_path / "golden")


def test_rows_curve_tracks_resolved_merge_counts(tmp_path):
    """unique_rows_curve is the resolved per-merge accumulator count,
    monotone nondecreasing, ending at the true unique-pair total."""
    docs = zipf_corpus(num_docs=20, vocab_size=60, tokens_per_doc=8, seed=4)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    report = InvertedIndexModel(_cfg(stream_chunk_docs=4)).run(
        m, output_dir=tmp_path / "out")
    curve = report["unique_rows_curve"]
    # 5 windows, 2-deep pipeline: at least 3 counts resolve in feed
    assert len(curve) >= 3
    assert curve == sorted(curve)
    assert curve[-1] <= report["unique_pairs"]


def test_rows_curve_survives_crash_resume(tmp_path, monkeypatch):
    """A resumed run's curve must cover the WHOLE stream: the pre-crash
    history rides the checkpoint (review r5 — without it the scale
    artifact's growth curve starts mid-stream on exactly the long runs
    it exists to observe)."""
    docs = zipf_corpus(num_docs=32, vocab_size=90, tokens_per_doc=9, seed=2)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    ckpt = tmp_path / "s.npz"
    cfg = _cfg(stream_chunk_docs=4, stream_checkpoint=str(ckpt),
               stream_checkpoint_every=2)
    monkeypatch.setenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS", "5")
    with pytest.raises(RuntimeError, match="injected stream crash"):
        InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out")
    monkeypatch.delenv("MRI_TPU_STREAM_CRASH_AFTER_WINDOWS")
    resumed = InvertedIndexModel(cfg).run(m, output_dir=tmp_path / "out")

    whole = InvertedIndexModel(_cfg(stream_chunk_docs=4)).run(
        m, output_dir=tmp_path / "out2")
    rc, wc = resumed["unique_rows_curve"], whole["unique_rows_curve"]
    # the checkpoint (window 4) drained every in-flight merge, so the
    # resumed curve's prefix is the uninterrupted run's first 4 counts
    assert rc[:4] == wc[:4]
    assert rc == sorted(rc) and len(rc) >= len(wc)
    assert rc[-1] <= resumed["unique_pairs"]


def test_snapshot_prefix_fetch_matches_full_fetch():
    """The granule-padded prefix fetch (snapshot cost trim) must hand
    back exactly the rows the full-capacity fetch would: every valid
    row lives in acc[:count], so a pad >= count loses nothing."""
    eng = _fed_engine()
    eng._snapshot_granule = 8   # force pad < cap (cap is 1 << 16)
    assert eng.snapshot_nbytes < (2 * eng._num_groups + 1) * eng._cap * 4
    trimmed = eng.snapshot()

    full = _fed_engine()
    full._snapshot_granule = full._cap   # pad == cap -> full device_get
    reference = full.snapshot()

    assert trimmed["count"] == reference["count"] > 0
    for a, b in zip(trimmed["columns"], reference["columns"]):
        np.testing.assert_array_equal(a, b)
    # and the trimmed snapshot still restores into a working engine
    eng2 = DS.DeviceStreamEngine(width=12)
    eng2.restore(trimmed)
    assert eng2.windows_fed == trimmed["windows_fed"]


def test_restore_rejects_truncated_checkpoint():
    """A truncated/corrupt snapshot must fail with the same clear
    ValueError diagnostics as the width/column-count checks, not an
    opaque numpy broadcast error (advisor r4)."""
    snap = _fed_engine().snapshot()

    over = dict(snap, count=snap["cap"] + 1)
    with pytest.raises(ValueError, match="exceeds its capacity"):
        DS.DeviceStreamEngine(width=12).restore(over)

    cut = dict(snap, columns=[c[:-1] for c in snap["columns"]])
    with pytest.raises(ValueError, match="truncated or corrupt"):
        DS.DeviceStreamEngine(width=12).restore(cut)

    one_short = dict(snap, columns=(snap["columns"][:-1]
                                    + [snap["columns"][-1][:-1]]))
    with pytest.raises(ValueError, match="column .* truncated or corrupt"):
        DS.DeviceStreamEngine(width=12).restore(one_short)

    # the untouched snapshot still restores (validation is not lossy)
    fresh = DS.DeviceStreamEngine(width=12)
    fresh.restore(snap)
    assert fresh.windows_fed == snap["windows_fed"]


def test_width_overflow_clears_stream_checkpoint(tmp_path):
    """A WidthOverflow fallback abandons the stream for the host path;
    the checkpoint must not survive to poison later runs."""
    docs = [b"short words here", b"also small ones",
            b"x" * 60 + b" overflowing token window"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "golden")
    ckpt = tmp_path / "stream.ckpt.npz"
    report = InvertedIndexModel(_cfg(
        stream_chunk_docs=1, device_tokenize_width=48,
        stream_checkpoint=str(ckpt), stream_checkpoint_every=1)).run(
            m, output_dir=tmp_path / "out")
    assert "device_tokenize_fallback" in report
    assert not ckpt.exists(), "fallback left a stale stream checkpoint"
    assert read_letter_files(tmp_path / "out") == read_letter_files(
        tmp_path / "golden")


def test_stream_checkpoint_config_validation():
    with pytest.raises(ValueError, match="single-chip only"):
        IndexConfig(backend="tpu", device_tokenize=True,
                    stream_chunk_docs=4, stream_checkpoint="x.npz")
    with pytest.raises(ValueError, match="streaming all-device engine"):
        IndexConfig(backend="tpu", stream_checkpoint="x.npz")
    with pytest.raises(ValueError, match="stream_checkpoint_every"):
        IndexConfig(stream_checkpoint_every=0)


def test_width_overflow_falls_back_exactly(tmp_path):
    """An over-width token in a LATER window must abort the whole run
    to the host path with byte-identical output."""
    docs = [b"early window words"] * 6 + [b"a" * 30 + b" tail"] + [b"end"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    report = InvertedIndexModel(
        _cfg(stream_chunk_docs=3, device_tokenize_width=16)).run(
        m, output_dir=tmp_path / "dev")
    assert "device_tokenize_fallback" in report
    assert read_letter_files(tmp_path / "dev") == read_letter_files(
        tmp_path / "oracle")


def test_empty_and_numbers_only_corpus(tmp_path):
    docs = [b"", b"   ", b"123 456", b"--- !!!"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    InvertedIndexModel(_cfg(stream_chunk_docs=2)).run(
        m, output_dir=tmp_path / "dev")
    assert read_letter_files(tmp_path / "dev") == b""


# -- mesh variant (parallel/dist_device_streaming.py) ---------------------


def _dist_cfg(**kw):
    kw.setdefault("device_shards", None)  # all 8 virtual devices
    return _cfg(**kw)


def _needs_mesh():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("mesh streaming device engine needs >= 2 devices")


@pytest.mark.parametrize("seed,chunk", [(3, 4), (14, 11)])
def test_dist_stream_vs_oracle(tmp_path, seed, chunk):
    _needs_mesh()
    docs = zipf_corpus(num_docs=35, vocab_size=650, tokens_per_doc=50,
                       seed=seed)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    report = InvertedIndexModel(_dist_cfg(stream_chunk_docs=chunk)).run(
        m, output_dir=tmp_path / "dev")
    assert report["device_shards"] > 1
    assert report["stream_windows"] >= 2
    assert read_letter_files(tmp_path / "dev") == read_letter_files(
        tmp_path / "oracle")


def test_dist_stream_matches_single_chip_stream(tmp_path):
    _needs_mesh()
    docs = zipf_corpus(num_docs=27, vocab_size=400, tokens_per_doc=45,
                       seed=22)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    build_index(m, _cfg(stream_chunk_docs=5), output_dir=tmp_path / "one")
    build_index(m, _dist_cfg(stream_chunk_docs=5),
                output_dir=tmp_path / "mesh")
    assert read_letter_files(tmp_path / "mesh") == read_letter_files(
        tmp_path / "one")


def test_dist_stream_growth_and_retry_path(tmp_path):
    """Tiny per-owner capacity forces the merge-retry + regrow path;
    output must stay byte-identical."""
    _needs_mesh()
    docs = zipf_corpus(num_docs=30, vocab_size=800, tokens_per_doc=60,
                       seed=9)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")

    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel import (
        dist_device_streaming as DDS,
    )

    orig = DDS.DistDeviceStreamEngine

    class Tiny(orig):
        def __init__(self, **kw):
            kw["initial_capacity"] = 128
            kw["window_pad"] = 128
            super().__init__(**kw)

    DDS.DistDeviceStreamEngine = Tiny
    try:
        report = InvertedIndexModel(_dist_cfg(stream_chunk_docs=6)).run(
            m, output_dir=tmp_path / "dev")
    finally:
        DDS.DistDeviceStreamEngine = orig
    assert report["accumulator_capacity_per_owner"] > 128
    assert read_letter_files(tmp_path / "dev") == read_letter_files(
        tmp_path / "oracle")


def test_dist_stream_fewer_docs_than_chips(tmp_path):
    """Chunks smaller than the mesh leave empty byte shards — they
    must contribute nothing, not crash."""
    _needs_mesh()
    docs = [b"alpha beta", b"beta gamma", b"delta"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    build_index(m, _dist_cfg(stream_chunk_docs=2),
                output_dir=tmp_path / "dev")
    assert read_letter_files(tmp_path / "dev") == read_letter_files(
        tmp_path / "oracle")


def test_dist_stream_width_overflow_falls_back(tmp_path):
    _needs_mesh()
    docs = [b"short words"] * 4 + [b"b" * 30 + b" tail"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    report = InvertedIndexModel(
        _dist_cfg(stream_chunk_docs=2, device_tokenize_width=16)).run(
        m, output_dir=tmp_path / "dev")
    assert "device_tokenize_fallback" in report
    assert read_letter_files(tmp_path / "dev") == read_letter_files(
        tmp_path / "oracle")


def test_pack_unpack_groups_roundtrip():
    """unpack_groups must be the exact inverse of pack_groups on valid
    rows for every column count."""
    rng = np.random.default_rng(0)
    ncols = 12
    n = 64
    # random cleaned rows: 0-terminated lowercase prefixes
    rows = np.zeros((n, 4 * ncols), np.uint8)
    for i in range(n):
        ln = int(rng.integers(1, 4 * ncols + 1))
        rows[i, :ln] = rng.integers(97, 123, ln, np.uint8)
    r32 = rows.reshape(n, ncols, 4).astype(np.int64)
    cols = tuple(
        ((r32[:, c, 0] << 24) | (r32[:, c, 1] << 16)
         | (r32[:, c, 2] << 8) | r32[:, c, 3]).astype(np.int32)
        for c in range(ncols))
    import jax.numpy as jnp

    jcols = tuple(jnp.asarray(c) for c in cols)
    groups = DT.pack_groups(jcols, ncols)
    back = DT.unpack_groups(groups, ncols)
    for want, got in zip(cols, back):
        np.testing.assert_array_equal(want, np.asarray(got))
