"""CLI smoke test for tools/measure_tpu.py — the designated on-chip
re-timing tool (VERDICT r2 #1/#8).  Runs it end-to-end on the smoke
corpus with a forced cpu platform so the recovery tool cannot rot
between tunnel windows."""

import json
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent


def test_measure_tpu_cli_smoke_on_cpu():
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "measure_tpu.py"),
         "--platform", "cpu", "--quick",
         "--corpus", str(REPO_ROOT / "tests" / "fixtures" / "smoke" / "docs")],
        capture_output=True, text=True, timeout=420, cwd=str(REPO_ROOT))
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    header, engines = lines[0], lines[1:]
    assert "devices" in header and header["devices"]
    labels = [e["engine"] for e in engines]
    assert labels == ["cpu_native", "overlap_0.5", "overlap_0.5_1win",
                      "device_tokenize_oneshot"]
    for e in engines:
        assert e["e2e_ms"] > 0
        assert e["phases_ms"]
    # non-reference corpus: every tpu engine is cross-checked against
    # the cpu backend's md5
    assert all(e["md5_ok"] for e in engines if "md5_ok" in e)
    assert sum("md5_ok" in e for e in engines) == 3


def test_bench_tpu_child_fast_lane_cpu_smoke():
    """bench.py's TPU child must print a complete, parseable result
    line after the FAST LANE alone, then re-print after each extension
    stage (VERDICT r2 #2: the parent salvages the last complete line of
    a timed-out child, so the fast-lane line is what guarantees a
    driver-captured TPU number)."""
    import os
    import subprocess

    env = dict(
        os.environ,
        MRI_TPU_BENCH_PLATFORM="cpu",
        JAX_PLATFORMS="cpu",
        MRI_TPU_BENCH_CORPUS=str(
            REPO_ROOT / "tests" / "fixtures" / "smoke" / "docs"),
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "bench.py"), "--tpu-child"],
        capture_output=True, text=True, timeout=540, env=env,
        cwd=str(REPO_ROOT))
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    # fast lane, grid, kernel probe, devtok probe: 4 stage prints
    assert len(lines) == 4
    fast = lines[0]
    assert fast["stage"] == "fast-lane"
    assert fast["best_ms"] > 0
    assert fast["best_plan"] == {"overlap_tail_fraction": 0.5,
                                 "device_shards": 1}
    assert fast["phases_ms"]
    # every later stage line remains a complete salvageable result
    for line in lines[1:]:
        assert line["best_ms"] > 0 and "best_plan" in line
    assert "kernel_timings" in lines[2]
    assert "device_tokenize_ms" in lines[3]


def test_profile_stream_stages_smoke_on_cpu():
    """The stream-stage profiler replicates DeviceStreamEngine.feed's
    staging by hand; this smoke run is the drift guard — if feed()'s
    staging changes and the serialized replication desynchronizes, the
    tool crashes or its pair count diverges from the generator's
    ground truth."""
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "tools" / "profile_stream_stages.py"),
         "--platform", "cpu", "--docs", "3000", "--vocab", "500",
         "--chunk", "1000"],
        capture_output=True, text=True, timeout=420, cwd=str(REPO_ROOT))
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    full = lines[-1]
    assert full["windows"] == 3
    assert full["serialized_wall_s"] > 0 and full["pipelined_feed_wall_s"] > 0
    for k in ("host_prep_s", "upload_s", "window_rows_s", "merge_s",
              "finalize_s"):
        assert k in full
    # ground truth from the same deterministic generator
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        synthetic_manifest,
    )

    m = synthetic_manifest(num_docs=3000, vocab_size=500, tokens_per_doc=40,
                           seed=11)
    pairs = {(w, i) for i in range(3000)
             for w in m.read_doc(i).split()}
    assert full["unique_pairs"] == len(pairs)


def test_bench_fallback_embeds_attestation(tmp_path):
    """VERDICT r3 #2: when the tunnel is down at driver time, the
    cpu-fallback line must still carry the most recent builder-side
    on-chip measurement (BENCH_ATTEST.json) — a rev-stamped claim
    chain instead of a bare cpu number."""
    import os
    import subprocess

    attest = tmp_path / "attest.json"
    attest.write_text(json.dumps({
        "captured_unix": 1700000000,
        "captured_utc": "2026-07-31T05:00:00Z",
        "git_rev": "abc1234",
        "tpu_line": {"value": 57.28, "vs_baseline": 13.898,
                     "tpu_plan": {"overlap_tail_fraction": 0.5}},
    }))
    env = dict(
        os.environ,
        MRI_TPU_BENCH_ATTEST=str(attest),
        MRI_TPU_BENCH_CORPUS=str(
            REPO_ROOT / "tests" / "fixtures" / "smoke" / "docs"),
        # make every TPU attempt fail fast: probe forced onto a
        # platform that errors out in the probe subprocess
        MRI_TPU_BENCH_PROBE_S="30",
        MRI_TPU_BENCH_TIMEOUTS="20",
        MRI_TPU_BENCH_ATTEMPTS="1",
        JAX_PLATFORMS="bogus-platform",
    )
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "bench.py")],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=str(REPO_ROOT))
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["measured_backend"] == "cpu-fallback"
    att = line["last_builder_tpu"]
    assert att["value_ms"] == 57.28
    assert att["git_rev"] == "abc1234"
    assert att["captured_utc"] == "2026-07-31T05:00:00Z"
