"""backend="cpu": the whole pipeline in one native C++ call — the
reference's all-on-host regime re-architected (no spill files, no
locks, no token-scale sorts).  Must be byte-identical to the oracle and
to the reference goldens everywhere the device engines are.
"""

import hashlib

import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
    build_index,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import native
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    manifest_from_dir,
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)
from test_conformance import FULL_CORPUS_MD5


def test_cpu_matches_goldens(smoke_fixture, tmp_path):
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    report = InvertedIndexModel(IndexConfig(backend="cpu")).run(
        m, output_dir=tmp_path)
    assert read_letter_files(tmp_path) == read_letter_files(smoke_fixture / "golden")
    if native.available():
        # single-threaded default takes the pipelined ingest path;
        # multi-thread (or --io-prefetch 0) the one-shot fork-join call
        assert ("ingest_scan" in report["phases_ms"]
                or "index_emit" in report["phases_ms"])
        assert report["unique_terms"] > 0


def test_cpu_matches_oracle_on_random_corpus(tmp_path):
    docs = zipf_corpus(num_docs=37, vocab_size=900, tokens_per_doc=70, seed=5)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    build_index(m, IndexConfig(backend="cpu"), output_dir=tmp_path / "cpu")
    assert read_letter_files(tmp_path / "cpu") == read_letter_files(tmp_path / "oracle")


def test_cpu_empty_corpus(tmp_path):
    (tmp_path / "nums.txt").write_bytes(b"123 456\n")
    write_manifest(tmp_path / "list.txt", [str(tmp_path / "nums.txt")])
    m = read_manifest(tmp_path / "list.txt")
    InvertedIndexModel(IndexConfig(backend="cpu")).run(m, output_dir=tmp_path / "out")
    assert read_letter_files(tmp_path / "out") == b""


def test_cpu_falls_back_to_oracle_without_native(smoke_fixture, tmp_path, monkeypatch):
    monkeypatch.setattr(native, "available", lambda: False)
    report = InvertedIndexModel(IndexConfig(backend="cpu")).run(
        read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture),
        output_dir=tmp_path)
    assert report["cpu_fallback"] == "oracle"
    assert read_letter_files(tmp_path) == read_letter_files(smoke_fixture / "golden")


@pytest.mark.slow
def test_cpu_full_corpus_md5(reference_dir, tmp_path):
    pytest.importorskip("numpy")
    if not native.available():
        pytest.skip("native toolchain unavailable")
    m = manifest_from_dir(reference_dir / "test_in")
    build_index(m, IndexConfig(backend="cpu"), output_dir=tmp_path)
    md5 = hashlib.md5(read_letter_files(tmp_path)).hexdigest()
    assert md5 == FULL_CORPUS_MD5


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
def test_cpu_mapper_threads_output_invariant(smoke_fixture, tmp_path):
    """num_mappers drives the host map threads (reference main.c:348-365);
    output must be byte-identical at any count, like the reference's."""
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    outs = []
    for i, mappers in enumerate((1, 4)):
        out = tmp_path / f"m{mappers}"
        report = InvertedIndexModel(
            IndexConfig(backend="cpu", num_mappers=mappers, num_reducers=2)
        ).run(m, output_dir=out)
        assert report["num_mappers"] == mappers
        assert report["num_reducers"] == 2
        assert report["host_threads"] == (mappers if mappers > 1
                                          else native.default_threads())
        outs.append(read_letter_files(out))
    assert outs[0] == outs[1]
    assert outs[0] == read_letter_files(smoke_fixture / "golden")
