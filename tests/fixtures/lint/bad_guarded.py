"""Planted guarded-by violation: one unguarded write, one clean read."""
import threading


class SharedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0  # guarded by: self._lock

    def bump(self):
        self.value += 1  # violation: write without the lock

    def read_locked(self):
        with self._lock:
            return self.value  # clean: lock held

    # mrilint: holds(self._lock)
    def _bump_locked(self):
        self.value += 1  # clean: helper documents the caller holds it
