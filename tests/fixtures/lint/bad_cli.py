"""Planted unwrapped-raise violation (filename ends in cli.py on purpose:
the raise rule only applies to CLI entry-point files)."""


def main(argv):
    if not argv:
        raise ValueError("no args")  # violation: escapes as exit 1
    if argv[0] == "usage":
        raise SystemExit(2)  # clean: maps onto the contract
    return 0


def helper(x):
    raise RuntimeError(x)  # clean: not an entry point
