"""Fixture: ad-hoc stream writes (obs-metrics findings when the file
sits under serve/ or obs/ — the test overrides src.rel, mirroring the
dict-counter scoping test).  Daemon-side output goes through the
structured obs/logging.py funnel, never bare print()/stderr writes."""
import sys


def report(msg):
    # the ad-hoc idiom the checker exists to catch
    print("status:", msg)


def warn(msg):
    sys.stderr.write(msg + "\n")


def emit_ready(line):
    # mrilint: allow(obs-metrics) protocol line on stdout by contract
    print(line)


def log_elsewhere(logger, msg):
    # routed output: not a stream write, stays silent
    logger.info(msg)
