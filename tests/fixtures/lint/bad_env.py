"""Planted env-knobs violations: three raw reads; the write is legal."""
import os

chunk = os.environ.get("MRI_FIXTURE_CHUNK", "4")      # violation: .get()
flag = os.environ["MRI_FIXTURE_FLAG"]                 # violation: subscript
present = "MRI_FIXTURE_FLAG" in os.environ            # violation: membership
os.environ["MRI_FIXTURE_CHILD"] = "1"                 # clean: write for a child
other = os.environ.get("PATH", "")                    # clean: not an MRI_* knob
