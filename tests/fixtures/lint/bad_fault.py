"""Planted fault-boundary violation: raw I/O with no hook or suppression.
(The rule only fires for package files; the test rebinds the path.)"""


def read_raw(path):
    with open(path, "rb") as f:  # violation when inside the package
        return f.read()
