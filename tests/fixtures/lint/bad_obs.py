"""Fixture: hand-rolled counter-dict bumps (obs-metrics findings when
the file sits under serve/ — the test overrides src.rel, mirroring the
fault-boundary scoping test)."""


class Handler:
    def __init__(self):
        self._counters = {"requests": 0, "shed": 0}
        self._weights = {}

    def on_request(self):
        # the pre-obs idiom the checker exists to catch
        self._counters["requests"] += 1

    def on_shed(self, n):
        self._counters["shed"] += n

    def on_weight(self, key, w):
        # variable key: not a counter-dict bump, stays silent
        self._weights[key] += w

    def on_tally(self):
        # mrilint: allow(obs-metrics) bookkeeping dict, not a metric
        self._counters["requests"] += 1
