"""Clean fixture: every rule satisfied, plus one reasoned suppression."""
import os
import threading

suppressed = os.environ.get("MRI_FIXTURE_OK", "")  # mrilint: allow(env-knobs) fixture demonstrates suppression


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0  # guarded by: self._lock

    def bump(self):
        with self._lock:
            self.count += 1


def read_file(path):
    with open(path, "rb") as f:
        return f.read()


def main(argv):
    if not argv:
        raise SystemExit(2)
    return 0
