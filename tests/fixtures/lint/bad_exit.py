"""Planted exit-code violation: exit 1 is reserved by the contract."""
import sys


def main(argv):
    if not argv:
        sys.exit(1)  # violation: 1 is outside the 0/2/3 contract
    if argv[0] == "bad":
        raise SystemExit(2)  # clean: sanctioned usage-error exit
    return 0
