"""Planted lifecycle violations: dropped and leaked handles."""


def read_chained(path):
    return open(path).read()  # violation: handle dropped after chained read


def leak_handle(path):
    f = open(path, "rb")  # violation: never closed on any path
    f.read()
    return None


def read_managed(path):
    with open(path, "rb") as f:  # clean: context-managed
        return f.read()


def read_finally(path):
    f = open(path, "rb")  # clean: closed in a finally
    try:
        return f.read()
    finally:
        f.close()
