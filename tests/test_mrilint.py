"""mrilint suite: checker semantics on planted fixtures, suppression and
baseline mechanics, and the repo-clean gate (`make lint` exit 0)."""
import subprocess
import sys
from collections import Counter
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from tools.mrilint.core import (  # noqa: E402
    PACKAGE,
    REPO_ROOT,
    Source,
    iter_files,
    load_baseline,
    run_lint,
    write_baseline,
)
from tools.mrilint.checks import (  # noqa: E402
    CHECKERS,
    env_knobs,
    exit_codes,
    fault_boundary,
    guarded_by,
    lifecycle,
    obs_metrics,
)

pytestmark = pytest.mark.lint

FIXTURES = Path(__file__).parent / "fixtures" / "lint"


def _check(module, name):
    return module.check(Source(FIXTURES / name))


# -- checker semantics on planted fixtures ---------------------------------

def test_guarded_by_flags_unlocked_write_only():
    findings = _check(guarded_by, "bad_guarded.py")
    assert [f.key for f in findings] == ["SharedCounter.value@bump"]
    assert "with self._lock" in findings[0].message


def test_env_knobs_flags_reads_not_writes():
    findings = _check(env_knobs, "bad_env.py")
    assert sorted(f.key for f in findings) == [
        "MRI_FIXTURE_CHUNK@os.environ.get()",
        "MRI_FIXTURE_FLAG@membership test",
        "MRI_FIXTURE_FLAG@os.environ[...]",
    ]


def test_exit_code_flags_reserved_code():
    findings = _check(exit_codes, "bad_exit.py")
    assert [f.key for f in findings] == ["sys.exit(1)@main"]


def test_exit_code_flags_unwrapped_raise_in_cli():
    findings = _check(exit_codes, "bad_cli.py")
    # only the entry point's ValueError; SystemExit(2) and the helper pass
    assert [f.key for f in findings] == ["raise@main"]


def test_lifecycle_flags_dropped_and_leaked_handles():
    findings = _check(lifecycle, "bad_lifecycle.py")
    assert sorted(f.key for f in findings) == [
        "open@leak_handle", "open@read_chained"]


def test_fault_boundary_scopes_to_package():
    src = Source(FIXTURES / "bad_fault.py")
    assert fault_boundary.check(src) == []  # outside the package: silent
    src.rel = f"{PACKAGE}/corpus/bad_fault.py"
    findings = fault_boundary.check(src)
    assert [f.key for f in findings] == ["open@read_raw"]


def test_obs_metrics_scopes_to_serve_and_flags_dict_bumps():
    src = Source(FIXTURES / "bad_obs.py")
    assert obs_metrics.check(src) == []  # outside serve/: silent
    src.rel = f"{PACKAGE}/serve/bad_obs.py"
    findings = obs_metrics.check(src)
    # constant-string keys flagged; the variable-key bump and the
    # allow()-suppressed bump stay silent
    assert sorted(f.key for f in findings) == [
        "dict-counter@requests", "dict-counter@shed"]
    assert "obs.metrics Counter" in findings[0].message


def test_obs_metrics_flags_stream_writes_in_serve_and_obs():
    src = Source(FIXTURES / "bad_obs_print.py")
    assert obs_metrics.check(src) == []  # outside the scope: silent
    for scope in ("serve", "obs"):
        src.rel = f"{PACKAGE}/{scope}/bad_obs_print.py"
        findings = obs_metrics.check(src)
        # bare print + sys.stderr.write flagged; the allow()-suppressed
        # protocol print and the logger call stay silent
        assert sorted(f.key for f in findings) == [
            "print@report", "stderr-write@warn"]
        assert "obs.logging.emit" in findings[0].message


def test_obs_metrics_readme_table_in_sync():
    # the repo-level drift check: the committed README metrics table
    # must match what --write-readme would generate
    assert obs_metrics.check_repo(REPO_ROOT) == []


def test_obs_metrics_repo_check_detects_drift(tmp_path):
    pkg = tmp_path / PACKAGE / "obs"
    pkg.mkdir(parents=True)
    real = REPO_ROOT / PACKAGE / "obs" / "metrics.py"
    (pkg / "metrics.py").write_text(real.read_text(encoding="utf-8"),
                                    encoding="utf-8")
    readme = tmp_path / "README.md"
    readme.write_text("x\n<!-- obsmetrics:begin -->\nstale\n"
                      "<!-- obsmetrics:end -->\ny\n", encoding="utf-8")
    # the standalone loader caches by module name; force a fresh load
    sys.modules.pop("mrilint_obs_metrics", None)
    findings = obs_metrics.check_repo(tmp_path)
    assert [f.key for f in findings] == ["drift"]
    obs_metrics.write_readme(tmp_path)
    assert obs_metrics.check_repo(tmp_path) == []
    sys.modules.pop("mrilint_obs_metrics", None)


def test_clean_fixture_passes_every_checker():
    src = Source(FIXTURES / "clean.py")
    for checker in CHECKERS:
        assert checker.check(src) == [], checker.__name__


def test_suppression_comment_silences_env_knobs():
    # clean.py reads MRI_FIXTURE_OK raw but carries an allow() comment
    src = Source(FIXTURES / "clean.py")
    assert "MRI_FIXTURE_OK" in src.text
    assert env_knobs.check(src) == []


# -- baseline mechanics ----------------------------------------------------

def test_baseline_roundtrip_and_shrink_only(tmp_path):
    path = tmp_path / "baseline.txt"
    entries = Counter({"rule|a.py|k1": 2, "rule|b.py|k2": 1})
    write_baseline(entries, path)
    assert load_baseline(path) == entries
    # pruning intersects with current findings — it can only shrink
    current = Counter({"rule|a.py|k1": 1, "rule|c.py|new": 1})
    write_baseline(entries & current, path)
    assert load_baseline(path) == Counter({"rule|a.py|k1": 1})


def test_cli_nonzero_on_fixtures():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mrilint", "--no-baseline",
         str(FIXTURES)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    for rule in ("guarded-by", "env-knobs", "exit-code", "lifecycle"):
        assert f"[{rule}]" in proc.stdout


# -- the repo-clean gate ---------------------------------------------------

def test_repo_is_clean_against_baseline():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.mrilint"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_baseline_entries_still_correspond_to_findings():
    # every baseline line must match a live finding (stale entries are
    # a failed shrink — prune with --update-baseline)
    baseline = load_baseline()
    current = Counter(f.baseline_key for f in run_lint(iter_files()))
    stale = baseline - current
    assert not stale, f"stale baseline entries: {sorted(stale)}"
