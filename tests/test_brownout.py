"""Cluster brownout suite: partial-result degradation, circuit
breakers, retry budgets, and CoDel adaptive admission.

Five layers:

* degradation primitives — the ``partial_policy`` grammar, the
  per-replica :class:`Breaker` state machine (rolling window, cooldown,
  single half-open probe, probe-gated recovery), the token-bucket
  :class:`RetryBudget`, and the :class:`_CoDelGate` admission
  controller, all under fake clocks;
* fault grammar — ``shard-blackout`` / ``overload-storm`` parse with
  their outage-shaped defaults and the ``chaos:`` sampler emits them;
* restricted-parity oracle — :class:`ShardRestrictedOracle` with full
  coverage IS the monolith, so the partial-merge contract has a
  trustworthy reference;
* router degradation — a blacked-out shard under ``fail`` policy is a
  typed ``shard_unavailable`` naming the shard at EVERY op; under
  ``allow`` the answer is flagged ``partial`` with coverage metadata
  and is byte-identical to the oracle restricted to the live shards
  (BM25 floats included), fuzzed across D in {2, 4, 8}; retries stay
  bounded when every replica refuses forever (the retry-storm
  regression); ``min_coverage`` floors degraded answers;
* daemon admission — a dispatcher stall under CoDel turns into typed
  ``overloaded`` sheds (counted, exactly-one-answer) instead of a
  silently aging queue, and the gate re-closes once delay recovers.
"""

import contextlib
import json
import time

import pytest

from test_serve import build_corpus, naive_index
from test_cluster import Client, cluster_up

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    faults,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
    _top_render,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cluster import (
    partition as part_mod,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cluster import (
    pool as pool_mod,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cluster.router import (
    parse_partial_policy,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.daemon import (
    ServeDaemon,
    _CoDelGate,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.engine import (
    create_engine,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.multi_engine import (
    ShardRestrictedOracle,
)

pytestmark = [pytest.mark.cluster, pytest.mark.serve]

daemonized = pytest.mark.daemon


@pytest.fixture(autouse=True)
def _disarm():
    faults.install(None)
    yield
    faults.install(None)


# -- partial_policy grammar ---------------------------------------------


def test_parse_partial_policy_shapes():
    assert parse_partial_policy("fail") == ("fail", 1.0)
    assert parse_partial_policy("allow") == ("allow", 0.0)
    assert parse_partial_policy("allow:min_coverage=0.5") == \
        ("allow", 0.5)
    assert parse_partial_policy(" allow:min_coverage=1 ") == \
        ("allow", 1.0)
    for bad in ("", "maybe", "allow:min_coverage=nope",
                "allow:min_coverage=1.5", "allow:max_coverage=0.5",
                3, None, ["allow"]):
        with pytest.raises(ValueError):
            parse_partial_policy(bad)


# -- circuit breaker ----------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_breaker_trips_on_windowed_failures():
    clk = FakeClock()
    b = pool_mod.Breaker(threshold=5, cooldown_s=1.0, clock=clk)
    assert b.state == b.CLOSED and b.allow()
    for _ in range(4):
        b.record_failure()
    assert b.state == b.CLOSED  # under threshold
    b.record_failure()
    assert b.state == b.OPEN
    assert not b.allow()


def test_breaker_needs_more_failures_than_successes():
    clk = FakeClock()
    b = pool_mod.Breaker(threshold=5, cooldown_s=1.0, clock=clk)
    for _ in range(6):
        b.record_success()
    for _ in range(6):
        b.record_failure()
    assert b.state == b.CLOSED  # 6 err vs 6 ok: not strictly more
    b.record_failure()
    assert b.state == b.OPEN


def test_breaker_window_expires_old_evidence():
    clk = FakeClock()
    b = pool_mod.Breaker(threshold=3, cooldown_s=1.0, clock=clk)
    b.record_failure()
    b.record_failure()
    clk.t += pool_mod.Breaker.WINDOW_S + 1  # evidence ages out
    b.record_failure()
    assert b.state == b.CLOSED


def test_breaker_half_open_single_probe_then_close_or_reopen():
    clk = FakeClock()
    b = pool_mod.Breaker(threshold=2, cooldown_s=1.0, clock=clk)
    b.record_failure()
    b.record_failure()
    assert b.state == b.OPEN and not b.allow()
    clk.t += 1.5  # cooldown passed: exactly one probe admitted
    assert b.allow()
    assert b.state == b.HALF_OPEN
    assert not b.allow()  # the probe slot is taken
    b.record_failure()  # probe failed
    assert b.state == b.OPEN and not b.allow()
    clk.t += 1.5
    assert b.allow()
    b.record_success()  # probe succeeded
    assert b.state == b.CLOSED and b.allow()
    # recovery resets the window: one stray error must not re-open
    b.record_failure()
    assert b.state == b.CLOSED


def test_breaker_health_verdict_closes():
    clk = FakeClock()
    b = pool_mod.Breaker(threshold=2, cooldown_s=1.0, clock=clk)
    b.record_failure()
    b.record_failure()
    assert b.state == b.OPEN
    b.note_ready()  # prober heard a ready healthz
    assert b.state == b.CLOSED and b.allow()


# -- retry budget -------------------------------------------------------


def test_retry_budget_token_bucket():
    bud = pool_mod.RetryBudget(0.25, cap=8.0)  # binary-exact ratio
    assert bud.tokens() == 2.0  # cold-start allowance
    assert bud.try_spend() and bud.try_spend()
    assert not bud.try_spend()  # bucket empty
    assert bud.denied == 1
    for _ in range(4):  # 4 live requests refill one token
        bud.deposit()
    assert bud.try_spend()
    assert not bud.try_spend()
    for _ in range(100):
        bud.deposit()
    assert bud.tokens() == 8.0  # capped


def test_retry_budget_ratio_zero_disables_retries():
    bud = pool_mod.RetryBudget(0.0)
    assert bud.tokens() == 0.0
    bud.deposit()
    assert not bud.try_spend()


# -- CoDel admission gate -----------------------------------------------


def test_codel_gate_disabled_at_target_zero():
    g = _CoDelGate(0.0, 0.1, clock=FakeClock())
    g.on_delay(99.0)
    assert not g.should_shed() and not g.late_shed(99.0)
    assert not g.dropping


def test_codel_gate_enters_dropping_after_full_interval():
    clk = FakeClock()
    g = _CoDelGate(0.005, 0.1, clock=clk)
    g.on_delay(0.050)  # first above-target sighting arms the clock
    assert not g.dropping
    clk.t += 0.05
    g.on_delay(0.050)  # only half an interval above target
    assert not g.dropping
    clk.t += 0.06
    g.on_delay(0.050)  # sustained a full interval: dropping
    assert g.dropping
    assert g.late_shed(0.050)
    assert not g.late_shed(0.001)
    # control law: first shed immediate, next at interval/sqrt(2)
    assert g.should_shed()
    assert not g.should_shed()
    clk.t += 0.1 / (2 ** 0.5) + 1e-6
    assert g.should_shed()
    # one below-target delay exits dropping at once
    g.on_delay(0.001)
    assert not g.dropping
    assert not g.should_shed()


def test_codel_gate_restart_resumes_near_old_rate():
    clk = FakeClock()
    g = _CoDelGate(0.005, 0.1, clock=clk)

    def drive_into_dropping():
        g.on_delay(0.05)
        clk.t += 0.11
        g.on_delay(0.05)

    drive_into_dropping()
    for _ in range(6):
        g.should_shed()
        clk.t += 1.0
    count_before = g.state()["count"]
    g.on_delay(0.001)  # recover
    drive_into_dropping()
    assert g.state()["count"] == count_before - 2


# -- fault grammar ------------------------------------------------------


def test_brownout_fault_kinds_parse_with_defaults():
    inj = faults.FaultInjector("shard-blackout:shard=1")
    assert inj.rules[0].times == -1  # an outage, not a blip
    assert inj.rules[0].shard == 1
    inj = faults.FaultInjector("shard-blackout:shard=0:times=2")
    assert inj.rules[0].times == 2  # explicit budget respected
    inj = faults.FaultInjector("overload-storm")
    assert inj.rules[0].req == 1 and inj.rules[0].times == 16
    inj = faults.FaultInjector("overload-storm:req=5:times=3")
    assert [inj.on_serve_admit(i) for i in range(1, 10)] == \
        [False] * 4 + [True] * 3 + [False] * 2


def test_chaos_sampler_emits_cluster_brownout_kinds():
    inj = faults.FaultInjector(
        "chaos:seed=11:n=12:kinds=shard-blackout,overload-storm")
    kinds = {r.kind for r in inj.rules}
    assert kinds == {"shard-blackout", "overload-storm"}
    for r in inj.rules:
        if r.kind == "shard-blackout":
            assert r.times == -1 and r.shard in (0, 1)
        else:
            assert r.req >= 1 and r.times in (8, 16, 32)
    # determinism: same seed, same schedule
    again = faults.FaultInjector(
        "chaos:seed=11:n=12:kinds=shard-blackout,overload-storm")
    assert [(r.kind, r.shard, r.req, r.times) for r in inj.rules] == \
        [(r.kind, r.shard, r.req, r.times) for r in again.rules]


# -- cluster fixtures ---------------------------------------------------

DOCS = zipf_corpus(num_docs=48, vocab_size=600, tokens_per_doc=60,
                   seed=23)


@pytest.fixture(scope="module")
def mono(tmp_path_factory):
    out = build_corpus(tmp_path_factory.mktemp("brown_mono"), DOCS)
    return out, naive_index(DOCS)


@pytest.fixture(scope="module")
def clusters(tmp_path_factory, mono):
    out, _ = mono
    src = out.parent / "list.txt"
    dirs = {}
    for d in (2, 4, 8):
        cl = tmp_path_factory.mktemp(f"brown_d{d}")
        part_mod.partition(src, d, cl)
        dirs[d] = cl
    return src, dirs


def _wait_docs_learned(router, deadline_s: float = 5.0) -> None:
    """Block until the router's background learner has the per-shard
    doc counts (so coverage reports docs_fraction, not a shard count)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        docs = router.stats()["cluster"]["docs"]
        if docs["total"] and all(d is not None
                                 for d in docs["per_shard"]):
            return
        time.sleep(0.02)
    raise AssertionError("router never learned per-shard doc counts")


# -- restricted-parity oracle -------------------------------------------


def test_oracle_full_coverage_is_the_monolith(mono):
    out, naive = mono
    eng = create_engine(str(out), engine="host")
    try:
        oracle = ShardRestrictedOracle.round_robin(
            eng, 2, covered={0, 1})
        terms = sorted(naive)[:4]
        batch = eng.encode_batch(terms)
        assert oracle.df(batch).tolist() == eng.df(batch).tolist()
        want = [None if p is None else p.tolist()
                for p in eng.postings(batch)]
        got = [None if p is None else p.tolist()
               for p in oracle.postings(batch)]
        assert got == want
        assert oracle.query_and(batch).tolist() == \
            eng.query_and(batch).tolist()
        assert oracle.query_or(batch).tolist() == \
            eng.query_or(batch).tolist()
        assert oracle.top_k_scored(batch, 10) == \
            eng.top_k_scored(batch, 10)
        assert oracle.top_k("t", 5) == eng.top_k("t", 5)
    finally:
        eng.close()


def test_oracle_restriction_drops_missing_shard_docs(mono):
    out, naive = mono
    eng = create_engine(str(out), engine="host")
    try:
        oracle = ShardRestrictedOracle.round_robin(eng, 2, covered={1})
        terms = sorted(naive)[:4]
        batch = eng.encode_batch(terms)
        # shard 1 of D=2 round-robin owns the EVEN gids
        for p in oracle.postings(batch):
            if p is not None:
                assert all(d % 2 == 0 for d in p.tolist())
        for d, _s in oracle.top_k_scored(batch, 20):
            assert d % 2 == 0
        # a term whose postings are all odd gids vanishes (None, not [])
        only_odd = [t for t, posts in naive.items()
                    if posts and all(g % 2 == 1 for g in posts)]
        if only_odd:
            got = oracle.postings(eng.encode_batch(only_odd[:1]))
            assert got[0] is None
    finally:
        eng.close()


# -- router degradation: blackout × policy × op -------------------------


@daemonized
def test_blackout_fail_policy_types_every_op(clusters):
    """Default policy: a blacked-out shard is a typed
    ``shard_unavailable`` error NAMING the shard, at every data op."""
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, _):
        _wait_docs_learned(router)
        faults.install("shard-blackout:shard=0")
        with Client(router) as c:
            ops = [
                dict(op="df", terms=["the"]),
                dict(op="postings", terms=["the"]),
                dict(op="and", terms=["the"]),
                dict(op="or", terms=["the"]),
                dict(op="top_k", terms=["the"], k=3, score="bm25"),
                dict(op="top_k", letter="t", k=3),
            ]
            for i, req in enumerate(ops):
                r = c.rpc(id=i, **req)
                assert r["error"] == "shard_unavailable", r
                assert r["shard"] == 0
        st = router.stats()
        assert st["counters"]["shard_unavailable"] >= len(ops)
        assert st["counters"]["partial"] == 0


@daemonized
def test_blackout_allow_policy_answers_partial(clusters, mono):
    """``allow``: the gathered answer is flagged partial with coverage
    metadata and equals the monolith restricted to the live shard —
    BM25 floats byte-identical through the JSON round-trip."""
    out, naive = mono
    _, dirs = clusters
    eng = create_engine(str(out), engine="host")
    try:
        oracle = ShardRestrictedOracle.round_robin(eng, 2, covered={1})
        terms = sorted(naive)[:3]
        batch = eng.encode_batch(terms)
        with cluster_up(dirs[2], 2) as (router, _):
            _wait_docs_learned(router)
            faults.install("shard-blackout:shard=0")
            with Client(router) as c:
                r = c.rpc(id=1, op="df", terms=terms,
                          partial_policy="allow")
                assert r["ok"] and r["partial"] is True
                cov = r["coverage"]
                assert cov["shards_answered"] == 1
                assert cov["shards_total"] == 2
                assert cov["missing"] == [0]
                assert cov["docs_fraction"] == 0.5  # 24 of 48 docs
                assert r["df"] == oracle.df(batch).tolist()

                r = c.rpc(id=2, op="postings", terms=terms,
                          partial_policy="allow")
                want = [None if p is None else p.tolist()
                        for p in oracle.postings(batch)]
                assert r["partial"] and r["postings"] == want

                r = c.rpc(id=3, op="and", terms=terms,
                          partial_policy="allow")
                assert r["docs"] == oracle.query_and(batch).tolist()

                r = c.rpc(id=4, op="or", terms=terms,
                          partial_policy="allow")
                assert r["docs"] == oracle.query_or(batch).tolist()

                r = c.rpc(id=5, op="top_k", terms=terms, k=7,
                          score="bm25", partial_policy="allow")
                want = [[doc, score] for doc, score
                        in oracle.top_k_scored(batch, 7)]
                assert r["partial"] and r["docs"] == want  # floats exact

                r = c.rpc(id=6, op="top_k", letter="t", k=4,
                          partial_policy="allow")
                want = [[t.decode("ascii"), int(df)] for t, df
                        in oracle.top_k("t", 4)]
                assert r["partial"] and r["top"] == want
                assert r["coverage"]["missing"] == [0]
            st = router.stats()
            assert st["counters"]["partial"] >= 6
            assert st["counters"]["shard_unavailable"] == 0
    finally:
        eng.close()


@daemonized
@pytest.mark.parametrize("d", [2, 4, 8])
def test_blackout_partial_parity_fuzz(clusters, mono, d):
    """Fuzz across D: with one shard blacked out, every degraded answer
    matches the shard-restricted oracle exactly."""
    import random

    out, naive = mono
    _, dirs = clusters
    vocab = sorted(naive)
    rng = random.Random(500 + d)
    dead = rng.randrange(d)
    eng = create_engine(str(out), engine="host")
    try:
        oracle = ShardRestrictedOracle.round_robin(
            eng, d, covered=set(range(d)) - {dead})
        with cluster_up(dirs[d], d) as (router, _):
            _wait_docs_learned(router)
            faults.install(f"shard-blackout:shard={dead}")
            with Client(router) as c:
                for i in range(12):
                    terms = rng.sample(vocab, rng.randint(1, 4))
                    batch = eng.encode_batch(terms)
                    r = c.rpc(id=i, op="df", terms=terms,
                              partial_policy="allow")
                    assert r["ok"] and r["coverage"]["missing"] == \
                        [dead]
                    assert r["df"] == oracle.df(batch).tolist()
                    r = c.rpc(id=i, op="or", terms=terms,
                              partial_policy="allow")
                    assert r["docs"] == \
                        oracle.query_or(batch).tolist()
                    k = rng.randint(1, 10)
                    r = c.rpc(id=i, op="top_k", terms=terms, k=k,
                              score="bm25", partial_policy="allow")
                    want = [[doc, score] for doc, score
                            in oracle.top_k_scored(batch, k)]
                    assert r["docs"] == want
    finally:
        eng.close()


@daemonized
def test_min_coverage_floor_rejects_thin_answers(clusters):
    """allow:min_coverage above the surviving fraction: typed failure
    WITH the coverage block, so the client sees how short it fell."""
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, _):
        _wait_docs_learned(router)
        faults.install("shard-blackout:shard=0")
        with Client(router) as c:
            r = c.rpc(id=1, op="df", terms=["the"],
                      partial_policy="allow:min_coverage=0.9")
            assert r["error"] == "shard_unavailable"
            assert r["coverage"]["docs_fraction"] == 0.5
            assert r["shard"] == 0
            # floor at/below the surviving fraction still answers
            r = c.rpc(id=2, op="df", terms=["the"],
                      partial_policy="allow:min_coverage=0.5")
            assert r["ok"] and r["partial"] is True


@daemonized
def test_bad_partial_policy_is_bad_request(clusters):
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, _), Client(router) as c:
        r = c.rpc(id=1, op="df", terms=["the"],
                  partial_policy="sometimes")
        assert r["error"] == "bad_request"
        assert "partial_policy" in r["detail"]


@daemonized
def test_env_default_policy_applies(clusters, monkeypatch):
    monkeypatch.setenv("MRI_CLUSTER_PARTIAL", "allow")
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, _):
        assert router.partial_default == ("allow", 0.0)
        _wait_docs_learned(router)
        faults.install("shard-blackout:shard=1")
        with Client(router) as c:
            r = c.rpc(id=1, op="df", terms=["the"])  # no per-request
            assert r["ok"] and r["partial"] is True
            assert r["coverage"]["missing"] == [1]


# -- bounded retries under persistent refusal (the storm regression) ----


@daemonized
def test_retries_bounded_when_every_replica_refuses(clusters):
    """Every replica of every shard sheds forever (overload storm):
    the router must answer a typed error promptly with a BOUNDED
    number of shard RPCs — no retry storm, no hang — even with the
    budget knob giving it cold-start tokens."""
    _, dirs = clusters
    with cluster_up(dirs[2], 2, replicas=2) as (router, _):
        base = router.stats()["counters"]["scatter_rpcs"]
        faults.install("overload-storm:req=1:times=-1")
        with Client(router) as c:
            t0 = time.monotonic()
            r = c.rpc(id=1, op="df", terms=["the"])
            elapsed = time.monotonic() - t0
        assert r["error"] == "shard_unavailable"
        assert elapsed < 5.0  # typed failure, not a deadline crawl
        st = router.stats()["counters"]
        # per leg: at most the attempt cap (3 passes over 2 replicas)
        assert st["scatter_rpcs"] - base <= 2 * 6 + 4
        assert st["retry_denied"] >= 1


@daemonized
def test_breaker_opens_under_blackout_and_recovers(clusters):
    """Sustained blackout walks the shard's breakers open (visible in
    stats/healthz); disarming the fault lets the health prober close
    them again — probe-gated recovery, no manual reset."""
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, _):
        _wait_docs_learned(router)
        faults.install("shard-blackout:shard=0")
        with Client(router) as c:
            for i in range(12):
                r = c.rpc(id=i, op="df", terms=["the"],
                          partial_policy="allow")
                assert r["ok"]
            deadline = time.monotonic() + 5.0
            opened = 0
            while time.monotonic() < deadline:
                opened = router.stats()["cluster"]["breakers_open"]
                if opened:
                    break
                c.rpc(id=99, op="df", terms=["the"],
                      partial_policy="allow")
            assert opened >= 1
            h = c.rpc(id=100, op="healthz")
            assert h["breakers_open"] >= 1
            # recovery: disarm, wait for the prober to re-close
            faults.install(None)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if router.stats()["cluster"]["breakers_open"] == 0:
                    break
                time.sleep(0.05)
            assert router.stats()["cluster"]["breakers_open"] == 0
            r = c.rpc(id=101, op="df", terms=["the"])
            assert r["ok"] and "partial" not in r


@daemonized
def test_breaker_state_in_metrics_and_top(clusters):
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, _), Client(router) as c:
        text = c.rpc(id=1, op="metrics")["text"]
        assert "mri_cluster_breakers_open 0" in text
        assert "mri_cluster_breaker_state_s0_r0 0" in text
        assert "mri_cluster_breaker_state_s1_r0 0" in text
        st = c.rpc(id=2, op="stats")["stats"]
        sample = {"healthz": c.rpc(id=3, op="healthz"),
                  "stats": st, "slo": {}}
        frame = _top_render("r:1", sample)
        assert "breaker" in frame
        assert "closed" in frame
        assert "coverage: 2/2 shards answerable" in frame
        assert "DEGRADED" not in frame


def test_top_render_flags_degraded_fleet():
    sample = {
        "healthz": {"ready": True, "status": "ok", "reasons": []},
        "stats": {
            "queue_depth": 0, "inflight": 0, "connections": 1,
            "counters": {}, "rolling": {},
            "cluster": {
                "partial_default": "allow", "breakers_open": 1,
                "shards": [
                    {"shard": 0, "p95_ms": 1.0, "replicas": [
                        {"addr": "h:1", "ready": False,
                         "reasons": ["connection_lost"],
                         "primary": True, "breaker": "open"}]},
                    {"shard": 1, "p95_ms": 1.0, "replicas": [
                        {"addr": "h:2", "ready": True, "reasons": [],
                         "primary": True, "breaker": "closed"}]},
                ]},
        },
        "slo": {},
    }
    frame = _top_render("r:1", sample)
    assert "coverage: 1/2 shards answerable" in frame
    assert "[DEGRADED]" in frame
    assert "open" in frame and "breakers_open=1" in frame


# -- daemon CoDel admission ---------------------------------------------


@daemonized
def test_codel_sheds_typed_overloaded_under_stall(mono, monkeypatch):
    """A wedged dispatcher with CoDel armed: queued requests that aged
    past target are shed as typed ``overloaded`` answers (counted),
    every request gets exactly one answer, and the gate re-closes once
    the queue drains."""
    out, _ = mono
    monkeypatch.setenv("MRI_SERVE_CODEL_TARGET_MS", "1")
    monkeypatch.setenv("MRI_SERVE_CODEL_INTERVAL_MS", "5")
    # hang EVERY one of the first few batch pickups: a single stall
    # lets the dispatcher drain the whole backlog within one CoDel
    # interval, never sustaining the over-target delay the gate needs
    faults.install("dispatcher-hang:ms=120:times=4")
    # queue deep enough that the fixed bound never fires (every shed
    # must come from the CoDel gate) and batches small enough that the
    # backlog spans several hung pickups instead of draining in one
    daemon = ServeDaemon(str(out), coalesce_us=0, queue_depth=2048,
                         max_batch=32)
    daemon.start()
    try:
        with Client(daemon) as c:
            n = 300
            for i in range(n):
                c.send(id=i, op="df", terms=["the"])
            got = [c.recv() for _ in range(n)]
        assert sorted(r["id"] for r in got) == list(range(n))
        ok = [r for r in got if r.get("ok")]
        shed = [r for r in got if r.get("error") == "overloaded"]
        assert len(ok) + len(shed) == n
        assert shed, "CoDel shed nothing under a 400ms stall"
        assert any("CoDel" in r["detail"] for r in shed)
        st = daemon.stats()
        assert st["counters"]["codel_sheds"] >= len(shed)
        assert st["config"]["codel_target_ms"] == 1.0
        # drained queue: the gate stays dropping (admission sheds at
        # the control-law cadence) until one request slips through,
        # reports a below-target delay, and re-closes it.  Probe with
        # a fresh term each time so every probe is a result-cache miss
        # that must actually transit the queue.
        with Client(daemon) as c:
            deadline = time.monotonic() + 5.0
            recovered = False
            i = 999
            while time.monotonic() < deadline and not recovered:
                recovered = c.rpc(id=i, op="df",
                                  terms=[f"novel{i}"]).get("ok", False)
                i += 1
                time.sleep(0.01)
            assert recovered
            assert daemon.stats()["codel"]["dropping"] is False
    finally:
        daemon.drain()


@daemonized
def test_codel_off_by_default_keeps_fixed_queue_semantics(mono):
    out, _ = mono
    daemon = ServeDaemon(str(out), coalesce_us=0)
    daemon.start()
    try:
        assert daemon.stats()["config"]["codel_target_ms"] == 0.0
        with Client(daemon) as c:
            r = c.rpc(id=1, op="df", terms=["the"])
            assert r["ok"]
        assert daemon.stats()["counters"]["codel_sheds"] == 0
    finally:
        daemon.drain()


@daemonized
def test_overload_storm_feeds_router_breakers(clusters):
    """A shard daemon in a (injected) sustained overload storm: the
    router converts the typed ``overloaded`` refusals into breaker
    pressure instead of hammering the replica."""
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, daemons):
        _wait_docs_learned(router)
        # the injector is process-global, so every daemon storms —
        # what matters is that each refusal lands as breaker evidence
        # and the router's answer stays typed and bounded
        faults.install("overload-storm:req=1:times=-1")
        with Client(router) as c:
            r = c.rpc(id=1, op="df", terms=["the"],
                      partial_policy="allow")
            # every shard refuses: nothing to answer from
            assert r["error"] == "shard_unavailable"
        st = router.stats()["counters"]
        assert st["shard_errors"] >= 2
        assert st["retry_denied"] >= 1
