"""io subsystem: window arenas, byte-window planning, the prefetching
reader, and the pipelined cpu path's byte-identity to the legacy
one-shot call and the oracle.

The arenas are the zero-copy seam between the manifest readers and the
native scan (`mri_hidx_feed` consumes their raw pointers with the GIL
released), so the equivalence tests here are what lets the perf path
skip the join/marshal copies without a parity risk.
"""

import numpy as np
import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    build_index,
    native,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    load_documents,
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.io import (
    PipelinedWindowReader,
    WindowArena,
    plan_byte_windows,
    read_window_into,
)


def _small_manifest(tmp_path, num_docs=23, seed=11):
    docs = zipf_corpus(num_docs=num_docs, vocab_size=400,
                       tokens_per_doc=60, seed=seed)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    return read_manifest(tmp_path / "list.txt")


# -- WindowArena ------------------------------------------------------


def test_arena_roundtrip_and_views():
    a = WindowArena(byte_capacity=8, doc_capacity=2)
    a.append_bytes(5, b"hello")
    a.append_bytes(9, b" world of arenas")  # forces byte growth
    a.append_bytes(2, b"x")                 # forces doc growth
    buf, ends, ids = a.feed_views()
    assert buf.dtype == np.uint8 and ends.dtype == np.int64
    assert ids.dtype == np.int32
    assert bytes(buf) == b"hello world of arenasx"
    assert ends.tolist() == [5, 21, 22]
    assert ids.tolist() == [5, 9, 2]
    assert a.contents() == [b"hello", b" world of arenas", b"x"]


def test_arena_growth_preserves_committed_prefix():
    a = WindowArena(byte_capacity=4, doc_capacity=1)
    a.append_bytes(0, b"abc")
    # an oversized view must not clobber what's already committed
    v = a.view(64)
    v[:3] = b"def"
    a.commit(1, 3)
    assert a.contents() == [b"abc", b"def"]


def test_arena_short_read_commit():
    a = WindowArena()
    v = a.view(100)
    v[:7] = b"short!!"
    a.commit(3, 7)  # source shrank: commit fewer bytes than viewed
    buf, ends, ids = a.feed_views()
    assert bytes(buf) == b"short!!"
    assert ends.tolist() == [7] and ids.tolist() == [3]


def test_arena_reset_reuses_buffer():
    a = WindowArena(byte_capacity=16, doc_capacity=4)
    a.append_bytes(0, b"first window")
    backing = a._buf
    a.reset()
    a.append_bytes(1, b"second")
    assert a._buf is backing  # same pages, no fresh allocation
    assert a.contents() == [b"second"]


# -- planning + window reads ------------------------------------------


def test_plan_byte_windows_covers_manifest(tmp_path):
    m = _small_manifest(tmp_path)
    windows = plan_byte_windows(m, target_bytes=1 << 10)
    assert windows[0][0] == 0 and windows[-1][1] == len(m)
    for (_, hi), (lo, _) in zip(windows, windows[1:]):
        assert hi == lo  # contiguous, no gaps or overlap
    assert len(windows) > 1  # the target actually splits this corpus


def test_plan_byte_windows_single_window(tmp_path):
    m = _small_manifest(tmp_path)
    assert plan_byte_windows(m, target_bytes=1 << 30) == [(0, len(m))]


class _FakeManifest:
    """Sizes-only duck manifest for planner edge cases."""

    def __init__(self, sizes):
        self.sizes = tuple(sizes)
        self.paths = tuple(f"<doc{i}>" for i in range(len(sizes)))

    def __len__(self):
        return len(self.sizes)


def test_plan_byte_windows_empty_manifest():
    assert plan_byte_windows(_FakeManifest([]), target_bytes=1024) == []


def test_plan_byte_windows_single_oversized_doc():
    # one doc larger than the target: exactly one whole-doc window,
    # never a split mid-document
    assert plan_byte_windows(_FakeManifest([1 << 20]),
                             target_bytes=4096) == [(0, 1)]


def test_plan_byte_windows_all_zero_sizes():
    # unstat-able files keep size 0 (manifest contract): the running
    # total never reaches the target, so everything lands in one
    # trailing window instead of producing per-doc degenerate windows
    assert plan_byte_windows(_FakeManifest([0, 0, 0, 0]),
                             target_bytes=1) == [(0, 4)]


def test_read_window_into_matches_load_documents(tmp_path):
    m = _small_manifest(tmp_path)
    contents, doc_ids = load_documents(m)
    arena = read_window_into(m, 0, len(m), WindowArena())
    assert arena.contents() == contents
    _, _, ids = arena.feed_views()
    assert ids.tolist() == list(doc_ids)


def test_read_window_into_virtual_manifest_fallback():
    # duck-typed manifest with only read_doc(): the copy fallback path
    class Virtual:
        sizes = [4, 6]
        paths = ["<v0>", "<v1>"]

        def __len__(self):
            return 2

        def doc_id(self, i):
            return i + 1

        def read_doc(self, i):
            return [b"aaaa", b"bbbbbb"][i]

    arena = read_window_into(Virtual(), 0, 2, WindowArena())
    assert arena.contents() == [b"aaaa", b"bbbbbb"]


# -- PipelinedWindowReader --------------------------------------------


def test_reader_yields_every_window_in_order(tmp_path):
    m = _small_manifest(tmp_path)
    windows = plan_byte_windows(m, target_bytes=1 << 10)
    contents, _ = load_documents(m)
    reader = PipelinedWindowReader(m, windows, depth=2)
    seen = []
    for arena in reader:
        seen.extend(arena.contents())
        reader.recycle(arena)
    assert seen == contents
    assert reader.read_busy_s >= 0.0


def test_reader_reuses_caller_ring(tmp_path):
    m = _small_manifest(tmp_path)
    windows = plan_byte_windows(m, target_bytes=1 << 10)
    ring = [WindowArena(byte_capacity=1 << 12) for _ in range(3)]
    reader = PipelinedWindowReader(m, windows, depth=2, arenas=ring)
    assert reader.arenas is ring
    for arena in reader:
        assert arena in ring
        reader.recycle(arena)


def test_reader_propagates_source_exception():
    class Broken:
        sizes = [4]
        paths = ["<b0>"]

        def __len__(self):
            return 1

        def doc_id(self, i):
            return i

        def read_doc(self, i):
            raise ValueError("corrupt source")

    reader = PipelinedWindowReader(Broken(), [(0, 1)], depth=1)
    with pytest.raises(ValueError, match="corrupt source"):
        for arena in reader:
            reader.recycle(arena)


def test_reader_close_joins_abandoned_thread(tmp_path):
    """Regression: abandoning the iterator mid-loop used to leave the
    daemon reader thread alive until process exit; close() must join
    it (and stay idempotent)."""
    m = _small_manifest(tmp_path)
    windows = plan_byte_windows(m, target_bytes=256)
    assert len(windows) > 2
    reader = PipelinedWindowReader(m, windows, depth=1)
    it = iter(reader)
    reader.recycle(next(it))  # consume one window, then walk away
    assert reader.close() is True
    assert not reader._thread.is_alive()
    assert reader.close() is True


def test_reader_context_manager_joins(tmp_path):
    m = _small_manifest(tmp_path)
    windows = plan_byte_windows(m, target_bytes=256)
    with PipelinedWindowReader(m, windows, depth=1) as reader:
        next(iter(reader))  # not even recycled: close must still win
    assert not reader._thread.is_alive()


# -- zero-copy feed + whole-path equivalence --------------------------


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
def test_feed_arrays_matches_feed_lists(tmp_path):
    m = _small_manifest(tmp_path)
    contents, doc_ids = load_documents(m)
    arena = read_window_into(m, 0, len(m), WindowArena())

    with native.HostIndexStream() as s1:
        s1.feed_arrays(*arena.feed_views())
        stats1 = s1.finalize_emit(tmp_path / "arrays")
    with native.HostIndexStream() as s2:
        s2.feed(contents, doc_ids)
        stats2 = s2.finalize_emit(tmp_path / "lists")

    assert read_letter_files(tmp_path / "arrays") == \
        read_letter_files(tmp_path / "lists")
    assert stats1["unique_terms"] == stats2["unique_terms"]
    assert stats1["tokens"] == stats2["tokens"]


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
def test_pipelined_cpu_matches_legacy_and_oracle(tmp_path):
    m = _small_manifest(tmp_path, num_docs=41, seed=3)
    oracle_index(m, tmp_path / "oracle")
    r = build_index(m, IndexConfig(backend="cpu", host_threads=1,
                                   io_prefetch=2),
                    output_dir=tmp_path / "pipe")
    build_index(m, IndexConfig(backend="cpu", host_threads=1,
                               io_prefetch=0),
                output_dir=tmp_path / "legacy")
    golden = read_letter_files(tmp_path / "oracle")
    assert read_letter_files(tmp_path / "pipe") == golden
    assert read_letter_files(tmp_path / "legacy") == golden
    # the pipelined run reports its stage split
    for key in ("stage_read_ms", "stage_tokenize_ms", "stage_emit_ms"):
        assert key in r


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
def test_pipelined_many_tiny_windows(tmp_path, monkeypatch):
    """Window-boundary stress: one document per window."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.models import (
        inverted_index as mod,
    )

    monkeypatch.setattr(mod.InvertedIndexModel, "_CPU_WINDOW_BYTES", 1)
    m = _small_manifest(tmp_path, num_docs=17, seed=8)
    oracle_index(m, tmp_path / "oracle")
    build_index(m, IndexConfig(backend="cpu", host_threads=1,
                               io_prefetch=3),
                output_dir=tmp_path / "tiny")
    assert read_letter_files(tmp_path / "tiny") == \
        read_letter_files(tmp_path / "oracle")
