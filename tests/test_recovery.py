"""In-run fault tolerance: leased re-execution, reducer takeover, audit.

The tentpole invariant: a worker or reducer death INSIDE a run must be
invisible in the output — survivors (or a respawned replacement) rescan
the dead worker's windows and the letter files come out byte-identical
to a fault-free run, at every (K, M) and every death point.  Only when
the respawn budget is exhausted with no survivors does the run degrade
(exit 3) — and then it says exactly which documents were lost.

The audit layer's job is the opposite direction: prove that a bug in
THIS recovery machinery (a silently dropped window) can never produce a
plausible-but-wrong index without failing loudly first.
"""

import json
import time

import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    build_index,
    faults,
    native,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.audit import (
    MANIFEST_NAME,
    AuditError,
    WindowLedger,
    verify_output_dir,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
    main,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.scheduler import (
    StealQueue,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.io.reader import (
    plan_byte_windows,
)

pytestmark = [pytest.mark.faults, pytest.mark.parallel_host]

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="no C++ toolchain")

_WINDOW_BYTES = 512  # tiny windows: ~16 windows over the 29-doc corpus


@pytest.fixture(autouse=True)
def _disarm(monkeypatch):
    """No injector armed before/after, fresh run report, tiny windows."""
    monkeypatch.setenv("MRI_CPU_WINDOW_BYTES", str(_WINDOW_BYTES))
    faults.install(None)
    faults.begin_run()
    yield
    faults.install(None)
    faults.begin_run()


@pytest.fixture(scope="session")
def corpus(tmp_path_factory):
    """One 29-doc corpus + its oracle golden for the whole module
    (every recovery run must reproduce these exact bytes)."""
    root = tmp_path_factory.mktemp("recovery")
    docs = zipf_corpus(num_docs=29, vocab_size=500,
                       tokens_per_doc=60, seed=13)
    paths = write_corpus(root / "docs", docs)
    write_manifest(root / "list.txt", paths)
    m = read_manifest(root / "list.txt")
    oracle_index(m, root / "golden")
    return m, read_letter_files(root / "golden")


def _list_path(manifest):
    """The manifest file the corpus fixture wrote (docs live one level
    below it) — for CLI-level tests that need the path, not the object."""
    from pathlib import Path

    return str(Path(manifest.paths[0]).parent.parent / "list.txt")


def _num_windows(manifest):
    return len(list(plan_byte_windows(manifest, _WINDOW_BYTES)))


def _build(manifest, out, K, M, spec=None, audit=False):
    faults.install(spec)
    faults.begin_run()
    try:
        return build_index(
            manifest,
            IndexConfig(backend="cpu", num_mappers=K, num_reducers=M,
                        io_prefetch=2, audit=audit),
            output_dir=out)
    finally:
        faults.install(None)


# -- StealQueue lease/ack contract ------------------------------------


def test_steal_queue_lease_requeue_and_blacklist():
    q = StealQueue([(0, 2), (2, 5), (5, 6)])
    assert q.pop_window(worker=0) == (1, (0, 2))
    assert q.pop_window(worker=1) == (2, (2, 5))
    q.ack(1, worker=0)  # worker 0 completed window 1
    # worker 0 dies: its lease-free COMPLETED window comes back too
    # (its native handle held that window's postings)
    assert q.fail_worker(0) == [1]
    assert q.pop_window(worker=0) is None  # blacklisted forever
    # survivor drains the requeue plus the untouched tail
    got = []
    while (item := q.pop_window(worker=1)) is not None:
        got.append(item[0])
        q.ack(item[0], worker=1)
    assert sorted(got) == [1, 3]
    q.ack(2, worker=1)
    assert q.outstanding() == 0


def test_steal_queue_leased_windows_requeue_on_failure():
    q = StealQueue([(i, i + 1) for i in range(4)])
    for _ in range(3):
        q.pop_window(worker=2)  # three outstanding leases, no acks
    assert q.fail_worker(2) == [1, 2, 3]
    assert len(q) == 4  # all four hand-outs still ahead
    assert q.outstanding() == 0


def test_steal_queue_late_ack_from_retired_worker_dropped():
    q = StealQueue([(0, 1), (1, 2)])
    q.pop_window(worker=0)
    q.fail_worker(0)
    q.ack(1, worker=0)  # zombie thread wakes up and acks: ignored
    got = [q.pop_window(worker=1)[0] for _ in range(2)]
    assert sorted(got) == [1, 2]  # window 1 still got re-executed


def test_steal_queue_expired_workers_watchdog():
    q = StealQueue([(0, 1), (1, 2)])
    q.pop_window(worker=0)
    q.pop_window(worker=1)
    q.ack(2, worker=1)
    time.sleep(0.05)
    assert q.expired_workers(0.01) == {0}  # 1 acked in time
    assert q.expired_workers(10.0) == set()


# -- worker death: byte-identical recovery matrix ---------------------


@needs_native
@pytest.mark.parametrize("mappers", [2, 4])
@pytest.mark.parametrize("reducers", [1, 3, 26])
@pytest.mark.parametrize("position", ["early", "middle", "last"])
def test_worker_death_byte_identical(tmp_path, corpus, mappers, reducers,
                                     position):
    m, golden = corpus
    n = _num_windows(m)
    window = {"early": 1, "middle": n // 2, "last": n}[position]
    stats = _build(m, tmp_path / "out", mappers, reducers,
                   spec=f"worker-death:window={window}")
    d = stats["degradation"]
    assert d["worker_recoveries"] >= 1
    assert d["windows_requeued"] >= 1
    assert d["skipped_docs"] == []  # recovery is not degradation
    assert read_letter_files(tmp_path / "out") == golden


@needs_native
def test_two_worker_deaths_one_run(tmp_path, corpus):
    m, golden = corpus
    stats = _build(m, tmp_path / "out", 4, 3,
                   spec="worker-death:worker=1:window=0;"
                        "worker-death:worker=2:window=0")
    assert stats["degradation"]["worker_recoveries"] == 2
    assert read_letter_files(tmp_path / "out") == golden


@needs_native
def test_all_workers_die_respawn_drains(tmp_path, corpus):
    """Both workers die before the queue drains: the respawned
    replacement (budget default 1) rescans everything, still
    byte-identical, still exit-0 semantics."""
    m, golden = corpus
    stats = _build(m, tmp_path / "out", 2, 2,
                   spec="worker-death:worker=0:window=0;"
                        "worker-death:worker=1:window=0")
    d = stats["degradation"]
    assert d["worker_recoveries"] == 2
    assert d["skipped_docs"] == []
    assert read_letter_files(tmp_path / "out") == golden


@needs_native
def test_single_mapper_parallel_path_recovers(tmp_path, corpus):
    """K=1 with M>1 still routes through the parallel path: the lone
    worker's death leaves no survivors, only the respawn."""
    m, golden = corpus
    stats = _build(m, tmp_path / "out", 1, 2,
                   spec="worker-death:worker=0:window=2")
    assert stats["degradation"]["worker_recoveries"] == 1
    assert read_letter_files(tmp_path / "out") == golden


@needs_native
def test_scan_error_recovers(tmp_path, corpus):
    m, golden = corpus
    stats = _build(m, tmp_path / "out", 2, 3, spec="scan-error:window=3")
    assert stats["degradation"]["worker_recoveries"] >= 1
    assert read_letter_files(tmp_path / "out") == golden


@needs_native
def test_reader_death_in_parallel_path_recovers(tmp_path, corpus):
    """A silently dying reader thread surfaces as ReaderDied in its
    worker — which is now just another recoverable worker death, not a
    run-fatal error."""
    m, golden = corpus
    stats = _build(m, tmp_path / "out", 2, 2, spec="reader-death:window=2")
    assert stats["degradation"]["worker_recoveries"] >= 1
    assert read_letter_files(tmp_path / "out") == golden


@needs_native
def test_respawn_budget_exhausted_degrades_not_dies(tmp_path, corpus,
                                                    monkeypatch):
    monkeypatch.setenv("MRI_WORKER_RESPAWNS", "0")
    m, golden = corpus
    stats = _build(m, tmp_path / "out", 2, 2,
                   spec="worker-death:worker=0:window=0;"
                        "worker-death:worker=1:window=0")
    d = stats["degradation"]
    assert d["worker_recoveries"] == 2
    assert d["skipped_docs"]  # real data loss is REPORTED data loss
    # the run still completes: all 26 letter files exist
    for i in range(26):
        assert (tmp_path / "out" / f"{chr(ord('a') + i)}.txt").exists()
    assert read_letter_files(tmp_path / "out") != golden


@needs_native
def test_budget_exhausted_is_cli_exit_3(tmp_path, corpus, monkeypatch,
                                        capsys):
    monkeypatch.setenv("MRI_WORKER_RESPAWNS", "0")
    m, _ = corpus
    rc = main(["2", "2", _list_path(m), "--backend", "cpu",
               "--output-dir", str(tmp_path / "out"),
               "--fault-spec", "worker-death:worker=0:window=0;"
                               "worker-death:worker=1:window=0"])
    assert rc == faults.EXIT_DEGRADED
    assert "DEGRADED" in capsys.readouterr().err


@needs_native
def test_lease_deadline_watchdog_never_hangs(tmp_path, corpus,
                                             monkeypatch):
    """A worker wedged in a slow read past MRI_WINDOW_DEADLINE_S is
    retired in absentia.  Whichever worker the slow window lands on,
    the run must finish quickly and byte-identically — the watchdog
    exists so a wedge can never become a hang."""
    monkeypatch.setenv("MRI_WINDOW_DEADLINE_S", "0.25")
    m, golden = corpus
    t0 = time.monotonic()
    stats = _build(m, tmp_path / "out", 2, 2, spec="slow-read:doc=5:ms=900")
    assert time.monotonic() - t0 < 30
    assert stats["degradation"]["skipped_docs"] == []
    assert stats["degradation"]["worker_recoveries"] in (0, 1)
    assert read_letter_files(tmp_path / "out") == golden


# -- reducer takeover -------------------------------------------------


@needs_native
@pytest.mark.parametrize("reducers,dead", [
    (1, 0), (3, 0), (3, 1), (3, 2), (26, 0), (26, 12), (26, 25),
])
def test_reducer_death_range_reemitted(tmp_path, corpus, reducers, dead):
    m, golden = corpus
    stats = _build(m, tmp_path / "out", 2, reducers,
                   spec=f"reducer-death:reducer={dead}")
    assert stats["degradation"]["reducer_takeovers"] == 1
    assert stats["degradation"]["skipped_docs"] == []
    assert read_letter_files(tmp_path / "out") == golden


@needs_native
def test_worker_and_reducer_death_same_run(tmp_path, corpus):
    m, golden = corpus
    stats = _build(m, tmp_path / "out", 4, 3,
                   spec="worker-death:window=2;reducer-death:reducer=1",
                   audit=True)
    d = stats["degradation"]
    assert d["worker_recoveries"] >= 1 and d["reducer_takeovers"] == 1
    assert read_letter_files(tmp_path / "out") == golden


# -- integrity audit --------------------------------------------------


def test_window_ledger_names_dropped_window():
    led = WindowLedger()
    for wi in (1, 3):
        led.record(wi, worker=0, docs=2, nbytes=10, checksum=wi)
    with pytest.raises(AuditError, match="window 2"):
        led.check_complete(3)


def test_window_ledger_discard_then_reexecute():
    led = WindowLedger()
    led.record(1, worker=0, docs=2, nbytes=10, checksum=7)
    led.record(2, worker=1, docs=2, nbytes=10, checksum=8)
    assert led.discard_worker(0) == 1
    led.record(1, worker=2, docs=2, nbytes=10, checksum=7)  # rescan
    led.record(3, worker=0, docs=1, nbytes=5, checksum=9)  # zombie: ignored
    with pytest.raises(AuditError, match="window 3"):
        led.check_complete(3)
    led.record(3, worker=1, docs=1, nbytes=5, checksum=9)
    led.check_complete(3)  # complete now


def test_window_ledger_double_feed_is_an_error():
    led = WindowLedger()
    led.record(1, worker=0, docs=2, nbytes=10, checksum=7)
    led.record(1, worker=1, docs=2, nbytes=10, checksum=7)
    with pytest.raises(AuditError, match="more than once"):
        led.check_complete(1)


@needs_native
def test_audit_passes_on_clean_and_recovered_runs(tmp_path, corpus):
    m, golden = corpus
    for name, spec in (("clean", None), ("rec", "worker-death:window=2")):
        out = tmp_path / name
        stats = _build(m, out, 2, 3, spec=spec, audit=True)
        assert stats["audit_ms"] > 0
        assert read_letter_files(out) == golden
        manifest_doc = json.loads((out / MANIFEST_NAME).read_text())
        assert len(manifest_doc["files"]) == 26
        ok, problems = verify_output_dir(out)
        assert ok, problems


@needs_native
def test_audit_catches_silently_dropped_window(tmp_path, corpus):
    """THE reason the audit exists: a window dropped without an
    exception must fail loudly, naming the window — never exit 0 with
    missing postings."""
    m, _ = corpus
    with pytest.raises(AuditError, match="window 2"):
        _build(m, tmp_path / "out", 2, 2,
               spec="scan-error:window=2:silent=1", audit=True)


@needs_native
def test_silent_drop_without_audit_is_wrong_bytes(tmp_path, corpus):
    """Control for the test above: without --audit the same fault DOES
    corrupt the output — documenting exactly what the audit buys."""
    m, golden = corpus
    _build(m, tmp_path / "out", 2, 2,
           spec="scan-error:window=2:silent=1", audit=False)
    assert read_letter_files(tmp_path / "out") != golden


@needs_native
def test_verify_detects_post_run_tampering(tmp_path, corpus):
    m, _ = corpus
    _build(m, tmp_path / "out", 2, 2, audit=True)
    (tmp_path / "out" / "a.txt").write_bytes(b"tampered:[1]\n")
    ok, problems = verify_output_dir(tmp_path / "out")
    assert not ok and any("a.txt" in p for p in problems)


@needs_native
def test_cli_verify_mode_exit_codes(tmp_path, corpus, capsys):
    m, _ = corpus
    _build(m, tmp_path / "out", 2, 2, audit=True)
    assert main(["--verify", str(tmp_path / "out")]) == 0
    (tmp_path / "out" / "b.txt").write_bytes(b"x:[2]\n")
    assert main(["--verify", str(tmp_path / "out")]) == 2
    assert "b.txt" in capsys.readouterr().err
