"""Emit backend parity + crash durability.

The native vectorized emit (tokenizer.cc EmitLettersRuns) and the
pure-Python formatter are byte-identical by contract — the Python path
is the oracle the native one is judged against.  Both write each letter
file atomically (tmp + rename), so a crash mid-emit can leave a letter
missing but never truncated-but-plausible; the kill-mid-emit test
proves exactly that with a real SIGKILL.
"""

import os
import signal
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from conftest import REPO_ROOT, read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
    native,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text import (
    formatter,
)

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="no C++ toolchain")


def _emit_arrays(rng, n_terms, max_doc_id, letters="abcdefghijklmnopqrstuvwxyz"):
    """Random but well-formed device-engine output arrays: sorted 'S'
    vocab, (letter asc, df desc, word asc) order, ascending per-term
    postings."""
    alphabet = np.frombuffer(letters.encode(), np.uint8)
    words = set()
    while len(words) < n_terms:
        n = int(rng.integers(1, 18))
        words.add(bytes(rng.choice(alphabet, size=n)))
    vocab_list = sorted(words)
    width = max((len(w) for w in vocab_list), default=1)
    vocab = np.array(vocab_list, dtype=f"S{width}")
    letters_of = np.array([w[0] - ord("a") for w in vocab_list], np.int64)
    df = rng.integers(1, min(max_doc_id + 1, 7) + 1, size=n_terms).astype(np.int64)
    offsets = np.cumsum(df) - df
    postings = np.concatenate([
        np.sort(rng.choice(max_doc_id + 1, size=int(d), replace=False))
        for d in df]).astype(np.int32) if n_terms else np.empty(0, np.int32)
    order = np.lexsort((vocab, -df, letters_of))
    return vocab, letters_of, order, df, offsets, postings


def _emit_both(tmp_path, arrays, max_doc_id):
    vocab, letters_of, order, df, offsets, postings = arrays
    for backend in ("python", "native"):
        formatter.emit_index(
            tmp_path / backend, vocab=vocab, letter_of_term=letters_of,
            order=order, df=df, offsets=offsets, postings=postings,
            max_doc_id=max_doc_id, backend=backend)
    assert read_letter_files(tmp_path / "native") == \
        read_letter_files(tmp_path / "python")


@needs_native
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_native_emit_matches_python_random(tmp_path, seed):
    rng = np.random.default_rng(seed)
    arrays = _emit_arrays(rng, n_terms=120, max_doc_id=400)
    _emit_both(tmp_path, arrays, max_doc_id=400)


@needs_native
def test_native_emit_empty_letters(tmp_path):
    # only two first letters in play: 24 letter files must come out
    # empty (and still exist) from both writers
    rng = np.random.default_rng(7)
    arrays = _emit_arrays(rng, n_terms=30, max_doc_id=50, letters="qx")
    _emit_both(tmp_path, arrays, max_doc_id=50)
    content = (tmp_path / "native" / "a.txt").read_bytes()
    assert content == b""


@needs_native
def test_native_emit_empty_vocab(tmp_path):
    rng = np.random.default_rng(0)
    arrays = _emit_arrays(rng, n_terms=0, max_doc_id=0)
    _emit_both(tmp_path, arrays, max_doc_id=0)
    assert read_letter_files(tmp_path / "native") == b""


@needs_native
def test_native_emit_single_doc_postings(tmp_path):
    # every posting list is exactly one doc — the df==1 render edge
    # (separator patching must produce "w:[0]\n", never "w:[]\n")
    rng = np.random.default_rng(5)
    vocab, letters_of, order, df, offsets, postings = _emit_arrays(
        rng, n_terms=40, max_doc_id=0)
    assert df.tolist() == [1] * 40 and set(postings.tolist()) == {0}
    _emit_both(tmp_path, (vocab, letters_of, order, df, offsets, postings),
               max_doc_id=0)
    first_letter_file = tmp_path / "native" / (vocab[0][:1].decode() + ".txt")
    for line in first_letter_file.read_bytes().splitlines():
        assert line.endswith(b":[0]")


def test_emit_backend_python_forced(tmp_path):
    rng = np.random.default_rng(9)
    arrays = _emit_arrays(rng, n_terms=10, max_doc_id=5)
    vocab, letters_of, order, df, offsets, postings = arrays
    stats = formatter.emit_index(
        tmp_path, vocab=vocab, letter_of_term=letters_of, order=order,
        df=df, offsets=offsets, postings=postings, max_doc_id=5,
        backend="python")
    assert stats["emit_backend"] == "python"


def test_emit_backend_unknown_rejected(tmp_path):
    with pytest.raises(ValueError, match="emit backend"):
        formatter.emit_index(
            tmp_path, vocab=np.empty(0, "S1"),
            letter_of_term=np.empty(0, np.int64),
            order=np.empty(0, np.int64), df=np.empty(0, np.int64),
            offsets=np.empty(0, np.int64), postings=np.empty(0, np.int32),
            max_doc_id=0, backend="fortran")


def test_emit_backend_native_errors_when_unavailable(tmp_path, monkeypatch):
    monkeypatch.setattr(native, "load", lambda: None)
    with pytest.raises(RuntimeError, match="native"):
        formatter.emit_index(
            tmp_path, vocab=np.empty(0, "S1"),
            letter_of_term=np.empty(0, np.int64),
            order=np.empty(0, np.int64), df=np.empty(0, np.int64),
            offsets=np.empty(0, np.int64), postings=np.empty(0, np.int32),
            max_doc_id=0, backend="native")


# -- degenerate reference configs -------------------------------------


@needs_native
@pytest.mark.parametrize("mappers,reducers", [(400, 1), (4, 30), (400, 30)])
def test_degenerate_configs_backend_parity(smoke_fixture, tmp_path,
                                           mappers, reducers):
    """The reference's degenerate thread configs (more mappers than
    docs, more reducers than letters) must not disturb emit parity:
    python and native writers agree byte-for-byte and match the
    goldens."""
    m = read_manifest(smoke_fixture / "manifest.txt",
                      base_dir=smoke_fixture)
    golden = read_letter_files(smoke_fixture / "golden")
    for backend in ("python", "native"):
        out = tmp_path / backend
        # pipeline_chunk_docs=0: the one-shot engine (the multichip
        # fast path needs jax.shard_map, deprecated on this jax)
        InvertedIndexModel(IndexConfig(
            backend="tpu", num_mappers=mappers, num_reducers=reducers,
            emit_backend=backend, pad_multiple=64, device_shards=1,
            pipeline_chunk_docs=0)).run(m, output_dir=out)
        assert read_letter_files(out) == golden


# -- kill-mid-emit durability -----------------------------------------

_CHILD = """\
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text import formatter

vocab = np.array([b"ant", b"bee", b"cat", b"dog", b"eel"], dtype="S3")
letters = np.arange(5, dtype=np.int64)
df = np.array([2, 1, 3, 1, 2], dtype=np.int64)
offsets = np.cumsum(df) - df
postings = np.array([0, 1, 2, 0, 1, 2, 1, 0, 2], dtype=np.int32)
order = np.arange(5, dtype=np.int64)
formatter.emit_index(sys.argv[1], vocab=vocab, letter_of_term=letters,
                     order=order, df=df, offsets=offsets,
                     postings=postings, max_doc_id=2,
                     backend=sys.argv[2])
"""


@pytest.mark.parametrize("backend", [
    "python", pytest.param("native", marks=needs_native)])
def test_kill_mid_emit_leaves_no_truncated_file(tmp_path, backend):
    """SIGKILL after the 3rd letter: completed letters are byte-exact,
    later letters are absent or `.tmp` residue — NEVER a truncated
    `<letter>.txt` that would parse as a smaller-but-plausible index."""
    script = tmp_path / "child.py"
    script.write_text(_CHILD.format(repo=str(REPO_ROOT)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    ref_dir = tmp_path / "ref"
    subprocess.run([sys.executable, str(script), str(ref_dir), backend],
                   env=env, check=True, timeout=300)

    kill_dir = tmp_path / "killed"
    proc = subprocess.run(
        [sys.executable, str(script), str(kill_dir), backend],
        env={**env, "MRI_EMIT_KILL_AFTER_LETTERS": "3"}, timeout=300)
    assert proc.returncode == -signal.SIGKILL

    survivors = 0
    for i in range(26):
        name = f"{chr(ord('a') + i)}.txt"
        final = kill_dir / name
        if final.exists():
            # anything that looks complete must BE complete
            assert final.read_bytes() == (ref_dir / name).read_bytes()
            survivors += 1
        else:
            leftovers = list(kill_dir.glob(name + "*"))
            assert [p.suffix for p in leftovers] in ([], [".tmp"])
    assert survivors == 3  # killed right after the 3rd rename
