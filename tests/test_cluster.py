"""Scale-out cluster suite (``mri-tpu shard`` / ``mri-tpu router`` /
cluster/).

Four layers:

* merge kernels — the D-way ranked heap merge and doc-id gather that
  the router shares with MultiSegmentEngine: (score, gid) tie order,
  k larger than any part, empty parts;
* partition tool — round-robin and size-balanced assignment cover the
  corpus exactly once with ascending per-shard gid lists, the CLI's
  ``--verify`` byte-checks manifests and catches corruption, bad
  arguments are one-line exit 2s;
* router parity — a router over D shard daemons answers every data op
  BYTE-IDENTICALLY to one monolithic daemon over the same corpus,
  BM25 floats included, fuzzed across D in {1, 2, 4, 8} on the Zipf
  corpus (the global-stats sidecar is what makes this exact);
* failure envelope — injected ``shard-dead`` fails over to another
  replica (counted), a replica killed mid-burst loses zero
  acknowledged queries, hedges fire on a slowed shard, and
  ``router-conn-reset`` tears a client without tearing the router.

Daemon-spawning tests carry the ``daemon`` marker, so the conftest
leak guard asserts the router's clock/prober/pool threads and sockets
all die at drain.
"""

import contextlib
import json
import socket
import threading
import time

import pytest

from test_serve import build_corpus, naive_index

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    faults,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
    _top_render,
    main as cli_main,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cluster import (
    partition as part_mod,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cluster import (
    hedge as hedge_mod,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cluster.router import (
    RouterDaemon,
    parse_shard_arg,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.daemon import (
    ServeDaemon,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.engine import (
    create_engine,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.multi_engine import (
    merge_doc_ids,
    merge_ranked,
)

pytestmark = [pytest.mark.cluster, pytest.mark.serve]

daemonized = pytest.mark.daemon


@pytest.fixture(autouse=True)
def _disarm():
    faults.install(None)
    yield
    faults.install(None)


# -- merge kernels ------------------------------------------------------


def test_merge_ranked_tie_breaks_on_gid():
    # equal scores: the LOWER global doc id must win, matching the
    # single-engine heap's (-score, doc) order
    parts = [[(-1.5, 7), (-0.5, 9)], [(-1.5, 3), (-1.0, 4)]]
    assert merge_ranked(parts, 3) == [(3, 1.5), (7, 1.5), (4, 1.0)]


def test_merge_ranked_k_exceeds_every_part():
    parts = [[(-3.0, 1)], [(-2.0, 2)], [(-1.0, 3)]]
    assert merge_ranked(parts, 99) == [(1, 3.0), (2, 2.0), (3, 1.0)]


def test_merge_ranked_empty_and_all_empty_parts():
    assert merge_ranked([[], [(-1.0, 5)], []], 4) == [(5, 1.0)]
    assert merge_ranked([[], []], 4) == []
    assert merge_ranked([[(-1.0, 5)]], 0) == []


def test_merge_doc_ids_concatenates_and_sorts():
    out = merge_doc_ids([[1, 4], [2, 9], []])
    assert out.tolist() == [1, 2, 4, 9]
    # already-ordered disjoint runs stay intact
    assert merge_doc_ids([[1, 2], [5, 9]]).tolist() == [1, 2, 5, 9]
    assert merge_doc_ids([[], []]).tolist() == []


# -- --shards spec grammar ----------------------------------------------


def test_parse_shard_arg_shapes():
    assert parse_shard_arg("h:1,h:2") == [[("h", 1)], [("h", 2)]]
    assert parse_shard_arg("a:1|b:2,c:3") == \
        [[("a", 1), ("b", 2)], [("c", 3)]]
    for bad in ("", "h:0", "h", "h:1|,h:2", "h:99999"):
        with pytest.raises(ValueError):
            parse_shard_arg(bad)


def test_hedge_delay_policy():
    assert hedge_mod.hedge_delay_s(0, 0.5) is None          # off
    assert hedge_mod.hedge_delay_s(25.0, None) == 0.025     # fixed
    assert hedge_mod.hedge_delay_s(-1.0, None) is None      # no samples
    assert hedge_mod.hedge_delay_s(-1.0, 0.010) == 0.010    # adaptive
    assert hedge_mod.hedge_delay_s(-1.0, 1e-9) == \
        hedge_mod.MIN_HEDGE_S                               # floor


# -- partition tool -----------------------------------------------------


def _fake_paths(tmp_path, sizes):
    out = []
    for i, n in enumerate(sizes):
        p = tmp_path / f"f{i:03d}.txt"
        p.write_bytes(b"x" * n)
        out.append(str(p))
    return out


def test_assign_round_robin_tiles_ascending(tmp_path):
    paths = _fake_paths(tmp_path, [10] * 11)
    members = part_mod.assign(paths, 4, "round-robin")
    assert members[0] == [1, 5, 9]
    flat = sorted(g for m in members for g in m)
    assert flat == list(range(1, 12))
    for m in members:
        assert m == sorted(m)


def test_assign_size_balanced_covers_and_balances(tmp_path):
    sizes = [1000, 10, 10, 10, 500, 500, 10, 10]
    paths = _fake_paths(tmp_path, sizes)
    members = part_mod.assign(paths, 2, "size-balanced")
    flat = sorted(g for m in members for g in m)
    assert flat == list(range(1, 9))
    for m in members:
        assert m == sorted(m)
    loads = [sum(sizes[g - 1] for g in m) for m in members]
    # LPT puts the 1000-byte doc alone against the two 500s
    assert max(loads) <= 2 * min(loads)


def test_assign_bad_args_raise(tmp_path):
    paths = _fake_paths(tmp_path, [10, 10])
    with pytest.raises(part_mod.PartitionError):
        part_mod.assign(paths, 0)
    with pytest.raises(part_mod.PartitionError):
        part_mod.assign(paths, 3)  # more shards than docs
    with pytest.raises(part_mod.PartitionError):
        part_mod.assign(paths, 1, "nope")
    with pytest.raises(part_mod.PartitionError):
        part_mod.assign([], 1)


def test_shard_cli_exit2_contract(tmp_path):
    missing = str(tmp_path / "nope.list")
    assert cli_main(["shard", missing, "--shards", "2",
                     "--out", str(tmp_path / "cl")]) == 2


# -- cluster fixtures ---------------------------------------------------

DOCS = zipf_corpus(num_docs=48, vocab_size=600, tokens_per_doc=60,
                   seed=23)


@pytest.fixture(scope="module")
def mono(tmp_path_factory):
    """Monolithic artifact + naive oracle over the Zipf corpus."""
    out = build_corpus(tmp_path_factory.mktemp("cluster_mono"), DOCS)
    return out, naive_index(DOCS)


@pytest.fixture(scope="module")
def clusters(tmp_path_factory, mono):
    """Partitioned + built cluster dirs for D in {1, 2, 4, 8}, from
    the SAME manifest the monolith was built from."""
    out, _ = mono
    src = out.parent / "list.txt"
    dirs = {}
    for d in (1, 2, 4, 8):
        cl = tmp_path_factory.mktemp(f"cluster_d{d}")
        part_mod.partition(src, d, cl)
        dirs[d] = cl
    return src, dirs


@contextlib.contextmanager
def cluster_up(cl_dir, shards, *, replicas=1, **router_kw):
    """Spin shard daemons (``replicas`` per shard) + a router; yields
    ``(router, daemons)`` and drains everything on the way out."""
    daemons = []
    addrs = []
    try:
        for s in range(shards):
            reps = []
            for _ in range(replicas):
                d = ServeDaemon(str(part_mod.shard_dir(cl_dir, s)),
                                coalesce_us=100)
                d.start()
                daemons.append(d)
                reps.append(d.address)
            addrs.append(reps)
        router_kw.setdefault("hedge_ms", 0.0)
        router_kw.setdefault("health_ms", 100)
        router = RouterDaemon(addrs, "127.0.0.1", 0, **router_kw)
        router.start()
        try:
            yield router, daemons
        finally:
            router.drain()
    finally:
        for d in daemons:
            with contextlib.suppress(Exception):
                d.drain()


class Client:
    def __init__(self, target, timeout=15.0):
        addr = target.address if hasattr(target, "address") else target
        self.sock = socket.create_connection(addr, timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.f = self.sock.makefile("rb")

    def send(self, **obj):
        self.sock.sendall((json.dumps(obj) + "\n").encode())

    def recv(self):
        line = self.f.readline()
        assert line, "connection closed unexpectedly"
        return json.loads(line)

    def rpc(self, **obj):
        self.send(**obj)
        return self.recv()

    def close(self):
        with contextlib.suppress(OSError):
            self.f.close()
        with contextlib.suppress(OSError):
            self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- partition correctness over the real corpus -------------------------


def test_partition_verify_roundtrip(clusters):
    src, dirs = clusters
    for d, cl in dirs.items():
        summary = part_mod.verify(src, cl)
        assert summary == {"shards": d, "docs": len(DOCS),
                           "mode": "round-robin", "verified": True}


def test_partition_verify_catches_corruption(clusters, tmp_path):
    src, dirs = clusters
    cl = dirs[2]
    victim = part_mod.shard_dir(cl, 1) / "docs.list"
    orig = victim.read_bytes()
    try:
        victim.write_bytes(orig + b"extra\n")
        with pytest.raises(part_mod.PartitionError,
                           match="byte-match"):
            part_mod.verify(src, cl)
    finally:
        victim.write_bytes(orig)


def test_partition_sidecar_globals_match_monolith(clusters, mono):
    out, _ = mono
    eng = create_engine(str(out), engine="host")
    try:
        _, ndocs, avgdl = eng._bm25_corpus()
    finally:
        eng.close()
    _, dirs = clusters
    for d, cl in dirs.items():
        for s in range(d):
            sidecar = json.loads(
                (part_mod.shard_dir(cl, s) /
                 "cluster_shard.json").read_text())
            assert sidecar["ndocs"] == ndocs
            assert sidecar["avgdl"] == avgdl  # bit-equal, not approx


# -- router-vs-monolith byte identity -----------------------------------


@daemonized
@pytest.mark.parametrize("d", [1, 2, 4, 8])
def test_router_matches_monolith_fuzz(clusters, mono, d):
    """Every data op through the router over D shards is byte-identical
    to the monolithic engine: dfs, postings, boolean ops, ranked BM25
    floats, and per-letter top_k."""
    import random

    out, naive = mono
    _, dirs = clusters
    vocab = sorted(naive)
    rng = random.Random(100 + d)
    eng = create_engine(str(out), engine="host")
    try:
        with cluster_up(dirs[d], d) as (router, _), \
                Client(router) as c:
            for i in range(25):
                terms = rng.sample(vocab, rng.randint(1, 4))
                batch = eng.encode_batch(terms)
                r = c.rpc(id=i, op="df", terms=terms)
                assert r["ok"] and r["df"] == eng.df(batch).tolist()
                r = c.rpc(id=i, op="postings", terms=terms)
                want = [p.tolist() if p is not None else None
                        for p in eng.postings(batch)]
                assert r["postings"] == want
                r = c.rpc(id=i, op="and", terms=terms)
                assert r["docs"] == eng.query_and(batch).tolist()
                r = c.rpc(id=i, op="or", terms=terms)
                assert r["docs"] == eng.query_or(batch).tolist()
                k = rng.randint(1, 12)
                r = c.rpc(id=i, op="top_k", terms=terms, k=k,
                          score="bm25")
                want = [[doc, score] for doc, score
                        in eng.top_k_scored(batch, k)]
                assert r["docs"] == want  # floats exact, not approx
            for letter in "abcdefg":
                r = c.rpc(id=99, op="top_k", letter=letter, k=5)
                want = [[t.decode("ascii"), int(df)] for t, df
                        in eng.top_k(letter, 5)]
                assert r["top"] == want
    finally:
        eng.close()


@daemonized
def test_router_ranked_merge_k_spans_shards(clusters, mono):
    """k large enough that every shard contributes everything — the
    heap merge must return the full global ranking."""
    out, naive = mono
    _, dirs = clusters
    eng = create_engine(str(out), engine="host")
    try:
        terms = sorted(naive)[:3]
        batch = eng.encode_batch(terms)
        want = [[doc, score] for doc, score
                in eng.top_k_scored(batch, len(DOCS))]
        with cluster_up(dirs[4], 4) as (router, _), \
                Client(router) as c:
            r = c.rpc(id=1, op="top_k", terms=terms, k=len(DOCS),
                      score="bm25")
            assert r["docs"] == want
    finally:
        eng.close()


# -- router protocol / observability ------------------------------------


@daemonized
def test_router_admin_surface(clusters):
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, _), Client(router) as c:
        h = c.rpc(id=1, op="healthz")
        assert h["ok"] and h["ready"] and h["live"]
        st = c.rpc(id=2, op="stats")["stats"]
        assert len(st["cluster"]["shards"]) == 2
        assert all(rep["ready"]
                   for sh in st["cluster"]["shards"]
                   for rep in sh["replicas"])
        # shard-local admin ops don't fan out
        r = c.rpc(id=3, op="reload")
        assert r["error"] == "bad_request"
        # merged exposition: router families + per-shard labelled rows
        text = c.rpc(id=4, op="metrics")["text"]
        assert "mri_cluster_shards 2" in text
        assert 'shard="0"' in text and 'shard="1"' in text
        assert "mri_router_scatter_rpcs_total" in text


@daemonized
def test_router_trace_id_propagates(clusters):
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, _), Client(router) as c:
        r = c.rpc(id=1, op="df", terms=["the"], trace_id="cafe01")
        assert r["trace_id"] == "cafe01"
        r = c.rpc(id=2, op="top_k", terms=["the"], k=3, score="bm25",
                  explain=True)
        assert set(r["explain"]) == {"router", "per_shard"}
        assert r["explain"]["router"]["shards"] == 2


def test_top_render_shows_fleet_rows():
    sample = {
        "healthz": {"ready": True, "status": "ok", "reasons": []},
        "stats": {
            "queue_depth": 0, "inflight": 0, "connections": 1,
            "counters": {"requests": 5},
            "rolling": {},
            "cluster": {"shards": [
                {"shard": 0, "p95_ms": 1.25, "replicas": [
                    {"addr": "h:1", "ready": True, "reasons": [],
                     "primary": True},
                    {"addr": "h:2", "ready": False,
                     "reasons": ["connection_lost"], "primary": False},
                ]},
            ]},
        },
        "slo": {},
    }
    frame = _top_render("h:9", sample)
    assert "ready*" in frame and "DOWN" in frame
    assert "connection_lost" in frame and "h:2" in frame


# -- failure envelope ---------------------------------------------------


def test_cluster_fault_kinds_parse():
    spec = ("shard-dead:shard=1;shard-slow:shard=0:ms=5;"
            "router-conn-reset:req=2")
    inj = faults.FaultInjector(spec)
    kinds = [r.kind for r in inj.rules]
    assert kinds == ["shard-dead", "shard-slow", "router-conn-reset"]
    assert inj.rules[1].ms == 5.0
    with pytest.raises(faults.FaultSpecError):
        faults.FaultInjector("router-conn-reset")  # needs req=
    # chaos sampler accepts the cluster kinds
    inj = faults.FaultInjector(
        "chaos:seed=3:n=2:reqs=8:kinds=shard-dead,router-conn-reset")
    assert inj.rules


@daemonized
def test_injected_shard_dead_fails_over(clusters):
    """shard-dead on shard 0's primary: the RPC retries the other
    replica, the answer is still exact, and the failover is counted."""
    _, dirs = clusters
    with cluster_up(dirs[2], 2, replicas=2) as (router, _):
        faults.install("shard-dead:shard=0")
        with Client(router) as c:
            r = c.rpc(id=1, op="df", terms=["the"])
            assert r["ok"]
        st = router.stats()["counters"]
        assert st["failovers"] >= 1
        assert st["shard_errors"] >= 1


@daemonized
def test_replica_kill_loses_no_acked_queries(clusters, mono):
    """Kill shard 0's primary daemon mid-burst: every pipelined query
    still gets exactly one ok answer (zero lost acked queries)."""
    out, naive = mono
    _, dirs = clusters
    terms = sorted(naive)[:2]
    eng = create_engine(str(out), engine="host")
    try:
        want = [[doc, score] for doc, score
                in eng.top_k_scored(eng.encode_batch(terms), 5)]
    finally:
        eng.close()
    with cluster_up(dirs[2], 2, replicas=2) as (router, daemons):
        victim = daemons[0]  # shard 0, replica 0 (the primary)
        with Client(router) as c:
            n = 200
            got = []

            def reader():
                for _ in range(n):
                    got.append(c.recv())

            t = threading.Thread(target=reader)
            t.start()
            for i in range(n):
                c.send(id=i, op="top_k", terms=terms, k=5,
                       score="bm25")
                if i == 20:
                    victim._listener.close()
                    with victim._conn_lock:
                        conns = list(victim._conns)
                    for conn in conns:
                        with contextlib.suppress(OSError):
                            conn.sock.shutdown(socket.SHUT_RDWR)
                            conn.sock.close()
                if i % 50 == 49:
                    time.sleep(0.02)
            t.join(timeout=30)
        assert len(got) == n
        bad = [r for r in got if not r.get("ok")]
        assert bad == []
        assert sorted(r["id"] for r in got) == list(range(n))
        assert all(r["docs"] == want for r in got)


@daemonized
def test_hedges_fire_on_slowed_shard(clusters):
    """A slowed shard 0 plus a 5 ms fixed hedge: the duplicate RPC is
    counted and answers stay exact (either leg's answer is the same
    bytes)."""
    _, dirs = clusters
    with cluster_up(dirs[2], 2, replicas=2,
                    hedge_ms=5.0) as (router, _):
        faults.install("shard-slow:shard=0:ms=40:times=3")
        with Client(router) as c:
            for i in range(3):
                r = c.rpc(id=i, op="df", terms=["the"])
                assert r["ok"]
        # the hedge send itself rides the injected 40ms slow-down, so
        # its counter increment can land just after the primary's
        # answer — poll briefly instead of racing it
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            st = router.stats()["counters"]
            if st["hedges"] >= 1:
                break
            time.sleep(0.01)
        assert st["hedges"] >= 1


@daemonized
def test_router_conn_reset_tears_one_client_only(clusters):
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, _):
        faults.install("router-conn-reset:req=2")
        with Client(router) as c:
            assert c.rpc(id=1, op="df", terms=["the"])["ok"]
            # request 2 admits, then the connection is torn: EOF, and
            # never two answers for one request
            c.send(id=2, op="df", terms=["the"])
            assert c.f.readline() == b""
        with Client(router) as c2:  # the router itself survives
            assert c2.rpc(id=3, op="df", terms=["the"])["ok"]
        st = router.stats()["counters"]
        assert st["client_disconnects"] >= 1


@daemonized
def test_router_deadline_and_drain(clusters):
    _, dirs = clusters
    with cluster_up(dirs[2], 2) as (router, _):
        faults.install("shard-slow:shard=0:ms=300")
        with Client(router) as c:
            r = c.rpc(id=1, op="top_k", terms=["the"], k=3,
                      score="bm25", deadline_ms=30)
            assert r["error"] == "deadline_expired"
    # drained on exit: counters snapshot survives
    assert router.final_stats["counters"]["deadline_expired"] >= 1


# -- shard daemon micro-batching of router fan-in -----------------------


@daemonized
def test_daemon_groups_same_k_ranked_burst(mono):
    """A pipelined burst of same-k BM25 queries coalesces through
    top_k_scored_batch on the shard daemon — answers byte-identical to
    the solo path."""
    out, naive = mono
    vocab = sorted(naive)
    eng = create_engine(str(out), engine="host")
    try:
        want = {t: [[doc, score] for doc, score
                    in eng.top_k_scored(eng.encode_batch([t]), 4)]
                for t in vocab[:12]}
    finally:
        eng.close()
    daemon = ServeDaemon(str(out), coalesce_us=3000)
    daemon.start()
    try:
        with Client(daemon) as c:
            for i, t in enumerate(vocab[:12]):
                c.send(id=i, op="top_k", terms=[t], k=4, score="bm25")
            got = [c.recv() for _ in range(12)]
        by_id = {r["id"]: r for r in got}
        for i, t in enumerate(vocab[:12]):
            assert by_id[i]["docs"] == want[t]
    finally:
        daemon.drain()
