"""Distributed streaming accumulator (parallel/dist_streaming.py):
streaming + mesh in one path — BASELINE config 5's regime.  Must be
byte-identical to the oracle and bounded per owner."""

import numpy as np
import pytest

import jax

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="multi-chip paths need >= 2 devices (8 virtual on CPU; a "
           "single real TPU chip cannot form a mesh)")

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel.dist_streaming import (
    DistStreamingIndexEngine,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel.mesh import (
    make_mesh,
)


@pytest.fixture(scope="module")
def corpus_fixture(tmp_path_factory):
    root = tmp_path_factory.mktemp("dist_stream")
    docs = zipf_corpus(num_docs=120, vocab_size=1200, tokens_per_doc=90,
                       alpha=1.1, seed=31)
    paths = write_corpus(root / "docs", docs)
    write_manifest(root / "list.txt", paths)
    m = read_manifest(root / "list.txt")
    oracle_index(m, root / "oracle")
    return m, read_letter_files(root / "oracle")


@pytest.mark.parametrize("chunk", [7, 40, 1000])
def test_dist_streaming_matches_oracle(corpus_fixture, tmp_path, chunk):
    m, golden = corpus_fixture
    report = InvertedIndexModel(IndexConfig(
        backend="tpu", stream_chunk_docs=chunk, pad_multiple=256)).run(
        m, output_dir=tmp_path)
    assert report["device_shards"] == 8
    assert report["stream_windows"] == -(-len(m) // chunk)
    assert read_letter_files(tmp_path) == golden


def test_dist_streaming_matches_single_chip(corpus_fixture, tmp_path):
    m, golden = corpus_fixture
    InvertedIndexModel(IndexConfig(
        backend="tpu", stream_chunk_docs=25, device_shards=1,
        pad_multiple=256)).run(m, output_dir=tmp_path / "single")
    InvertedIndexModel(IndexConfig(
        backend="tpu", stream_chunk_docs=25, device_shards=4,
        pad_multiple=256)).run(m, output_dir=tmp_path / "mesh4")
    assert read_letter_files(tmp_path / "single") == read_letter_files(
        tmp_path / "mesh4") == golden


def test_engine_capacity_growth_and_retry():
    """A tiny initial capacity must grow (retry path) without losing
    pairs — skewed keys land on one owner to force per-owner overflow."""
    mesh = make_mesh(4)
    stride = 10
    eng = DistStreamingIndexEngine(
        max_doc_id=8, mesh=mesh, window_pad=64, initial_capacity=64)
    rng = np.random.default_rng(5)
    want = set()
    for _ in range(6):
        # terms all ≡ 0 (mod 4): every pair lands on owner 0
        terms = (rng.integers(0, 400, size=300) * 4).astype(np.int32)
        docs = rng.integers(1, 9, size=300).astype(np.int32)
        eng.feed(terms, docs, vocab_size_so_far=1600)
        want.update(int(t) * stride + int(d) for t, d in zip(terms, docs))
    mode, rows = eng.finalize()
    assert mode == "packed"
    got = sorted(int(k) for r in rows.values() for k in r)
    assert got == sorted(want)
    assert eng.capacity >= len(want)
    assert eng.merge_retries >= 1 or eng.capacity > 64


def test_engine_empty_feed_and_finalize():
    mesh = make_mesh(2)
    eng = DistStreamingIndexEngine(max_doc_id=3, mesh=mesh)
    eng.feed(np.empty(0, np.int32), np.empty(0, np.int32), vocab_size_so_far=0)
    assert eng.finalize() == ("packed", {})


def test_pair_mode_switch_mid_stream():
    """A vocabulary that outgrows int32 packing mid-stream switches the
    accumulator to pair mode without losing any pairs."""
    mesh = make_mesh(4)
    max_doc_id = 1 << 20  # stride 2^20+2: only ~2047 terms can pack
    eng = DistStreamingIndexEngine(
        max_doc_id=max_doc_id, mesh=mesh, window_pad=64,
        initial_capacity=1 << 12)
    rng = np.random.default_rng(9)
    want = set()
    vocab = 100
    for step in range(4):
        terms = rng.integers(0, vocab, size=200).astype(np.int32)
        docs = rng.integers(1, max_doc_id + 1, size=200).astype(np.int32)
        eng.feed(terms, docs, vocab_size_so_far=vocab)
        want.update(zip(terms.tolist(), docs.tolist()))
        if step == 1:
            vocab = 5000  # no longer packs with this stride
    assert eng.mode == "pairs"
    mode, rows = eng.finalize()
    assert mode == "pairs"
    got = sorted((int(t), int(d)) for tt, dd in rows.values()
                 for t, d in zip(tt, dd))
    assert got == sorted(want)


def test_pair_mode_from_first_window(tmp_path):
    """Model-level: corpus whose doc count forces pair mode from window
    one must still match the oracle byte-for-byte (synthetic manifest
    inflates max_doc_id, not actual docs)."""
    docs = zipf_corpus(num_docs=40, vocab_size=600, tokens_per_doc=50, seed=3)
    paths = write_corpus(tmp_path / "docs", docs)
    # pad the manifest with unreadable ghost entries to blow up
    # max_doc_id (they are warned about and skipped, main.c:97-100)
    ghost = [str(tmp_path / "missing" / f"g{i}.txt") for i in range(3)]
    write_manifest(tmp_path / "list.txt", paths + ghost * 1)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    report = InvertedIndexModel(IndexConfig(
        backend="tpu", stream_chunk_docs=8, pad_multiple=256)).run(
        m, output_dir=tmp_path / "out")
    assert read_letter_files(tmp_path / "out") == read_letter_files(tmp_path / "oracle")
