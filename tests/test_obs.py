"""Observability layer suite (obs/): metrics registry semantics under
thread pressure, histogram exactness against the numpy oracle, request
tracing over the daemon wire protocol, the slow-query log, Prometheus
exposition parity with the legacy ``stats`` op, the ``mri metrics``
CLI, the plain-HTTP scrape listener, and Chrome-trace build export.

Daemon-touching tests carry the ``daemon`` marker too, so the conftest
leak guard holds them to the no-stray-sockets/threads contract.
"""

import json
import logging
import math
import socket
import time

import numpy as np
import pytest

from test_daemon import DOCS, Client, serving

from test_serve import build_corpus, naive_index, write_manifest

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    faults,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
    main as cli_main,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    metrics as obs_metrics,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    timing as obs_timing,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    tracing as obs_tracing,
)

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _disarm():
    faults.install(None)
    yield
    faults.install(None)


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    out = build_corpus(tmp_path_factory.mktemp("obs_corpus"), DOCS)
    return out, naive_index(DOCS)


# -- registry semantics ----------------------------------------------------

def test_registry_get_or_create_and_kind_mismatch():
    reg = obs_metrics.Registry()
    c = reg.counter("x_total", "help text")
    assert reg.counter("x_total") is c
    assert c.help == "help text"
    with pytest.raises(TypeError):
        reg.gauge("x_total")
    # well-known names pick up their canonical help automatically
    r = reg.counter("mri_serve_requests_total")
    assert "admitted" in r.help


def test_counter_thread_hammer():
    reg = obs_metrics.Registry()
    c = reg.counter("hammer_total")
    g = reg.gauge("hammer_gauge")
    import threading

    def work():
        for _ in range(5000):
            c.inc()
            g.inc(1.0)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8 * 5000
    assert g.value == 8 * 5000.0


def test_histogram_buckets_match_numpy_oracle():
    h = obs_metrics.Histogram("t_seconds")
    rng = np.random.default_rng(7)
    # log-uniform across the bucket span plus exact-boundary values
    # (le semantics: a sample equal to a bound lands in that bucket)
    samples = list(np.exp(rng.uniform(np.log(1e-7), np.log(100.0), 3000)))
    samples += [h.bounds[0], h.bounds[5], h.bounds[-1], 1e9]
    for v in samples:
        h.observe(v)
    arr = np.sort(np.asarray(samples))
    cum = h.cumulative_counts()
    for bound, got in zip(h.bounds, cum):
        want = int(np.searchsorted(arr, bound, side="right"))
        assert got == want, f"bucket le={bound}"
    assert cum[-1] == len(samples) == h.count
    assert h.sum == pytest.approx(float(np.sum(arr)))


def test_histogram_quantiles_exact_vs_numpy():
    h = obs_metrics.Histogram("q_seconds")
    rng = np.random.default_rng(13)
    samples = rng.gamma(2.0, 0.003, 5001)
    for v in samples:
        h.observe(v)
    assert h.exact
    for p in (0, 5, 50, 90, 99, 99.9, 100):
        assert h.quantile(p) == pytest.approx(
            float(np.percentile(samples, p)), rel=1e-12)


def test_histogram_sample_cap_flags_truncation():
    h = obs_metrics.Histogram("cap_seconds")
    for i in range(obs_metrics.SAMPLE_CAP + 10):
        h.observe(1e-5)
    assert not h.exact
    assert h.count == obs_metrics.SAMPLE_CAP + 10  # buckets stay exact
    assert h.cumulative_counts()[-1] == h.count


def test_render_text_prometheus_shape():
    reg = obs_metrics.Registry()
    reg.counter("a_total").inc(3)
    reg.gauge("b_depth").set(2.5)
    h = reg.histogram("c_seconds")
    h.observe(1e-6)
    h.observe(5.0)
    text = reg.render_text()
    assert "# TYPE a_total counter\na_total 3" in text
    assert "# TYPE b_depth gauge\nb_depth 2.5" in text
    assert "# TYPE c_seconds histogram" in text
    assert 'c_seconds_bucket{le="+Inf"} 2' in text
    assert "c_seconds_count 2" in text
    # bucket series is cumulative-monotonic
    buckets = [int(line.rsplit(" ", 1)[1])
               for line in text.splitlines()
               if line.startswith("c_seconds_bucket")]
    assert buckets == sorted(buckets)
    # every sample line parses as "name value" or 'name{le="..."} value'
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        name, _, v = line.rpartition(" ")
        float(v)
        assert name


# -- timer shims -----------------------------------------------------------

def test_optimer_shim_and_stats_shape():
    # the historical import paths still resolve to the obs classes
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.engine import (  # noqa: E501
        OpTimer,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.utils.timing import (  # noqa: E501
        PhaseTimer,
    )
    assert OpTimer is obs_timing.OpTimer
    assert PhaseTimer is obs_timing.PhaseTimer

    t = OpTimer()
    with t.time("df"):
        pass
    s = t.stats()
    assert set(s) == {"df"}
    assert set(s["df"]) == {"calls", "total_ms", "avg_us"}
    assert s["df"]["calls"] == 1
    assert not math.isnan(t.quantile_ms("df", 50))
    t.reset()
    assert t.stats() == {}

    pt = PhaseTimer()
    with pt.phase("scan"):
        pass
    pt.count("tokens", 42)
    pt.phases["aborted_thing"] = 0.5  # direct assignment must keep working
    rep = pt.report()
    assert set(rep["phases_ms"]) == {"scan", "aborted_thing"}
    assert rep["tokens"] == 42
    assert json.loads(pt.dumps()) == json.loads(
        json.dumps(rep, sort_keys=True))
    assert pt.histogram("scan").count == 1


# -- request tracing over the wire ----------------------------------------

def _poll_traces(cli, n, want, timeout=5.0):
    """Trace records land just after the response line — poll briefly."""
    deadline = time.monotonic() + timeout
    while True:
        r = cli.rpc(op="trace", n=n)
        assert r["ok"]
        if len(r["traces"]) >= want or time.monotonic() > deadline:
            return r["traces"]
        time.sleep(0.01)


@pytest.mark.daemon
@pytest.mark.serve
def test_trace_id_echo_and_autogeneration(built):
    out, _ = built
    with serving(out) as d, Client(d) as cli:
        r = cli.rpc(id=1, op="df", terms=["cat"], trace_id="my-trace-7")
        assert r["ok"] and r["trace_id"] == "my-trace-7"
        r = cli.rpc(id=2, op="df", terms=["dog"])
        assert r["ok"]
        assert len(r["trace_id"]) == 16
        int(r["trace_id"], 16)  # hex
        # admin ops echo a provided trace_id too
        r = cli.rpc(id=3, op="healthz", trace_id="adm")
        assert r["trace_id"] == "adm"


@pytest.mark.daemon
@pytest.mark.serve
def test_trace_op_spans_complete_and_contiguous(built):
    out, _ = built
    with serving(out) as d, Client(d) as cli:
        for i in range(6):
            r = cli.rpc(id=i, op="and", terms=["cat", "the"],
                        trace_id=f"t{i}")
            assert r["ok"]
        traces = _poll_traces(cli, 32, 6)
        assert len(traces) >= 6
        # most-recent-first ordering
        ids = [t["trace_id"] for t in traces if t["trace_id"].startswith("t")]
        assert ids == sorted(ids, reverse=True)
        engine_traces = 0
        for t in traces:
            assert t["status"] == "ok"
            assert t["op"] == "and"
            names = [s["name"] for s in t["spans"]]
            if names == ["result_cache"]:
                # repeat of a hot query answered by the result cache:
                # a single span covers the whole request
                assert t["spans"][0]["start_ms"] == 0.0
                continue
            engine_traces += 1
            assert names == ["queue_wait", "coalesce", "engine"]
            # spans start at admission and tile the request wall time
            assert t["spans"][0]["start_ms"] == 0.0
            for a, b in zip(t["spans"], t["spans"][1:]):
                assert b["start_ms"] == pytest.approx(
                    a["start_ms"] + a["dur_ms"], abs=2e-3)
            last = t["spans"][-1]
            assert t["dur_ms"] >= last["start_ms"] + last["dur_ms"] - 2e-3
        # the first (cold) query must have reached the engine
        assert engine_traces >= 1


@pytest.mark.daemon
@pytest.mark.serve
def test_trace_ring_capacity_and_n(built, monkeypatch):
    monkeypatch.setenv("MRI_OBS_TRACE_RING", "3")
    out, _ = built
    with serving(out) as d, Client(d) as cli:
        for i in range(8):
            assert cli.rpc(id=i, op="df", terms=["cat"])["ok"]
        traces = _poll_traces(cli, 32, 3)
        assert len(traces) == 3
        assert len(cli.rpc(op="trace", n=1)["traces"]) == 1
        # a junk n falls back to the default window rather than erroring
        r = cli.rpc(op="trace", n=0)
        assert r["ok"] and len(r["traces"]) == 3


@pytest.mark.daemon
@pytest.mark.serve
def test_obs_disabled_skips_generation_but_echoes(built, monkeypatch):
    monkeypatch.setenv("MRI_OBS_ENABLE", "0")
    out, _ = built
    with serving(out) as d, Client(d) as cli:
        r = cli.rpc(id=1, op="df", terms=["cat"])
        assert r["ok"] and "trace_id" not in r
        r = cli.rpc(id=2, op="df", terms=["cat"], trace_id="still-echoed")
        assert r["trace_id"] == "still-echoed"


@pytest.mark.daemon
@pytest.mark.serve
def test_slow_query_log_fires(built, monkeypatch, caplog):
    monkeypatch.setenv("MRI_OBS_SLOW_MS", "0.000001")
    out, _ = built
    with caplog.at_level(logging.WARNING, logger="mri_tpu.obs"):
        with serving(out) as d, Client(d) as cli:
            assert cli.rpc(id=1, op="df", terms=["cat"],
                           trace_id="slowone")["ok"]
        # serving() drained: every _finish (and its slow-log emit) done
    lines = [json.loads(rec.message) for rec in caplog.records
             if rec.name == "mri_tpu.obs"]
    mine = [ln for ln in lines if ln.get("trace_id") == "slowone"]
    assert mine and mine[0]["event"] == "slow_query"
    assert mine[0]["status"] == "ok"
    assert [s["name"] for s in mine[0]["spans"]] \
        == ["queue_wait", "coalesce", "engine"]


@pytest.mark.daemon
@pytest.mark.serve
def test_slow_query_log_quiet_by_default(built, caplog):
    out, _ = built
    with caplog.at_level(logging.WARNING, logger="mri_tpu.obs"):
        with serving(out) as d, Client(d) as cli:
            assert cli.rpc(id=1, op="df", terms=["cat"])["ok"]
    assert not [r for r in caplog.records if r.name == "mri_tpu.obs"]


# -- Prometheus exposition parity -----------------------------------------

def _prom_scalars(text: str) -> dict:
    vals = {}
    for line in text.splitlines():
        if not line or line.startswith("#") or "{" in line.split(" ", 1)[0]:
            continue
        name, _, v = line.partition(" ")
        vals[name] = float(v)
    return vals


@pytest.mark.daemon
@pytest.mark.serve
def test_metrics_op_matches_stats_counters(built):
    out, _ = built
    with serving(out) as d, Client(d) as cli:
        for i in range(5):
            assert cli.rpc(id=i, op="df", terms=["cat"])["ok"]
        # a bad request and a shed-free baseline for the error counters
        assert cli.rpc(id=9, op="nope")["error"] == "bad_request"
        stats = cli.rpc(op="stats")["stats"]
        r = cli.rpc(op="metrics")
        assert r["ok"]
        vals = _prom_scalars(r["text"])
        counters = stats["counters"]
        for key in ("requests", "shed", "deadline_expired", "bad_request",
                    "draining_rejected", "reload_ok", "reload_rejected"):
            assert vals[f"mri_serve_{key}_total"] == counters[key], key
        # engine + cache metrics ride along in the same exposition
        assert "mri_engine_vocab_terms" in vals
        assert "mri_serve_cache_hits_total" in vals
        # latency histograms are exposed with _count matching traffic
        assert "mri_serve_request_seconds_count" in vals
        assert vals["mri_serve_request_seconds_count"] >= 5


@pytest.mark.daemon
@pytest.mark.serve
def test_engine_describe_unchanged_by_migration(built):
    # the byte-compat contract: describe()/stats() keep their legacy
    # shapes even though every number now lives in the obs registry
    out, _ = built
    with serving(out) as d, Client(d) as cli:
        assert cli.rpc(id=1, op="df", terms=["cat"])["ok"]
        stats = cli.rpc(op="stats")["stats"]
        eng = stats["engine"]
        assert {"hits", "misses", "evictions", "capacity", "entries"} \
            <= set(eng["cache"])
        assert {"blocks_decoded", "blocks_skipped", "bytes_decoded"} \
            == set(eng["decode"])


# -- scrape surfaces: CLI + HTTP listener ---------------------------------

def test_metrics_cli_artifact_dir(built, capsys):
    out, _ = built
    assert cli_main(["metrics", str(out)]) == 0
    text = capsys.readouterr().out
    assert "# TYPE mri_engine_vocab_terms gauge" in text
    assert "# TYPE mri_serve_cache_hits_total counter" in text


def test_metrics_cli_bad_dir(tmp_path, capsys):
    assert cli_main(["metrics", str(tmp_path / "nope")]) == 2
    assert "error" in capsys.readouterr().err


@pytest.mark.daemon
@pytest.mark.serve
def test_metrics_cli_against_daemon(built, capsys):
    out, _ = built
    with serving(out) as d, Client(d) as cli:
        assert cli.rpc(id=1, op="df", terms=["cat"])["ok"]
        host, port = d.address
        assert cli_main(["metrics", f"{host}:{port}"]) == 0
        text = capsys.readouterr().out
        vals = _prom_scalars(text)
        assert vals["mri_serve_requests_total"] == 1


def test_metrics_cli_unreachable_addr(capsys):
    # a closed port: connection refused -> one-line exit 2
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    assert cli_main(["metrics", f"127.0.0.1:{port}", "--timeout", "2"]) == 2
    assert "error" in capsys.readouterr().err


@pytest.mark.daemon
@pytest.mark.serve
def test_http_scrape_listener(built):
    out, _ = built
    with serving(out, metrics_port=0) as d:
        assert d.metrics_address is not None
        with socket.create_connection(d.metrics_address, timeout=10) as s:
            s.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
            buf = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
        head, _, body = buf.partition(b"\r\n\r\n")
        assert b"200 OK" in head
        assert b"text/plain" in head
        vals = _prom_scalars(body.decode())
        assert "mri_serve_requests_total" in vals
    # after drain the listener is gone
    with pytest.raises(OSError):
        socket.create_connection(d.metrics_address, timeout=1)


# -- Chrome-trace build export --------------------------------------------

def _build_with_trace(tmp_path, monkeypatch, capsys, *, mappers, reducers,
                      window_bytes=96, artifact=True, extra=()):
    ddir = tmp_path / "docs"
    ddir.mkdir()
    paths = []
    for i, blob in enumerate(DOCS * 3):
        p = ddir / f"d{i:04d}.txt"
        p.write_bytes(blob)
        paths.append(str(p))
    listfile = tmp_path / "list.txt"
    write_manifest(listfile, paths)
    out = tmp_path / "out"
    trace_path = tmp_path / "trace.json"
    monkeypatch.setenv("MRI_CPU_WINDOW_BYTES", str(window_bytes))
    argv = [str(mappers), str(reducers), str(listfile),
            "--backend", "cpu", "--output-dir", str(out), "--stats",
            "--trace-out", str(trace_path), *extra]
    if artifact:
        argv.append("--artifact")
    assert cli_main(argv) == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["trace_out"] == str(trace_path)
    with open(trace_path, "r", encoding="utf-8") as f:
        return stats, json.load(f)


def _check_trace_doc(doc):
    """Spans are well-formed and, per thread lane, non-overlapping."""
    events = doc["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    named_tids = {e["tid"] for e in meta if e["name"] == "thread_name"}
    by_tid = {}
    for e in spans:
        assert e["ts"] >= 0.0 and e["dur"] >= 0.0
        assert e["tid"] in named_tids, f"unnamed lane {e['tid']}"
        by_tid.setdefault(e["tid"], []).append(e)
    for tid, evs in by_tid.items():
        evs.sort(key=lambda e: e["ts"])
        for a, b in zip(evs, evs[1:]):
            assert b["ts"] >= a["ts"] + a["dur"] - 0.01, \
                f"overlap on tid {tid}: {a} / {b}"
    assert doc["displayTimeUnit"] == "ms"
    return spans


def test_trace_out_parallel_build(tmp_path, monkeypatch, capsys):
    stats, doc = _build_with_trace(tmp_path, monkeypatch, capsys,
                                   mappers=2, reducers=3)
    spans = _check_trace_doc(doc)
    names = {}
    for e in spans:
        names[e["name"]] = names.get(e["name"], 0) + 1
    windows = stats["io_windows"]
    assert windows > 1, "window override did not take"
    # one complete span per scheduled window, on both pipeline stages
    assert names["scan"] == windows
    assert names["read"] == windows
    assert names["merge"] == 1
    assert names["emit_range"] == stats["reduce_workers"]
    assert names["artifact_pack"] == 1
    # scan windows are labeled with their global plan index
    scan_windows = sorted(e["args"]["window"] for e in spans
                          if e["name"] == "scan")
    assert scan_windows == list(range(1, windows + 1))


def test_trace_out_pipelined_build(tmp_path, monkeypatch, capsys):
    # the single-worker pipelined path needs --host-threads 1 (with
    # mappers=1 the default would still spin min(cores, 8) workers)
    # and no --artifact (which routes through the parallel reduce)
    stats, doc = _build_with_trace(tmp_path, monkeypatch, capsys,
                                   mappers=1, reducers=1, artifact=False,
                                   extra=("--host-threads", "1"))
    spans = _check_trace_doc(doc)
    names = {e["name"] for e in spans}
    windows = stats["io_windows"]
    assert windows > 1
    assert sum(1 for e in spans if e["name"] == "scan") == windows
    assert "finalize_emit" in names


def test_trace_out_absent_without_flag(tmp_path, capsys):
    # no --trace-out: no trace file, no trace_out stats key
    out = build_corpus(tmp_path, DOCS)
    assert not list(tmp_path.rglob("trace.json"))
    assert out.exists()
