"""Streaming (windowed) pipeline tests.

The north-star property: streaming output is byte-identical to the
one-shot pipeline and the oracle, for any window size — including
windows of one document and windows larger than the corpus — while the
device accumulator stays bounded and grows only by host-side doubling.
"""

import pathlib

import numpy as np
import pytest

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.config import IndexConfig
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    Manifest, iter_document_chunks,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus, zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.models.inverted_index import (
    build_index,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops.streaming import (
    StreamingIndexEngine,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.streaming import (
    StreamingTokenizer,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    tokenize_documents,
)


def _letters_dir(d: pathlib.Path) -> dict[str, bytes]:
    return {f"{c}.txt": (d / f"{c}.txt").read_bytes()
            for c in "abcdefghijklmnopqrstuvwxyz"}


def _manifest_for(tmp_path, num_docs=12, seed=0):
    docs = zipf_corpus(num_docs=num_docs, vocab_size=400, tokens_per_doc=120, seed=seed)
    paths = write_corpus(tmp_path / "docs", docs)
    return Manifest(paths=tuple(str(p) for p in paths),
                    sizes=tuple(pathlib.Path(p).stat().st_size for p in paths))


@pytest.mark.parametrize("chunk_docs", [1, 5, 100])
def test_streaming_matches_oneshot(tmp_path, chunk_docs):
    m = _manifest_for(tmp_path)
    one = tmp_path / "one"
    stream = tmp_path / "stream"
    build_index(m, IndexConfig(), output_dir=str(one))
    stats = build_index(
        m, IndexConfig(stream_chunk_docs=chunk_docs), output_dir=str(stream))
    assert _letters_dir(one) == _letters_dir(stream)
    assert stats["documents"] == len(m)
    assert stats["stream_windows"] == -(-len(m) // chunk_docs)


def test_streaming_tokenizer_ids_stable_across_windows(tmp_path):
    docs = [b"beta alpha", b"alpha gamma", b"gamma beta delta"]
    tok = StreamingTokenizer(use_native=False)
    c1 = tok.feed([docs[0]], [1])
    c2 = tok.feed([docs[1]], [2])
    c3 = tok.feed([docs[2]], [3])
    # provisional ids: assigned per window in that window's sorted-vocab
    # order, stable once assigned (append-only across windows)
    vocab, remap, letters = tok.finalize()
    assert vocab.tolist() == [b"alpha", b"beta", b"delta", b"gamma"]
    # window 1 sorted [alpha, beta] -> 0, 1; window 2 adds gamma -> 2;
    # window 3 adds delta -> 3
    np.testing.assert_array_equal(remap, [0, 1, 3, 2])
    np.testing.assert_array_equal(c1.prov_term_ids, [1, 0])
    np.testing.assert_array_equal(c2.prov_term_ids, [0, 2])
    np.testing.assert_array_equal(c3.prov_term_ids, [2, 1, 3])
    np.testing.assert_array_equal(letters, [0, 1, 3, 6])


def test_engine_accumulator_grows_by_doubling():
    eng = StreamingIndexEngine(max_doc_id=3, window_pad=128, initial_capacity=256)
    rng = np.random.default_rng(0)
    for w in range(4):
        terms = rng.integers(0, 5000, 200).astype(np.int32)
        docs = rng.integers(1, 4, 200).astype(np.int32)
        eng.feed(terms, docs, vocab_size_so_far=5000)
    assert eng.capacity == 1024  # 800 pairs fed -> two doublings from 256
    assert eng.windows_fed == 4


def test_engine_switches_to_pair_mode_on_unpackable_vocab():
    # stride 100_002 stops packing once vocab exceeds ~21k terms; the
    # engine must switch representations mid-stream without data loss
    max_doc = 100_000
    vocab_size = 30_000
    rng = np.random.default_rng(1)
    eng = StreamingIndexEngine(max_doc_id=max_doc, window_pad=128,
                               initial_capacity=2048)
    seen: dict[int, set] = {}
    vocab_so_far = 10_000  # packable at first
    for w in range(4):
        terms = rng.integers(0, vocab_so_far, 300).astype(np.int32)
        docs = rng.integers(1, 50, 300).astype(np.int32)
        for t, d in zip(terms.tolist(), docs.tolist()):
            seen.setdefault(t, set()).add(d)
        eng.feed(terms, docs, vocab_size_so_far=vocab_so_far)
        if w == 1:
            vocab_so_far = vocab_size  # crosses the packing bound
    assert eng.mode == "pairs"
    remap = np.arange(vocab_size, dtype=np.int32)  # identity: already ranked
    letters = np.zeros(vocab_size, np.int32)
    out = eng.finalize(remap, letters, vocab_size)
    df = np.asarray(out["df"])
    postings = np.asarray(out["postings"])
    offsets = np.asarray(out["offsets"])
    assert int(np.asarray(out["num_unique"])) == sum(len(s) for s in seen.values())
    for t, docs_set in seen.items():
        got = postings[offsets[t]: offsets[t] + df[t]].tolist()
        assert got == sorted(docs_set), t


def test_config_rejects_streaming_incompatible_options(tmp_path):
    with pytest.raises(ValueError, match="checkpoint_path"):
        IndexConfig(stream_chunk_docs=4, checkpoint_path=str(tmp_path / "c.npz"))
    with pytest.raises(ValueError, match="collect_skew_stats"):
        IndexConfig(stream_chunk_docs=4, collect_skew_stats=True)
    # streaming + mesh is a supported combination now (the distributed
    # streaming accumulator, parallel/dist_streaming.py)
    IndexConfig(stream_chunk_docs=4, device_shards=2)
    with pytest.raises(ValueError, match="emit_ownership"):
        IndexConfig(stream_chunk_docs=4, emit_ownership="letter")


def test_streaming_engine_matches_oracle_postings():
    # drive the engine directly (no files): dedup across windows
    docs = [b"x y z x", b"y y w", b"z q x"]
    ids = [1, 2, 3]
    corpus = tokenize_documents(docs, ids)  # sorted-vocab one-shot view
    tok = StreamingTokenizer(use_native=False)
    eng = StreamingIndexEngine(max_doc_id=3, window_pad=128, initial_capacity=256)
    for d, i in zip(docs, ids):
        ch = tok.feed([d], [i])
        eng.feed(ch.prov_term_ids, ch.doc_ids, tok.vocab_size)
    vocab, remap, letters = tok.finalize()
    out = eng.finalize(remap, letters, int(vocab.shape[0]))
    np.testing.assert_array_equal(vocab, corpus.vocab)
    df = np.asarray(out["df"])
    postings = np.asarray(out["postings"])
    offsets = np.asarray(out["offsets"])
    # oracle: q->[3] w->[2] x->[1 3] y->[1 2] z->[1 3]
    expect = {b"q": [3], b"w": [2], b"x": [1, 3], b"y": [1, 2], b"z": [1, 3]}
    for t, word in enumerate(vocab.tolist()):
        got = postings[offsets[t]: offsets[t] + df[t]].tolist()
        assert got == expect[word], word


def test_iter_document_chunks_windows(tmp_path):
    m = _manifest_for(tmp_path, num_docs=7)
    chunks = list(iter_document_chunks(m, 3))
    assert [len(c[0]) for c in chunks] == [3, 3, 1]
    assert [c[1] for c in chunks] == [[1, 2, 3], [4, 5, 6], [7]]
    with pytest.raises(ValueError):
        next(iter_document_chunks(m, 0))


def test_config_validates_stream_chunk_docs():
    with pytest.raises(ValueError, match="stream_chunk_docs"):
        IndexConfig(stream_chunk_docs=0)
