"""Tokenizer conformance: the edge cases of SURVEY.md §2.3."""

import numpy as np
import pytest

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
    TokenizedCorpus,
    clean_token,
    tokenize_documents,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.models.oracle import (
    oracle_postings,
)


@pytest.mark.parametrize(
    "raw,expected",
    [
        ("Don't", "dont"),
        ("foo-bar", "foobar"),
        ("x1y2z3", "xyz"),
        ("café", "caf"),          # UTF-8 continuation bytes dropped
        ("I.Loomings", "iloomings"),
        ("42", ""),
        ("---", ""),
        ("HELLO", "hello"),
        ("MiXeD", "mixed"),
        ("", ""),
    ],
)
def test_clean_token(raw, expected):
    assert clean_token(raw) == expected


def test_clean_token_cap_299():
    # Reference keeps at most MAX_WORD-1 = 299 letters (main.c:105).
    assert clean_token("a" * 500) == "a" * 299
    assert clean_token("a" * 299) == "a" * 299
    # Non-letters don't count toward the cap (they're deleted first-ish:
    # the C loop appends letters until j==299 scanning all bytes).
    assert clean_token("1" * 400 + "b" * 400) == "b" * 299


def _pairs(corpus: TokenizedCorpus) -> set:
    words = corpus.vocab_strings()
    return {(words[t], int(d)) for t, d in zip(corpus.term_ids, corpus.doc_ids)}


def test_tokenize_documents_matches_oracle_small():
    docs = [
        b"The quick brown Fox! don't stop x1y2z3",
        b"quick\tquick\nfox 42 --- caf\xc3\xa9",
        b"",
        b"...only punct 123...",
    ]
    ids = [1, 2, 3, 4]
    corpus = tokenize_documents(docs, ids)
    expected = oracle_postings(docs, ids)
    expected_pairs = {(w, d) for w, dl in expected.items() for d in dl}
    assert _pairs(corpus) == expected_pairs


def test_vocab_sorted_and_letters():
    corpus = tokenize_documents([b"banana apple Cherry apple zzz a"], [1])
    words = corpus.vocab_strings()
    assert words == sorted(words)
    assert words == ["a", "apple", "banana", "cherry", "zzz"]
    np.testing.assert_array_equal(corpus.letter_of_term, [0, 0, 1, 2, 25])


def test_doc_boundaries_exact():
    # Words at document edges must get the right 1-based doc id even with
    # no trailing newline and with leading/trailing whitespace.
    docs = [b"alpha beta", b"beta gamma", b"  gamma\talpha "]
    corpus = tokenize_documents(docs, [1, 2, 3])
    got = {}
    words = corpus.vocab_strings()
    for t, d in zip(corpus.term_ids, corpus.doc_ids):
        got.setdefault(words[t], set()).add(int(d))
    assert got == {"alpha": {1, 3}, "beta": {1, 2}, "gamma": {2, 3}}


def test_empty_corpus():
    corpus = tokenize_documents([], [])
    assert corpus.num_tokens == 0 and corpus.vocab_size == 0
    corpus = tokenize_documents([b"123 ... \n\n"], [1])
    assert corpus.num_tokens == 0 and corpus.vocab_size == 0


def test_token_spanning_cap_in_stream():
    # >299-letter token inside a doc stream: truncated, not crashed (the
    # reference would overflow its fscanf buffer here — SURVEY.md §2.3).
    long_tok = b"A" * 350
    corpus = tokenize_documents([b"x " + long_tok + b" y"], [1])
    words = corpus.vocab_strings()
    assert "a" * 299 in words and "x" in words and "y" in words


def test_random_corpora_match_oracle():
    rng = np.random.default_rng(0)
    alphabet = list(b"abcXYZ0-' \t\n\xc3\xa9")
    for trial in range(10):
        n_docs = int(rng.integers(1, 6))
        docs = [
            bytes(rng.choice(alphabet, size=int(rng.integers(0, 200))))
            for _ in range(n_docs)
        ]
        ids = list(range(1, n_docs + 1))
        corpus = tokenize_documents(docs, ids)
        expected = oracle_postings(docs, ids)
        expected_pairs = {(w, d) for w, dl in expected.items() for d in dl}
        assert _pairs(corpus) == expected_pairs, f"trial {trial}"
