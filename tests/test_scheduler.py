"""Host shard planning incl. the reference's degenerate configs."""

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import Manifest
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.scheduler import (
    plan_host_shards,
    plan_letter_ranges,
    shard_balance_stats,
)


def _manifest(sizes):
    return Manifest(paths=tuple(f"f{i}" for i in range(len(sizes))), sizes=tuple(sizes))


def test_lpt_covers_all_files_once():
    m = _manifest([100, 10, 90, 20, 80, 30])
    plan = plan_host_shards(m, 3)
    seen = sorted(i for shard in plan.shards for i in shard)
    assert seen == list(range(6))


def test_lpt_balance_reasonable():
    m = _manifest([50] * 8)
    plan = plan_host_shards(m, 4)
    stats = shard_balance_stats(m, plan)
    assert stats["max_over_mean"] == 1.0


def test_more_shards_than_files():
    # Reference UB case (uninitialized ranges, SURVEY.md §2.1); here: empty shards.
    m = _manifest([5, 5])
    plan = plan_host_shards(m, 5)
    assert plan.num_shards == 5
    assert sorted(i for s in plan.shards for i in s) == [0, 1]
    assert sum(1 for s in plan.shards if not s) == 3


def test_letter_ranges_basic():
    assert plan_letter_ranges(1) == ((0, 26),)
    ranges = plan_letter_ranges(4)
    assert ranges == ((0, 6), (6, 12), (12, 18), (18, 26))


def test_letter_ranges_degenerate_over_26():
    # reducers > 26: 26/R == 0, all letters collapse onto the last reducer
    # (main.c:129-130) — part of the observable contract.
    ranges = plan_letter_ranges(27)
    assert all(r == (0, 0) for r in ranges[:-1])
    assert ranges[-1] == (0, 26)
