"""Operational-health layer suite (obs/windows, obs/slo, obs/watchdog,
obs/logging, the daemon surfaces, and `mri top`).

Four layers:

* unit math — RollingWindows over a fake clock (rates, expiry,
  windowed quantiles / threshold fractions), SLOTracker burn rates,
  and Watchdog episode semantics with a manual monitor pass;
* structured logging — the emit() funnel's text/json rendering and
  the per-event rate limiter (drops counted, never silent);
* daemon surfaces — the `slo` admin op, the rolling/slo stats blocks,
  liveness-vs-readiness healthz, mri_slo_*/mri_watchdog_* gauges in
  the scrape, and trace/slow-log/windows under concurrent churn;
* the contract — a subprocess daemon with an injected dispatcher hang
  must flip readiness to `stalled` within 2x MRI_OBS_STALL_MS, dump a
  flight-<pid>-stall.json, recover, and still drain to exit 0; and
  `mri top --once --json` must agree with the raw stats/slo ops.
"""

import io
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from conftest import REPO_ROOT

from test_daemon import DOCS, Client, serving

from test_serve import build_corpus

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    faults,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.cli import (
    main as cli_main,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    logging as obs_logging,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    metrics as obs_metrics,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    slo as obs_slo,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    watchdog as obs_watchdog,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs import (
    windows as obs_windows,
)

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _disarm():
    faults.install(None)
    yield
    faults.install(None)


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    return build_corpus(tmp_path_factory.mktemp("ophealth_corpus"), DOCS)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _windows(reg, clock, **kw):
    kw.setdefault("counters", ["c"])
    kw.setdefault("histograms", ["h"])
    return obs_windows.RollingWindows(reg, period_s=1.0, clock=clock, **kw)


# -- RollingWindows math ---------------------------------------------------


def test_windows_counter_rates_and_age_clamp():
    reg, clock = obs_metrics.Registry(), FakeClock()
    rw = _windows(reg, clock)
    reg.counter("c").inc(5)
    clock.advance(1.0)
    rw.sample()
    assert rw.counts(10.0)["c"] == 5
    # span clamps to process age: 1s old, so the "10s" rate is 5/s
    assert rw.rate("c", 10.0) == pytest.approx(5.0)
    # 9 more idle ticks: same 5 events over a full 10s window now
    for _ in range(9):
        clock.advance(1.0)
        rw.sample()
    assert rw.rate("c", 10.0) == pytest.approx(0.5)


def test_windows_buckets_expire():
    reg, clock = obs_metrics.Registry(), FakeClock()
    rw = _windows(reg, clock)
    reg.counter("c").inc(7)
    clock.advance(1.0)
    rw.sample()
    assert rw.counts(10.0)["c"] == 7
    # idle-tick past the 10s horizon: the burst ages out of the window
    for _ in range(12):
        clock.advance(1.0)
        rw.sample()
    assert rw.counts(10.0)["c"] == 0
    # ... but the 1m window still sees it
    assert rw.counts(60.0)["c"] == 7


def test_windows_quantile_and_good_fraction():
    reg, clock = obs_metrics.Registry(), FakeClock()
    rw = _windows(reg, clock)
    h = reg.histogram("h")
    for _ in range(8):
        h.observe(0.001)   # in the (512us, 1024us] bucket
    for _ in range(2):
        h.observe(1.0)     # far above any sane threshold
    clock.advance(1.0)
    rw.sample()
    assert rw.hist_count("h", 10.0) == 10
    q = rw.quantile("h", 10.0, 50)
    assert 0.000512 <= q <= 0.001024  # interpolated inside the bucket
    # 50ms threshold: the 8 fast obs are good, the 2 slow are not
    assert rw.good_fraction("h", 10.0, 0.05) == pytest.approx(0.8)
    # no samples in the window -> None, never a fake 0
    assert rw.quantile("h", 10.0, 99) is not None
    for _ in range(12):
        clock.advance(1.0)
        rw.sample()
    assert rw.quantile("h", 10.0, 99) is None
    assert rw.good_fraction("h", 10.0, 0.05) is None


def test_windows_sampler_thread_lifecycle():
    reg = obs_metrics.Registry()
    rw = obs_windows.RollingWindows(reg, counters=["c"], period_s=0.01)
    reg.counter("c").inc(3)
    rw.start()
    try:
        deadline = time.monotonic() + 5.0
        while rw.counts(10.0)["c"] < 3:
            assert time.monotonic() < deadline, "sampler never ticked"
            time.sleep(0.005)
    finally:
        rw.stop()
    assert not [t for t in threading.enumerate()
                if t.name == "mri-obs-sampler"]


# -- SLOTracker ------------------------------------------------------------


def _slo_windows(reg, clock):
    names = [obs_slo._TOTAL, *obs_slo._BAD]
    return obs_windows.RollingWindows(
        reg, counters=names, histograms=[obs_slo._LATENCY_HIST],
        period_s=1.0, clock=clock)


def test_slo_availability_burn_math():
    reg, clock = obs_metrics.Registry(), FakeClock()
    rw = _slo_windows(reg, clock)
    tracker = obs_slo.SLOTracker(rw, slos=(obs_slo.SLO("availability",
                                                       0.999),))
    # idle: a quiet daemon is not failing
    idle = tracker.report()["availability"]["windows"]["10s"]
    assert idle["ratio"] == 1.0 and idle["burn"] == 0.0
    # 95 admitted + 5 shed: 100 admission attempts, 5 bad
    reg.counter(obs_slo._TOTAL).inc(95)
    reg.counter("mri_serve_shed_total").inc(5)
    clock.advance(1.0)
    rw.sample()
    pt = tracker.report()["availability"]["windows"]["10s"]
    assert pt["total"] == 100 and pt["bad"] == 5
    assert pt["ratio"] == pytest.approx(0.95)
    # burn = (1 - 0.95) / (1 - 0.999): 50x the sustainable error rate
    assert pt["burn"] == pytest.approx(50.0)


def test_slo_latency_burn_and_gauges():
    reg, clock = obs_metrics.Registry(), FakeClock()
    rw = _slo_windows(reg, clock)
    tracker = obs_slo.SLOTracker(
        rw, slos=(obs_slo.SLO("latency", 0.99, threshold_ms=50.0),))
    h = reg.histogram(obs_slo._LATENCY_HIST)
    for _ in range(8):
        h.observe(0.001)
    for _ in range(2):
        h.observe(1.0)
    clock.advance(1.0)
    rw.sample()
    pt = tracker.report()["latency"]["windows"]["10s"]
    assert pt["total"] == 10
    assert pt["ratio"] == pytest.approx(0.8)
    assert pt["burn"] == pytest.approx(0.2 / 0.01)
    tracker.set_gauges(reg)
    text = reg.render_text()
    assert "mri_slo_latency_ratio_10s 0.8" in text
    assert "mri_slo_latency_burn_1m" in text


def test_default_slos_read_knobs(monkeypatch):
    monkeypatch.setenv("MRI_OBS_SLO_TARGET", "0.95")
    monkeypatch.setenv("MRI_OBS_SLO_LATENCY_MS", "12.5")
    avail, lat = obs_slo.default_slos()
    assert avail.target == lat.target == 0.95
    assert avail.threshold_ms is None and lat.threshold_ms == 12.5
    assert avail.budget() == pytest.approx(0.05)


# -- Watchdog --------------------------------------------------------------


def test_watchdog_fires_once_per_episode_and_recovers():
    clock = FakeClock()
    reg = obs_metrics.Registry()
    stalls, recoveries = [], []
    wd = obs_watchdog.Watchdog(
        100.0, on_stall=lambda n, age: stalls.append((n, age)),
        on_recover=recoveries.append, registry=reg, clock=clock)
    wd.register("dispatcher")
    assert wd.check() == [] and wd.stalled() == []
    clock.advance(0.2)  # 200ms > the 100ms threshold
    assert wd.check() == ["dispatcher"]
    assert len(stalls) == 1 and stalls[0][0] == "dispatcher"
    assert stalls[0][1] == pytest.approx(200.0)
    # still stalled: no re-fire within the same episode
    clock.advance(0.2)
    assert wd.check() == ["dispatcher"] and len(stalls) == 1
    assert reg.counter(obs_watchdog.STALLS_TOTAL).value == 1
    # heartbeat resumes: recovery fires, a new episode can fire again
    wd.beat("dispatcher")
    assert wd.check() == []
    assert recoveries == ["dispatcher"]
    clock.advance(0.2)
    assert wd.check() == ["dispatcher"] and len(stalls) == 2
    assert reg.counter(obs_watchdog.STALLS_TOTAL).value == 2


def test_watchdog_zero_threshold_disables():
    wd = obs_watchdog.Watchdog(0.0)
    assert not wd.enabled
    wd.start()
    assert wd._thread is None  # start() is a no-op when disabled
    wd.register("x")
    assert wd.check() == []


def test_watchdog_ages_and_callback_exceptions_swallowed():
    clock = FakeClock()
    wd = obs_watchdog.Watchdog(
        50.0, on_stall=lambda n, a: 1 / 0, clock=clock)
    wd.register("a")
    clock.advance(0.1)
    assert wd.ages_ms()["a"] == pytest.approx(100.0)
    assert wd.max_age_s() == pytest.approx(0.1)
    assert wd.check() == ["a"]  # the ZeroDivisionError never escapes


# -- structured logging ----------------------------------------------------


@pytest.fixture
def _fresh_logging():
    yield
    obs_logging.reset()


def test_emit_text_format(_fresh_logging):
    stream = io.StringIO()
    obs_logging.configure(stream)
    log = logging.getLogger("mri_tpu.test_text")
    obs_logging.emit(log, "hello", level=logging.WARNING, a=1, b="x")
    line = stream.getvalue().strip()
    assert line.startswith("WARNING mri_tpu.test_text: ")
    payload = json.loads(line.split(": ", 1)[1])
    assert payload == {"event": "hello", "a": 1, "b": "x"}


def test_emit_json_format(monkeypatch, _fresh_logging):
    monkeypatch.setenv("MRI_OBS_LOG_FORMAT", "json")
    stream = io.StringIO()
    obs_logging.configure(stream)
    log = logging.getLogger("mri_tpu.test_json")
    obs_logging.emit(log, "hello", a=1)
    rec = json.loads(stream.getvalue().strip())
    assert rec["event"] == "hello" and rec["a"] == 1
    assert rec["level"] == "INFO" and rec["logger"] == "mri_tpu.test_json"
    assert isinstance(rec["ts"], float)
    # reconfigure back to text swaps the formatter without stacking
    monkeypatch.setenv("MRI_OBS_LOG_FORMAT", "text")
    obs_logging.configure(stream)
    root = logging.getLogger(obs_logging.ROOT_LOGGER)
    assert sum(1 for h in root.handlers
               if getattr(h, "_mri_obs_handler", False)) == 1


def test_emit_rate_limit_counts_drops(monkeypatch, _fresh_logging):
    monkeypatch.setenv("MRI_OBS_LOG_RATE_LIMIT", "1")
    stream = io.StringIO()
    obs_logging.configure(stream)
    log = logging.getLogger("mri_tpu.test_rate")
    dropped = obs_metrics.default_registry().counter(
        "mri_obs_log_dropped_total")
    before = dropped.value
    for i in range(50):
        obs_logging.emit(log, "burst", i=i)
    lines = [ln for ln in stream.getvalue().splitlines() if ln]
    # 1/sec allowed; the loop may straddle one second boundary
    assert 1 <= len(lines) <= 2
    assert dropped.value - before == 50 - len(lines)
    # a different event key has its own bucket
    obs_logging.emit(log, "other")
    assert "other" in stream.getvalue()


# -- daemon surfaces -------------------------------------------------------


@pytest.mark.daemon
@pytest.mark.serve
def test_daemon_slo_op_and_stats_blocks(built):
    with serving(built) as d, Client(d) as cli:
        for i in range(4):
            assert cli.rpc(id=i, op="df", terms=["cat"])["ok"]
        r = cli.rpc(op="slo")
        assert r["ok"]
        assert set(r["slo"]) == {"availability", "latency"}
        for entry in r["slo"].values():
            assert set(entry["windows"]) == {"10s", "1m", "5m"}
            for pt in entry["windows"].values():
                assert 0.0 <= pt["ratio"] <= 1.0 and pt["burn"] >= 0.0
        assert r["slo"]["latency"]["threshold_ms"] == \
            obs_slo.slo_latency_ms()
        st = cli.rpc(op="stats")["stats"]
        assert set(st["rolling"]) == {"10s", "1m", "5m"}
        for w in st["rolling"].values():
            assert {"qps", "shed_per_s", "error_per_s",
                    "p50_ms", "p99_ms"} <= set(w)
        assert set(st["slo"]) == {"availability", "latency"}


@pytest.mark.daemon
@pytest.mark.serve
def test_daemon_healthz_liveness_vs_readiness(built):
    with serving(built) as d, Client(d) as cli:
        h = cli.rpc(op="healthz")
        assert h["ok"] is True and h["live"] is True
        assert h["ready"] is True and h["reasons"] == []
        assert h["status"] == "ok"
        # a reload in flight flips readiness, never liveness
        d._reloading = True
        try:
            h = cli.rpc(op="healthz")
            assert h["ok"] is True and h["live"] is True
            assert h["ready"] is False and h["reasons"] == ["reloading"]
            assert h["status"] == "reloading"
        finally:
            d._reloading = False


@pytest.mark.daemon
@pytest.mark.serve
def test_daemon_scrape_has_health_gauges(built):
    with serving(built) as d, Client(d) as cli:
        assert cli.rpc(id=1, op="df", terms=["cat"])["ok"]
        text = cli.rpc(op="metrics")["text"]
        for name in ("mri_slo_availability_ratio_10s",
                     "mri_slo_availability_burn_5m",
                     "mri_slo_latency_ratio_1m",
                     "mri_watchdog_heartbeat_age_seconds"):
            assert f"\n{name} " in text, name
        vals = {}
        for line in text.splitlines():
            if line and not line.startswith("#") \
                    and "{" not in line.split(" ", 1)[0]:
                name, _, v = line.partition(" ")
                vals[name] = float(v)
        assert 0.0 <= vals["mri_slo_availability_ratio_10s"] <= 1.0
        # live heartbeats: well under the 5s default stall threshold
        assert vals["mri_watchdog_heartbeat_age_seconds"] < 5.0


@pytest.mark.daemon
@pytest.mark.serve
def test_daemon_obs_surfaces_under_concurrent_churn(built):
    """Trace ring + slow-query log + windows sampler while queries,
    hot reloads and scrapes all run concurrently: every response is
    answered, the final exposition has no duplicate families, and the
    rolling stats stay well-formed."""
    errors = []
    with serving(built) as d:
        stop = threading.Event()

        def hammer(wid):
            try:
                with Client(d) as cli:
                    i = 0
                    while not stop.is_set():
                        r = cli.rpc(id=i, op="df", terms=["cat"],
                                    trace_id=f"w{wid}-{i}")
                        assert r["ok"], r
                        i += 1
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        def scraper():
            try:
                with Client(d) as cli:
                    while not stop.is_set():
                        assert cli.rpc(op="metrics")["ok"]
                        assert cli.rpc(op="trace", n=8)["ok"]
                        assert cli.rpc(op="stats")["ok"]
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=hammer, args=(w,))
                   for w in range(2)] + [threading.Thread(target=scraper)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 1.5
        reloads = 0
        while time.monotonic() < deadline:
            ok, _msg = d.reload()
            assert ok
            reloads += 1
            time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive()
        assert not errors, errors
        assert reloads >= 3
        with Client(d) as cli:
            text = cli.rpc(op="metrics")["text"]
            fams = [ln.split()[2] for ln in text.splitlines()
                    if ln.startswith("# TYPE ")]
            assert len(fams) == len(set(fams))
            traces = cli.rpc(op="trace", n=16)["traces"]
            assert traces and all(t["status"] == "ok" for t in traces)
            st = cli.rpc(op="stats")["stats"]
            assert st["counters"]["requests"] > 0
            assert st["counters"]["internal_errors"] == 0
            assert st["counters"]["reload_ok"] == reloads
            for w in st["rolling"].values():
                assert w["qps"] >= 0.0


# -- mri top ---------------------------------------------------------------


@pytest.mark.daemon
@pytest.mark.serve
def test_top_once_json_parity_with_raw_ops(built, capsys):
    with serving(built) as d, Client(d) as cli:
        for i in range(3):
            assert cli.rpc(id=i, op="df", terms=["dog"])["ok"]
        stats = cli.rpc(op="stats")["stats"]
        slo = cli.rpc(op="slo")["slo"]
        host, port = d.address
        assert cli_main(["top", f"{host}:{port}", "--once",
                         "--json"]) == 0
        sample = json.loads(capsys.readouterr().out)
        # admission counters are frozen on the quiescent daemon;
        # responses/connections move with every admin RPC (including
        # top's own poll), so those are gated monotone, not exact
        top_counters = dict(sample["stats"]["counters"])
        want = dict(stats["counters"])
        for key in ("responses", "connections"):
            assert top_counters.pop(key) >= want.pop(key)
        assert top_counters == want
        h = sample["healthz"]
        assert h["ok"] and h["live"] and h["ready"]
        assert set(sample["slo"]) == set(slo)
        for name, entry in sample["slo"].items():
            assert entry["target"] == slo[name]["target"]
            assert set(entry["windows"]) == {"10s", "1m", "5m"}


@pytest.mark.daemon
@pytest.mark.serve
def test_top_plain_frame_renders(built, capsys):
    with serving(built) as d, Client(d) as cli:
        assert cli.rpc(id=1, op="df", terms=["cat"])["ok"]
        host, port = d.address
        assert cli_main(["top", f"{host}:{port}", "--once"]) == 0
        frame = capsys.readouterr().out
        assert "ready" in frame
        assert "slo availability" in frame and "slo latency" in frame
        for label in ("10s", "1m", "5m"):
            assert label in frame


def test_top_static_dir_mode(built, capsys):
    assert cli_main(["top", str(built), "--once", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert "engine" in doc
    assert "mri_engine_vocab_terms" in doc["metrics_text"]


def test_top_unreachable_addr_exit_2(capsys):
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    assert cli_main(["top", f"127.0.0.1:{port}", "--once",
                     "--timeout", "2"]) == 2
    assert "error" in capsys.readouterr().err


# -- the stall contract (subprocess) ---------------------------------------


def _spawn_serve(out, *extra, env_extra=None):
    env = dict(os.environ, PYTHONPATH=str(REPO_ROOT), JAX_PLATFORMS="cpu")
    if env_extra:
        env.update(env_extra)
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "parallel_computation_of_an_inverted_index_using_map_reduce_tpu",
         "serve", str(out), "--listen", "127.0.0.1:0", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        cwd=str(REPO_ROOT), text=True)
    line = proc.stdout.readline()
    if not line:
        proc.wait(timeout=10)
        raise AssertionError(
            f"daemon died on startup: {proc.stderr.read()}")
    ready = json.loads(line)
    assert ready["event"] == "listening"
    return proc, (ready["host"], ready["port"])


@pytest.mark.daemon
@pytest.mark.serve
@pytest.mark.faults
def test_cli_dispatcher_hang_flips_readiness_and_dumps(tmp_path):
    """The acceptance contract: an injected dispatcher hang must flip
    healthz readiness to `stalled` within 2x MRI_OBS_STALL_MS, bump
    mri_watchdog_stalls_total, drop a flight-<pid>-stall.json next to
    the artifact, recover when the hang ends, and still drain to 0."""
    stall_ms, hang_ms = 400.0, 2000.0
    out = build_corpus(tmp_path, DOCS)
    proc, addr = _spawn_serve(
        out, "--fault-spec", f"dispatcher-hang:ms={hang_ms:.0f}",
        env_extra={"MRI_OBS_STALL_MS": str(stall_ms)})
    try:
        with Client(addr) as trigger, Client(addr) as probe:
            # healthz answers inline from the reader thread, so the
            # probe keeps working while the dispatcher is wedged
            trigger.send(id=1, op="df", terms=["cat"])
            t0 = time.monotonic()
            flip = None
            deadline = t0 + 2 * stall_ms / 1e3 + 2.0
            while time.monotonic() < deadline:
                h = probe.rpc(op="healthz")
                assert h["ok"] is True, h  # liveness never flips
                if not h["ready"] and "stalled" in h["reasons"]:
                    flip = (time.monotonic() - t0) * 1e3
                    break
                time.sleep(0.02)
            assert flip is not None, "readiness never flipped to stalled"
            assert flip <= 2 * stall_ms + 2000.0

            vals = {}
            for line in probe.rpc(op="metrics")["text"].splitlines():
                if line and not line.startswith("#"):
                    name, _, v = line.partition(" ")
                    if "{" not in name:
                        vals[name] = float(v)
            assert vals["mri_watchdog_stalls_total"] >= 1

            dump = out / f"flight-{proc.pid}-stall.json"
            for _ in range(100):  # the dump is written off-thread
                if dump.exists():
                    break
                time.sleep(0.05)
            doc = json.loads(dump.read_text(encoding="utf-8"))
            assert doc, "stall flight dump is empty"

            # the hang ends: the wedged request answers, health recovers
            r = trigger.recv()
            assert r["ok"] and r["id"] == 1
            deadline = time.monotonic() + hang_ms / 1e3 + 5.0
            while time.monotonic() < deadline:
                h = probe.rpc(op="healthz")
                if h["ready"]:
                    break
                time.sleep(0.05)
            assert h["ready"] and "stalled" not in h["reasons"]

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
        assert rc == 0
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()
        proc.stdout.close()
        proc.stderr.close()
