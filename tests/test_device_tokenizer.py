"""All-device engine (ops/device_tokenizer.py + device_tokenize=True):
the entire map phase as one XLA program over raw corpus bytes.

Exactness contract: byte-identical to the oracle for every corpus whose
cleaned tokens fit ``device_tokenize_width``; anything longer trips
WidthOverflow and falls back to the host-scan path — so output is
byte-identical ALWAYS, and the engine never silently truncates."""

import numpy as np
import pytest

from conftest import read_letter_files

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    IndexConfig,
    InvertedIndexModel,
    build_index,
    oracle_index,
    read_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
    write_manifest,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
    write_corpus,
    zipf_corpus,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import (
    device_tokenizer as DT,
)


def _cfg(**kw):
    kw.setdefault("backend", "tpu")
    kw.setdefault("device_tokenize", True)
    kw.setdefault("pad_multiple", 256)
    kw.setdefault("device_shards", 1)  # 8 virtual devices otherwise -> dist
    return IndexConfig(**kw)


def test_matches_goldens_smoke(smoke_fixture, tmp_path):
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    report = InvertedIndexModel(_cfg()).run(m, output_dir=tmp_path)
    assert "host_views" in report["phases_ms"]  # really took the device engine
    assert "load" in report["phases_ms"]
    assert read_letter_files(tmp_path) == read_letter_files(smoke_fixture / "golden")


@pytest.mark.parametrize("seed", [2, 9])
def test_property_random_corpus_vs_oracle(tmp_path, seed):
    docs = zipf_corpus(num_docs=37, vocab_size=800, tokens_per_doc=60, seed=seed)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    build_index(m, _cfg(), output_dir=tmp_path / "dev")
    assert read_letter_files(tmp_path / "dev") == read_letter_files(tmp_path / "oracle")


def test_tokenizer_edge_cases(tmp_path):
    """The §2.3 contract cases through the device byte classifier."""
    docs = [b"don't foo-bar x1y2z3 I.Loomings cafe\xcc\x81 42 --- UPPER",
            b"a  b\tc\nd\ve\ff\rg", b"", b"ab ab\x00 ab"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    build_index(m, _cfg(), output_dir=tmp_path / "dev")
    assert read_letter_files(tmp_path / "dev") == read_letter_files(tmp_path / "oracle")


def test_width_overflow_falls_back_exactly(tmp_path):
    """A cleaned token longer than the row width must abort to the host
    path and still produce byte-identical output."""
    docs = [b"short words here", b"a" * 30 + b" tail", b"end doc"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    report = InvertedIndexModel(
        _cfg(device_tokenize_width=16)).run(m, output_dir=tmp_path / "dev")
    assert "device_tokenize_fallback" in report
    assert "aborted_device_tokenize" in report["phases_ms"]
    assert read_letter_files(tmp_path / "dev") == read_letter_files(tmp_path / "oracle")


def test_over_299_letter_token_falls_back(tmp_path):
    """Tokens past the reference's own 299-letter cap (main.c:105) can
    never be represented in a device row; the guard must fire."""
    docs = [b"x" * 400 + b" normal words"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    report = InvertedIndexModel(_cfg()).run(m, output_dir=tmp_path / "dev")
    assert "device_tokenize_fallback" in report
    assert read_letter_files(tmp_path / "dev") == read_letter_files(tmp_path / "oracle")


def test_empty_and_allspace_corpus(tmp_path):
    docs = [b"", b"  \t \r\n "]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    InvertedIndexModel(_cfg()).run(m, output_dir=tmp_path / "dev")
    assert read_letter_files(tmp_path / "dev") == b""


def test_numbers_only_corpus(tmp_path):
    docs = [b"123 456", b"--- !!!"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    InvertedIndexModel(_cfg()).run(m, output_dir=tmp_path / "dev")
    assert read_letter_files(tmp_path / "dev") == b""


def test_config_validation():
    with pytest.raises(ValueError, match="backend"):
        IndexConfig(backend="cpu", device_tokenize=True)
    with pytest.raises(ValueError, match="host-scan"):
        IndexConfig(device_tokenize=True, overlap_tail_fraction=0.4)
    # device_tokenize + stream_chunk_docs is the STREAMING all-device
    # engine — single-chip (ops/device_streaming.py) or mesh
    # (parallel/dist_device_streaming.py)
    IndexConfig(device_tokenize=True, stream_chunk_docs=10)
    IndexConfig(device_tokenize=True, stream_chunk_docs=10, device_shards=4)
    with pytest.raises(ValueError, match="skew"):
        IndexConfig(device_tokenize=True, collect_skew_stats=True)
    with pytest.raises(ValueError, match="device_tokenize_width"):
        IndexConfig(device_tokenize_width=30)  # not a multiple of 4
    with pytest.raises(ValueError, match="device_tokenize_width"):
        IndexConfig(device_tokenize_width=300)  # could hide the 299 cap


def test_tiny_docs_tok_cap_bound(tmp_path):
    # One-byte docs: up to one token per byte (doc boundaries split
    # tokens) -- the review-found tok_cap crash regression test.
    docs = [b"a"] * 64
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    build_index(m, _cfg(pad_multiple=64), output_dir=tmp_path / "dev")
    assert read_letter_files(tmp_path / "dev") == read_letter_files(tmp_path / "oracle")


# -- mesh variant (parallel/dist_device_tokenizer.py) ---------------------


def _dist_cfg(**kw):
    kw.setdefault("device_shards", None)  # all 8 virtual devices
    return _cfg(**kw)


def _needs_mesh():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("mesh device tokenizer needs >= 2 devices")


def test_dist_matches_goldens_smoke(smoke_fixture, tmp_path):
    _needs_mesh()
    m = read_manifest(smoke_fixture / "manifest.txt", base_dir=smoke_fixture)
    report = InvertedIndexModel(_dist_cfg()).run(m, output_dir=tmp_path)
    assert report["device_shards"] > 1  # really took the mesh engine
    assert "exchange_capacity" in report
    assert read_letter_files(tmp_path) == read_letter_files(smoke_fixture / "golden")


@pytest.mark.parametrize("seed", [4, 13])
def test_dist_property_vs_oracle(tmp_path, seed):
    _needs_mesh()
    docs = zipf_corpus(num_docs=41, vocab_size=700, tokens_per_doc=55, seed=seed)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    build_index(m, _dist_cfg(), output_dir=tmp_path / "dev")
    assert read_letter_files(tmp_path / "dev") == read_letter_files(tmp_path / "oracle")


def test_dist_matches_single_chip(tmp_path):
    _needs_mesh()
    docs = zipf_corpus(num_docs=29, vocab_size=400, tokens_per_doc=45, seed=21)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    build_index(m, _cfg(), output_dir=tmp_path / "one")
    build_index(m, _dist_cfg(), output_dir=tmp_path / "mesh")
    assert read_letter_files(tmp_path / "mesh") == read_letter_files(tmp_path / "one")


def test_dist_fewer_docs_than_chips(tmp_path):
    _needs_mesh()
    docs = [b"alpha beta", b"beta gamma"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    build_index(m, _dist_cfg(), output_dir=tmp_path / "dev")
    assert read_letter_files(tmp_path / "dev") == read_letter_files(tmp_path / "oracle")


@pytest.mark.parametrize("seed", [6, 17])
def test_dist_letter_emit_vs_oracle(tmp_path, seed):
    """Letter-ownership emit on the mesh device engine: owners hold
    whole letter ranges (main.c:129-150 at raw-text level) and emit
    their own files — no global merge anywhere."""
    _needs_mesh()
    docs = zipf_corpus(num_docs=31, vocab_size=600, tokens_per_doc=50,
                       seed=seed)
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    report = InvertedIndexModel(
        _dist_cfg(emit_ownership="letter")).run(m, output_dir=tmp_path / "dev")
    assert report.get("emit_ownership") == "letter"
    assert "letter_owners" in report
    assert read_letter_files(tmp_path / "dev") == read_letter_files(
        tmp_path / "oracle")


def test_dist_letter_emit_single_chip_rejected(tmp_path):
    docs = [b"alpha beta"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    with pytest.raises(ValueError, match="multi-chip"):
        build_index(m, _cfg(emit_ownership="letter"),
                    output_dir=tmp_path / "dev")


def test_dist_width_overflow_falls_back(tmp_path):
    _needs_mesh()
    docs = [b"regular words", b"a" * 40 + b" tail"]
    paths = write_corpus(tmp_path / "docs", docs)
    write_manifest(tmp_path / "list.txt", paths)
    m = read_manifest(tmp_path / "list.txt")
    oracle_index(m, tmp_path / "oracle")
    report = InvertedIndexModel(
        _dist_cfg(device_tokenize_width=16)).run(m, output_dir=tmp_path / "dev")
    assert "device_tokenize_fallback" in report
    assert read_letter_files(tmp_path / "dev") == read_letter_files(tmp_path / "oracle")


def _pad_concat(docs, multiple=256):
    total = sum(len(d) for d in docs)
    padded = -(-max(total, 1) // multiple) * multiple
    buf = np.full(padded, 0x20, np.uint8)
    if total:
        buf[:total] = np.frombuffer(b"".join(docs), np.uint8)
    ends = np.cumsum([len(d) for d in docs]).astype(np.int32)
    return buf, ends


@pytest.mark.parametrize("docs", [
    [b"don't foo-bar x1y2z3 I.Loomings supercalifragilistic"],
    [b"a"] * 7 + [b"bb ccc"],
    [b"", b"   ", b"42 --- !!!"],
    [b"x" * 400 + b" tail", b"mid"],
    [b"word\tword\nword\vword\fword\rword"],
    [b"abc", b"", b"de"],  # zero-length doc between others
])
def test_max_cleaned_token_len_matches_python_reference(docs):
    """Host helper vs a trivially-correct per-doc Python scan (the
    reference's clean loop, main.c:105-111: letters-only length)."""
    expect = 0
    for d in docs:
        for tok in d.split():
            expect = max(expect, sum(1 for b in tok if
                                     (65 <= b <= 90) or (97 <= b <= 122)))
    buf, ends = _pad_concat(docs)
    assert DT.max_cleaned_token_len(buf, ends) == expect


def test_sort_cols_pass_skipping_is_exact(tmp_path):
    """index_bytes_device with the host-measured sort_cols bound must
    produce identical outputs to the full 13-pass sort."""
    import jax

    docs = [b"gamma beta alpha alpha", b"delta beta longishword here"]
    buf, ends = _pad_concat(docs)
    ids = np.arange(1, len(docs) + 1, dtype=np.int32)
    tok_cap = 256
    width = 48
    max_len = DT.max_cleaned_token_len(buf, ends)
    full = DT.index_bytes_device(
        jax.device_put(buf), jax.device_put(ends), jax.device_put(ids),
        width=width, tok_cap=tok_cap, num_docs=len(docs))
    skip = DT.index_bytes_device(
        jax.device_put(buf), jax.device_put(ends), jax.device_put(ids),
        width=width, tok_cap=tok_cap, num_docs=len(docs),
        sort_cols=-(-max_len // 4))
    for k in ("counts", "df", "postings"):
        np.testing.assert_array_equal(np.asarray(full[k]), np.asarray(skip[k]))
    for (ah, al), (bh, bl) in zip(full["unique_groups"],
                                  skip["unique_groups"]):
        np.testing.assert_array_equal(np.asarray(ah), np.asarray(bh))
        np.testing.assert_array_equal(np.asarray(al), np.asarray(bl))


@pytest.mark.parametrize("width", [40, 48, 64])
@pytest.mark.parametrize("docs", [
    [b"don't foo-bar x1y2z3 I.Loomings tail42", b"", b"  42 ",
     b"pack my box with five dozen liquor jugs"],
    [b"supercalifragilisticexpialidocious antidisestablishmentarianism",
     b"zz top zz top aa"],
    # 39- and 37-letter words reach into the partial last group at
    # width 40 (chars 36-39 of a 36..41 window)
    [b"a" * 39 + b" zz " + b"q" * 37, b"mid"],
])
def test_tokenize_groups_matches_pack_of_tokenize_rows(docs, width):
    """The 5-bit group frontend must emit EXACTLY
    pack_groups(tokenize_rows(x)) padded with zero pairs — the
    property that lets tokenize_rows stand as the directly-
    byte-addressed reference implementation.  Widths 40 and 64 are NOT
    multiples of 12, so the last group's window reaches past the row
    and the width cap in tokenize_groups' mask is what keeps the two
    frontends identical there."""
    import jax

    buf, ends = _pad_concat(docs)
    ids = np.arange(1, len(docs) + 1, dtype=np.int32)
    kw = dict(width=width, tok_cap=256, num_docs=len(docs))
    args = (jax.device_put(buf), jax.device_put(ends), jax.device_put(ids))
    max_len = DT.max_cleaned_token_len(buf, ends)
    sort_cols = -(-max_len // 4)

    cols, doc_r, len_r, cnt_r = jax.jit(
        lambda *a: DT.tokenize_rows(*a, **kw))(*args)
    nsort = DT.clamp_sort_cols(sort_cols, len(cols))
    ref_groups = DT.pack_groups(
        DT.zero_tail_cols(cols, nsort, 256), nsort)

    groups, doc_g, len_g, cnt_g = jax.jit(
        lambda *a: DT.tokenize_groups(*a, **kw, sort_cols=sort_cols))(*args)
    assert len(groups) == DT.num_groups_for(width)
    assert int(len_r) == int(len_g)
    assert int(cnt_r) == int(cnt_g)
    np.testing.assert_array_equal(np.asarray(doc_r), np.asarray(doc_g))
    for g, (hi, lo) in enumerate(groups):
        if g < len(ref_groups):
            eh, el = ref_groups[g]
        else:
            eh = el = np.zeros(256, np.int32)
        np.testing.assert_array_equal(np.asarray(hi), np.asarray(eh))
        np.testing.assert_array_equal(np.asarray(lo), np.asarray(el))


def test_device_program_has_no_token_scale_scatter():
    """Design guard: TPU scatter lowers to a serial per-update loop
    (~75 ns/update measured), so the device program must stay
    scatter-free at token scale — only the two num_docs-sized
    doc-boundary scatters are allowed.  Lower the jit and count."""
    import re

    import jax

    num_docs, tok_cap, n = 4, 256, 1024
    lowered = jax.jit(
        lambda d, e, i: DT.index_bytes_device(
            d, e, i, width=48, tok_cap=tok_cap, num_docs=num_docs)
    ).lower(
        jax.ShapeDtypeStruct((n,), np.uint8),
        jax.ShapeDtypeStruct((num_docs,), np.int32),
        jax.ShapeDtypeStruct((num_docs,), np.int32),
    )
    text = lowered.as_text()
    # exactly the three num_docs-sized doc-boundary scatters survive:
    # doc_starts .at[ends].set / .at[0].set, and the doc-slot
    # scatter-max — every one carries <= num_docs-1 updates
    scatters = re.findall(r'= "stablehlo\.scatter"', text)
    assert len(scatters) == 3, (
        f"{len(scatters)} scatter ops in the device program (expected "
        "the 3 tiny doc-boundary ones) — token-scale compactions must "
        "stay sort/gather formulations")
    # and NO loops: jnp.searchsorted's default method='scan' lowers to
    # a sequential log2(n) while-loop of dynamic slices, the round-3
    # regression's root cause (702 ms at 2^20 queries into 5.7M on the
    # v5e, BENCH_TPU_r03.json) — the program must stay loop-free
    assert 'stablehlo.while' not in text, (
        "a while loop appeared in the device program — most likely a "
        "scan-lowered searchsorted crept back in; use "
        "segment.searchsorted_device / segment.set_bit_positions")


def test_decode_word_groups_roundtrip():
    """Host decoder vs pack_groups on hand-built byte columns: the
    5-bit group pairs must decode back to the original words."""
    words = [b"cat", b"aardvark", b"z" * 12, b"q" * 16]
    width = 16
    rows = np.zeros((len(words), width), np.uint8)
    for i, w in enumerate(words):
        rows[i, : len(w)] = np.frombuffer(w, np.uint8)
    r32 = rows.reshape(len(words), width // 4, 4).astype(np.int64)
    cols = [
        ((r32[:, c, 0] << 24) | (r32[:, c, 1] << 16)
         | (r32[:, c, 2] << 8) | r32[:, c, 3]).astype(np.int32)
        for c in range(width // 4)
    ]
    import jax.numpy as jnp

    groups = DT.pack_groups([jnp.asarray(c) for c in cols], width // 4)
    decoded = DT.decode_word_groups(
        [(np.asarray(h), np.asarray(l)) for h, l in groups], width)
    assert [w.rstrip(b"\x00") for w in decoded.tolist()] == words


def test_two_key_letter_compaction_branch_matches(monkeypatch):
    """The n >= 2^24 letter-compaction fallback (the (flag, position)
    key no longer fits in one int32, tokenize_rows) must agree exactly
    with the one-key path — forced here by dropping the module
    threshold so both branches run on the same small buffer."""
    import jax

    docs = [b"don't foo-bar x1y2z3 I.Loomings tail42", b"", b"  42 ",
            b"pack my box with five dozen liquor jugs"]
    buf, ends = _pad_concat(docs)
    ids = np.arange(1, len(docs) + 1, dtype=np.int32)
    kw = dict(width=48, tok_cap=256, num_docs=len(docs))
    args = (jax.device_put(buf), jax.device_put(ends), jax.device_put(ids))

    one = jax.jit(lambda *a: DT.tokenize_rows(*a, **kw))(*args)
    monkeypatch.setattr(DT, "_ONE_KEY_COMPACTION_LIMIT", 0)
    two = jax.jit(lambda *a: DT.tokenize_rows(*a, **kw))(*args)

    one_cols, one_doc, one_len, one_cnt = one
    two_cols, two_doc, two_len, two_cnt = two
    assert int(one_len) == int(two_len)
    assert int(one_cnt) == int(two_cnt)
    np.testing.assert_array_equal(np.asarray(one_doc), np.asarray(two_doc))
    for a, b in zip(one_cols, two_cols):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_tokenize_rows_buffer_ending_in_letter():
    """A buffer whose LAST byte is a letter (no trailing pad) must
    tokenize exactly: the compaction tail and the final token's length
    come from the clamped start-byte gather, which must not read past
    the exclusive-cumsum array.  (This input guarded the removed
    searchsorted compaction variant; kept for the sort path.)"""
    import jax

    docs = [b"don't foo-bar x1y2z3 I.Loomings tail42", b"", b"  42 ",
            b"pack my box with five dozen liquor jugz"]  # ends in a letter
    buf, ends = _pad_concat(docs)
    buf = buf[: int(ends[-1])]  # no trailing pad: last byte IS a letter
    ids = np.arange(1, len(docs) + 1, dtype=np.int32)
    kw = dict(width=48, tok_cap=256, num_docs=len(docs))
    args = (jax.device_put(buf), jax.device_put(ends), jax.device_put(ids))

    trunc = jax.jit(lambda *a: DT.tokenize_rows(*a, **kw))(*args)
    pad_buf, _ = _pad_concat(docs)  # same docs, space-padded tail
    padded = jax.jit(lambda *a: DT.tokenize_rows(*a, **kw))(
        jax.device_put(pad_buf), jax.device_put(ends), jax.device_put(ids))

    t_cols, t_doc, t_len, t_cnt = trunc
    p_cols, p_doc, p_len, p_cnt = padded
    assert int(t_len) == int(p_len)
    assert int(t_cnt) == int(p_cnt)
    np.testing.assert_array_equal(np.asarray(t_doc), np.asarray(p_doc))
    for a, b in zip(t_cols, p_cols):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_native_token_stats_matches_numpy_mirror():
    """mri_token_stats (SIMD masks) vs the numpy mirror on edge cases:
    inner doc boundaries splitting runs, letter as the last byte,
    non-space bytes past the last doc end, zero-length docs, padded
    equal ends, empty input."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        native,
    )

    if not native.available():
        pytest.skip("native tokenizer unavailable")

    def both(buf, ends):
        got = native.token_stats(buf, ends)
        want = DT._host_token_stats_numpy(buf, ends)
        assert got == want, (got, want, bytes(buf), ends.tolist())
        return got

    cases = []
    # handcrafted edges
    b = np.frombuffer(b"abXcd ef", np.uint8).copy()
    cases.append((b, np.array([4, 8], np.int64)))       # boundary mid-token
    cases.append((b, np.array([8], np.int64)))          # single doc
    cases.append((b, np.array([2, 2, 8], np.int64)))    # zero-length doc
    cases.append((b, np.array([3], np.int64)))          # bytes past last end
    cases.append((np.frombuffer(b"  42!  ", np.uint8).copy(),
                  np.array([7], np.int64)))             # letterless token
    cases.append((np.frombuffer(b"z", np.uint8).copy(),
                  np.array([1], np.int64)))             # last byte a letter
    # padded-ends shape the streaming feed uses
    pad = np.full(64, 0x20, np.uint8)
    pad[:11] = np.frombuffer(b"hello world", np.uint8)
    cases.append((pad, np.array([5, 11, 64, 64], np.int64)))
    # randomized sweep incl. >64-byte tokens spanning mask words
    rng = np.random.default_rng(9)
    alphabet = np.frombuffer(b"ab XY.9\t\n-z", np.uint8)
    for _ in range(25):
        n = int(rng.integers(1, 400))
        buf = rng.choice(alphabet, n).astype(np.uint8)
        k = int(rng.integers(1, 6))
        ends = np.sort(rng.integers(0, n + 1, k)).astype(np.int64)
        ends[-1] = int(rng.integers(0, n + 1))
        ends = np.sort(ends)
        cases.append((buf, ends))
    cases.append((np.frombuffer(b"a" * 200 + b" " + b"b" * 70, np.uint8).copy(),
                  np.array([271], np.int64)))           # long tokens
    for buf, ends in cases:
        both(buf, ends)

    # non-monotonic / negative ends: the native path refuses (None) so
    # host_token_stats falls back to the numpy mirror instead of
    # double-scanning or reading out of bounds
    assert native.token_stats(b, np.array([9, 3, 11], np.int64)) is None
    assert native.token_stats(b, np.array([-1, 8], np.int64)) is None


@pytest.mark.parametrize("k,narrow", [(1, True), (3, True), (1, False)])
def test_fetch_pack_roundtrip(k, narrow):
    """fetch_pack's transfer set must reconstruct exactly the dense
    df/postings/unique_groups prefixes: packed postings (3 doc ids per
    int32 when they fit 10 bits / uint16 otherwise) and the SPARSE
    tail-group form (indices + values for >12-char words only)."""
    import jax

    docs = [b"short words here on every line",
            b"supercalifragilisticexpialidocious floccinaucinihilipilification",
            b"medium sized tokens xyz pneumonoultramicroscopicsilicovolcanoconiosis"]
    buf, ends = _pad_concat(docs)
    ids = np.arange(1, len(docs) + 1, dtype=np.int32)
    width, tok_cap = 48, 256
    max_len = DT.max_cleaned_token_len(buf, ends)
    sort_cols = -(-max_len // 4)
    out = DT.index_bytes_device(
        jax.device_put(buf), jax.device_put(ends), jax.device_put(ids),
        width=width, tok_cap=tok_cap, num_docs=len(docs),
        sort_cols=sort_cols)
    num_words, num_pairs, _, _, num_long = (
        int(v) for v in np.asarray(out["counts"]))
    assert num_long == 3  # the three >12-char words above
    live = DT.live_groups_for(sort_cols, width)
    nu = npairs = tok_cap
    nlong = 64
    packed = DT.fetch_pack(out, nu=nu, npairs=npairs, nlong=nlong,
                           k=k, live=live, narrow=narrow)

    dense_df = np.asarray(out["df"])[:num_words]
    dense_post = np.asarray(out["postings"])[:num_pairs]
    np.testing.assert_array_equal(
        np.asarray(packed["df"])[:num_words].astype(np.int32), dense_df)
    if not narrow:  # wide path must NOT narrow the dtypes
        assert np.asarray(packed["df"]).dtype == np.int32
        assert np.asarray(packed["post"]).dtype == np.int32
    np.testing.assert_array_equal(
        DT.unpack_postings(packed["post"], num_pairs, k), dense_post)

    # rebuild dense tails from the sparse transfer and compare
    idx = np.asarray(packed["long_idx"])[:num_long]
    for g in range(1, live):
        eh = np.asarray(out["unique_groups"][g][0])[:num_words]
        el = np.asarray(out["unique_groups"][g][1])[:num_words]
        h = np.zeros(num_words, np.int32)
        l = np.zeros(num_words, np.int32)
        th, tl = packed["tail"][g - 1]
        h[idx] = np.asarray(th)[:num_long]
        l[idx] = np.asarray(tl)[:num_long]
        np.testing.assert_array_equal(h, eh)
        np.testing.assert_array_equal(l, el)


@pytest.mark.parametrize("npairs", [1, 2, 3, 7, 4096])
def test_pack_unpack_postings_boundary_values(npairs):
    """pack_postings/unpack_postings at the 10-bit field boundary:
    doc ids up to 1023 (doc_pack_width's k=3 threshold is < 1024) and
    lengths not divisible by k must round-trip exactly."""
    import jax.numpy as jnp

    rng = np.random.default_rng(npairs)
    post = rng.integers(0, 1024, npairs).astype(np.int32)
    post[0] = 1023  # field-boundary value
    packed = np.asarray(DT.pack_postings(jnp.asarray(post), 3))
    assert packed.shape[0] == -(-npairs // 3)
    np.testing.assert_array_equal(
        DT.unpack_postings(packed, npairs, 3), post)
    # k=1 passthrough
    np.testing.assert_array_equal(
        DT.unpack_postings(post, npairs, 1), post)
    # the k selector: packing only when ids fit 10 bits
    assert DT.doc_pack_width(1023) == 3
    assert DT.doc_pack_width(1024) == 1
    assert DT.doc_pack_width(70000) == 1
