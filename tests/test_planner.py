"""Ranked-query planner suite: block-max pruning must be invisible.

The v2.1 artifact stores per-block (max tf, min doc-length) columns;
the planner uses them to skip blocks (BMW) or whole terms (MaxScore)
during BM25 top-k.  Pruning is an optimization, never an answer
change, so the core guarantee is byte-identity against exhaustive
scoring — checked here on an adversarial corpus whose document
frequencies straddle the 128-doc block boundary (1 / B-1 / B / B+1 /
2B / 300) and whose tf spikes park the max-score block first, in the
middle, and last within a term's posting list.

Also covered: the pre-v2.1 graceful fallback (v1 and plain-v2
artifacts answer exhaustively no matter what the planner knob says),
the planner's mode-selection rules as units, the per-engine
``bm25_corpus`` memo, the crossover ``auto`` engine's routing, and the
daemon trace ring carrying planner labels on ranked spans.
"""

import os
import time

import numpy as np
import pytest

from test_serve import build_corpus, naive_index
from test_format_v2 import build_corpus_fmt, word

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve import (
    AutoEngine, Engine, create_engine, load_artifact,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve import (
    artifact as artifact_mod,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve import (
    planner as planner_mod,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.artifact import (
    DEFAULT_BLOCK_SIZE, VERSION_V21, artifact_path,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.device_engine import (
    DeviceEngine,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.engine import (
    BM25_B, BM25_K1, CROSSOVER_ENV,
)
from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.serve.planner import (
    PLANNER_ENV, Planner, block_upper_bounds, resolve_planner,
)

pytestmark = pytest.mark.serve

B = DEFAULT_BLOCK_SIZE
N_DOCS = 300

#: dfs that straddle the block boundary: single-doc, B-1, B, B+1, 2B,
#: and a 3-block term with a partial tail.
TARGET_DFS = (1, B - 1, B, B + 1, 2 * B, N_DOCS)

#: tf-spiked terms (df = 300 each): the doc holding the spike decides
#: which block carries the term's max score — first, middle, or last.
SPIKES = {"first": 2, "mid": 160, "last": N_DOCS - 1}
SPIKE_TF = 30


def _df_term(j: int) -> str:
    return word(j)


def _spike_term(pos: str) -> str:
    return word(500 + list(SPIKES).index(pos))


def _adversarial_docs() -> list[bytes]:
    docs = []
    for d in range(N_DOCS):
        toks = [_df_term(j) for j, df in enumerate(TARGET_DFS) if d < df]
        for pos, spike_doc in SPIKES.items():
            tf = SPIKE_TF if d == spike_doc else 1
            toks += [_spike_term(pos)] * tf
        # unique filler varies doc lengths so blk_min_dl is non-trivial
        toks += [word(9000 + d)] * ((d * 7) % 13)
        docs.append(" ".join(toks).encode())
    return docs


@pytest.fixture(scope="module")
def adversarial_built(tmp_path_factory):
    docs = _adversarial_docs()
    out1 = build_corpus_fmt(tmp_path_factory.mktemp("pln_v1"), docs, 1)
    out2 = build_corpus_fmt(tmp_path_factory.mktemp("pln_v2"), docs, 2)
    out3 = build_corpus_fmt(tmp_path_factory.mktemp("pln_v21"), docs, 3)
    return out1, out2, out3


def _queries() -> list[list[str]]:
    """Singles, pairs, duplicates, a triple, and a missing term —
    every arm of the ranked path (lean small-query, essential-term,
    block-survivor, rescore-on-3+-occurrences)."""
    dfs = [_df_term(j) for j in range(len(TARGET_DFS))]
    sp = [_spike_term(p) for p in SPIKES]
    qs = [[t] for t in dfs + sp]
    qs += [[sp[0], sp[2]], [sp[1], dfs[0]], [sp[2], dfs[3]],
           [dfs[1], dfs[2]], [dfs[5], sp[0]], [sp[1], sp[1]],
           [dfs[4], dfs[4]]]
    qs += [[sp[0], sp[1], sp[2]], [dfs[5], sp[0], dfs[2]],
           [sp[2], sp[2], sp[2]]]
    qs += [["zzzzabsent"], ["zzzzabsent", sp[0]]]
    return qs


KS = (1, 5, B, 2 * N_DOCS)


def _pinned(monkeypatch, mode: str):
    monkeypatch.setenv(PLANNER_ENV, mode)


@pytest.mark.parametrize("mode", ["bmw", "maxscore"])
def test_pruned_modes_match_exhaustive_host(adversarial_built,
                                            monkeypatch, mode):
    """Warm engine: BMW and MaxScore answers are byte-identical to
    exhaustive scoring — same docs, same float64 bits, same tie order
    — at every k, across the boundary dfs and all spike positions."""
    _, _, out3 = adversarial_built
    with Engine(artifact_path(out3)) as eng:
        assert eng.artifact.version == VERSION_V21
        assert eng.artifact.has_block_scores
        for q in _queries():
            batch = eng.encode_batch(q)
            for k in KS:
                _pinned(monkeypatch, "exhaustive")
                ref = eng.top_k_scored(batch, k)
                _pinned(monkeypatch, mode)
                assert eng.top_k_scored(batch, k) == ref, (q, k)
        d = eng.planner.describe()
        assert d["ranked"][mode] > 0
        assert d["ranked"]["exhaustive"] > 0


@pytest.mark.parametrize("mode", ["bmw", "maxscore"])
def test_pruned_modes_match_exhaustive_cold_engine(adversarial_built,
                                                   monkeypatch, mode):
    """Cold engine per mode: nothing memoized, so the uncached
    block-decode arm runs — answers still byte-identical."""
    _, _, out3 = adversarial_built
    refs = {}
    _pinned(monkeypatch, "exhaustive")
    with Engine(artifact_path(out3)) as eng:
        for qi, q in enumerate(_queries()):
            batch = eng.encode_batch(q)
            for k in (1, 5, B):
                refs[(qi, k)] = eng.top_k_scored(batch, k)
    _pinned(monkeypatch, mode)
    with Engine(artifact_path(out3)) as eng:
        for qi, q in enumerate(_queries()):
            batch = eng.encode_batch(q)
            for k in (1, 5, B):
                assert eng.top_k_scored(batch, k) == refs[(qi, k)], (q, k)


def test_pruned_modes_match_exhaustive_device(adversarial_built,
                                              monkeypatch):
    """Device engine: the block-survivor scatter-add returns the same
    ranking as the device's own exhaustive kernel.  Scores compare at
    the float32 tolerance the device suite already uses (rel 1e-4):
    the block-window and term-window kernels round differently."""
    _, _, out3 = adversarial_built
    dfs = [_df_term(j) for j in range(len(TARGET_DFS))]
    sp = [_spike_term(p) for p in SPIKES]
    queries = [[sp[0]], [sp[0], sp[2]], [sp[1], dfs[0]],
               [dfs[5], sp[0]], [sp[1], sp[1]], [sp[0], sp[1], sp[2]]]
    with DeviceEngine(artifact_path(out3)) as dev:
        for q in queries:
            batch = dev.encode_batch(q)
            for k in (1, 10):
                _pinned(monkeypatch, "exhaustive")
                ref = dev.top_k_scored(batch, k)
                for mode in ("bmw", "maxscore"):
                    _pinned(monkeypatch, mode)
                    got = dev.top_k_scored(batch, k)
                    assert [d for d, _ in got] == \
                        [d for d, _ in ref], (q, k, mode)
                    for (_, gs), (_, rs) in zip(got, ref):
                        assert gs == pytest.approx(rs, rel=1e-4), \
                            (q, k, mode)
        d = dev.planner.describe()
        assert d["ranked"]["bmw"] > 0
        assert d["ranked"]["maxscore"] > 0


@pytest.mark.parametrize("fmt", [1, 2])
def test_pre_v21_artifacts_fall_back_to_exhaustive(adversarial_built,
                                                   monkeypatch, fmt):
    """v1 and plain-v2 artifacts have no block-score columns: a forced
    pruning mode silently answers exhaustively, with the fallback
    visible in the planner counters."""
    out = adversarial_built[fmt - 1]
    q = [_spike_term("first"), _df_term(5)]
    with Engine(artifact_path(out)) as eng:
        assert not eng.artifact.has_block_scores
        batch = eng.encode_batch(q)
        _pinned(monkeypatch, "exhaustive")
        ref = eng.top_k_scored(batch, 5)
        _pinned(monkeypatch, "bmw")
        assert eng.top_k_scored(batch, 5) == ref
        d = eng.planner.describe()
        assert d["ranked"]["bmw"] == 0
        assert d["ranked"]["maxscore"] == 0
        assert d["ranked"]["exhaustive"] >= 2
        assert d["blocks_skipped"] == 0


def test_block_upper_bounds_dominate_contributions(adversarial_built):
    """Soundness of the stored bound: every document's actual BM25
    contribution is <= its block's upper bound, for every term."""
    _, _, out3 = adversarial_built
    art = load_artifact(artifact_path(out3))
    doc_lens, ndocs, avgdl = artifact_mod.bm25_corpus(art)
    with Engine(artifact_path(out3)) as eng:
        terms = [_df_term(j) for j in range(len(TARGET_DFS))] + \
            [_spike_term(p) for p in SPIKES]
        idx, found = eng.lookup(eng.encode_batch(terms))
        assert found.all()
        for i in idx.tolist():
            docs, contrib, _srt = eng._term_scores(i)
            dfi = len(docs)
            idf = float(np.log(1.0 + (ndocs - dfi + 0.5) / (dfi + 0.5)))
            ubs = block_upper_bounds(art, i, idf, avgdl, BM25_K1, BM25_B)
            for pos, c in enumerate(contrib):
                assert c <= ubs[pos // art.block_size] * (1 + 1e-12)


def test_resolve_planner_choices_and_validation(monkeypatch):
    for m in ("auto", "exhaustive", "bmw", "maxscore"):
        assert resolve_planner(m) == m
    monkeypatch.setenv(PLANNER_ENV, "maxscore")
    assert resolve_planner(None) == "maxscore"
    monkeypatch.delenv(PLANNER_ENV)
    assert resolve_planner(None) == "auto"
    with pytest.raises(ValueError):
        resolve_planner("wand")
    monkeypatch.setenv(PLANNER_ENV, "nonsense")
    with pytest.raises(ValueError):
        resolve_planner(None)


def test_plan_ranked_rules(adversarial_built, monkeypatch):
    """Mode selection: exhaustive when pruning can't help (no block
    scores / k covers everything / k<=0), else auto splits bmw vs
    maxscore on whether any term spans >4 blocks."""
    _, out2, out3 = adversarial_built
    art2 = load_artifact(artifact_path(out2))
    art3 = load_artifact(artifact_path(out3))
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs.metrics import (
        Registry,
    )
    monkeypatch.delenv(PLANNER_ENV, raising=False)
    p = Planner(Registry())
    assert p.plan_ranked(art2, [500, 600], 10) == "exhaustive"
    assert p.plan_ranked(art3, [500], 0) == "exhaustive"
    assert p.plan_ranked(art3, [5, 7], 12) == "exhaustive"
    # all dfs within 4 blocks -> maxscore; any longer term -> bmw
    assert p.plan_ranked(art3, [4 * B, 10], 5) == "maxscore"
    assert p.plan_ranked(art3, [4 * B + 1, 10], 5) == "bmw"
    # explicit mode wins over auto
    assert p.plan_ranked(art3, [4 * B + 1, 10], 5,
                         mode="maxscore") == "maxscore"
    monkeypatch.setenv(PLANNER_ENV, "bmw")
    assert p.plan_ranked(art3, [10, 10], 5) == "bmw"


def test_plan_and_threshold():
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs.metrics import (
        Registry,
    )
    p = Planner(Registry())
    assert p.plan_and(100, 200) == "merge"    # df <= 2 * n_acc
    assert p.plan_and(100, 201) == "gallop"
    # native takes the gallop arm's territory, never the merge arm's
    assert p.plan_and(100, 200, native=True) == "merge"
    assert p.plan_and(100, 201, native=True) == "native"
    d = p.describe()
    assert d["and"] == {"merge": 2, "gallop": 1, "native": 1}


def test_note_ranked_counters_and_last(monkeypatch):
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.obs.metrics import (
        Registry,
    )
    p = Planner(Registry())
    p.note_ranked("bmw", scored=7, skipped=3, candidates=12)
    p.note_ranked("exhaustive", 0, 0, 40)
    d = p.describe()
    assert d["ranked"]["bmw"] == 1 and d["ranked"]["exhaustive"] == 1
    assert d["blocks_scored"] == 7 and d["blocks_skipped"] == 3
    assert d["last_ranked"] == {"mode": "exhaustive", "backend": "numpy",
                                "blocks_scored": 0, "blocks_skipped": 0,
                                "candidates": 40}


def test_bm25_corpus_memoized_per_engine(tmp_path, monkeypatch):
    """Satellite: a v1 artifact reconstructs doc lengths from postings
    exactly once per engine, not once per scored query."""
    docs = [b"cat sat", b"cat cat dog", b"dog ran far away"]
    out = build_corpus_fmt(tmp_path, docs, 1)
    calls = {"n": 0}
    real = artifact_mod.bm25_corpus

    def counting(art):
        calls["n"] += 1
        return real(art)

    monkeypatch.setattr(artifact_mod, "bm25_corpus", counting)
    with Engine(artifact_path(out)) as eng:
        b = eng.encode_batch(["cat", "dog"])
        first = eng.top_k_scored(b, 3)
        assert first and eng.top_k_scored(b, 3) == first
        eng.top_k_scored(eng.encode_batch(["far"]), 2)
    assert calls["n"] == 1


def test_auto_engine_is_default_and_serves_from_host(adversarial_built,
                                                     monkeypatch):
    _, _, out3 = adversarial_built
    monkeypatch.delenv("MRI_SERVE_ENGINE", raising=False)
    monkeypatch.delenv(CROSSOVER_ENV, raising=False)
    with create_engine(artifact_path(out3)) as eng:
        assert isinstance(eng, AutoEngine)
        assert eng.engine_name == "auto"
        d = eng.describe()
        assert d["engine"] == "auto"
        assert d["auto"]["device_ready"] is False
        assert d["auto"]["probe"] is None
        # small batches never probe: answered by the host engine
        q = [_spike_term("first"), _df_term(2)]
        with Engine(artifact_path(out3)) as host:
            batch = eng.encode_batch(q)
            assert eng.df(batch).tolist() == host.df(batch).tolist()
            assert eng.top_k_scored(batch, 5) == \
                host.top_k_scored(host.encode_batch(q), 5)
        assert eng.describe()["auto"]["device_ready"] is False


def test_auto_engine_crossover_pins(adversarial_built, monkeypatch):
    """$MRI_SERVE_CROSSOVER: 0 pins host forever, N>0 routes batches
    >= N to the device engine (answers stay identical)."""
    _, _, out3 = adversarial_built
    q = [_df_term(j) for j in range(4)] + [_spike_term("mid")]
    monkeypatch.delenv("MRI_SERVE_ENGINE", raising=False)
    monkeypatch.setenv(CROSSOVER_ENV, "0")
    with create_engine(artifact_path(out3)) as eng:
        assert eng.describe()["auto"]["crossover"] == 0
        eng.df(eng.encode_batch(q))
        assert eng.describe()["auto"]["device_ready"] is False
    monkeypatch.setenv(CROSSOVER_ENV, "4")
    with create_engine(artifact_path(out3)) as eng, \
            Engine(artifact_path(out3)) as host:
        batch = eng.encode_batch(q)
        assert eng.df(batch).tolist() == host.df(batch).tolist()
        assert eng.describe()["auto"]["device_ready"] is True
        # below the threshold the host answers (no way to observe the
        # routing directly, but the answers must agree regardless)
        small = eng.encode_batch(q[:2])
        assert eng.df(small).tolist() == host.df(small).tolist()


@pytest.mark.daemon
def test_trace_spans_carry_planner_for_ranked(tmp_path):
    """Satellite: a bm25 top_k through the daemon leaves its planner
    decision (mode + block counters) on the engine span in the trace
    ring; unranked ops don't grow a planner label."""
    from test_daemon import Client, serving
    from test_obs import _poll_traces
    docs = _adversarial_docs()[:40]
    out = build_corpus_fmt(tmp_path, docs, 3)
    q = [_spike_term("first"), _df_term(2)]
    with serving(out) as d, Client(d) as cli:
        r = cli.rpc(id=1, op="top_k", score="bm25", k=3, terms=q,
                    trace_id="ranked-1")
        assert r["ok"] and r["docs"]
        r = cli.rpc(id=2, op="df", terms=q, trace_id="plain-1")
        assert r["ok"]
        traces = _poll_traces(cli, 16, 2)
        by_id = {t["trace_id"]: t for t in traces}
        eng_span = by_id["ranked-1"]["spans"][-1]
        assert eng_span["name"] == "engine"
        pl = eng_span["planner"]
        assert pl["mode"] in ("exhaustive", "bmw", "maxscore")
        assert pl["blocks_scored"] >= 0
        assert pl["blocks_skipped"] >= 0
        assert pl["candidates"] >= 1
        assert "planner" not in by_id["plain-1"]["spans"][-1]
