"""Real multi-host seam: 2 OS processes, a localhost coordinator, and a
4-device global mesh (2 virtual CPU devices per process) running the
letter-ownership dist pipeline end-to-end vs the oracle.

This is the reference's "no multi-node story at all" (SURVEY.md §4)
replaced with the TPU framework's: ``parallel/distributed.initialize``
(the ``jax.distributed`` seam), cross-process ``all_to_all`` (the DCN
analogue on CPU), and per-owner letter emission where each process
writes only its own owners' files (VERDICT r1 #4 + #6).
"""

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from conftest import REPO_ROOT, read_letter_files

WORKER = textwrap.dedent("""
    import sys
    repo, pid, coord, corpus_dir, out_dir = sys.argv[1:6]
    sys.path.insert(0, repo)
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        load_documents, manifest_from_dir,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.scheduler import (
        plan_letter_ranges,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import engine
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel import (
        dist_engine, distributed,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel.mesh import (
        make_mesh, shard_spec, sharding,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text import formatter
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
        tokenize_documents,
    )

    distributed.initialize(coordinator_address=coord, num_processes=2,
                           process_id=int(pid))
    info = distributed.runtime_info()
    assert info["process_count"] == 2, info
    assert info["global_devices"] == 4, info

    # Every process tokenizes the same corpus deterministically (in a
    # real pod each host reads its own shard; the exchange is the same).
    m = manifest_from_dir(corpus_dir)
    contents, ids = load_documents(m)
    corpus = tokenize_documents(contents, ids)
    stride = len(m) + 2
    keys = np.unique(
        corpus.term_ids.astype(np.int64) * stride + corpus.doc_ids)
    vocab_size = corpus.vocab_size
    df = np.bincount((keys // stride).astype(np.int64),
                     minlength=vocab_size).astype(np.int64)
    order, _ = engine.host_order_offsets(corpus.letter_of_term, df)

    n = 4
    padded = -(-keys.size // n) * n
    buf = np.full(padded, dist_engine.K.INT32_MAX, dtype=np.int32)
    buf[: keys.size] = keys

    mesh = make_mesh(n)
    sh = sharding(mesh, shard_spec())
    # multi-controller feed: every process donates its local slice.
    # Owners are MESH POSITIONS (multi-process device ids are sparse,
    # e.g. 2048+ on host 1 — never index by device.id).
    pos_of_device = {d: i for i, d in enumerate(mesh.devices.flat)}
    local = buf.reshape(n, -1)
    arrays = [
        jax.device_put(local[pos_of_device[d]], d)
        for d in jax.local_devices()
    ]
    keys_global = jax.make_array_from_single_device_arrays(
        (padded,), sh, arrays)

    ranges = plan_letter_ranges(n)
    owner_of_letter = np.zeros(26, dtype=np.int32)
    for o, (lo, hi) in enumerate(ranges):
        owner_of_letter[lo:hi] = o
    owner_of_term = owner_of_letter[np.asarray(corpus.letter_of_term)]

    rows = dist_engine.dist_letter_windows(
        [keys_global], owner_of_term, stride=stride, mesh=mesh)
    local_owner_ids = sorted(rows)
    expected = sorted(pos_of_device[d] for d in jax.local_devices())
    assert local_owner_ids == expected, (local_owner_ids, expected)

    df64 = df
    for o, row in sorted(rows.items()):
        df_o = np.where(owner_of_term == o, df64, 0)
        offsets_local = np.cumsum(df_o) - df_o
        postings_o = dist_engine.merge_owner_runs(
            [row], stride, offsets_local, int(df_o.sum()))
        formatter.emit_index(
            out_dir, vocab=corpus.vocab,
            letter_of_term=corpus.letter_of_term, order=order, df=df64,
            offsets=offsets_local, postings=postings_o,
            max_doc_id=len(m), letter_range=ranges[o])
    print(f"proc {pid} emitted owners {local_owner_ids}", flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_workers(tmp_path, worker_src: str, docs_dir, out_dir):
    """Launch 2 coordinator-connected worker processes (2 virtual CPU
    devices each -> a 4-device global mesh) and return their outputs."""
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(worker_src)
    coord = f"127.0.0.1:{_free_port()}"
    env = {
        **os.environ,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_PLATFORMS": "cpu",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker_py), str(REPO_ROOT), str(pid), coord,
             str(docs_dir), str(out_dir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=300) for p in procs]
    finally:
        for p in procs:  # no orphans holding the coordinator port
            if p.poll() is None:
                p.kill()
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}\n{err[-3000:]}"
    return outs


def _check_owner_blocks_vs_oracle(out_dir, docs_dir):
    """Merge the workers' owner*.npz blocks and compare the (word, doc)
    pair set + df against the numpy tokenizer frontend."""
    import numpy as np

    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        load_documents, manifest_from_dir,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.tokenizer import (
        tokenize_documents,
    )

    got_pairs = set()
    got_df = {}
    for f in sorted(Path(out_dir).glob("owner*.npz")):
        blk = np.load(f)
        words, df, postings = blk["words"], blk["df"], blk["postings"]
        off = 0
        for w, d in zip(words, df):
            word = w.rstrip(b"\x00").decode()
            got_df[word] = got_df.get(word, 0) + int(d)
            for doc in postings[off:off + int(d)]:
                got_pairs.add((word, int(doc)))
            off += int(d)
    m = manifest_from_dir(docs_dir)
    contents, ids = load_documents(m)
    corpus = tokenize_documents(contents, ids)
    vocab = [w.rstrip(b"\x00").decode() for w in corpus.vocab.tolist()]
    want_pairs = {(vocab[t], int(d))
                  for t, d in zip(corpus.term_ids, corpus.doc_ids)}
    assert got_pairs == want_pairs
    want_df = {}
    for w, _ in want_pairs:
        want_df[w] = want_df.get(w, 0) + 1
    assert got_df == want_df


@pytest.mark.slow
def test_two_process_letter_emit_matches_oracle(tmp_path):
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        oracle_index,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        manifest_from_dir,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        write_corpus, zipf_corpus,
    )

    docs = zipf_corpus(num_docs=24, vocab_size=300, tokens_per_doc=60, seed=77)
    write_corpus(tmp_path / "docs", docs)
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    outs = _run_two_workers(tmp_path, WORKER, tmp_path / "docs", out_dir)

    m = manifest_from_dir(tmp_path / "docs")
    oracle_index(m, tmp_path / "oracle")
    assert read_letter_files(out_dir) == read_letter_files(tmp_path / "oracle")
    # each process emitted a disjoint half of the owners
    assert "owners [0, 1]" in outs[0][0]
    assert "owners [2, 3]" in outs[1][0]


# -- mesh all-device engine (parallel/dist_device_tokenizer.py) -----------

DEVTOK_WORKER = textwrap.dedent("""
    import sys
    repo, pid, coord, corpus_dir, out_dir = sys.argv[1:6]
    sys.path.insert(0, repo)
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        iter_document_ranges, manifest_from_dir,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.scheduler import (
        plan_contiguous_windows,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import (
        device_tokenizer as DT,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel import (
        dist_device_tokenizer as DDT, distributed,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel.mesh import (
        make_mesh,
    )

    distributed.initialize(coordinator_address=coord, num_processes=2,
                           process_id=int(pid))
    n = 4
    mesh = make_mesh(n)
    width = 48

    # Every process builds the same shard set deterministically (in a
    # real pod each host reads only its ranges; the feed path uploads
    # only local positions either way).
    m = manifest_from_dir(corpus_dir)
    windows = plan_contiguous_windows(m, n)
    shards = list(iter_document_ranges(m, windows))
    shard_len = max(max(sum(len(b) for b in c) for c, _ in shards), 1)
    shard_len = -(-shard_len // 256) * 256
    docs_cap = max(max(len(c) for c, _ in shards), 1)
    bufs, ends_l, ids_l = [], [], []
    tok_count = host_max_len = 0
    for contents, ids in shards:
        buf = np.full(shard_len, 0x20, np.uint8)
        nb = 0
        ends = np.full(docs_cap, shard_len, np.int64)
        idv = np.full(docs_cap, 1, np.int32)
        for j, (c, i) in enumerate(zip(contents, ids)):
            buf[nb:nb + len(c)] = np.frombuffer(c, np.uint8)
            nb += len(c)
            ends[j] = nb
            idv[j] = i
        cnt, ml = DT.host_token_stats(buf, ends)
        tok_count = max(tok_count, cnt)
        host_max_len = max(host_max_len, ml)
        bufs.append(buf)
        ends_l.append(ends.astype(np.int32))
        ids_l.append(idv)
    tok_cap = -(-(tok_count + 1) // (1 << 14)) * (1 << 14)
    sort_cols = -(-max(host_max_len, 1) // 4)

    owners, (max_len, retries) = DDT.index_bytes_dist(
        bufs, ends_l, ids_l, width=width, tok_cap=tok_cap, mesh=mesh,
        sort_cols=sort_cols, max_doc_id=len(m))
    assert max_len == host_max_len, (max_len, host_max_len)

    # each process must see exactly its local mesh positions as owners
    got = sorted(owners)
    want = sorted(DDT._local_mesh_positions(mesh))
    assert got == want, (got, want)

    import pathlib
    for o, ow in owners.items():
        words = DT.decode_word_groups(ow["unique_groups"], width)
        np.savez(pathlib.Path(out_dir) / f"owner{o}.npz",
                 words=words, df=ow["df"], postings=ow["postings"])
    print(f"proc {pid} fetched owners {got}", flush=True)
""")


@pytest.mark.slow
def test_two_process_device_tokenize_fetch(tmp_path):
    """The mesh all-device engine's multi-controller seam: 2 OS
    processes drive index_bytes_dist on a 4-device global mesh; each
    fetches only its addressable owners, and the union of the fetched
    blocks reconstructs the exact (word, doc) index."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        write_corpus, zipf_corpus,
    )

    docs = zipf_corpus(num_docs=22, vocab_size=250, tokens_per_doc=50, seed=31)
    write_corpus(tmp_path / "docs", docs)
    out_dir = tmp_path / "blocks"
    out_dir.mkdir()
    outs = _run_two_workers(tmp_path, DEVTOK_WORKER, tmp_path / "docs",
                            out_dir)
    assert "owners [0, 1]" in outs[0][0]
    assert "owners [2, 3]" in outs[1][0]
    # merge the four owner blocks and compare against the numpy frontend
    _check_owner_blocks_vs_oracle(out_dir, tmp_path / "docs")


DEVTOK_LETTER_WORKER = textwrap.dedent("""
    import sys
    repo, pid, coord, corpus_dir, out_dir = sys.argv[1:6]
    sys.path.insert(0, repo)
    import jax
    jax.config.update("jax_platforms", "cpu")

    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        IndexConfig, InvertedIndexModel,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        manifest_from_dir,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel import (
        distributed,
    )

    distributed.initialize(coordinator_address=coord, num_processes=2,
                           process_id=int(pid))
    m = manifest_from_dir(corpus_dir)
    report = InvertedIndexModel(IndexConfig(
        backend="tpu", device_tokenize=True, device_shards=4,
        emit_ownership="letter", pad_multiple=256,
        output_dir=out_dir)).run(m)
    # each process emitted only its ADDRESSABLE owners' letter ranges
    print(f"proc {pid} letter_owners={report['letter_owners']} "
          f"lines={report['lines_written']}", flush=True)
""")


@pytest.mark.slow
def test_two_process_device_tokenize_letter_emit(tmp_path):
    """The mesh all-device engine's full multi-host regime: 2 OS
    processes run the MODEL with letter ownership; each writes only its
    addressable owners' letter files into a shared directory, and the
    union is byte-identical to the oracle — no host ever assembles the
    global index."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        oracle_index,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        manifest_from_dir,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        write_corpus, zipf_corpus,
    )

    docs = zipf_corpus(num_docs=26, vocab_size=350, tokens_per_doc=45, seed=91)
    write_corpus(tmp_path / "docs", docs)
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(DEVTOK_LETTER_WORKER)

    coord = f"127.0.0.1:{_free_port()}"
    env = {
        **os.environ,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_PLATFORMS": "cpu",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker_py), str(REPO_ROOT), str(pid), coord,
             str(tmp_path / "docs"), str(out_dir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=300) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}\n{err[-3000:]}"

    m = manifest_from_dir(tmp_path / "docs")
    oracle_index(m, tmp_path / "oracle")
    assert read_letter_files(out_dir) == read_letter_files(tmp_path / "oracle")


DEVSTREAM_WORKER = textwrap.dedent("""
    import sys
    repo, pid, coord, corpus_dir, out_dir = sys.argv[1:6]
    sys.path.insert(0, repo)
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.manifest import (
        iter_document_chunks, manifest_from_dir,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.scheduler import (
        plan_contiguous_ranges,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.ops import (
        device_tokenizer as DT,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel import (
        dist_device_streaming as DDS, dist_device_tokenizer as DDT, distributed,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.parallel.mesh import (
        make_mesh,
    )

    distributed.initialize(coordinator_address=coord, num_processes=2,
                           process_id=int(pid))
    n = 4
    mesh = make_mesh(n)
    width = 48

    # Every process builds the same shard windows deterministically (a
    # real pod host reads only its ranges; feed uploads only local
    # positions either way).  Tiny initial capacity forces regrows
    # across the multi-controller accumulator too.
    m = manifest_from_dir(corpus_dir)
    eng = DDS.DistDeviceStreamEngine(width=width, mesh=mesh,
                                     window_pad=1 << 10,
                                     initial_capacity=32)
    for contents, ids in iter_document_chunks(m, 8):
        ranges_c = plan_contiguous_ranges([len(c) for c in contents], n)
        parts = [(contents[lo:hi], ids[lo:hi]) for lo, hi in ranges_c]
        shard_len = max(max((sum(len(c) for c in cs) for cs, _ in parts),
                            default=1), 1)
        shard_len = -(-shard_len // 256) * 256
        docs_cap = max(max(len(c) for c, _ in parts), 1)
        bufs, ends_l, ids_l = [], [], []
        tok_count = max_len = 0
        for contents_s, ids_s in parts:
            buf = np.full(shard_len, 0x20, np.uint8)
            nb = 0
            ends = np.full(docs_cap, shard_len, np.int32)
            idv = np.full(docs_cap, 1, np.int32)
            for j, (c, i) in enumerate(zip(contents_s, ids_s)):
                buf[nb:nb + len(c)] = np.frombuffer(c, np.uint8)
                nb += len(c)
                ends[j] = nb
                idv[j] = i
            cnt, ml = DT.host_token_stats(buf, ends)
            tok_count = max(tok_count, cnt)
            max_len = max(max_len, ml)
            bufs.append(buf)
            ends_l.append(ends)
            ids_l.append(idv)
        assert max_len <= width
        eng.feed(bufs, ends_l, ids_l, tok_count=tok_count, max_len=max_len)

    sort_cols = -(-max(eng.max_word_len, 1) // 4)
    owners = eng.finalize(sort_cols=sort_cols, max_doc_id=len(m))

    # each process must see exactly its local mesh positions as owners
    got = sorted(owners)
    want = sorted(DDT._local_mesh_positions(mesh))
    assert got == want, (got, want)

    import pathlib
    for o, ow in owners.items():
        words = DT.decode_word_groups(ow["unique_groups"], width)
        np.savez(pathlib.Path(out_dir) / f"owner{o}.npz",
                 words=words, df=ow["df"], postings=ow["postings"])
    print(f"proc {pid} stream owners {got} windows {eng.windows_fed} "
          f"cap {eng.capacity}", flush=True)
""")


@pytest.mark.slow
def test_two_process_device_stream_accumulator(tmp_path):
    """The mesh streaming all-device engine's multi-controller seam
    (ADVICE r2: _empty must not need every device addressable): 2 OS
    processes drive DistDeviceStreamEngine over a 4-device global mesh
    through several windows with regrows; the union of the fetched
    owner blocks reconstructs the exact (word, doc) index."""
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.corpus.synthetic import (
        write_corpus, zipf_corpus,
    )

    docs = zipf_corpus(num_docs=26, vocab_size=220, tokens_per_doc=40, seed=37)
    write_corpus(tmp_path / "docs", docs)
    out_dir = tmp_path / "blocks"
    out_dir.mkdir()
    outs = _run_two_workers(tmp_path, DEVSTREAM_WORKER, tmp_path / "docs",
                            out_dir)
    assert "stream owners [0, 1]" in outs[0][0]
    assert "stream owners [2, 3]" in outs[1][0]
    _check_owner_blocks_vs_oracle(out_dir, tmp_path / "docs")
