"""Chaos soak (tools/chaos.py) under pytest: seeded fault schedules
against the (K, M) plan matrix.

Tier-1 runs one full cycle of the matrix (9 trials, a few seconds); the
``slow`` soak runs the 50+-trial acceptance sweep.  Both hold every
trial to the harness's contract: clean ⇒ byte-identical + verified
manifest, degraded ⇒ reported loss + complete letter set, and never a
hang.
"""

import importlib.util
import json
from pathlib import Path

import pytest

from conftest import REPO_ROOT

from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
    faults,
    native,
)

pytestmark = [pytest.mark.chaos, pytest.mark.faults]

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="no C++ toolchain")


def _load_chaos():
    spec = importlib.util.spec_from_file_location(
        "mri_chaos", REPO_ROOT / "tools" / "chaos.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def chaos():
    return _load_chaos()


@pytest.fixture(autouse=True)
def _disarm(monkeypatch):
    # the harness pins MRI_CPU_WINDOW_BYTES itself; monkeypatch makes
    # sure the pin can't leak past each test
    monkeypatch.setenv("MRI_CPU_WINDOW_BYTES", "512")
    faults.install(None)
    faults.begin_run()
    yield
    faults.install(None)
    faults.begin_run()


def _assert_contract(summary):
    assert summary["failures"] == [], \
        "chaos contract violated:\n" + "\n".join(
            json.dumps(f, sort_keys=True) for f in summary["failures"])
    # every trial landed in one of the two permitted outcomes
    assert summary["clean"] + summary["degraded"] == summary["trials"]


@needs_native
def test_chaos_matrix_cycle_fast(tmp_path, chaos):
    """One trial per (K, M) cell — the tier-1 smoke that keeps the
    harness itself from rotting between full soaks."""
    summary = chaos.run_soak(Path(tmp_path), trials=9, seed_base=1000,
                             deadline_s=120.0, verbose=False)
    _assert_contract(summary)
    assert summary["trials"] == 9


@needs_native
def test_chaos_trial_reproducible(tmp_path, chaos):
    """Same seed, same schedule, same verdict — the repro contract the
    --repro flag depends on."""
    m = chaos.make_corpus(tmp_path / "corpus")
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import (
        oracle_index,
    )
    from parallel_computation_of_an_inverted_index_using_map_reduce_tpu.text.formatter import (
        letters_md5,
    )

    oracle_index(m, tmp_path / "golden")
    gold = letters_md5(tmp_path / "golden")
    a = chaos.run_trial(m, gold, tmp_path / "a", 1004, 2, 3)
    b = chaos.run_trial(m, gold, tmp_path / "b", 1004, 2, 3)
    assert a["ok"] and b["ok"]
    assert a["spec"] == b["spec"]
    assert (a["outcome"], a["recoveries"], a["takeovers"], a["skipped"]) \
        == (b["outcome"], b["recoveries"], b["takeovers"], b["skipped"])


@pytest.mark.wal
def test_chaos_wal_cycle_fast(tmp_path, chaos):
    """One trial per durability scenario — SIGKILL'd primaries recover
    every acked mutation, the replica converges byte-equal, a stolen
    lease rejects cleanly."""
    summary = chaos.run_wal_soak(Path(tmp_path), trials=4,
                                 seed_base=7000, deadline_s=120.0,
                                 verbose=False)
    assert summary["failures"] == [], summary["failures"]
    assert summary["clean"] == summary["trials"] == 4
    assert all(n == 1 for n in summary["by_scenario"].values())


def test_chaos_list_covers_every_mode(chaos, capsys):
    """--list is the discovery surface: every soak mode and scenario
    name must appear, and the flag exits 0 without running anything."""
    assert chaos.main(["--list"]) == 0
    out = capsys.readouterr().out
    for mode, _flag, _desc, names in chaos.SCENARIO_REGISTRY:
        assert mode in out
        for name in names:
            assert name in out
    assert "--wal" in out and "kill-mid-compaction" in out


@pytest.mark.wal
@pytest.mark.slow
def test_chaos_wal_soak_twenty_four_trials(tmp_path, chaos):
    """The acceptance soak: >=24 seeded durability trials — zero lost
    acknowledged mutations, byte-equal replicas, clean exits, no
    leaked scratch dirs (every one of those is a failure verdict)."""
    summary = chaos.run_wal_soak(Path(tmp_path), trials=24,
                                 seed_base=7100, deadline_s=120.0,
                                 verbose=False)
    assert summary["failures"] == [], summary["failures"]
    assert summary["clean"] == summary["trials"] == 24
    # every scenario pulled its weight
    assert all(n == 6 for n in summary["by_scenario"].values())


@needs_native
@pytest.mark.slow
def test_chaos_soak_fifty_trials(tmp_path, chaos):
    """The acceptance soak: >=50 seeded trials across the matrix —
    zero hangs, zero wrong bytes, every clean run's manifest verifies."""
    summary = chaos.run_soak(Path(tmp_path), trials=54, seed_base=2000,
                             deadline_s=120.0, verbose=False)
    _assert_contract(summary)
    assert summary["trials"] == 54
    # a soak that never exercised recovery proves nothing
    assert summary["recoveries"] + summary["takeovers"] >= 5
