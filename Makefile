# Build / toolchain layer (reference Makefile:1-4 had `build` compiling
# main.c with ASan and `clean` removing the binary).  Here `build`
# compiles the native host runtime ahead of time (it otherwise builds
# lazily on first use), and the reference's manual run-then-diff
# workflow is replaced by real targets.

PY ?= python

.PHONY: build lint test test-fast test-lint test-faults test-parallel test-spill test-chaos test-chaos-all test-wal test-qos test-serve test-serve-device test-daemon test-obs test-segments test-attrib test-cluster test-native-asan test-native-ubsan bench bench-scale bench-sweep bench-build-ooc bench-serve bench-serve-device bench-serve-v2 bench-serve-ranked bench-serve-native bench-daemon bench-scrape bench-segments bench-wal bench-slo bench-cluster bench-brownout bench-qos bench-history capture rehearse clean clean-native

build:
	$(PY) -c "from parallel_computation_of_an_inverted_index_using_map_reduce_tpu import native; \
	          assert native.available(), 'native build failed'; print('native runtime built')"

# repo-contract static analysis (tools/mrilint): exit 0 means clean
# against the checked-in shrink-only baseline; the bench-history check
# keeps the README "Bench trajectory" table in sync with BENCH_*.json
lint:
	$(PY) -m tools.mrilint
	$(PY) tools/bench_history.py --check

test:
	$(PY) -m pytest tests/ -q

# Tier-1 selection (-m "not slow") — includes the fast `parallel_host`
# multi-worker map/reduce tests — parallelized over workers when
# pytest-xdist is installed (falls back to a serial run when not —
# the verify pipeline's own serial invocation is untouched)
test-fast: lint
	$(PY) -m pytest tests/ -q -m "not slow" \
	  $$($(PY) -c "import importlib.util as u; print('-n auto' if u.find_spec('xdist') else '')")
	$(PY) tools/chaos.py --all --fast

# mrilint's own suite: checker semantics on planted fixtures under
# tests/fixtures/lint/ plus the repo-clean gate
test-lint:
	$(PY) -m pytest tests/ -q -m lint

# failure-semantics suite only: fault injection, retry/skip policy,
# crash-safe resume (tests marked `faults`)
test-faults:
	$(PY) -m pytest tests/ -q -m faults

# multi-worker host map/reduce suite only (steal queue, (K, M)
# byte-identity matrix, letter-partitioned reduce)
test-parallel:
	$(PY) -m pytest tests/ -q -m parallel_host

# out-of-core build suite: spill container integrity, shard-merge
# algebra, (shards, budget, K, M) byte-identity matrix, quarantine /
# takeover degradation, SIGKILL-at-spill-boundary resume
test-spill:
	$(PY) -m pytest tests/ -q -m spill

# chaos suite: the fast matrix cycle runs in tier-1 (`chaos and not
# slow`); this target adds the full 50+-trial seeded soaks (build
# matrix, daemon scenarios, segments schedules, and the --wal
# durability/replication sweep)
test-chaos:
	$(PY) -m pytest tests/ -q -m chaos

# every chaos mode off the `tools/chaos.py --list` registry, full
# trial counts, one process per mode; a new mode added to the registry
# is picked up here with no Makefile edit.  `--fast` (the test-fast
# cycle) runs the same sweep at reduced trials/deadlines
test-chaos-all:
	$(PY) tools/chaos.py --all

# durability suite: WAL container integrity, torn-tail quarantine,
# crash replay (incl. SIGKILL during a buffered tombstone batch),
# lease semantics, segment-shipping replica catch-up + rollback refusal
test-wal:
	$(PY) -m pytest tests/ -q -m wal

# multi-tenant QoS + result-cache suite: generation-keyed cache
# byte-identity/invalidation, LRU byte accounting, token-bucket
# admission, weighted-fair dequeue, per-tenant stats/flightdump/top
test-qos:
	$(PY) -m pytest tests/ -q -m qos

# query-serving suite: index.mri format + Engine parity vs a naive text
# scan, artifact corruption rejection, LRU cache semantics
test-serve:
	$(PY) -m pytest tests/ -q -m serve

# device query-engine suite: host/device byte parity (batches 1..8192),
# shared-prefix fixup, zero-recompile steady state — forced onto the
# jax cpu backend so it runs on any box (the same code path serves
# accelerators)
test-serve-device:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m device_serve

# the sanitizer suite targets the native C++ runtime: every native /
# tokenizer / emit test plus the oracle conformance check.  Tests that
# jit through jax are excluded under ASan only — its __cxa_throw
# interceptor aborts inside jaxlib's bundled MLIR bindings (a toolchain
# clash, not a bug in this code) — and kept out of the ubsan run too so
# both targets certify the same selection.
NATIVE_SAN_TESTS = tests/test_native.py tests/test_native_serve.py \
  tests/test_tokenizer.py \
  tests/test_emit_backend.py tests/test_conformance.py
NATIVE_SAN_K = not tpu and not single_chip and not numpy_tokenizer \
  and not backends_agree and not degenerate_configs

# native tokenizer under AddressSanitizer: MRI_NATIVE_SANITIZE=asan
# compiles a separately-tagged .so (never shadows the production one)
# and the runtime loads it.  libasan must be first in the process, so
# it is LD_PRELOADed into the python interpreter; leak checking is off
# because the long-lived interpreter never frees everything at exit.
test-native-asan:
	LD_PRELOAD=$$(g++ -print-file-name=libasan.so) \
	ASAN_OPTIONS=detect_leaks=0 \
	MRI_NATIVE_SANITIZE=asan JAX_PLATFORMS=cpu \
	$(PY) -m pytest $(NATIVE_SAN_TESTS) -q -m "not slow" -k "$(NATIVE_SAN_K)"

# same suite under UndefinedBehaviorSanitizer (traps on UB, no preload
# needed — libubsan is a direct dependency of the tagged .so)
test-native-ubsan:
	MRI_NATIVE_SANITIZE=ubsan JAX_PLATFORMS=cpu \
	$(PY) -m pytest $(NATIVE_SAN_TESTS) -q -m "not slow" -k "$(NATIVE_SAN_K)"

# resident serve-daemon suite: JSON-lines protocol parity, admission
# control / load shedding, deadlines, graceful drain, crash-safe hot
# reload, serve-side chaos trials; none are `slow`, so the default
# `make test-fast` sweep runs them too
test-daemon:
	$(PY) -m pytest tests/ -q -m daemon

# observability layer (obs/): metrics registry semantics, Prometheus
# exposition parity with the legacy stats op, request tracing over the
# wire, slow-query log, Chrome-trace build export; none are `slow`, so
# the default `make test-fast` sweep runs them too
test-obs:
	$(PY) -m pytest tests/ -q -m obs

# incremental indexing (segments/): manifest + tombstone integrity,
# append/delete/compact lifecycle, multi-segment byte-identity vs a
# from-scratch build, fault kinds, CLI + daemon admin surfaces
test-segments:
	$(PY) -m pytest tests/ -q -m segments

# scale-out serving suite (cluster/): corpus partitioner invariants,
# D-way gather merge byte-identity (incl. the D in {1,2,4,8} fuzz vs a
# monolithic build), router failover / hedging / deadline semantics
# against live shard daemons; none are `slow`, so the default
# `make test-fast` sweep runs them too
test-cluster:
	$(PY) -m pytest tests/ -q -m cluster

# query-cost attribution suite: per-request EXPLAIN reports vs registry
# counter parity (host/device/multi-segment), daemon explain + flight
# recorder dumps, OpenMetrics exemplars, trace-coverage checker; none
# are `slow`, so the default `make test-fast` sweep runs them too
test-attrib: lint
	$(PY) -m pytest tests/ -q -m attrib

bench:
	$(PY) bench.py

# 1M-doc streaming benchmark (BASELINE config 4); see bench.py for the
# MRI_TPU_SCALE_* knobs (REALTEXT=1 switches to the config-5 regime)
bench-scale:
	$(PY) bench.py --scale

# host map-phase scaling curve: cpu e2e at 1/2/4 scan workers on the
# same corpus, with the per-worker stage split (prints a JSON line)
bench-sweep:
	$(PY) bench.py --sweep

# out-of-core build bench: spill-tier wall vs the in-memory parallel
# build on a >= 20x-budget Zipf corpus, byte-parity + peak-memory
# gated -> BENCH_BUILD_OOC_r15.json
bench-build-ooc:
	$(PY) tools/bench_build_ooc.py

# query-serving QPS/latency bench against the packed artifact (Zipf
# workload, batch sizes 1/32/1024; prints a JSON line) — see
# tools/bench_serve.py for the MRI_SERVE_* knobs
bench-serve:
	$(PY) tools/bench_serve.py

# host-vs-device serving A/B (batch 1/1K/8K/64K, per-op breakdown,
# byte-parity + zero-recompile assertions) -> BENCH_SERVE_DEVICE_r06.json
bench-serve-device:
	$(PY) tools/bench_serve.py --device-ab

# artifact format v1-vs-v2 A/B (bytes on disk, boolean QPS, cold-decode
# latency, BM25 throughput; byte-parity gated) -> BENCH_SERVE_V2_r09.json
bench-serve-v2:
	$(PY) tools/bench_serve.py --format-ab

# ranked-query A/B on a v2.1 artifact: exhaustive vs Block-Max WAND vs
# MaxScore at k=1/10/100 over the Zipf mix, byte-parity gated, with
# cold-sweep block-skip ratios -> BENCH_RANKED_r11.json
bench-serve-ranked:
	$(PY) tools/bench_serve.py --ranked-ab

# native serve-kernel A/B: numpy host engine vs the C++ mri_serve_*
# kernels on the same v2.1 artifact (bm25 top-10 QPS at batch
# 1/8/32/1024 + AND QPS, byte-parity gated against the numpy oracle,
# >= 3x the r11 ranked gate) -> BENCH_NATIVE_r16.json
bench-serve-native:
	$(PY) tools/bench_serve.py --native-ab

# resident-daemon bench: coalesced pipelined capacity vs the batch-1
# closed-loop baseline, plus an open-loop (Poisson) sweep reporting
# p50/p99 from scheduled arrival, shed rate, and deadline-miss rate at
# 3 offered loads -> BENCH_DAEMON_r07.json
bench-daemon:
	$(PY) tools/bench_serve.py --daemon-bench

# observability overhead gate: Prometheus-vs-stats counter parity on a
# live daemon + the `metrics` op priced against the r09 serving
# capacity (1 Hz scrape must cost <1%) -> BENCH_SCRAPE_r10.json
bench-scrape:
	$(PY) tools/bench_serve.py --scrape-check

# incremental-indexing A/B: append->visible refresh latency, query QPS
# at 1/4/16 segments vs the single-artifact baseline (byte-parity
# gated), and compaction cost -> BENCH_SEGMENTS_r12.json
bench-segments:
	$(PY) tools/bench_serve.py --segments-ab

# durability A/B: the same live-daemon mutation schedule with the WAL
# off vs on (ack p99 gated at 2x, byte-parity between the legs), plus
# cold replica catch-up rate by segment shipping -> BENCH_WAL_r17.json
bench-wal:
	$(PY) tools/bench_serve.py --wal-ab

# operational-health overhead gate: rolling-windows sampler tick + a
# 1 Hz `slo` poll priced in-run (<1% of a serving second), with `mri
# top --once --json` parity vs the raw ops -> BENCH_SLO_r14.json
bench-slo:
	$(PY) tools/bench_serve.py --slo-check

# doc-sharded cluster A/B: monolithic engine vs D local shard daemons
# behind the scatter-gather router at D=4/8 (pipelined + open-loop
# Poisson ranked load, byte-parity gated vs the monolithic artifact,
# hedged-vs-unhedged p99 under an injected slow shard)
# -> BENCH_CLUSTER_r18.json; see tools/bench_serve.py for the
# MRI_CLUSTER_BENCH_* knobs
bench-cluster:
	$(PY) tools/bench_serve.py --cluster-ab

# brownout A/B: retry amplification on a D=2 cluster under a shard
# blackout and an intermittent overload storm (default token-bucket
# budget vs a loose contrast leg, gated at 1.1x), plus CoDel adaptive
# admission vs a fixed queue at 2x measured capacity (compliant p99
# gated at 2x unloaded) -> BENCH_BROWNOUT_r19.json
bench-brownout:
	$(PY) tools/bench_serve.py --brownout-ab

# result-cache + QoS A/B: cached-hot vs uncached Zipf replay on one
# daemon (speedup gated at 5x, byte-parity gated), then a diurnal-burst
# tank tenant vs a paying tenant at 2x measured capacity — paying p99
# gated at 1.2x its alone run, with an unfenced contrast leg
# -> BENCH_QOS_r20.json
bench-qos:
	$(PY) tools/bench_serve.py --qos-ab

# print the cross-round BENCH_*.json trajectory table (ratios against
# each round's own baseline); `--write` regenerates the README block
bench-history:
	$(PY) tools/bench_history.py

# full on-chip capture (run when the tunnel is up); round-parameterized
# (tools/capture.sh R OUT) — assembles AND commits its artifacts
ROUND ?= 5
capture:
	PY=$(PY) bash tools/capture.sh $(ROUND)

# CPU rehearsal of every capture step at tiny sizes (no chip needed)
rehearse:
	PY=$(PY) bash tools/rehearse.sh $(ROUND)

# drop every hashed native build artifact — production AND sanitizer
# variants, in both the in-tree dir and the /tmp fallback (stale .so
# files of the same variant are also auto-pruned on every rebuild).
# The serve kernels (mri_serve_*) live in the same tagged .so as the
# build-path symbols, so one sweep covers both API families.
clean-native:
	rm -rf parallel_computation_of_an_inverted_index_using_map_reduce_tpu/native/_build
	rm -rf /tmp/mri_tpu_native_$$(id -u)

clean: clean-native
	find . -name __pycache__ -type d -prune -exec rm -rf {} +
