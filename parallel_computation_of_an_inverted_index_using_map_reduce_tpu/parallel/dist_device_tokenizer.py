"""Mesh variant of the all-device engine: sharded bytes in, index out.

Completes the engine matrix — {host scan, device scan} x {single chip,
multi chip}.  The single-chip all-device engine
(ops/device_tokenizer.py) removes the host from the compute path; this
module removes the single-chip limit: each chip receives a contiguous
doc range's raw bytes, tokenizes/cleans them locally with the SAME
traceable stages, and one ``all_to_all`` exchanges whole word rows
(the live 5-bit (hi, lo) group halves + doc, carried side by side)
bucketed by a word-content hash, so every term is deduped/counted by
exactly one owner — the
reference's reducer ownership (main.c:129-150) re-keyed from its
~1000x-skewed letters to a near-uniform hash, at the level of raw
text rather than pre-tokenized pairs.

Per chip, as one ``shard_map`` program:

    rows   <- tokenize_groups(bytes_shard)          # local scans/sorts
    owner  <- mix32(word columns) % n
    recv   <- all_to_all(bucket(rows, owner))       # ICI, 2*live+1 rows
    index  <- sort_dedup_groups(recv)               # owner-side radix

Static exchange capacity with a provably-safe overflow retry
(psum-reduced flag), the same discipline as the integer-pair engines
(parallel/dist_engine.py).  Exactness story is inherited:
byte-identical output or WidthOverflow fallback, never truncation.

Multi-controller contract: :func:`index_bytes_dist` feeds each
process's local mesh positions via
``make_array_from_single_device_arrays`` and fetches ONLY addressable
shards — per-owner counts from the sharded counts array, data through
a device-side prefix slice shaped by device-replicated count maxima
(so every process compiles the same fetch program).  In a
single-process run every owner is addressable and behavior is
unchanged; on a multi-host pod each process gets exactly its local
owners' blocks, the same discipline as parallel/dist_engine
(exercised cross-process by tests/test_distributed.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ..ops.device_tokenizer import (
    INT32_MAX,
    doc_pack_width,
    gather_long_tails,
    live_groups_for,
    num_groups_for,
    pack_postings,
    rebuild_tail_groups,
    sort_dedup_groups,
    tokenize_groups,
    unpack_postings,
)
from ..ops.segment import bucket_edges
from ..utils.rounding import round_up as _round_up
from .dist_engine import default_capacity
from .mesh import SHARD_AXIS, replicated_spec, shard_spec, sharding
from .compat import shard_map


def _mix32(cols):
    """Deterministic word-content hash from the packed columns (uint32
    mul-xor mix; identical rows always hash identically)."""
    h = cols[0].astype(jnp.uint32)
    for c in cols[1:]:
        h = (h ^ c.astype(jnp.uint32)) * jnp.uint32(0x9E3779B1)
    h ^= h >> 16
    h *= jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    return h


def _body(data_l, ends_l, ids_l, *, width: int, tok_cap: int, num_docs: int,
          num_shards: int, capacity: int, sort_cols: int | None,
          owner_of_letter: tuple | None):
    groups, doc_col, max_len, num_tokens = tokenize_groups(
        data_l, ends_l, ids_l, width=width, tok_cap=tok_cap,
        num_docs=num_docs, sort_cols=sort_cols)
    live = live_groups_for(sort_cols, width)
    # group pairs past the host-exact sort_cols bound are all zero for
    # every row (valid AND padding): don't build, exchange, or sort
    # them — XLA dead-code-eliminates their windowed gathers, and the
    # all_to_all payload shrinks proportionally
    rows = (*(h for pair in groups[:live] for h in pair), doc_col)
    nrows = len(rows)

    valid = groups[0][0] != INT32_MAX
    if owner_of_letter is None:  # near-uniform content-hash ownership
        dest = (_mix32(rows[:-1]) % num_shards).astype(jnp.int32)
    else:
        # letter ownership (the reference's reducer letter ranges,
        # main.c:129-130, re-keyed at raw-text level): each owner
        # receives whole letters and can emit its own letter files
        # with no global merge — the multi-host emit mode.  Skewed by
        # construction (SURVEY.md §2.3); the provably-safe capacity
        # retry absorbs it.  First char's 5-bit code sits at group 0
        # hi's top field (pad 0, a=1 .. z=26).
        letter = ((groups[0][0] >> 25) & 31) - 1
        dest = jnp.asarray(np.asarray(owner_of_letter, np.int32))[
            jnp.clip(letter, 0, 25)]
    owner = jnp.where(valid, dest, num_shards)
    # bucket rows by owner: stable sort of (owner, perm), then windowed
    # gather per destination (the integer engines' exchange shape,
    # dist_engine._bucket_exchange, carrying the live columns side by
    # side)
    b_s, perm = lax.sort(
        (owner, jnp.arange(tok_cap, dtype=jnp.int32)), num_keys=1,
        is_stable=True)
    counts, offsets = bucket_edges(b_s, num_shards)
    overflow_local = (counts > capacity).any()
    slot = jnp.arange(capacity, dtype=jnp.int32)[None, :]
    gather_idx = jnp.clip(offsets[:, None] + slot, 0, tok_cap - 1)
    in_bucket = slot < counts[:, None]
    pg = perm[gather_idx]  # compose the two gathers once, not per row
    send = jnp.concatenate(
        [jnp.where(in_bucket, r[pg], INT32_MAX) for r in rows],
        axis=1)  # (num_shards, nrows * capacity)
    recv = lax.all_to_all(send, SHARD_AXIS, 0, 0, tiled=True)
    recv = recv.reshape(num_shards, nrows, capacity)
    recv_rows = [recv[:, r, :].reshape(-1) for r in range(nrows)]

    # un-exchanged tail group pairs are reconstructed as the constants
    # they provably are (tokenize_groups' zero-tail contract)
    zero = jnp.zeros(num_shards * capacity, jnp.int32)
    recv_groups = tuple(
        [(recv_rows[2 * g], recv_rows[2 * g + 1]) for g in range(live)]
        + [(zero, zero)] * (num_groups_for(width) - live))
    num_words, num_pairs, df, postings, unique_groups = sort_dedup_groups(
        recv_groups, recv_rows[-1], num_shards * capacity, live)
    # per-owner >12-char word count for the sparse tail-group fetch
    # (ops/device_tokenizer.fetch_pack discipline, per owner here);
    # unique_groups are already zero past num_words, so the nonzero
    # count IS the long-word count
    num_long = ((unique_groups[1][0] != 0).sum(dtype=jnp.int32)
                if len(unique_groups) > 1 else jnp.int32(0))
    return {
        # per-owner counts, sharded (n, 3) once stacked over the mesh
        "counts": jnp.stack([num_words, num_pairs, num_long])[None, :],
        # replicated health scalars: [global max word len, overflow,
        # max per-shard token count, max owner words, max owner pairs,
        # max owner long-words] — the maxima size the prefix-slice
        # fetch identically on every process (a host-side max over
        # counts would only see the local shards in a
        # multi-controller run)
        "globals": jnp.stack([
            lax.pmax(max_len, SHARD_AXIS),
            lax.psum(overflow_local.astype(jnp.int32), SHARD_AXIS),
            lax.pmax(num_tokens, SHARD_AXIS),
            lax.pmax(num_words, SHARD_AXIS),
            lax.pmax(num_pairs, SHARD_AXIS),
            lax.pmax(num_long, SHARD_AXIS),
        ]),
        "df": df,
        "postings": postings,
        "unique_groups": unique_groups,
    }


@functools.lru_cache(maxsize=32)
def _build(mesh: Mesh, width: int, tok_cap: int, num_docs: int,
           capacity: int, sort_cols: int | None,
           owner_of_letter: tuple | None):
    n = mesh.devices.size
    body = functools.partial(
        _body, width=width, tok_cap=tok_cap, num_docs=num_docs,
        num_shards=n, capacity=capacity, sort_cols=sort_cols,
        owner_of_letter=owner_of_letter)
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(shard_spec(),) * 3,
        out_specs={"counts": shard_spec(), "globals": replicated_spec(),
                   "df": shard_spec(), "postings": shard_spec(),
                   "unique_groups": ((shard_spec(), shard_spec()),)
                   * num_groups_for(width)},
        check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def _build_prefix_slice(mesh: Mesh, nu: int, npairs: int, live: int,
                        narrow: bool, k: int, nlong: int):
    """Per-owner valid-prefix slice with the single-chip tail's
    transfer trimming (ops/device_tokenizer.fetch_pack), device side,
    so the D2H transfer tracks unique counts — the fetch discipline of
    dist_engine._dist_prov_exchange (VERDICT r1 #7).  Per owner:
    postings pack ``k`` doc ids per int32 / narrow to uint16; group 0
    rides dense; tail groups ride sparsely (set-bit indices + values
    for the ``nlong``-capped >12-char words).  Output order:
    ``(df, post, g0_hi, g0_lo[, long_idx, *tail_halves])``."""
    def body(df, postings, *halves):
        dfp, pp = df[:nu], postings[:npairs]
        if narrow:
            dfp = dfp.astype(jnp.uint16)
        if k > 1:
            pp = pack_postings(pp, k)
        elif narrow:
            pp = pp.astype(jnp.uint16)
        out = [dfp, pp, halves[0][:nu], halves[1][:nu]]
        if nlong:
            idx, gathered = gather_long_tails(
                halves[2:2 * live], nu, nlong)
            out.append(idx)
            out.extend(gathered)
        return tuple(out)

    nout = 4 + ((1 + 2 * (live - 1)) if nlong else 0)
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(shard_spec(),) * (2 + 2 * live),
        out_specs=(shard_spec(),) * nout,
        check_vma=False,
    ))


def _local_mesh_positions(mesh: Mesh):
    """mesh position -> device for THIS process's devices (multi-
    process device ids are sparse; never index by device.id)."""
    me = jax.process_index()
    return {i: d for i, d in enumerate(mesh.devices.flat)
            if d.process_index == me}


def index_bytes_dist(shard_bufs, shard_ends, shard_ids, *, width: int,
                     tok_cap: int, mesh: Mesh, stats: dict | None = None,
                     sort_cols: int | None = None,
                     max_doc_id: int | None = None,
                     owner_of_letter: np.ndarray | None = None):
    """Sharded raw bytes -> per-owner index rows, over the mesh.

    ``shard_bufs``: list of n equal-length uint8 buffers (space-padded
    contiguous doc ranges).  ``shard_ends`` / ``shard_ids``: per-shard
    int32 arrays, equal lengths across shards (pad ends with the buffer
    length — padding spaces produce no tokens).  ``tok_cap``: per-shard
    token capacity (callers bound it exactly per shard and take the
    max).  Returns ``(owners, globals)`` where ``owners`` maps owner ->
    dict(num_words, num_pairs, df, postings, unique_groups) with valid
    prefixes already cut, and ``globals`` is ``(max_word_len,
    exchange_retries)``.

    Multi-controller contract: feed and fetch touch only THIS
    process's addressable devices — each process uploads its local
    mesh positions' shards and ``owners`` contains exactly the owners
    whose device is local (all of them in a single-process run).  The
    prefix-slice shape comes from the device-replicated count maxima,
    so every process compiles the same fetch program.
    """
    n = mesh.devices.size
    local_pos = _local_mesh_positions(mesh)
    ref = min(local_pos)  # any local position: shapes are uniform
    num_docs = shard_ends[ref].shape[0]
    sh = sharding(mesh, shard_spec())

    def _feed(parts):
        # only THIS process's positions are read — a pod host may pass
        # None for shards it did not load
        arrays = [jax.device_put(parts[i], d) for i, d in local_pos.items()]
        shape = (n * parts[ref].shape[0],)
        return jax.make_array_from_single_device_arrays(shape, sh, arrays)

    data = _feed(shard_bufs)
    ends = _feed(shard_ends)
    ids = _feed(shard_ids)
    owner_key = (tuple(int(x) for x in owner_of_letter)
                 if owner_of_letter is not None else None)
    capacity = default_capacity(tok_cap, n)
    retries = 0
    while True:
        out = _build(mesh, width, tok_cap, num_docs, capacity, sort_cols,
                     owner_key)(data, ends, ids)
        g = np.asarray(out["globals"])
        if int(g[1]) > 0 and capacity < tok_cap:
            capacity = tok_cap  # provably safe: a shard holds <= tok_cap rows
            retries += 1
            continue
        break
    max_len = int(g[0])
    max_shard_tokens = int(g[2])
    if max_shard_tokens + 1 > tok_cap:
        raise AssertionError(
            f"device token count {max_shard_tokens} exceeded tok_cap "
            f"{tok_cap}: host mask count diverged from the device "
            "classifier (bug)")

    # per-owner counts from THIS process's shards only (the (n, 3)
    # counts array is device-sharded; a whole-array np.asarray would
    # need every shard addressable and break multi-controller)
    owners = fetch_owner_blocks(
        out, mesh=mesh, local_len=n * capacity, width=width,
        sort_cols=sort_cols, max_doc_id=max_doc_id, max_words=int(g[3]),
        max_pairs=int(g[4]), max_long=int(g[5]), stats=stats)
    if stats is not None:
        stats["exchange_retries"] = retries
        stats["exchange_capacity"] = capacity
    return owners, (max_len, retries)


def fetch_owner_blocks(out, *, mesh: Mesh, local_len: int, width: int,
                       sort_cols: int | None, max_doc_id: int | None,
                       max_words: int, max_pairs: int, max_long: int,
                       stats: dict | None = None):
    """Addressable-shard fetch of per-owner index blocks — the shared
    tail of the mesh device engines (one-shot and streaming).

    ``out`` must carry device-sharded ``counts`` ((n, 3): words,
    pairs, >12-char words per owner), ``df``, ``postings`` and
    ``unique_groups``; ``max_words`` / ``max_pairs`` / ``max_long``
    are the device-REPLICATED per-owner maxima (identical prefix-slice
    shapes on every process).  Transfer trimming matches the
    single-chip tail (ops/device_tokenizer.fetch_pack): fetched bytes
    track unique counts, postings pack 3 doc ids per int32 when they
    fit 10 bits (uint16 under 2^16, untouched int32 above), and tail
    group pairs ride sparsely — indices + values for each owner's
    long words, the dense arrays rebuilt by host scatter.
    """
    counts = {
        (s.index[0].start or 0): np.asarray(s.data).reshape(3)
        for s in out["counts"].addressable_shards
    }
    ngroups_fetch = min(len(out["unique_groups"]),
                        live_groups_for(sort_cols, width))
    narrow = max_doc_id is not None and max_doc_id < (1 << 16)
    k = doc_pack_width(max_doc_id) if max_doc_id else 1
    # 1k granule: tight enough that fetched bytes track the max owner's
    # unique counts, coarse enough that slice programs reuse across
    # similar corpora
    nu = min(local_len, _round_up(max(max_words, 1), 1 << 10))
    npairs = min(local_len, _round_up(max(max_pairs, 1), 1 << 10))
    nlong = (min(nu, _round_up(max_long, 1 << 10))
             if ngroups_fetch > 1 and max_long else 0)
    halves = [h for pair in out["unique_groups"][:ngroups_fetch]
              for h in pair]
    sliced = _build_prefix_slice(
        mesh, nu, npairs, ngroups_fetch, narrow, k, nlong)(
        out["df"], out["postings"], *halves)
    for arr in sliced:
        for s in arr.addressable_shards:
            s.data.copy_to_host_async()

    owners = {}
    fetched = 0

    def _per_owner(arr, stride_len):
        return {(s.index[0].start or 0) // stride_len: np.asarray(s.data)
                for s in arr.addressable_shards}

    df_sh = _per_owner(sliced[0], nu)
    post_sh = _per_owner(sliced[1], (npairs + k - 1) // k if k > 1
                         else npairs)
    g0_sh = (_per_owner(sliced[2], nu), _per_owner(sliced[3], nu))
    if nlong:
        idx_sh = _per_owner(sliced[4], nlong)
        tails_sh = [_per_owner(h, nlong) for h in sliced[5:]]
    for o, cnt in counts.items():
        num_words, num_pairs, num_long = (int(v) for v in cnt)
        fetched += df_sh[o].nbytes + post_sh[o].nbytes \
            + g0_sh[0][o].nbytes + g0_sh[1][o].nbytes
        if nlong:
            fetched += idx_sh[o].nbytes + sum(
                t[o].nbytes for t in tails_sh)
        groups = (
            [(g0_sh[0][o][:num_words], g0_sh[1][o][:num_words])]
            + rebuild_tail_groups(
                num_words, ngroups_fetch,
                idx=idx_sh[o][:num_long] if nlong else None,
                tails=[(tails_sh[2 * g][o], tails_sh[2 * g + 1][o])
                       for g in range(ngroups_fetch - 1)] if nlong
                else (),
                num_long=num_long if nlong else 0))
        owners[o] = {
            "num_words": num_words, "num_pairs": num_pairs,
            "df": df_sh[o][:num_words].astype(np.int32),
            "postings": unpack_postings(post_sh[o], num_pairs, k),
            "unique_groups": groups,
        }
    if stats is not None:
        stats["dist_fetched_bytes"] = fetched
    return owners
