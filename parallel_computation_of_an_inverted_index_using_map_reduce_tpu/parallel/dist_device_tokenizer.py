"""Mesh variant of the all-device engine: sharded bytes in, index out.

Completes the engine matrix — {host scan, device scan} x {single chip,
multi chip}.  The single-chip all-device engine
(ops/device_tokenizer.py) removes the host from the compute path; this
module removes the single-chip limit: each chip receives a contiguous
doc range's raw bytes, tokenizes/cleans them locally with the SAME
traceable stages, and one ``all_to_all`` exchanges whole word rows
(13 int32 columns carried side by side) bucketed by a word-content
hash, so every term is deduped/counted by exactly one owner — the
reference's reducer ownership (main.c:129-150) re-keyed from its
~1000x-skewed letters to a near-uniform hash, at the level of raw
text rather than pre-tokenized pairs.

Per chip, as one ``shard_map`` program:

    rows   <- tokenize_rows(bytes_shard)            # local scans/scatter
    owner  <- mix32(word columns) % n
    recv   <- all_to_all(bucket(rows, owner))       # ICI, 13 columns
    index  <- sort_dedup_rows(recv)                 # owner-side radix

Static exchange capacity with a provably-safe overflow retry
(psum-reduced flag), the same discipline as the integer-pair engines
(parallel/dist_engine.py).  Exactness story is inherited:
byte-identical output or WidthOverflow fallback, never truncation.

Single-controller fetch: :func:`index_bytes_dist` materializes every
owner's results in one process (fine for one host driving a mesh).  On
a multi-host pod the fetch loop would read only addressable shards per
process, like parallel/dist_engine's multi-host contract — wiring that
seam is future work; the exchange program itself is already
process-count agnostic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ..ops.device_tokenizer import (
    INT32_MAX,
    clamp_sort_cols,
    sort_dedup_rows,
    tokenize_rows,
)
from ..ops.segment import bucket_edges
from .dist_engine import default_capacity
from .mesh import SHARD_AXIS, replicated_spec, shard_spec, sharding


def _mix32(cols):
    """Deterministic word-content hash from the packed columns (uint32
    mul-xor mix; identical rows always hash identically)."""
    h = cols[0].astype(jnp.uint32)
    for c in cols[1:]:
        h = (h ^ c.astype(jnp.uint32)) * jnp.uint32(0x9E3779B1)
    h ^= h >> 16
    h *= jnp.uint32(0x85EBCA6B)
    h ^= h >> 13
    return h


def _body(data_l, ends_l, ids_l, *, width: int, tok_cap: int, num_docs: int,
          num_shards: int, capacity: int, sort_cols: int | None):
    cols, doc_col, max_len, num_tokens = tokenize_rows(
        data_l, ends_l, ids_l, width=width, tok_cap=tok_cap,
        num_docs=num_docs)
    ncols = len(cols)
    nsort = clamp_sort_cols(sort_cols, ncols)
    # columns past the host-exact sort_cols bound are all zero for
    # every row (valid AND padding): don't build, exchange, or sort
    # them — XLA dead-code-eliminates their windowed gathers, and the
    # all_to_all payload shrinks proportionally
    rows = (*cols[:nsort], doc_col)
    nrows = len(rows)

    valid = cols[0] != INT32_MAX
    owner = jnp.where(valid,
                      (_mix32(rows[:-1]) % num_shards).astype(jnp.int32),
                      num_shards)
    # bucket rows by owner: stable sort of (owner, perm), then windowed
    # gather per destination (the integer engines' exchange shape,
    # dist_engine._bucket_exchange, carrying the live columns side by
    # side)
    b_s, perm = lax.sort(
        (owner, jnp.arange(tok_cap, dtype=jnp.int32)), num_keys=1,
        is_stable=True)
    counts, offsets = bucket_edges(b_s, num_shards)
    overflow_local = (counts > capacity).any()
    slot = jnp.arange(capacity, dtype=jnp.int32)[None, :]
    gather_idx = jnp.clip(offsets[:, None] + slot, 0, tok_cap - 1)
    in_bucket = slot < counts[:, None]
    pg = perm[gather_idx]  # compose the two gathers once, not per row
    send = jnp.concatenate(
        [jnp.where(in_bucket, r[pg], INT32_MAX) for r in rows],
        axis=1)  # (num_shards, nrows * capacity)
    recv = lax.all_to_all(send, SHARD_AXIS, 0, 0, tiled=True)
    recv = recv.reshape(num_shards, nrows, capacity)
    recv_rows = [recv[:, r, :].reshape(-1) for r in range(nrows)]

    # un-exchanged tail columns are reconstructed as the constants they
    # provably are (same zeros-splice contract as zero_tail_cols)
    zero = jnp.zeros(num_shards * capacity, jnp.int32)
    recv_cols = (*recv_rows[:-1], *([zero] * (ncols - nsort)))
    num_words, num_pairs, df, postings, unique_cols = sort_dedup_rows(
        recv_cols, recv_rows[-1], num_shards * capacity, nsort)
    return {
        # per-owner counts, sharded (n, 2) once stacked over the mesh
        "counts": jnp.stack([num_words, num_pairs])[None, :],
        # replicated health scalars:
        # [global max word len, overflow, max per-shard token count]
        "globals": jnp.stack([
            lax.pmax(max_len, SHARD_AXIS),
            lax.psum(overflow_local.astype(jnp.int32), SHARD_AXIS),
            lax.pmax(num_tokens, SHARD_AXIS),
        ]),
        "df": df,
        "postings": postings,
        "unique_cols": unique_cols,
    }


@functools.lru_cache(maxsize=32)
def _build(mesh: Mesh, width: int, tok_cap: int, num_docs: int,
           capacity: int, sort_cols: int | None):
    n = mesh.devices.size
    body = functools.partial(
        _body, width=width, tok_cap=tok_cap, num_docs=num_docs,
        num_shards=n, capacity=capacity, sort_cols=sort_cols)
    return jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(shard_spec(),) * 3,
        out_specs={"counts": shard_spec(), "globals": replicated_spec(),
                   "df": shard_spec(), "postings": shard_spec(),
                   "unique_cols": (shard_spec(),) * (width // 4)},
        check_vma=False,
    ))


def index_bytes_dist(shard_bufs, shard_ends, shard_ids, *, width: int,
                     tok_cap: int, mesh: Mesh, stats: dict | None = None,
                     sort_cols: int | None = None,
                     max_doc_id: int | None = None):
    """Sharded raw bytes -> per-owner index rows, over the mesh.

    ``shard_bufs``: list of n equal-length uint8 buffers (space-padded
    contiguous doc ranges).  ``shard_ends`` / ``shard_ids``: per-shard
    int32 arrays, equal lengths across shards (pad ends with the buffer
    length — padding spaces produce no tokens).  ``tok_cap``: per-shard
    token capacity (callers bound it exactly per shard and take the
    max).  Returns ``(owners, globals)`` where ``owners`` maps owner ->
    dict(num_words, num_pairs, df, postings, unique_cols) with valid
    prefixes already cut, and ``globals`` is ``(max_word_len,
    exchange_retries)``.
    """
    n = mesh.devices.size
    num_docs = shard_ends[0].shape[0]
    data = jax.device_put(np.concatenate(shard_bufs),
                          sharding(mesh, shard_spec()))
    ends = jax.device_put(np.concatenate(shard_ends),
                          sharding(mesh, shard_spec()))
    ids = jax.device_put(np.concatenate(shard_ids),
                         sharding(mesh, shard_spec()))
    capacity = default_capacity(tok_cap, n)
    retries = 0
    while True:
        out = _build(mesh, width, tok_cap, num_docs, capacity, sort_cols)(
            data, ends, ids)
        g = np.asarray(out["globals"])
        if int(g[1]) > 0 and capacity < tok_cap:
            capacity = tok_cap  # provably safe: a shard holds <= tok_cap rows
            retries += 1
            continue
        break
    max_len = int(g[0])
    max_shard_tokens = int(g[2])
    if max_shard_tokens + 1 > tok_cap:
        raise AssertionError(
            f"device token count {max_shard_tokens} exceeded tok_cap "
            f"{tok_cap}: host mask count diverged from the device "
            "classifier (bug)")

    counts = np.asarray(out["counts"])  # (n, 2)
    owners = {}
    fetched = 0
    per_owner = n * capacity
    # dispatch every owner's prefix slices, then materialize them all —
    # sequential fetches would each pay the link's fixed RTT.  Transfer
    # trimming mirrors the single-chip engine: columns past sort_cols
    # are provably all zero (decode restores the zero padding for
    # free); df/postings ride down as uint16 when doc ids fit.
    ncols_fetch = clamp_sort_cols(sort_cols, len(out["unique_cols"]))
    narrow = max_doc_id is not None and max_doc_id < (1 << 16)
    pending = {}
    for o in range(n):
        num_words, num_pairs = int(counts[o, 0]), int(counts[o, 1])
        lo = o * per_owner
        df_d = out["df"][lo:lo + num_words]
        post_d = out["postings"][lo:lo + num_pairs]
        if narrow:
            df_d = df_d.astype(jnp.uint16)
            post_d = post_d.astype(jnp.uint16)
        cols_d = [c[lo:lo + num_words]
                  for c in out["unique_cols"][:ncols_fetch]]
        for a in (df_d, post_d, *cols_d):
            a.copy_to_host_async()
        pending[o] = (num_words, num_pairs, df_d, post_d, cols_d)
    for o, (num_words, num_pairs, df_d, post_d, cols_d) in pending.items():
        df = np.asarray(df_d).astype(np.int32)
        postings = np.asarray(post_d).astype(np.int32)
        cols = [np.asarray(c) for c in cols_d]
        fetched += np.asarray(df_d).nbytes + np.asarray(post_d).nbytes \
            + sum(c.nbytes for c in cols)
        owners[o] = {"num_words": num_words, "num_pairs": num_pairs,
                     "df": df, "postings": postings, "unique_cols": cols}
    if stats is not None:
        stats["dist_fetched_bytes"] = fetched
        stats["exchange_retries"] = retries
        stats["exchange_capacity"] = capacity
    return owners, (max_len, retries)
