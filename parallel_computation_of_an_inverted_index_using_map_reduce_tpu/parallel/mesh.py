"""Device mesh construction.

The reference's "mesh" is N pthreads in one address space
(main.c:348-384).  Here parallelism is a 1-D JAX mesh over TPU chips;
pairs are sharded along it and exchanged with XLA collectives over ICI
(multi-host: DCN, via ``jax.distributed`` — see ``distributed.py``).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shards"


def make_mesh(num_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``num_devices`` local devices."""
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    return Mesh(devices, (SHARD_AXIS,))


def shard_spec() -> P:
    return P(SHARD_AXIS)


def replicated_spec() -> P:
    return P()


def sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)
