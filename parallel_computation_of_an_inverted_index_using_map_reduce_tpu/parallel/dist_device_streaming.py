"""Mesh streaming all-device engine: sharded raw byte windows in,
bounded per-owner row accumulators on every chip.

Completes the engine matrix's last cell — {device scan} x {mesh} x
{streaming}.  Combines the three scale mechanisms the other engines
prove separately:

- **device scan** (ops/device_tokenizer.py): the whole map phase as
  array ops over raw bytes — no host tokenizer anywhere;
- **streaming** (ops/device_streaming.py): the device carries only
  the unique (word, doc) rows seen so far, as compressed 30-bit
  (hi, lo) code pairs + doc, bounded by output size not stream length;
- **multi-chip** (parallel/dist_device_tokenizer.py): word rows are
  content-hash-partitioned over the mesh with one ``all_to_all`` per
  window, so each chip's accumulator holds only its owned terms —
  per-chip memory is O(unique / n) and the shuffle rides ICI.

Per window, as ONE ``shard_map`` program per chip:

    rows   <- tokenize_groups(local byte shard)   # 5-bit pairs direct
    recv   <- all_to_all(bucket(rows, mix32 % n))          # ICI
    acc_o  <- compact(unique(sort(acc_o ++ recv)))         # owner merge

Reference seams re-expressed: the mappers' shared spill-file shuffle
(main.c:116, 332-341) is the per-window ``all_to_all``; the reducer's
per-(word, doc) dedup (main.c:176-184) is the owner merge's
boundary-diff — with the strict map->reduce barrier (main.c:367-369)
dissolved into a window pipeline that never materializes the full
token stream anywhere.

Like the pair-mode mesh streaming engine (parallel/dist_streaming.py),
a per-owner bound cannot be derived host-side without assuming hash
uniformity, so each merge returns the replicated max per-owner count
(one scalar sync per window, amortized over large windows) and an
overflowing merge retries against the PRESERVED previous accumulator
at a doubled capacity — no data loss, no uniformity assumption.

Exactness contract is the family's: rows are actual cleaned bytes
under an injective code map; the caller rejects over-width windows
host-side BEFORE feeding (WidthOverflow -> host fallback), and every
window's device stats are re-checked against the host classifier at
finalize.  Finalize runs ops/device_streaming.finalize_rows_body per
owner inside ``shard_map`` and hands the per-owner blocks to the
shared addressable-shard fetch
(dist_device_tokenizer.fetch_owner_blocks), so the multi-controller
contract matches the one-shot mesh engine's.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ..ops.device_streaming import _compact_rows, _row_first_mask, finalize_rows_body
from ..ops.device_tokenizer import (
    INT32_MAX,
    groups_sort_perm,
    live_groups_for,
    num_groups_for,
    tokenize_groups,
)
from ..ops.segment import bucket_edges
from ..utils.rounding import round_up
from .dist_device_tokenizer import _local_mesh_positions, _mix32, fetch_owner_blocks
from .dist_engine import default_capacity
from .mesh import SHARD_AXIS, replicated_spec, shard_spec, sharding
from .compat import shard_map


def _window_merge_body(acc_and_window, *, width: int, tok_cap: int,
                       num_docs: int, num_shards: int, cap: int,
                       exchange_capacity: int, sort_cols: int,
                       live_groups: int, num_groups: int):
    """Per-chip: tokenize the local byte shard, exchange rows by
    content hash, fold received rows into this owner's accumulator."""
    nrows_acc = 2 * num_groups + 1
    acc = acc_and_window[:nrows_acc]
    data_l, ends_l, ids_l = acc_and_window[nrows_acc:]

    groups_all, doc_col, max_len, num_tokens = tokenize_groups(
        data_l, ends_l, ids_l, width=width, tok_cap=tok_cap,
        num_docs=num_docs, sort_cols=sort_cols)
    live = groups_all[:live_groups]
    send_rows = tuple(g for pair in live for g in pair) + (doc_col,)
    nrows = len(send_rows)

    valid = groups_all[0][0] != INT32_MAX
    # STABLE ownership across the whole stream: live_groups grows as
    # longer words appear, so the hash folds a FIXED number of columns
    # (all num_groups pairs — tokenize_groups emits the un-exchanged
    # tails as the constant zeros they provably are) — hashing only
    # the live columns would re-home a word mid-stream and split its
    # postings across owners
    hash_cols = tuple(g for pair in groups_all for g in pair)
    owner = jnp.where(
        valid, (_mix32(hash_cols) % num_shards).astype(jnp.int32),
        num_shards)
    b_s, perm = lax.sort(
        (owner, jnp.arange(tok_cap, dtype=jnp.int32)), num_keys=1,
        is_stable=True)
    counts, offsets = bucket_edges(b_s, num_shards)
    overflow_ex = (counts > exchange_capacity).any()
    slot = jnp.arange(exchange_capacity, dtype=jnp.int32)[None, :]
    gather_idx = jnp.clip(offsets[:, None] + slot, 0, tok_cap - 1)
    in_bucket = slot < counts[:, None]
    pg = perm[gather_idx]
    send = jnp.concatenate(
        [jnp.where(in_bucket, r[pg], INT32_MAX) for r in send_rows],
        axis=1)
    recv = lax.all_to_all(send, SHARD_AXIS, 0, 0, tiled=True)
    recv = recv.reshape(num_shards, nrows, exchange_capacity)
    recv_rows = [recv[:, r, :].reshape(-1) for r in range(nrows)]

    # splice the un-exchanged all-zero tail groups back, then fold
    zero = jnp.zeros(num_shards * exchange_capacity, jnp.int32)
    lg = len(live)
    recv_full = (tuple(recv_rows[:-1])
                 + tuple([zero] * (2 * (num_groups - lg)))
                 + (recv_rows[-1],))
    cat = tuple(jnp.concatenate([a, w]) for a, w in zip(acc, recv_full))
    doc = cat[-1]
    sort_groups = [(cat[2 * g], cat[2 * g + 1]) for g in range(max(lg, 1))]
    s_perm = groups_sort_perm(sort_groups, doc, doc.shape[0])
    s_rows = tuple(r[s_perm] for r in cat)
    first = _row_first_mask(s_rows)
    count = first.sum(dtype=jnp.int32)
    new_acc = _compact_rows(s_rows, first, cap)
    return {
        "acc": new_acc,
        # replicated health: [max per-owner unique count, exchange
        # overflow, global max word len, max per-shard token count]
        "globals": jnp.stack([
            lax.pmax(count, SHARD_AXIS),
            lax.psum(overflow_ex.astype(jnp.int32), SHARD_AXIS),
            lax.pmax(max_len, SHARD_AXIS),
            lax.pmax(num_tokens, SHARD_AXIS),
        ]),
    }


@functools.lru_cache(maxsize=64)
def _build_merge(mesh: Mesh, width: int, tok_cap: int, num_docs: int,
                 cap: int, exchange_capacity: int, sort_cols: int,
                 live_groups: int, num_groups: int):
    n = mesh.devices.size
    nrows_acc = 2 * num_groups + 1
    body = functools.partial(
        _window_merge_body, width=width, tok_cap=tok_cap,
        num_docs=num_docs, num_shards=n, cap=cap,
        exchange_capacity=exchange_capacity, sort_cols=sort_cols,
        live_groups=live_groups, num_groups=num_groups)

    def wrapper(*args):
        return body(args)

    # no donation: an overflowing merge retries against the same
    # accumulator and window at a larger capacity
    return jax.jit(shard_map(
        wrapper, mesh=mesh,
        in_specs=(shard_spec(),) * (nrows_acc + 3),
        out_specs={"acc": (shard_spec(),) * nrows_acc,
                   "globals": replicated_spec()},
        check_vma=False,
    ))


@functools.lru_cache(maxsize=64)
def _build_regrow(mesh: Mesh, old_cap: int, new_cap: int, nrows: int):
    def body(*acc):
        def one(a):
            out = jnp.full((new_cap,), INT32_MAX, jnp.int32)
            return lax.dynamic_update_slice(out, a, (0,))
        return tuple(one(a) for a in acc)

    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=(shard_spec(),) * nrows,
        out_specs=(shard_spec(),) * nrows, check_vma=False))


@functools.lru_cache(maxsize=64)
def _build_finalize(mesh: Mesh, cap: int, num_groups: int):
    def body(*acc):
        out = finalize_rows_body(acc, num_groups=num_groups)
        c = out["counts"]
        return {
            "counts": c[None, :],  # (n, 3) once stacked
            # replicated per-owner maxima [words, pairs, long] so every
            # process sizes the same prefix-slice fetch (the one-shot
            # mesh engine's globals discipline); one pmax over the
            # counts vector
            "maxima": lax.pmax(c, SHARD_AXIS),
            "df": out["df"],
            "postings": out["postings"],
            "unique_groups": out["unique_groups"],
        }

    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=(shard_spec(),) * (2 * num_groups + 1),
        out_specs={"counts": shard_spec(), "maxima": replicated_spec(),
                   "df": shard_spec(), "postings": shard_spec(),
                   "unique_groups": ((shard_spec(), shard_spec()),)
                   * num_groups},
        check_vma=False,
    ))


class DistDeviceStreamEngine:
    """Hash-sharded bounded row accumulators over a raw byte-window
    stream.  ``initial_capacity`` is *per owner*.  The caller guards
    WidthOverflow per window BEFORE feeding and supplies per-window
    host stats (host_token_stats per byte shard)."""

    def __init__(self, *, width: int, mesh: Mesh,
                 window_pad: int = 1 << 13,
                 initial_capacity: int = 1 << 15):
        self._width = width
        self._num_groups = num_groups_for(width)
        self._mesh = mesh
        self._n = mesh.devices.size
        self._window_pad = window_pad
        self._cap = initial_capacity
        self._acc = None
        self._count = 0          # last observed max per-owner count
        self._live_groups = 1
        self.windows_fed = 0
        self.max_word_len = 0
        self.merge_retries = 0
        self._window_checks = []  # (device max_len, tok_cap, host stats)

    @property
    def capacity(self) -> int:
        """Per-owner accumulator capacity."""
        return self._cap

    def _empty(self, cap: int):
        # built per addressable device (not one global device_put) so a
        # multi-host pod process can create the accumulator without
        # seeing the other hosts' devices — the same multi-controller
        # contract as _feed_arr and the addressable-shard fetch
        pad = np.full(cap, INT32_MAX, np.int32)
        sh = sharding(self._mesh, shard_spec())
        local_pos = _local_mesh_positions(self._mesh)

        def one():
            arrays = [jax.device_put(pad, d) for d in local_pos.values()]
            return jax.make_array_from_single_device_arrays(
                (self._n * cap,), sh, arrays)

        return tuple(one() for _ in range(2 * self._num_groups + 1))

    def _regrow(self, old_cap: int) -> None:
        if self._acc is not None and old_cap < self._cap:
            self._acc = _build_regrow(
                self._mesh, old_cap, self._cap,
                2 * self._num_groups + 1)(*self._acc)

    def feed(self, shard_bufs, shard_ends, shard_ids, *, tok_count: int,
             max_len: int) -> None:
        """Tokenize + exchange + fold one sharded byte window.

        ``tok_count`` / ``max_len``: max per-shard token count and max
        cleaned length over the window's shards (host-exact); the
        caller has already rejected ``max_len > width``."""
        if tok_count == 0:
            return
        self.max_word_len = max(self.max_word_len, max_len)
        # sort_cols tracks the stream's RUNNING max length, so the
        # window's live group count below equals self._live_groups --
        # the exchange payload never carries zero pairs past it
        sort_cols = -(-max(self.max_word_len, 1) // 4)
        self._live_groups = max(self._live_groups,
                                live_groups_for(sort_cols, self._width))
        tok_cap = round_up(tok_count + 1, self._window_pad)
        exchange_cap = default_capacity(tok_cap, self._n)

        local_pos = _local_mesh_positions(self._mesh)
        # only THIS process's positions are read (a pod host may pass
        # None for shards it did not load — the one-shot mesh engine's
        # multi-controller contract)
        num_docs = shard_ends[min(local_pos)].shape[0]
        sh = sharding(self._mesh, shard_spec())

        def _feed_arr(parts):
            arrays = [jax.device_put(parts[i], d)
                      for i, d in local_pos.items()]
            return jax.make_array_from_single_device_arrays(
                (self._n * parts[min(local_pos)].shape[0],), sh, arrays)

        data = _feed_arr(shard_bufs)
        ends = _feed_arr(shard_ends)
        ids = _feed_arr(shard_ids)
        if self._acc is None:
            self._acc = self._empty(self._cap)

        while True:
            out = _build_merge(
                self._mesh, self._width, tok_cap, num_docs, self._cap,
                exchange_cap, sort_cols, self._live_groups,
                self._num_groups)(*self._acc, data, ends, ids)
            g = np.asarray(out["globals"])  # one scalar sync per window
            if int(g[1]) > 0 and exchange_cap < tok_cap:
                exchange_cap = tok_cap  # provably safe: <= tok_cap rows
                self.merge_retries += 1
                continue
            if int(g[0]) > self._cap:
                old = self._cap
                while self._cap < int(g[0]):
                    self._cap *= 2
                self.merge_retries += 1
                self._regrow(old)
                continue
            break
        self._acc = out["acc"]
        self._count = int(g[0])
        self._window_checks.append((int(g[2]), tok_cap, int(g[3]),
                                    max_len))
        # grow ahead of the next window once 3/4 full (amortized)
        if self._count * 4 > self._cap * 3:
            old = self._cap
            self._cap *= 2
            self._regrow(old)
        self.windows_fed += 1

    def finalize(self, *, sort_cols: int | None, max_doc_id: int,
                 stats: dict | None = None):
        """Per-owner index blocks via the shared addressable fetch
        (``{owner: dict}``, the one-shot mesh engine's contract).
        Re-checks every window's device stats against the host
        classifier first, like the single-chip streaming engine."""
        if self._acc is None:
            raise ValueError("no windows fed")
        for dev_max_len, tok_cap, dev_tokens, host_max_len in (
                self._window_checks):
            if dev_tokens + 1 > tok_cap:
                raise AssertionError(
                    f"device token count {dev_tokens} exceeded tok_cap "
                    f"{tok_cap}: host mask count diverged from the "
                    "device classifier (bug)")
            if dev_max_len != host_max_len:
                raise AssertionError(
                    f"device max word len {dev_max_len} != host "
                    f"{host_max_len}: classifier divergence (bug)")
        out = _build_finalize(
            self._mesh, self._cap, self._num_groups)(*self._acc)
        self._acc = None
        self._window_checks = []
        mx = np.asarray(out["maxima"])
        owners = fetch_owner_blocks(
            out, mesh=self._mesh, local_len=self._cap, width=self._width,
            sort_cols=sort_cols, max_doc_id=max_doc_id,
            max_words=int(mx[0]), max_pairs=int(mx[1]),
            max_long=int(mx[2]), stats=stats)
        if stats is not None:
            stats["merge_retries"] = self.merge_retries
            stats["accumulator_capacity_per_owner"] = self._cap
        return owners
