"""Multi-chip engine: hash-bucket ``all_to_all`` shuffle over the mesh.

This is the TPU-native replacement for the reference's shuffle — 26
shared spill files written by every mapper under implicit stdio locks
and re-read by letter-owning reducers (main.c:116, 332-341, 135-137):

- pairs are sharded over chips (data parallelism over documents,
  main.c:307-328's file ranges);
- each chip buckets its pairs by ``term % n_chips`` — a uniform hash
  partition, unlike the reference's ~1000x-skewed first-letter
  partition (SURVEY.md §2.3) — and exchanges them with one
  ``lax.all_to_all`` over ICI;
- each chip dedups its owned terms locally (sorted boundary diff — the
  global dedup, since a term's pairs all land on its owner) and keeps
  its survivors *sharded*;
- only vocab-sized aggregates cross chips after the exchange: document
  frequency via one ``psum``, from which the emit order is computed
  replicated.  The deduped pair shards go straight to the host, which
  merges n sorted runs during emit (emit is host-bound regardless) —
  per-chip work and memory stay O(N/n log N/n), never O(N).

The exchange uses a fixed per-bucket capacity (static shapes for XLA);
a returned overflow flag triggers one retry at the provably-safe
capacity.  Every step is a collective or a fused elementwise/scan —
no host round-trips inside the program.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ..ops import keys as K
from ..ops.engine import emit_order
from ..ops.segment import compact, first_occurrence_mask
from ..utils.rounding import round_up as _round_up
from .mesh import SHARD_AXIS, make_mesh, replicated_spec, shard_spec


def default_capacity(local_size: int, num_shards: int, factor: float = 2.0) -> int:
    """Per-(src, dst) bucket capacity.

    Expected load is ``local_size / num_shards``; ``factor`` covers hash
    imbalance.  Capped at ``local_size`` (the provably-safe value: one
    source cannot send more pairs than it holds).
    """
    if num_shards == 1:
        return local_size
    return min(local_size, _round_up(int(math.ceil(local_size / num_shards * factor)), 8))


def _bucket_exchange(keys_local, valid_limit, *, num_shards: int,
                     capacity: int, stride: int):
    """Shared exchange core: hash-partition packed keys and run one ICI
    ``all_to_all``.

    Buckets by ``term % num_shards`` (uniform, unlike the reference's
    ~1000x-skewed first-letter partition); keys ``>= valid_limit`` go to
    the padding bucket.  Returns ``(recv, overflow_local)`` where row b
    of the fixed-shape send buffer went to device b.
    """
    local = keys_local.shape[0]
    term = keys_local // stride
    bucket = jnp.where(keys_local < valid_limit, term % num_shards, num_shards)
    bucket_s, keys_s = lax.sort((bucket.astype(jnp.int32), keys_local), num_keys=2)
    counts = jnp.zeros((num_shards,), jnp.int32).at[bucket_s].add(1, mode="drop")
    offsets = jnp.cumsum(counts) - counts
    overflow_local = (counts > capacity).any()

    # fixed-shape send buffer (num_shards, capacity)
    slot = jnp.arange(capacity, dtype=jnp.int32)[None, :]
    gather_idx = jnp.clip(offsets[:, None] + slot, 0, local - 1)
    in_bucket = slot < counts[:, None]
    send = jnp.where(in_bucket, keys_s[gather_idx], K.INT32_MAX)
    return lax.all_to_all(send, SHARD_AXIS, 0, 0, tiled=True), overflow_local


def _shuffle_body(keys_local, letter_of_term, *, num_shards: int, capacity: int,
                  vocab_size: int, max_doc_id: int):
    """shard_map body: runs per-device with collectives over SHARD_AXIS."""
    stride = max_doc_id + 2
    valid_limit = vocab_size * stride
    recv, overflow_local = _bucket_exchange(
        keys_local, valid_limit, num_shards=num_shards, capacity=capacity,
        stride=stride)

    # --- owner-side global dedup of this device's terms.
    recv_s = lax.sort(recv.reshape(-1))
    first = first_occurrence_mask(recv_s) & (recv_s < valid_limit)
    uniq = compact(recv_s, first, recv_s.shape[0], K.INT32_MAX)

    # --- vocab-sized aggregates only: df by psum, emit order replicated.
    owned_term = recv_s // stride
    df_local = jnp.zeros((vocab_size,), jnp.int32).at[
        jnp.where(first, owned_term, vocab_size)
    ].add(1, mode="drop")
    df = lax.psum(df_local, SHARD_AXIS)
    order = emit_order(letter_of_term, df, vocab_size, max_doc_id)
    offsets = jnp.cumsum(df) - df
    return {
        "uniq_sharded": uniq,
        "df": df,
        "order": order,
        "offsets": offsets,
        "num_unique": lax.psum(first.astype(jnp.int32).sum(), SHARD_AXIS),
        "overflow": lax.psum(overflow_local.astype(jnp.int32), SHARD_AXIS),
    }


@functools.lru_cache(maxsize=64)
def _build(mesh: Mesh, num_shards: int, capacity: int, vocab_size: int,
           max_doc_id: int, donate: bool):
    def body(keys_local, letters):
        return _shuffle_body(
            keys_local, letters, num_shards=num_shards, capacity=capacity,
            vocab_size=vocab_size, max_doc_id=max_doc_id)

    out_specs = {
        "uniq_sharded": shard_spec(),
        "df": replicated_spec(),
        "order": replicated_spec(),
        "offsets": replicated_spec(),
        "num_unique": replicated_spec(),
        "overflow": replicated_spec(),
    }
    return jax.jit(
        jax.shard_map(
            body, mesh=mesh,
            in_specs=(shard_spec(), replicated_spec()),
            out_specs=out_specs,
            check_vma=False,
        ),
        # Donation frees the input keys' HBM during the exchange, but the
        # overflow retry re-feeds the same buffer, so only donate when no
        # retry can follow (capacity already at the provably-safe bound).
        donate_argnums=(0,) if donate else (),
    )


def assemble_postings(uniq_sharded, max_doc_id: int, valid_limit: int) -> np.ndarray:
    """Host-side merge of the sharded deduped pair keys into the global
    term-major postings array (runs during emit, which is host-bound)."""
    keys = np.asarray(uniq_sharded)
    ks = np.sort(keys[keys < valid_limit], kind="stable")
    return (ks % (max_doc_id + 2)).astype(np.int32)


def _prov_shuffle_body(window_locals, *, num_shards: int, capacity: int,
                       stride: int):
    """shard_map body for the pipelined (provisional-key) dist path.

    Unlike :func:`_shuffle_body`, the feed is already combiner-deduped
    and emit order is resolved host-side from the combiner's df counts
    (models/inverted_index.py), so the program is pure data movement:
    concat this device's slice of every upload window, bucket by term
    hash, one ``all_to_all`` over ICI, owner-side sort.  The owner sort
    makes each device's slice ascending and term-grouped, so the host
    assembles global postings with one valid-prefix merge instead of a
    re-sort.
    """
    keys_local = jnp.concatenate(list(window_locals))
    recv, overflow_local = _bucket_exchange(
        keys_local, K.INT32_MAX, num_shards=num_shards, capacity=capacity,
        stride=stride)
    recv_s = lax.sort(recv.reshape(-1))
    return {
        "owned_sorted": recv_s,
        "overflow": lax.psum(overflow_local.astype(jnp.int32), SHARD_AXIS),
    }


@functools.lru_cache(maxsize=64)
def _build_prov(mesh: Mesh, num_windows: int, window_local: tuple,
                num_shards: int, capacity: int, stride: int, donate: bool):
    def body(*window_locals):
        return _prov_shuffle_body(
            window_locals, num_shards=num_shards, capacity=capacity,
            stride=stride)

    return jax.jit(
        jax.shard_map(
            body, mesh=mesh,
            in_specs=tuple(shard_spec() for _ in range(num_windows)),
            out_specs={"owned_sorted": shard_spec(),
                       "overflow": replicated_spec()},
            check_vma=False,
        ),
        donate_argnums=tuple(range(num_windows)) if donate else (),
    )


def dist_sort_prov_windows(windows, *, stride: int, mesh: Mesh,
                           capacity_factor: float = 2.0) -> np.ndarray:
    """Distributed tail of the pipelined path: shuffle + sort the
    sharded provisional-key upload windows; returns the host-assembled
    postings array (docs grouped by prov term id, ascending).

    Each element of ``windows`` is an int32 device array sharded over
    ``mesh`` (padded with ``K.INT32_MAX`` to a multiple of the mesh
    size).  Overflow of the per-bucket capacity triggers one retry at
    the provably-safe bound, exactly like :func:`dist_index`.
    """
    n = mesh.devices.size
    local_total = sum(w.shape[0] for w in windows) // n
    capacity = default_capacity(local_total, n, capacity_factor)
    shapes = tuple(w.shape[0] for w in windows)
    out = _build_prov(mesh, len(windows), shapes, n, capacity, stride,
                      capacity >= local_total)(*windows)
    if capacity < local_total and int(out["overflow"]) > 0:
        out = _build_prov(mesh, len(windows), shapes, n, local_total, stride,
                          True)(*windows)
    # Owner d holds ascending keys of exactly the terms ≡ d (mod n), so
    # every term's postings are contiguous within one shard; the host
    # merges the n sorted runs into global term order (at multi-host
    # scale this merge disappears — each host emits its own owners'
    # letters instead, the reference's reducer ownership re-expressed).
    owned = np.asarray(out["owned_sorted"]).reshape(n, -1)
    valid = [row[row < K.INT32_MAX] for row in owned]
    keys = np.concatenate(valid) if valid else np.empty(0, np.int32)
    keys.sort(kind="stable")
    return (keys % stride).astype(np.int32)


def dist_index(keys, letter_of_term, *, vocab_size: int, max_doc_id: int,
               mesh: Mesh | None = None, capacity_factor: float = 2.0):
    """Distributed index of packed pair keys sharded over the mesh.

    ``keys`` length must be a multiple of the mesh size (pad with
    ``K.INT32_MAX``).  Returns the single-chip engine's dict interface;
    ``postings`` is assembled on host from the sharded unique keys, the
    vocab-sized outputs (df/order/offsets) are replicated device arrays.
    If the hash partition overflows the default capacity, the exchange
    is re-run once at the provably-safe capacity.
    """
    mesh = mesh if mesh is not None else make_mesh()
    n = mesh.devices.size
    if keys.shape[0] % n:
        raise ValueError(f"keys length {keys.shape[0]} not divisible by mesh size {n}")
    local = keys.shape[0] // n
    capacity = default_capacity(local, n, capacity_factor)
    out = _build(mesh, n, capacity, vocab_size, max_doc_id, capacity >= local)(
        keys, letter_of_term)
    if capacity < local and int(out["overflow"]) > 0:
        out = _build(mesh, n, local, vocab_size, max_doc_id, True)(keys, letter_of_term)
    out.pop("overflow", None)
    uniq = out.pop("uniq_sharded")
    out["postings"] = assemble_postings(
        uniq, max_doc_id, vocab_size * (max_doc_id + 2))
    return out
