"""Multi-chip engine: hash-bucket ``all_to_all`` shuffle over the mesh.

This is the TPU-native replacement for the reference's shuffle — 26
shared spill files written by every mapper under implicit stdio locks
and re-read by letter-owning reducers (main.c:116, 332-341, 135-137):

- pairs are sharded over chips (data parallelism over documents,
  main.c:307-328's file ranges);
- each chip buckets its pairs by ``term % n_chips`` — a uniform hash
  partition, unlike the reference's ~1000x-skewed first-letter
  partition (SURVEY.md §2.3) — and exchanges them with one
  ``lax.all_to_all`` over ICI;
- each chip dedups its owned terms locally (sorted boundary diff — the
  global dedup, since a term's pairs all land on its owner) and keeps
  its survivors *sharded*;
- only vocab-sized aggregates cross chips after the exchange: document
  frequency via one ``psum``, from which the emit order is computed
  replicated.  The deduped pair shards go straight to the host, which
  merges n sorted runs during emit (emit is host-bound regardless) —
  per-chip work and memory stay O(N/n log N/n), never O(N).

The exchange uses a fixed per-bucket capacity (static shapes for XLA);
a returned overflow flag triggers one retry at the provably-safe
capacity.  Every step is a collective or a fused elementwise/scan —
no host round-trips inside the program.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ..ops import keys as K
from ..ops.engine import emit_order
from ..ops.segment import (
    bucket_edges,
    compact,
    first_occurrence_mask,
    sorted_segment_counts,
)
from ..utils.rounding import round_up as _round_up
from .mesh import SHARD_AXIS, make_mesh, replicated_spec, shard_spec, sharding
from .compat import shard_map


def default_capacity(local_size: int, num_shards: int, factor: float = 2.0) -> int:
    """Per-(src, dst) bucket capacity.

    Expected load is ``local_size / num_shards``; ``factor`` covers hash
    imbalance.  Capped at ``local_size`` (the provably-safe value: one
    source cannot send more pairs than it holds).
    """
    if num_shards == 1:
        return local_size
    return min(local_size, _round_up(int(math.ceil(local_size / num_shards * factor)), 8))


def _bucket_exchange(keys_local, valid_limit, *, num_shards: int,
                     capacity: int, stride: int, owner_of_term=None):
    """Shared exchange core: partition packed keys and run one ICI
    ``all_to_all``.

    Default bucketing is ``term % num_shards`` (uniform, unlike the
    reference's ~1000x-skewed first-letter partition); passing
    ``owner_of_term`` (a replicated term->owner map) buckets by it
    instead — the letter-ownership partition of the per-owner emit mode.
    Keys ``>= valid_limit`` go to the padding bucket.  Returns
    ``(recv, overflow_local)`` where row b of the fixed-shape send
    buffer went to device b.
    """
    local = keys_local.shape[0]
    term = keys_local // stride
    owner = (term % num_shards if owner_of_term is None
             else owner_of_term[jnp.clip(term, 0, owner_of_term.shape[0] - 1)])
    bucket = jnp.where(keys_local < valid_limit, owner, num_shards)
    bucket_s, keys_s = lax.sort((bucket.astype(jnp.int32), keys_local), num_keys=2)
    counts, offsets = bucket_edges(bucket_s, num_shards)
    overflow_local = (counts > capacity).any()

    # fixed-shape send buffer (num_shards, capacity)
    slot = jnp.arange(capacity, dtype=jnp.int32)[None, :]
    gather_idx = jnp.clip(offsets[:, None] + slot, 0, local - 1)
    in_bucket = slot < counts[:, None]
    send = jnp.where(in_bucket, keys_s[gather_idx], K.INT32_MAX)
    return lax.all_to_all(send, SHARD_AXIS, 0, 0, tiled=True), overflow_local


def _shuffle_body(keys_local, letter_of_term, *, num_shards: int, capacity: int,
                  vocab_size: int, max_doc_id: int):
    """shard_map body: runs per-device with collectives over SHARD_AXIS."""
    stride = max_doc_id + 2
    valid_limit = vocab_size * stride
    recv, overflow_local = _bucket_exchange(
        keys_local, valid_limit, num_shards=num_shards, capacity=capacity,
        stride=stride)

    # --- owner-side global dedup of this device's terms.
    recv_s = lax.sort(recv.reshape(-1))
    first = first_occurrence_mask(recv_s) & (recv_s < valid_limit)
    uniq = compact(recv_s, first, recv_s.shape[0], K.INT32_MAX)

    # --- vocab-sized aggregates only: df by psum, emit order replicated.
    owned_term = recv_s // stride  # nondecreasing: recv_s is sorted
    df_local = sorted_segment_counts(owned_term, first.astype(jnp.int32), vocab_size)
    df = lax.psum(df_local, SHARD_AXIS)
    order = emit_order(letter_of_term, df, vocab_size, max_doc_id)
    offsets = jnp.cumsum(df) - df
    return {
        "uniq_sharded": uniq,
        "df": df,
        "order": order,
        "offsets": offsets,
        "num_unique": lax.psum(first.astype(jnp.int32).sum(), SHARD_AXIS),
        "overflow": lax.psum(overflow_local.astype(jnp.int32), SHARD_AXIS),
    }


@functools.lru_cache(maxsize=64)
def _build(mesh: Mesh, num_shards: int, capacity: int, vocab_size: int,
           max_doc_id: int, donate: bool):
    def body(keys_local, letters):
        return _shuffle_body(
            keys_local, letters, num_shards=num_shards, capacity=capacity,
            vocab_size=vocab_size, max_doc_id=max_doc_id)

    out_specs = {
        "uniq_sharded": shard_spec(),
        "df": replicated_spec(),
        "order": replicated_spec(),
        "offsets": replicated_spec(),
        "num_unique": replicated_spec(),
        "overflow": replicated_spec(),
    }
    return jax.jit(
        shard_map(
            body, mesh=mesh,
            in_specs=(shard_spec(), replicated_spec()),
            out_specs=out_specs,
            check_vma=False,
        ),
        # Donation frees the input keys' HBM during the exchange, but the
        # overflow retry re-feeds the same buffer, so only donate when no
        # retry can follow (capacity already at the provably-safe bound).
        donate_argnums=(0,) if donate else (),
    )


def assemble_postings(uniq_sharded, max_doc_id: int, valid_limit: int,
                      offsets: np.ndarray, num_pairs: int) -> np.ndarray:
    """O(N) host-side merge of the sharded deduped pair keys into the
    global term-major postings array (runs during emit, which is
    host-bound).

    Each shard's keys are ascending (owner-side sort, INT32_MAX padding
    packed at the tail) and every term's pairs live on exactly one
    owner, so scattering each shard's term runs at the replicated
    global ``offsets`` is a complete, collision-free merge — no
    token-scale re-sort anywhere in the dist tails."""
    stride = max_doc_id + 2
    shards = uniq_sharded.addressable_shards
    if len(shards) < uniq_sharded.sharding.num_devices:
        raise RuntimeError(
            "global postings assembly needs every shard addressable; in a "
            "multi-host run use emit_ownership='letter' so each host emits "
            "only its own owners' letters")
    postings = np.empty(max(num_pairs, 1), dtype=np.int32)
    for s in shards:  # overlap the D2H transfers before the serial reads
        s.data.copy_to_host_async()
    for s in shards:
        keys = np.asarray(s.data)
        keys = keys[: np.searchsorted(keys, valid_limit)]
        if keys.size:
            _scatter_run(keys // stride, keys % stride, offsets, postings)
    return postings[:num_pairs]


def _prov_shuffle_body(window_locals, *, num_shards: int, capacity: int,
                       stride: int, owner_of_term=None):
    """shard_map body for the pipelined (provisional-key) dist path.

    Unlike :func:`_shuffle_body`, the feed is already combiner-deduped
    and emit order is resolved host-side from the combiner's df counts
    (models/inverted_index.py), so the program is pure data movement:
    concat this device's slice of every upload window, bucket by term
    hash — or by ``owner_of_term`` (the letter-ownership partition of
    the per-owner emit mode, the reference's reducer letter ranges
    main.c:129-130) — one ``all_to_all`` over ICI, owner-side sort.
    The owner sort makes each device's slice ascending and
    term-grouped, so the host assembles postings with one valid-prefix
    merge instead of a re-sort.  ``valid`` (per-owner count of real
    keys) lets the host fetch only the valid prefix instead of the
    2x-overprovisioned capacity buffer (VERDICT r1 #7).
    """
    keys_local = jnp.concatenate(list(window_locals))
    recv, overflow_local = _bucket_exchange(
        keys_local, K.INT32_MAX, num_shards=num_shards, capacity=capacity,
        stride=stride, owner_of_term=owner_of_term)
    recv_s = lax.sort(recv.reshape(-1))
    valid = (recv_s < K.INT32_MAX).sum(dtype=jnp.int32)
    return {
        "owned_sorted": recv_s,
        "valid": valid[None],
        # replicated global max -> every process computes the same
        # fetch-slice shape without seeing the other hosts' counts
        "max_valid": lax.pmax(valid, SHARD_AXIS),
        "overflow": lax.psum(overflow_local.astype(jnp.int32), SHARD_AXIS),
    }


@functools.lru_cache(maxsize=64)
def _build_prov(mesh: Mesh, num_windows: int, window_local: tuple,
                num_shards: int, capacity: int, stride: int, donate: bool,
                with_owner: bool = False):
    """Compiled exchange program; ``with_owner`` prepends a replicated
    term->owner map argument (letter-ownership mode)."""
    if with_owner:
        def body(owner_of_term, *window_locals):
            return _prov_shuffle_body(
                window_locals, num_shards=num_shards, capacity=capacity,
                stride=stride, owner_of_term=owner_of_term)

        in_specs = (replicated_spec(),) + tuple(
            shard_spec() for _ in range(num_windows))
        donate_argnums = tuple(range(1, num_windows + 1))
    else:
        def body(*window_locals):
            return _prov_shuffle_body(
                window_locals, num_shards=num_shards, capacity=capacity,
                stride=stride)

        in_specs = tuple(shard_spec() for _ in range(num_windows))
        donate_argnums = tuple(range(num_windows))

    return jax.jit(
        shard_map(
            body, mesh=mesh,
            in_specs=in_specs,
            out_specs={"owned_sorted": shard_spec(),
                       "valid": shard_spec(),
                       "max_valid": replicated_spec(),
                       "overflow": replicated_spec()},
            check_vma=False,
        ),
        donate_argnums=donate_argnums if donate else (),
    )


def _exchange_and_fetch_rows(windows, *, stride: int, mesh: Mesh,
                             capacity_factor: float,
                             owner_of_prov: np.ndarray | None,
                             stats: dict | None) -> dict[int, np.ndarray]:
    """Shared tail of both dist paths: run the (possibly letter-keyed)
    exchange with the capacity-overflow retry, then fetch each owner's
    valid prefix — per-owner counts, then one device-side slice at the
    replicated global max count rounded to a reuse granule, so fetched
    bytes track unique pairs, not the overprovisioned capacity
    (VERDICT r1 #7).

    Returns ``{owner_id: keys}`` for every *addressable* owner: in a
    multi-host (multi-controller) run each process sees only its local
    devices' shards — exactly what the per-owner emit needs, and why
    the slice shape comes from the device-computed ``max_valid``
    (replicated) rather than a host-side max over counts this process
    cannot see.
    """
    n = mesh.devices.size
    local_total = sum(w.shape[0] for w in windows) // n
    capacity = default_capacity(local_total, n, capacity_factor)
    shapes = tuple(w.shape[0] for w in windows)
    with_owner = owner_of_prov is not None
    args = tuple(windows)
    if with_owner:
        owner_dev = jax.device_put(
            np.ascontiguousarray(owner_of_prov, dtype=np.int32),
            sharding(mesh, replicated_spec()))
        args = (owner_dev,) + args
    # donate the window buffers only when no retry can re-feed them
    # (the owner map, arg 0 in owner mode, is never donated)
    out = _build_prov(mesh, len(windows), shapes, n, capacity, stride,
                      capacity >= local_total, with_owner)(*args)
    if capacity < local_total and int(out["overflow"]) > 0:
        out = _build_prov(mesh, len(windows), shapes, n, local_total, stride,
                          True, with_owner)(*args)
    # shard.index[0].start is None for a full-span shard (1-device mesh)
    counts = {
        (s.index[0].start or 0): int(np.asarray(s.data)[0])
        for s in out["valid"].addressable_shards
    }
    local_len = int(out["owned_sorted"].shape[0]) // n
    nfetch = min(local_len,
                 _round_up(max(int(out["max_valid"]), 1), 1 << 13))
    sliced = _build_prefix_slice(mesh, local_len, nfetch)(out["owned_sorted"])
    rows = {}
    fetched = 0
    for s in sliced.addressable_shards:  # overlap the D2H transfers
        s.data.copy_to_host_async()
    for s in sliced.addressable_shards:
        owner = (s.index[0].start or 0) // nfetch
        row = np.asarray(s.data)
        rows[owner] = row[: counts[owner]]
        fetched += row.nbytes
    if stats is not None:
        stats["dist_fetched_bytes"] = fetched + 4 * len(counts)
        stats["dist_valid_pairs"] = int(sum(counts.values()))
    return rows


def dist_letter_windows(windows, owner_of_prov: np.ndarray, *, stride: int,
                        mesh: Mesh, capacity_factor: float = 2.0,
                        stats: dict | None = None) -> dict[int, np.ndarray]:
    """Per-owner-emit tail of the pipelined path: exchange the sharded
    upload windows by letter owner (the reference's reducer letter
    ranges, main.c:129-130, via corpus/scheduler.plan_letter_ranges);
    returns ``{owner: keys}`` (prov-grouped ascending, docs ascending
    inside each term) for every addressable owner.  The letter
    partition is skewed by construction (SURVEY.md §2.3); the
    capacity-overflow retry at the provably-safe bound absorbs it.

    In the multi-host regime each process receives only its own local
    owners' rows and emits just those letter files; a single-controller
    run receives all of them.
    """
    return _exchange_and_fetch_rows(
        windows, stride=stride, mesh=mesh, capacity_factor=capacity_factor,
        owner_of_prov=owner_of_prov, stats=stats)


@functools.lru_cache(maxsize=64)
def _build_prefix_slice(mesh: Mesh, local_len: int, nfetch: int):
    """Per-shard valid-prefix slice, compiled once per (len, nfetch)
    bucket: the owner sort packs real keys first, so ``x[:nfetch]`` on
    each shard drops the INT32_MAX padding *before* the D2H transfer."""
    return jax.jit(shard_map(
        lambda x: x[:nfetch], mesh=mesh,
        in_specs=shard_spec(), out_specs=shard_spec(), check_vma=False))


def _scatter_run(term: np.ndarray, doc: np.ndarray,
                 offsets_prov: np.ndarray, postings: np.ndarray) -> None:
    """Scatter one owner's (term-grouped ascending) run into the global
    prov-grouped postings array — vectorized, collision-free because
    every term lives on exactly one owner."""
    change = np.empty(term.shape[0], dtype=bool)
    change[0] = True
    np.not_equal(term[1:], term[:-1], out=change[1:])
    starts = np.flatnonzero(change)
    run_of_elem = np.cumsum(change) - 1
    within = np.arange(term.shape[0], dtype=np.int64) - starts[run_of_elem]
    postings[offsets_prov[term] + within] = doc


def merge_owner_runs(rows, stride: int, offsets_prov: np.ndarray,
                     num_pairs: int) -> np.ndarray:
    """O(N) host merge of per-owner sorted *packed-key* runs into the
    global prov-grouped postings array.

    Each ``rows[d]`` is owner d's valid keys, ascending — grouped by
    prov term with docs ascending inside each group — and every term's
    pairs live on exactly one owner, so scattering each group to its
    term's global slot (``offsets_prov``) is a complete, collision-free
    merge: no token-scale sort anywhere, just vectorized index math.
    """
    postings = np.empty(max(num_pairs, 1), dtype=np.int32)
    for row in rows:
        if row.size:
            _scatter_run(row // stride, row % stride, offsets_prov, postings)
    return postings[:num_pairs]


def merge_owner_pair_runs(rows, offsets_prov: np.ndarray,
                          num_pairs: int) -> np.ndarray:
    """Pair-mode variant of :func:`merge_owner_runs`: each ``rows[d]``
    is ``(terms, docs)`` sorted by (term, doc)."""
    postings = np.empty(max(num_pairs, 1), dtype=np.int32)
    for term, doc in rows:
        if term.size:
            _scatter_run(term.astype(np.int64), doc, offsets_prov, postings)
    return postings[:num_pairs]


def dist_sort_prov_windows(windows, *, stride: int, mesh: Mesh,
                           offsets_prov: np.ndarray, num_pairs: int,
                           capacity_factor: float = 2.0,
                           stats: dict | None = None) -> np.ndarray:
    """Distributed tail of the pipelined path: shuffle + sort the
    sharded provisional-key upload windows; returns the host-assembled
    postings array (docs grouped by prov term id, ascending).

    Each element of ``windows`` is an int32 device array sharded over
    ``mesh`` (padded with ``K.INT32_MAX`` to a multiple of the mesh
    size).  Overflow of the per-bucket capacity triggers one retry at
    the provably-safe bound, exactly like :func:`dist_index`.

    ``offsets_prov`` (prov-space postings offsets from the combiner's
    df counts) drives the O(N) :func:`merge_owner_runs`; only the
    valid prefix of each owner's sorted buffer crosses the D2H link —
    the padded capacity tail never leaves the device.  ``stats`` (if
    given) records ``dist_fetched_bytes`` for observability.
    """
    rows = _exchange_and_fetch_rows(
        windows, stride=stride, mesh=mesh, capacity_factor=capacity_factor,
        owner_of_prov=None, stats=stats)
    if len(rows) < mesh.devices.size:
        raise RuntimeError(
            "merged postings assembly needs every shard addressable; in a "
            "multi-host run use emit_ownership='letter' so each host emits "
            "only its own owners' letters")
    return merge_owner_runs(rows.values(), stride, offsets_prov, num_pairs)


def dist_index(keys, letter_of_term, *, vocab_size: int, max_doc_id: int,
               mesh: Mesh | None = None, capacity_factor: float = 2.0):
    """Distributed index of packed pair keys sharded over the mesh.

    ``keys`` length must be a multiple of the mesh size (pad with
    ``K.INT32_MAX``).  Returns the single-chip engine's dict interface;
    ``postings`` is assembled on host from the sharded unique keys, the
    vocab-sized outputs (df/order/offsets) are replicated device arrays.
    If the hash partition overflows the default capacity, the exchange
    is re-run once at the provably-safe capacity.
    """
    mesh = mesh if mesh is not None else make_mesh()
    n = mesh.devices.size
    if keys.shape[0] % n:
        raise ValueError(f"keys length {keys.shape[0]} not divisible by mesh size {n}")
    local = keys.shape[0] // n
    capacity = default_capacity(local, n, capacity_factor)
    out = _build(mesh, n, capacity, vocab_size, max_doc_id, capacity >= local)(
        keys, letter_of_term)
    if capacity < local and int(out["overflow"]) > 0:
        out = _build(mesh, n, local, vocab_size, max_doc_id, True)(keys, letter_of_term)
    out.pop("overflow", None)
    uniq = out.pop("uniq_sharded")
    out["postings"] = assemble_postings(
        uniq, max_doc_id, vocab_size * (max_doc_id + 2),
        np.asarray(out["offsets"]), int(out["num_unique"]))
    return out
