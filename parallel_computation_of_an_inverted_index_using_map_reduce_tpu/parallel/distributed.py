"""Multi-host runtime initialization.

The reference is strictly single-process (SURVEY.md §4: "no multi-node
story at all"); its communication backend is the filesystem.  The TPU
framework's backend is XLA collectives: ICI within a slice, DCN across
hosts.  This module is the thin seam over ``jax.distributed`` so the
same ``dist_index`` program runs on a multi-host pod — every host feeds
its local shard of pairs and the collectives span the global mesh.
"""

from __future__ import annotations

import jax


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join (or start) a multi-host JAX runtime.

    With no arguments, relies on the environment (TPU pod metadata /
    ``JAX_COORDINATOR_ADDRESS`` etc.), which is how TPU VMs are normally
    launched.  Safe to call once per process before any computation.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def runtime_info() -> dict:
    """Structured view of the distributed topology for logs/metrics."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
    }
