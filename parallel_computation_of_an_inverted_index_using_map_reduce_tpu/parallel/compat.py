"""jax version compat: one place that resolves ``shard_map``.

``jax.shard_map`` became a top-level export (with the ``check_vma``
kwarg) only in newer jax; older releases ship it as
``jax.experimental.shard_map.shard_map`` where the same knob is called
``check_rep``.  Every ``parallel/dist_*`` engine (and the ops-layer
code that runs inside their mapped bodies) imports :func:`shard_map`
from here so the version probe happens exactly once, at import time —
call sites keep the modern signature unchanged.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax < 0.6: the experimental module, check_vma spelled check_rep
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

__all__ = ["shard_map"]
