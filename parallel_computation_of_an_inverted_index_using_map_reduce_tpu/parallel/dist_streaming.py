"""Distributed streaming engine: bounded per-chip accumulators over an
unbounded pair stream (BASELINE.json config 5's regime — "streaming
host->device token batches" on a mesh).

Combines the two scale axes the single-chip engines cover separately:

- **streaming** (ops/streaming.py): the device carries only the sorted
  unique pairs seen so far, bounded by output size, not stream length;
- **multi-chip** (parallel/dist_engine.py): pairs are hash-partitioned
  over the mesh with one ``all_to_all`` per window, so each chip's
  accumulator holds only its own terms — per-chip memory is
  O(unique / n), the shuffle rides ICI, and the map→reduce spill files
  of the reference (main.c:332-341) never exist.

Per window, as one ``shard_map`` program:

    recv   <- all_to_all(bucket(window, term % n))        # ICI shuffle
    acc_d  <- compact(unique(sort(acc_d ++ recv)))        # owner merge

Like the single-chip engine, two accumulator representations are
switched automatically mid-stream: **packed** (one int32
``term * stride + doc`` key) while the growing vocabulary still packs
(K.can_pack), and **pairs** (separate term/doc arrays, a three-key
bucket sort for the exchange and a two-key merge sort) once it
outgrows int32 — so the mesh path handles the same 10^6-doc corpora
single-chip streaming does.

The window feed is combiner-deduped per document by the tokenizer, but
cross-window duplicates (the numpy fallback tokenizer emits them) fold
into the accumulator exactly like the reference reducer's dedup
(main.c:176-184).

Unlike the single-chip engine's host-side bound (unique <= fed), a
per-owner bound cannot be derived host-side without assuming hash
uniformity, so each feed returns the replicated max per-owner count
(one scalar fetch per window — amortized over 10^5-doc windows) and an
overflowing merge is *retried* against the preserved previous
accumulator at a doubled capacity: no data loss, no uniformity
assumption.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

from ..ops import keys as K
from ..ops.segment import bucket_edges, compact, first_occurrence_mask
from ..utils.rounding import round_up
from .dist_engine import _bucket_exchange, _build_prefix_slice, default_capacity
from .mesh import SHARD_AXIS, replicated_spec, shard_spec, sharding
from .compat import shard_map


def _pair_bucket_exchange(term, doc, *, num_shards: int, capacity: int):
    """Pair-mode exchange: bucket (term, doc) rows by ``term % n`` and
    run one ``all_to_all`` carrying both halves side by side
    (``[terms | docs]`` per destination row)."""
    local = term.shape[0]
    valid = term < K.INT32_MAX
    bucket = jnp.where(valid, term % num_shards, num_shards)
    b_s, t_s, d_s = lax.sort(
        (bucket.astype(jnp.int32), term, doc), num_keys=3)
    counts, offsets = bucket_edges(b_s, num_shards)
    overflow_local = (counts > capacity).any()
    slot = jnp.arange(capacity, dtype=jnp.int32)[None, :]
    gather_idx = jnp.clip(offsets[:, None] + slot, 0, local - 1)
    in_bucket = slot < counts[:, None]
    send = jnp.concatenate([
        jnp.where(in_bucket, t_s[gather_idx], K.INT32_MAX),
        jnp.where(in_bucket, d_s[gather_idx], K.INT32_MAX),
    ], axis=1)  # (num_shards, 2 * capacity)
    recv = lax.all_to_all(send, SHARD_AXIS, 0, 0, tiled=True)
    recv = recv.reshape(num_shards, 2, capacity)
    return recv[:, 0, :].reshape(-1), recv[:, 1, :].reshape(-1), overflow_local


def _merge_body(acc_local, window_local, *, num_shards: int, cap: int,
                exchange_capacity: int, stride: int):
    recv, overflow_ex = _bucket_exchange(
        window_local, K.INT32_MAX, num_shards=num_shards,
        capacity=exchange_capacity, stride=stride)
    s = lax.sort(jnp.concatenate([acc_local, recv.reshape(-1)]))
    first = first_occurrence_mask(s) & (s < K.INT32_MAX)
    count = first.sum(dtype=jnp.int32)
    return {
        "acc": compact(s, first, cap, K.INT32_MAX),
        "max_count": lax.pmax(count, SHARD_AXIS),
        "exchange_overflow": lax.psum(
            overflow_ex.astype(jnp.int32), SHARD_AXIS),
    }


def _merge_body_pairs(acc_t, acc_d, win_t, win_d, *, num_shards: int,
                      cap: int, exchange_capacity: int):
    recv_t, recv_d, overflow_ex = _pair_bucket_exchange(
        win_t, win_d, num_shards=num_shards, capacity=exchange_capacity)
    t = jnp.concatenate([acc_t, recv_t])
    d = jnp.concatenate([acc_d, recv_d])
    t_s, d_s = lax.sort((t, d), num_keys=2)
    first = (first_occurrence_mask(t_s) | first_occurrence_mask(d_s)) & (
        t_s < K.INT32_MAX)
    count = first.sum(dtype=jnp.int32)
    return {
        "acc_t": compact(t_s, first, cap, K.INT32_MAX),
        "acc_d": compact(d_s, first, cap, K.INT32_MAX),
        "max_count": lax.pmax(count, SHARD_AXIS),
        "exchange_overflow": lax.psum(
            overflow_ex.astype(jnp.int32), SHARD_AXIS),
    }


@functools.lru_cache(maxsize=64)
def _build_merge(mesh: Mesh, window_local: int, num_shards: int, cap: int,
                 exchange_capacity: int, stride: int):
    def body(acc_local, window_local_arr):
        return _merge_body(
            acc_local, window_local_arr, num_shards=num_shards, cap=cap,
            exchange_capacity=exchange_capacity, stride=stride)

    # no donation: an overflowing merge is retried against the same
    # accumulator and window at a larger capacity
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(shard_spec(), shard_spec()),
        out_specs={"acc": shard_spec(),
                   "max_count": replicated_spec(),
                   "exchange_overflow": replicated_spec()},
        check_vma=False,
    ))


@functools.lru_cache(maxsize=64)
def _build_merge_pairs(mesh: Mesh, window_local: int, num_shards: int,
                       cap: int, exchange_capacity: int):
    def body(acc_t, acc_d, win_t, win_d):
        return _merge_body_pairs(
            acc_t, acc_d, win_t, win_d, num_shards=num_shards, cap=cap,
            exchange_capacity=exchange_capacity)

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(shard_spec(),) * 4,
        out_specs={"acc_t": shard_spec(), "acc_d": shard_spec(),
                   "max_count": replicated_spec(),
                   "exchange_overflow": replicated_spec()},
        check_vma=False,
    ))


@functools.lru_cache(maxsize=64)
def _build_regrow(mesh: Mesh, old_cap: int, new_cap: int):
    def body(acc_local):
        out = jnp.full((new_cap,), K.INT32_MAX, jnp.int32)
        return lax.dynamic_update_slice(out, acc_local, (0,))

    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=shard_spec(), out_specs=shard_spec(),
        check_vma=False))


@functools.lru_cache(maxsize=64)
def _build_unpack(mesh: Mesh, cap: int, stride: int):
    """Packed sharded accumulator -> (term, doc) pair accumulators."""
    def body(acc_local):
        valid = acc_local < K.INT32_MAX
        term = jnp.where(valid, acc_local // stride, K.INT32_MAX)
        doc = jnp.where(valid, acc_local % stride, K.INT32_MAX)
        return term, doc

    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=shard_spec(),
        out_specs=(shard_spec(), shard_spec()), check_vma=False))


class DistStreamingIndexEngine:
    """Hash-sharded bounded accumulator over a provisional-id pair stream.

    One per-owner sorted-unique buffer per chip; each :meth:`feed`
    shuffles a window over ICI and folds it in.  ``initial_capacity``
    is *per owner*.  Starts in packed mode and switches permanently to
    pair mode the first time ``vocab_size_so_far`` stops packing into
    int32 keys (exactly like ops/streaming.StreamingIndexEngine).
    """

    def __init__(self, *, max_doc_id: int, mesh: Mesh,
                 window_pad: int = 1 << 16,
                 initial_capacity: int = 1 << 16):
        self._stride = max_doc_id + 2
        self._max_doc_id = max_doc_id
        self._mesh = mesh
        self._n = mesh.devices.size
        self._window_pad = window_pad
        self._cap = initial_capacity
        self._acc = None        # packed mode
        self._acc_pair = None   # pair mode: (terms, docs)
        self._count = 0         # last observed max per-owner count
        self.windows_fed = 0
        self.merge_retries = 0

    @property
    def capacity(self) -> int:
        """Per-owner accumulator capacity (total device memory is
        ``capacity * mesh size`` int32s per buffer)."""
        return self._cap

    @property
    def mode(self) -> str:
        return "pairs" if self._acc_pair is not None else "packed"

    def _empty(self, cap: int):
        return jax.device_put(
            np.full(self._n * cap, K.INT32_MAX, np.int32),
            sharding(self._mesh, shard_spec()))

    def _switch_to_pairs(self) -> None:
        if self._acc is None:
            self._acc_pair = (self._empty(self._cap), self._empty(self._cap))
        else:
            self._acc_pair = _build_unpack(
                self._mesh, self._cap, self._stride)(self._acc)
            self._acc = None

    def _upload(self, host: np.ndarray):
        return jax.device_put(host, sharding(self._mesh, shard_spec()))

    def feed(self, prov_term_ids: np.ndarray, doc_ids: np.ndarray,
             vocab_size_so_far: int) -> None:
        """Shuffle + fold one window of (provisional term, doc) pairs."""
        n_pairs = int(prov_term_ids.shape[0])
        if n_pairs == 0:
            return
        if self.mode == "packed" and not K.can_pack(vocab_size_so_far,
                                                    self._max_doc_id):
            self._switch_to_pairs()
        padded = round_up(n_pairs, max(self._window_pad, self._n))
        padded = round_up(padded, self._n)
        window_local = padded // self._n
        exchange_cap = default_capacity(window_local, self._n)

        if self.mode == "packed":
            if self._acc is None:
                self._acc = self._empty(self._cap)
            host = np.full(padded, K.INT32_MAX, np.int32)
            np.multiply(prov_term_ids, self._stride, out=host[:n_pairs])
            host[:n_pairs] += doc_ids
            window = (self._upload(host),)
        else:
            ht = np.full(padded, K.INT32_MAX, np.int32)
            hd = np.full(padded, K.INT32_MAX, np.int32)
            ht[:n_pairs] = prov_term_ids
            hd[:n_pairs] = doc_ids
            window = (self._upload(ht), self._upload(hd))

        while True:
            if self.mode == "packed":
                out = _build_merge(
                    self._mesh, window_local, self._n, self._cap,
                    exchange_cap, self._stride)(self._acc, *window)
            else:
                out = _build_merge_pairs(
                    self._mesh, window_local, self._n, self._cap,
                    exchange_cap)(*self._acc_pair, *window)
            max_count = int(out["max_count"])  # one scalar sync per window
            if int(out["exchange_overflow"]) > 0:
                exchange_cap = window_local  # provably safe
                self.merge_retries += 1
                continue
            if max_count > self._cap:
                # grow and retry against the preserved accumulator
                while self._cap < max_count:
                    self._cap *= 2
                self.merge_retries += 1
                self._regrow_acc()
                continue
            break
        if self.mode == "packed":
            self._acc = out["acc"]
        else:
            self._acc_pair = (out["acc_t"], out["acc_d"])
        self._count = max_count
        # grow ahead of the next window once 3/4 full (amortized)
        if self._count * 4 > self._cap * 3:
            self._cap *= 2
            self._regrow_acc()
        self.windows_fed += 1

    def _regrow_acc(self) -> None:
        """Pad the live accumulator buffers up to the current capacity."""
        if self._acc is not None:
            old = self._acc.shape[0] // self._n
            if old < self._cap:
                self._acc = _build_regrow(self._mesh, old, self._cap)(self._acc)
        if self._acc_pair is not None:
            old = self._acc_pair[0].shape[0] // self._n
            if old < self._cap:
                grow = _build_regrow(self._mesh, old, self._cap)
                self._acc_pair = (grow(self._acc_pair[0]),
                                  grow(self._acc_pair[1]))

    def finalize(self, stats: dict | None = None):
        """``(mode, {owner: rows})`` for every addressable owner, valid
        prefix only — the capacity tail never crosses the D2H link,
        mirroring dist_engine's multi-host fetch contract.  Packed
        mode: rows are sorted packed keys.  Pair mode: rows are
        ``(terms, docs)`` tuples sorted by (term, doc)."""
        mode = self.mode
        if self._acc is None and self._acc_pair is None:
            return mode, {}
        nfetch = min(self._cap, round_up(max(self._count, 1), 1 << 13))
        slicer = _build_prefix_slice(self._mesh, self._cap, nfetch)

        def fetch_rows(arr):
            rows, fetched = {}, 0
            for s in slicer(arr).addressable_shards:
                owner = (s.index[0].start or 0) // nfetch
                row = np.asarray(s.data)
                rows[owner] = row
                fetched += row.nbytes
            return rows, fetched

        if mode == "packed":
            rows, fetched = fetch_rows(self._acc)
            rows = {o: r[r < K.INT32_MAX] for o, r in rows.items()}
        else:
            rows_t, f1 = fetch_rows(self._acc_pair[0])
            rows_d, f2 = fetch_rows(self._acc_pair[1])
            fetched = f1 + f2
            rows = {}
            for o, t in rows_t.items():
                valid = t < K.INT32_MAX
                rows[o] = (t[valid], rows_d[o][valid])
        if stats is not None:
            stats["dist_fetched_bytes"] = fetched
        self._acc = self._acc_pair = None
        return mode, rows
