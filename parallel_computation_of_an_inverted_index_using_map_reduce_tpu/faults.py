"""Deterministic fault injection + the pipeline's resilience policy.

The reference dies on any I/O hiccup (a failed ``fopen`` merely warns,
main.c:97-100, but nothing retries, nothing reports, and a crash loses
the whole run).  This module makes failure handling a *tested
subsystem*: every failure mode the pipeline claims to survive can be
armed deterministically and proven in a test, the same way DrJAX
(arXiv:2403.07128) treats MapReduce structure as an explicit primitive
rather than emergent behavior.

Three layers live here:

``FaultInjector``
    Seedable, deterministic injection hooks.  Armed via
    :func:`install` (the CLI's ``--fault-spec``) or the ``MRI_FAULTS``
    env var (so subprocess e2e tests can arm a child they then
    SIGKILL).  Spec grammar — clauses joined by ``;``, fields by ``:``::

        read-error:doc=2:times=2     transient OSError, first 2 attempts
        read-error:all:times=-1      permanent OSError on every doc
        read-error:every=3:times=1   every 3rd manifest index
        read-error:all:p=0.5:times=1 probabilistic (seed=N clause)
        slow-read:doc=1:ms=50        sleep before the read
        truncate:doc=4:bytes=10      document bytes cut short
        reader-death:window=1        silent reader-thread death
        sigkill:window=2             SIGKILL at stream window boundary
        stream-crash:window=2        RuntimeError from the stream engine
        ckpt-corrupt:save=1          corrupt checkpoint bytes post-save
        worker-death:worker=1:window=2  scan worker dies at window 2
        worker-death:window=2        ... whichever worker scans window 2
        reducer-death:reducer=0      reduce worker 0 dies before emit
        scan-error:window=3          native scan failure on window 3
        scan-error:window=3:silent=1 window silently dropped (corruption)
        handler-crash:req=3          serve daemon: handler dies on req 3
        client-disconnect:req=2      serve daemon: peer gone at response 2
        slow-client:req=1:ms=200     serve daemon: response write stalls
        reload-corrupt               serve daemon: next hot reload fails
        dispatcher-hang:ms=500       serve daemon: dispatch loop wedges
                                     for ms on its next batch (the
                                     watchdog-stall proof)
        append-torn-manifest         segments: staged manifest torn
                                     mid-publish (append aborts, old
                                     generation keeps serving)
        compact-crash                segments: crash after the merged
                                     segment is built, before publish
        tombstone-corrupt            segments: staged tombstone bitmap
                                     corrupted (write rejected)
        wal-torn-record              segments: WAL record torn before
                                     its fsync (mutation fails un-acked;
                                     recovery quarantines the tail)
        fetch-partial                replica: one fetch_segment payload
                                     truncated on the primary (the
                                     replica's checksum rejects + retries)
        lease-steal                  replica: the primary's lease is
                                     rewritten to a foreign owner once
                                     (next mutation rejects lease_lost)
        shard-dead:shard=1           cluster router: shard 1's next
                                     RPC send dies with a connection
                                     reset (omit shard= for any shard;
                                     the router must fail over)
        shard-slow:shard=2:ms=50     cluster router: shard 2's next
                                     send stalls ms before the write
                                     (the hedging trigger; replica=K
                                     pins either cluster kind to one
                                     replica of the shard)
        router-conn-reset:req=3      cluster router: the client
                                     connection carrying data request
                                     3 is dropped before its answer
        chaos:seed=5:n=3             sample 3 faults from a seeded RNG
        seed=7                       RNG seed for ``p=`` rules

    ``doc`` / ``every`` match the 0-based manifest index; ``window``
    and ``save`` are 1-based ordinals (matching ``win_i`` in the
    stream loop and "the Nth save"); ``worker`` / ``reducer`` are the
    0-based thread ordinals of the parallel host path; ``req`` is the
    1-based global data-request ordinal of the serve daemon.  Clauses
    join with ``;`` into multi-fault schedules.  The death/scan/serve
    kinds default to ``times=1`` and their firing state is GLOBAL, so
    a window requeued after a worker death does not re-kill the
    survivor that rescans it — recovery converges.

    ``chaos:seed=S:n=K`` expands at parse time into K concrete rules
    sampled deterministically from ``seed`` — the soak harness's
    randomized-but-reproducible fault schedules.  Optional bounds:
    ``windows=`` / ``workers=`` / ``reducers=`` / ``docs=`` /
    ``reqs=`` cap the sampled ordinals, and ``kinds=a,b,c`` restricts
    the kinds drawn (default: every recoverable build-side kind; the
    serve kinds are samplable only when named explicitly, so build
    soaks stay build-shaped).

``RetryPolicy``
    Bounded retries with exponential backoff and a per-document
    deadline — replaces the single-shot warn-and-skip on the read
    paths (io/reader.py, corpus/manifest.iter_document_ranges).

``DegradationReport``
    The structured outcome of a run's failure handling: retry counts
    and exactly which doc ids were skipped, with reasons.  The model
    attaches it to run stats; the CLI turns a non-empty skip list into
    the documented degraded exit code (:data:`EXIT_DEGRADED`).
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import os
import random
import signal
import threading
import time

from .obs import metrics as obs_metrics
from .utils import envknobs

log = logging.getLogger("mri_tpu.faults")

ENV_VAR = "MRI_FAULTS"

#: CLI exit code for a run that completed but skipped documents after
#: exhausting its retry budget (0 = clean, 2 = error, 3 = degraded).
EXIT_DEGRADED = 3


class FaultSpecError(ValueError):
    """Malformed ``--fault-spec`` / ``MRI_FAULTS`` string."""


class InjectedReadError(OSError):
    """The injected transient/permanent read failure (an OSError, so
    the production retry/skip machinery handles it like a real one)."""


class ReaderThreadDeath(BaseException):
    """Injected *silent* reader-thread death.

    Deliberately a BaseException: the executor's reader loop catches it
    specially and exits without posting anything to the consumer — the
    fire-and-forget daemon-thread failure mode the consumer-side
    watchdog exists to detect.
    """


class WorkerDeath(RuntimeError):
    """Injected scan-worker death (``worker-death`` rule): escapes the
    worker's scan loop like any real crash would, exercising the lease
    requeue + respawn recovery in models/inverted_index."""


class ScanError(RuntimeError):
    """Injected native-scan failure on one window (``scan-error``
    rule) — the recoverable form; ``silent=1`` drops the window
    without raising instead, the corruption the audit ledger exists
    to catch."""


class HandlerCrash(RuntimeError):
    """Injected serve-daemon handler failure (``handler-crash`` rule):
    escapes one request's handling like any real bug would; the daemon
    must answer that request with a counted well-formed ``internal``
    error and keep serving every other connection."""


class InjectedReloadCorrupt(RuntimeError):
    """Injected hot-reload verification failure (``reload-corrupt``
    rule).  Raised from the reload hook as if the replacement
    ``index.mri`` failed its checksum: the daemon must keep serving
    the old artifact and count ``reload_rejected`` instead of dying.
    (A plain RuntimeError, not an ArtifactError subclass — faults.py
    sits below serve/ in the import graph.)"""


class InjectedPublishTear(RuntimeError):
    """Injected segment-manifest publish tear (``append-torn-manifest``
    rule): the STAGED manifest was truncated and the rename must never
    happen.  ``segments.manifest.save_manifest`` maps it to a
    SegmentError so the mutation aborts and the previous generation
    keeps serving.  (Plain RuntimeError — faults.py sits below
    segments/ in the import graph.)"""


class InjectedCompactCrash(RuntimeError):
    """Injected mid-compaction crash (``compact-crash`` rule): fires
    after the replacement segment is fully built but before the
    generation swap, leaving the old generation serving plus an orphan
    directory no manifest references — what a real crash leaves."""


class InjectedConnReset(ConnectionError):
    """Injected cluster connection loss (``shard-dead`` /
    ``router-conn-reset`` rules).  A ConnectionError on purpose: the
    router's replica pool handles it through the same OSError path a
    real RST takes, so failover is proven against production code,
    not a parallel test-only branch."""


class InjectedWalTorn(RuntimeError):
    """Injected WAL append tear (``wal-torn-record`` rule): the record
    bytes were truncated mid-payload and the fsync never ran, so the
    mutation fails *un-acked* — exactly the torn tail
    ``segments.wal.read_records`` must quarantine on the next read.
    ``segments.wal`` maps it to a ``WalError`` so callers see the
    usual SegmentError surface.  (Plain RuntimeError — faults.py sits
    below segments/ in the import graph.)"""


# -- injector ---------------------------------------------------------

_READ_KINDS = ("read-error", "slow-read", "truncate")
_DEATH_KINDS = ("reader-death", "sigkill", "stream-crash", "ckpt-corrupt",
                "worker-death", "reducer-death", "scan-error",
                "spill-corrupt", "merge-crash", "chaos")
_SERVE_KINDS = ("client-disconnect", "slow-client", "reload-corrupt",
                "handler-crash", "dispatcher-hang")
_SEGMENT_KINDS = ("append-torn-manifest", "compact-crash",
                  "tombstone-corrupt")
_WAL_KINDS = ("wal-torn-record", "fetch-partial", "lease-steal")
_CLUSTER_KINDS = ("shard-dead", "shard-slow", "router-conn-reset",
                  "shard-blackout", "overload-storm")

#: What ``chaos:`` may sample by default — every kind the parallel host
#: path recovers from in-run (sigkill is excluded: its story is the
#: cross-run ``--resume=auto`` path, not in-run re-execution).
CHAOS_KINDS = ("worker-death", "reducer-death", "scan-error",
               "reader-death", "read-error", "slow-read")

#: What ``chaos:kinds=...`` may additionally name for daemon soaks —
#: every serve-side fault the daemon absorbs without dying or sending
#: a torn response.  Not in the default draw: a build soak armed via
#: the same grammar should not sample request-ordinal rules that can
#: never fire.
SERVE_CHAOS_KINDS = ("client-disconnect", "slow-client", "handler-crash",
                     "reload-corrupt")

#: What ``chaos:kinds=...`` may name for segment soaks — the mutation
#: crash points the generation-swap discipline absorbs (old generation
#: keeps serving in every case).  Named-only for the same reason as the
#: serve kinds: a build soak should never sample them.
SEGMENT_CHAOS_KINDS = _SEGMENT_KINDS

#: What ``chaos:kinds=...`` may name for spill-armed build soaks —
#: the out-of-core tier's fault points (torn run file, dead shard
#: merger).  Named-only: they can only fire when
#: ``MRI_BUILD_SPILL_BYTES`` routes the build through the spill tier.
SPILL_CHAOS_KINDS = ("spill-corrupt", "merge-crash")

#: What ``chaos:kinds=...`` may name for durability/replication soaks
#: — the WAL tear, the partial segment ship, and the lease steal.
#: Named-only like the other serve-side families.
WAL_CHAOS_KINDS = _WAL_KINDS

#: What ``chaos:kinds=...`` may name for cluster soaks — the router's
#: fault points (a shard replica's connection dying or stalling, a
#: router client connection reset, every replica of one shard going
#: dark, a shard daemon shedding a synthetic overload storm).
#: Named-only: they fire inside router/shard processes.
CLUSTER_CHAOS_KINDS = _CLUSTER_KINDS


@dataclasses.dataclass
class _Rule:
    kind: str
    doc: int | None = None      # manifest index; None = all (read kinds)
    every: int | None = None
    p: float | None = None
    times: int = 1              # -1 = permanent (read-error)
    ms: float = 0.0             # slow-read
    bytes: int = 0              # truncate
    window: int = 0             # reader-death / sigkill / stream-crash /
                                # worker-death / scan-error (0 = any)
    save: int = 0               # ckpt-corrupt
    spill: int = 0              # spill-corrupt: 1-based run-file ordinal
    shard: int | None = None    # merge-crash (None = any shard)
    replica: int | None = None  # cluster kinds (None = any replica)
    worker: int | None = None   # worker-death (None = any worker)
    reducer: int | None = None  # reducer-death (None = any reducer)
    silent: int = 0             # scan-error: 1 = drop window, no raise
    req: int = 0                # serve kinds: 1-based data-request
                                # ordinal (0 never matches — admin ops)
    # chaos sampler bounds (chaos:seed=S:n=K clause only)
    seed: int = 0
    n: int = 0
    windows: int = 8
    workers: int = 4
    reducers: int = 4
    docs: int = 16
    reqs: int = 32
    kinds: tuple = CHAOS_KINDS


def _parse_int(kind: str, key: str, value: str) -> int:
    try:
        return int(value)
    except ValueError:
        raise FaultSpecError(
            f"{kind}: {key}={value!r} is not an integer") from None


def _parse_clause(clause: str, kv_global: dict) -> _Rule | None:
    parts = [p for p in clause.strip().split(":") if p]
    if not parts:
        return None
    head = parts[0]
    if "=" in head:  # bare global assignment, e.g. seed=7
        k, v = head.split("=", 1)
        if k != "seed":
            raise FaultSpecError(f"unknown global fault key {k!r}")
        kv_global["seed"] = _parse_int("seed", "seed", v)
        if len(parts) > 1:
            raise FaultSpecError("seed=N must be a clause of its own")
        return None
    rule = _Rule(kind=head)
    if head not in (_READ_KINDS + _DEATH_KINDS + _SERVE_KINDS
                    + _SEGMENT_KINDS + _WAL_KINDS + _CLUSTER_KINDS):
        raise FaultSpecError(f"unknown fault kind {head!r}")
    saw_times = False
    for field in parts[1:]:
        if field == "all":
            rule.doc = None
            continue
        if "=" not in field:
            raise FaultSpecError(
                f"{head}: expected key=value, got {field!r}")
        k, v = field.split("=", 1)
        if k == "doc":
            rule.doc = _parse_int(head, k, v)
        elif k == "every":
            rule.every = _parse_int(head, k, v)
        elif k == "times":
            rule.times = _parse_int(head, k, v)
            saw_times = True
        elif k == "p":
            try:
                rule.p = float(v)
            except ValueError:
                raise FaultSpecError(
                    f"{head}: p={v!r} is not a float") from None
        elif k == "ms":
            rule.ms = float(_parse_int(head, k, v))
        elif k == "bytes":
            rule.bytes = _parse_int(head, k, v)
        elif k == "window":
            rule.window = _parse_int(head, k, v)
        elif k == "save":
            rule.save = _parse_int(head, k, v)
        elif k == "spill":
            rule.spill = _parse_int(head, k, v)
        elif k == "shard":
            rule.shard = _parse_int(head, k, v)
        elif k == "replica":
            rule.replica = _parse_int(head, k, v)
        elif k == "worker":
            rule.worker = _parse_int(head, k, v)
        elif k == "reducer":
            rule.reducer = _parse_int(head, k, v)
        elif k == "silent":
            rule.silent = _parse_int(head, k, v)
        elif k == "req":
            rule.req = _parse_int(head, k, v)
        elif k == "reqs" and head == "chaos":
            rule.reqs = _parse_int(head, k, v)
        elif k == "seed" and head == "chaos":
            rule.seed = _parse_int(head, k, v)
        elif k == "n" and head == "chaos":
            rule.n = _parse_int(head, k, v)
        elif k == "windows" and head == "chaos":
            rule.windows = _parse_int(head, k, v)
        elif k == "workers" and head == "chaos":
            rule.workers = _parse_int(head, k, v)
        elif k == "reducers" and head == "chaos":
            rule.reducers = _parse_int(head, k, v)
        elif k == "docs" and head == "chaos":
            rule.docs = _parse_int(head, k, v)
        elif k == "kinds" and head == "chaos":
            kinds = tuple(s for s in v.split(",") if s)
            bad = [s for s in kinds
                   if s not in (CHAOS_KINDS + SERVE_CHAOS_KINDS
                                + SEGMENT_CHAOS_KINDS + SPILL_CHAOS_KINDS
                                + WAL_CHAOS_KINDS + CLUSTER_CHAOS_KINDS)]
            if bad:
                raise FaultSpecError(
                    f"chaos: kinds not samplable: {bad} "
                    f"(choose from "
                    f"{list(CHAOS_KINDS + SERVE_CHAOS_KINDS + SEGMENT_CHAOS_KINDS + SPILL_CHAOS_KINDS + WAL_CHAOS_KINDS + CLUSTER_CHAOS_KINDS)})")
            rule.kinds = kinds
        else:
            raise FaultSpecError(f"{head}: unknown key {k!r}")
    if rule.kind in ("reader-death", "sigkill", "stream-crash") \
            and rule.window < 1:
        raise FaultSpecError(f"{head} needs window=N (1-based)")
    if rule.kind == "ckpt-corrupt" and rule.save < 1:
        raise FaultSpecError("ckpt-corrupt needs save=N (1-based)")
    if rule.kind == "spill-corrupt" and rule.spill < 1:
        raise FaultSpecError("spill-corrupt needs spill=N (1-based)")
    if rule.kind == "scan-error" and rule.window < 1:
        raise FaultSpecError("scan-error needs window=N (1-based)")
    if rule.kind in ("client-disconnect", "slow-client", "handler-crash") \
            and rule.req < 1:
        raise FaultSpecError(f"{head} needs req=N (1-based)")
    if rule.kind == "slow-client" and rule.ms <= 0:
        rule.ms = 50.0
    if rule.kind == "shard-slow" and rule.ms <= 0:
        rule.ms = 20.0
    if rule.kind == "router-conn-reset" and rule.req < 1:
        raise FaultSpecError("router-conn-reset needs req=N (1-based)")
    if rule.kind == "shard-blackout" and not saw_times:
        # a blackout is an outage, not a blip: every send to the shard
        # dies until the rule is disarmed (override with times=N)
        rule.times = -1
    if rule.kind == "overload-storm":
        if rule.req < 1:
            rule.req = 1  # storm from the first data request
        if not saw_times:
            rule.times = 16  # a burst, not a single shed
    if rule.kind == "dispatcher-hang" and rule.ms <= 0:
        rule.ms = 500.0
    if rule.kind == "chaos":
        if rule.n < 1:
            raise FaultSpecError("chaos needs n=K (faults to sample)")
        if min(rule.windows, rule.workers, rule.reducers, rule.docs,
               rule.reqs) < 1 or not rule.kinds:
            raise FaultSpecError("chaos bounds must be >= 1")
    return rule


def _sample_chaos(rule: _Rule) -> list[_Rule]:
    """Expand one ``chaos:seed=S:n=K`` clause into K concrete rules.

    Deterministic in ``seed`` (the soak harness's repro contract).
    Every sampled rule keeps the default ``times=1`` budget, so a
    schedule is a finite set of one-shot faults — recovery always has
    a fixed point to converge to.  Permanent read-errors (the degraded
    exit-3 arm) are sampled with times=-1 occasionally.
    """
    rng = random.Random(rule.seed)
    out: list[_Rule] = []
    for _ in range(rule.n):
        kind = rng.choice(rule.kinds)
        if kind == "worker-death":
            # mostly any-worker (fires for whoever scans the window, so
            # the fault is guaranteed to land); occasionally pinned
            worker = rng.randrange(rule.workers) if rng.random() < 0.25 \
                else None
            out.append(_Rule(kind=kind, worker=worker,
                             window=rng.randint(1, rule.windows)))
        elif kind == "reducer-death":
            out.append(_Rule(kind=kind,
                             reducer=rng.randrange(rule.reducers)))
        elif kind == "scan-error":
            out.append(_Rule(kind=kind,
                             window=rng.randint(1, rule.windows)))
        elif kind == "reader-death":
            out.append(_Rule(kind=kind,
                             window=rng.randint(1, rule.windows)))
        elif kind == "read-error":
            out.append(_Rule(kind=kind, doc=rng.randrange(rule.docs),
                             times=rng.choice((1, 2, 2, -1))))
        elif kind == "slow-read":
            out.append(_Rule(kind="slow-read",
                             doc=rng.randrange(rule.docs),
                             ms=float(rng.choice((2, 5, 10)))))
        elif kind in ("client-disconnect", "handler-crash"):
            out.append(_Rule(kind=kind, req=rng.randint(1, rule.reqs)))
        elif kind == "slow-client":
            out.append(_Rule(kind=kind, req=rng.randint(1, rule.reqs),
                             ms=float(rng.choice((20, 50, 100)))))
        elif kind == "spill-corrupt":
            # early run ordinals: tiny-budget soaks write a handful of
            # runs per worker, so the Nth file must exist to be torn
            out.append(_Rule(kind=kind, spill=rng.randint(1, 3)))
        elif kind == "merge-crash":
            # any-shard: fires on whichever merger reaches it first,
            # so the takeover is guaranteed to be exercised
            out.append(_Rule(kind=kind))
        elif kind == "shard-dead":
            # any-shard: fires on whichever scatter send reaches it
            # first, so the failover is guaranteed to be exercised
            out.append(_Rule(kind=kind))
        elif kind == "shard-slow":
            out.append(_Rule(kind=kind,
                             ms=float(rng.choice((20, 50, 100)))))
        elif kind == "router-conn-reset":
            out.append(_Rule(kind=kind, req=rng.randint(1, rule.reqs)))
        elif kind == "shard-blackout":
            # pinned to one shard (soaks run small D): every replica
            # of that shard refuses until the soak's recovery phase
            out.append(_Rule(kind=kind, shard=rng.randrange(2),
                             times=-1))
        elif kind == "overload-storm":
            out.append(_Rule(kind=kind, req=rng.randint(1, rule.reqs),
                             times=rng.choice((8, 16, 32))))
        elif kind in _SEGMENT_KINDS + _WAL_KINDS:
            # no ordinal to pick: each fires once, on the next matching
            # segment mutation / fetch / lease check (times=1 default)
            out.append(_Rule(kind=kind))
        else:  # reload-corrupt
            out.append(_Rule(kind="reload-corrupt"))
    return out


class FaultInjector:
    """Parsed fault spec + per-rule firing state.  Thread-safe: the
    read hooks fire from reader threads concurrently with the main
    thread's checkpoint/window hooks."""

    def __init__(self, spec: str):
        self.spec = spec
        kv_global: dict = {}
        self.rules: list[_Rule] = []
        for clause in spec.split(";"):
            rule = _parse_clause(clause, kv_global)
            if rule is None:
                continue
            if rule.kind == "chaos":
                self.rules.extend(_sample_chaos(rule))
            else:
                self.rules.append(rule)
        if not self.rules and "seed" not in kv_global:
            raise FaultSpecError(f"empty fault spec {spec!r}")
        self._rng = random.Random(kv_global.get("seed", 0))
        self._lock = threading.Lock()
        self._fired: dict[tuple[int, int], int] = {}
        self._saves = 0
        self._spills = 0

    def _matches(self, rule: _Rule, index: int) -> bool:
        if rule.doc is not None and index != rule.doc:
            return False
        if rule.every is not None and index % rule.every != 0:
            return False
        if rule.p is not None and self._rng.random() >= rule.p:
            return False
        return True

    # -- hooks (each a no-op unless a matching rule is armed) ---------

    def on_read(self, index: int, path: str) -> int | None:
        """Per-attempt read hook.  May raise :class:`InjectedReadError`
        or sleep; returns a byte cap to truncate the document to, or
        None.  ``times=N`` counts *per document*, so a retrying caller
        sees N failures then success — the transient-fault contract."""
        cap = None
        delay = 0.0
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind not in _READ_KINDS \
                        or not self._matches(rule, index):
                    continue
                if rule.kind == "slow-read":
                    delay = max(delay, rule.ms / 1e3)
                elif rule.kind == "truncate":
                    cap = rule.bytes if cap is None \
                        else min(cap, rule.bytes)
                else:  # read-error
                    key = (ri, index)
                    n = self._fired.get(key, 0)
                    if rule.times < 0 or n < rule.times:
                        self._fired[key] = n + 1
                        raise InjectedReadError(
                            errno.EIO, "injected read failure "
                            f"(attempt {n + 1})", path)
        if delay:
            time.sleep(delay)
        return cap

    def on_reader_window(self, window: int) -> None:
        """Fires in the executor's reader thread before window
        ``window`` (1-based) is read; may raise ReaderThreadDeath.
        The firing budget is GLOBAL (``times=1`` by default) like the
        other death kinds: when the parallel host path requeues the
        dead reader's windows, the survivor that re-reads this window
        must not die of the same injection — recovery converges."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind == "reader-death" and rule.window == window:
                    if self._fire_once(ri, rule):
                        raise ReaderThreadDeath()

    def on_window_boundary(self, window: int) -> None:
        """Fires after window ``window`` completes — on the stream
        loop's main thread (post-checkpoint) and, for the pipelined cpu
        path, in each reader thread after the window is read and handed
        downstream (the window index is the GLOBAL plan index, so specs
        are worker-count-invariant); may SIGKILL."""
        for rule in self.rules:
            if rule.kind == "sigkill" and rule.window == window:
                log.warning("fault injection: SIGKILL at stream "
                            "window boundary %d", window)
                os.kill(os.getpid(), signal.SIGKILL)

    def on_stream_window(self, window: int) -> None:
        """Fires inside the device stream engine after it folds window
        ``window``; may raise (the round-3 TPU worker crash, as a
        first-class fault instead of an ad-hoc env hook)."""
        for rule in self.rules:
            if rule.kind == "stream-crash" and rule.window == window:
                raise RuntimeError(
                    f"injected stream crash after window {window} "
                    "(fault spec)")

    def _fire_once(self, ri: int, rule: _Rule) -> bool:
        """Global once-per-rule firing budget (``times``), shared across
        workers: a requeued window rescanned by a survivor must NOT
        re-trigger the fault that killed the first worker, or recovery
        could never converge.  Caller holds ``self._lock``."""
        key = (ri, 0)
        n = self._fired.get(key, 0)
        if rule.times < 0 or n < rule.times:
            self._fired[key] = n + 1
            # fault firings are process-global obs counters (the obs
            # Counter has its own lock; safe under self._lock)
            reg = obs_metrics.default_registry()
            reg.counter("mri_faults_fired_total").inc()
            kind = rule.kind.replace("-", "_")
            reg.counter(f"mri_fault_{kind}_fired_total").inc()
            return True
        return False

    def on_worker_window(self, worker: int, window: int) -> None:
        """Fires in scan worker ``worker`` (0-based) as it picks up
        window ``window`` (1-based global plan index); may raise
        :class:`WorkerDeath` — the in-run worker-crash injection the
        lease/requeue recovery is proven against."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "worker-death":
                    continue
                if rule.window and rule.window != window:
                    continue
                if rule.worker is not None and rule.worker != worker:
                    continue
                if self._fire_once(ri, rule):
                    raise WorkerDeath(
                        f"injected worker death: worker {worker} at "
                        f"window {window} (fault spec)")

    def on_scan_window(self, window: int) -> bool:
        """Fires in the scan worker before window ``window`` is fed to
        the native scan.  May raise :class:`ScanError` (recoverable —
        the worker dies and the window is rescanned), or return True
        for ``silent=1`` rules: the caller drops the window without
        any error, the silent corruption ``--audit`` must catch."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "scan-error" or rule.window != window:
                    continue
                if not self._fire_once(ri, rule):
                    continue
                if rule.silent:
                    log.warning("fault injection: silently dropping "
                                "window %d from the scan", window)
                    return True
                raise ScanError(
                    f"injected native scan failure on window {window} "
                    "(fault spec)")
        return False

    def on_reducer(self, reducer: int) -> None:
        """Fires in reduce worker ``reducer`` (0-based) before it emits
        its letter range; may raise — the dead reducer whose range a
        surviving thread re-emits (takeover off the read-only merge)."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "reducer-death":
                    continue
                if rule.reducer is not None and rule.reducer != reducer:
                    continue
                if self._fire_once(ri, rule):
                    raise RuntimeError(
                        f"injected reducer death: reducer {reducer} "
                        "(fault spec)")

    def on_checkpoint_saved(self, path: str) -> None:
        """Fires after every atomic checkpoint save; the Nth save may
        be corrupted in place (truncated to a third), simulating the
        torn/bit-rotted file ``--resume=auto`` must survive."""
        with self._lock:
            self._saves += 1
            saves = self._saves
        for rule in self.rules:
            if rule.kind == "ckpt-corrupt" and rule.save == saves:
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.truncate(max(size // 3, 1))
                log.warning("fault injection: corrupted checkpoint "
                            "%s (save #%d)", path, saves)

    def on_spill_written(self, path: str) -> None:
        """Fires after every atomic spill-run write (build/spill.py);
        the Nth run file process-wide may have a byte flipped in place,
        simulating the torn run the reduce-side checksum walk must
        quarantine instead of merging."""
        with self._lock:
            self._spills += 1
            spills = self._spills
            for ri, rule in enumerate(self.rules):
                if rule.kind != "spill-corrupt" or rule.spill != spills:
                    continue
                if self._fire_once(ri, rule):
                    with open(path, "r+b") as f:
                        data = f.read()
                        at = max(len(data) // 2 - 1, 0)
                        f.seek(at)
                        f.write(bytes([data[at] ^ 0xFF]))
                    log.warning("fault injection: corrupted spill run "
                                "%s (run file #%d)", path, spills)

    def on_shard_merge(self, shard: int) -> None:
        """Fires in a reduce worker before it k-way-merges term-hash
        shard ``shard`` (0-based); may raise — the dead shard merger
        whose shards the main thread re-merges (the runs on disk are
        read-only inputs, so re-merge is idempotent)."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "merge-crash":
                    continue
                if rule.shard is not None and rule.shard != shard:
                    continue
                if self._fire_once(ri, rule):
                    raise RuntimeError(
                        f"injected shard-merge crash: shard {shard} "
                        "(fault spec)")

    def on_serve_request(self, req: int) -> None:
        """Fires in the serve daemon as data request ``req`` (1-based
        global ordinal) is handled; may raise :class:`HandlerCrash`.
        Admin ops pass req=0, which never matches an armed rule.  The
        firing budget is GLOBAL like the other death kinds: the daemon
        answers the crashed request with a counted ``internal`` error
        and the next request through the same code path survives."""
        if req < 1:
            return
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "handler-crash" or rule.req != req:
                    continue
                if self._fire_once(ri, rule):
                    raise HandlerCrash(
                        f"injected handler crash on request {req} "
                        "(fault spec)")

    def on_serve_response(self, req: int) -> bool:
        """Fires in the serve daemon's writer just before response
        ``req`` is sent.  ``slow-client`` sleeps here (outside the
        injector lock — a stalled peer must not serialize the whole
        daemon); ``client-disconnect`` returns True and the caller
        drops the connection as if the peer vanished mid-response."""
        if req < 1:
            return False
        delay = 0.0
        drop = False
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.req != req:
                    continue
                if rule.kind == "slow-client":
                    if self._fire_once(ri, rule):
                        delay = max(delay, rule.ms / 1e3)
                elif rule.kind == "client-disconnect":
                    if self._fire_once(ri, rule):
                        drop = True
        if delay:
            time.sleep(delay)
        return drop

    def on_serve_admit(self, req: int) -> bool:
        """Fires in the serve daemon as data request ``req`` (1-based
        ordinal) is admitted, before it is queued.  True means the
        daemon must shed it with a typed ``overloaded`` answer
        (``overload-storm`` rule: fires for every request from ordinal
        ``req=N`` on while its ``times`` budget lasts) — a synthetic
        sustained overload the admission-control and router-breaker
        soaks lean on without having to genuinely saturate the box.
        An ``every=K`` clause sheds only every Kth request: an
        INTERMITTENT overload, where the replica stays mostly healthy
        so breakers correctly hold closed and the retry budget is the
        only thing standing between a flaky shard and retry
        amplification."""
        if req < 1:
            return False
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "overload-storm" or req < rule.req:
                    continue
                if rule.every is not None and req % rule.every != 0:
                    continue
                if self._fire_once(ri, rule):
                    return True
        return False

    def on_router_send(self, shard: int, replica: int) -> None:
        """Fires in the cluster router as an RPC is handed to the
        connection for ``(shard, replica)``.  ``shard-dead`` (matching
        ``shard=K`` or any-shard) raises :class:`InjectedConnReset`,
        which the replica pool handles exactly like a real RST —
        condemn the connection, fail its pending RPCs, let the router
        fail over.  ``shard-slow`` sleeps ``ms`` here, outside the
        injector lock, stalling only this shard's sends (the hedging
        trigger)."""
        delay = 0.0
        dead = False
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.shard is not None and rule.shard != shard:
                    continue
                if rule.replica is not None and rule.replica != replica:
                    continue
                if rule.kind == "shard-dead":
                    if self._fire_once(ri, rule):
                        dead = True
                elif rule.kind == "shard-blackout":
                    # permanent by default (times=-1): EVERY send to
                    # the matched shard dies, all replicas — the
                    # replica-set-exhausted path partial results and
                    # breakers exist for
                    if self._fire_once(ri, rule):
                        dead = True
                elif rule.kind == "shard-slow":
                    if self._fire_once(ri, rule):
                        delay = max(delay, rule.ms / 1e3)
        if delay:
            time.sleep(delay)
        if dead:
            raise InjectedConnReset(
                f"injected shard-dead: shard {shard} replica {replica} "
                "(fault spec)")

    def on_router_client(self, req: int) -> bool:
        """Fires in the router as data request ``req`` (1-based global
        ordinal) is admitted; True means the client connection must be
        dropped as if the peer's NAT sent an RST (``router-conn-reset``
        rule).  The chaos soak uses it to prove a torn client sees
        either no answer or one answer — never two."""
        if req < 1:
            return False
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "router-conn-reset" or rule.req != req:
                    continue
                if self._fire_once(ri, rule):
                    return True
        return False

    def on_dispatch_batch(self) -> None:
        """Fires in the serve daemon's dispatcher thread as it picks up
        a batch.  An armed ``dispatcher-hang`` rule sleeps ``ms`` here
        — outside the injector lock, mirroring ``slow-client`` — so
        the single dispatch thread wedges with requests queued behind
        it while admin ops keep answering from the reader threads:
        exactly the failure shape the watchdog exists to detect.
        One-shot by default (``times=1``), like the other serve kinds."""
        delay = 0.0
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "dispatcher-hang":
                    continue
                if self._fire_once(ri, rule):
                    delay = max(delay, rule.ms / 1e3)
        if delay:
            time.sleep(delay)

    def on_segment_publish(self, op: str, tmp_path: str) -> None:
        """Fires in ``segments.manifest.save_manifest`` after the new
        manifest generation is staged, before the rename.  The
        ``append-torn-manifest`` rule truncates the STAGED file and
        raises :class:`InjectedPublishTear`, so the swap never happens
        and the previous generation keeps serving — the crash-
        mid-publish the stage+rename discipline exists to survive."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "append-torn-manifest" or op != "append":
                    continue
                if self._fire_once(ri, rule):
                    size = os.path.getsize(tmp_path)
                    with open(tmp_path, "r+b") as f:
                        f.truncate(max(size // 2, 1))
                    log.warning("fault injection: tore staged segment "
                                "manifest %s mid-publish", tmp_path)
                    raise InjectedPublishTear(
                        f"injected manifest tear publishing {op!r} "
                        "(fault spec)")

    def on_tombstone_write(self, tmp_path: str) -> None:
        """Fires in ``segments.tombstones.save`` after the bitmap is
        staged; the ``tombstone-corrupt`` rule flips a byte in place.
        Does not raise — the writer's read-back verification must be
        the thing that rejects the corrupted bytes before publish."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "tombstone-corrupt":
                    continue
                if self._fire_once(ri, rule):
                    with open(tmp_path, "r+b") as f:
                        data = f.read()
                        at = max(len(data) // 2 - 1, 0)
                        f.seek(at)
                        f.write(bytes([data[at] ^ 0xFF]))
                    log.warning("fault injection: corrupted staged "
                                "tombstone bitmap %s", tmp_path)

    def on_compact(self) -> None:
        """Fires in the compactor after the replacement segment is
        fully built, before the manifest swap; may raise
        :class:`InjectedCompactCrash` — the mid-compaction death that
        must leave the old generation serving (plus an orphan dir)."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "compact-crash":
                    continue
                if self._fire_once(ri, rule):
                    raise InjectedCompactCrash(
                        "injected compaction crash before publish "
                        "(fault spec)")

    def on_wal_append(self, path: str) -> None:
        """Fires in ``segments.wal.log_mutation`` after the record
        bytes are written, BEFORE the fsync.  The ``wal-torn-record``
        rule truncates the just-appended record mid-payload and raises
        :class:`InjectedWalTorn`: the mutation fails un-acked, and the
        next WAL read quarantines the torn tail — proving "acked means
        durable" covers the append syscall window itself."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "wal-torn-record":
                    continue
                if self._fire_once(ri, rule):
                    size = os.path.getsize(path)
                    with open(path, "r+b") as f:
                        f.truncate(max(size - 7, 1))
                    log.warning("fault injection: tore wal record in "
                                "%s before fsync", path)
                    raise InjectedWalTorn(
                        "injected wal record tear before fsync "
                        "(fault spec)")

    def on_fetch_payload(self, name: str, data: bytes) -> bytes:
        """Fires on the PRIMARY as a ``fetch_segment`` admin payload is
        about to ship; the ``fetch-partial`` rule truncates it to half.
        The replica's per-file adler32 verification must reject the
        short payload and retry — a partial ship may never be swapped
        into a manifest."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "fetch-partial":
                    continue
                if self._fire_once(ri, rule):
                    log.warning("fault injection: truncating fetch "
                                "payload for %s (%d -> %d bytes)",
                                name, len(data), max(len(data) // 2, 1))
                    return data[:max(len(data) // 2, 1)]
        return data

    def on_lease_check(self) -> bool:
        """Fires as a lease holder validates/renews its lease before a
        mutation.  The ``lease-steal`` rule returns True ONCE: the
        caller rewrites the lease to a foreign owner before its normal
        check runs, which must then reject the mutation with
        ``lease_lost`` while the old generation keeps serving reads."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "lease-steal":
                    continue
                if self._fire_once(ri, rule):
                    log.warning("fault injection: stealing the "
                                "mutation lease")
                    return True
        return False

    def on_reload(self) -> None:
        """Fires in the serve daemon's hot-reload path after the
        replacement artifact is opened but before the engine swap; may
        raise :class:`InjectedReloadCorrupt` — the verification
        failure a reload must survive by keeping the old artifact."""
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind != "reload-corrupt":
                    continue
                if self._fire_once(ri, rule):
                    raise InjectedReloadCorrupt(
                        "injected reload verification failure "
                        "(fault spec)")


# -- arming -----------------------------------------------------------

_UNSET = object()
_active: FaultInjector | None | object = _UNSET
_active_lock = threading.Lock()


def install(spec: str | None) -> FaultInjector | None:
    """Arm the injector from a spec string (None/empty disarms)."""
    global _active
    with _active_lock:
        _active = FaultInjector(spec) if spec else None
        return _active  # type: ignore[return-value]


def active() -> FaultInjector | None:
    """The armed injector, or None.  First call parses ``MRI_FAULTS``
    if :func:`install` was never called (subprocess arming)."""
    global _active
    if _active is _UNSET:
        with _active_lock:
            if _active is _UNSET:
                spec = envknobs.get(ENV_VAR)
                _active = FaultInjector(spec) if spec else None
    return _active  # type: ignore[return-value]


# -- retry policy -----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retries with exponential backoff and a per-document
    deadline.  ``max_attempts`` counts the first try: 3 attempts = up
    to 2 retries.  The deadline bounds the *total* time (including the
    upcoming sleep) one document may consume before its error is
    final — a pathological device can't stall the whole window."""

    max_attempts: int = 3
    backoff_s: float = 0.005
    backoff_mult: float = 2.0
    deadline_s: float = 1.0
    sleep: object = time.sleep  # injectable for tests

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        """Knobs: MRI_READ_RETRIES (attempts), MRI_READ_BACKOFF_MS,
        MRI_READ_DEADLINE_S.

        Invalid values raise a one-line KnobError naming the variable
        (the CLI maps it to exit 2) instead of surfacing a bare
        ``int()`` traceback three layers down a worker thread; the
        casts and bounds live with the declarations in
        :mod:`..utils.envknobs`.
        """
        return cls(
            max_attempts=envknobs.get("MRI_READ_RETRIES"),
            backoff_s=envknobs.get("MRI_READ_BACKOFF_MS") / 1e3,
            deadline_s=envknobs.get("MRI_READ_DEADLINE_S"),
        )

    def run(self, fn, *, doc_id: int | None = None, path: str = "",
            report: "DegradationReport | None" = None):
        """Call ``fn`` retrying OSError; the final error re-raises."""
        delay = self.backoff_s
        deadline = time.monotonic() + self.deadline_s
        attempt = 1
        while True:
            try:
                return fn()
            except OSError:
                if (attempt >= self.max_attempts
                        or time.monotonic() + delay > deadline):
                    raise
                if report is not None:
                    report.record_retry(doc_id=doc_id, path=path)
                self.sleep(delay)
                delay *= self.backoff_mult
                attempt += 1


def default_policy() -> RetryPolicy:
    """The pipeline-wide read policy (env-tunable, see
    :meth:`RetryPolicy.from_env`)."""
    return RetryPolicy.from_env()


# -- degradation report -----------------------------------------------

class DegradationReport:
    """Thread-safe tally of what failure handling did in one run."""

    def __init__(self):
        self._lock = threading.Lock()
        self.read_retries = 0  # guarded by: self._lock
        # {"doc_id", "path", "reason"}  # guarded by: self._lock
        self.skips: list[dict] = []
        # In-run fault-tolerance tallies (models/inverted_index
        # parallel host path): a recovered worker death leaves the
        # output byte-identical, so these are the only observable
        # trace that recovery ran at all.
        self.worker_recoveries = 0   # guarded by: self._lock
        self.windows_requeued = 0    # guarded by: self._lock
        self.reducer_takeovers = 0   # guarded by: self._lock

    def record_retry(self, *, doc_id: int | None = None,
                     path: str = "") -> None:
        with self._lock:
            self.read_retries += 1

    def record_skip(self, *, doc_id: int, path: str,
                    reason: str) -> None:
        log.debug("skipping unreadable document %s (doc id %d): %s",
                  path, doc_id, reason)
        with self._lock:
            self.skips.append(
                {"doc_id": doc_id, "path": path, "reason": reason})

    def record_worker_recovery(self, *, windows_requeued: int = 0) -> None:
        """One scan worker died and its windows went back to the pool
        (survivors or a respawned replacement rescan them)."""
        with self._lock:
            self.worker_recoveries += 1
            self.windows_requeued += int(windows_requeued)

    def record_reducer_takeover(self) -> None:
        """One dead reducer's letter range was re-emitted by a
        surviving thread (atomic tmp+rename makes the re-emit safe)."""
        with self._lock:
            self.reducer_takeovers += 1

    def merge(self, other: "DegradationReport") -> None:
        """Fold ``other``'s tallies into this report (thread-safe on
        both sides).  The multi-worker host path gives each scan worker
        its own report — readers record without contending on the
        run-scoped lock — and merges them at the join barrier, so a
        degraded K-worker run still exits with the COMPLETE skipped-doc
        list no matter which worker hit the bad stripe."""
        if other is self:
            return
        with other._lock:
            retries = other.read_retries
            skips = list(other.skips)
            recoveries = other.worker_recoveries
            requeued = other.windows_requeued
            takeovers = other.reducer_takeovers
        with self._lock:
            self.read_retries += retries
            self.skips.extend(skips)
            self.worker_recoveries += recoveries
            self.windows_requeued += requeued
            self.reducer_takeovers += takeovers

    @property
    def degraded(self) -> bool:
        with self._lock:
            return bool(self.skips)

    def skipped_doc_ids(self) -> list[int]:
        with self._lock:
            return [s["doc_id"] for s in self.skips]

    def summary(self) -> dict:
        """The stats-dict form (bench JSON / ``--stats`` fields)."""
        with self._lock:
            return {
                "read_retries": self.read_retries,
                "skipped_docs": [s["doc_id"] for s in self.skips],
                "skip_reasons": {
                    str(s["doc_id"]): s["reason"] for s in self.skips},
                "worker_recoveries": self.worker_recoveries,
                "windows_requeued": self.windows_requeued,
                "reducer_takeovers": self.reducer_takeovers,
            }

    def log_summary(self, logger: logging.Logger = log) -> None:
        """ONE counted line for the whole run — per-document warnings
        are deduplicated here (each skip is DEBUG-logged at the site)."""
        with self._lock:
            recoveries = self.worker_recoveries
            requeued = self.windows_requeued
            takeovers = self.reducer_takeovers
        if recoveries or takeovers:
            logger.info(
                "fault tolerance: recovered %d worker death(s) "
                "(%d window(s) requeued), %d reducer takeover(s)",
                recoveries, requeued, takeovers)
        if not self.degraded:
            return
        with self._lock:
            ids = [s["doc_id"] for s in self.skips]
            first = self.skips[0]
            retries = self.read_retries
        logger.warning(
            "degraded run: skipped %d unreadable document(s) "
            "(doc ids %s) after %d retr%s; first reason: %s",
            len(ids), ids, retries,
            "y" if retries == 1 else "ies", first["reason"])


_report_lock = threading.Lock()
_current_report = DegradationReport()


def current_report() -> DegradationReport:
    """The run-scoped report the read paths record into by default."""
    with _report_lock:
        return _current_report


def begin_run() -> DegradationReport:
    """Start a fresh report (the model calls this at run() entry)."""
    global _current_report
    with _report_lock:
        _current_report = DegradationReport()
        return _current_report
