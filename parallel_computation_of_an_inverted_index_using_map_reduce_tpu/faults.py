"""Deterministic fault injection + the pipeline's resilience policy.

The reference dies on any I/O hiccup (a failed ``fopen`` merely warns,
main.c:97-100, but nothing retries, nothing reports, and a crash loses
the whole run).  This module makes failure handling a *tested
subsystem*: every failure mode the pipeline claims to survive can be
armed deterministically and proven in a test, the same way DrJAX
(arXiv:2403.07128) treats MapReduce structure as an explicit primitive
rather than emergent behavior.

Three layers live here:

``FaultInjector``
    Seedable, deterministic injection hooks.  Armed via
    :func:`install` (the CLI's ``--fault-spec``) or the ``MRI_FAULTS``
    env var (so subprocess e2e tests can arm a child they then
    SIGKILL).  Spec grammar — clauses joined by ``;``, fields by ``:``::

        read-error:doc=2:times=2     transient OSError, first 2 attempts
        read-error:all:times=-1      permanent OSError on every doc
        read-error:every=3:times=1   every 3rd manifest index
        read-error:all:p=0.5:times=1 probabilistic (seed=N clause)
        slow-read:doc=1:ms=50        sleep before the read
        truncate:doc=4:bytes=10      document bytes cut short
        reader-death:window=1        silent reader-thread death
        sigkill:window=2             SIGKILL at stream window boundary
        stream-crash:window=2        RuntimeError from the stream engine
        ckpt-corrupt:save=1          corrupt checkpoint bytes post-save
        seed=7                       RNG seed for ``p=`` rules

    ``doc`` / ``every`` match the 0-based manifest index; ``window``
    and ``save`` are 1-based ordinals (matching ``win_i`` in the
    stream loop and "the Nth save").

``RetryPolicy``
    Bounded retries with exponential backoff and a per-document
    deadline — replaces the single-shot warn-and-skip on the read
    paths (io/reader.py, corpus/manifest.iter_document_ranges).

``DegradationReport``
    The structured outcome of a run's failure handling: retry counts
    and exactly which doc ids were skipped, with reasons.  The model
    attaches it to run stats; the CLI turns a non-empty skip list into
    the documented degraded exit code (:data:`EXIT_DEGRADED`).
"""

from __future__ import annotations

import dataclasses
import errno
import logging
import os
import random
import signal
import threading
import time

log = logging.getLogger("mri_tpu.faults")

ENV_VAR = "MRI_FAULTS"

#: CLI exit code for a run that completed but skipped documents after
#: exhausting its retry budget (0 = clean, 2 = error, 3 = degraded).
EXIT_DEGRADED = 3


class FaultSpecError(ValueError):
    """Malformed ``--fault-spec`` / ``MRI_FAULTS`` string."""


class InjectedReadError(OSError):
    """The injected transient/permanent read failure (an OSError, so
    the production retry/skip machinery handles it like a real one)."""


class ReaderThreadDeath(BaseException):
    """Injected *silent* reader-thread death.

    Deliberately a BaseException: the executor's reader loop catches it
    specially and exits without posting anything to the consumer — the
    fire-and-forget daemon-thread failure mode the consumer-side
    watchdog exists to detect.
    """


# -- injector ---------------------------------------------------------

_READ_KINDS = ("read-error", "slow-read", "truncate")


@dataclasses.dataclass
class _Rule:
    kind: str
    doc: int | None = None      # manifest index; None = all (read kinds)
    every: int | None = None
    p: float | None = None
    times: int = 1              # -1 = permanent (read-error)
    ms: float = 0.0             # slow-read
    bytes: int = 0              # truncate
    window: int = 0             # reader-death / sigkill / stream-crash
    save: int = 0               # ckpt-corrupt


def _parse_int(kind: str, key: str, value: str) -> int:
    try:
        return int(value)
    except ValueError:
        raise FaultSpecError(
            f"{kind}: {key}={value!r} is not an integer") from None


def _parse_clause(clause: str, kv_global: dict) -> _Rule | None:
    parts = [p for p in clause.strip().split(":") if p]
    if not parts:
        return None
    head = parts[0]
    if "=" in head:  # bare global assignment, e.g. seed=7
        k, v = head.split("=", 1)
        if k != "seed":
            raise FaultSpecError(f"unknown global fault key {k!r}")
        kv_global["seed"] = _parse_int("seed", "seed", v)
        if len(parts) > 1:
            raise FaultSpecError("seed=N must be a clause of its own")
        return None
    rule = _Rule(kind=head)
    if head not in _READ_KINDS + ("reader-death", "sigkill",
                                  "stream-crash", "ckpt-corrupt"):
        raise FaultSpecError(f"unknown fault kind {head!r}")
    for field in parts[1:]:
        if field == "all":
            rule.doc = None
            continue
        if "=" not in field:
            raise FaultSpecError(
                f"{head}: expected key=value, got {field!r}")
        k, v = field.split("=", 1)
        if k == "doc":
            rule.doc = _parse_int(head, k, v)
        elif k == "every":
            rule.every = _parse_int(head, k, v)
        elif k == "times":
            rule.times = _parse_int(head, k, v)
        elif k == "p":
            try:
                rule.p = float(v)
            except ValueError:
                raise FaultSpecError(
                    f"{head}: p={v!r} is not a float") from None
        elif k == "ms":
            rule.ms = float(_parse_int(head, k, v))
        elif k == "bytes":
            rule.bytes = _parse_int(head, k, v)
        elif k == "window":
            rule.window = _parse_int(head, k, v)
        elif k == "save":
            rule.save = _parse_int(head, k, v)
        else:
            raise FaultSpecError(f"{head}: unknown key {k!r}")
    if rule.kind in ("reader-death", "sigkill", "stream-crash") \
            and rule.window < 1:
        raise FaultSpecError(f"{head} needs window=N (1-based)")
    if rule.kind == "ckpt-corrupt" and rule.save < 1:
        raise FaultSpecError("ckpt-corrupt needs save=N (1-based)")
    return rule


class FaultInjector:
    """Parsed fault spec + per-rule firing state.  Thread-safe: the
    read hooks fire from reader threads concurrently with the main
    thread's checkpoint/window hooks."""

    def __init__(self, spec: str):
        self.spec = spec
        kv_global: dict = {}
        self.rules: list[_Rule] = []
        for clause in spec.split(";"):
            rule = _parse_clause(clause, kv_global)
            if rule is not None:
                self.rules.append(rule)
        if not self.rules and "seed" not in kv_global:
            raise FaultSpecError(f"empty fault spec {spec!r}")
        self._rng = random.Random(kv_global.get("seed", 0))
        self._lock = threading.Lock()
        self._fired: dict[tuple[int, int], int] = {}
        self._saves = 0

    def _matches(self, rule: _Rule, index: int) -> bool:
        if rule.doc is not None and index != rule.doc:
            return False
        if rule.every is not None and index % rule.every != 0:
            return False
        if rule.p is not None and self._rng.random() >= rule.p:
            return False
        return True

    # -- hooks (each a no-op unless a matching rule is armed) ---------

    def on_read(self, index: int, path: str) -> int | None:
        """Per-attempt read hook.  May raise :class:`InjectedReadError`
        or sleep; returns a byte cap to truncate the document to, or
        None.  ``times=N`` counts *per document*, so a retrying caller
        sees N failures then success — the transient-fault contract."""
        cap = None
        delay = 0.0
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind not in _READ_KINDS \
                        or not self._matches(rule, index):
                    continue
                if rule.kind == "slow-read":
                    delay = max(delay, rule.ms / 1e3)
                elif rule.kind == "truncate":
                    cap = rule.bytes if cap is None \
                        else min(cap, rule.bytes)
                else:  # read-error
                    key = (ri, index)
                    n = self._fired.get(key, 0)
                    if rule.times < 0 or n < rule.times:
                        self._fired[key] = n + 1
                        raise InjectedReadError(
                            errno.EIO, "injected read failure "
                            f"(attempt {n + 1})", path)
        if delay:
            time.sleep(delay)
        return cap

    def on_reader_window(self, window: int) -> None:
        """Fires in the executor's reader thread before window
        ``window`` (1-based) is read; may raise ReaderThreadDeath."""
        for rule in self.rules:
            if rule.kind == "reader-death" and rule.window == window:
                raise ReaderThreadDeath()

    def on_window_boundary(self, window: int) -> None:
        """Fires after window ``window`` completes — on the stream
        loop's main thread (post-checkpoint) and, for the pipelined cpu
        path, in each reader thread after the window is read and handed
        downstream (the window index is the GLOBAL plan index, so specs
        are worker-count-invariant); may SIGKILL."""
        for rule in self.rules:
            if rule.kind == "sigkill" and rule.window == window:
                log.warning("fault injection: SIGKILL at stream "
                            "window boundary %d", window)
                os.kill(os.getpid(), signal.SIGKILL)

    def on_stream_window(self, window: int) -> None:
        """Fires inside the device stream engine after it folds window
        ``window``; may raise (the round-3 TPU worker crash, as a
        first-class fault instead of an ad-hoc env hook)."""
        for rule in self.rules:
            if rule.kind == "stream-crash" and rule.window == window:
                raise RuntimeError(
                    f"injected stream crash after window {window} "
                    "(fault spec)")

    def on_checkpoint_saved(self, path: str) -> None:
        """Fires after every atomic checkpoint save; the Nth save may
        be corrupted in place (truncated to a third), simulating the
        torn/bit-rotted file ``--resume=auto`` must survive."""
        with self._lock:
            self._saves += 1
            saves = self._saves
        for rule in self.rules:
            if rule.kind == "ckpt-corrupt" and rule.save == saves:
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.truncate(max(size // 3, 1))
                log.warning("fault injection: corrupted checkpoint "
                            "%s (save #%d)", path, saves)


# -- arming -----------------------------------------------------------

_UNSET = object()
_active: FaultInjector | None | object = _UNSET
_active_lock = threading.Lock()


def install(spec: str | None) -> FaultInjector | None:
    """Arm the injector from a spec string (None/empty disarms)."""
    global _active
    with _active_lock:
        _active = FaultInjector(spec) if spec else None
        return _active  # type: ignore[return-value]


def active() -> FaultInjector | None:
    """The armed injector, or None.  First call parses ``MRI_FAULTS``
    if :func:`install` was never called (subprocess arming)."""
    global _active
    if _active is _UNSET:
        with _active_lock:
            if _active is _UNSET:
                _active = (FaultInjector(os.environ[ENV_VAR])
                           if os.environ.get(ENV_VAR) else None)
    return _active  # type: ignore[return-value]


# -- retry policy -----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retries with exponential backoff and a per-document
    deadline.  ``max_attempts`` counts the first try: 3 attempts = up
    to 2 retries.  The deadline bounds the *total* time (including the
    upcoming sleep) one document may consume before its error is
    final — a pathological device can't stall the whole window."""

    max_attempts: int = 3
    backoff_s: float = 0.005
    backoff_mult: float = 2.0
    deadline_s: float = 1.0
    sleep: object = time.sleep  # injectable for tests

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        """Knobs: MRI_READ_RETRIES (attempts), MRI_READ_BACKOFF_MS,
        MRI_READ_DEADLINE_S."""
        return cls(
            max_attempts=int(os.environ.get("MRI_READ_RETRIES", 3)),
            backoff_s=float(os.environ.get("MRI_READ_BACKOFF_MS", 5)) / 1e3,
            deadline_s=float(os.environ.get("MRI_READ_DEADLINE_S", 1.0)),
        )

    def run(self, fn, *, doc_id: int | None = None, path: str = "",
            report: "DegradationReport | None" = None):
        """Call ``fn`` retrying OSError; the final error re-raises."""
        delay = self.backoff_s
        deadline = time.monotonic() + self.deadline_s
        attempt = 1
        while True:
            try:
                return fn()
            except OSError:
                if (attempt >= self.max_attempts
                        or time.monotonic() + delay > deadline):
                    raise
                if report is not None:
                    report.record_retry(doc_id=doc_id, path=path)
                self.sleep(delay)
                delay *= self.backoff_mult
                attempt += 1


def default_policy() -> RetryPolicy:
    """The pipeline-wide read policy (env-tunable, see
    :meth:`RetryPolicy.from_env`)."""
    return RetryPolicy.from_env()


# -- degradation report -----------------------------------------------

class DegradationReport:
    """Thread-safe tally of what failure handling did in one run."""

    def __init__(self):
        self._lock = threading.Lock()
        self.read_retries = 0
        self.skips: list[dict] = []  # {"doc_id", "path", "reason"}

    def record_retry(self, *, doc_id: int | None = None,
                     path: str = "") -> None:
        with self._lock:
            self.read_retries += 1

    def record_skip(self, *, doc_id: int, path: str,
                    reason: str) -> None:
        log.debug("skipping unreadable document %s (doc id %d): %s",
                  path, doc_id, reason)
        with self._lock:
            self.skips.append(
                {"doc_id": doc_id, "path": path, "reason": reason})

    def merge(self, other: "DegradationReport") -> None:
        """Fold ``other``'s tallies into this report (thread-safe on
        both sides).  The multi-worker host path gives each scan worker
        its own report — readers record without contending on the
        run-scoped lock — and merges them at the join barrier, so a
        degraded K-worker run still exits with the COMPLETE skipped-doc
        list no matter which worker hit the bad stripe."""
        if other is self:
            return
        with other._lock:
            retries = other.read_retries
            skips = list(other.skips)
        with self._lock:
            self.read_retries += retries
            self.skips.extend(skips)

    @property
    def degraded(self) -> bool:
        return bool(self.skips)

    def skipped_doc_ids(self) -> list[int]:
        with self._lock:
            return [s["doc_id"] for s in self.skips]

    def summary(self) -> dict:
        """The stats-dict form (bench JSON / ``--stats`` fields)."""
        with self._lock:
            return {
                "read_retries": self.read_retries,
                "skipped_docs": [s["doc_id"] for s in self.skips],
                "skip_reasons": {
                    str(s["doc_id"]): s["reason"] for s in self.skips},
            }

    def log_summary(self, logger: logging.Logger = log) -> None:
        """ONE counted line for the whole run — per-document warnings
        are deduplicated here (each skip is DEBUG-logged at the site)."""
        if not self.degraded:
            return
        with self._lock:
            ids = [s["doc_id"] for s in self.skips]
            first = self.skips[0]
        logger.warning(
            "degraded run: skipped %d unreadable document(s) "
            "(doc ids %s) after %d retr%s; first reason: %s",
            len(ids), ids, self.read_retries,
            "y" if self.read_retries == 1 else "ies", first["reason"])


_report_lock = threading.Lock()
_current_report = DegradationReport()


def current_report() -> DegradationReport:
    """The run-scoped report the read paths record into by default."""
    with _report_lock:
        return _current_report


def begin_run() -> DegradationReport:
    """Start a fresh report (the model calls this at run() entry)."""
    global _current_report
    with _report_lock:
        _current_report = DegradationReport()
        return _current_report
