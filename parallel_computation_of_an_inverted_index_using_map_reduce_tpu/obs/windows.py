"""Rolling-window SLIs: snapshot-diffed off the cumulative registry.

Every number the obs layer accumulates is cumulative-since-start; an
operator (and the SLO layer) needs *current* rates and quantiles.  The
:class:`RollingWindows` aggregator gets them with **zero new hot-path
feed sites**: a sampler thread wakes every ``MRI_OBS_SAMPLE_MS`` and
diffs the tracked counters and histograms against its previous
snapshot, appending one per-period bucket of deltas to a bounded ring.
Rolling rates, latency quantiles and threshold fractions over the
10s / 1m / 5m windows are then pure reads over the ring.

Histogram buckets are stored in cumulative-delta form (the elementwise
difference of two ``cumulative_counts()`` snapshots), so summing
buckets over a window directly yields the window's cumulative
histogram — quantiles and "fraction under threshold" interpolate
linearly inside one bucket, exactly like PromQL's
``histogram_quantile``.

Stdlib-only by design: the sampler must be importable (and priceable)
without jax/numpy.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils import envknobs
from . import metrics as obs_metrics

SAMPLE_ENV = "MRI_OBS_SAMPLE_MS"

#: the rolling windows every SLI surface reports, label -> span seconds
WINDOWS = (("10s", 10.0), ("1m", 60.0), ("5m", 300.0))
_MAX_SPAN = max(span for _label, span in WINDOWS)


def sample_period_s() -> float:
    return envknobs.get(SAMPLE_ENV) / 1e3


class _Bucket:
    __slots__ = ("ts", "counters", "hists")

    def __init__(self, ts: float, counters: dict, hists: dict):
        self.ts = ts
        self.counters = counters  # name -> delta
        self.hists = hists        # name -> (d_count, d_sum, d_cum tuple)


class RollingWindows:
    """Per-period delta ring over a :class:`obs.metrics.Registry`.

    ``counters`` / ``histograms`` name the registry series to track;
    they are get-or-created up front so the sampler never races metric
    creation.  :meth:`sample` is public so tests (and the pricing
    bench) can tick it deterministically without the thread.
    """

    def __init__(self, registry: obs_metrics.Registry,
                 counters=(), histograms=(),
                 period_s: float | None = None,
                 clock=time.monotonic):
        self.registry = registry
        self.period_s = float(period_s if period_s is not None
                              else sample_period_s())
        self._clock = clock
        self._counters = {n: registry.counter(n) for n in counters}
        self._hists = {n: registry.histogram(n) for n in histograms}
        self._lock = threading.Lock()
        maxlen = int(_MAX_SPAN / self.period_s) + 2
        self._ring: deque = deque(maxlen=maxlen)  # guarded by: self._lock
        self._prev_c: dict = {}    # guarded by: self._lock
        self._prev_h: dict = {}    # guarded by: self._lock
        self._start = self._clock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # seed the baseline snapshot so the first tick diffs cleanly
        with self._lock:
            self._snapshot_locked()

    # mrilint: holds(self._lock)
    def _snapshot_locked(self) -> tuple[dict, dict]:
        """Read cumulative state and return (counter, hist) deltas
        against the previous snapshot, updating it in place."""
        d_c, d_h = {}, {}
        for name, c in self._counters.items():
            cur = c.value
            d_c[name] = cur - self._prev_c.get(name, 0)
            self._prev_c[name] = cur
        for name, h in self._hists.items():
            cum = tuple(h.cumulative_counts())
            total = h.sum
            p_cum, p_sum = self._prev_h.get(
                name, ((0,) * len(cum), 0.0))
            d_h[name] = (cum[-1] - p_cum[-1], total - p_sum,
                         tuple(a - b for a, b in zip(cum, p_cum)))
            self._prev_h[name] = (cum, total)
        return d_c, d_h

    def sample(self) -> None:
        """One sampler tick: append the delta bucket for this period."""
        now = self._clock()
        with self._lock:
            d_c, d_h = self._snapshot_locked()
            self._ring.append(_Bucket(now, d_c, d_h))

    def track(self, counters=(), histograms=()) -> None:
        """Register additional registry series after construction —
        per-tenant lanes appear lazily on a tenant's first request.
        New series are seeded at their *current* cumulative value so
        the next tick diffs cleanly (no phantom first-bucket spike);
        already-tracked names are no-ops."""
        with self._lock:
            for n in counters:
                if n in self._counters:
                    continue
                c = self.registry.counter(n)
                self._counters[n] = c
                self._prev_c[n] = c.value
            for n in histograms:
                if n in self._hists:
                    continue
                h = self.registry.histogram(n)
                self._hists[n] = h
                self._prev_h[n] = (tuple(h.cumulative_counts()), h.sum)

    def tracks(self, name: str) -> bool:
        with self._lock:
            return name in self._counters or name in self._hists

    # -- sampler thread -------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mri-obs-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — sampler must survive races
                pass

    # -- window reads ---------------------------------------------------

    # mrilint: holds(self._lock)
    def _buckets(self, window_s: float, now: float) -> list:
        cutoff = now - window_s - self.period_s / 2
        return [b for b in self._ring if b.ts > cutoff]

    def span(self, window_s: float) -> float:
        """Effective denominator: the window, clamped to process age
        (so early-life rates aren't diluted by an empty prefix)."""
        return max(self.period_s,
                   min(float(window_s), self._clock() - self._start))

    def counts(self, window_s: float) -> dict:
        """Summed counter deltas over the window."""
        now = self._clock()
        with self._lock:
            out = dict.fromkeys(self._counters, 0)
            for b in self._buckets(window_s, now):
                for name, d in b.counters.items():
                    out[name] += d
        return out

    def rate(self, name: str, window_s: float) -> float:
        """Events per second for one counter over the window."""
        return self.counts(window_s).get(name, 0) / self.span(window_s)

    def _hist_cum(self, name: str, window_s: float):
        """(cumulative bucket counts, count, sum) over the window."""
        h = self._hists[name]
        now = self._clock()
        cum = [0] * (len(h.bounds) + 1)
        count, total = 0, 0.0
        with self._lock:
            for b in self._buckets(window_s, now):
                entry = b.hists.get(name)
                if entry is None:
                    continue
                d_count, d_sum, d_cum = entry
                count += d_count
                total += d_sum
                for i, d in enumerate(d_cum):
                    cum[i] += d
        return cum, count, total

    def hist_count(self, name: str, window_s: float) -> int:
        return self._hist_cum(name, window_s)[1]

    def quantile(self, name: str, window_s: float,
                 p: float) -> float | None:
        """Windowed quantile in the histogram's native unit (seconds),
        linearly interpolated inside the landing bucket; ``None`` when
        the window saw no observations."""
        cum, count, _ = self._hist_cum(name, window_s)
        if count <= 0:
            return None
        bounds = self._hists[name].bounds
        rank = max(1e-12, (p / 100.0) * count)
        prev = 0
        for i, c in enumerate(cum):
            if c >= rank:
                if i >= len(bounds):      # +Inf bucket: clamp
                    return float(bounds[-1])
                lo = bounds[i - 1] if i > 0 else 0.0
                hi = bounds[i]
                frac = (rank - prev) / max(1, c - prev)
                return lo + (hi - lo) * frac
            prev = c
        return float(bounds[-1])

    def good_fraction(self, name: str, window_s: float,
                      threshold_s: float) -> float | None:
        """Fraction of windowed observations at or under the
        threshold (the latency-SLO SLI); ``None`` with no samples."""
        cum, count, _ = self._hist_cum(name, window_s)
        if count <= 0:
            return None
        bounds = self._hists[name].bounds
        prev_c, lo = 0, 0.0
        for i, hi in enumerate(bounds):
            if threshold_s <= hi:
                inside = cum[i] - prev_c
                frac = (threshold_s - lo) / max(hi - lo, 1e-30)
                le = prev_c + inside * min(1.0, max(0.0, frac))
                return min(1.0, le / count)
            prev_c, lo = cum[i], hi
        return 1.0
