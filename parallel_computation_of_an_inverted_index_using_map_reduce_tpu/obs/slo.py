"""Declarative SLOs + multi-window burn rates over the rolling SLIs.

Two objectives ship by default, both against ``MRI_OBS_SLO_TARGET``:

* **availability** — 1 − (errors + sheds + deadline misses) /
  admission attempts, per rolling window.  "Bad" counts internal
  errors, admission sheds, draining rejections and expired deadlines;
  client-caused ``bad_request`` lines are the client's fault and do
  not burn the serving budget.
* **latency** — the fraction of data requests answered within
  ``MRI_OBS_SLO_LATENCY_MS``, interpolated from the windowed request
  histogram.

The burn rate per window is the standard multi-window form:
``(1 - ratio) / (1 - target)`` — 1.0 means the error budget burns
exactly at the objective's rate; a 10s burn ≫ 1 with a calm 5m burn
is a spike, both elevated is an outage.  A window with no events
reports ratio 1.0 / burn 0.0: an idle daemon is not failing.

Surfaced three ways by the daemon: the ``slo`` admin op, the ``slo``
block inside ``stats``, and ``mri_slo_*`` gauges in the Prometheus
exposition.  Stdlib-only by design.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils import envknobs
from . import metrics as obs_metrics
from . import windows as obs_windows

LATENCY_ENV = "MRI_OBS_SLO_LATENCY_MS"
TARGET_ENV = "MRI_OBS_SLO_TARGET"

#: availability inputs, in daemon counter-name form
_TOTAL = "mri_serve_requests_total"
_BAD = ("mri_serve_internal_errors_total",
        "mri_serve_shed_total",
        "mri_serve_draining_rejected_total",
        "mri_serve_deadline_expired_total")
_LATENCY_HIST = "mri_serve_request_seconds"


def slo_target() -> float:
    return envknobs.get(TARGET_ENV)


def slo_latency_ms() -> float:
    return envknobs.get(LATENCY_ENV)


@dataclass(frozen=True)
class SLO:
    """One declarative objective: a named good-event fraction."""

    name: str
    target: float
    threshold_ms: float | None = None  # latency SLOs only

    def budget(self) -> float:
        return max(1e-9, 1.0 - self.target)


def default_slos() -> tuple:
    t = slo_target()
    return (SLO("availability", t),
            SLO("latency", t, threshold_ms=slo_latency_ms()))


class SLOTracker:
    """Window math over a :class:`RollingWindows` for a set of SLOs.

    The metric names default to the daemon-wide families; per-tenant
    trackers pass their own lane's names (``total``/``bad``/
    ``extra_total``/``latency_hist``) and reuse the same math.
    """

    def __init__(self, windows: obs_windows.RollingWindows, slos=None, *,
                 total: str = _TOTAL, bad=_BAD,
                 extra_total=("mri_serve_shed_total",
                              "mri_serve_draining_rejected_total"),
                 latency_hist: str = _LATENCY_HIST):
        self.windows = windows
        self.slos = tuple(slos) if slos is not None else default_slos()
        self._total = total
        self._bad = tuple(bad)
        # sheds/rejections never reach the requests counter: the
        # denominator is every admission attempt the window saw
        self._extra_total = tuple(extra_total)
        self._latency_hist = latency_hist

    def _window_point(self, slo: SLO, span: float) -> dict:
        if slo.threshold_ms is None:
            counts = self.windows.counts(span)
            bad = sum(counts.get(n, 0) for n in self._bad)
            total = (counts.get(self._total, 0)
                     + sum(counts.get(n, 0) for n in self._extra_total))
            ratio = 1.0 if total <= 0 else max(
                0.0, 1.0 - bad / total)
            point = {"total": total, "bad": bad}
        else:
            total = self.windows.hist_count(self._latency_hist, span)
            frac = self.windows.good_fraction(
                self._latency_hist, span, slo.threshold_ms / 1e3)
            ratio = 1.0 if frac is None else frac
            point = {"total": total}
        point["ratio"] = round(ratio, 6)
        point["burn"] = round((1.0 - ratio) / slo.budget(), 4)
        return point

    def report(self) -> dict:
        """The ``slo`` admin-op / stats payload."""
        out = {}
        for slo in self.slos:
            entry = {"target": slo.target}
            if slo.threshold_ms is not None:
                entry["threshold_ms"] = slo.threshold_ms
            entry["windows"] = {
                label: self._window_point(slo, span)
                for label, span in obs_windows.WINDOWS}
            out[slo.name] = entry
        return out

    def set_gauges(self, registry: obs_metrics.Registry) -> None:
        """Refresh the ``mri_slo_*`` gauges (called at scrape time)."""
        for name, entry in self.report().items():
            for label, point in entry["windows"].items():
                registry.gauge(
                    f"mri_slo_{name}_ratio_{label}").set(point["ratio"])
                registry.gauge(
                    f"mri_slo_{name}_burn_{label}").set(point["burn"])
