"""Per-request tracing: trace ids, the recent-trace ring, slow log.

The daemon accepts an optional ``trace_id`` on every wire request and
echoes it on the response (auto-generating one when observability is
on).  Each finished request leaves one trace record — contiguous spans
covering queue wait → coalesce → engine — in a bounded ring queryable
via the ``trace`` admin op, and requests slower than
``MRI_OBS_SLOW_MS`` additionally emit one structured JSON line on the
``mri_tpu.obs`` logger.
"""

from __future__ import annotations

import logging
import os
import random
import threading
from collections import deque

from ..utils import envknobs
from . import logging as obs_logging

ENABLE_ENV = "MRI_OBS_ENABLE"
RING_ENV = "MRI_OBS_TRACE_RING"
SLOW_ENV = "MRI_OBS_SLOW_MS"

#: The slow-query logger: one ``{"event":"slow_query",...}`` JSON line
#: per offending request (WARNING level, never raises into serving).
slow_log = logging.getLogger("mri_tpu.obs")


def enabled() -> bool:
    return envknobs.get(ENABLE_ENV) != 0


def slow_ms() -> float:
    return envknobs.get(SLOW_ENV)


def ring_capacity() -> int:
    return envknobs.get(RING_ENV)


#: seeded once from the OS, stepped in C thereafter: trace ids are
#: collision-avoidance for a bounded ring, not secrets, and a
#: getrandom(2) syscall per request dominates the serve loop's serial
#: read path on slow-entropy hosts.  getrandbits is a single C call,
#: so concurrent callers are safe under the GIL.
_trace_rng = random.Random(os.urandom(16))


def gen_trace_id() -> str:
    """16 hex chars, collision-safe for a ring of recent traces."""
    return f"{_trace_rng.getrandbits(64):016x}"


class TraceRing:
    """Bounded, thread-safe ring of completed trace records (dicts)."""

    def __init__(self, capacity: int | None = None):
        cap = capacity if capacity is not None else ring_capacity()
        self._lock = threading.Lock()
        self._dq: deque = deque(maxlen=max(1, cap))  # guarded by: self._lock

    def push(self, trace: dict) -> None:
        with self._lock:
            self._dq.append(trace)

    def snapshot(self, n: int | None = None) -> list[dict]:
        """Most-recent-first list of up to ``n`` traces."""
        with self._lock:
            out = list(self._dq)
        out.reverse()
        if n is not None:
            out = out[:max(0, n)]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)


def emit_slow(trace: dict) -> None:
    """One structured JSON line for a slow request — routed through
    the unified obs logging funnel (rate-limited).  Never raises."""
    obs_logging.emit(slow_log, "slow_query", level=logging.WARNING,
                     **trace)
