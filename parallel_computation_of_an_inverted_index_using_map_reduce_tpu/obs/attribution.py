"""Request-scoped cost attribution: the EXPLAIN collector + flight ring.

The aggregate obs layer (:mod:`.metrics`, :mod:`.tracing`) answers
"how is the daemon doing?"; this module answers "why was THIS query
slow?".  A :class:`Collector` rides one request end to end — installed
in a :mod:`contextvars` context variable so the engines, planner and
cache can feed it without threading a handle through every signature —
and every feed sits directly beside the registry-counter increment it
mirrors, so summing per-request reports over a run reproduces the
registry counters exactly (the parity gate in tests/test_attrib.py).

Cost discipline: when no collector is installed (the default serving
path) the only overhead is one ``ContextVar.get`` returning ``None``
per feed site — no allocation, no locking.  Feeds on an installed
collector are plain attribute adds and list appends; a collector is
single-writer by construction (it lives in one request's context), so
no lock is taken on the hot path.

The :class:`FlightRecorder` is the after-the-incident black box: a
bounded ring (``MRI_OBS_FLIGHT_RING``) of the last N completed request
records (trace + optional cost report) plus the slow-log offenders,
dumped as one JSON file on SIGQUIT, on daemon crash or abnormal drain,
and on demand via the ``flightdump`` admin op.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from collections import deque

from ..utils import envknobs

FLIGHT_RING_ENV = "MRI_OBS_FLIGHT_RING"
EXEMPLARS_ENV = "MRI_OBS_EXEMPLARS"

#: the request-scoped collector; ``None`` means attribution is off and
#: every feed site reduces to one ContextVar.get.
_current: contextvars.ContextVar = contextvars.ContextVar(
    "mri_attribution", default=None)


def active():
    """The installed :class:`Collector`, or ``None`` (the fast path)."""
    return _current.get()


def install(coll):
    """Install ``coll`` for the current context; returns a reset token."""
    return _current.set(coll)


def uninstall(token) -> None:
    _current.reset(token)


@contextlib.contextmanager
def collect(op: str = ""):
    """Run a block under a fresh collector and yield it.

    >>> with attribution.collect("top_k_scored") as coll:
    ...     engine.top_k_scored(batch, k=10)
    >>> coll.report()["engine"]["blocks_decoded"]
    """
    coll = Collector(op=op)
    token = _current.set(coll)
    try:
        yield coll
    finally:
        _current.reset(token)


def flight_ring_capacity() -> int:
    return envknobs.get(FLIGHT_RING_ENV)


def exemplars_enabled() -> bool:
    return envknobs.get(EXEMPLARS_ENV) != 0


class Collector:
    """Cost ledger for one request.

    Every mutator mirrors exactly one registry-counter increment at its
    call site; :meth:`report` assembles the structured JSON cost report
    the ``explain`` surface returns.  Single-writer: one request, one
    context, one collector (multi-segment requests attach one child
    collector per segment via :meth:`child`).
    """

    __slots__ = (
        "op", "terms", "blocks_decoded", "blocks_skipped",
        "bytes_decoded", "cache_hits", "cache_misses", "cache_events",
        "planner_mode", "planner_scored", "planner_skipped",
        "planner_candidates", "thetas", "and_arms", "stages_us",
        "segments",
    )

    def __init__(self, op: str = ""):
        self.op = op
        self.terms: list = []
        self.blocks_decoded = 0
        self.blocks_skipped = 0
        self.bytes_decoded = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_events: list = []
        self.planner_mode = ""
        self.planner_scored = 0
        self.planner_skipped = 0
        self.planner_candidates = 0
        self.thetas: list = []
        self.and_arms: list = []
        self.stages_us: dict = {}
        self.segments: list = []

    # -- feeds (each mirrors one registry increment) --------------------

    def term(self, term, idx: int, found: bool, df: int,
             path: str) -> None:
        """One resolved query term: ``path`` is how the lex index was
        found — ``memo`` / ``bisect`` (host), ``device`` (device
        bisect), ``cache`` (whole-batch occ memo)."""
        if isinstance(term, bytes):
            term = term.decode("utf-8", "replace")
        self.terms.append({"term": str(term), "idx": int(idx),
                           "found": bool(found), "df": int(df),
                           "path": path})

    def decoded(self, blocks: int, nbytes: int) -> None:
        """Mirrors ``mri_engine_blocks_decoded_total`` +
        ``mri_engine_bytes_decoded_total``."""
        self.blocks_decoded += int(blocks)
        self.bytes_decoded += int(nbytes)

    def skipped(self, blocks: int) -> None:
        """Mirrors ``mri_engine_blocks_skipped_total``."""
        self.blocks_skipped += int(blocks)

    def cache_event(self, key, hit: bool, cache: str = "") -> None:
        """Mirrors ``<cache>_{hits,misses}_total`` for one probe;
        ``key`` is the lex index, joinable against :meth:`term`."""
        if hit:
            self.cache_hits += 1
        else:
            self.cache_misses += 1
        if not isinstance(key, (int, str)):
            try:
                key = int(key)  # numpy integer keys
            except (TypeError, ValueError):
                key = str(key)
        self.cache_events.append(
            {"cache": cache, "key": key, "hit": bool(hit)})

    def ranked(self, mode: str, scored: int, skipped: int,
               candidates: int) -> None:
        """Mirrors ``Planner.note_ranked``'s counter increments."""
        self.planner_mode = mode
        self.planner_scored += int(scored)
        self.planner_skipped += int(skipped)
        self.planner_candidates += int(candidates)

    def and_arm(self, arm: str) -> None:
        """Mirrors ``mri_planner_and_{gallop,merge}_total``."""
        self.and_arms.append(arm)

    def theta(self, value: float) -> None:
        """One point of the pruning threshold's progression."""
        self.thetas.append(float(value))

    def stage(self, name: str, us: float) -> None:
        """Per-stage wall time in microseconds (queue/coalesce/engine)."""
        self.stages_us[name] = round(float(us), 1)

    def child(self, segment: str) -> "Collector":
        """A per-segment child collector (multi-segment engines install
        it around each segment-engine call; totals roll up)."""
        c = Collector(op=self.op)
        self.segments.append((str(segment), c))
        return c

    # -- assembly -------------------------------------------------------

    def totals(self) -> dict:
        """Rolled-up counts (self plus all segment children): the
        numbers the parity gate sums against the registry."""
        t = {
            "blocks_decoded": self.blocks_decoded,
            "blocks_skipped": self.blocks_skipped,
            "bytes_decoded": self.bytes_decoded,
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "planner_blocks_scored": self.planner_scored,
            "planner_blocks_skipped": self.planner_skipped,
        }
        for _name, c in self.segments:
            for k, v in c.totals().items():
                t[k] += v
        return t

    def report(self) -> dict:
        """The structured JSON cost report for the explain surface."""
        rep: dict = {"op": self.op, "terms": self.terms}
        rep["planner"] = {
            "mode": self.planner_mode,
            "blocks_scored": self.planner_scored,
            "blocks_skipped": self.planner_skipped,
            "candidates": self.planner_candidates,
            "theta": self.thetas,
            "and_arms": self.and_arms,
        }
        rep["engine"] = {
            "blocks_decoded": self.blocks_decoded,
            "blocks_skipped": self.blocks_skipped,
            "bytes_decoded": self.bytes_decoded,
        }
        rep["cache"] = {
            "hits": self.cache_hits,
            "misses": self.cache_misses,
            "events": self.cache_events,
        }
        if self.stages_us:
            rep["stages_us"] = dict(self.stages_us)
        if self.segments:
            rep["segments"] = [
                {"segment": name, **c.report()}
                for name, c in self.segments
            ]
        rep["totals"] = self.totals()
        return rep


class FlightRecorder:
    """Bounded ring of completed request records + slow offenders.

    Each entry is ``{"trace": <trace dict>, "report": <cost report or
    None>}``; slow requests (``dur_ms >= slow_threshold_ms > 0``) are
    additionally retained in a separate offenders ring so one burst of
    fast traffic cannot evict the evidence.  ``capacity == 0`` disables
    recording entirely (every method is a cheap no-op).
    """

    def __init__(self, capacity: int | None = None,
                 slow_threshold_ms: float = 0.0):
        cap = capacity if capacity is not None else flight_ring_capacity()
        self.capacity = max(0, int(cap))
        self.slow_threshold_ms = float(slow_threshold_ms)
        self._lock = threading.Lock()
        self._dq: deque = deque(
            maxlen=max(1, self.capacity))  # guarded by: self._lock
        self._slow: deque = deque(
            maxlen=max(1, self.capacity))  # guarded by: self._lock

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def record(self, trace: dict, report: dict | None = None) -> None:
        if self.capacity <= 0:
            return
        entry = {"trace": trace, "report": report}
        with self._lock:
            self._dq.append(entry)
            dur = trace.get("dur_ms")
            if (self.slow_threshold_ms > 0 and dur is not None
                    and dur >= self.slow_threshold_ms):
                self._slow.append(entry)

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)

    def dump(self, reason: str) -> dict:
        """One self-describing JSON document (most-recent-first)."""
        with self._lock:
            recent = list(self._dq)
            slow = list(self._slow)
        recent.reverse()
        slow.reverse()
        return {
            "event": "flight_dump",
            "reason": reason,
            "pid": os.getpid(),
            "ts": time.time(),
            "capacity": self.capacity,
            "slow_threshold_ms": self.slow_threshold_ms,
            "requests": recent,
            "slow": slow,
        }

    def dump_to_file(self, where: str, reason: str) -> str | None:
        """Write :meth:`dump` as ``flight-<pid>-<reason>.json`` under
        ``where`` (a directory, or a file whose directory is used).
        Crash-path safe: returns the path, or ``None`` — never raises.
        """
        if self.capacity <= 0:
            return None
        try:
            d = where if os.path.isdir(where) else os.path.dirname(
                os.path.abspath(where))
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in reason) or "dump"
            path = os.path.join(d, f"flight-{os.getpid()}-{safe}.json")
            tmp = path + ".tmp"
            # mrilint: allow(fault-boundary) crash-path black-box dump, not corpus I/O; any failure returns None
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.dump(reason), f, separators=(",", ":"))
            os.replace(tmp, path)
            return path
        except Exception:
            return None
