"""Chrome ``trace_event`` export for the build pipeline.

``mri build --trace-out FILE`` reconstructs the pipelined build as a
flame chart loadable in ``chrome://tracing`` / Perfetto: the reader
thread's per-window reads, each scan worker's window scans, every
reducer's emit range, the merge, and the artifact pack — one complete
("X"-phase) span per event, timestamped off ``time.perf_counter``.

Thread ids follow a fixed scheme so lanes sort sensibly:
``MAIN``=0, scan worker *w* = 1+w, reader *w* = 100+w, reducer *r* =
200+r.  :meth:`TraceEvents.name_thread` attaches the human-readable
lane names via ``"M"`` metadata events.
"""

from __future__ import annotations

import json
import os
import threading

MAIN = 0
SCAN_BASE = 1
READER_BASE = 100
REDUCE_BASE = 200


class TraceEvents:
    """Thread-safe collector of complete spans; write() emits the
    ``{"traceEvents": [...]}`` JSON Chrome and Perfetto load."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: list[dict] = []  # guarded by: self._lock
        self._names: dict[int, str] = {}  # guarded by: self._lock

    def name_thread(self, tid: int, name: str) -> None:
        with self._lock:
            self._names[tid] = name

    def span(self, name: str, t0: float, t1: float, *, tid: int = MAIN,
             args: dict | None = None) -> None:
        """One complete span; t0/t1 are ``time.perf_counter`` seconds."""
        ev = {
            "name": name,
            "ph": "X",
            "ts": t0 * 1e6,
            "dur": max(0.0, (t1 - t0) * 1e6),
            "pid": 0,
            "tid": tid,
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def count(self, name: str | None = None) -> int:
        with self._lock:
            if name is None:
                return len(self._events)
            return sum(1 for e in self._events if e["name"] == name)

    def write(self, path: str) -> None:
        """Write the trace JSON (timestamps rebased to start near 0)."""
        with self._lock:
            events = [dict(e) for e in self._events]
            names = dict(self._names)
        base = min((e["ts"] for e in events), default=0.0)
        for e in events:
            e["ts"] = round(e["ts"] - base, 3)
            e["dur"] = round(e["dur"], 3)
        meta = [{
            "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
            "args": {"name": "mri build"},
        }]
        for tid in sorted(names):
            meta.append({
                "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                "args": {"name": names[tid]},
            })
        doc = {"traceEvents": meta + events, "displayTimeUnit": "ms",
               "otherData": {"pid": os.getpid()}}
        tmp = f"{path}.tmp.{os.getpid()}"
        # mrilint: allow(fault-boundary) post-run export, outside the fault envelope
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, separators=(",", ":"))
        os.replace(tmp, path)
