"""Op/phase timers unified over the obs histogram.

Historically the repo had two near-duplicate aggregators: the serve
engines' ``OpTimer`` (per-op call count + total seconds, previously in
``serve/engine.py``) and the build pipeline's ``PhaseTimer``
(``utils/timing.py``).  Both now record through
:class:`~.metrics.Histogram`, so every timed op/phase gets a latency
distribution (exact quantiles under the sample cap) for free, while
the legacy ``stats()`` / ``report()`` dict shapes stay byte-identical.
The old import paths remain as thin shims.
"""

from __future__ import annotations

import json
import time
from contextlib import contextmanager

from . import metrics


class OpTimer:
    """Per-op latency accounting for the serve engines.

    ``stats()`` keeps the historical shape (``calls`` / ``total_ms`` /
    ``avg_us`` per op, sorted by op name); when constructed with a
    :class:`~.metrics.Registry`, each op's histogram is registered as
    ``<prefix>_<op>_seconds`` and shows up in the Prometheus text.
    """

    def __init__(self, registry: metrics.Registry | None = None,
                 prefix: str = "mri_engine_op"):
        self._registry = registry if registry is not None \
            else metrics.Registry()
        self._prefix = prefix
        self._hists: dict[str, metrics.Histogram] = {}

    def _hist(self, op: str) -> metrics.Histogram:
        h = self._hists.get(op)
        if h is None:
            h = self._registry.histogram(f"{self._prefix}_{op}_seconds")
            self._hists[op] = h
        return h

    @contextmanager
    def time(self, op: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._hist(op).observe(time.perf_counter() - t0)

    def histogram(self, op: str) -> metrics.Histogram:
        """The op's latency histogram, for callers that inline their
        timing — a hot path observes directly instead of paying the
        context-manager machinery per call."""
        return self._hist(op)

    def stats(self) -> dict:
        out = {}
        for op in sorted(self._hists):
            h = self._hists[op]
            calls, secs = h.count, h.sum
            if not calls:
                continue
            out[op] = {
                "calls": calls,
                "total_ms": round(secs * 1e3, 3),
                "avg_us": round(secs / calls * 1e6, 2),
            }
        return out

    def quantile_ms(self, op: str, p: float) -> float:
        """p-th percentile of one op's latency in ms (nan if unseen)."""
        h = self._hists.get(op)
        return h.quantile(p) * 1e3 if h is not None else float("nan")

    def reset(self) -> None:
        for h in self._hists.values():
            h.reset()
        self._hists.clear()


class PhaseTimer:
    """Wall-clock phase accounting for one build run.

    ``self.phases`` stays a plain mutable dict (callers assign into it
    for abort bookkeeping); each ``phase()`` observation additionally
    lands in a histogram so repeated phases expose a distribution.
    """

    def __init__(self):
        self.phases: dict[str, float] = {}
        self.counters: dict = {}
        self._hists: dict[str, metrics.Histogram] = {}

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.phases[name] = self.phases.get(name, 0.0) + dt
            h = self._hists.get(name)
            if h is None:
                h = metrics.Histogram(f"mri_build_phase_{name}_seconds")
                self._hists[name] = h
            h.observe(dt)

    def count(self, name: str, value) -> None:
        """Record a scalar alongside the timings (sets, not adds)."""
        self.counters[name] = value

    def histogram(self, name: str) -> metrics.Histogram | None:
        return self._hists.get(name)

    @property
    def total_seconds(self) -> float:
        return sum(self.phases.values())

    def report(self) -> dict:
        out = {
            "phases_ms": {k: round(v * 1e3, 3)
                          for k, v in self.phases.items()},
            "total_ms": round(self.total_seconds * 1e3, 3),
        }
        out.update(self.counters)
        return out

    def dumps(self) -> str:
        return json.dumps(self.report(), sort_keys=True)
