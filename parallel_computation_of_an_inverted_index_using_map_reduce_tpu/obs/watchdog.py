"""Daemon watchdog: heartbeat stamps + a stall-detecting monitor.

``healthz`` answers inline from the reader threads by design, which
means a wedged dispatcher looks perfectly healthy from the outside
while every query queues to death.  The watchdog closes that gap:
monitored threads (dispatcher, accept loop) stamp a monotonic
heartbeat each loop iteration — including the idle path, so quiet is
never mistaken for stalled — and a monitor thread fires once per
stall episode when a heartbeat ages past ``MRI_OBS_STALL_MS``:

* bumps ``mri_watchdog_stalls_total``,
* invokes the daemon's ``on_stall`` callback (structured stall event
  + FlightRecorder dump with reason ``stall``), and
* keeps the thread listed in :meth:`stalled` until its heartbeat
  resumes, which is what flips ``healthz`` readiness to ``stalled``
  and back.

``beat()`` is one lock-free float store into a dict slot — cheap
enough for the dispatcher's inner loop.  Stdlib-only by design.
"""

from __future__ import annotations

import threading
import time

from ..utils import envknobs
from . import metrics as obs_metrics

STALL_ENV = "MRI_OBS_STALL_MS"

STALLS_TOTAL = "mri_watchdog_stalls_total"


def stall_ms() -> float:
    return envknobs.get(STALL_ENV)


class Watchdog:
    """Heartbeat registry + monitor thread.

    ``on_stall(name, age_ms)`` runs on the monitor thread, once per
    stall episode; exceptions from it are swallowed — detection must
    never take the monitor down.  ``stall_ms == 0`` disables the
    monitor entirely (``start()`` is a no-op, nothing ever stalls).
    """

    def __init__(self, stall_ms_: float | None = None, on_stall=None,
                 on_recover=None,
                 registry: obs_metrics.Registry | None = None,
                 clock=time.monotonic):
        self.stall_ms = float(stall_ms_ if stall_ms_ is not None
                              else stall_ms())
        self.on_stall = on_stall
        self.on_recover = on_recover
        self.registry = registry
        self._clock = clock
        self._beats: dict = {}         # name -> last monotonic stamp
        self._lock = threading.Lock()
        self._stalled: set = set()     # guarded by: self._lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def enabled(self) -> bool:
        return self.stall_ms > 0

    def register(self, name: str) -> None:
        """Create the slot (counts as a fresh beat)."""
        self._beats[name] = self._clock()

    def beat(self, name: str) -> None:
        """Stamp one heartbeat — a single dict-slot float store."""
        self._beats[name] = self._clock()

    def ages_ms(self) -> dict:
        now = self._clock()
        return {n: (now - t) * 1e3 for n, t in self._beats.items()}

    def max_age_s(self) -> float:
        ages = self.ages_ms()
        return max(ages.values()) / 1e3 if ages else 0.0

    def stalled(self) -> list:
        """Names currently past the stall threshold (sorted)."""
        with self._lock:
            return sorted(self._stalled)

    # -- monitor thread -------------------------------------------------

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mri-obs-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def check(self) -> list:
        """One monitor pass (public for tests): fire newly stalled
        threads, clear recovered ones, return the stalled list."""
        if not self.enabled:
            return []
        fired, recovered = [], []
        ages = self.ages_ms()
        with self._lock:
            for name, age in ages.items():
                if age > self.stall_ms:
                    if name not in self._stalled:
                        self._stalled.add(name)
                        fired.append((name, age))
                elif name in self._stalled:
                    self._stalled.discard(name)
                    recovered.append(name)
            current = sorted(self._stalled)
        for name, age in fired:
            if self.registry is not None:
                self.registry.counter(STALLS_TOTAL).inc()
            if self.on_stall is not None:
                try:
                    self.on_stall(name, age)
                except Exception:  # noqa: BLE001 — detection must survive
                    pass
        for name in recovered:
            if self.on_recover is not None:
                try:
                    self.on_recover(name)
                except Exception:  # noqa: BLE001 — detection must survive
                    pass
        return current

    def _run(self) -> None:
        # 4 checks per stall threshold: detection lag stays well under
        # the 2x flip bound the healthz contract promises
        interval = max(0.01, min(1.0, self.stall_ms / 4e3))
        while not self._stop.wait(interval):
            self.check()
