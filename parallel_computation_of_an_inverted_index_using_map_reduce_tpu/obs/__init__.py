"""Unified observability layer: metrics, tracing, trace export.

- :mod:`.metrics` — thread-safe Counter/Gauge/Histogram + Registry
  with Prometheus text exposition (stdlib-only, standalone-loadable).
- :mod:`.timing` — OpTimer / PhaseTimer unified over the histogram.
- :mod:`.tracing` — per-request trace ids, trace ring, slow-query log.
- :mod:`.attribution` — request-scoped cost collector (the EXPLAIN
  surface) and the crash-dump flight recorder.
- :mod:`.chrometrace` — Chrome ``trace_event`` export for builds.
"""

from .chrometrace import TraceEvents
from .metrics import (Counter, Gauge, Histogram, KNOWN_METRICS, Registry,
                      default_registry)
from .timing import OpTimer, PhaseTimer
from .tracing import TraceRing, gen_trace_id

__all__ = [
    "Counter", "Gauge", "Histogram", "KNOWN_METRICS", "OpTimer",
    "PhaseTimer", "Registry", "TraceEvents", "TraceRing",
    "default_registry", "gen_trace_id",
]
