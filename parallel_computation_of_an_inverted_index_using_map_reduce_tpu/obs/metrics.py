"""Thread-safe metrics registry: counters, gauges, log-bucket histograms.

The single telemetry substrate for the repo.  Every serve-plane tally
(daemon admission counters, engine decode counters, cache hit/miss,
per-op latency) is an object from this module; the legacy ``stats`` /
``describe()`` dicts are views over it, and ``Registry.render_text()``
exposes the same numbers in Prometheus text-exposition format.

Deliberately stdlib-only and free of package-relative imports: the
mrilint ``obs-metrics`` repo check file-loads this module standalone
(exactly as ``readme_knobs`` loads ``envknobs``) to regenerate and
drift-check the README metrics-name table from :data:`KNOWN_METRICS`.

Registries are cheap instance objects, not process singletons: each
daemon and each engine owns one, so two daemons in one test process
never share counts and a hot reload starts the new engine's telemetry
from zero (matching the historical ``describe()`` semantics).  The one
process-global registry, :func:`default_registry`, exists only for
truly process-wide events — fault-injection firings.
"""

from __future__ import annotations

import bisect
import math
import threading
import time


class Counter:
    """Monotonic (but resettable) counter with its own lock."""

    __slots__ = ("name", "help", "_lock", "_n")

    def __init__(self, name: str, help: str = ""):  # noqa: A002
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._n = 0  # guarded by: self._lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._n += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._n

    def reset(self) -> None:
        """Zero the counter.  Exists for the legacy ``cache.clear()``
        and ``OpTimer.reset()`` contracts, which reset their tallies."""
        with self._lock:
            self._n = 0


class Gauge:
    """A value that goes up and down (queue depth, vocab size)."""

    __slots__ = ("name", "help", "_lock", "_v")

    def __init__(self, name: str, help: str = ""):  # noqa: A002
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._v = 0.0  # guarded by: self._lock

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


#: Raw samples retained per histogram for exact quantiles.  Past the
#: cap the histogram stops retaining (buckets/sum/count stay exact,
#: quantiles fall back to the retained prefix and are flagged).
SAMPLE_CAP = 65536


class Histogram:
    """Fixed log-spaced buckets plus a capped raw-sample buffer.

    Buckets are ``base * growth**i`` upper bounds (``le`` semantics,
    like Prometheus); the defaults span 1 us .. ~68 s, which covers
    every op latency in this repo.  While under :data:`SAMPLE_CAP`
    observations, :meth:`quantile` is *exact* (numpy linear
    interpolation over the raw samples), not a bucket estimate.
    """

    __slots__ = ("name", "help", "_lock", "_bounds", "_counts",
                 "_count", "_sum", "_min", "_max", "_samples",
                 "_truncated", "_exemplars")

    def __init__(self, name: str, help: str = "", *,  # noqa: A002
                 base: float = 1e-6, growth: float = 2.0,
                 nbuckets: int = 27):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._bounds = [base * growth ** i for i in range(nbuckets)]
        # one slot per bound plus the +Inf overflow slot
        self._counts = [0] * (nbuckets + 1)  # guarded by: self._lock
        self._count = 0  # guarded by: self._lock
        self._sum = 0.0  # guarded by: self._lock
        self._min = math.inf  # guarded by: self._lock
        self._max = -math.inf  # guarded by: self._lock
        self._samples: list[float] = []  # guarded by: self._lock
        self._truncated = False  # guarded by: self._lock
        # per-bucket (trace_id, value, unix_ts) of a recent
        # representative observation; allocated on first exemplar so
        # exemplar-free histograms pay nothing
        self._exemplars: list | None = None  # guarded by: self._lock

    def observe(self, v: float, exemplar: str | None = None) -> None:
        """Record ``v``; ``exemplar`` optionally attaches a trace id as
        the bucket's OpenMetrics exemplar (last writer wins, which
        keeps each bucket's exemplar recent)."""
        v = float(v)
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if len(self._samples) < SAMPLE_CAP:
                self._samples.append(v)
            else:
                self._truncated = True
            if exemplar is not None:
                if self._exemplars is None:
                    self._exemplars = [None] * len(self._counts)
                self._exemplars[i] = (str(exemplar), v, time.time())

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def bounds(self) -> list[float]:
        return list(self._bounds)

    def cumulative_counts(self) -> list[int]:
        """Per-bound cumulative counts (observations <= bound), one
        entry per bound plus the final +Inf total — the shape of the
        Prometheus ``_bucket`` series."""
        with self._lock:
            out, acc = [], 0
            for c in self._counts:
                acc += c
                out.append(acc)
            return out

    def quantile(self, p: float) -> float:
        """p-th percentile (0..100), numpy ``linear`` interpolation.

        Exact while the raw-sample buffer is complete; past
        :data:`SAMPLE_CAP` it interpolates over the retained prefix.
        """
        with self._lock:
            s = sorted(self._samples)
        if not s:
            return math.nan
        pos = (len(s) - 1) * (float(p) / 100.0)
        lo = int(math.floor(pos))
        frac = pos - lo
        hi = min(lo + 1, len(s) - 1)
        return s[lo] * (1.0 - frac) + s[hi] * frac

    @property
    def exact(self) -> bool:
        with self._lock:
            return not self._truncated

    def exemplars(self) -> list:
        """Per-bucket exemplar snapshot (one slot per bound plus +Inf);
        ``None`` slots have never seen an exemplar."""
        with self._lock:
            if self._exemplars is None:
                return [None] * len(self._counts)
            return list(self._exemplars)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self._counts)
            self._count = 0
            self._sum = 0.0
            self._min = math.inf
            self._max = -math.inf
            self._samples = []
            self._truncated = False
            self._exemplars = None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
            }


#: Canonical metric documentation: (name, kind, meaning).  The README
#: "Observability" table is generated from this tuple (and
#: drift-checked by mrilint's ``obs-metrics`` repo check).  Names with
#: ``<..>`` placeholders describe dynamically-created families.
KNOWN_METRICS = (
    # daemon admission / dispatch plane
    ("mri_serve_requests_total", "counter",
     "Data requests admitted past validation (the legacy `requests`)."),
    ("mri_serve_responses_total", "counter",
     "Response lines written back to clients."),
    ("mri_serve_shed_total", "counter",
     "Requests shed by admission control (pending queue full)."),
    ("mri_serve_deadline_expired_total", "counter",
     "Requests whose `deadline_ms` passed before dispatch."),
    ("mri_serve_draining_rejected_total", "counter",
     "Requests rejected because the daemon was draining."),
    ("mri_serve_bad_request_total", "counter",
     "Malformed lines and unknown ops."),
    ("mri_serve_internal_errors_total", "counter",
     "Requests that failed inside the engine."),
    ("mri_serve_client_disconnects_total", "counter",
     "Connections that dropped mid-write."),
    ("mri_serve_slow_client_closes_total", "counter",
     "Connections closed for not draining their response queue."),
    ("mri_serve_reload_ok_total", "counter",
     "Successful hot reloads (engine swapped)."),
    ("mri_serve_reload_rejected_total", "counter",
     "Hot reloads rejected; the old artifact kept serving."),
    ("mri_serve_batches_total", "counter",
     "Coalesced micro-batches dispatched to the engine."),
    ("mri_serve_batched_requests_total", "counter",
     "Requests executed inside those micro-batches."),
    ("mri_serve_connections_total", "counter",
     "Client connections accepted."),
    ("mri_serve_queue_depth", "gauge",
     "Pending-queue depth at scrape time."),
    ("mri_serve_inflight", "gauge",
     "Admitted-but-unanswered requests at scrape time."),
    ("mri_serve_draining", "gauge",
     "1 while the daemon is draining, else 0."),
    ("mri_serve_request_seconds", "histogram",
     "End-to-end data-request latency (admission to response enqueue)."),
    ("mri_serve_queue_wait_seconds", "histogram",
     "Time spent waiting in the pending queue before dispatch pop."),
    # engine-side caches (per-engine registry)
    ("mri_serve_cache_hits_total", "counter",
     "Postings LRU cache hits."),
    ("mri_serve_cache_misses_total", "counter",
     "Postings LRU cache misses."),
    ("mri_serve_cache_evictions_total", "counter",
     "Postings LRU cache evictions."),
    ("mri_serve_tf_cache_hits_total", "counter",
     "Term-frequency LRU cache hits (BM25 path)."),
    ("mri_serve_tf_cache_misses_total", "counter",
     "Term-frequency LRU cache misses."),
    ("mri_serve_tf_cache_evictions_total", "counter",
     "Term-frequency LRU cache evictions."),
    # engine decode plane
    ("mri_engine_blocks_decoded_total", "counter",
     "v2 posting blocks (v1: whole lists) bit-unpacked."),
    ("mri_engine_blocks_skipped_total", "counter",
     "v2 posting blocks skipped via the block-max table."),
    ("mri_engine_bytes_decoded_total", "counter",
     "Bytes materialized by posting decode."),
    ("mri_engine_vocab_terms", "gauge",
     "Vocabulary size of the loaded artifact."),
    ("mri_engine_artifact_bytes", "gauge",
     "On-disk size of the loaded artifact."),
    ("mri_engine_op_<op>_seconds", "histogram",
     "Per-op engine latency (df, postings, and, or, top_k, ...)."),
    # query planner (per-engine registry)
    ("mri_planner_ranked_exhaustive_total", "counter",
     "Ranked queries the planner scored exhaustively."),
    ("mri_planner_ranked_bmw_total", "counter",
     "Ranked queries evaluated with Block-Max WAND pruning."),
    ("mri_planner_ranked_maxscore_total", "counter",
     "Ranked queries evaluated with MaxScore pruning."),
    ("mri_planner_and_gallop_total", "counter",
     "AND intersection steps taken by the galloping-probe arm."),
    ("mri_planner_and_merge_total", "counter",
     "AND intersection steps taken by the linear-merge arm."),
    ("mri_planner_blocks_scored_total", "counter",
     "Posting blocks pruned ranked evaluation had to score."),
    ("mri_planner_blocks_skipped_total", "counter",
     "Posting blocks whose max-score bound kept them unscored."),
    # incremental indexing (segment-managed dirs; daemon + engine)
    ("mri_segments_active", "gauge",
     "Segments in the live manifest generation."),
    ("mri_generation", "gauge",
     "Generation number of the live segment manifest."),
    ("mri_compactions_total", "counter",
     "Segment compactions completed (runs merged + published)."),
    ("mri_tombstoned_docs", "gauge",
     "Documents masked by tombstone bitmaps in the live generation."),
    ("mri_serve_mutations_total", "counter",
     "Live mutations (append/delete/compact) applied by the daemon."),
    ("mri_serve_mutation_rejected_total", "counter",
     "Live mutations rejected; the old generation kept serving."),
    # durability & replication (WAL + segment shipping; daemon registry)
    ("mri_wal_records_total", "counter",
     "Mutation WAL records fsync'd (the durability point every "
     "acknowledgement waits on)."),
    ("mri_wal_replayed_total", "counter",
     "WAL records applied by crash recovery (acknowledged mutations "
     "rolled forward after a crash)."),
    ("mri_replica_lag_generations", "gauge",
     "Manifest generations a replica was behind its primary at the "
     "last successful catch-up round (0 = caught up)."),
    ("mri_serve_stale_generation_total", "counter",
     "Requests refused because the client's min_generation token is "
     "ahead of the serving generation (read-your-writes fence)."),
    # operational health (rolling SLIs, SLOs, watchdog; daemon registry)
    ("mri_slo_<slo>_ratio_<window>", "gauge",
     "Rolling good-event ratio of one SLO (availability, latency) "
     "over one window (10s, 1m, 5m); 1 when the window saw no events."),
    ("mri_slo_<slo>_burn_<window>", "gauge",
     "SLO burn rate over one window: error-rate / error-budget, where "
     "the budget is 1 - MRI_OBS_SLO_TARGET; above 1 the daemon burns "
     "its budget faster than the objective allows."),
    ("mri_watchdog_stalls_total", "counter",
     "Watchdog-detected stalls: a monitored daemon thread's heartbeat "
     "aged past MRI_OBS_STALL_MS."),
    ("mri_watchdog_heartbeat_age_seconds", "gauge",
     "Age of the oldest monitored-thread heartbeat at scrape time."),
    ("mri_obs_log_dropped_total", "counter",
     "Structured log records dropped by the per-event rate limiter "
     "(MRI_OBS_LOG_RATE_LIMIT)."),
    # fault injection (process-global default registry)
    ("mri_faults_fired_total", "counter",
     "Fault-injection rules fired, all kinds."),
    ("mri_fault_<kind>_fired_total", "counter",
     "Fault-injection firings of one kind (read_error, ...)."),
    # scale-out cluster (router registry: the admission plane reuses
    # the mri_serve_* families above — the router is a serve-plane
    # daemon, so SLO/windows/top math applies unchanged — while shard
    # families arrive in the router scrape labelled
    # {shard="K",replica="R"} via merge_expositions label injection)
    ("mri_cluster_shards", "gauge",
     "Doc-shards the router scatters every data op to."),
    ("mri_cluster_replicas_ready", "gauge",
     "Replica endpoints whose last health probe answered ready."),
    ("mri_router_scatter_rpcs_total", "counter",
     "Shard RPCs issued by scatter fan-out (hedges/retries included)."),
    ("mri_cluster_hedges_total", "counter",
     "Hedge RPCs fired after MRI_CLUSTER_HEDGE_MS (or the shard's "
     "rolling p95) with no primary answer."),
    ("mri_cluster_hedge_wins_total", "counter",
     "Hedged shard RPCs the hedge replica answered first."),
    ("mri_cluster_failovers_total", "counter",
     "Shard RPCs re-routed to another replica after a connection "
     "failure or a not-ready health probe."),
    ("mri_cluster_shard_errors_total", "counter",
     "Shard RPC failures (connection loss / error responses) the "
     "router observed before any retry."),
    # brownout degradation plane (router + daemon registries)
    ("mri_cluster_shard_unavailable_total", "counter",
     "Requests failed with the typed shard_unavailable error: a "
     "shard's replica set was exhausted (or its leg timed out) under "
     "partial_policy=fail, or coverage fell below min_coverage."),
    ("mri_cluster_partial_total", "counter",
     "Degraded answers served with partial=true coverage metadata "
     "(partial_policy=allow riding out missing shards)."),
    ("mri_cluster_retry_denied_total", "counter",
     "Retries/hedges suppressed by the per-shard retry budget "
     "(MRI_CLUSTER_RETRY_BUDGET token bucket empty)."),
    ("mri_cluster_breakers_open", "gauge",
     "Replica circuit breakers currently not closed (open or "
     "half-open) across every shard."),
    ("mri_cluster_breaker_state_s<shard>_r<replica>", "gauge",
     "One replica's circuit-breaker state: 0 closed, 1 half-open, "
     "2 open."),
    ("mri_serve_codel_sheds_total", "counter",
     "Requests shed by CoDel adaptive admission (typed overloaded "
     "answer): queue delay stayed over MRI_SERVE_CODEL_TARGET_MS for "
     "a full interval."),
    ("mri_serve_codel_state", "gauge",
     "CoDel admission controller state: 1 while in the dropping "
     "regime, else 0."),
)

_HELP = {name: help for name, _kind, help in KNOWN_METRICS}


def _fmt(v) -> str:
    """Prometheus sample value: integers without a trailing .0."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _exemplar_suffix(ex) -> str:
    """OpenMetrics exemplar suffix for one bucket line ('' when none)."""
    if ex is None:
        return ""
    trace_id, v, ts = ex
    return f' # {{trace_id="{trace_id}"}} {_fmt(v)} {ts:.3f}'


class Registry:
    """Get-or-create home for named metrics plus the text renderer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}  # guarded by: self._lock

    def _get(self, name: str, cls, help: str, **kw):  # noqa: A002
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help or _HELP.get(name, ""), **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered "
                                f"as {type(m).__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:  # noqa: A002
        return self._get(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:  # noqa: A002
        return self._get(name, Gauge, help)

    def histogram(self, name: str, help: str = "", **kw) -> Histogram:  # noqa: A002
        return self._get(name, Histogram, help, **kw)

    def metrics(self) -> list:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def render_text(self, *, exemplars: bool = False) -> str:
        """Prometheus text exposition (``# TYPE``-annotated).

        With ``exemplars=True``, histogram bucket lines that have seen
        an exemplar carry an OpenMetrics exemplar suffix —
        ``... # {trace_id="<id>"} <value> <unix_ts>`` — linking the
        bucket to a recent representative request in the trace ring.
        Plain-Prometheus scrapers that split on whitespace and skip
        ``{``-labelled names are unaffected (the suffix sits after the
        sample value).
        """
        out = []
        for m in self.metrics():
            if isinstance(m, Counter):
                if m.help:
                    out.append(f"# HELP {m.name} {m.help}")
                out.append(f"# TYPE {m.name} counter")
                out.append(f"{m.name} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                if m.help:
                    out.append(f"# HELP {m.name} {m.help}")
                out.append(f"# TYPE {m.name} gauge")
                out.append(f"{m.name} {_fmt(m.value)}")
            elif isinstance(m, Histogram):
                if m.help:
                    out.append(f"# HELP {m.name} {m.help}")
                out.append(f"# TYPE {m.name} histogram")
                cum = m.cumulative_counts()
                exm = m.exemplars() if exemplars else [None] * (
                    len(cum) + 1)
                for bound, c, ex in zip(m.bounds, cum, exm):
                    line = f'{m.name}_bucket{{le="{repr(bound)}"}} {c}'
                    out.append(line + _exemplar_suffix(ex))
                out.append(f'{m.name}_bucket{{le="+Inf"}} {cum[-1]}'
                           + _exemplar_suffix(exm[len(cum) - 1]
                                              if exemplars else None))
                out.append(f"{m.name}_sum {_fmt(m.sum)}")
                out.append(f"{m.name}_count {m.count}")
        return "\n".join(out) + "\n" if out else ""

    def as_dict(self) -> dict:
        """Scalar view: counter/gauge values and histogram snapshots."""
        out = {}
        for m in self.metrics():
            if isinstance(m, Histogram):
                out[m.name] = m.snapshot()
            else:
                out[m.name] = m.value
        return out


def _label_sample(line: str, label_txt: str) -> str:
    """Inject a rendered label set into one sample line, preserving
    existing labels (histogram ``le``) and any exemplar suffix."""
    head, sep, ex = line.partition(" # ")
    try:
        body, val = head.rsplit(" ", 1)
    except ValueError:
        return line
    if body.endswith("}"):
        body = body[:-1] + "," + label_txt + "}"
    else:
        body = body + "{" + label_txt + "}"
    return body + " " + val + (sep + ex if sep else "")


def merge_expositions(parts, labels=None) -> str:
    """Concatenate text expositions into one legal exposition.

    Unlabelled parts keep the historical semantics: later duplicate
    metric families are dropped by name (first occurrence wins).
    Several registries can legitimately carry the same family — e.g.
    the serve daemon's own registry and a multi-segment engine's both
    track ``mri_generation`` — but one exposition must name each
    family's ``# HELP``/``# TYPE`` exactly once.

    ``labels`` (optional, parallel to ``parts``) maps a part to a
    label dict (or None) injected into every one of its sample lines —
    the scatter-gather router merges its own registry with D shard
    scrapes whose families all collide, so per-part ``{shard="K"}``
    labels keep every series while HELP/TYPE stay deduplicated.
    """
    seen: set[str] = set()
    out: list[str] = []
    for pi, text in enumerate(parts):
        if not text:
            continue
        part_labels = labels[pi] if labels is not None else None
        label_txt = ",".join(
            f'{k}="{v}"' for k, v in part_labels.items()) \
            if part_labels else ""
        keep = True
        for line in text.splitlines():
            if line.startswith(("# HELP ", "# TYPE ")):
                name = line.split(" ", 3)[2]
                if line.startswith("# TYPE "):
                    keep = name not in seen
                    seen.add(name)
                else:
                    # HELP precedes TYPE: peek whether its family is new
                    keep = name not in seen
                if keep:
                    out.append(line)
                continue
            if label_txt:
                # labelled samples always survive — the labels are the
                # disambiguation — only their HELP/TYPE dedups above
                out.append(_label_sample(line, label_txt))
            elif keep:
                out.append(line)
    return "\n".join(out) + "\n" if out else ""


_default = Registry()


def default_registry() -> Registry:
    """The process-global registry (fault firings only — everything
    serve-plane lives on per-daemon / per-engine registries)."""
    return _default


def markdown_table() -> str:
    """The README metrics-name table, generated from KNOWN_METRICS."""
    lines = ["| Metric | Type | Meaning |", "| --- | --- | --- |"]
    for name, kind, help in KNOWN_METRICS:  # noqa: A001
        lines.append(f"| `{name}` | {kind} | {help} |")
    return "\n".join(lines)
