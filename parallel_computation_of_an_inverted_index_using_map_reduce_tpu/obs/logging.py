"""Unified structured logging for the mri_tpu runtime.

Every runtime event the serve/obs layer reports — slow queries, stall
detections, reload outcomes — funnels through :func:`emit`: one JSON
payload per record (``{"event": ..., **fields}``), rate-limited per
``(logger, event)`` key so a pathological burst (every request slow,
a flapping watchdog) cannot flood stderr or the test log.  The record
*message* is always the compact JSON payload, so ``caplog``-style
consumers parse it identically in both output formats.

:func:`configure` (the serve daemon calls it at startup) attaches one
stderr handler to the ``mri_tpu`` logger tree and picks the rendering
from ``MRI_OBS_LOG_FORMAT``:

* ``text`` — classic ``LEVEL logger: message`` lines, and
* ``json`` — one self-describing JSON object per line (``ts``,
  ``level``, ``logger`` + the payload fields), ready for ingestion.

Dropped records are counted in ``mri_obs_log_dropped_total`` on the
process-global default registry — silence is never silent.

Stdlib-only by design (plus the sibling stdlib-only modules): import
must never pull jax/numpy.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from ..utils import envknobs
from . import metrics as obs_metrics

FORMAT_ENV = "MRI_OBS_LOG_FORMAT"
RATE_LIMIT_ENV = "MRI_OBS_LOG_RATE_LIMIT"

#: root of the runtime logger tree configure() attaches to
ROOT_LOGGER = "mri_tpu"

_HANDLER_TAG = "_mri_obs_handler"


def log_format() -> str:
    return envknobs.get(FORMAT_ENV)


def rate_limit() -> int:
    return envknobs.get(RATE_LIMIT_ENV)


class _RateLimiter:
    """Token bucket per key: ``limit`` records per rolling second."""

    def __init__(self, limit: int):
        self.limit = int(limit)
        self._lock = threading.Lock()
        self._state: dict = {}  # guarded by: self._lock

    def allow(self, key) -> bool:
        if self.limit <= 0:
            return True
        now = time.monotonic()
        with self._lock:
            sec, n = self._state.get(key, (0, 0))
            cur = int(now)
            if cur != sec:
                sec, n = cur, 0
            if n >= self.limit:
                self._state[key] = (sec, n)
                return False
            self._state[key] = (sec, n + 1)
            return True


_limiter: _RateLimiter | None = None
_limiter_lock = threading.Lock()


def _get_limiter() -> _RateLimiter:
    global _limiter
    with _limiter_lock:
        if _limiter is None or _limiter.limit != rate_limit():
            _limiter = _RateLimiter(rate_limit())
        return _limiter


def emit(logger: logging.Logger, event: str,
         level: int = logging.INFO, **fields) -> None:
    """The one funnel for runtime events: rate-limited, JSON payload.

    Never raises — a logging failure must not take a serving thread
    down with it.
    """
    try:
        if not _get_limiter().allow((logger.name, event)):
            obs_metrics.default_registry().counter(
                "mri_obs_log_dropped_total").inc()
            return
        payload = {"event": event, **fields}
        logger.log(level, "%s",
                   json.dumps(payload, separators=(",", ":"),
                              default=str))
    except Exception:  # noqa: BLE001 — logging must never crash serving
        pass


class JsonFormatter(logging.Formatter):
    """One JSON object per line: envelope + the record's payload.

    A message that is itself a JSON object (everything :func:`emit`
    produces) is merged into the envelope; anything else lands under
    ``msg`` so third-party records still serialize cleanly.
    """

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
        }
        msg = record.getMessage()
        try:
            payload = json.loads(msg)
        except ValueError:
            payload = None
        if isinstance(payload, dict):
            for k, v in payload.items():
                out.setdefault(k, v)
        else:
            out["msg"] = msg
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, separators=(",", ":"), default=str)


def configure(stream=None) -> logging.Handler:
    """Attach (or re-format) the single mri_tpu stderr handler.

    Idempotent: repeated calls swap the formatter in place instead of
    stacking handlers, so a test can flip ``MRI_OBS_LOG_FORMAT`` and
    reconfigure.  Returns the handler for tests.
    """
    root = logging.getLogger(ROOT_LOGGER)
    handler = None
    for h in root.handlers:
        if getattr(h, _HANDLER_TAG, False):
            handler = h
            break
    if handler is None:
        handler = logging.StreamHandler(stream)
        setattr(handler, _HANDLER_TAG, True)
        root.addHandler(handler)
        root.propagate = False
    elif stream is not None:
        handler.setStream(stream)
    if log_format() == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(levelname)s %(name)s: %(message)s"))
    if root.level == logging.NOTSET:
        root.setLevel(logging.INFO)
    return handler


def reset() -> None:
    """Detach the configure() handler (tests)."""
    root = logging.getLogger(ROOT_LOGGER)
    for h in list(root.handlers):
        if getattr(h, _HANDLER_TAG, False):
            root.removeHandler(h)
    root.propagate = True
