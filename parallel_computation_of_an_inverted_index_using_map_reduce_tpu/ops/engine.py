"""Single-chip device engine: the whole reduce phase as one XLA program.

The reference's reduce phase — re-parse spill text, linear-scan dict
dedup, qsort by (df desc, word asc), bubble-sort postings, format
(main.c:126-242) — becomes one jitted program over integer arrays:

    sort packed (term, doc) keys          ->  lax.sort (radix under XLA)
    per-(term, doc) dedup                 ->  boundary diff on sorted keys
    document frequency                    ->  segmented add
    postings lists (ascending, compact)   ->  cumsum + scatter
    final emit order (letter, -df, term)  ->  second key sort

Everything is fixed-shape; padding keys sort to the tail and are dropped
by bounds-checked scatters.  Control crosses host<->device exactly twice
(feed pairs, fetch postings) vs. the reference's per-token lock/IO
crossing (SURVEY.md §3.5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import keys as K
from .segment import compact, first_occurrence_mask, segment_counts


def emit_order_keys(letter_of_term, df, vocab_size: int, max_doc_id: int):
    """Sort key giving the reference's output order (main.c:55-64).

    Within a letter file: df descending, then word ascending — and term
    ids are assigned in sorted-vocab order, so ``term id asc == word
    asc`` and no strings are needed on device.
    """
    neg_df = (max_doc_id + 1) - df  # df <= max_doc_id + 1 always
    return letter_of_term, neg_df


@functools.partial(jax.jit, static_argnames=("vocab_size", "max_doc_id"), donate_argnums=(0,))
def index_packed(keys, letter_of_term, *, vocab_size: int, max_doc_id: int):
    """Index a batch of packed (term, doc) int32 keys.

    ``keys`` may be padded with ``K.INT32_MAX`` (sorts after every valid
    key since ``can_pack`` guarantees headroom).
    """
    stride = max_doc_id + 2
    valid_limit = vocab_size * stride
    keys_s = lax.sort(keys)
    term_s, doc_s = K.unpack_pairs(keys_s, max_doc_id)
    first = first_occurrence_mask(keys_s) & (keys_s < valid_limit)
    df = segment_counts(term_s, first.astype(jnp.int32), vocab_size)
    postings = compact(doc_s, first, keys_s.shape[0], jnp.int32(0))

    letter, neg_df = emit_order_keys(letter_of_term, df, vocab_size, max_doc_id)
    if K.can_pack(vocab_size, max_doc_id) and 26 * stride * (vocab_size + 1) < np.iinfo(np.int32).max:
        emit_key = (letter * stride + neg_df) * vocab_size + jnp.arange(vocab_size, dtype=jnp.int32)
        _, order = lax.sort_key_val(emit_key, jnp.arange(vocab_size, dtype=jnp.int32))
    else:
        _, _, order = lax.sort(
            (letter, neg_df, jnp.arange(vocab_size, dtype=jnp.int32)), num_keys=2
        )
    offsets = jnp.cumsum(df) - df
    return {
        "postings": postings,
        "df": df,
        "order": order,
        "offsets": offsets,
        "num_unique": first.astype(jnp.int32).sum(),
    }


@functools.partial(jax.jit, static_argnames=("vocab_size", "max_doc_id"), donate_argnums=(0, 1))
def index_pairs(term_ids, doc_ids, letter_of_term, *, vocab_size: int, max_doc_id: int):
    """General path for corpora too large to pack into one int32 key.

    Two-key variadic sort; otherwise identical semantics to
    :func:`index_packed`.  Padding: term = INT32_MAX.
    """
    term_s, doc_s = lax.sort((term_ids, doc_ids), num_keys=2)
    valid = term_s < vocab_size
    first = (
        first_occurrence_mask(term_s) | first_occurrence_mask(doc_s)
    ) & valid
    df = segment_counts(jnp.where(valid, term_s, vocab_size), first.astype(jnp.int32), vocab_size)
    postings = compact(doc_s, first, term_s.shape[0], jnp.int32(0))
    letter, neg_df = emit_order_keys(letter_of_term, df, vocab_size, max_doc_id)
    _, _, order = lax.sort((letter, neg_df, jnp.arange(vocab_size, dtype=jnp.int32)), num_keys=2)
    offsets = jnp.cumsum(df) - df
    return {
        "postings": postings,
        "df": df,
        "order": order,
        "offsets": offsets,
        "num_unique": first.astype(jnp.int32).sum(),
    }
