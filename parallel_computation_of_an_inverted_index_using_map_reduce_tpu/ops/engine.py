"""Device engine: the whole reduce phase as one XLA program.

The reference's reduce phase — re-parse spill text, linear-scan dict
dedup, qsort by (df desc, word asc), bubble-sort postings, format
(main.c:126-242) — becomes one jitted program over integer arrays:

    sort packed (term, doc) keys          ->  lax.sort (radix under XLA)
    per-(term, doc) dedup                 ->  boundary diff on sorted keys
    document frequency                    ->  run-edge cumsum differences
    postings lists (ascending, compact)   ->  rank searchsorted + gather
    final emit order (letter, -df, term)  ->  second key sort

Everything is fixed-shape; padding keys sort to the tail and fall out of
the searchsorted edges (ops/segment.py — scatter-free by design: TPU
scatter serializes per update).  Control crosses host<->device exactly twice
(feed pairs, fetch postings) vs. the reference's per-token lock/IO
crossing (SURVEY.md §3.5).

The post-sort tail (:func:`postings_from_sorted`) is shared with the
multi-chip engine in ``parallel/dist_engine.py``, which reaches the same
sorted state via a hash-bucket ``all_to_all`` instead of one local sort.
"""

from __future__ import annotations

import functools
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import keys as K
from .segment import compact, first_occurrence_mask, sorted_segment_counts
from ..utils import envknobs


def _quiet_donation(fn):
    """Silence JAX's unusable-donation warning around one jitted entry.

    Feed buffers are donated so XLA reuses their device memory as sort
    scratch; the programs' *outputs* are deliberately narrower than the
    feeds, so no output can alias them and JAX warns at lowering time.
    Scoped per call so user code importing this library keeps the
    diagnostic for its own donations.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return fn(*args, **kwargs)

    for attr in ("clear_cache", "lower", "trace", "eval_shape"):  # jit API
        if hasattr(fn, attr):
            setattr(wrapper, attr, getattr(fn, attr))
    return wrapper

# Fused Pallas kernel for the dedup mask (ops/pallas/kernels.py):
#   "auto"  — compiled kernel on TPU, XLA elsewhere (default)
#   "force" — always (interpret mode off-TPU; used by tests)
#   "off"   — XLA everywhere
_PALLAS_MODE = envknobs.get("MRI_TPU_PALLAS")


def _dedup_mask(keys_s, valid_limit: int):
    """(mask, count) over ascending keys: first-occurrence & validity.

    Via the fused Pallas kernel when eligible (trace-time choice),
    which also yields the unique count for free; the XLA fallback
    returns ``count=None`` and callers reduce the mask instead.
    """
    if _PALLAS_MODE != "off":
        from .pallas import kernels as pk

        if pk.supports(keys_s.shape[0]) and (
            _PALLAS_MODE == "force" or jax.default_backend() == "tpu"
        ):
            return pk.unique_mask_count(keys_s, valid_limit)
    return first_occurrence_mask(keys_s) & (keys_s < valid_limit), None


def emit_order_keys(letter_of_term, df, max_doc_id: int):
    """Sort keys giving the reference's output order (main.c:55-64).

    Within a letter file: df descending, then word ascending — and term
    ids are assigned in sorted-vocab order, so ``term id asc == word
    asc`` and no strings are needed on device.
    """
    neg_df = (max_doc_id + 1) - df  # df <= max_doc_id + 1 always
    return letter_of_term, neg_df


def emit_order(letter_of_term, df, vocab_size: int, max_doc_id: int):
    """Term ids ordered (letter asc, df desc, term asc)."""
    letter, neg_df = emit_order_keys(letter_of_term, df, max_doc_id)
    stride = max_doc_id + 2
    terms = jnp.arange(vocab_size, dtype=jnp.int32)
    if 26 * stride * (vocab_size + 1) < np.iinfo(np.int32).max:
        emit_key = (letter * stride + neg_df) * vocab_size + terms
        _, order = lax.sort_key_val(emit_key, terms)
    else:
        # stable two-key sort; stability supplies the term-asc tiebreak
        _, _, order = lax.sort((letter, neg_df, terms), num_keys=2)
    return order


def host_order_offsets(letter_of_term, df):
    """Emit order + postings offsets computed on host from fetched df.

    Cheaper than fetching the device-computed versions over a slow
    device->host link: both are vocab-sized and derive from df alone.
    ``np.lexsort`` is stable, so full ties fall back to term id ascending
    == word ascending, matching main.c:55-64.
    """
    df64 = np.asarray(df).astype(np.int64)
    order = np.lexsort((-df64, np.asarray(letter_of_term)))
    offsets = np.cumsum(df64) - df64
    return order.astype(np.int64), offsets


def dedup_df_postings(keys_s, *, vocab_size: int, max_doc_id: int):
    """Shared post-sort block: per-(term, doc) dedup, document frequency,
    compacted postings — from an ascending packed-key array (may contain
    ``K.INT32_MAX`` padding, which sorts last and is dropped).

    Returns ``(first, df, postings, num_unique)``; the unique count
    comes fused from the Pallas kernel when it ran."""
    valid_limit = vocab_size * (max_doc_id + 2)
    term_s, doc_s = K.unpack_pairs(keys_s, max_doc_id)
    first, count = _dedup_mask(keys_s, valid_limit)
    df = sorted_segment_counts(term_s, first.astype(jnp.int32), vocab_size)
    postings = compact(doc_s, first, keys_s.shape[0], jnp.int32(0))
    num_unique = count if count is not None else first.astype(jnp.int32).sum()
    return first, df, postings, num_unique


def postings_from_sorted(keys_s, letter_of_term, *, vocab_size: int, max_doc_id: int):
    """Postings/df/order from an ascending packed-key array."""
    _, df, postings, num_unique = dedup_df_postings(
        keys_s, vocab_size=vocab_size, max_doc_id=max_doc_id)
    order = emit_order(letter_of_term, df, vocab_size, max_doc_id)
    offsets = jnp.cumsum(df) - df
    return {
        "postings": postings,
        "df": df,
        "order": order,
        "offsets": offsets,
        "num_unique": num_unique,
    }


@_quiet_donation
@functools.partial(jax.jit, static_argnames=("vocab_size", "max_doc_id"), donate_argnums=(0,))
def index_packed(keys, letter_of_term, *, vocab_size: int, max_doc_id: int):
    """Index a batch of packed (term, doc) int32 keys.

    ``keys`` may be padded with ``K.INT32_MAX`` (sorts after every valid
    key since ``can_pack`` guarantees headroom).
    """
    return postings_from_sorted(
        lax.sort(keys), letter_of_term, vocab_size=vocab_size, max_doc_id=max_doc_id)


def pack_u16_feed(terms, docs, padded: int) -> np.ndarray:
    """Host-side encode of the half-bandwidth uint16 feed buffer:
    ``[terms | docs]``, each half ``padded`` long, 0xFFFF padding —
    the layout :func:`_u16_feed_to_keys` decodes on device."""
    buf = np.full(2 * padded, 0xFFFF, dtype=np.uint16)
    n = len(terms)
    buf[:n] = terms
    buf[padded : padded + n] = docs
    return buf


def _u16_feed_to_keys(feed_u16, max_doc_id: int):
    """[terms | docs] uint16 buffer (0xFFFF padding) -> packed int32 keys."""
    pad = jnp.uint16(0xFFFF)
    stride = max_doc_id + 2
    half = feed_u16.shape[0] // 2
    term_u16, doc_u16 = feed_u16[:half], feed_u16[half:]
    return jnp.where(
        term_u16 == pad, K.INT32_MAX,
        term_u16.astype(jnp.int32) * stride + doc_u16.astype(jnp.int32))


@_quiet_donation
@functools.partial(jax.jit, static_argnames=("max_doc_id", "out_size"), donate_argnums=(0,))
def index_prededuped_u16(feed_u16, *, max_doc_id: int, out_size: int | None = None):
    """Minimal device program for a combiner-deduped feed.

    When the host map phase already emitted each (term, doc) pair once
    (native tokenizer's combiner), the reduce phase is exactly one sort:
    postings = doc component of the ascending pair keys.  df, order and
    offsets all derive from the deduped term ids on host (np.bincount +
    lexsort, vocab-sized).  One upload, one download — ``out_size``
    (static) limits the download to the valid prefix so the D2H
    transfer never includes padding beyond the rounding granule.
    """
    keys = _u16_feed_to_keys(feed_u16, max_doc_id)
    sorted_docs = (lax.sort(keys) % (max_doc_id + 2)).astype(jnp.uint16)
    return sorted_docs if out_size is None else sorted_docs[:out_size]


@_quiet_donation
@functools.partial(jax.jit, static_argnames=("stride", "out_size"), donate_argnums=(0,))
def sort_prov_chunks(chunks, *, stride: int, out_size: int):
    """Pipelined path: sort packed *provisional*-id keys fed per chunk.

    Each element of ``chunks`` is one upload window, asynchronously
    DMA'd while the host tokenizer was still scanning later documents —
    possible because provisional ids are first-occurrence ids, stable
    the moment a chunk is scanned, so this program never depends on the
    final sorted vocab.  A window is either an int32 array of
    ``prov_id * stride + doc`` keys (INT32_MAX padding) or, when its
    prov ids still fit, a half-bandwidth uint16 ``[terms | docs]``
    buffer (0xFFFF padding) packed into the same keys on device.
    Postings only need *grouping* by term and docs ascending, which the
    prov-key sort already gives; the host resolves emit order / offsets
    in prov space from vocab-sized arrays (models/inverted_index.py),
    leaving exactly one device->host round-trip on the critical path
    after tokenization ends.

    Combiner-deduped feeds only (each (term, doc) at most once).
    Returns the doc component of the ascending keys — the concatenated
    postings lists in prov-id order — as uint16 (callers guarantee
    ``stride <= 0x10000``); padding sorts last and is cut by
    ``out_size``.
    """
    as_keys = [
        _u16_feed_to_keys(c, stride - 2) if c.dtype == jnp.uint16 else c
        for c in chunks
    ]
    keys = as_keys[0] if len(as_keys) == 1 else jnp.concatenate(as_keys)
    return (lax.sort(keys)[:out_size] % stride).astype(jnp.uint16)


@_quiet_donation
@functools.partial(jax.jit, static_argnames=("vocab_size", "max_doc_id"),
                   donate_argnums=(0,))
def index_u16(feed_u16, *, vocab_size: int, max_doc_id: int):
    """Transfer-minimized path for corpora with vocab_size <= 65535 and
    max_doc_id <= 65534 (covers the reference's whole envelope,
    MAX_FILES=360 at main.c:8).

    The device<->host link has a large per-transfer fixed cost, so input
    is ONE uint16 buffer: term ids in the first half, doc ids in the
    second, 0xFFFF padding; keys are packed on device.  Output postings
    and df are uint16 — halving the bytes fetched — and
    ``order``/``offsets``/``num_unique`` are left for the host to derive
    from df (engine.host_order_offsets), saving further transfers.
    (Feeds already deduped by the combiner skip this entirely —
    :func:`index_prededuped_u16` is one sort and one download.)
    """
    keys = _u16_feed_to_keys(feed_u16, max_doc_id)
    _, df, postings, _ = dedup_df_postings(
        lax.sort(keys), vocab_size=vocab_size, max_doc_id=max_doc_id)
    # single output [df | postings]: callers slice host-side, so the fetch
    # is at most two download ops (df prefix, then valid postings prefix)
    return {"combined": jnp.concatenate(
        [df.astype(jnp.uint16), postings.astype(jnp.uint16)])}


@_quiet_donation
@functools.partial(jax.jit, static_argnames=("vocab_size", "max_doc_id"), donate_argnums=(0, 1))
def index_pairs(term_ids, doc_ids, letter_of_term, *, vocab_size: int, max_doc_id: int):
    """General path for corpora too large to pack into one int32 key.

    Two-key variadic sort; otherwise identical semantics to
    :func:`index_packed`.  Padding: term = doc = INT32_MAX.
    """
    term_s, doc_s = lax.sort((term_ids, doc_ids), num_keys=2)
    valid = term_s < vocab_size
    first = (first_occurrence_mask(term_s) | first_occurrence_mask(doc_s)) & valid
    df = sorted_segment_counts(jnp.where(valid, term_s, vocab_size), first.astype(jnp.int32), vocab_size)
    postings = compact(doc_s, first, term_s.shape[0], jnp.int32(0))
    order = emit_order(letter_of_term, df, vocab_size, max_doc_id)
    offsets = jnp.cumsum(df) - df
    return {
        "postings": postings,
        "df": df,
        "order": order,
        "offsets": offsets,
        "num_unique": first.astype(jnp.int32).sum(),
    }
