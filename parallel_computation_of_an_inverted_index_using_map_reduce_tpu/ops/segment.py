"""Segmented primitives over sorted key arrays.

These replace the reference reducer's O(tokens x unique_words) linear
dictionary scan and O(n^2) bubble sort (main.c:172-187, 217-226) with
O(n) boundary diffs, cumsums and searchsorted/gather compactions over a
sorted array — the shapes XLA vectorizes well on TPU.  None of them
scatters: XLA lowers TPU scatter to a serial per-update loop
(~75 ns/update measured on v5e — one 1M-update scatter costs more than
five 1M-element stable-sort passes), so every compaction here is
formulated as cumsum-rank + searchsorted + gather instead (see
ops/device_tokenizer.py module docstring for the measurement).
"""

from __future__ import annotations

import jax.numpy as jnp


def first_occurrence_mask(sorted_keys):
    """mask[i] = sorted_keys[i] is the first of its run.

    On a sorted pair array this is exactly the reference's per-(word, doc)
    dedup (main.c:176-184): one True per unique pair.
    """
    prev = jnp.concatenate([sorted_keys[:1] - 1, sorted_keys[:-1]])
    return sorted_keys != prev


def sorted_segment_counts(segment_ids, weights, num_segments: int):
    """Sum ``weights`` per segment id over a NONDECREASING id array;
    ids >= num_segments are dropped.  The name carries the precondition:
    the searchsorted run edges are silently wrong on unsorted ids (the
    scatter-based formulation this replaced accepted any order).

    Used for document frequency: df[t] = number of unique (t, doc) pairs
    (the count the reference accumulates per dictionary entry at
    main.c:176-187 and sorts by at main.c:55-64).  Every caller passes
    term ids taken from an already-sorted key array, so each segment is
    one contiguous run and its sum is a cumsum difference at the run's
    searchsorted edges — no scatter.
    """
    wext = jnp.concatenate(
        [jnp.zeros(1, weights.dtype), jnp.cumsum(weights)])
    edges = jnp.searchsorted(
        segment_ids, jnp.arange(num_segments + 1, dtype=segment_ids.dtype))
    return wext[edges[1:]] - wext[edges[:-1]]


def bucket_edges(sorted_bucket_ids, num_buckets: int):
    """``(counts, offsets)`` of each bucket's run in a sorted id array.

    The exchange cores sort rows by destination bucket and then need
    each bucket's count and start offset; both fall out of one
    searchsorted over the sorted column (ids >= num_buckets — the
    padding bucket — land past the last edge and are dropped).
    """
    edges = jnp.searchsorted(
        sorted_bucket_ids,
        jnp.arange(num_buckets + 1, dtype=jnp.int32)).astype(jnp.int32)
    return edges[1:] - edges[:-1], edges[:-1]


def compact(values, keep_mask, out_size: int, fill):
    """Stable-compact ``values[keep_mask]`` into a fixed-size array.

    The result's first ``keep_mask.sum()`` slots are the kept values in
    order, remaining slots are ``fill`` (kept values past ``out_size``
    are dropped).  The kept ranks are nondecreasing, so the j-th kept
    value's position is one searchsorted over the rank array and the
    compaction is a plain gather — no scatter.
    """
    n = values.shape[0]
    if n == 0:
        return jnp.full((out_size,), fill, dtype=values.dtype)
    rank = jnp.cumsum(keep_mask.astype(jnp.int32)) - 1
    slots = jnp.arange(out_size, dtype=jnp.int32)
    pos = jnp.searchsorted(rank, slots)
    live = slots < rank[-1] + 1
    return jnp.where(live, values[jnp.clip(pos, 0, n - 1)], fill)
