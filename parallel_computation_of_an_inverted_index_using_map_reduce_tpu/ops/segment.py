"""Segmented primitives over sorted key arrays.

These replace the reference reducer's O(tokens x unique_words) linear
dictionary scan and O(n^2) bubble sort (main.c:172-187, 217-226) with
O(n) boundary diffs, cumsums and sort/gather compactions over a sorted
array — the shapes XLA vectorizes well on TPU.  None of them scatters:
XLA lowers TPU scatter to a serial per-update loop (~75 ns/update
measured on v5e — one 1M-update scatter costs more than five
1M-element stable-sort passes), so every compaction here is a
set-bit-position ``lax.sort`` plus a gather (:func:`set_bit_positions`;
the round-2 cumsum-rank + searchsorted formulation lost the round-3
on-chip A/B — see :func:`searchsorted_device`, kept for run-edge
lookups where the sought values are not mask positions).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .keys import INT32_MAX as _INT32_MAX


def searchsorted_device(a, v):
    """``searchsorted(a, v, side='left')`` for NONDECREASING queries
    ``v``, formulated for TPU (both inputs same int dtype).

    CONTRACT: ``v`` must be nondecreasing — the formulation takes each
    query's index as its rank among queries, so unsorted queries get
    silently wrong edges (failure mode pinned by
    tests/test_segment.py::test_searchsorted_device_requires_monotone_
    queries).  Every in-tree caller passes an ``arange``.

    ``jnp.searchsorted``'s default ``method='scan'`` binary search
    lowers to a sequential log2(n)-step loop of dynamic slices —
    measured on the v5e (round 3, tools/profile_device_stages.py):
    173 ms for 2^20 sorted queries into a 2^20 array, 702 ms into a
    5.7M array.  Three of those per run dominated the all-device
    engine's 1157 ms device_index regression.

    This is the co-sort formulation instead: stable-sort
    ``concat([v, a])`` (ties put queries first = side='left'), invert
    the permutation, and subtract each query's own rank — for
    nondecreasing ``v`` that rank is just its index.  The inverse is a
    second ``argsort`` rather than the iota-scatter
    ``jnp.searchsorted(method='sort')`` uses, which keeps the device
    program scatter-free (the design guard in
    tests/test_device_tokenizer.py) AND measures faster: 72 ms / 90 ms
    on the shapes above vs 88 / 135 for ``method='sort'`` (the
    permutation scatter is not the serial per-update worst case, but
    it still loses to the sort).
    """
    m = v.shape[0]
    idx = jnp.argsort(jnp.concatenate([v, a]), stable=True)
    inv = jnp.argsort(idx)
    return inv[:m] - jnp.arange(m, dtype=inv.dtype)


def set_bit_positions(mask, out_len: int):
    """Positions of ``mask``'s True slots, in order, as an
    ``out_len``-long int32 array padded with INT32_MAX.

    ONE single-key ``lax.sort`` of (slot where set, INT32_MAX
    elsewhere) front-compacts the positions; set bits past ``out_len``
    are dropped.  This is the shared core of every compaction in the
    device programs (``segment.compact``, the streaming row compactor,
    and the W/P word/pair-start lookups of both dedup tails) — cheaper
    on TPU than the rank-cumsum searchsorted it replaced (round-3
    on-chip measurement, see :func:`searchsorted_device`).
    """
    n = mask.shape[0]
    kept = lax.sort(
        jnp.where(mask, jnp.arange(n, dtype=jnp.int32), _INT32_MAX))
    if out_len <= n:
        return kept[:out_len]
    return jnp.concatenate(
        [kept, jnp.full(out_len - n, _INT32_MAX, jnp.int32)])


def first_occurrence_mask(sorted_keys):
    """mask[i] = sorted_keys[i] is the first of its run.

    On a sorted pair array this is exactly the reference's per-(word, doc)
    dedup (main.c:176-184): one True per unique pair.
    """
    prev = jnp.concatenate([sorted_keys[:1] - 1, sorted_keys[:-1]])
    return sorted_keys != prev


def sorted_segment_counts(segment_ids, weights, num_segments: int):
    """Sum ``weights`` per segment id over a NONDECREASING id array;
    ids >= num_segments are dropped.  The name carries the precondition:
    the searchsorted run edges are silently wrong on unsorted ids (the
    scatter-based formulation this replaced accepted any order).

    Used for document frequency: df[t] = number of unique (t, doc) pairs
    (the count the reference accumulates per dictionary entry at
    main.c:176-187 and sorts by at main.c:55-64).  Every caller passes
    term ids taken from an already-sorted key array, so each segment is
    one contiguous run and its sum is a cumsum difference at the run's
    searchsorted edges — no scatter.
    """
    wext = jnp.concatenate(
        [jnp.zeros(1, weights.dtype), jnp.cumsum(weights)])
    edges = searchsorted_device(
        segment_ids, jnp.arange(num_segments + 1, dtype=segment_ids.dtype))
    return wext[edges[1:]] - wext[edges[:-1]]


def bucket_edges(sorted_bucket_ids, num_buckets: int):
    """``(counts, offsets)`` of each bucket's run in a sorted id array.

    The exchange cores sort rows by destination bucket and then need
    each bucket's count and start offset; both fall out of one
    searchsorted over the sorted column (ids >= num_buckets — the
    padding bucket — land past the last edge and are dropped).
    """
    edges = searchsorted_device(
        sorted_bucket_ids,
        jnp.arange(num_buckets + 1, dtype=jnp.int32)).astype(jnp.int32)
    return edges[1:] - edges[:-1], edges[:-1]


def compact(values, keep_mask, out_size: int, fill):
    """Stable-compact ``values[keep_mask]`` into a fixed-size array.

    The result's first ``keep_mask.sum()`` slots are the kept values in
    order, remaining slots are ``fill`` (kept values past ``out_size``
    are dropped): :func:`set_bit_positions` then a plain gather — no
    scatter.
    """
    n = values.shape[0]
    if n == 0:
        return jnp.full((out_size,), fill, dtype=values.dtype)
    kept = set_bit_positions(keep_mask, out_size)
    live = kept != _INT32_MAX
    return jnp.where(live, values[jnp.clip(kept, 0, n - 1)], fill)
