"""Segmented primitives over sorted key arrays.

These replace the reference reducer's O(tokens x unique_words) linear
dictionary scan and O(n^2) bubble sort (main.c:172-187, 217-226) with
O(n) boundary diffs, cumsums and scatters over a sorted array — the
shapes XLA fuses well on TPU (all elementwise + scan + scatter, no
data-dependent shapes).
"""

from __future__ import annotations

import jax.numpy as jnp


def first_occurrence_mask(sorted_keys):
    """mask[i] = sorted_keys[i] is the first of its run.

    On a sorted pair array this is exactly the reference's per-(word, doc)
    dedup (main.c:176-184): one True per unique pair.
    """
    prev = jnp.concatenate([sorted_keys[:1] - 1, sorted_keys[:-1]])
    return sorted_keys != prev


def segment_counts(segment_ids, weights, num_segments: int):
    """Sum ``weights`` per segment id; ids >= num_segments are dropped.

    Used for document frequency: df[t] = number of unique (t, doc) pairs
    (the count the reference accumulates per dictionary entry at
    main.c:176-187 and sorts by at main.c:55-64).
    """
    out = jnp.zeros((num_segments,), dtype=weights.dtype)
    return out.at[segment_ids].add(weights, mode="drop")


def compact(values, keep_mask, out_size: int, fill):
    """Stable-compact ``values[keep_mask]`` into a fixed-size array.

    Scatter to cumsum positions; dropped lanes go out of bounds.  The
    result's first ``keep_mask.sum()`` slots are the kept values in
    order, remaining slots are ``fill``.
    """
    pos = jnp.cumsum(keep_mask.astype(jnp.int32)) - 1
    idx = jnp.where(keep_mask, pos, out_size)
    out = jnp.full((out_size,), fill, dtype=values.dtype)
    return out.at[idx].set(values, mode="drop")
