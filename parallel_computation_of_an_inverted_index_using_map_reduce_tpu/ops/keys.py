"""Packed-key helpers for the sort-based engine.

The reference shuffles ``"word doc_id"`` text lines through 26 spill
files (main.c:116) and re-parses them in the reducer (main.c:170).  On
TPU both the pair and its ordering live in a single int32 radix-sort key
whenever ``vocab_size * (max_doc_id + 2)`` fits in int32 — true even for
corpora orders of magnitude beyond the reference's caps (MAX_FILES=360,
main.c:8).  A two-key variadic ``lax.sort`` is the general fallback.

Padding uses a sentinel that sorts after every real key so fixed-shape
batches stay XLA-friendly (no dynamic shapes, SURVEY.md §7).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

INT32_MAX = np.int32(np.iinfo(np.int32).max)


def can_pack(vocab_size: int, max_doc_id: int) -> bool:
    """True if (term, doc) pairs fit one int32 key with room for a sentinel."""
    return (vocab_size + 1) * (max_doc_id + 2) < np.iinfo(np.int32).max


def pack_pairs(term_ids, doc_ids, max_doc_id: int):
    """key = term * (max_doc+2) + doc; key order == (term, doc) lex order."""
    stride = max_doc_id + 2
    return term_ids.astype(jnp.int32) * stride + doc_ids.astype(jnp.int32)


def unpack_pairs(keys, max_doc_id: int):
    stride = max_doc_id + 2
    return keys // stride, keys % stride
