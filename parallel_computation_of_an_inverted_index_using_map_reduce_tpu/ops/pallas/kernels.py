"""Pallas TPU kernels for the device engine's hot ops.

Two kernels, both exact drop-ins for their XLA counterparts:

- :func:`unique_mask_count` — the reduce phase's per-(term, doc) dedup
  (the reference's linear dictionary scan, main.c:172-187) as ONE fused
  pass over the sorted key array: boundary diff + validity mask +
  global unique count.  XLA expresses this as three kernels (pad-shift
  compare, elementwise and, reduce); here it is a single VMEM sweep
  using the sequential-grid carry pattern — block ``i+1`` sees block
  ``i``'s last key through SMEM scratch, which TPU's in-order grid
  execution makes race-free.

- :func:`bucket_histogram` — per-partition pair counts used by
  utils/stats.py to measure shuffle skew per run: the reference's
  first-letter partition is ~1000x imbalanced on real text while the
  engine's hash buckets are near-uniform (SURVEY.md §2.3).  Bucket
  counts are small (mesh size or 26 letters), so each block reduces
  with a static unrolled compare loop on the VPU; counts accumulate in
  SMEM across the sequential grid.

Both run compiled on TPU and in interpreter mode elsewhere (tests force
``interpret=True`` on the CPU backend via :func:`_should_interpret`).

Measured on a real v5e (bench.py ``kernel_timings``, 2^20 keys,
device-side dispatch loops): the fused kernel runs ~18 us vs ~14 us for
the XLA three-kernel path — XLA's own fusion already wins here, and the
sequential-grid carry serializes what XLA parallelizes.  The kernel is
kept (a) as the measured datapoint behind that conclusion and (b) as
the fused-sweep pattern the playbook needs at sizes where the extra
pass over HBM dominates; ``MRI_TPU_PALLAS=off`` selects XLA everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# One grid block: 64 sublanes x 128 lanes of int32 = 32 KiB of VMEM.
_BLOCK_ROWS = 64
_LANES = 128
_BLOCK = _BLOCK_ROWS * _LANES
BLOCK = _BLOCK  # public: callers pad array lengths to a multiple of this


def _should_interpret() -> bool:
    """Compiled on real TPU; interpreted on CPU (tests, dry runs)."""
    return jax.default_backend() != "tpu"


def supports(n: int) -> bool:
    """True if an ``n``-element array fits the kernels' block layout."""
    return n >= _BLOCK and n % _BLOCK == 0


# ---------------------------------------------------------------------------
# unique_mask_count
# ---------------------------------------------------------------------------


def _unique_kernel(keys_ref, limit_ref, mask_ref, count_ref, carry_ref, *,
                   block_rows: int):
    i = pl.program_id(0)
    k = keys_ref[:]  # (R, 128) int32, ascending across the flattened array

    @pl.when(i == 0)
    def _init():
        # packed keys are >= 0, so k[0,0] - 1 cannot wrap
        carry_ref[0] = k[0, 0] - 1
        count_ref[0, 0] = 0

    # shifted[r, l] = previous element in flattened row-major order,
    # built from full-block rolls (Mosaic-friendly: no narrow concats).
    # roll along lanes puts k[r, 127] at (r, 0) — wrong row; a second
    # roll along sublanes fixes column 0, and (0, 0) comes from the
    # cross-block carry.
    rolled_lanes = pltpu.roll(k, shift=1, axis=1)
    rolled_both = pltpu.roll(rolled_lanes, shift=1, axis=0)
    row = jax.lax.broadcasted_iota(jnp.int32, (block_rows, _LANES), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (block_rows, _LANES), 1)
    shifted = jnp.where(col == 0, rolled_both, rolled_lanes)
    shifted = jnp.where((col == 0) & (row == 0), carry_ref[0], shifted)

    mask = (k != shifted) & (k < limit_ref[0, 0])
    mask_ref[:] = mask.astype(jnp.int32)
    count_ref[0, 0] += jnp.sum(mask.astype(jnp.int32))
    carry_ref[0] = k[block_rows - 1, _LANES - 1]


@functools.partial(jax.jit, static_argnames=("interpret", "block_rows"))
def _unique_call(keys2d, limit, *, interpret: bool,
                 block_rows: int = _BLOCK_ROWS):
    if keys2d.shape[0] % block_rows:
        raise ValueError(
            f"{keys2d.shape[0]} rows not divisible by block_rows {block_rows}")
    grid = keys2d.shape[0] // block_rows
    mask, count = pl.pallas_call(
        functools.partial(_unique_kernel, block_rows=block_rows),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(keys2d.shape, jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=interpret,
    )(keys2d, limit)
    return mask, count


def unique_mask_count(sorted_keys, valid_limit: int):
    """Fused first-occurrence mask + unique count over ascending keys.

    Equivalent to ``first_occurrence_mask(k) & (k < valid_limit)`` plus
    the mask's sum (ops/segment.py), in one pass.  Returns
    ``(mask bool (n,), count int32 scalar)``.  Requires
    :func:`supports`\\ ``(n)``; callers fall back to the XLA path
    otherwise.
    """
    n = sorted_keys.shape[0]
    if not supports(n):
        raise ValueError(f"unsupported size {n}; check supports() first")
    keys2d = sorted_keys.reshape(n // _LANES, _LANES)
    limit = jnp.full((1, 1), valid_limit, jnp.int32)
    mask, count = _unique_call(keys2d, limit, interpret=_should_interpret())
    return mask.reshape(n).astype(bool), count[0, 0]


# ---------------------------------------------------------------------------
# bucket_histogram
# ---------------------------------------------------------------------------


def _hist_kernel(vals_ref, counts_ref, *, num_buckets: int):
    i = pl.program_id(0)
    v = vals_ref[:]  # (R, 128) int32

    @pl.when(i == 0)
    def _init():
        for b in range(num_buckets):
            counts_ref[0, b] = 0

    # static unrolled compare loop: num_buckets is small (mesh size / 26)
    for b in range(num_buckets):
        counts_ref[0, b] += jnp.sum((v == b).astype(jnp.int32))


@functools.partial(jax.jit,
                   static_argnames=("num_buckets", "interpret", "block_rows"))
def _hist_call(vals2d, *, num_buckets: int, interpret: bool,
               block_rows: int = _BLOCK_ROWS):
    if vals2d.shape[0] % block_rows:
        raise ValueError(
            f"{vals2d.shape[0]} rows not divisible by block_rows {block_rows}")
    grid = vals2d.shape[0] // block_rows
    return pl.pallas_call(
        functools.partial(_hist_kernel, num_buckets=num_buckets),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, num_buckets), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((1, num_buckets), jnp.int32),
        interpret=interpret,
    )(vals2d)


def bucket_histogram(values, num_buckets: int):
    """Count occurrences of each bucket id in ``values``.

    ``values`` outside ``[0, num_buckets)`` (e.g. padding) are ignored.
    Equivalent to ``jnp.bincount(values, length=num_buckets)`` for
    in-range values; int32 (num_buckets,).  Requires
    :func:`supports`\\ ``(len(values))`` and ``num_buckets <= 128``.
    """
    n = values.shape[0]
    if not supports(n):
        raise ValueError(f"unsupported size {n}; check supports() first")
    if not 1 <= num_buckets <= 128:
        raise ValueError(f"num_buckets must be in [1, 128], got {num_buckets}")
    vals2d = values.reshape(n // _LANES, _LANES).astype(jnp.int32)
    counts = _hist_call(
        vals2d, num_buckets=num_buckets, interpret=_should_interpret())
    return counts[0]
