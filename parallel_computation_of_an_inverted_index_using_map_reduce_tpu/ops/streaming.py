"""Streaming device engine: blockwise reduction over an unbounded pair
stream with a bounded on-device accumulator.

The single-shot engine (ops/engine.py) needs the whole packed-key
array in HBM at once.  Here the token stream arrives in fixed-size
windows (text/streaming.py feeds them) and the device carries only the
**sorted unique (term, doc) pairs seen so far** — bounded by the
output's unique-pair count, not the stream length.  This is the sort
pipeline's analogue of blockwise/sequence-parallel attention
accumulators (SURVEY.md §5 "long-context"): per window

    acc <- unique(merge_sort(acc, sort(window)))

as one fused XLA program (concat -> lax.sort -> boundary dedup ->
compact), all static shapes.  The accumulator capacity grows by
host-side doubling *before* a window that could overflow it is merged
(the host tracks ``unique <= fed_pairs``), so no device->host sync ever
happens inside the stream loop; each capacity is a separate compiled
program, hit at most O(log unique) times.

Two accumulator representations, switched automatically mid-stream:

- **packed**: one int32 key per pair (``term * stride + doc``) while
  the growing vocabulary still packs (K.can_pack) — one buffer, one
  single-key sort;
- **pairs**: separate (term, doc) int32 arrays with a two-key sort
  once the vocabulary outgrows the packed key space — the streaming
  counterpart of the one-shot path's ``index_pairs`` fallback, so
  streaming never hard-fails on the large corpora it exists for.

At ``finalize`` the provisional (append-stable) term ids are remapped
on device to sorted-vocab rank with one gather, re-sorted, and handed
to the shared tail — output is byte-identical to the single-shot path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.rounding import round_up
from . import keys as K
from .engine import index_pairs, postings_from_sorted
from .segment import compact, first_occurrence_mask


@functools.partial(jax.jit, static_argnames=("cap",), donate_argnums=(0,))
def _merge_unique(acc, window, *, cap: int):
    """Fold a packed-key window into the sorted-unique accumulator."""
    s = lax.sort(jnp.concatenate([acc, window]))
    first = first_occurrence_mask(s) & (s < K.INT32_MAX)
    return compact(s, first, cap, K.INT32_MAX)


@functools.partial(jax.jit, static_argnames=("cap",), donate_argnums=(0, 1))
def _merge_unique_pairs(acc_t, acc_d, feed, *, cap: int):
    """Pair-mode merge: ``feed`` is one [terms | docs] int32 buffer."""
    half = feed.shape[0] // 2
    t = jnp.concatenate([acc_t, feed[:half]])
    d = jnp.concatenate([acc_d, feed[half:]])
    t_s, d_s = lax.sort((t, d), num_keys=2)
    first = (first_occurrence_mask(t_s) | first_occurrence_mask(d_s)) & (
        t_s < K.INT32_MAX)
    return (compact(t_s, first, cap, K.INT32_MAX),
            compact(d_s, first, cap, K.INT32_MAX))


@functools.partial(jax.jit, static_argnames=("cap",))
def _regrow(acc, *, cap: int):
    """Copy a buffer into a larger one (INT32_MAX padded).  No donation:
    the output shape never matches the input, so aliasing is impossible."""
    out = jnp.full((cap,), K.INT32_MAX, jnp.int32)
    return lax.dynamic_update_slice(out, acc, (0,))


@functools.partial(jax.jit, static_argnames=("stride",), donate_argnums=(0,))
def _unpack_acc(acc, *, stride: int):
    """Packed accumulator -> (term, doc) pair accumulator (mode switch)."""
    valid = acc < K.INT32_MAX
    term = jnp.where(valid, acc // stride, K.INT32_MAX)
    doc = jnp.where(valid, acc % stride, K.INT32_MAX)
    return term, doc


@functools.partial(
    jax.jit, static_argnames=("vocab_size", "max_doc_id"), donate_argnums=(0,))
def _final_index(acc, remap, letter_of_term, *, vocab_size: int, max_doc_id: int):
    """Packed provisional keys -> sorted-rank keys -> shared tail."""
    stride = max_doc_id + 2
    valid = acc < K.INT32_MAX
    term = jnp.where(valid, acc // stride, 0)
    doc = acc % stride
    final = jnp.where(valid, remap[term] * stride + doc, K.INT32_MAX)
    return postings_from_sorted(
        lax.sort(final), letter_of_term,
        vocab_size=vocab_size, max_doc_id=max_doc_id)


def _final_pairs(acc_t, acc_d, remap, letter_of_term, *, vocab_size: int,
                 max_doc_id: int):
    """Pair-mode finalize: remap terms, then the two-key engine path."""
    valid = acc_t < K.INT32_MAX
    final_t = jnp.where(valid, remap[jnp.where(valid, acc_t, 0)], K.INT32_MAX)
    return index_pairs(final_t, acc_d, letter_of_term,
                       vocab_size=vocab_size, max_doc_id=max_doc_id)


class StreamingIndexEngine:
    """Bounded-memory device reduction over a provisional-id pair stream.

    ``max_doc_id`` fixes the key stride for the whole stream; the vocab
    may keep growing while feeding (provisional ids).  Starts in packed
    mode and switches permanently to pair mode the first time the
    vocabulary seen so far stops packing into int32 keys.
    """

    def __init__(self, *, max_doc_id: int, window_pad: int = 1 << 16,
                 initial_capacity: int = 1 << 18):
        self._stride = max_doc_id + 2
        self._max_doc_id = max_doc_id
        self._window_pad = window_pad
        self._cap = initial_capacity
        self._acc = None            # packed mode: int32 (cap,)
        self._acc_pair = None       # pair mode: (term, doc) int32 (cap,) each
        self._unique_bound = 0      # host upper bound on unique pairs in acc
        self.windows_fed = 0

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def mode(self) -> str:
        return "pairs" if self._acc_pair is not None else "packed"

    def _ensure_capacity(self, extra: int) -> None:
        self._unique_bound += extra
        while self._unique_bound > self._cap:
            # grow BEFORE a potentially-overflowing merge: no data loss,
            # no device sync; at most O(log unique) recompiles total
            self._cap *= 2
            if self._acc is not None:
                self._acc = _regrow(self._acc, cap=self._cap)
            if self._acc_pair is not None:
                t, d = self._acc_pair
                self._acc_pair = (_regrow(t, cap=self._cap),
                                  _regrow(d, cap=self._cap))

    def _switch_to_pairs(self) -> None:
        if self._acc is None:
            self._acc_pair = tuple(
                jax.device_put(np.full(self._cap, K.INT32_MAX, np.int32))
                for _ in range(2))
        else:
            self._acc_pair = _unpack_acc(self._acc, stride=self._stride)
            self._acc = None

    def feed(self, prov_term_ids: np.ndarray, doc_ids: np.ndarray,
             vocab_size_so_far: int) -> None:
        """Merge one window of (provisional term, doc) pairs."""
        n = int(prov_term_ids.shape[0])
        if n == 0:
            return
        if self.mode == "packed" and not K.can_pack(vocab_size_so_far,
                                                    self._max_doc_id):
            self._switch_to_pairs()
        if self.mode == "packed" and self._acc is None:
            self._acc = jax.device_put(np.full(self._cap, K.INT32_MAX, np.int32))

        padded = round_up(n, self._window_pad)
        self._ensure_capacity(n)
        if self.mode == "packed":
            host = np.full(padded, K.INT32_MAX, np.int32)
            np.multiply(prov_term_ids, self._stride, out=host[:n])
            host[:n] += doc_ids
            self._acc = _merge_unique(
                self._acc, jax.device_put(host), cap=self._cap)
        else:
            host = np.full(2 * padded, K.INT32_MAX, np.int32)
            host[:n] = prov_term_ids
            host[padded : padded + n] = doc_ids
            self._acc_pair = _merge_unique_pairs(
                *self._acc_pair, jax.device_put(host), cap=self._cap)
        self.windows_fed += 1

    def finalize(self, remap: np.ndarray, letter_of_term: np.ndarray,
                 vocab_size: int):
        """Device dict of postings/df/order/offsets/num_unique (the
        engine.postings_from_sorted interface) from the accumulated
        stream.  ``remap[prov_id] == sorted rank``."""
        remap_dev = jax.device_put(remap.astype(np.int32))
        letters_dev = jax.device_put(letter_of_term.astype(np.int32))
        if self._acc is not None:
            out = _final_index(self._acc, remap_dev, letters_dev,
                               vocab_size=vocab_size, max_doc_id=self._max_doc_id)
        elif self._acc_pair is not None:
            out = _final_pairs(*self._acc_pair, remap_dev, letters_dev,
                               vocab_size=vocab_size, max_doc_id=self._max_doc_id)
        else:
            raise ValueError("no windows fed")
        self._acc = self._acc_pair = None
        return out
