"""Device-side tokenizer: the ENTIRE map phase as one XLA program.

Every other engine in this package keeps the reference's split: host
scans text (main.c:102-117 re-expressed in C++/numpy), device sorts
integers.  This module removes the host from the compute path entirely:
raw corpus bytes go up, the finished index comes down.

    bytes (uint8, N) ──► classify: space/letter as fused compares
            (256-entry table gathers cost ~100 ms at 5.7M bytes on the
            v5e; the compare chain is free — round-3 attribution)
        ──► token segmentation: start mask, letter-count cumsum
        ──► letter compaction: ONE position-keyed ``lax.sort`` moves
            every cleaned letter to the front in byte order (the
            byte stream with non-letters deleted, main.c:105-111),
            carrying the lowered bytes as a sort payload
        ──► per-token offsets/lengths: token start bytes via a second
            single-key sort (set-bit positions), then F = one gather
            of the exclusive letter cumsum — no token-scale
            searchsorted (its scan lowering was the round-2 program's
            dominant cost: 702 ms for 2^20 queries into 5.7M)
        ──► word rows: windowed gathers off the compacted letter
            stream pack big-endian int32 columns (cleaned bytes are
            a-z < 0x80, so signed int32 ascending == byte-
            lexicographic ascending)
        ──► LSD radix ``lax.sort`` passes over (word columns…, doc)
        ──► boundary-diff word/pair dedup ► df ► postings ► unique rows

    Why sorts/gathers and never large scatters: XLA lowers TPU scatter
    to a serial per-update loop (~75 ns/update measured on v5e — a
    single 1M-update scatter costs ~75 ms, 5x a whole 1M stable-sort
    pass).  The first cut of this module scattered letters into rows
    and compacted results with scatters; every token-scale scatter is
    now a sort/cumsum/gather formulation.  Scatters are kept only at
    trivial sizes (the num_docs-entry doc-boundary marker).

Exactness without strings-on-host: rows are the *actual cleaned bytes*
(no hashing, no collisions); sorted-row order IS strcmp order because
rows are zero-padded (0x00 < any letter, so shorter words sort first —
the same argument as the C side's prefix keys, native/tokenizer.cc
SortedOrder).  Words longer than ``width`` cleaned letters cannot be
represented exactly; the program returns the global max cleaned length
and the caller MUST fall back to a host path when it exceeds ``width``
(``WidthOverflow``).  The reference's own cap is 299 (main.c:105), and
its corpus maxes at 38, so ``width=48`` covers real text with margin.

This is the TPU-first endpoint of the design space: on hardware where
the host<->device link is ~free (local PCIe), the whole pipeline runs
at device sort throughput; on a high-RTT link the host-scan engines
win end-to-end (bench.py records both, labeled).  Reference seams
re-expressed: mapper tokenize+emit (main.c:85-124) and reducer
dedup/sort (main.c:126-242) become one fused program with no
intermediate materialization at all — not even the (term, doc) pair
array the other engines feed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import segment

INT32_MAX = np.iinfo(np.int32).max

# Byte-buffer size above which the letter compaction's (flag, position)
# key no longer fits in one int32 and tokenize_rows switches to a
# two-key sort.  Module-level so tests can force the two-key branch on
# small inputs and compare it against the one-key path.
_ONE_KEY_COMPACTION_LIMIT = 1 << 24

# The round-2 MRI_TPU_LETTER_COMPACTION=searchsorted variant was
# removed after the round-3 on-chip A/B: the cumsum-rank binary-search
# compaction measured 2150.8 ms device_index vs 1156.6 ms for the
# position-keyed sort on the v5e (BENCH_TPU_r03.json
# letter_compaction_ab), and the sort formulation then absorbed the
# letter payload for free.


class WidthOverflow(Exception):
    """A cleaned token exceeded the row width — the device rows would be
    truncated (inexact); the caller must fall back to a host tokenizer."""


@functools.lru_cache(maxsize=1)
def _byte_tables():
    """(space, lower) 256-entry tables — the exact C-locale contract of
    the native scan (native/tokenizer.cc ByteTables).  Cached as numpy
    (NOT device arrays: an lru-cached jnp value created inside a trace
    would leak that trace's tracers into later calls); jit closes over
    them as constants."""
    space = np.zeros(256, np.bool_)
    for b in b" \t\n\v\f\r":
        space[b] = True
    lower = np.zeros(256, np.uint8)
    for b in range(ord("a"), ord("z") + 1):
        lower[b] = b
    for b in range(ord("A"), ord("Z") + 1):
        lower[b] = b + 32
    return space, lower


def _tokenize_front(data, doc_ends, doc_id_values, *, tok_cap: int,
                    num_docs: int):
    """Shared front half of both tokenizer frontends: byte classify,
    token segmentation, letter compaction, per-token offsets/lengths
    and doc ids.  Returns ``(letters, F0, tok_len, max_word_len,
    doc_of_tok, valid_tok, num_tokens, n)`` — everything the word-row
    packers (:func:`tokenize_rows`, :func:`tokenize_groups`) need."""
    n = data.shape[0]
    # byte classifiers as arithmetic, not 256-entry table gathers: a
    # token-scale gather costs ~7 ms/2^20 rows on the v5e where the
    # compare chain fuses for free (round-3 attribution,
    # tools/attribute_device_stages.py — the two table lookups were
    # ~100 ms of the program).  Exact C-locale contract of
    # native/tokenizer.cc ByteTables: space = {0x20, 0x09..0x0D};
    # A-Z|0x20 lands in [a-z] and no non-letter byte does (the only
    # preimages of [0x61,0x7A] under |0x20 are the two letter ranges).
    is_space = (data == 0x20) | ((data >= 0x09) & (data <= 0x0D))
    lc = data | jnp.uint8(0x20)
    is_letter = (lc >= 0x61) & (lc <= 0x7A)
    lowered = jnp.where(is_letter, lc, jnp.uint8(0)).astype(jnp.int32)

    pos = jnp.arange(n, dtype=jnp.int32)
    # first byte of each document forces a token break (tokens never
    # span documents — the per-doc scan loop of every host frontend).
    # num_docs-entry scatters: trivially small, the only ones kept.
    doc_starts = jnp.zeros(n, jnp.bool_).at[doc_ends[:-1]].set(
        True, mode="drop").at[0].set(True)
    # manifest slot per byte: scatter-max doc slots at their start
    # bytes (max resolves zero-length-doc collisions the same way as
    # searchsorted side="right": the last doc starting there owns the
    # byte), then cummax propagates slots forward
    doc_slot_of_byte = lax.cummax(
        jnp.zeros(n, jnp.int32).at[doc_ends[:-1]].max(
            jnp.arange(1, num_docs, dtype=jnp.int32), mode="drop"))
    nonspace = ~is_space
    prev_space = jnp.concatenate([jnp.ones(1, jnp.bool_), is_space[:-1]])
    token_start = nonspace & (prev_space | doc_starts)

    cs = jnp.cumsum(is_letter.astype(jnp.int32))

    # letter compaction: ONE sort on (non-letter flag, byte position)
    # packed into a single key moves every cleaned letter to the front
    # in byte order — the reference's delete-non-letters pass
    # (main.c:105-111) with no scatter.  Position fits the key's low
    # bits; the flag rides above them, so ascending key order is
    # "letters first, each group in byte order".  ``lowered`` rides
    # along as a payload of the SAME sort, so the compacted letter
    # stream needs no n-scale gather afterwards (round-3 on-chip
    # attribution: each such gather is ~40 ms at 5.7M bytes).
    if n < _ONE_KEY_COMPACTION_LIMIT:
        key = jnp.where(is_letter, pos, pos + jnp.int32(1 << 24))
        _, letters = lax.sort((key, lowered), num_keys=1, is_stable=True)
    else:  # buffers >= 16 MiB per program: flag no longer fits beside
        # the position in an int32 (and int64 needs jax_enable_x64),
        # so sort on (flag, position) as two keys instead
        _, _, letters = lax.sort(
            ((~is_letter).astype(jnp.int32), pos, lowered), num_keys=2,
            is_stable=True)
    # compacted letter stream: past num_letters every payload is 0
    # (non-letters carry lowered == 0), but no consumer may rely on
    # the tail: every unmasked window read below stays inside its own
    # token's letters (masktab[nbytes]).

    # per-token letter offsets/lengths WITHOUT a token-scale
    # searchsorted (the round-2 formulation's dominant cost): token
    # start bytes move to the front with the shared set-bit sort
    # (segment.set_bit_positions), and F[t] = letters strictly before
    # start byte t = one gather of the exclusive letter-count cumsum.
    # Every letter between token t's start byte and token t+1's start
    # byte belongs to token t (the gap is spaces / non-letters), so F
    # is exactly "first compacted slot of token t's letters"; a token
    # with no letters (e.g. "42", skipped at main.c:113) gets
    # F[t] == F[t+1] => length 0 => masked invalid below.  Slots past
    # num_tokens hold INT32_MAX -> clamp to n -> F = total letters =>
    # length 0.
    sb = segment.set_bit_positions(token_start, tok_cap + 1)
    sbc = jnp.minimum(sb, jnp.int32(n))
    cse = jnp.concatenate([jnp.zeros(1, jnp.int32), cs])  # exclusive
    F = cse[sbc]
    tok_len = F[1:] - F[:-1]
    F0 = F[:-1]
    # true cleaned length, NO width clip (the exactness guard; the
    # reference's own cap is 299, enforced by the caller)
    max_word_len = tok_len.max() if tok_cap else jnp.int32(0)

    # doc id per token: start byte -> manifest slot -> 1-based id
    # (tokens never span docs, so the start byte's doc is the token's)
    slot = doc_slot_of_byte[jnp.clip(sb[:-1], 0, n - 1)]
    doc_of_tok = doc_id_values[jnp.clip(slot, 0, num_docs - 1)]

    num_tokens = jnp.int32(0) + jnp.sum(token_start.astype(jnp.int32))
    valid_tok = (tok_len > 0) & (jnp.arange(tok_cap) < num_tokens)
    return (letters, F0, tok_len, max_word_len, doc_of_tok, valid_tok,
            num_tokens, n)


def tokenize_rows(data, doc_ends, doc_id_values, *, width: int,
                  tok_cap: int, num_docs: int):
    """bytes -> packed word-row byte columns + doc column (device,
    traceable).

    The byte-column frontend: ``width // 4`` big-endian int32 columns
    per word row.  :func:`tokenize_groups` (the 5-bit compressed
    frontend the engines run) supersedes it on the hot paths — this
    one is kept as the directly-byte-addressed reference whose output
    the group frontend is property-tested against
    (pack_groups(tokenize_rows(x)) == tokenize_groups(x)).  Returns
    ``(cols, doc_col, max_word_len, num_tokens)``: ``cols[0]`` carries
    INT32_MAX on empty/padding rows (sorts last), ``doc_col``
    likewise.
    """
    (letters, F0, tok_len, max_word_len, doc_of_tok, valid_tok,
     num_tokens, n) = _tokenize_front(
        data, doc_ends, doc_id_values, tok_cap=tok_cap,
        num_docs=num_docs)

    # big-endian int32 word columns via windowed gathers: 4-byte packs
    # of the letter stream at every alignment (elementwise shifts of
    # padded slices), then one gather per column at F[t] + 4c, masked
    # by how many of the window's 4 bytes belong to the token.  Mask
    # values are uint32 byte prefixes viewed as int32.
    lp = jnp.concatenate([letters, jnp.zeros(3, jnp.int32)])
    l4 = ((lp[0:n] << 24) | (lp[1:n + 1] << 16)
          | (lp[2:n + 2] << 8) | lp[3:n + 3])
    masktab = jnp.array([0, -16777216, -65536, -256, -1], jnp.int32)
    ncols = width // 4
    cols = []
    for c in range(ncols):
        idx = jnp.clip(F0 + 4 * c, 0, n - 1)
        nbytes = jnp.clip(tok_len - 4 * c, 0, 4)
        cols.append(l4[idx] & masktab[nbytes])

    # valid rows (>= 1 letter) have column 0's top byte in [a-z] =>
    # positive int32; empty/padding rows get INT32_MAX in column 0 so
    # they sort after every real word
    col0 = jnp.where(valid_tok, cols[0], INT32_MAX)
    doc_col = jnp.where(valid_tok, doc_of_tok, INT32_MAX)

    return (col0, *cols[1:]), doc_col, max_word_len, num_tokens


def num_groups_for(width: int) -> int:
    """Total (hi, lo) group pairs a ``width``-byte word row packs into
    (12 chars per group — see :func:`pack_groups`)."""
    return (width // 4 + 2) // 3


def live_groups_for(sort_cols: int | None, width: int) -> int:
    """Group pairs that can be non-constant given the host-exact
    ``sort_cols`` byte-column bound (the :func:`clamp_sort_cols`
    discipline, lifted to groups)."""
    return (clamp_sort_cols(sort_cols, width // 4) + 2) // 3


def tokenize_groups(data, doc_ends, doc_id_values, *, width: int,
                    tok_cap: int, num_docs: int,
                    sort_cols: int | None = None):
    """bytes -> 5-bit-compressed word-row group pairs + doc column.

    The frontend both device engines run: word rows come out directly
    as the ``(hi, lo)`` 30-bit code pairs of :func:`pack_groups`
    (12 chars per pair, order-preserving, injective), built by TWO
    windowed gathers per group off a 6-char packed letter stream —
    instead of 12 byte-column gathers then an elementwise repack.
    Groups past the host-exact ``sort_cols`` bound are constant zeros
    (XLA dead-code-eliminates their gathers), mirroring
    :func:`zero_tail_cols`.  Group 0 pins INT32_MAX on empty/padding
    rows so they sort last; ``doc_col`` likewise.

    Returns ``(groups, doc_col, max_word_len, num_tokens)`` with
    ``groups`` a list of ``num_groups_for(width)`` pairs, exactly
    ``pack_groups(tokenize_rows(...), nsort)`` padded with zero pairs
    (property-tested).
    """
    (letters, F0, tok_len, max_word_len, doc_of_tok, valid_tok,
     num_tokens, n) = _tokenize_front(
        data, doc_ends, doc_id_values, tok_cap=tok_cap,
        num_docs=num_docs)

    # 6-char packed stream: l6[i] = letters[i..i+5] as 5-bit codes
    # (byte & 31: pad 0, a=1 .. z=26 — order-preserving), char k at
    # shift 25-5k.  One gather at F[t]+12g yields group g's hi half,
    # one at F[t]+12g+6 its lo half; the mask keeps only the token's
    # own chars (the compacted stream runs straight into the next
    # token's letters).
    codes = letters & 31
    cp = jnp.concatenate([codes, jnp.zeros(5, jnp.int32)])
    l6 = ((cp[0:n] << 25) | (cp[1:n + 1] << 20) | (cp[2:n + 2] << 15)
          | (cp[3:n + 3] << 10) | (cp[4:n + 4] << 5) | cp[5:n + 5])
    full = (1 << 30) - 1
    masktab6 = jnp.array(
        [0] + [full ^ ((1 << (30 - 5 * m)) - 1) for m in range(1, 7)],
        jnp.int32)

    def half(char_off):
        idx = jnp.clip(F0 + char_off, 0, n - 1)
        # cap at width too: when 12 * num_groups_for(width) > width
        # (width not divisible by 12), the last group's window reaches
        # past the row — the byte-column reference drops those chars
        # (it only builds width//4 columns), so the mask must as well
        nchars = jnp.clip(
            jnp.minimum(tok_len, jnp.int32(width)) - char_off, 0, 6)
        return l6[idx] & masktab6[nchars]

    total = num_groups_for(width)
    live = live_groups_for(sort_cols, width)
    groups = []
    for g in range(live):
        hi, lo = half(12 * g), half(12 * g + 6)
        if g == 0:
            hi = jnp.where(valid_tok, hi, INT32_MAX)
            lo = jnp.where(valid_tok, lo, INT32_MAX)
        groups.append((hi, lo))
    zero = jnp.zeros(tok_cap, jnp.int32)
    groups.extend((zero, zero) for _ in range(total - live))

    doc_col = jnp.where(valid_tok, doc_of_tok, INT32_MAX)
    return tuple(groups), doc_col, max_word_len, num_tokens


def clamp_sort_cols(sort_cols: int | None, ncols: int) -> int:
    """The ONE clamp every consumer of ``sort_cols`` must share: the
    number of leading word columns that can be non-constant.  Sorting,
    exchange, and fetch all rely on the same bound — a desynchronized
    copy would silently drop live columns."""
    return ncols if sort_cols is None else max(1, min(sort_cols, ncols))


def zero_tail_cols(cols, nsort: int, n: int):
    """Splice constant zeros for the provably-all-zero trailing columns
    (valid rows have no letters there; padding rows carry 0 in every
    column but 0) so XLA dead-code-eliminates whatever built them."""
    if nsort >= len(cols):
        return tuple(cols)
    zero = jnp.zeros(n, jnp.int32)
    return (*cols[:nsort], *([zero] * (len(cols) - nsort)))


def pack_groups(cols, nsort: int):
    """Radix compression of word-row byte columns: cleaned bytes are
    only 0 or a..z, and ``byte & 31`` maps them order-preservingly to
    5-bit codes (pad 0, a=1 .. z=26).  Three byte columns (12 chars)
    repack into one 30-bit (hi, lo) int32 pair — a 2-key stable pass
    over the pair replaces three single-key passes (int64 keys would
    halve again but need jax_enable_x64).  Returns ``ceil(nsort/3)``
    pairs; group 0 pins INT32_MAX padding rows so they sort last.
    The mapping is injective on the charset, so group equality ==
    column equality (see :func:`unpack_groups` for the exact inverse).
    """
    col0 = cols[0]

    def _codes(c):
        return ((c >> 24) & 31, (c >> 16) & 31, (c >> 8) & 31, c & 31)

    zero_col = jnp.zeros_like(col0)
    groups = []
    for g in range((nsort + 2) // 3):
        ga = cols[3 * g]
        gb = cols[3 * g + 1] if 3 * g + 1 < nsort else zero_col
        gc = cols[3 * g + 2] if 3 * g + 2 < nsort else zero_col
        a0, a1, a2, a3 = _codes(ga)
        b0, b1, b2, b3 = _codes(gb)
        c0, c1, c2, c3 = _codes(gc)
        hi = (a0 << 25) | (a1 << 20) | (a2 << 15) | (a3 << 10) | (b0 << 5) | b1
        lo = (b2 << 25) | (b3 << 20) | (c0 << 15) | (c1 << 10) | (c2 << 5) | c3
        if g == 0:
            pad = col0 == INT32_MAX
            hi = jnp.where(pad, INT32_MAX, hi)
            lo = jnp.where(pad, INT32_MAX, lo)
        groups.append((hi, lo))
    return groups


def unpack_groups(groups, ncols: int):
    """Exact inverse of :func:`pack_groups` for non-padding rows:
    (hi, lo) code pairs back to big-endian byte columns.  Callers mask
    padding rows (their codes decode to garbage bytes) — every consumer
    already filters by a validity mask before using columns."""
    zero = jnp.zeros_like(groups[0][0])

    def _byte(code):
        return jnp.where(code > 0, code + 96, 0)

    cols = []
    for c in range(ncols):
        g, r = divmod(c, 3)
        if g >= len(groups):
            cols.append(zero)
            continue
        hi, lo = groups[g]
        if r == 0:
            codes = (hi >> 25, hi >> 20, hi >> 15, hi >> 10)
        elif r == 1:
            codes = (hi >> 5, hi, lo >> 25, lo >> 20)
        else:
            codes = (lo >> 15, lo >> 10, lo >> 5, lo)
        b = [_byte(x & 31) for x in codes]
        cols.append((b[0] << 24) | (b[1] << 16) | (b[2] << 8) | b[3])
    return tuple(cols)


def groups_sort_perm(groups, doc_col, cap: int):
    """Sort permutation for lexicographic ((group pairs…), doc) order:
    LSD from the least-significant segment — doc rides as a third key
    of the most-minor group's pass (perm starts as the identity so the
    first pass gathers nothing), then one 2-key stable pass per
    remaining group.  Wide comparators blow up TPU AOT compile time
    (~80x — measured: 1403 s AOT-compiling a 13-key comparator sort vs
    17.8 s for 13 single-key passes at 2^21); 2-3-key ones are cheap."""
    perm = jnp.arange(cap, dtype=jnp.int32)
    hi, lo = groups[-1]
    _, _, _, perm = lax.sort((hi, lo, doc_col, perm), num_keys=3,
                             is_stable=True)
    for hi, lo in reversed(groups[:-1]):
        _, _, perm = lax.sort((hi[perm], lo[perm], perm), num_keys=2,
                              is_stable=True)
    return perm


def sort_dedup_groups(groups, doc_col, cap: int, live: int):
    """Sorted/deduped index from 5-bit group pairs (device, traceable).

    The reduce stage, operating natively on the compressed
    representation :func:`tokenize_groups` emits — no byte columns
    ever materialize at token scale.  Lexicographic ((group pairs…),
    doc) order via the LSD radix passes of :func:`groups_sort_perm`;
    INT32_MAX rows (padding / empty) sort last and are dropped by the
    validity mask.  ``live``: group pairs
    that can be non-constant (:func:`live_groups_for`); constant-zero
    tail pairs are excluded from the radix passes (a stable pass over
    a constant key is the identity) and returned as zeros.

    Returns ``(num_words, num_pairs, df, postings, unique_groups)``
    with ``unique_groups`` shaped like ``groups``.
    """
    live_pairs = list(groups[:max(1, live)])
    perm = groups_sort_perm(live_pairs, doc_col, cap)
    s_groups = [(hi[perm], lo[perm]) for hi, lo in live_pairs]
    s_docs = doc_col[perm]

    def neq_prev(a):
        return jnp.concatenate(
            [jnp.ones(1, jnp.bool_), a[1:] != a[:-1]])

    word_valid = s_groups[0][0] != INT32_MAX
    first_word = word_valid & functools.reduce(
        jnp.logical_or,
        (neq_prev(h) for pair in s_groups for h in pair))
    first_pair = word_valid & (first_word | neq_prev(s_docs))

    num_words = first_word.sum(dtype=jnp.int32)
    num_pairs = first_pair.sum(dtype=jnp.int32)

    # Compaction WITHOUT scatters: the shared set-bit sort
    # (segment.set_bit_positions) — one cap-sized 1-key sort per
    # compaction, cheaper than the rank-cumsum searchsorted it
    # replaced (round 3 on-chip).
    pair_rank = jnp.cumsum(first_pair.astype(jnp.int32)) - 1
    slots = jnp.arange(cap, dtype=jnp.int32)
    W = jnp.concatenate([
        jnp.minimum(segment.set_bit_positions(first_word, cap), cap),
        jnp.full(1, cap, jnp.int32)])
    P = jnp.minimum(segment.set_bit_positions(first_pair, cap), cap)
    word_live = slots < num_words
    pair_live = slots < num_pairs
    Wg = jnp.clip(W[:-1], 0, cap - 1).astype(jnp.int32)
    Pg = jnp.clip(P, 0, cap - 1).astype(jnp.int32)

    pair_excl = jnp.concatenate(
        [pair_rank + 1 - first_pair.astype(jnp.int32),
         jnp.full(1, num_pairs, jnp.int32)])
    df = jnp.where(
        word_live, pair_excl[jnp.minimum(W[1:], cap)] - pair_excl[Wg], 0)
    postings = jnp.where(pair_live, s_docs[Pg], 0)
    zero = jnp.zeros(cap, jnp.int32)
    unique_groups = tuple(
        [(jnp.where(word_live, hi[Wg], 0),
          jnp.where(word_live, lo[Wg], 0)) for hi, lo in s_groups]
        + [(zero, zero)] * (len(groups) - len(live_pairs)))
    return num_words, num_pairs, df, postings, unique_groups


@functools.partial(
    jax.jit,
    static_argnames=("width", "tok_cap", "num_docs", "sort_cols"),
)
def index_bytes_device(data, doc_ends, doc_id_values, *, width: int,
                       tok_cap: int, num_docs: int,
                       sort_cols: int | None = None):
    """bytes -> sorted/deduped index, entirely on device (single chip).

    ``data``: uint8 (N,) — concatenated documents, padded with spaces
    (0x20) to a static length.  ``doc_ends``: int32 (num_docs,)
    exclusive end offsets.  ``doc_id_values``: int32 (num_docs,)
    1-based ids.  ``width``: word-row bytes, multiple of 4.
    ``tok_cap``: static token capacity — must be > the true token count
    (callers compute it exactly with vectorized masks; note doc
    boundaries split tokens, so up to one token per byte can exist).

    Returns a dict of fixed-shape arrays; valid prefixes are bounded by
    ``num_words`` / ``num_pairs`` (see caller).  ``max_word_len`` must
    be checked against ``width`` host-side (WidthOverflow contract).
    ``sort_cols``: optional static radix-pass bound from the host-exact
    :func:`max_cleaned_token_len`.  Word rows live and return as the
    5-bit ``unique_groups`` pairs (:func:`tokenize_groups`) — the
    host decodes them at vocab scale (:func:`decode_word_groups`),
    and the fetch rides 2 int32 per 12 chars instead of 3.
    """
    groups, doc_col, max_word_len, num_tokens = tokenize_groups(
        data, doc_ends, doc_id_values, width=width, tok_cap=tok_cap,
        num_docs=num_docs, sort_cols=sort_cols)
    num_words, num_pairs, df, postings, unique_groups = sort_dedup_groups(
        groups, doc_col, tok_cap, live_groups_for(sort_cols, width))
    # words needing any tail group (cleaned length > 12): group 1's hi
    # is nonzero iff char 13 exists.  The count rides with the other
    # counts so the fetch can size a SPARSE tail-group transfer
    # (long words are rare in real text; see fetch_pack).
    slots = jnp.arange(tok_cap, dtype=jnp.int32)
    if len(unique_groups) > 1:
        long_mask = (slots < num_words) & (unique_groups[1][0] != 0)
        num_long = long_mask.sum(dtype=jnp.int32)
    else:
        num_long = jnp.int32(0)
    return {
        # one 5-scalar array: ONE host sync fetches all counts (each
        # scalar fetched separately would pay the link RTT per scalar);
        # num_tokens lets the caller verify its tok_cap bound held
        "counts": jnp.stack([num_words, num_pairs, max_word_len,
                             num_tokens, num_long]),
        "df": df,                    # (tok_cap,) valid prefix num_words
        "postings": postings,        # (tok_cap,) valid prefix num_pairs
        # num_groups_for(width) x (hi, lo), valid prefix num_words
        "unique_groups": unique_groups,
    }


def doc_pack_width(max_doc_id: int) -> int:
    """Doc ids per packed int32 for the postings fetch: 3 when ids fit
    10 bits, else 1 (below 2^16 the uint16 cast already covers
    2-per-4-bytes and packing would only add shifts for the same
    transfer size; above it ids must ride int32 untouched)."""
    return 3 if 0 < max_doc_id < (1 << 10) else 1


def pack_postings(post, k: int):
    """Traceable postings packer: ``k`` doc ids per int32 in 10-bit
    fields (``k == 1`` passes through).  The ONE pack implementation —
    the single-chip tail (:func:`fetch_pack`) and the mesh prefix
    slice both call it, and :func:`unpack_postings` is its pinned
    inverse; a second copy could silently drift from the decoder."""
    if k == 1:
        return post
    npairs = post.shape[0]
    pad = (-npairs) % k
    p = jnp.concatenate([post, jnp.zeros(pad, post.dtype)]).reshape(-1, k)
    return (p[:, 0] | (p[:, 1] << 10) | (p[:, 2] << 20)
            if k == 3 else p[:, 0])


def gather_long_tails(halves, nu: int, nlong: int):
    """Traceable sparse tail-group gather: set-bit indices of the
    >12-char rows (group 1's hi is nonzero exactly there; tail halves
    are zero past ``num_words``, so padding never matches) and every
    tail half gathered at them.  Returns ``(idx, gathered_halves)``
    with ``idx`` INT32_MAX past the true long count — callers slice by
    the count they carried in their counts array."""
    long_mask = halves[0][:nu] != 0
    idx = segment.set_bit_positions(long_mask, nlong)
    gi = jnp.clip(idx, 0, nu - 1)
    return idx, tuple(h[:nu][gi] for h in halves)


@functools.partial(jax.jit,
                   static_argnames=("nu", "npairs", "nlong", "k", "live",
                                    "narrow"))
def fetch_pack(out, *, nu: int, npairs: int, nlong: int, k: int,
               live: int, narrow: bool):
    """Device-side fetch packer for the single-chip engines' tail.

    Returns the minimal transfer set (everything int32/uint16, every
    array dispatched before any is read by the caller):

    - ``df``: valid prefix, uint16 when ``narrow`` (df <= max_doc_id,
      so the same bound governs both; packing further would save
      little — df is the smallest array), int32 otherwise;
    - ``post``: postings packed ``k`` ids per int32 (10-bit fields,
      :func:`doc_pack_width`), else the uint16 cast when ``narrow``,
      else untouched int32 (doc ids >= 2^16 MUST ride wide —
      truncation here would silently corrupt the index);
    - ``g0``: group 0's (hi, lo) prefix — every word's first 12 chars;
    - ``long_idx`` + ``tail``: row indices and tail-group halves for
      ONLY the words longer than 12 chars (``num_long`` of them, from
      the program's counts) — the dense tail arrays are provably zero
      everywhere else, so the host rebuilds them by scatter at vocab
      scale.  Real-text corpora put ~1-5% of the vocab here, cutting
      the dominant group transfer ~(live-1)/live.
    """
    df = out["df"][:nu]
    post = out["postings"][:npairs]
    if narrow:
        df = df.astype(jnp.uint16)
    if k > 1:
        post = pack_postings(post, k)
    elif narrow:
        post = post.astype(jnp.uint16)
    hi0, lo0 = out["unique_groups"][0]
    res = {"df": df, "post": post, "g0": (hi0[:nu], lo0[:nu])}
    if live > 1 and nlong > 0:
        halves = [h for pair in out["unique_groups"][1:live]
                  for h in pair]
        idx, gathered = gather_long_tails(halves, nu, nlong)
        res["long_idx"] = idx  # INT32_MAX past num_long; caller slices
        res["tail"] = tuple(
            (gathered[2 * g], gathered[2 * g + 1])
            for g in range(live - 1))
    return res


def rebuild_tail_groups(num_words: int, ngroups_fetch: int, *,
                        idx=None, tails=(), num_long: int = 0):
    """Host-side inverse of the sparse tail-group transfer
    (:func:`gather_long_tails`): dense (hi, lo) pairs for groups
    1..ngroups_fetch-1, zeros everywhere except the ``num_long`` long
    words' rows scattered back at ``idx``.  The ONE rebuild
    implementation — the single-chip tail and the mesh owner fetch
    both call it (same anti-drift rationale as
    :func:`unpack_postings`)."""
    out = []
    for g in range(ngroups_fetch - 1):
        h = np.zeros(num_words, np.int32)
        l = np.zeros(num_words, np.int32)
        if num_long:
            h[idx] = np.asarray(tails[g][0])[:num_long]
            l[idx] = np.asarray(tails[g][1])[:num_long]
        out.append((h, l))
    return out


def unpack_postings(packed: np.ndarray, num_pairs: int,
                    k: int) -> np.ndarray:
    """Host-side inverse of :func:`fetch_pack`'s postings packing —
    kept next to the pack so field width and ``k`` can never drift
    apart.  ``k == 1`` input is the uint16/int32 passthrough."""
    if k == 1:
        return np.asarray(packed)[:num_pairs].astype(np.int32)
    pw = np.asarray(packed).astype(np.int64)
    return np.stack(
        [pw & 1023, (pw >> 10) & 1023, (pw >> 20) & 1023],
        axis=1).reshape(-1)[:num_pairs].astype(np.int32)


def _host_start_mask(buf: np.ndarray, ends: np.ndarray) -> np.ndarray:
    """Token-start mask, host side.  MUST mirror the device classifier
    in :func:`tokenize_rows` byte for byte (same whitespace set, same
    doc-boundary break rule); divergence is asserted loudly by callers.
    Vectorized whole-array compares, not a scan."""
    sp = ((buf == 0x20) | (buf == 0x09) | (buf == 0x0A)
          | (buf == 0x0B) | (buf == 0x0C) | (buf == 0x0D))
    prev_sp = np.empty_like(sp)
    prev_sp[0] = True
    prev_sp[1:] = sp[:-1]
    start = ~sp & prev_sp
    start[0] = not sp[0]
    de = ends[:-1][ends[:-1] < buf.shape[0]]
    start[de] |= ~sp[de]
    return start


def host_token_stats(buf: np.ndarray, ends: np.ndarray) -> tuple[int, int]:
    """``(token_count, max_cleaned_len)`` in ONE pass over the buffer.

    The count sizes the static ``tok_cap`` (the device's reported
    ``num_tokens`` is asserted against it, so classifier divergence
    fails loudly instead of silently dropping tokens).  The exact max
    cleaned (letters-only) length lets callers raise
    :class:`WidthOverflow` before paying for a doomed launch and pass a
    tight ``sort_cols`` bound (skipping radix passes and fetch bytes
    over provably all-zero word columns); the device's own
    ``max_word_len`` output is asserted equal by callers.

    Delegates to the native SIMD scan when available (~10x the numpy
    mirror below, which stays as the portable fallback and the
    cross-check reference in tests).
    """
    from .. import native

    res = native.token_stats(buf, ends)
    if res is not None:
        return res
    return _host_token_stats_numpy(buf, ends)


def _host_token_stats_numpy(buf: np.ndarray, ends: np.ndarray) -> tuple[int, int]:
    """Portable numpy mirror of ``mri_token_stats`` (the cross-check
    reference in tests)."""
    start = _host_start_mask(buf, ends)
    count = int(np.count_nonzero(start))
    if count == 0:
        return 0, 0
    _, lower_np = _byte_tables()
    is_letter = lower_np[buf] > 0
    excl = np.cumsum(is_letter, dtype=np.int64) - is_letter
    total = int(excl[-1]) + int(is_letter[-1])
    lens = np.diff(np.append(excl[np.flatnonzero(start)], total))
    return count, int(lens.max())


def count_token_starts(buf: np.ndarray, ends: np.ndarray) -> int:
    """Exact host-side token count (see :func:`host_token_stats`)."""
    return int(np.count_nonzero(_host_start_mask(buf, ends)))


def max_cleaned_token_len(buf: np.ndarray, ends: np.ndarray) -> int:
    """Exact max cleaned token length (see :func:`host_token_stats`)."""
    return host_token_stats(buf, ends)[1]


def decode_word_groups(groups, width: int) -> np.ndarray:
    """Fetched (hi, lo) 5-bit group pairs -> numpy 'S(width)' word
    array — the host-side inverse of :func:`tokenize_groups`'s packing
    (same layout as :func:`unpack_groups`, but in numpy at vocab
    scale).  Padding rows must already be sliced off by the caller
    (their codes decode to garbage) — the valid-prefix contract of the
    engines' fetch tails."""
    u = np.asarray(groups[0][0]).shape[0]
    out = np.zeros((u, width), np.uint8)
    for g, (hi, lo) in enumerate(groups):
        for half_idx, arr in ((0, hi), (1, lo)):
            a = np.asarray(arr).astype(np.int64)
            for k in range(6):
                ch = 12 * g + 6 * half_idx + k
                if ch >= width:
                    break
                code = (a >> (25 - 5 * k)) & 31
                out[:, ch] = np.where(code > 0, code + 96, 0)
    return np.ascontiguousarray(out).view(f"S{width}").reshape(u)
