"""Device-side tokenizer: the ENTIRE map phase as one XLA program.

Every other engine in this package keeps the reference's split: host
scans text (main.c:102-117 re-expressed in C++/numpy), device sorts
integers.  This module removes the host from the compute path entirely:
raw corpus bytes go up, the finished index comes down.

    bytes (uint8, N) ──► classify: space/letter via 256-entry tables
        ──► token segmentation: start mask, token ids, within-token
            letter ranks — all cumsum/cummax scans, no loops
        ──► scatter cleaned letters into fixed-width word rows
        ──► pack rows into big-endian int32 columns
            (cleaned bytes are a-z < 0x80, so signed int32 ascending
             == byte-lexicographic ascending)
        ──► ONE variadic ``lax.sort`` over (word columns…, doc)
        ──► boundary-diff word/pair dedup ► df ► postings ► unique rows

Exactness without strings-on-host: rows are the *actual cleaned bytes*
(no hashing, no collisions); sorted-row order IS strcmp order because
rows are zero-padded (0x00 < any letter, so shorter words sort first —
the same argument as the C side's prefix keys, native/tokenizer.cc
SortedOrder).  Words longer than ``width`` cleaned letters cannot be
represented exactly; the program returns the global max cleaned length
and the caller MUST fall back to a host path when it exceeds ``width``
(``WidthOverflow``).  The reference's own cap is 299 (main.c:105), and
its corpus maxes at 38, so ``width=48`` covers real text with margin.

This is the TPU-first endpoint of the design space: on hardware where
the host<->device link is ~free (local PCIe), the whole pipeline runs
at device sort throughput; on a high-RTT link the host-scan engines
win end-to-end (bench.py records both, labeled).  Reference seams
re-expressed: mapper tokenize+emit (main.c:85-124) and reducer
dedup/sort (main.c:126-242) become one fused program with no
intermediate materialization at all — not even the (term, doc) pair
array the other engines feed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .segment import compact

INT32_MAX = np.iinfo(np.int32).max


class WidthOverflow(Exception):
    """A cleaned token exceeded the row width — the device rows would be
    truncated (inexact); the caller must fall back to a host tokenizer."""


@functools.lru_cache(maxsize=1)
def _byte_tables():
    """(space, lower) 256-entry tables — the exact C-locale contract of
    the native scan (native/tokenizer.cc ByteTables).  Cached as numpy
    (NOT device arrays: an lru-cached jnp value created inside a trace
    would leak that trace's tracers into later calls); jit closes over
    them as constants."""
    space = np.zeros(256, np.bool_)
    for b in b" \t\n\v\f\r":
        space[b] = True
    lower = np.zeros(256, np.uint8)
    for b in range(ord("a"), ord("z") + 1):
        lower[b] = b
    for b in range(ord("A"), ord("Z") + 1):
        lower[b] = b + 32
    return space, lower


def tokenize_rows(data, doc_ends, doc_id_values, *, width: int,
                  tok_cap: int, num_docs: int):
    """bytes -> packed word-row columns + doc column (device, traceable).

    The map phase's tokenize/clean stage as pure array ops — shared by
    the single-chip program below and the mesh variant
    (parallel/dist_device_tokenizer.py), where it runs per shard inside
    ``shard_map``.  Returns ``(cols, doc_col, max_word_len,
    num_tokens)``: ``cols[0]`` carries INT32_MAX on empty/padding rows
    (sorts last), ``doc_col`` likewise.
    """
    n = data.shape[0]
    space_np, lower_np = _byte_tables()
    is_space = jnp.asarray(space_np)[data]
    lowered = jnp.asarray(lower_np)[data]
    is_letter = lowered > 0

    pos = jnp.arange(n, dtype=jnp.int32)
    # first byte of each document forces a token break (tokens never
    # span documents — the per-doc scan loop of every host frontend)
    doc_starts = jnp.zeros(n, jnp.bool_).at[doc_ends[:-1]].set(
        True, mode="drop").at[0].set(True)
    nonspace = ~is_space
    prev_space = jnp.concatenate([jnp.ones(1, jnp.bool_), is_space[:-1]])
    token_start = nonspace & (prev_space | doc_starts)

    tok_id = jnp.cumsum(token_start.astype(jnp.int32)) - 1  # per byte
    # within-token letter rank: letters in [token_start, i)
    cs = jnp.cumsum(is_letter.astype(jnp.int32))
    start_pos = lax.cummax(jnp.where(token_start, pos, -1))
    cs_at_start = cs[jnp.maximum(start_pos, 0)]
    letter_at_start = is_letter[jnp.maximum(start_pos, 0)].astype(jnp.int32)
    k = cs - cs_at_start + letter_at_start - 1  # 0-based, valid where is_letter

    # scatter cleaned letters straight into big-endian-packed int32 word
    # columns, laid out column-major as ONE flat (width/4 * tok_cap)
    # buffer — a (tok_cap, width) byte matrix (or any array with a tiny
    # minor dimension) would be padded to the TPU's (8, 128) tile and
    # blow HBM by ~32x.  Each (token, letter-rank) cell is written at
    # most once, so scatter-add over zeros composes the shifted bytes.
    ncols = width // 4
    emit = is_letter & (k < width) & (tok_id >= 0)
    shifted = lowered.astype(jnp.int32) << (8 * (3 - (k % 4)))
    flat_idx = jnp.where(emit, (k // 4) * tok_cap + tok_id, ncols * tok_cap)
    packed = jnp.zeros(ncols * tok_cap, jnp.int32).at[flat_idx].add(
        shifted, mode="drop")

    # cleaned length per token (for the exactness guard): letters with
    # NO width clip — a token's true cleaned length, capped only by the
    # reference's own 299 semantics at the caller
    tok_len = jnp.zeros(tok_cap, jnp.int32).at[
        jnp.where(is_letter & (tok_id >= 0), tok_id, tok_cap)
    ].add(1, mode="drop")
    max_word_len = tok_len.max() if tok_cap else jnp.int32(0)

    # doc id per token: token start byte -> manifest slot -> 1-based id
    tok_start_byte = jnp.zeros(tok_cap, jnp.int32).at[
        jnp.where(token_start, tok_id, tok_cap)
    ].add(jnp.where(token_start, pos, 0), mode="drop")
    slot = jnp.searchsorted(doc_ends, tok_start_byte, side="right")
    doc_of_tok = doc_id_values[jnp.clip(slot, 0, num_docs - 1)]

    # valid rows (>= 1 letter) have column 0's top byte in [a-z] =>
    # positive int32; empty/padding rows get INT32_MAX in column 0 so
    # they sort after every real word
    num_tokens = jnp.int32(0) + jnp.sum(token_start.astype(jnp.int32))
    valid_tok = (tok_len > 0) & (jnp.arange(tok_cap) < num_tokens)
    cols = [packed[c * tok_cap:(c + 1) * tok_cap] for c in range(ncols)]
    col0 = jnp.where(valid_tok, cols[0], INT32_MAX)
    doc_col = jnp.where(valid_tok, doc_of_tok, INT32_MAX)

    return (col0, *cols[1:]), doc_col, max_word_len, num_tokens


def sort_dedup_rows(cols, doc_col, cap: int):
    """Sorted/deduped index from word-row columns (device, traceable).

    The reduce stage shared by both device engines: lexicographic
    (word columns…, doc) order via LSD radix — stable single-key passes
    from least significant (doc) to most (column 0).  Identical result
    to one variadic comparator sort, but the TPU AOT compiler takes
    ~80x longer on the wide comparator (measured: 1403 s for a 13-key
    sort vs 17.8 s for 13 stable passes at 2^21).  INT32_MAX rows
    (padding / empty) sort last and are dropped by the validity mask.
    """
    ncols = len(cols)
    col0 = cols[0]
    perm = jnp.arange(cap, dtype=jnp.int32)
    for key in (doc_col, *cols[ncols - 1:0:-1], col0):
        _, perm = lax.sort((key[perm], perm), num_keys=1, is_stable=True)
    s_cols = tuple(c[perm] for c in cols)
    s_docs = doc_col[perm]

    def neq_prev(a):
        return jnp.concatenate(
            [jnp.ones(1, jnp.bool_), a[1:] != a[:-1]])

    word_valid = s_cols[0] != INT32_MAX
    first_word = word_valid & functools.reduce(
        jnp.logical_or, (neq_prev(c) for c in s_cols))
    first_pair = word_valid & (first_word | neq_prev(s_docs))

    word_rank = jnp.cumsum(first_word.astype(jnp.int32)) - 1
    num_words = first_word.sum(dtype=jnp.int32)
    num_pairs = first_pair.sum(dtype=jnp.int32)
    df = jnp.zeros(cap, jnp.int32).at[
        jnp.where(first_pair, word_rank, cap)
    ].add(1, mode="drop")
    postings = compact(s_docs, first_pair, cap, jnp.int32(0))
    unique_cols = tuple(
        compact(c, first_word, cap, jnp.int32(0)) for c in s_cols)
    return num_words, num_pairs, df, postings, unique_cols


@functools.partial(
    jax.jit,
    static_argnames=("width", "tok_cap", "num_docs"),
)
def index_bytes_device(data, doc_ends, doc_id_values, *, width: int,
                       tok_cap: int, num_docs: int):
    """bytes -> sorted/deduped index, entirely on device (single chip).

    ``data``: uint8 (N,) — concatenated documents, padded with spaces
    (0x20) to a static length.  ``doc_ends``: int32 (num_docs,)
    exclusive end offsets.  ``doc_id_values``: int32 (num_docs,)
    1-based ids.  ``width``: word-row bytes, multiple of 4.
    ``tok_cap``: static token capacity — must be > the true token count
    (callers compute it exactly with vectorized masks; note doc
    boundaries split tokens, so up to one token per byte can exist).

    Returns a dict of fixed-shape arrays; valid prefixes are bounded by
    ``num_words`` / ``num_pairs`` (see caller).  ``max_word_len`` must
    be checked against ``width`` host-side (WidthOverflow contract).
    """
    cols, doc_col, max_word_len, num_tokens = tokenize_rows(
        data, doc_ends, doc_id_values, width=width, tok_cap=tok_cap,
        num_docs=num_docs)
    num_words, num_pairs, df, postings, unique_cols = sort_dedup_rows(
        cols, doc_col, tok_cap)
    return {
        # one 4-scalar array: ONE host sync fetches all counts (each
        # scalar fetched separately would pay the link RTT per scalar);
        # num_tokens lets the caller verify its tok_cap bound held
        "counts": jnp.stack([num_words, num_pairs, max_word_len,
                             num_tokens]),
        "df": df,                    # (tok_cap,) valid prefix num_words
        "postings": postings,        # (tok_cap,) valid prefix num_pairs
        "unique_cols": unique_cols,  # width//4 x (tok_cap,) prefix num_words
    }


def count_token_starts(buf: np.ndarray, ends: np.ndarray) -> int:
    """Exact host-side token count for a space-padded byte buffer.

    MUST mirror the device classifier in :func:`tokenize_rows` byte for
    byte (same whitespace set, same doc-boundary break rule) — both
    engines size their static ``tok_cap`` from it, and the device's
    reported ``num_tokens`` is asserted against the resulting bound so
    any divergence fails loudly instead of silently dropping tokens.
    Vectorized whole-array compares, not a scan.
    """
    sp = ((buf == 0x20) | (buf == 0x09) | (buf == 0x0A)
          | (buf == 0x0B) | (buf == 0x0C) | (buf == 0x0D))
    prev_sp = np.empty_like(sp)
    prev_sp[0] = True
    prev_sp[1:] = sp[:-1]
    start = ~sp & prev_sp
    start[0] = not sp[0]
    de = ends[:-1][ends[:-1] < buf.shape[0]]
    start[de] |= ~sp[de]
    return int(np.count_nonzero(start))


def decode_word_rows(cols: list[np.ndarray], width: int) -> np.ndarray:
    """Fetched big-endian int32 columns -> numpy 'S(width)' word array.

    Column 0 of row 0..U-1 had INT32_MAX replaced only for padding rows,
    which the caller already sliced off, so a plain byte-reassembly is
    exact."""
    u = cols[0].shape[0]
    out = np.zeros((u, width), np.uint8)
    for c, col in enumerate(cols):
        col = col.astype(np.uint32)
        out[:, 4 * c + 0] = (col >> 24) & 0xFF
        out[:, 4 * c + 1] = (col >> 16) & 0xFF
        out[:, 4 * c + 2] = (col >> 8) & 0xFF
        out[:, 4 * c + 3] = col & 0xFF
    return np.ascontiguousarray(out).view(f"S{width}").reshape(u)
